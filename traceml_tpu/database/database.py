"""Bounded in-memory table store
(reference: src/traceml_ai/database/database.py:7-186).

Each sampler owns one ``Database``: a dict of named tables, each a
``deque(maxlen=N)`` of row dicts plus a **monotonic append counter** so an
incremental sender can detect new rows in O(1) without scanning
(rows may have been evicted from the left; the counter never decreases).

Producer fast path (r10): every table also keeps a **columnar append
accumulator** — a struct-of-arrays of the rows appended since the last
collection, built in lockstep with the row deque and the append counter.
Rows matching the window's shape are buffered and transposed in chunks
of ``_PEND_CHUNK`` (C-level listcomps beat a python-level per-row
scatter by roughly an order of magnitude), so ``add_record`` stays
near deque-append cost and ``collect_wire_tables`` hands wire-ready
columns to the incremental sender under one lock sweep — a publish tick
never re-transposes row dicts.  The accumulator is an optimization,
never a source of truth: any condition it cannot represent exactly (a
pending window larger than the retention bound, a consumer cursor that
does not match the accumulator's) falls back to the row deque, which
keeps the collected batch byte-identical to the pre-accumulator path
(see docs/developer_guide/rank-producer-path.md).
"""

from __future__ import annotations

import threading
from collections import deque
from itertools import islice
from typing import Any, Deque, Dict, List, Optional, Tuple, Union

from traceml_tpu.telemetry.envelope import SOA_KEY

DEFAULT_MAX_ROWS = 3000

# Rows buffered in a table's pend_tail before a chunked transpose.  Small
# enough that a publish-tick drain of the residue is trivial; large enough
# that the C-level ``zip`` amortizes the per-chunk python overhead.
_PEND_CHUNK = 16


class _SoaCol:
    """Incremental nested struct-of-arrays accumulator for one pending
    column whose cells — so far — are dicts with an identical key set.

    Mirrors ``envelope._encode_cells`` decision-for-decision, but spread
    across appends instead of re-scanning the batch every publish tick:
    a cell that breaks uniformity (non-dict, different key set, or a
    ``None`` pad for a row missing the column) degrades the node back to
    a plain cell list via :meth:`materialize`, which is exactly what the
    batch encoder would have chosen for that window.  Children recurse
    independently, like the batch encoder's per-subcolumn recursion.
    """

    __slots__ = ("keys", "keyset", "sub", "n")

    def __init__(self, keys: Tuple[str, ...], sub: List[Any], n: int) -> None:
        self.keys = keys
        self.keyset = set(keys)
        self.sub = sub
        self.n = n

    def add(self, v: Any) -> bool:
        """Append one cell; False when ``v`` breaks uniformity (the
        caller must materialize and go plain)."""
        if not isinstance(v, dict) or v.keys() != self.keyset:
            return False
        sub = self.sub
        for j, k in enumerate(self.keys):
            child = sub[j]
            cv = v[k]
            if type(child) is list:
                child.append(cv)
            elif not child.add(cv):
                plain = child.materialize()
                plain.append(cv)
                sub[j] = plain
        self.n += 1
        return True

    def bulk(self, cells) -> Optional[List[Any]]:
        """Append many cells at once; ``None`` on success, else the
        plain-cell list the caller must swap in for this column (the
        cells are consumed either way).

        The fast branch requires every cell to be a dict with exactly
        this node's key set (``dict_keys == set`` is a C-level,
        allocation-free compare), then transposes one key at a time with
        a listcomp of dict lookups — order-insensitive and all C-loop.
        Cells that break uniformity go through :meth:`add` per cell,
        preserving the exact semantics of the per-row path (including
        mid-batch degradation)."""
        keyset = self.keyset
        uniform = True
        for d in cells:
            if type(d) is not dict or d.keys() != keyset:
                uniform = False
                break
        if uniform:
            sub = self.sub
            for j, k in enumerate(self.keys):
                child = sub[j]
                colvals = [d[k] for d in cells]
                if type(child) is list:
                    child.extend(colvals)
                else:
                    plain = child.bulk(colvals)
                    if plain is not None:
                        sub[j] = plain
            self.n += len(cells)
            return None
        for i, d in enumerate(cells):
            if not self.add(d):
                plain = self.materialize()
                plain.extend(cells[i:])
                return plain
        return None

    def materialize(self) -> List[Any]:
        """Back to plain per-row cell dicts (content-identical to the
        originals; used only when the column degrades)."""
        cols = [
            c if type(c) is list else c.materialize() for c in self.sub
        ]
        keys = self.keys
        return [
            {k: cols[j][i] for j, k in enumerate(keys)}
            for i in range(self.n)
        ]

    def wire(self) -> Dict[str, Any]:
        """The wire nested-SoA encoding — what ``_encode_cells`` yields
        for a uniform dict column, built from already-transposed leaves."""
        return {
            SOA_KEY: [
                list(self.keys),
                [c if type(c) is list else c.wire() for c in self.sub],
            ]
        }


def _new_cell_store(v: Any) -> Union[List[Any], _SoaCol]:
    """Storage for a column born at row 0 with first cell ``v``: a SoA
    node for (str-keyed) dicts, a plain list otherwise."""
    if isinstance(v, dict) and all(type(k) is str for k in v):
        return _SoaCol(
            tuple(v), [_new_cell_store(cv) for cv in v.values()], 1
        )
    return [v]


class _Table:
    __slots__ = (
        "rows",
        "appended",
        "pend_cols",
        "pend_idx",
        "pend_vals",
        "pend_n",
        "pend_tail",
        "pend_overflow",
        "pend_shape",
        "collected",
    )

    def __init__(self, maxlen: int) -> None:
        self.rows: Deque[Dict[str, Any]] = deque(maxlen=maxlen)
        self.appended: int = 0  # total rows ever appended
        # columnar accumulator over rows appended since the last
        # collect_columns (invariant: pend_n + len(pend_tail) ==
        # appended - collected unless pend_overflow is set)
        self.pend_cols: List[str] = []
        self.pend_idx: Dict[str, int] = {}
        self.pend_vals: List[List[Any]] = []
        self.pend_n: int = 0
        # rows whose key tuple matches pend_shape, awaiting a chunked
        # transpose (one C-level listcomp per column) — per-row python
        # transposition costs more than it saves, so the hot append path
        # is one list append
        self.pend_tail: List[Dict[str, Any]] = []
        self.pend_overflow: bool = False
        # key tuple shared by every row this window (None once any row
        # deviates) — gates the tail fast path
        self.pend_shape: Optional[Tuple[str, ...]] = None
        self.collected: int = 0  # append count at last collect_columns

    def reset_pending(self) -> None:
        self.pend_cols = []
        self.pend_idx = {}
        self.pend_vals = []
        self.pend_n = 0
        self.pend_tail = []
        self.pend_overflow = False
        # pend_shape deliberately survives the reset: samplers emit the
        # same row shape tick after tick, so the NEXT window's rows can
        # join the tail immediately (drain_tail seeds the columns from
        # the first buffered row) instead of paying the general path and
        # a mod-chunk residue drain every window

    def pend_add(self, row: Dict[str, Any], maxlen: int) -> None:
        """Transpose ``row`` into the pending columns (lock held).

        Same semantics as ``rows_to_columns`` + ``_encode_cells``
        applied to the pending batch: first-appearance column order,
        ``None`` fill for keys a row lacks, uniform str-keyed dict
        columns accumulated as nested struct-of-arrays (:class:`_SoaCol`)
        so the publish tick never re-transposes.  A window that outgrows
        the retention bound can no longer be represented exactly (the
        deque evicts from the left) — it flips the sticky overflow flag
        and the next collection takes the row-deque path instead.
        """
        if self.pend_overflow:
            return
        if self.pend_n + len(self.pend_tail) >= maxlen:
            self.pend_overflow = True
            self.pend_cols = []
            self.pend_idx = {}
            self.pend_vals = []
            self.pend_n = 0
            self.pend_tail = []
            self.pend_shape = None
            return
        if self.pend_shape is not None:
            if tuple(row) == self.pend_shape:
                # hot path: the row has exactly the window's columns in
                # the window's order, so it just joins the tail buffer —
                # transposition is deferred to drain_tail's chunked
                # per-column listcomps (per-row python transposition has
                # a method-call floor the bulk path avoids)
                tail = self.pend_tail
                tail.append(row)
                if len(tail) >= _PEND_CHUNK:
                    self.drain_tail()
                return
            # shape drifted: flush buffered predecessors first so column
            # order is preserved, then general path from here on
            self.drain_tail()
            self.pend_shape = None
        n = self.pend_n
        idx = self.pend_idx
        vals = self.pend_vals
        for k, v in row.items():
            j = idx.get(k)
            if j is None:
                idx[k] = len(self.pend_cols)
                self.pend_cols.append(k)
                if n == 0:
                    vals.append(_new_cell_store(v))
                else:
                    # born mid-window: earlier rows pad with None, so
                    # the batch encoder would keep it plain regardless
                    col: List[Any] = [None] * n
                    col.append(v)
                    vals.append(col)
            else:
                col = vals[j]
                if type(col) is list:
                    col.append(v)
                elif not col.add(v):
                    plain = col.materialize()
                    plain.append(v)
                    vals[j] = plain
        self.pend_n = n + 1
        for j, col in enumerate(vals):
            if type(col) is list:
                if len(col) <= n:  # column absent from this row
                    col.append(None)
            elif col.n <= n:  # a None pad breaks dict uniformity
                plain = col.materialize()
                plain.append(None)
                vals[j] = plain
        if n == 0:
            # window seeded by this row: its key order IS the column
            # order, so identically-shaped successors take the fast path
            self.pend_shape = tuple(self.pend_cols)

    def drain_tail(self) -> None:
        """Transpose the buffered same-shape rows into the pending
        columns in one pass (lock held).  Equivalent to running each row
        through the general ``pend_add`` path — every tail row has
        exactly the window's columns in the window's order, so the
        ``None``-padding sweep is moot and each column is one C-level
        listcomp of dict lookups."""
        tail = self.pend_tail
        if not tail:
            return
        vals = self.pend_vals
        if self.pend_n == 0:
            # window opened straight into the tail (pend_shape survived
            # the last reset): seed the columns from the first buffered
            # row, exactly as the general path would have
            first = tail[0]
            cols = self.pend_cols
            idx = self.pend_idx
            for k, v in first.items():
                idx[k] = len(cols)
                cols.append(k)
                vals.append(_new_cell_store(v))
            self.pend_n = 1
            tail = tail[1:]
            if not tail:
                self.pend_tail = []
                return
        for j, k in enumerate(self.pend_cols):
            col = vals[j]
            colvals = [r[k] for r in tail]
            if type(col) is list:
                col.extend(colvals)
            else:
                plain = col.bulk(colvals)
                if plain is not None:
                    vals[j] = plain
        self.pend_n += len(tail)
        self.pend_tail = []


class Database:
    def __init__(self, max_rows_per_table: int = DEFAULT_MAX_ROWS) -> None:
        self._max_rows = int(max_rows_per_table)
        self._tables: Dict[str, _Table] = {}
        self._lock = threading.Lock()
        self._appended_total = 0  # across all tables; never decreases

    def add_record(self, table: str, row: Dict[str, Any]) -> None:
        with self._lock:
            t = self._tables.get(table)
            if t is None:
                t = self._tables[table] = _Table(self._max_rows)
            t.rows.append(row)
            t.appended += 1
            t.pend_add(row, self._max_rows)
            self._appended_total += 1

    def add_records(self, table: str, rows: List[Dict[str, Any]]) -> None:
        if not rows:
            return
        with self._lock:
            t = self._tables.get(table)
            if t is None:
                t = self._tables[table] = _Table(self._max_rows)
            t.rows.extend(rows)
            t.appended += len(rows)
            for row in rows:
                t.pend_add(row, self._max_rows)
            self._appended_total += len(rows)

    def table_names(self) -> List[str]:
        with self._lock:
            return list(self._tables.keys())

    def append_count(self, table: str) -> int:
        with self._lock:
            t = self._tables.get(table)
            return t.appended if t else 0

    def appended_total(self) -> int:
        """Monotonic count of rows ever appended, across all tables.

        Read without the lock: it is a single int only ever incremented
        under the lock, so a reader sees some recent value — enough for
        the sender's O(1) "anything new since my last collection?" gate
        (a concurrent append is picked up on the next tick either way).
        """
        return self._appended_total  # tracelint: unguarded(monotonic int incremented under lock; any recent value satisfies the anything-new gate)

    def tail(self, table: str, n: Optional[int] = None) -> List[Dict[str, Any]]:
        with self._lock:
            t = self._tables.get(table)
            if t is None:
                return []
            rows = list(t.rows)
        return rows if n is None else rows[-n:]

    def rows_since(self, table: str, cursor: int) -> List[Dict[str, Any]]:
        """Rows appended after append-count ``cursor``.

        If more rows were appended than the table retains, the evicted ones
        are silently lost (bounded-memory contract); callers get what is
        still buffered.
        """
        rows, _ = self.collect_since(table, cursor)
        return rows

    def collect_since(self, table: str, cursor: int):
        """Atomic (rows, new_cursor) snapshot.

        Senders MUST use this (not rows_since + append_count) so a row
        appended between the two reads cannot be skipped.
        """
        with self._lock:
            t = self._tables.get(table)
            if t is None:
                return [], cursor
            new = t.appended - cursor
            new_cursor = t.appended
            if new <= 0:
                return [], new_cursor
            take = min(new, len(t.rows))
            # Slice from the tail via reversed() so the lock-held work is
            # O(new rows), not O(retained rows) — a sender collecting a
            # handful of fresh rows must not copy the whole deque.
            rows = list(islice(reversed(t.rows), take))
        rows.reverse()
        return rows, new_cursor

    def collect_columns(
        self, table: str, cursor: int
    ) -> Tuple[Optional[Dict[str, Any]], Optional[List[Dict[str, Any]]], int]:
        """Atomic ``(columns, rows, new_cursor)`` snapshot for one table
        (single-table convenience over :meth:`collect_wire_tables`;
        same fast-path/fallback semantics)."""
        cursors = {table: cursor}
        fast, fallback = self.collect_wire_tables(cursors)
        new_cursor = cursors[table]
        if table in fast:
            return fast[table], None, new_cursor
        if table in fallback:
            return None, fallback[table], new_cursor
        return None, None, new_cursor

    def collect_wire_tables(
        self, cursors: Dict[str, int]
    ) -> Tuple[Dict[str, Dict[str, Any]], Dict[str, List[Dict[str, Any]]]]:
        """One-lock sweep over every table: ``(fast, fallback)``.

        ``cursors`` is the consumer's per-table cursor map, updated IN
        PLACE to each table's append count (the handoff is atomic per
        table: rows appended after the lock is taken land in the next
        collection).  ``fast[name]`` is a **wire-ready** columnar table
        — ``{"cols": [...], "vals": [...], "n": N}`` with nested
        struct-of-arrays columns already in their ``_encode_cells``
        form, handed over in O(columns) — and the accumulator resets.
        A table whose pending window overflowed the retention bound, or
        whose cursor does not match the accumulator's (``reset()``
        replay, a second consumer), lands in ``fallback[name]`` as the
        row snapshot ``collect_since`` would have served, golden-
        identical to the pre-accumulator path.
        """
        fast: Dict[str, Dict[str, Any]] = {}
        fallback: Dict[str, List[Dict[str, Any]]] = {}
        with self._lock:
            for name, t in self._tables.items():
                cursor = cursors.get(name, 0)
                new_cursor = t.appended
                new = new_cursor - cursor
                cursors[name] = new_cursor
                if new <= 0:
                    continue
                if not t.pend_overflow and cursor == t.collected:
                    t.drain_tail()  # fold the buffered chunk residue in
                    fast[name] = {
                        "cols": t.pend_cols,
                        "vals": [
                            c if type(c) is list else c.wire()
                            for c in t.pend_vals
                        ],
                        "n": t.pend_n,
                    }
                else:
                    take = min(new, len(t.rows))
                    fallback[name] = list(islice(reversed(t.rows), take))
                t.reset_pending()
                t.collected = new_cursor
        for rows in fallback.values():
            rows.reverse()
        return fast, fallback

    def clear(self) -> None:
        with self._lock:
            self._tables.clear()
