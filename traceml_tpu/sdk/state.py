"""Process-wide trace state
(reference: src/traceml_ai/runtime/state.py:27-91 + sdk/instrumentation.py:104-137).

Holds the step counter, the per-step event buffer, the step-memory
tracker, and the TLS gates the auto-timers consult.  Everything is
RLock-guarded; the hot-path reads are plain attribute loads on a
``threading.local``.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional

from traceml_tpu.utils.step_memory import StepMemoryTracker
from traceml_tpu.utils.timing import (
    GLOBAL_STEP_QUEUE,
    StepEventBuffer,
    StepTimeBatch,
    TimeEvent,
)


class _TLS(threading.local):
    def __init__(self) -> None:
        self.in_step = False
        self.forward_depth = 0
        self.backward_depth = 0
        self.h2d_depth = 0
        self.dataloader_depth = 0
        self.collective_depth = 0
        self.checkpoint_depth = 0


class TraceState:
    """Singleton-ish process state (tests may construct their own)."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self.tls = _TLS()
        self.step_counter = 0
        self.buffer = StepEventBuffer()
        self.mem_tracker: Optional[StepMemoryTracker] = None
        self.initialized = False
        self.patch_mode: Optional[str] = None
        self.active_step_event: Optional[TimeEvent] = None
        self.compile_events_seen = 0  # bumped by the compile tracker
        # wall-clock of the previous trace_step exit: successive steps
        # tile the wall clock, so inter-step host time (input fetch in the
        # idiomatic `for batch in loader: with trace_step():` pattern) is
        # attributed to the step that consumes the batch
        self.last_step_exit: Optional[float] = None
        # per-step device-marker gate, set by trace_step.__enter__ from
        # the overhead governor; marker creators (wrap_step_fn, phase
        # wrappers) consult it so a whole step is either marked or not —
        # mixed rows would skew the window's clock selection
        self.sample_markers = True
        # model FLOPs per training step (set_step_flops / wrap_step_fn's
        # cost-analysis estimate) — the MFU numerator.  flops_source is
        # "manual" | "cost_analysis"; device_kind pins the chip whose
        # peak is the denominator.
        self.flops_per_step: Optional[float] = None
        self.flops_source: Optional[str] = None
        self.flops_device_kind: Optional[str] = None
        # addressable devices behind this process's dispatches: lowered
        # cost_analysis() FLOPs are for the whole (global, pre-partition)
        # program, so with one process driving N chips the MFU
        # denominator must be N × chip peak or the ratio inflates N×
        self.flops_device_count: Optional[int] = None
        # tokens consumed per training step (set_step_tokens): the
        # tokens/s numerator — the throughput number LLM capacity plans
        # quote; optional, independent of FLOPs
        self.tokens_per_step: Optional[float] = None
        # called with the step number after each flush (max-steps lifecycle)
        self.on_step_flushed: List[Callable[[int], None]] = []
        # called with the StepTimeBatch after each non-empty flush
        # (ICI telemetry hook and other batch observers)
        self.on_batch_flushed: List[Callable[[StepTimeBatch], None]] = []

    # -- step lifecycle ------------------------------------------------
    def begin_step(self) -> int:
        with self._lock:
            self.step_counter += 1
            return self.step_counter

    @property
    def current_step(self) -> int:
        with self._lock:
            return self.step_counter

    def ensure_mem_tracker(self) -> StepMemoryTracker:
        mt = self.mem_tracker  # tracelint: unguarded(double-checked init fast path; None race falls through to the locked slow path)
        if mt is not None:
            return mt
        with self._lock:
            if self.mem_tracker is None:
                self.mem_tracker = StepMemoryTracker()
            return self.mem_tracker

    def markers_enabled(self) -> bool:
        """THE device-marker gating policy, in one place.

        Sample markers when the governor chose to for this step, and
        always for out-of-step dispatches (eval loops etc. are not under
        the per-step stride — they carry no step envelope to skew).
        Every marker creator (wrap_step_fn, phase wrappers, trace_time,
        dataloader/h2d patches) must route through this so a whole step
        is either marked or not — a policy fork at one site would
        produce the mixed marked/unmarked rows the window's clock
        selection cannot tolerate.
        """
        return self.sample_markers or not self.tls.in_step

    def mark_step_outputs(self, outputs: Any) -> None:
        """Point the open step envelope's device marker at ``outputs``.

        Called by wrap_step_fn / wrappers after each device dispatch; the
        last call before step exit wins, so the envelope's device end is
        the readiness of the final dispatched phase.  Inert on steps the
        overhead governor chose not to device-sample.
        """
        if not self.sample_markers:
            return
        ev = self.active_step_event
        if ev is not None:
            ev.attach_marker(outputs)

    def flush_step(self, step: int) -> Optional[StepTimeBatch]:
        batch = self.buffer.flush(step)
        if batch is not None:
            GLOBAL_STEP_QUEUE.put(batch)
            for cb in list(self.on_batch_flushed):
                try:
                    cb(batch)
                except Exception:
                    pass
        for cb in list(self.on_step_flushed):
            try:
                cb(step)
            except Exception:
                pass
        return batch


_state = TraceState()


def get_state() -> TraceState:
    return _state


def reset_state_for_tests() -> TraceState:
    """Replace global state (test isolation only).  Also resets the
    overhead governor: its step EMA changes the marker resolver's poll
    schedule, so leaking it across tests makes timing-sensitive suites
    order-dependent."""
    global _state
    from traceml_tpu.utils.overhead_governor import reset_governor_for_tests

    reset_governor_for_tests()
    _state = TraceState()
    return _state
