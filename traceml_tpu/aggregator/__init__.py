"""Out-of-process aggregator (reference: src/traceml_ai/aggregator/)."""
