"""Compare significance thresholds
(reference: src/traceml_ai/reporting/compare/policy.py:55-80 — the
conservative significance policy: small deltas are noise, not verdicts;
the policy is biased toward abstaining rather than overstating).

Tiers: ``negligible`` (below minor threshold — not even reported),
``minor`` and ``major``.  Every section comparer classifies through
:func:`classify` so the tiers are uniform across domains.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

MiB = 1024 * 1024
GiB = 1024 * MiB

SIGNIFICANCE_ORDER = {"negligible": 0, "minor": 1, "major": 2}

# diagnosis kinds ranked by how pathological they are — a candidate run
# moving UP this ladder is a regression signal even when raw deltas are
# small (reference: policy.py step_time_status_rank concept)
DIAGNOSIS_RANK = {
    "NO_DATA": 0,
    "WARMUP": 0,
    "HEALTHY": 1,
    "BALANCED": 1,
    "COMPUTE_BOUND": 2,
    "INPUT_BOUND": 2,
    "H2D_BOUND": 2,
    "RESIDUAL_HEAVY": 3,
    "COMPILE_BOUND": 3,
    "MEMORY_RISING": 2,
    "MEMORY_IMBALANCE": 3,
    "INPUT_STRAGGLER": 4,
    "COMPUTE_STRAGGLER": 4,
    "COLLECTIVE_STRAGGLER": 4,
    "CHECKPOINT_STRAGGLER": 4,
    "H2D_STRAGGLER": 4,
    "RESIDUAL_STRAGGLER": 4,
    "COMPILE_STRAGGLER": 4,
    "STRAGGLER": 4,
    "MEMORY_CREEP": 4,
    "HIGH_PRESSURE": 4,
}


@dataclasses.dataclass(frozen=True)
class ComparePolicy:
    # step average: minor / major relative change
    step_avg_minor: float = 0.03
    step_avg_major: float = 0.08
    # phase share shift in percentage points
    phase_shift_minor_pp: float = 0.75
    phase_shift_major_pp: float = 2.0
    # memory deltas (per-rank peak and global peak)
    memory_minor_bytes: int = 256 * MiB
    memory_major_bytes: int = 1 * GiB
    # cross-rank memory skew shift, percentage points of the median
    memory_skew_minor_pp: float = 0.75
    memory_skew_major_pp: float = 2.5
    # host cpu mean shift, percentage points
    system_cpu_minor_pp: float = 10.0
    system_cpu_major_pp: float = 25.0
    # host memory shift
    system_memory_minor_bytes: int = 512 * MiB
    system_memory_major_bytes: int = 2 * GiB
    # per-rank process cpu shift, percentage points
    process_cpu_minor_pp: float = 15.0
    process_cpu_major_pp: float = 40.0
    # per-rank process rss shift
    process_rss_minor_bytes: int = 256 * MiB
    process_rss_major_bytes: int = 1 * GiB
    # windows smaller than this are too noisy to compare
    min_steps: int = 8


DEFAULT_POLICY = ComparePolicy()


def classify(
    abs_value: Optional[float], minor: float, major: float
) -> str:
    """Uniform three-tier significance classification."""
    if abs_value is None:
        return "negligible"
    v = abs(abs_value)
    if v >= major:
        return "major"
    if v >= minor:
        return "minor"
    return "negligible"


def diagnosis_rank(kind: Optional[str]) -> int:
    return DIAGNOSIS_RANK.get(str(kind or "").upper(), 1)
