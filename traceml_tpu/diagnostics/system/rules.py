"""System (host + chip) rules
(reference: src/traceml_ai/diagnostics/system/rules.py:22-234,
policy.py:16-72; NVML-only rules (temperature, power, GPU util %) have
no public TPU counter — their slots are preserved with device-memory
and host-side equivalents, and utilization insight comes from the
step-time compute share instead).
"""

from __future__ import annotations

import dataclasses
import statistics
from typing import Any, Dict, List, Mapping, Sequence

from traceml_tpu.diagnostics.common import (
    confidence_from,
    SEVERITY_CRITICAL,
    SEVERITY_INFO,
    SEVERITY_WARNING,
    DiagnosticIssue,
)
from traceml_tpu.utils.formatting import fmt_bytes


@dataclasses.dataclass(frozen=True)
class SystemPolicy:
    host_cpu_warn: float = 80.0  # %
    host_cpu_critical: float = 95.0
    host_mem_warn: float = 0.85
    host_mem_critical: float = 0.95
    device_mem_warn: float = 0.92
    device_mem_critical: float = 0.97
    # data-gated device-counter rules: these columns are null on runtimes
    # without the counters (current libtpu), populated where available
    # (reference: system/rules.py utilization/temperature/power rules)
    device_util_low_warn: float = 30.0  # %
    device_util_moderate: float = 70.0  # % — below this is "moderate"
    device_temp_warn: float = 85.0  # °C
    device_temp_critical: float = 95.0
    device_power_warn_frac: float = 0.95  # of rated, when rated known
    device_power_rated_w: float = 0.0  # 0 = unknown → absolute threshold off


DEFAULT_POLICY = SystemPolicy()


@dataclasses.dataclass
class SystemContext:
    # node_rank → host sample rows
    host: Dict[int, List[Dict[str, Any]]]
    # (node_rank, device_id) → device sample rows
    devices: Dict[tuple, List[Dict[str, Any]]]
    policy: SystemPolicy = DEFAULT_POLICY


def build_system_context(
    host_rows: Mapping[int, Sequence[Mapping[str, Any]]],
    device_rows: Mapping[tuple, Sequence[Mapping[str, Any]]],
    policy: SystemPolicy = DEFAULT_POLICY,
) -> SystemContext:
    return SystemContext(
        host={int(k): list(v) for k, v in host_rows.items()},
        devices={k: list(v) for k, v in device_rows.items()},
        policy=policy,
    )


def _recent_mean(rows: List[Dict[str, Any]], key: str, n: int = 30):
    vals = [float(r[key]) for r in rows[-n:] if r.get(key) is not None]
    return statistics.mean(vals) if vals else None


class HighHostCPURule:
    def evaluate(self, ctx: SystemContext) -> List[DiagnosticIssue]:
        issues = []
        p = ctx.policy
        for node, rows in ctx.host.items():
            cpu = _recent_mean(rows, "cpu_pct")
            if cpu is None or cpu < p.host_cpu_warn:
                continue
            severity = (
                SEVERITY_CRITICAL if cpu >= p.host_cpu_critical else SEVERITY_WARNING
            )
            issues.append(
                DiagnosticIssue(
                    kind="HIGH_HOST_CPU",
                    severity=severity,
                    summary=f"Node {node} host CPU at {cpu:.0f}% (recent mean).",
                    action=(
                        "Host CPU saturation starves the input pipeline and "
                        "dispatch: reduce dataloader workers' work per sample, "
                        "move preprocessing offline, or get more host cores."
                    ),
                    metric="host_cpu_pct",
                    score=cpu / 100.0,
                    confidence=confidence_from(cpu, p.host_cpu_warn),
                    ranks=[node],
                )
            )
        return issues


class HighHostMemoryRule:
    def evaluate(self, ctx: SystemContext) -> List[DiagnosticIssue]:
        issues = []
        p = ctx.policy
        for node, rows in ctx.host.items():
            if not rows:
                continue
            last = rows[-1]
            used, total = last.get("memory_used_bytes"), last.get("memory_total_bytes")
            if not used or not total:
                continue
            frac = float(used) / float(total)
            if frac < p.host_mem_warn:
                continue
            severity = (
                SEVERITY_CRITICAL if frac >= p.host_mem_critical else SEVERITY_WARNING
            )
            issues.append(
                DiagnosticIssue(
                    kind="HIGH_HOST_MEMORY",
                    severity=severity,
                    summary=(
                        f"Node {node} host RAM at {frac * 100:.0f}% "
                        f"({fmt_bytes(used)} / {fmt_bytes(total)})."
                    ),
                    action=(
                        "OOM-killer risk: shrink host-side caches/prefetch "
                        "buffers, fewer dataloader workers, stream instead of "
                        "materializing datasets."
                    ),
                    metric="host_mem_pct",
                    score=frac,
                    share_pct=frac,
                    confidence=confidence_from(frac, p.host_mem_warn),
                    ranks=[node],
                )
            )
        return issues


class HighDeviceMemoryRule:
    def evaluate(self, ctx: SystemContext) -> List[DiagnosticIssue]:
        issues = []
        p = ctx.policy
        for (node, dev), rows in ctx.devices.items():
            if not rows:
                continue
            last = rows[-1]
            used, total = last.get("memory_used_bytes"), last.get("memory_total_bytes")
            if not used or not total:
                continue
            frac = float(used) / float(total)
            if frac < p.device_mem_warn:
                continue
            severity = (
                SEVERITY_CRITICAL
                if frac >= p.device_mem_critical
                else SEVERITY_WARNING
            )
            issues.append(
                DiagnosticIssue(
                    kind="HIGH_DEVICE_MEMORY",
                    severity=severity,
                    summary=(
                        f"Node {node} chip {dev} HBM at {frac * 100:.0f}% "
                        f"({fmt_bytes(used)} / {fmt_bytes(total)})."
                    ),
                    action=(
                        "One allocation spike from OOM: add remat, reduce "
                        "microbatch, or rebalance sharding."
                    ),
                    metric="device_mem_pct",
                    score=frac,
                    share_pct=frac,
                    confidence=confidence_from(frac, p.device_mem_warn),
                    ranks=[node],
                    evidence={"device_id": dev},
                )
            )
        return issues


class LowDeviceUtilizationCounterRule:
    """Counter-based low-utilization — fires only where the runtime
    populates ``utilization_pct`` (occupancy-derived utilization from
    the timing core is handled by the step-time domain's
    LOW_DEVICE_UTILIZATION rule; this one covers runtimes that DO expose
    a duty-cycle counter)."""

    def evaluate(self, ctx: SystemContext) -> List[DiagnosticIssue]:
        issues = []
        p = ctx.policy
        for (node, dev), rows in ctx.devices.items():
            util = _recent_mean(rows, "utilization_pct")
            if util is None or util >= p.device_util_moderate:
                continue
            if util < p.device_util_low_warn:
                kind, severity = "LOW_DEVICE_UTILIZATION", SEVERITY_WARNING
                summary = (
                    f"Node {node} chip {dev} duty cycle at {util:.0f}% "
                    "(recent mean) — the accelerator is mostly idle."
                )
            else:  # the 30–70% band (reference: MODERATE_GPU_UTILIZATION)
                kind, severity = "MODERATE_DEVICE_UTILIZATION", SEVERITY_INFO
                summary = (
                    f"Node {node} chip {dev} duty cycle at {util:.0f}% "
                    "(recent mean) — headroom left on the accelerator."
                )
            issues.append(
                DiagnosticIssue(
                    kind=kind,
                    severity=severity,
                    summary=summary,
                    action=(
                        "Feed the chip: prefetch input, increase per-step "
                        "work, check for host-side stalls in the phase table."
                    ),
                    metric="device_utilization_pct",
                    score=1.0 - util / 100.0,
                    share_pct=util / 100.0,
                    ranks=[node],
                    evidence={"device_id": dev},
                )
            )
        return issues


class HighDeviceTemperatureRule:
    def evaluate(self, ctx: SystemContext) -> List[DiagnosticIssue]:
        issues = []
        p = ctx.policy
        for (node, dev), rows in ctx.devices.items():
            temp = _recent_mean(rows, "temperature_c", n=10)
            if temp is None or temp < p.device_temp_warn:
                continue
            severity = (
                SEVERITY_CRITICAL
                if temp >= p.device_temp_critical
                else SEVERITY_WARNING
            )
            issues.append(
                DiagnosticIssue(
                    kind="HIGH_DEVICE_TEMPERATURE",
                    severity=severity,
                    summary=(
                        f"Node {node} chip {dev} at {temp:.0f}°C — thermal "
                        "throttling territory."
                    ),
                    action=(
                        "Sustained heat throttles the clock and skews this "
                        "rank: check cooling/airflow, and expect stragglers "
                        "attributed to this host."
                    ),
                    metric="device_temperature_c",
                    score=temp / 100.0,
                    ranks=[node],
                    evidence={"device_id": dev},
                )
            )
        return issues


class HighDevicePowerRule:
    def evaluate(self, ctx: SystemContext) -> List[DiagnosticIssue]:
        issues = []
        p = ctx.policy
        if p.device_power_rated_w <= 0:
            return []  # no rated power known → absolute rule disabled
        for (node, dev), rows in ctx.devices.items():
            power = _recent_mean(rows, "power_w", n=10)
            if power is None:
                continue
            frac = power / p.device_power_rated_w
            if frac < p.device_power_warn_frac:
                continue
            issues.append(
                DiagnosticIssue(
                    kind="HIGH_DEVICE_POWER",
                    severity=SEVERITY_WARNING,
                    summary=(
                        f"Node {node} chip {dev} drawing {power:.0f}W "
                        f"({frac * 100:.0f}% of rated) — power-limit "
                        "throttling possible."
                    ),
                    action=(
                        "Near the power cap the clock drops under sustained "
                        "load; expect per-rank slowdowns on this host."
                    ),
                    metric="device_power_w",
                    score=frac,
                    ranks=[node],
                    evidence={"device_id": dev},
                )
            )
        return issues


DEFAULT_RULES = (
    HighHostCPURule(),
    HighHostMemoryRule(),
    HighDeviceMemoryRule(),
    LowDeviceUtilizationCounterRule(),
    HighDeviceTemperatureRule(),
    HighDevicePowerRule(),
)
