"""Version of the traceml-tpu framework."""

__version__ = "0.1.0"
