import json

import numpy as np
import pytest

from traceml_tpu.utils import msgpack_codec
from traceml_tpu.utils.atomic_io import (
    atomic_write_json,
    atomic_write_text,
    read_json,
)
from traceml_tpu.utils.formatting import fmt_bytes, fmt_ms, fmt_pct


def test_codec_roundtrip_basic():
    obj = {"a": 1, "b": [1.5, "x", None, True], "nested": {"k": 2}}
    assert msgpack_codec.decode(msgpack_codec.encode(obj)) == obj


def test_codec_numpy_coercion():
    obj = {"arr": np.arange(3), "scalar": np.float32(1.5)}
    out = msgpack_codec.decode(msgpack_codec.encode(obj))
    assert out["arr"] == [0, 1, 2]
    assert abs(out["scalar"] - 1.5) < 1e-6


def test_codec_decodes_json_fallback_frames():
    body = b"\x02" + json.dumps({"x": 1}).encode()
    assert msgpack_codec.decode(body) == {"x": 1}


def test_codec_empty_frame_raises():
    with pytest.raises(msgpack_codec.CodecError):
        msgpack_codec.decode(b"")


def test_codec_decodes_legacy_raw_msgpack_map():
    # reference-style frame: raw msgpack body, no codec prefix
    import msgpack

    raw = msgpack.packb({"kind": "telemetry", "rank": 0}, use_bin_type=True)
    assert raw[0] & 0xF0 == 0x80  # fixmap — exercises the container gate
    assert msgpack_codec.decode(raw) == {"kind": "telemetry", "rank": 0}


def test_codec_prefix_collision_not_misparsed_as_legacy():
    # A raw msgpack body whose first byte is 0x01 (top-level int 1) looks
    # like our msgpack-prefix frame.  The legacy fallback must NOT try
    # raw-msgpack on it (envelopes are maps/arrays, never scalars); the
    # \x01 prefix route must win and report the stripped body as bad.
    import msgpack

    raw_int = msgpack.packb(1)
    assert raw_int == b"\x01"
    with pytest.raises(msgpack_codec.CodecError):
        msgpack_codec.decode(raw_int)  # body empty after prefix strip


def test_atomic_json_roundtrip(tmp_path):
    p = tmp_path / "deep" / "x.json"
    atomic_write_json(p, {"k": [1, 2]})
    assert read_json(p) == {"k": [1, 2]}
    assert read_json(tmp_path / "missing.json", default={}) == {}


def test_atomic_text_no_partial(tmp_path):
    p = tmp_path / "t.txt"
    atomic_write_text(p, "hello")
    atomic_write_text(p, "world")
    assert p.read_text() == "world"
    # no stray tmp files left behind
    assert [f.name for f in tmp_path.iterdir()] == ["t.txt"]


def test_formatting():
    assert fmt_bytes(512) == "512 B"
    assert fmt_bytes(1536) == "1.50 KiB"
    assert fmt_bytes(None) == "n/a"
    assert fmt_ms(0.5).endswith("µs")
    assert fmt_ms(12.3) == "12.3 ms"
    assert fmt_ms(2500) == "2.50 s"
    assert fmt_pct(0.1234) == "12.3%"
