"""Wire contract between per-rank runtimes and the aggregator
(reference: src/traceml_ai/telemetry/)."""

from traceml_tpu.telemetry.envelope import (  # noqa: F401
    SenderIdentity,
    TelemetryEnvelope,
    build_telemetry_envelope,
    normalize_telemetry_envelope,
)
from traceml_tpu.telemetry.control import (  # noqa: F401
    CONTROL_KEY,
    RANK_FINISHED,
    build_rank_finished,
    is_control_message,
    control_kind,
)
