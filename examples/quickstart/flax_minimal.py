"""Minimal Flax/Optax training loop under TraceML-TPU.

Run:  traceml-tpu run --mode cli examples/quickstart/flax_minimal.py
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

import traceml_tpu
from traceml_tpu.models import ModelConfig, init_train_state, make_train_step

traceml_tpu.init(mode="auto")

cfg = ModelConfig(vocab_size=4096, hidden=256, n_layers=4, n_heads=8,
                  n_kv_heads=4, max_seq_len=256)
model, state, tx = init_train_state(cfg, jax.random.PRNGKey(0))
step = traceml_tpu.wrap_step_fn(make_train_step(model, tx), donate_argnums=(0,))

rng = np.random.default_rng(0)


def batches(n=60):
    for _ in range(n):
        yield rng.integers(0, cfg.vocab_size, (8, 256)).astype(np.int32)


for tokens in traceml_tpu.wrap_dataloader(batches()):
    with traceml_tpu.trace_step():
        tokens = jax.device_put(jnp.asarray(tokens))
        state, metrics = step(state, tokens)

print("final loss:", float(metrics["loss"]))
# per-step/live projection works standalone (in-process); the full
# summary() projection needs the aggregator `traceml-tpu run` provides
print(traceml_tpu.live_metrics())
import os

if os.environ.get("TRACEML_SESSION_ID"):  # under the launcher
    print(traceml_tpu.summary())
