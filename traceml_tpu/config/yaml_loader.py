"""traceml.yaml resolution
(reference: src/traceml_ai/config/yaml_loader.py:1-215).

Precedence: CLI > TRACEML_* env > traceml.yaml > built-in defaults.
The yaml file is searched upward from cwd (10 levels).
"""

from __future__ import annotations

from pathlib import Path
from typing import Any, Dict, Optional

YAML_NAME = "traceml.yaml"
_SEARCH_LEVELS = 10

# keys the yaml may set, mapped onto TraceMLSettings field names
VALID_KEYS = {
    "mode": str,
    "logs_dir": str,
    "sampler_interval_sec": float,
    "trace_max_steps": int,
    "run_name": str,
    "finalize_timeout_sec": float,
    "summary_window_rows": int,
    "disk_backup": bool,
    "capture_stderr": bool,
    "aggregator_host": str,
    "aggregator_bind_host": str,
    "aggregator_port": int,
}


def find_yaml(start: Optional[Path] = None) -> Optional[Path]:
    d = Path(start or Path.cwd()).resolve()
    for _ in range(_SEARCH_LEVELS):
        candidate = d / YAML_NAME
        if candidate.is_file():
            return candidate
        if d.parent == d:
            break
        d = d.parent
    return None


def load_yaml_config(path: Optional[Path] = None) -> Dict[str, Any]:
    """Typed, validated yaml config.  A config file the user wrote but we
    cannot honor is warned about loudly — silently ignoring it would
    degrade the run behind their back."""
    import sys

    target = Path(path) if path else find_yaml()
    if target is None or not target.is_file():
        return {}
    try:
        import yaml

        raw = yaml.safe_load(target.read_text(encoding="utf-8")) or {}
    except Exception as exc:
        print(
            f"[TraceML] WARNING: ignoring unreadable {target}: {exc}",
            file=sys.stderr,
        )
        return {}
    if not isinstance(raw, dict):
        print(
            f"[TraceML] WARNING: {target} is not a mapping; ignoring it",
            file=sys.stderr,
        )
        return {}
    out: Dict[str, Any] = {}
    for key, caster in VALID_KEYS.items():
        if key not in raw or raw[key] is None:
            continue
        try:
            if caster is bool and isinstance(raw[key], str):
                out[key] = raw[key].strip().lower() in ("1", "true", "yes", "on")
            else:
                out[key] = caster(raw[key])
        except (TypeError, ValueError):
            print(
                f"[TraceML] WARNING: {target}: bad value for {key!r}; ignored",
                file=sys.stderr,
            )
    unknown = sorted(set(raw) - set(VALID_KEYS))
    if unknown:
        print(
            f"[TraceML] WARNING: {target}: unknown keys ignored: {unknown}",
            file=sys.stderr,
        )
    return out
