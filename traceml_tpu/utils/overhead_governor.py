"""Adaptive tracer-overhead governor.

The reference promises "<1% overhead" but enforces it by construction
(CUDA events are cheap and local).  On TPU the cost model is runtime-
dependent: a PJRT ``is_ready()`` probe is ~1 µs on a local backend but
can be a full RPC round-trip (~0.3 ms) through a tunneled/remote PJRT
client — and a training step can be sub-millisecond when the host loop
is dispatch-bound.  A fixed per-step observation schedule therefore has
no fixed cost: the SAME tracer is 0.02% on one runtime and 30% on
another.

This governor closes the loop: it measures the tracer's own per-marker
cost (probe EMA) against the observed step duration (step EMA) and
adapts the *device-marker sampling stride* so tracer-attributable time
stays under a budget (default 1%, ``TRACEML_OVERHEAD_BUDGET``):

* stride 1 (every step) whenever the budget affords it — local
  backends and realistic step times stay fully sampled, nothing
  changes;
* stride N>1 on expensive-probe or tiny-step runtimes: device markers
  (readiness probes) are created every Nth step only.  Unsampled steps
  still get full HOST-side envelopes and phase regions — only the
  device readiness edge is skipped, so the step-time window degrades
  to the host clock (exactly what ``select_clock`` does when device
  timing is partial) while occupancy keeps flowing from sampled rows;
* inline sweeps (main-thread ``is_ready`` at step boundaries) are
  disabled outright when a single probe is expensive enough to matter
  (> ``inline_probe_ceiling``), shifting stamping to the background
  resolver whose cadence also backs off proportionally to probe cost.

Fail-open and allocation-free on the hot path: one branch + integer
tick per step.
"""

from __future__ import annotations

from traceml_tpu.config import flags

_DEF_BUDGET = 0.01           # tracer share of wall clock
_DEF_INLINE_CEILING = 100e-6  # s; inline sweeps off above this per-probe cost
_FIXED_MARKER_COST = 15e-6   # s; host-side flatten+submit+wake per marker
_PROBES_PER_MARKER = 3.0     # inline sweep + resolver polls, typical
_EMA_ALPHA = 0.2
_MAX_STRIDE = 256
# per-probe samples above this are either scheduling artifacts (a
# descheduled poller measuring its own GIL starvation) or a runtime
# whose probes are catastrophically slow.  CLAMPED, not ignored: the
# two cases are indistinguishable from one sample, and the safe failure
# direction is over-throttling (coarser observation) — discarding would
# leave the governor blind to a genuinely slow runtime, freezing the
# stride/inline policy in its maximum-overhead configuration.  A
# clamped 20 ms sample already drives every knob to full backoff.
_PROBE_SAMPLE_CEILING = 20e-3
_MAX_RESOLVER_DELAY = 0.1  # cap: stamp quality must bound EMA poisoning


class OverheadGovernor:
    """Per-process adaptive sampling policy for device markers."""

    def __init__(
        self,
        budget: float | None = None,
        inline_probe_ceiling: float = _DEF_INLINE_CEILING,
    ) -> None:
        if budget is None:
            budget = flags.OVERHEAD_BUDGET.get_float(_DEF_BUDGET)
        self.budget = max(1e-4, float(budget))
        self.inline_probe_ceiling = float(inline_probe_ceiling)
        # optimistic prior: local-backend probe cost.  The first sweeps
        # correct it within a handful of steps.
        self.probe_cost_ema = 2e-6
        self.step_ema: float | None = None
        # lifetime (dispatch → readiness) of step-end markers: the
        # resolver's sleep-to-expected-completion schedule keys off
        # THIS, not the step envelope — the envelope includes
        # pre-dispatch host time (input wait), which a marker's device
        # work does not (input-straggler regression: sleeping to 85% of
        # a 242 ms envelope stamped a 60 ms compute at ~206 ms)
        self.marker_lifetime_ema: float | None = None
        self._tick = 0
        self._stride = 1
        self._obs = 0

    # -- observations (any thread; lock-free on purpose) ---------------
    # EMA updates race benignly under the GIL (a lost update nudges the
    # EMA by one sample), and the hot path runs once per training step —
    # a lock here would cost more than the statistic is worth.
    def observe_probe(self, total_s: float, n_probes: int) -> None:
        """Feed the measured duration of a batch of is_ready() probes.

        Callers should pass the MINIMUM per-poll duration they saw in a
        batch (robust to a poller thread being descheduled mid-poll);
        samples above the artifact ceiling are CLAMPED to it before
        entering the EMA (see _PROBE_SAMPLE_CEILING — a descheduling
        artifact should register as "expensive", not be unboundedly
        believed)."""
        if n_probes <= 0 or total_s < 0:
            return
        per = min(total_s / n_probes, _PROBE_SAMPLE_CEILING)
        self.probe_cost_ema += _EMA_ALPHA * (per - self.probe_cost_ema)

    def observe_marker_lifetime(self, dur_s: float) -> None:
        """Resolution time of a step-end marker (non-late stamps only —
        a shutdown drain's stamp says nothing about device duration).

        Outlier-gated like observe_probe: a single stalled step
        (blocking checkpoint, retrace) can resolve at seconds; feeding
        it would push the resolver's sleep-to-completion schedule past
        every subsequent step's true readiness, and — because the first
        poll then never lands before 0.85×EMA — the inflated EMA would
        sustain itself.  A lifetime beyond 2× the step EMA is a stall,
        not the steady state."""
        if dur_s <= 0:
            return
        se = self.step_ema
        if se is not None and dur_s > 2.0 * se:
            return
        le = self.marker_lifetime_ema
        self.marker_lifetime_ema = (
            dur_s if le is None else le + _EMA_ALPHA * (dur_s - le)
        )

    def observe_step(self, dur_s: float) -> None:
        if dur_s <= 0:
            return
        se = self.step_ema
        self.step_ema = dur_s if se is None else se + _EMA_ALPHA * (dur_s - se)
        # stride recompute is decimated: the EMAs move slowly and the
        # policy only needs to track them at coarse cadence
        self._obs += 1
        if self._obs % 8 == 0:
            self._stride = self._compute_stride()

    # -- policy --------------------------------------------------------
    def _compute_stride(self) -> int:
        step = self.step_ema
        if step is None or step <= 0:
            return 1
        per_marker = _FIXED_MARKER_COST + _PROBES_PER_MARKER * self.probe_cost_ema
        affordable = self.budget * step
        if per_marker <= affordable:
            return 1
        stride = int(per_marker / affordable) + 1
        return min(_MAX_STRIDE, stride)

    @property
    def marker_stride(self) -> int:
        return self._stride

    def begin_step(self) -> bool:
        """Advance the per-step tick; True ⇒ sample device markers this
        step.  Called once per outermost trace_step."""
        self._tick += 1
        s = self._stride
        return s <= 1 or (self._tick % s) == 0

    def allow_inline_sweep(self) -> bool:
        return self.probe_cost_ema <= self.inline_probe_ceiling

    def resolver_min_delay(self) -> float:
        """Floor for the background resolver's poll cadence: keep the
        resolver thread itself under ~budget of one core by spacing
        polls ≥ probe_cost/budget apart (a 0.3 ms RPC probe at 1%
        budget → ≥30 ms cadence; a 2 µs local probe → no effect).
        Capped so a transiently poisoned EMA cannot collapse stamp
        quality below one poll per _MAX_RESOLVER_DELAY."""
        return min(_MAX_RESOLVER_DELAY, self.probe_cost_ema / self.budget)

    def snapshot(self) -> dict:
        return {
            "budget": self.budget,
            "probe_cost_ema_us": self.probe_cost_ema * 1e6,
            "step_ema_ms": (self.step_ema or 0.0) * 1e3,
            "marker_stride": self._stride,
            "inline_sweep": self.allow_inline_sweep(),
        }


_governor = OverheadGovernor()


def get_governor() -> OverheadGovernor:
    return _governor


def reset_governor_for_tests(**kwargs) -> OverheadGovernor:
    global _governor
    _governor = OverheadGovernor(**kwargs)
    return _governor
