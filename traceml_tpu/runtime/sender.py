"""Telemetry publisher (reference: src/traceml_ai/runtime/sender.py:17-174).

Per tick: collect each sampler sender's incremental payload, encode it
ONCE, hand the same bytes to the TCP batch and the disk backup, ship ONE
frame.  Best-effort all the way down.

Single-encode contract (r10, docs/developer_guide/rank-producer-path.md):

    payload = sender.collect_payload()        # columnar fast path
    enc = msgpack_codec.preencode(payload)    # THE encode
    batch.append(enc)                         # wire splices enc.raw
    writer.append_envelope(enc)               # disk splices enc.raw

Idle ticks take an O(#samplers) gate — ``sender.dirty()`` (one int
compare each) plus ``writer.has_pending()`` — and return without
building a payload, touching the disk, or taking the client lock.

Fault tolerance (docs/developer_guide/fault-tolerance.md):

* every outgoing payload is stamped with a per-rank monotonic ``seq``
  (``time_ns`` base, so a restarted rank resumes above its previous
  range without persisting a counter);
* with a spool directory configured, sends go through
  :class:`~traceml_tpu.transport.spool.DurableSender` — failed batches
  land in a bounded on-disk replay queue and drain on reconnect, with
  the aggregator deduping by seq;
* a ``rank_heartbeat`` control message ships every
  ``heartbeat_interval_s`` even across idle ticks (transient — never
  spooled), keeping the aggregator's liveness tracker fed.

The publisher also self-observes: per-sampler collect/encode/flush
nanoseconds, idle-tick ratio, and the transport/spool counters
(``reconnects``, ``replayed_envelopes``, ``spool_bytes``), exposed via
:meth:`stats` and shipped to the aggregator as a ``producer_stats``
control message (piggybacked on a non-idle batch at most every
``stats_interval_s``).
"""

from __future__ import annotations

import time
from pathlib import Path
from typing import Any, Dict, List, Optional

from traceml_tpu.samplers.base_sampler import BaseSampler
from traceml_tpu.telemetry.control import (
    build_producer_stats,
    build_rank_heartbeat,
    build_transport_hello,
)
from traceml_tpu.telemetry.envelope import SenderIdentity
from traceml_tpu.transport import compression as transport_compression
from traceml_tpu.transport.spool import DurableSender, ReplaySpool
from traceml_tpu.transport.tcp_transport import TCPClient
from traceml_tpu.utils import msgpack_codec
from traceml_tpu.utils.error_log import get_error_log

DEFAULT_HEARTBEAT_INTERVAL_S = 3.0


class TelemetryPublisher:
    def __init__(
        self,
        samplers: List[BaseSampler],
        client: Optional[TCPClient],
        identity: SenderIdentity,
        stats_interval_s: float = 10.0,
        spool_dir: Optional[Path] = None,
        heartbeat_interval_s: float = DEFAULT_HEARTBEAT_INTERVAL_S,
        transport_info: Optional[Dict[str, Any]] = None,
    ) -> None:
        self._samplers = samplers
        self._client = client
        self._identity = identity
        # transport-tier selection ({"kind", "compression", ...}) — see
        # transport/select.py; announced once via transport_hello and
        # reported in stats()
        self._transport_info = transport_info or {}
        self._hello_pending = client is not None
        codec = self._transport_info.get("compression")
        # wire/spool compression: the disk backup keeps the plain enc
        # (local reads should not pay a decompress), while the batch —
        # and therefore the spool, whose frames store exactly the wire
        # body — carries the compressed carrier
        self._compressor = (
            transport_compression.EnvelopeCompressor(codec) if codec else None
        )
        for s in samplers:
            s.sender.set_identity(identity)
            # the publisher owns collection; the writer must never fall
            # back to its legacy self-collecting row path (double-write)
            s.writer.mark_envelope_mode()
        self.ticks = 0
        self.idle_ticks = 0
        self.payloads_sent = 0
        self._stats_interval = stats_interval_s
        self._last_stats_emit = time.monotonic()
        self._heartbeat_interval = max(0.25, float(heartbeat_interval_s))
        self._last_heartbeat = 0.0  # monotonic; 0 → first tick sends one
        # per-rank monotonic seq: time_ns base means a restarted rank
        # (same session, same global_rank) resumes strictly above every
        # seq its previous incarnation could have stamped, so the
        # aggregator's max-seq dedup never swallows fresh telemetry
        self._seq = time.time_ns()
        self._durable: Optional[DurableSender] = None
        if client is not None and spool_dir is not None:
            try:
                self._durable = DurableSender(client, ReplaySpool(spool_dir))
            except Exception as exc:
                get_error_log().warning("replay spool unavailable", exc)
        self._sampler_stats: Dict[str, Dict[str, int]] = {
            s.name: {
                "envelopes": 0,
                "bytes": 0,
                "collect_ns": 0,
                "encode_ns": 0,
                "flush_ns": 0,
            }
            for s in samplers
        }
        # (sender, writer, stats) resolved once: the publish tick is the
        # producer hot path and skips per-tick attribute/dict lookups
        self._units = [
            (s, s.sender, s.writer, self._sampler_stats[s.name])
            for s in samplers
        ]

    def _idle(self) -> bool:
        for s in self._samplers:
            if s.sender.dirty() or s.writer.has_pending():
                return False
        return True

    def _stamp_seq(self, payload: Any) -> None:
        """Stamp the next per-rank seq into ``payload["meta"]``.  Control
        messages get one too — the spool frames every payload uniformly
        (their handlers are idempotent, so they skip the dedup table)."""
        self._seq += 1
        try:
            payload["meta"]["seq"] = self._seq
        except (TypeError, KeyError):
            pass

    def publish(
        self, extra_payloads: Optional[List[Any]] = None, final: bool = False
    ) -> int:
        """Collect + send; returns number of payloads in the batch."""
        self.ticks += 1
        if not final and not extra_payloads and self._idle():
            self.idle_ticks += 1
            self._maybe_heartbeat()
            return 0
        batch: List[Any] = []
        perf = time.perf_counter_ns
        for s, sender, writer, st in self._units:
            try:
                t0 = perf()
                payload = sender.collect_payload()
                t1 = perf()
                st["collect_ns"] += t1 - t0
                if payload is not None:
                    self._stamp_seq(payload)
                    enc = msgpack_codec.preencode(payload)
                    t2 = perf()
                    st["encode_ns"] += t2 - t1
                    st["envelopes"] += 1
                    st["bytes"] += enc.size()
                    if self._compressor is not None:
                        batch.append(self._compressor.wrap(enc))
                    else:
                        batch.append(enc)
                    writer.append_envelope(enc)
                    t3 = perf()
                    writer.flush(force=final)
                    st["flush_ns"] += perf() - t3
                elif final or writer.has_pending():
                    # nothing collected but buffered backup frames (or a
                    # final drain) still need the flush throttle to run
                    t3 = perf()
                    writer.flush(force=final)
                    st["flush_ns"] += perf() - t3
            except Exception as exc:
                get_error_log().warning(
                    f"collect failed for sampler {s.name}", exc
                )
        if extra_payloads:
            for p in extra_payloads:
                self._stamp_seq(p)
            batch.extend(extra_payloads)
        if batch:
            hello = self._take_hello()
            if hello is not None:
                self._stamp_seq(hello)
                batch.insert(0, hello)
            stats_msg = self._maybe_stats_message(final)
            if stats_msg is not None:
                self._stamp_seq(stats_msg)
                batch.append(stats_msg)
        if batch and self._client is not None:
            if self._durable is not None:
                if self._durable.send(batch):
                    self.payloads_sent += len(batch)
                self._last_heartbeat = time.monotonic()
            elif self._client.send_batch(batch):
                self.payloads_sent += len(batch)
                self._last_heartbeat = time.monotonic()
        return len(batch)

    def _take_hello(self) -> Optional[Dict[str, Any]]:
        """The send-once transport_hello announcement (observability:
        which tier and codec this rank selected)."""
        if not self._hello_pending:
            return None
        self._hello_pending = False
        try:
            return build_transport_hello(
                self._identity.to_meta(),
                self._transport_info.get("kind")
                or getattr(self._client, "kind", "tcp"),
                self._transport_info.get("compression"),
                self._transport_info.get("fallback_from"),
            )
        except Exception:
            return None

    def _maybe_heartbeat(self) -> None:
        """Liveness beacon on idle ticks.  Transient (never spooled — a
        replayed heartbeat carries no liveness information), but it
        kicks the durable sender's replay so an idle rank still drains
        its spool the moment the link heals."""
        if self._client is None:
            return
        now = time.monotonic()
        if now - self._last_heartbeat < self._heartbeat_interval:
            return
        self._last_heartbeat = now
        try:
            hb = build_rank_heartbeat(self._identity.to_meta())
            self._stamp_seq(hb)
            msgs = [hb]
            # a fully idle rank still announces its transport once
            hello = self._take_hello()
            if hello is not None:
                self._stamp_seq(hello)
                msgs.insert(0, hello)
            if self._durable is not None:
                self._durable.send_transient(msgs)
            else:
                self._client.send_batch(msgs)
        except Exception as exc:
            get_error_log().warning("heartbeat send failed", exc)

    def _maybe_stats_message(self, final: bool) -> Optional[Dict[str, Any]]:
        """Producer self-observability, piggybacked on a batch that is
        shipping anyway (never turns an idle tick into traffic)."""
        now = time.monotonic()
        if not final and now - self._last_stats_emit < self._stats_interval:
            return None
        self._last_stats_emit = now
        try:
            return build_producer_stats(self._identity.to_meta(), self.stats())
        except Exception:
            return None

    def close(self) -> None:
        if self._durable is not None:
            self._durable.close()

    def stats(self) -> Dict[str, Any]:
        """Per-sampler producer-path cost (microseconds) + idle ratio +
        transport/spool health."""
        samplers: Dict[str, Any] = {}
        for name, st in self._sampler_stats.items():
            samplers[name] = {
                "envelopes": st["envelopes"],
                "bytes": st["bytes"],
                "collect_us": st["collect_ns"] // 1000,
                "encode_us": st["encode_ns"] // 1000,
                "flush_us": st["flush_ns"] // 1000,
            }
        out: Dict[str, Any] = {
            "ticks": self.ticks,
            "idle_ticks": self.idle_ticks,
            "idle_ratio": (self.idle_ticks / self.ticks) if self.ticks else 0.0,
            "payloads_sent": self.payloads_sent,
            "samplers": samplers,
        }
        transport: Dict[str, Any] = {}
        if self._client is not None:
            # getattr: embedders pass client doubles that predate these
            # counters; stats must never take down the publish tick
            transport = {
                "kind": self._transport_info.get("kind")
                or getattr(self._client, "kind", "tcp"),
                "reconnects": getattr(self._client, "reconnects", 0),
                "batches_sent": getattr(self._client, "batches_sent", 0),
                "batches_dropped": getattr(self._client, "batches_dropped", 0),
            }
            ring_full = getattr(self._client, "ring_full_drops", None)
            if ring_full is not None:
                transport["ring_full_drops"] = ring_full
        if self._compressor is not None:
            transport["compression"] = self._compressor.stats()
        if self._durable is not None:
            transport.update(self._durable.stats())
        if transport:
            out["transport"] = transport
        return out
