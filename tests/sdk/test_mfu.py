"""MFU plumbing: FLOPs declaration/estimation → telemetry →
achieved-TFLOP/s + MFU in the step-time section (TPU-first metric, no
reference counterpart)."""

import jax
import jax.numpy as jnp

from traceml_tpu.sdk.state import get_state, reset_state_for_tests
from traceml_tpu.utils.chip_specs import peak_flops_for


def test_peak_flops_table():
    assert peak_flops_for("TPU v5p") == 459e12
    assert peak_flops_for("TPU v5 lite") == 197e12  # before the bare v5 guess
    assert peak_flops_for("TPU v6e") == 918e12
    assert peak_flops_for("TPU v4") == 275e12
    assert peak_flops_for("TFRT_CPU") is None
    assert peak_flops_for(None) is None


def test_set_step_flops_manual():
    import traceml_tpu

    reset_state_for_tests()
    traceml_tpu.set_step_flops(1.5e12, device_kind="TPU v5p")
    st = get_state()
    assert st.flops_per_step == 1.5e12
    assert st.flops_source == "manual"
    assert st.flops_device_kind == "TPU v5p"


def test_wrap_step_fn_estimates_flops_on_first_in_step_call():
    import traceml_tpu
    from traceml_tpu.sdk.step_fn import wrap_step_fn

    reset_state_for_tests()

    def step(x, y):
        return (x @ y).sum()

    wrapped = wrap_step_fn(step)
    x = jnp.ones((64, 128))
    y = jnp.ones((128, 32))
    with traceml_tpu.trace_step():
        wrapped(x, y)
    st = get_state()
    assert st.flops_per_step is not None
    # 2*M*K*N matmul flops, give-or-take the reduction
    assert st.flops_per_step >= 2 * 64 * 128 * 32
    assert st.flops_per_step < 4 * 64 * 128 * 32
    assert st.flops_source == "cost_analysis"


def test_out_of_step_eval_fn_does_not_publish_flops():
    """A wrapped EVAL fn dispatched outside trace_step (common pattern)
    must not claim the MFU numerator just because it ran first."""
    import traceml_tpu
    from traceml_tpu.sdk.step_fn import wrap_step_fn

    reset_state_for_tests()
    eval_fn = wrap_step_fn(lambda x: (x * 2).sum())
    eval_fn(jnp.ones((8, 8)))  # out-of-step: no estimate
    assert get_state().flops_per_step is None
    train = wrap_step_fn(lambda x, y: (x @ y).sum())
    with traceml_tpu.trace_step():
        train(jnp.ones((32, 64)), jnp.ones((64, 16)))
    flops = get_state().flops_per_step
    assert flops is not None and flops >= 2 * 32 * 64 * 16


def test_manual_value_wins_over_estimate():
    import traceml_tpu
    from traceml_tpu.sdk.step_fn import wrap_step_fn

    reset_state_for_tests()
    traceml_tpu.set_step_flops(7.0e9)
    wrapped = wrap_step_fn(lambda x: (x * 2).sum())
    with traceml_tpu.trace_step():
        wrapped(jnp.ones((8, 8)))
    assert get_state().flops_per_step == 7.0e9
    assert get_state().flops_source == "manual"


def test_estimate_opt_out():
    import traceml_tpu
    from traceml_tpu.sdk.step_fn import wrap_step_fn

    reset_state_for_tests()
    wrapped = wrap_step_fn(lambda x: (x @ x).sum(), estimate_flops=False)
    with traceml_tpu.trace_step():
        wrapped(jnp.ones((16, 16)))
    assert get_state().flops_per_step is None


def _window(step_ms=100.0, compute_ms=90.0, n=60):
    from traceml_tpu.utils import timing as T
    from traceml_tpu.utils.step_time_window import build_step_time_window

    rows = [
        {
            "step": i,
            "timestamp": float(i),
            "clock": "device",
            "events": {
                T.STEP_TIME: {"cpu_ms": step_ms, "device_ms": step_ms, "count": 1},
                T.COMPUTE_TIME: {"cpu_ms": 0.5, "device_ms": compute_ms, "count": 1},
            },
        }
        for i in range(1, n + 1)
    ]
    return build_step_time_window({0: rows})


def test_low_mfu_rule_fires_when_compute_bound_and_wasteful():
    from traceml_tpu.diagnostics.step_time.api import diagnose_window

    eff = {
        "mfu_median": 0.08, "achieved_tflops_median": 36.7,
        "peak_tflops": 459.0, "device_kind": "TPU v5p",
        "flops_source": "cost_analysis",
    }
    result = diagnose_window(_window(), mode="summary", efficiency=eff)
    issue = next(i for i in result.issues if i.kind == "LOW_MFU")
    assert issue.severity == "warning"
    assert issue.evidence["compute_share"] > 0.5
    # moderate band → info
    eff["mfu_median"] = 0.22
    result = diagnose_window(_window(), mode="summary", efficiency=eff)
    issue = next(i for i in result.issues if i.kind == "MODERATE_MFU")
    assert issue.severity == "info"
    # healthy MFU → silent
    eff["mfu_median"] = 0.45
    result = diagnose_window(_window(), mode="summary", efficiency=eff)
    assert not any("MFU" in i.kind for i in result.issues)


def test_low_mfu_gated_on_compute_share():
    """An input-bound job's low MFU is the input's fault — no MFU
    verdict when compute doesn't dominate."""
    from traceml_tpu.diagnostics.step_time.api import diagnose_window

    eff = {"mfu_median": 0.05, "achieved_tflops_median": 10.0,
           "peak_tflops": 459.0, "device_kind": "TPU v5p"}
    result = diagnose_window(
        _window(step_ms=100.0, compute_ms=30.0), mode="summary", efficiency=eff
    )
    assert not any("MFU" in i.kind for i in result.issues)


def test_no_efficiency_no_mfu_verdict():
    from traceml_tpu.diagnostics.step_time.api import diagnose_window

    result = diagnose_window(_window(), mode="summary")
    assert not any("MFU" in i.kind for i in result.issues)


def test_sampler_publishes_model_stats_once(tmp_path):
    import traceml_tpu
    from traceml_tpu.samplers.step_time_sampler import StepTimeSampler

    reset_state_for_tests()
    sampler = StepTimeSampler()
    traceml_tpu.set_step_flops(2.0e12, device_kind="TPU v5p")
    sampler.sample()
    sampler.sample()  # unchanged → no second row
    rows = sampler.db.tail("model_stats", 10)
    assert len(rows) == 1
    assert rows[0]["flops_per_step"] == 2.0e12
    assert rows[0]["peak_flops"] == 459e12
    traceml_tpu.set_step_flops(3.0e12)  # changed → one more row
    sampler.sample()
    assert len(sampler.db.tail("model_stats", 10)) == 2
    # a device_kind correction with UNCHANGED flops republishes too
    traceml_tpu.set_step_flops(3.0e12, device_kind="TPU v6e")
    sampler.sample()
    rows = sampler.db.tail("model_stats", 10)
    assert len(rows) == 3 and rows[-1]["peak_flops"] == 918e12


def test_efficiency_scales_denominator_by_device_count():
    """cost_analysis() FLOPs are for the whole pre-partition program:
    one process driving 4 chips must be judged against 4 chips' peak
    (ADVICE r2 medium — MFU was inflated N× before)."""
    from traceml_tpu.analytics.efficiency import build_efficiency

    stats = {0: {"flops_per_step": 459e12 * 0.4 * 4,  # 40% MFU on 4 chips
                 "flops_source": "cost_analysis", "device_kind": "TPU v5p",
                 "peak_flops": 459e12, "device_count": 4}}
    eff = build_efficiency(stats, {0: 1000.0})  # 1 s/step
    assert eff is not None
    assert abs(eff["mfu_median"] - 0.4) < 1e-6
    assert eff["device_count"] == 4
    # without device_count the old single-chip semantics hold
    stats[0]["device_count"] = None
    eff = build_efficiency(stats, {0: 1000.0})
    assert abs(eff["mfu_median"] - 1.6) < 1e-6


def test_efficiency_uses_each_ranks_own_declaration():
    """Heterogeneous declarations (pipeline stages, mixed chips) must
    not silently inherit rank 0's numbers (ADVICE r2 low)."""
    from traceml_tpu.analytics.efficiency import build_efficiency

    stats = {
        0: {"flops_per_step": 100e12, "flops_source": "manual",
            "device_kind": "TPU v5p", "peak_flops": 459e12,
            "device_count": 1},
        1: {"flops_per_step": 200e12, "flops_source": "manual",
            "device_kind": "TPU v6e", "peak_flops": 918e12,
            "device_count": 1},
    }
    eff = build_efficiency(stats, {0: 1000.0, 1: 1000.0})
    by_rank = eff["achieved_tflops_by_rank"]
    assert by_rank["0"] == 100.0 and by_rank["1"] == 200.0
    # a rank with NO declaration falls back to the first declaring rank
    eff = build_efficiency(stats, {0: 1000.0, 1: 1000.0, 2: 500.0})
    assert eff["achieved_tflops_by_rank"]["2"] == 200.0


# -- tokens/s (set_step_tokens, r4) ----------------------------------------

def test_tokens_per_sec_in_efficiency_block():
    from traceml_tpu.analytics.efficiency import build_efficiency

    stats = {
        0: {"flops_per_step": 100e12, "flops_source": "manual",
            "device_kind": "TPU v5p", "peak_flops": 459e12,
            "device_count": 1, "tokens_per_step": 8192.0},
    }
    eff = build_efficiency(stats, {0: 1000.0})  # 1 s steps
    assert eff["tokens_per_sec_median"] == 8192.0
    assert eff["tokens_per_step"] == 8192.0
    assert eff["achieved_tflops_median"] == 100.0


def test_tokens_only_declaration_still_builds_block():
    """set_step_tokens without set_step_flops: tokens/s reports,
    TFLOP/s and MFU stay null — no crash on any surface."""
    from traceml_tpu.analytics.efficiency import build_efficiency

    stats = {0: {"flops_per_step": None, "flops_source": None,
                 "device_kind": None, "peak_flops": None,
                 "device_count": None, "tokens_per_step": 4096.0}}
    eff = build_efficiency(stats, {0: 500.0})  # 0.5 s steps
    assert eff["tokens_per_sec_median"] == 8192.0
    assert eff["achieved_tflops_median"] is None
    assert eff["mfu_median"] is None
    # the text card renders without TypeError
    from traceml_tpu.reporting.final import _step_time_card

    card = _step_time_card({
        "global": {"clock": "device", "n_steps": 60,
                   "step_range": [1, 60], "efficiency": eff,
                   "phases": {"step_time": {"median_ms": 500.0,
                                            "worst_ms": 500.0,
                                            "worst_rank": 0,
                                            "skew_pct": 0.0,
                                            "share_of_step": None}}},
    })
    assert "8,192 tokens/s" in card


def test_set_step_tokens_ships_through_sampler(tmp_path):
    import traceml_tpu
    from traceml_tpu.sdk import state as state_mod

    state_mod.reset_state_for_tests()
    traceml_tpu.set_step_tokens(2048)
    assert state_mod.get_state().tokens_per_step == 2048.0


def test_mixed_declarations_report_both_numerators():
    """One rank flops-only, another tokens-only: both rates populate and
    both numerators are reported (review r4 — ms0 alone lost one)."""
    from traceml_tpu.analytics.efficiency import build_efficiency

    stats = {
        0: {"flops_per_step": 100e12, "flops_source": "manual",
            "device_kind": "TPU v5p", "peak_flops": 459e12,
            "device_count": 1, "tokens_per_step": None},
        1: {"flops_per_step": None, "flops_source": None,
            "device_kind": None, "peak_flops": None,
            "device_count": None, "tokens_per_step": 4096.0},
    }
    eff = build_efficiency(stats, {0: 1000.0, 1: 1000.0})
    assert eff["flops_per_step"] == 100e12
    assert eff["tokens_per_step"] == 4096.0
    assert eff["tokens_per_sec_median"] is not None
    assert eff["achieved_tflops_median"] is not None
    # and the text card renders with no TypeError either way around
    from traceml_tpu.reporting.final import _step_time_card

    card = _step_time_card({
        "global": {"clock": "device", "n_steps": 60,
                   "step_range": [1, 60],
                   "efficiency": dict(eff, flops_per_step=None),
                   "phases": {"step_time": {"median_ms": 100.0,
                                            "worst_ms": 100.0,
                                            "worst_rank": 0,
                                            "skew_pct": 0.0,
                                            "share_of_step": None}}},
    })
    assert "TFLOP/s achieved" in card


def test_mixed_declarations_metadata_from_flops_declaration():
    """Under mixed declarations (rank 0 tokens-only, rank 1 flops), the
    headline numerator AND its source/chip/peak metadata must come from
    the SAME declaration — not a real FLOPs value paired with the
    tokens-only rank's null metadata (advisor r4)."""
    from traceml_tpu.analytics.efficiency import build_efficiency

    stats = {
        0: {"flops_per_step": None, "flops_source": None,
            "device_kind": None, "peak_flops": None,
            "device_count": None, "tokens_per_step": 4096.0},
        1: {"flops_per_step": 200e12, "flops_source": "cost_analysis",
            "device_kind": "TPU v6e", "peak_flops": 918e12,
            "device_count": 2},
    }
    eff = build_efficiency(stats, {0: 1000.0, 1: 1000.0})
    assert eff["flops_per_step"] == 200e12
    assert eff["flops_source"] == "cost_analysis"
    assert eff["device_kind"] == "TPU v6e"
    assert eff["device_count"] == 2
    assert eff["peak_tflops"] == 918.0
    assert eff["tokens_per_step"] == 4096.0
