"""Process supervision utilities
(reference: src/traceml_ai/launcher/process.py:30-300).

Beyond bare spawn/terminate, the launcher keeps a bounded STDERR RING
per supervised child: stderr is teed through to the launcher's own
stderr (live visibility unchanged) while the last 64 KiB are retained
in memory.  When a child dies abnormally — including signal deaths
(segfault, OOM-kill) that bypass every in-process crash hook — the ring
is flushed to ``<session>/rank_<r>/crash_stderr.log`` so the death is
diagnosable from artifacts alone.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
from pathlib import Path
from typing import Dict, List, Optional

from traceml_tpu.utils.atomic_io import read_json

STDERR_RING_LIMIT = 64 * 1024


class StderrRing:
    """Drain a child's stderr on a daemon thread: tee every chunk to
    ``sink`` and retain the newest ``limit`` bytes."""

    def __init__(self, stream, limit: int = STDERR_RING_LIMIT, sink=None):
        self._stream = stream
        self._limit = int(limit)
        self._buf = bytearray()
        self._lock = threading.Lock()
        self._sink = sink
        self.truncated = False
        self._thread = threading.Thread(
            target=self._drain, name="traceml-stderr-ring", daemon=True
        )
        self._thread.start()

    def _drain(self) -> None:
        sink = self._sink
        if sink is None:
            sink = getattr(sys.stderr, "buffer", None)
        try:
            for chunk in iter(lambda: self._stream.read1(8192), b""):
                with self._lock:
                    self._buf.extend(chunk)
                    if len(self._buf) > self._limit:
                        del self._buf[: len(self._buf) - self._limit]
                        self.truncated = True
                if sink is not None:
                    try:
                        sink.write(chunk)
                        sink.flush()
                    except (OSError, ValueError):
                        sink = None  # parent stderr gone; keep ringing
        except (OSError, ValueError):
            pass  # child closed / killed mid-read

    def join(self, timeout: float = 5.0) -> None:
        self._thread.join(timeout)

    def tail(self) -> bytes:
        with self._lock:
            return bytes(self._buf)


class SupervisedChild:
    """A spawned child plus its stderr ring and crash-log writer."""

    def __init__(self, proc: subprocess.Popen, label: str):
        self.proc = proc
        self.label = label
        self.ring = StderrRing(proc.stderr) if proc.stderr else None
        self._crash_written: Optional[Path] = None

    def poll(self):
        return self.proc.poll()

    @property
    def returncode(self):
        return self.proc.returncode

    def describe_exit(self) -> str:
        rc = self.proc.returncode
        if rc is not None and rc < 0:
            try:
                name = signal.Signals(-rc).name
            except ValueError:
                name = f"signal {-rc}"
            return f"killed by {name}"
        return f"exit code {rc}"

    def write_crash_log(self, session_dir: Path) -> Optional[Path]:
        """Flush the ring to ``<session>/<label>/crash_stderr.log``
        (idempotent; written even when the ring is empty — a silent
        SIGKILL still deserves an artifact naming the signal)."""
        if self._crash_written is not None:
            return self._crash_written
        if self.ring is not None:
            self.ring.join(timeout=2.0)
        path = Path(session_dir) / self.label / "crash_stderr.log"
        tail = self.ring.tail() if self.ring is not None else b""
        header = (
            f"# {self.label} died abnormally: {self.describe_exit()}\n"
            f"# captured {len(tail)} bytes of stderr"
            f"{' (ring truncated to newest 64 KiB)' if self.ring is not None and self.ring.truncated else ''}\n"
        ).encode()
        try:
            path.parent.mkdir(parents=True, exist_ok=True)
            tmp = path.with_suffix(".tmp")
            tmp.write_bytes(header + tail)
            os.replace(tmp, path)
        except OSError:
            return None
        self._crash_written = path
        return path


def spawn_supervised(
    argv: List[str],
    label: str,
    env: Optional[Dict[str, str]] = None,
    cwd: Optional[str] = None,
) -> SupervisedChild:
    """Spawn with a stderr ring (see module docstring)."""
    proc = spawn(argv, env=env, cwd=cwd, stderr=subprocess.PIPE)
    return SupervisedChild(proc, label)


def spawn(
    argv: List[str],
    env: Optional[Dict[str, str]] = None,
    cwd: Optional[str] = None,
    stdout=None,
    stderr=None,
) -> subprocess.Popen:
    """Start a child in its own process group so we can terminate the
    whole tree."""
    full_env = dict(os.environ)
    if env:
        full_env.update(env)
    kwargs = {}
    if os.name == "posix":
        kwargs["start_new_session"] = True
    return subprocess.Popen(
        argv,
        env=full_env,
        cwd=cwd,
        stdout=stdout,
        stderr=stderr,
        **kwargs,
    )


def terminate(proc: subprocess.Popen, grace_sec: float = 10.0) -> int:
    """SIGTERM the process group, escalate to SIGKILL after the grace
    period; returns the exit code."""
    if proc.poll() is not None:
        return proc.returncode
    try:
        if os.name == "posix":
            os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
        else:  # pragma: no cover
            proc.terminate()
    except (ProcessLookupError, PermissionError):
        pass
    deadline = time.monotonic() + grace_sec
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            return proc.returncode
        time.sleep(0.1)
    try:
        if os.name == "posix":
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        else:  # pragma: no cover
            proc.kill()
    except (ProcessLookupError, PermissionError):
        pass
    proc.wait(timeout=10)
    return proc.returncode


def wait_for_ready_file(path: Path, timeout: float = 30.0) -> Optional[dict]:
    """Poll the aggregator's ready file for the bound port
    (replaces the reference's TCP-listen poll — the file also carries
    the ephemeral port, which a connect probe cannot discover)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        data = read_json(path)
        if data and data.get("port"):
            return data
        time.sleep(0.1)
    return None


def python_argv(module: str) -> List[str]:
    return [sys.executable, "-m", module]
