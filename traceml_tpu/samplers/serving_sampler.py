"""Serving sampler — per-window inference/serving telemetry.

Drains the global serving queue (fed by the lifecycle recorders in
instrumentation/serving.py) and folds the raw per-event records into
ONE aggregate row per sampler window::

    {step, timestamp, requests_enqueued, requests_completed,
     requests_active, queue_depth, decode_tokens, prefill_ms, decode_ms,
     tokens_per_s, batch_occupancy, ttft_p50_ms, ttft_p95_ms,
     ttft_p99_ms, e2e_p50_ms, e2e_p95_ms, e2e_p99_ms,
     kv_bytes, kv_limit_bytes, kv_headroom,
     ttft_ms_list, e2e_ms_list, tokens_list}

``step`` is a per-replica window sequence number — serving has no
training step, but a monotone window index gives the (rank × step)
columnar cube the same alignment key the training domains use.  The
``*_list`` columns carry the window's PER-REQUEST values packed as
``%.3f`` comma strings: percentiles of percentiles are wrong, so the
window build (utils/columnar.py ``RaggedEventColumns``) re-ranks the
raw populations across windows and replicas instead of averaging the
row-level p99s (which exist for ``traceml inspect`` convenience).

Aggregating here bounds the wire at one row per window per replica
regardless of request fan-out — a thousand requests in a window cost
the same fixed columns plus ~12 bytes per completed request.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from traceml_tpu.instrumentation.serving import (
    EV_DECODE,
    EV_ENQUEUED,
    EV_FINISHED,
    EV_PREFILL_END,
    EV_PREFILL_START,
    GLOBAL_SERVING_QUEUE,
    sample_kv_cache,
)
from traceml_tpu.samplers.base_sampler import BaseSampler

TABLE = "serving"

#: in-flight table bound — a leaked request (enqueued, never finished)
#: must not grow state forever; oldest entries are dropped past this
_MAX_INFLIGHT = 4096


def percentile(sorted_vals: List[float], q: float) -> float:
    """Index-style percentile over an ascending list — the exact formula
    the window build and the diagnosis rules share (no interpolation, so
    scalar and columnar paths pick the same element)."""
    n = len(sorted_vals)
    if n == 0:
        return 0.0
    return float(sorted_vals[min(n - 1, int(n * q))])


def pack_floats(vals: List[float]) -> str:
    """``%.3f`` comma packing — the one formatting both the packer and
    the ragged-ring parser use, so parse(pack(x)) is bit-stable."""
    return ",".join(f"{float(v):.3f}" for v in vals)


class _Request:
    __slots__ = ("enq_ts", "prefill_start_ts", "prefill_end_ts", "prompt_tokens", "tokens")

    def __init__(self, enq_ts: float) -> None:
        self.enq_ts = enq_ts
        self.prefill_start_ts: Optional[float] = None
        self.prefill_end_ts: Optional[float] = None
        self.prompt_tokens = 0
        self.tokens = 0


class ServingAccumulator:
    """Pure event→row fold (unit-testable and bench-drivable without a
    runtime): ``feed()`` events in arrival order, then ``window_row()``
    closes the current window and returns its aggregate row (or None
    when the replica has never seen a serving event)."""

    def __init__(self, now: Optional[float] = None) -> None:
        self._inflight: Dict[str, _Request] = {}
        self._window_start = time.time() if now is None else float(now)
        self._seq = 0
        self._seen_any = False
        # per-window accumulators
        self._enqueued = 0
        self._decode_tokens = 0
        self._ttft_ms: List[float] = []
        self._e2e_ms: List[float] = []
        self._req_tokens: List[int] = []
        self._prefill_ms = 0.0
        self._decode_ms = 0.0

    def feed(self, events: List[Dict[str, Any]]) -> None:
        for ev in events:
            try:
                kind = ev["ev"]
                req = str(ev["req"])
                ts = float(ev["ts"])
            except (KeyError, TypeError, ValueError):
                continue
            self._seen_any = True
            if kind == EV_ENQUEUED:
                self._enqueued += 1
                if len(self._inflight) >= _MAX_INFLIGHT:
                    oldest = next(iter(self._inflight))
                    del self._inflight[oldest]
                self._inflight[req] = _Request(ts)
                continue
            r = self._inflight.get(req)
            if r is None:
                continue  # lifecycle event for an unknown/evicted request
            if kind == EV_PREFILL_START:
                r.prefill_start_ts = ts
                r.prompt_tokens = int(ev.get("tokens", 0) or 0)
            elif kind == EV_PREFILL_END:
                r.prefill_end_ts = ts
            elif kind == EV_DECODE:
                n = int(ev.get("tokens", 0) or 0)
                r.tokens += n
                self._decode_tokens += n
            elif kind == EV_FINISHED:
                self._finish(req, r, ts)

    def _finish(self, req: str, r: _Request, ts: float) -> None:
        del self._inflight[req]
        pe = r.prefill_end_ts if r.prefill_end_ts is not None else ts
        ps = r.prefill_start_ts if r.prefill_start_ts is not None else r.enq_ts
        self._ttft_ms.append(max(0.0, (pe - r.enq_ts) * 1000.0))
        self._e2e_ms.append(max(0.0, (ts - r.enq_ts) * 1000.0))
        self._req_tokens.append(r.tokens)
        self._prefill_ms += max(0.0, (pe - ps) * 1000.0)
        self._decode_ms += max(0.0, (ts - pe) * 1000.0)

    @property
    def seen_any(self) -> bool:
        return self._seen_any

    def window_row(
        self, now: Optional[float] = None, kv: Optional[Dict[str, Any]] = None
    ) -> Optional[Dict[str, Any]]:
        """Close the window at ``now``; returns the aggregate row, or
        None when no serving event was ever observed (a pure-training
        session emits NOTHING — the byte-identity contract)."""
        if not self._seen_any:
            return None
        now = time.time() if now is None else float(now)
        dt_s = max(1e-9, now - self._window_start)
        ttft = sorted(self._ttft_ms)
        e2e = sorted(self._e2e_ms)
        queue_depth = sum(
            1 for r in self._inflight.values() if r.prefill_start_ts is None
        )
        kv = kv or {}
        row = {
            "step": self._seq,
            "timestamp": now,
            "requests_enqueued": self._enqueued,
            "requests_completed": len(self._ttft_ms),
            "requests_active": len(self._inflight),
            "queue_depth": queue_depth,
            "decode_tokens": self._decode_tokens,
            "prefill_ms": round(self._prefill_ms, 3),
            "decode_ms": round(self._decode_ms, 3),
            "tokens_per_s": round(self._decode_tokens / dt_s, 3),
            "batch_occupancy": round(self._decode_ms / (dt_s * 1000.0), 4),
            "ttft_p50_ms": round(percentile(ttft, 0.50), 3),
            "ttft_p95_ms": round(percentile(ttft, 0.95), 3),
            "ttft_p99_ms": round(percentile(ttft, 0.99), 3),
            "e2e_p50_ms": round(percentile(e2e, 0.50), 3),
            "e2e_p95_ms": round(percentile(e2e, 0.95), 3),
            "e2e_p99_ms": round(percentile(e2e, 0.99), 3),
            "kv_bytes": int(kv.get("kv_bytes", -1) if kv else -1),
            "kv_limit_bytes": int(kv.get("kv_limit_bytes", -1) if kv else -1),
            "kv_headroom": round(float(kv.get("kv_headroom", -1.0)), 4)
            if kv
            else -1.0,
            "ttft_ms_list": pack_floats(self._ttft_ms),
            "e2e_ms_list": pack_floats(self._e2e_ms),
            "tokens_list": ",".join(str(int(t)) for t in self._req_tokens),
        }
        # roll the window (in-flight requests carry over)
        self._seq += 1
        self._window_start = now
        self._enqueued = 0
        self._decode_tokens = 0
        self._ttft_ms = []
        self._e2e_ms = []
        self._req_tokens = []
        self._prefill_ms = 0.0
        self._decode_ms = 0.0
        return row


class ServingSampler(BaseSampler):
    name = "serving"

    def __init__(self, *args: Any, **kw: Any):
        super().__init__(*args, **kw)
        self.acc = ServingAccumulator()
        self.rows_emitted = 0

    def _sample(self) -> None:
        events = GLOBAL_SERVING_QUEUE.drain()
        if events:
            self.acc.feed(events)
        row = self.acc.window_row(kv=sample_kv_cache())
        if row is None:
            return
        self.db.add_record(TABLE, row)
        self.rows_emitted += 1

    def drain(self) -> None:
        """End-of-run: fold whatever is still queued into a final row."""
        self._sample()
