"""Project-level static code scan
(reference: src/traceml_ai/utils/ast_analysis/scanner.py:59-369 — the
reference walks local imports from the entry script and extracts
framework/strategy/precision/QLoRA signals; rebuilt here around one
visitor shared by the single-file and project-level paths, tuned for
JAX/TPU signals first).

``analyze_script``  — one file (the round-1 scanner, extended).
``analyze_project`` — entry file + bounded BFS over its LOCAL imports
(modules resolvable to files under the script's directory), merged into
one manifest with per-module provenance.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Any, Dict, List, Optional, Set

_MAX_MODULES = 24
_MAX_FILE_BYTES = 512 * 1024


#: host-sync leaf attrs — each forces a device→host round trip
_SYNC_ATTRS = (
    "item", "cpu", "numpy", "tolist", "block_until_ready", "device_get",
)
#: calls that mark a loop as training-like (torch AND jax vocabularies)
_TRAIN_MARKERS = (
    "backward", "zero_grad", "apply_gradients", "apply_updates",
    "trace_step", "train_step",
)
#: markers valid only as a BARE NAME call — ``step(state, batch)`` is
#: the canonical jitted-jax-step idiom, but the attribute form
#: (scheduler.step(), env.step(), optimizer.step() without backward)
#: matches far too much non-training code (advisor r4)
_TRAIN_NAME_MARKERS = _TRAIN_MARKERS + ("step",)


def _receiver_is_optimizer(node: ast.AST) -> bool:
    """Any name/attr along the receiver chain mentions an optimizer —
    handles `optimizer`, `self.optimizer`, `optimizers[0]`, and
    `self.optimizers()[0]` receivers alike."""
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and "opt" in n.id.lower():
            return True
        if isinstance(n, ast.Attribute) and "opt" in n.attr.lower():
            return True
    return False


class _ScriptVisitor(ast.NodeVisitor):
    def __init__(self) -> None:
        self.imports: Set[str] = set()        # top-level names
        self.import_modules: Set[str] = set()  # full dotted module names
        # (level, module-or-"", names) for `from . import x` forms —
        # resolved against the IMPORTING file's package, not the entry
        self.relative_imports: List[tuple] = []
        self.calls: List[str] = []
        self.attrs: List[str] = []
        # call name → list of per-call {kwarg: literal value} (a script
        # may build several DataLoaders with different configs)
        self.call_kwargs: Dict[str, List[Dict[str, Any]]] = {}
        # per-site classification (reference role: ast_analysis/
        # visitor.py:498-565 — sync calls, H2D idioms, and loop flags
        # are classified PER CALL SITE with training-loop context, not
        # just noted to exist)
        self.sync_sites: Dict[str, Dict[str, Any]] = {}
        self.h2d: Dict[str, Any] = {
            "to_device": False, "non_blocking": False,
            "device_put_count": 0, "h2d_in_loop": 0,
        }
        self.loop_flags: Dict[str, bool] = {}
        self.distributed_sampler_used = False
        self.set_epoch_called = False
        self._loop_stack: List[bool] = []  # is-training per open loop

    # -- loop context ------------------------------------------------

    def _loop_is_training(self, loop: ast.AST) -> bool:
        for child in ast.walk(loop):
            if isinstance(child, ast.Call):
                f = child.func
                if isinstance(f, ast.Attribute):
                    if f.attr in _TRAIN_MARKERS:
                        return True
                    # attribute .step() counts only on an optimizer-named
                    # receiver: catches `optimizer.step(closure)` (LBFGS,
                    # where backward lives in the closure outside the
                    # loop) and `optimizers[0].step()` without
                    # re-admitting scheduler/env/tqdm .step false
                    # positives (review r5)
                    if f.attr == "step" and _receiver_is_optimizer(f.value):
                        return True
                if isinstance(f, ast.Name) and f.id in _TRAIN_NAME_MARKERS:
                    return True
        return False

    def _in_train_loop(self) -> bool:
        return any(self._loop_stack)

    def visit_For(self, node: ast.For) -> None:
        self._loop_stack.append(self._loop_is_training(node))
        self.generic_visit(node)
        self._loop_stack.pop()

    visit_AsyncFor = visit_For

    def visit_While(self, node: ast.While) -> None:
        self._loop_stack.append(self._loop_is_training(node))
        self.generic_visit(node)
        self._loop_stack.pop()

    _KWARG_TARGETS = (
        "DataLoader",
        "TrainingArguments",
        "jit",
        "pjit",
        "Trainer",
        "BitsAndBytesConfig",
        "LoraConfig",
        "from_pretrained",
    )

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            self.imports.add(a.name.split(".")[0])
            self.import_modules.add(a.name)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.level and node.level > 0:
            self.relative_imports.append(
                (node.level, node.module or "", [a.name for a in node.names])
            )
        elif node.module:
            self.imports.add(node.module.split(".")[0])
            self.import_modules.add(node.module)
        for a in node.names:
            # imported symbol names carry parallelism signals
            # (Mesh, PartitionSpec, shard_map, …)
            self.attrs.append(a.name)
            if node.module and not node.level:
                self.import_modules.add(f"{node.module}.{a.name}")

    def visit_Call(self, node: ast.Call) -> None:
        name = _dotted(node.func)
        if name is None and isinstance(node.func, ast.Attribute):
            # chained / subscripted receivers (`metrics["loss"].item()`,
            # `model(x).cpu()`) have no resolvable dotted chain but the
            # leaf attr still classifies the site; record the leaf in
            # ``calls`` too so sync_call_hints (built from calls) stays
            # consistent with sync_sites (review r5)
            self.calls.append(node.func.attr)
            self._classify_site(node, node.func.attr)
        if name:
            self.calls.append(name)
            tail = name.split(".")[-1]
            if tail in self._KWARG_TARGETS:
                kws: Dict[str, Any] = {}
                for kw in node.keywords:
                    if kw.arg is None:
                        continue
                    try:
                        kws[kw.arg] = ast.literal_eval(kw.value)
                    except (ValueError, SyntaxError):
                        kws[kw.arg] = "<dynamic>"
                self.call_kwargs.setdefault(tail, []).append(kws)
            self._classify_site(node, tail)
        self.generic_visit(node)

    def _classify_site(self, node: ast.Call, leaf: str) -> None:
        in_loop = self._in_train_loop()
        line = getattr(node, "lineno", 0)
        if leaf in _SYNC_ATTRS:
            site = self.sync_sites.setdefault(
                leaf, {"count": 0, "in_loop": 0, "lines": []}
            )
            site["count"] += 1
            site["in_loop"] += int(in_loop)
            if len(site["lines"]) < 10:
                site["lines"].append(line)
        if leaf in ("to", "cuda"):
            self.h2d["to_device"] = True
            for kw in node.keywords:
                if kw.arg == "non_blocking":
                    try:
                        if ast.literal_eval(kw.value) is True:
                            self.h2d["non_blocking"] = True
                    except (ValueError, SyntaxError):
                        pass
            if in_loop:
                self.h2d["h2d_in_loop"] += 1
        elif leaf == "device_put":
            self.h2d["device_put_count"] += 1
            if in_loop:
                self.h2d["h2d_in_loop"] += 1
        if in_loop:
            if leaf in ("save", "save_checkpoint", "save_pretrained"):
                self.loop_flags["checkpoint_in_loop"] = True
            elif leaf in ("eval", "no_grad", "inference_mode"):
                self.loop_flags["validation_in_loop"] = True
            elif leaf in ("log", "add_scalar"):
                self.loop_flags["logging_in_loop"] = True
            elif leaf == "print":
                # ordinary progress prints are too common to count as
                # logger traffic (advisor r4) — separate advisory flag
                self.loop_flags["print_in_loop"] = True
        if leaf == "DistributedSampler":
            self.distributed_sampler_used = True
        elif leaf == "set_epoch":
            self.set_epoch_called = True

    def visit_Attribute(self, node: ast.Attribute) -> None:
        name = _dotted(node)
        if name:
            self.attrs.append(name)
        self.generic_visit(node)


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _visit_file(path: Path, enforce_size: bool = True) -> Optional[_ScriptVisitor]:
    """Parse + visit one file; None on parse failure (or oversize, when
    ``enforce_size`` — the traversal bound; the ENTRY script is always
    scanned in full)."""
    try:
        if enforce_size and path.stat().st_size > _MAX_FILE_BYTES:
            return None
        tree = ast.parse(path.read_text(encoding="utf-8"))
    except Exception:
        return None
    v = _ScriptVisitor()
    v.visit(tree)
    return v


def _extract(v: _ScriptVisitor, out: Dict[str, Any]) -> None:
    """Fold one visitor's signals into the manifest dict."""
    names = set(v.calls) | set(v.attrs)
    imports = v.imports

    # jax/flax anywhere in the project wins (torch often appears as a
    # data-utility import in jax projects); order-independent
    if "jax" in imports or "flax" in imports:
        out["framework"] = "jax"
    elif out["framework"] == "unknown" and imports & {
        "torch", "lightning", "pytorch_lightning"
    }:
        out["framework"] = "torch"
    out["uses"] = sorted(
        set(out["uses"])
        | (
            imports
            & {
                "jax", "flax", "optax", "orbax", "torch", "transformers",
                "numpy", "tensorflow", "grain", "lightning",
                "pytorch_lightning", "deepspeed", "accelerate", "peft",
                "bitsandbytes", "ray",
            }
        )
    )

    def any_in(*subs: str) -> bool:
        return any(any(s in n for n in names) for s in subs)

    def add(field: str, value: str) -> None:
        if value not in out[field]:
            out[field].append(value)

    if any_in("pjit", "shard_map", "NamedSharding", "PartitionSpec", "Mesh"):
        add("parallelism_hints", "gspmd")
    if any_in("pmap"):
        add("parallelism_hints", "pmap")
    if any_in("distributed.initialize"):
        add("parallelism_hints", "multi_host")
    if any_in("DistributedDataParallel", "DDPStrategy"):
        add("parallelism_hints", "ddp")
    if any_in("FSDP", "fully_shard", "FSDPStrategy"):
        add("parallelism_hints", "fsdp")
    if "deepspeed" in imports or any_in("DeepSpeedStrategy", "deepspeed"):
        add("parallelism_hints", "deepspeed")
    # lightning Trainer(strategy="...") literal
    for call in v.call_kwargs.get("Trainer", []):
        strategy = call.get("strategy")
        if isinstance(strategy, str):
            out["trainer_strategy"] = strategy
            for tag in ("ddp", "fsdp", "deepspeed"):
                if tag in strategy:
                    add("parallelism_hints", tag)
        for k in ("devices", "num_nodes", "precision", "accumulate_grad_batches"):
            if k in call:
                out.setdefault("trainer_args", {})[k] = call[k]
    if any_in("bfloat16", "bf16"):
        add("precision_hints", "bf16")
    if any_in("float16", "fp16", "autocast"):
        add("precision_hints", "fp16/amp")
    for opt in ("adamw", "adam", "sgd", "adafactor", "lion", "lamb"):
        if any_in(opt):
            add("optimizer_hints", opt)
    if any_in("DataLoader"):
        add("input_hints", "torch_dataloader")
    if any_in("device_put"):
        add("input_hints", "explicit_device_put")
    if any_in("jax.checkpoint", "remat") and "remat" not in out["uses"]:
        out["uses"].append("remat")

    # config extraction (reference: scanner pulls dataloader args,
    # TrainingArguments precision, grad accumulation, QLoRA markers)
    dls = v.call_kwargs.get("DataLoader", [])
    if dls:
        keep = ("num_workers", "pin_memory", "prefetch_factor",
                "batch_size", "persistent_workers")
        out.setdefault("dataloader_args", []).extend(
            {k: dl[k] for k in keep if k in dl} for dl in dls[:8]
        )
        # torch's DataLoader default is num_workers=0 (single worker in
        # the main process) — exactly the input-bound setup this hint
        # exists to flag, so a missing kwarg counts
        if any(dl.get("num_workers", 0) in (0, None) for dl in dls):
            add("input_hints", "single_worker_dataloader")
    ta = {
        k: val
        for call in v.call_kwargs.get("TrainingArguments", [])
        for k, val in call.items()
    }
    if ta:
        out.setdefault("hf_training_args", {}).update(
            {
                k: ta[k]
                for k in ("per_device_train_batch_size",
                          "gradient_accumulation_steps", "bf16", "fp16",
                          "gradient_checkpointing", "optim",
                          "deepspeed", "fsdp")
                if k in ta
            }
        )
        if ta.get("bf16"):
            add("precision_hints", "bf16")
        if ta.get("fp16"):
            add("precision_hints", "fp16/amp")
        if ta.get("fsdp"):
            add("parallelism_hints", "fsdp")
        if ta.get("deepspeed"):
            add("parallelism_hints", "deepspeed")
    jit_kw = {
        k: val
        for call in v.call_kwargs.get("jit", []) + v.call_kwargs.get("pjit", [])
        for k, val in call.items()
    }
    if "donate_argnums" in jit_kw and "buffer_donation" not in out["uses"]:
        out["uses"].append("buffer_donation")

    # QLoRA / quantization (reference: scanner QLoRA detection)
    quant: Dict[str, Any] = dict(out.get("quantization") or {})
    for call in v.call_kwargs.get("BitsAndBytesConfig", []):
        for k in ("load_in_4bit", "load_in_8bit", "bnb_4bit_quant_type",
                  "bnb_4bit_compute_dtype"):
            if k in call:
                quant[k] = call[k]
    for call in v.call_kwargs.get("from_pretrained", []):
        for k in ("load_in_4bit", "load_in_8bit"):
            if call.get(k):
                quant[k] = call[k]
    lora = {
        k: val
        for call in v.call_kwargs.get("LoraConfig", [])
        for k, val in call.items()
        if k in ("r", "lora_alpha", "target_modules", "lora_dropout")
    }
    if lora:
        quant["lora"] = lora
    if quant:
        out["quantization"] = quant
    if (
        imports & {"peft", "bitsandbytes"}
        or any_in("lora", "Lora", "LoRA")
    ) and "lora/qlora" not in out["uses"]:
        out["uses"].append("lora/qlora")
    # host-sync calls inside the loop are a classic TPU/GPU perf trap
    sync_markers = [
        n for n in _SYNC_ATTRS
        if any(name.endswith("." + n) or name == n for name in set(v.calls))
    ]
    for m in sync_markers:
        if m not in out.setdefault("sync_call_hints", []):
            out["sync_call_hints"].append(m)

    # per-site classification (reference visitor.py:498-565): sync call
    # counts with training-loop context and line numbers, H2D idioms,
    # and loop hygiene flags — merged across project files
    if v.sync_sites:
        merged = out.setdefault("sync_sites", {})
        for leaf, site in v.sync_sites.items():
            dst = merged.setdefault(
                leaf, {"count": 0, "in_loop": 0, "lines": []}
            )
            dst["count"] += site["count"]
            dst["in_loop"] += site["in_loop"]
            dst["lines"] = (dst["lines"] + site["lines"])[:10]
        if any(s["in_loop"] for s in merged.values()):
            add("input_hints", "host_sync_in_loop")
    if v.h2d["to_device"] or v.h2d["device_put_count"]:
        h2d = out.setdefault("h2d", {
            "to_device": False, "non_blocking": False,
            "device_put_count": 0, "h2d_in_loop": 0,
        })
        h2d["to_device"] = h2d["to_device"] or v.h2d["to_device"]
        h2d["non_blocking"] = h2d["non_blocking"] or v.h2d["non_blocking"]
        h2d["device_put_count"] += v.h2d["device_put_count"]
        h2d["h2d_in_loop"] += v.h2d["h2d_in_loop"]
        if h2d["to_device"] and not h2d["non_blocking"]:
            add("input_hints", "blocking_h2d")
        elif h2d["non_blocking"] and "blocking_h2d" in out["input_hints"]:
            # an earlier file looked blocking; a later one proved
            # non_blocking is used — drop the stale hint
            out["input_hints"].remove("blocking_h2d")
    if v.loop_flags:
        out.setdefault("loop_flags", {}).update(v.loop_flags)
    # fold set_epoch UNCONDITIONALLY: the sampler and its set_epoch
    # call may live in different project files, and extraction order
    # is BFS over imports — gating this on the same file using
    # DistributedSampler would fabricate the missing-set_epoch hint
    out["set_epoch_called"] = (
        out.get("set_epoch_called") or v.set_epoch_called
    )
    if v.distributed_sampler_used:
        add("input_hints", "distributed_sampler")
        out["_sampler_seen"] = True
    if out.get("_sampler_seen"):
        if not out["set_epoch_called"]:
            # same-order shards every epoch — the classic missing
            # sampler.set_epoch bug the reference flags
            add("input_hints", "distributed_sampler_no_set_epoch")
        elif "distributed_sampler_no_set_epoch" in out["input_hints"]:
            out["input_hints"].remove("distributed_sampler_no_set_epoch")


def _empty_manifest(script: Path) -> Dict[str, Any]:
    return {
        "script": str(script),
        "framework": "unknown",
        "uses": [],
        "parallelism_hints": [],
        "precision_hints": [],
        "optimizer_hints": [],
        "input_hints": [],
    }


def analyze_script(script: Path) -> Dict[str, Any]:
    """Best-effort static scan of ONE file (reference: scanner.py:59)."""
    out = _empty_manifest(script)
    v = _visit_file(Path(script), enforce_size=False)
    if v is None:
        try:
            ast.parse(Path(script).read_text(encoding="utf-8"))
        except Exception as exc:
            out["error"] = str(exc)
        return out
    _extract(v, out)
    return {k: val for k, val in out.items() if not k.startswith("_")}


def _resolve_local(module: str, roots: List[Path]) -> Optional[Path]:
    """Dotted module name → local file under one of ``roots``, or None."""
    rel = module.replace(".", "/")
    for root in roots:
        for candidate in (root / f"{rel}.py", root / rel / "__init__.py"):
            try:
                if candidate.is_file():
                    return candidate.resolve()
            except OSError:
                continue
    return None


def analyze_project(script: Path, max_modules: int = _MAX_MODULES) -> Dict[str, Any]:
    """Entry script + bounded BFS over its LOCAL imports
    (reference: ast_analysis local-import traversal).

    Only modules that resolve to files under the entry script's directory
    (the project) are followed; site-packages never are.  Bounded by
    ``max_modules`` and per-file size, tolerant of cycles and syntax
    errors (a broken module is recorded, not fatal).
    """
    entry = Path(script).resolve()
    out = _empty_manifest(entry)
    roots = [entry.parent]
    queue: List[Path] = [entry]
    seen: Set[Path] = set()
    scanned: List[str] = []
    failed: List[str] = []
    while queue and len(seen) < max_modules:
        path = queue.pop(0)
        if path in seen:
            continue
        seen.add(path)
        v = _visit_file(path, enforce_size=path != entry)
        if v is None:
            failed.append(str(path))
            continue
        scanned.append(str(path))
        _extract(v, out)
        for module in sorted(v.import_modules):
            local = _resolve_local(module, roots)
            if local is not None and local not in seen:
                queue.append(local)
        # relative imports resolve against THIS file's package, walking
        # one directory up per extra leading dot
        for level, module, names in v.relative_imports:
            base = path.parent
            for _ in range(level - 1):
                base = base.parent
            candidates = [module] if module else []
            candidates += (
                [f"{module}.{n}" for n in names] if module else list(names)
            )
            for mod in candidates:
                local = _resolve_local(mod, [base])
                if local is not None and local not in seen:
                    queue.append(local)
    out["modules_scanned"] = len(scanned)
    out["local_modules"] = [str(p) for p in scanned if Path(p) != entry]
    if failed:
        out["modules_failed"] = failed
    # cross-file extraction state (e.g. _sampler_seen) is not manifest
    out = {k: v for k, v in out.items() if not k.startswith("_")}
    return out
