"""Incremental envelope builder over a Database
(reference: src/traceml_ai/database/database_sender.py:29-188).

Keeps a per-table cursor on the append counter; ``collect_payload`` ships
only rows appended since the previous call, wrapped in a canonical
telemetry envelope.  Returns ``None`` when there is nothing new (so the
publisher can skip the network entirely on idle ticks).

Envelopes go out as **schema v2 (columnar)** — each table transposed to
struct-of-arrays so table keys are encoded once per batch instead of
once per row (see docs/developer_guide/wire-schema-v2.md).  The
aggregator still accepts v1 row-lists from older senders.

Producer fast path (r10, docs/developer_guide/rank-producer-path.md):
``dirty()`` is an O(1) gate on the database's global append counter —
an idle publish tick never touches per-table state.  When there IS new
data, one :meth:`Database.collect_wire_tables` sweep (a single lock
round-trip for all tables) hands over wire-ready columnar tables
accumulated at ``add_record`` time (nested struct-of-arrays included),
so the per-tick transpose is gone; the row→column path only
runs on the fallback (overflowed window or replayed cursor), where it
is golden-identical to the pre-r10 ``collect_since`` output.  The
envelope meta is built from a cached template — only the timestamp
changes per tick.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

from traceml_tpu.database.database import Database
from traceml_tpu.telemetry.envelope import (
    SCHEMA_V2,
    SenderIdentity,
    rows_to_columns,
)


class DBIncrementalSender:
    def __init__(self, sampler_name: str, db: Database) -> None:
        self._sampler = sampler_name
        self._db = db
        self._cursors: Dict[str, int] = {}
        self._identity: Optional[SenderIdentity] = None
        self._last_total = 0  # db.appended_total() at last collection
        self._meta_template: Optional[Dict[str, Any]] = None

    @property
    def sampler_name(self) -> str:
        return self._sampler

    def set_identity(self, identity: SenderIdentity) -> None:
        self._identity = identity
        self._meta_template = None

    def dirty(self) -> bool:
        """O(1), lock-free: rows appended since the last collection?"""
        return self._db.appended_total() != self._last_total

    def _wire_meta(self) -> Dict[str, Any]:
        tmpl = self._meta_template
        if tmpl is None:
            identity = self._identity or SenderIdentity()
            tmpl = identity.to_meta()
            tmpl["schema"] = SCHEMA_V2
            tmpl["sampler"] = self._sampler
            self._meta_template = tmpl
        meta = dict(tmpl)
        meta["timestamp"] = time.time()
        return meta

    def collect_payload(self) -> Optional[Dict[str, Any]]:
        if not self.dirty():
            return None
        # Read the total BEFORE collecting: rows appended mid-collect may
        # or may not land in this batch, but the stale total keeps dirty()
        # true so the next tick picks them up (at worst one extra scan —
        # never a skipped row).
        total = self._db.appended_total()
        tables, fallback = self._db.collect_wire_tables(self._cursors)
        self._last_total = total
        for table, rows in fallback.items():
            tables[table] = rows_to_columns(rows)
        if not tables:
            return None
        # the canonical wire shape, assembled directly (what
        # build_columnar_envelope_from_columns(...).to_wire() returns)
        return {"meta": self._wire_meta(), "body": {"tables": tables}}

    def reset(self) -> None:
        self._cursors.clear()
        self._last_total = 0
