"""Cross-rank point attribution helpers.

Shared by the live views and the final-report rollup so "median rank" /
"worst rank" mean the SAME thing on every surface: ``median`` names the
rank whose value sits closest to the cross-rank median (deterministic
tie-breaks: value distance, then value, then rank id), ``worst`` the
maximum (ties toward the smaller rank id).
"""

from __future__ import annotations

import statistics
from typing import Mapping, Optional


def _rank_sort(rank_key) -> int:
    try:
        return int(rank_key)
    except (TypeError, ValueError):
        return 0


def closest_rank_to_median(values: Mapping) -> Optional[str]:
    """The rank id whose value sits closest to the cross-rank median."""
    if not values:
        return None
    median_value = statistics.median(values.values())
    return min(
        values,
        key=lambda k: (abs(values[k] - median_value), values[k], _rank_sort(k)),
    )


def worst_rank(values: Mapping) -> Optional[str]:
    """The rank id with the maximum value (ties → smaller rank id)."""
    if not values:
        return None
    return max(values, key=lambda k: (values[k], -_rank_sort(k)))
