"""Mixed wire-schema ingest: one aggregator fed interleaved v1 row-list,
v2 columnar, and legacy flat envelopes from different "ranks" over a real
TCPServer must land byte-for-byte the same SQLite contents as an all-v1
run — the back-compat guarantee of schema v2
(docs/developer_guide/wire-schema-v2.md)."""

import sqlite3

from traceml_tpu.aggregator.trace_aggregator import TraceMLAggregator
from traceml_tpu.runtime.settings import AggregatorEndpoint, TraceMLSettings
from traceml_tpu.telemetry.control import build_rank_finished
from traceml_tpu.telemetry.envelope import (
    SenderIdentity,
    build_columnar_envelope,
    build_telemetry_envelope,
)
from traceml_tpu.transport import TCPClient

N_STEPS = 25


def _settings(tmp_path, name):
    return TraceMLSettings(
        session_id=f"mixed-{name}",
        logs_dir=tmp_path / name,
        mode="summary",
        aggregator=AggregatorEndpoint(port=0),
        expected_world_size=3,
        finalize_timeout_sec=3.0,
    )


def _ident(rank):
    return SenderIdentity(
        session_id="mixed", global_rank=rank, local_rank=rank, world_size=3,
        hostname=f"host-{rank}", pid=1000 + rank,
    )


def _tables(rank):
    return {
        "step_time": [
            {"step": s, "timestamp": float(s), "clock": "device",
             "late_markers": 0,
             "events": {"phase": {"cpu_ms": 1.0 * s + rank,
                                  "device_ms": 2.0 * s, "count": 1}}}
            for s in range(1, N_STEPS + 1)
        ],
        "model_stats": [
            {"timestamp": 1.0, "flops_per_step": 1e9 * (rank + 1),
             "flops_source": "provided", "device_kind": "tpu",
             "peak_flops": 1e14, "device_count": 3, "tokens_per_step": 512.0}
        ],
    }


def _payload(rank, schema):
    ident = _ident(rank)
    tables = _tables(rank)
    if schema == "v1":
        return build_telemetry_envelope("step_time", tables, ident).to_wire()
    if schema == "v2":
        return build_columnar_envelope("step_time", tables, ident).to_wire()
    # legacy flat shape, as a pre-envelope sender would emit it
    flat = {"sampler": "step_time", "tables": tables, "timestamp": 1.0}
    flat.update(ident.to_meta())
    flat.pop("schema", None)
    return flat


def _run_session(tmp_path, name, schemas):
    settings = _settings(tmp_path, name)
    agg = TraceMLAggregator(settings)
    agg.start()
    try:
        client = TCPClient("127.0.0.1", agg.port)
        # interleave: every rank's telemetry in ONE batch frame, mixed forms
        batch = [_payload(rank, schema) for rank, schema in enumerate(schemas)]
        batch.extend(build_rank_finished(_ident(r).to_meta()) for r in range(3))
        assert client.send_batch(batch)
        client.close()
    finally:
        agg.stop()
    return settings.session_dir / "telemetry.sqlite"


def _dump(db_path):
    conn = sqlite3.connect(db_path)
    out = {}
    for table in ("step_time_samples", "model_stats_samples"):
        cols = [
            r[1]
            for r in conn.execute(f"PRAGMA table_info({table})")
            if r[1] != "id"  # autoincrement id depends on arrival order
        ]
        rows = conn.execute(
            f"SELECT {', '.join(cols)} FROM {table}"
        ).fetchall()
        out[table] = sorted(rows)
    conn.close()
    return out


def test_mixed_schema_ingest_matches_all_v1(tmp_path):
    mixed = _dump(_run_session(tmp_path, "mixed", ("v1", "v2", "legacy")))
    allv1 = _dump(_run_session(tmp_path, "allv1", ("v1", "v1", "v1")))
    assert mixed["step_time_samples"], "no step_time rows ingested"
    assert len(mixed["step_time_samples"]) == 3 * N_STEPS
    assert mixed == allv1
