"""Test bootstrap: repo-root import path + virtual 8-device CPU JAX.

Mirrors the reference's conftest sys.path trick
(reference: tests/conftest.py:14-19) and forces JAX onto the host
platform with 8 virtual devices so multi-chip sharding tests run in
CPU-only CI (see SURVEY.md §4 "fake device layer").

Must run before anything imports jax — conftest import time is early
enough as long as test modules import jax at module scope or later.
"""

import os
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# If a PJRT plugin's sitecustomize already pinned a platform, re-pin to cpu
# before the backend initializes (jax config wins over the env snapshot).
try:
    import jax

    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass

import faulthandler  # noqa: E402
import threading  # noqa: E402
import time  # noqa: E402

import pytest  # noqa: E402

# A hung collective / wedged transport thread turns into a silent CI
# timeout without this: dump every thread's stack on SIGABRT so the
# killed run still says WHERE it was stuck.
faulthandler.enable()


@pytest.fixture()
def tmp_session_dir(tmp_path):
    d = tmp_path / "session"
    d.mkdir()
    return d


@pytest.fixture(scope="session", autouse=True)
def _no_leaked_threads():
    """Every subsystem in this package promises clean teardown
    (close()/stop() joins its workers).  A non-daemon thread that
    outlives the whole test session broke that promise somewhere —
    fail loudly with the survivors' names instead of letting pytest
    hang at interpreter exit."""
    yield
    deadline = time.time() + 5.0
    while time.time() < deadline:
        leaked = [
            t
            for t in threading.enumerate()
            if t is not threading.main_thread()
            and t.is_alive()
            and not t.daemon
        ]
        if not leaked:
            return
        time.sleep(0.05)
    names = ", ".join(sorted(t.name for t in leaked))
    pytest.fail(
        f"non-daemon thread(s) survived session teardown: {names}",
        pytrace=False,
    )
