"""Liveness thresholds, live vs summary.

The state machine itself (ACTIVE→STALE→LOST) lives aggregator-side in
:mod:`traceml_tpu.aggregator.liveness`, driven by heartbeat age; these
policies only govern how the *diagnosis* reads a persisted
``rank_status.json`` snapshot — chiefly how abruptly a rank must have
gone silent for LIKELY_PREEMPTED to refine RANK_LOST.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class LivenessPolicy:
    # LIKELY_PREEMPTED: a lost rank whose last step progress landed
    # within this many seconds of its last contact died mid-stride —
    # the abrupt-kill / preemption profile, as opposed to a rank that
    # idled (hung, deadlocked, draining) before vanishing
    preempt_stride_sec: float = 10.0
    # STALE ranks alone never fire RANK_LOST, but enough of the world
    # simultaneously stale is worth a warning (network partition /
    # aggregator overload profile)
    stale_share_warn: float = 0.5
    # coverage denominator for confidence_from: observed world share
    min_ranks: int = 1


LIVE_POLICY = LivenessPolicy()

SUMMARY_POLICY = LivenessPolicy()


def policy_for(mode: str) -> LivenessPolicy:
    return SUMMARY_POLICY if mode == "summary" else LIVE_POLICY
