"""Per-step device memory tracking
(reference: src/traceml_ai/utils/step_memory.py:32-112).

The reference resets ``torch.cuda`` peak stats at step start and reads
``max_memory_allocated/reserved`` at step end.  TPU runtimes expose
``jax.Device.memory_stats()`` (libtpu-backed: ``bytes_in_use``,
``peak_bytes_in_use``, ``bytes_limit``, …) but **no per-step peak reset**
— the peak is cumulative.  So the tracker records, per step and device:

* ``current_bytes``   — bytes in use at step end
* ``peak_bytes``      — cumulative allocator peak (monotone)
* ``step_peak_bytes`` — max of the observations this tracker made during
  the step (start/end edges) — a lower bound on the true step peak
* ``limit_bytes``     — device capacity

Backends are pluggable because ``memory_stats()`` returns ``None`` on
some runtimes (CPU, tunneled devices): the live-arrays backend sums
``jax.live_arrays()`` nbytes per device, and tests inject a deterministic
fake (SURVEY.md §4 "fake device layer").
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Protocol

from traceml_tpu.utils.error_log import get_error_log
from traceml_tpu.utils.timing import push_step_memory_row


class DeviceMemorySample(dict):
    """Row shape: {device_id, device_kind, current_bytes, peak_bytes,
    limit_bytes} — plain dict subclass for codec friendliness."""


class MemoryBackend(Protocol):
    name: str

    def sample(self) -> List[Dict[str, Any]]: ...


class JaxMemoryStatsBackend:
    """libtpu allocator counters via ``jax.Device.memory_stats()``."""

    name = "jax_memory_stats"

    def __init__(self) -> None:
        import jax

        self._devices = jax.local_devices()
        # Probe once: some runtimes return None.
        probe = self._devices[0].memory_stats() if self._devices else None
        if not probe:
            raise RuntimeError("memory_stats unavailable on this runtime")

    def sample(self) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for d in self._devices:
            stats = d.memory_stats() or {}
            out.append(
                {
                    "device_id": int(d.id),
                    "device_kind": str(d.device_kind),
                    "current_bytes": int(stats.get("bytes_in_use", 0)),
                    "peak_bytes": int(
                        stats.get("peak_bytes_in_use", stats.get("bytes_in_use", 0))
                    ),
                    "limit_bytes": int(stats.get("bytes_limit", 0)) or None,
                }
            )
        return out


class LiveArraysBackend:
    """Fallback: per-device sum of live ``jax.Array`` buffer sizes.

    Approximates allocated bytes (misses allocator overhead / temp
    buffers) but works on every backend, including CPU CI.

    CRITICAL: uses only array METADATA (nbytes, sharding, device ids).
    Touching ``shard.data`` marks buffers as externally referenced,
    which defeats XLA's buffer reuse and was measured to DOUBLE step
    time on the CPU backend — the observer must not perturb the
    allocator it observes.
    """

    name = "live_arrays"

    def __init__(self) -> None:
        import jax

        self._jax = jax
        self._kinds = {d.id: str(d.device_kind) for d in jax.local_devices()}
        self._pid = jax.process_index()
        # (sharding, shape, itemsize) → (local device ids, bytes/shard).
        # Training loops re-create arrays with identical layout every
        # step; memoizing turns the per-array device_set/shard_shape work
        # into one dict hit (holding the sharding key keeps it alive, so
        # ids can't be recycled under us).
        self._layout_cache: Dict[Any, Any] = {}

    def sample(self) -> List[Dict[str, Any]]:
        import math

        per_dev: Dict[int, int] = {}
        cache = self._layout_cache
        for arr in self._jax.live_arrays():
            try:
                key = (arr.sharding, arr.shape, arr.dtype.itemsize)
                hit = cache.get(key)
                if hit is None:
                    sharding = arr.sharding
                    # true per-device shard size from METADATA: replicated
                    # arrays cost full nbytes on every device, sharded
                    # ones cost their shard — shard_shape computes both
                    dev_ids = [
                        d.id
                        for d in sharding.device_set
                        if d.process_index == self._pid
                    ]
                    per_shard = int(
                        math.prod(sharding.shard_shape(arr.shape))
                        * arr.dtype.itemsize
                    )
                    if len(cache) > 4096:
                        cache.clear()
                    cache[key] = hit = (dev_ids, per_shard)
                dev_ids, per_shard = hit
                for did in dev_ids:
                    per_dev[did] = per_dev.get(did, 0) + per_shard
            except Exception:
                continue
        return [
            {
                "device_id": did,
                "device_kind": self._kinds.get(did, "unknown"),
                "current_bytes": n,
                "peak_bytes": n,  # no allocator peak; tracker maxes edges
                "limit_bytes": None,
            }
            for did, n in sorted(per_dev.items())
        ]


class FakeMemoryBackend:
    """Deterministic scripted backend for tests."""

    name = "fake"

    def __init__(self, script: Optional[List[List[Dict[str, Any]]]] = None):
        self._script = list(script or [])
        self._i = 0
        self.calls = 0

    def push(self, sample: List[Dict[str, Any]]) -> None:
        self._script.append(sample)

    def sample(self) -> List[Dict[str, Any]]:
        self.calls += 1
        if not self._script:
            return []
        sample = self._script[min(self._i, len(self._script) - 1)]
        self._i += 1
        return [dict(row) for row in sample]


class NullMemoryBackend:
    name = "null"

    def sample(self) -> List[Dict[str, Any]]:
        return []


def detect_backend() -> MemoryBackend:
    """Best available backend, fail-open to null.

    torch-xla wins when the process has torch_xla LOADED (explicit
    signal this is a torch-xla job — its lazy tensors never show up in
    jax's live-arrays view); detection is sys.modules-gated so this
    never imports a framework the job didn't choose."""
    import sys

    if "torch_xla" in sys.modules:
        try:
            from traceml_tpu.instrumentation.torch_xla_support import (
                XlaMemoryBackend,
            )

            return XlaMemoryBackend()
        except Exception:
            pass
    try:
        return JaxMemoryStatsBackend()
    except Exception:
        pass
    try:
        return LiveArraysBackend()
    except Exception:
        pass
    return NullMemoryBackend()


def jax_is_initialized() -> bool:
    """True only when a jax backend already exists in this process.

    Samplers MUST consult this before touching devices: triggering XLA
    backend init from a background thread before the user's own
    ``jax.distributed.initialize`` is the TPU analogue of the
    reference's touch-CUDA-before-init_process_group hazard
    (reference: process_sampler.py CUDA-safety gate).

    IMPORT-FREE on purpose: this runs on the sampler thread, and an
    ``import jax...`` here can race the MAIN thread's in-progress
    ``import jax`` (slow under CPU oversubscription), leaving jax's
    modules partially initialized and crashing unrelated user imports —
    observed as chex failing with "partially initialized module
    jax._src.xla_bridge".  Only ``sys.modules`` inspection is safe.
    """
    import sys

    m = sys.modules.get("jax")
    if m is None:
        return False
    spec = getattr(m, "__spec__", None)
    if spec is not None and getattr(spec, "_initializing", False):
        return False  # main thread is mid-import; hands off
    xb = sys.modules.get("jax._src.xla_bridge")
    if xb is None:
        return False
    try:
        return bool(getattr(xb, "_backends", None))
    except Exception:
        return False


def device_memory_rows(backend_holder: Dict[str, Any], ts: float) -> List[Dict[str, Any]]:
    """Shared per-device row builder for the system/process samplers.

    ``backend_holder`` is a one-key dict {"backend": MemoryBackend|None}
    owned by the caller; detection is lazy and gated on jax being
    initialized so the sampler thread can never force backend init.
    """
    backend = backend_holder.get("backend")
    if backend is None:
        import sys

        # torch-xla jobs never initialize jax — their own loaded module
        # is the detection signal (sys.modules check only: this thread
        # must never import a framework)
        if not jax_is_initialized() and "torch_xla" not in sys.modules:
            return []
        try:
            backend = detect_backend()
        except Exception:
            return []
        backend_holder["backend"] = backend
    return [
        {
            "timestamp": ts,
            "device_id": r["device_id"],
            "device_kind": r.get("device_kind", "unknown"),
            "memory_used_bytes": r.get("current_bytes"),
            "memory_peak_bytes": r.get("peak_bytes"),
            "memory_total_bytes": r.get("limit_bytes"),
        }
        for r in backend.sample()
    ]


class StepMemoryTracker:
    """Records device memory at step edges and emits one row per
    (step, device) into the global step-memory queue."""

    def __init__(
        self,
        backend: Optional[MemoryBackend] = None,
        min_sample_interval_s: float = 0.2,
    ) -> None:
        self._backend = backend or detect_backend()
        self._step_start: Dict[int, Dict[str, Any]] = {}
        self._have_edge = False
        # Time-based throttle: sub-interval steps share one sample, so
        # memory sampling cost stays O(1/interval) per second instead of
        # O(1/step) — short-step jobs keep <1% overhead, and the creep/
        # pressure diagnostics are cadence-based, not per-step.  Rows
        # are simply sparse in `step`; every consumer iterates rows.
        self._min_interval = float(min_sample_interval_s)
        self._last_sample_mono = 0.0

    @property
    def backend_name(self) -> str:
        return getattr(self._backend, "name", "unknown")

    def reset(self, step: int) -> None:
        """Step-start edge (reference: reset_peak_memory_stats analogue).

        In a contiguous step loop the previous step's EXIT sample is this
        step's entry edge, so only the first step pays a sample here —
        one backend sample per step, not two.
        """
        if self._have_edge:
            return
        try:
            self._step_start = {row["device_id"]: row for row in self._backend.sample()}
            self._have_edge = True
        except Exception as exc:
            get_error_log().warning("step memory reset failed", exc)
            self._step_start = {}

    def record(self, step: int, *, force: bool = False) -> List[Dict[str, Any]]:
        """Step-end edge; emits rows and returns them (for tests).
        Skipped (returns []) when inside the sampling throttle window,
        unless ``force`` — the shutdown path forces one last sample so
        a run shorter than the throttle interval still records its end
        state (a creep diagnosis needs first AND last; r4 fix)."""
        now = time.monotonic()
        if (
            not force
            and self._min_interval > 0
            and now - self._last_sample_mono < self._min_interval
        ):
            return []
        self._last_sample_mono = now
        rows: List[Dict[str, Any]] = []
        try:
            ts = time.time()
            end_rows = self._backend.sample()
            for row in end_rows:
                start = self._step_start.get(row["device_id"], {})
                step_peak = max(
                    int(row.get("current_bytes", 0)),
                    int(start.get("current_bytes", 0)),
                )
                out = {
                    "step": step,
                    "timestamp": ts,
                    "device_id": row["device_id"],
                    "device_kind": row.get("device_kind", "unknown"),
                    "current_bytes": int(row.get("current_bytes", 0)),
                    "peak_bytes": int(row.get("peak_bytes", 0)),
                    "step_peak_bytes": step_peak,
                    "limit_bytes": row.get("limit_bytes"),
                    "backend": self.backend_name,
                }
                rows.append(out)
                push_step_memory_row(out)
            # this exit sample becomes the next step's entry edge
            self._step_start = {r["device_id"]: r for r in end_rows}
            self._have_edge = True
        except Exception as exc:
            get_error_log().warning("step memory record failed", exc)
        return rows
