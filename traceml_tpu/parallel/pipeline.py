"""Pipeline parallelism over a mesh axis
(SURVEY §2.13 / driver mandate: the ``pp`` axis of tp/pp/dp/sp/ep —
the reference's trainer has no pipeline engine; this is the TPU-native
design for one).

GPipe-style schedule as ONE compiled program:

* per-stage parameters are stacked on a leading stage dimension and
  sharded over the mesh's ``stage`` axis (each chip holds its stage
  only);
* inside ``shard_map`` every stage runs the same ``lax.scan`` over
  ``n_microbatches + n_stages − 1`` ticks; activations move stage→stage
  with a single ``lax.ppermute`` per tick (point-to-point over ICI);
* stage 0 injects microbatch ``t`` at tick ``t``; the last stage's
  output of microbatch ``m`` appears at tick ``m + S − 1`` and is
  collected with a static mask — no data-dependent control flow, fully
  jittable;
* ``ppermute`` has a well-defined transpose (the reverse permutation),
  so ``jax.grad`` differentiates straight through the schedule — the
  backward pipeline needs no hand-written schedule.

This runs identically on the 8-device CPU CI mesh and a real slice.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from traceml_tpu.utils.jax_compat import shard_map


def stack_stage_params(per_stage_params: list) -> Any:
    """[stage0_tree, stage1_tree, …] → one tree with a leading stage dim."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves, axis=0), *per_stage_params
    )


def stage_param_shardings(stacked_params, mesh, axis: str = "stage") -> Any:
    """Leading (stage) dim over the pipeline axis, rest replicated within
    the stage group (compose with fsdp/tensor specs for real models)."""

    def spec(leaf):
        ndim = getattr(leaf, "ndim", 1)
        return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))

    return jax.tree_util.tree_map(spec, stacked_params)


def make_pipeline_fn(
    stage_apply: Callable[[Any, jnp.ndarray], jnp.ndarray],
    mesh,
    n_microbatches: int,
    axis: str = "stage",
):
    """Build the pipelined forward.

    ``stage_apply(stage_params, x) -> x`` is one stage's computation
    (stage_params WITHOUT the leading stage dim).  Returns
    ``pipeline_fn(stacked_params, microbatches)`` with
    ``microbatches: (n_micro, mb, …)`` → ``(n_micro, mb, …)`` outputs
    (valid on every chip after the closing all-gather of the last
    stage's buffer).
    """
    n_stages = mesh.shape[axis]
    total_ticks = n_microbatches + n_stages - 1
    fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def _check_stage_dim(stacked_params) -> None:
        for leaf in jax.tree_util.tree_leaves(stacked_params):
            if leaf.shape[0] != n_stages:
                raise ValueError(
                    f"stacked stage dim {leaf.shape[0]} != mesh {axis} "
                    f"size {n_stages} — each chip must hold exactly one "
                    "stage (a mismatch would silently drop stages)"
                )

    def per_stage(stacked_local, micro_local, stage_index):
        # stacked_local leaves: (1, …) — this chip's stage; drop the dim
        params = jax.tree_util.tree_map(lambda l: l[0], stacked_local)
        mb_shape = micro_local.shape[1:]
        outputs = jnp.zeros((n_microbatches,) + mb_shape, micro_local.dtype)
        inbuf = jnp.zeros(mb_shape, micro_local.dtype)

        def tick(carry, t):
            inbuf, outputs = carry
            # stage 0 injects microbatch t (static gather with clamp;
            # ticks ≥ n_micro re-inject the last microbatch into the
            # bubble — masked out at collection)
            mb_idx = jnp.minimum(t, n_microbatches - 1)
            injected = jax.lax.dynamic_index_in_dim(
                micro_local, mb_idx, axis=0, keepdims=False
            )
            x = jnp.where(stage_index == 0, injected, inbuf)
            y = stage_apply(params, x)
            # collect on the last stage: microbatch m completes at tick
            # m + S − 1
            out_idx = jnp.clip(t - (n_stages - 1), 0, n_microbatches - 1)
            valid = (stage_index == n_stages - 1) & (t >= n_stages - 1)
            current = jax.lax.dynamic_index_in_dim(
                outputs, out_idx, axis=0, keepdims=False
            )
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs,
                jnp.where(valid, y, current),
                out_idx,
                axis=0,
            )
            # hand activations to the next stage (ring permute; the
            # wrap-around edge is ignored by stage 0's injection select)
            inbuf = jax.lax.ppermute(y, axis, perm=fwd_perm)
            return (inbuf, outputs), None

        (_, outputs), _ = jax.lax.scan(
            tick, (inbuf, outputs), jnp.arange(total_ticks)
        )
        # every chip returns the LAST stage's collected outputs: psum of
        # stage-masked buffers replicates them across the pipeline
        mask = (stage_index == n_stages - 1).astype(outputs.dtype)
        return jax.lax.psum(outputs * mask, axis)

    def pipeline_fn(stacked_params, microbatches):
        _check_stage_dim(stacked_params)
        if microbatches.shape[0] != n_microbatches:
            raise ValueError(
                f"got {microbatches.shape[0]} microbatches, schedule was "
                f"built for {n_microbatches} — the clamp in the injection "
                "gather would silently duplicate the last microbatch"
            )

        def wrapped(stacked_local, micro_local):
            stage_index = jax.lax.axis_index(axis)
            return per_stage(stacked_local, micro_local, stage_index)

        n_leaf_specs = jax.tree_util.tree_map(
            lambda _: P(axis), stacked_params
        )
        return shard_map(
            wrapped,
            mesh=mesh,
            in_specs=(n_leaf_specs, P()),
            out_specs=P(),
            check_vma=False,
        )(stacked_params, microbatches)

    return pipeline_fn


def make_pipeline_train_step(
    stage_apply: Callable[[Any, jnp.ndarray], jnp.ndarray],
    mesh,
    n_microbatches: int,
    axis: str = "stage",
    learning_rate: float = 1e-2,
) -> Tuple[Callable, Callable]:
    """(init, train_step) for a pipelined regression objective —
    gradients flow through the schedule via ppermute's transpose."""
    import optax

    tx = optax.sgd(learning_rate)
    pipeline_fn = make_pipeline_fn(stage_apply, mesh, n_microbatches, axis)

    def loss_fn(stacked_params, micro_x, micro_y):
        out = pipeline_fn(stacked_params, micro_x)
        return jnp.mean((out - micro_y) ** 2)

    def train_step(stacked_params, opt_state, micro_x, micro_y):
        loss, grads = jax.value_and_grad(loss_fn)(
            stacked_params, micro_x, micro_y
        )
        updates, opt_state = tx.update(grads, opt_state, stacked_params)
        stacked_params = optax.apply_updates(stacked_params, updates)
        return stacked_params, opt_state, {"loss": loss}

    def init(stacked_params):
        return tx.init(stacked_params)

    return init, train_step


def linear_stage_apply(params: Dict[str, jnp.ndarray], x: jnp.ndarray) -> jnp.ndarray:
    """Reference stage: y = tanh(x @ w + b) — used by tests/dryrun."""
    return jnp.tanh(x @ params["w"] + params["b"])


def init_linear_stages(
    n_stages: int, width: int, rng: jax.Array
) -> list:
    keys = jax.random.split(rng, n_stages)
    return [
        {
            "w": jax.random.normal(k, (width, width), jnp.float32) * 0.3,
            "b": jnp.zeros((width,), jnp.float32),
        }
        for k in keys
    ]
