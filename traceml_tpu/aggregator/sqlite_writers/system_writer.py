"""system projection → ``system_samples`` + ``system_device_samples``
(reference: aggregator/sqlite_writers/system.py)."""

from __future__ import annotations

from typing import Dict, List, Tuple

from traceml_tpu.aggregator.sqlite_writers.common import (
    IDENTITY_SCHEMA,
    identity_tuple,
)
from traceml_tpu.telemetry.envelope import TelemetryEnvelope

TABLE_HOST = "system_samples"
TABLE_DEVICE = "system_device_samples"
RETENTION_TABLES = (TABLE_HOST, TABLE_DEVICE)


def accepts_sampler(name: str) -> bool:
    return name == "system"


def init_schema(conn) -> None:
    conn.execute(
        f"""CREATE TABLE IF NOT EXISTS {TABLE_HOST} (
            id INTEGER PRIMARY KEY AUTOINCREMENT,
            {IDENTITY_SCHEMA},
            timestamp REAL,
            cpu_pct REAL,
            memory_used_bytes INTEGER,
            memory_total_bytes INTEGER,
            memory_pct REAL,
            load_1m REAL,
            load_5m REAL,
            load_15m REAL
        )"""
    )
    conn.execute(
        f"""CREATE TABLE IF NOT EXISTS {TABLE_DEVICE} (
            id INTEGER PRIMARY KEY AUTOINCREMENT,
            {IDENTITY_SCHEMA},
            timestamp REAL,
            device_id INTEGER,
            device_kind TEXT,
            memory_used_bytes INTEGER,
            memory_peak_bytes INTEGER,
            memory_total_bytes INTEGER,
            utilization_pct REAL,
            temperature_c REAL,
            power_w REAL
        )"""
    )
    conn.execute(
        f"CREATE INDEX IF NOT EXISTS idx_{TABLE_HOST}_rank "
        f"ON {TABLE_HOST} (session_id, node_rank, timestamp)"
    )
    conn.execute(
        f"CREATE INDEX IF NOT EXISTS idx_{TABLE_DEVICE}_rank "
        f"ON {TABLE_DEVICE} (session_id, node_rank, device_id, timestamp)"
    )


def insert_sql(table: str) -> str:
    if table == TABLE_HOST:
        return (
            f"INSERT INTO {TABLE_HOST} (session_id, global_rank, local_rank,"
            " world_size, local_world_size, node_rank, hostname, pid, timestamp,"
            " cpu_pct, memory_used_bytes, memory_total_bytes, memory_pct,"
            " load_1m, load_5m, load_15m) VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?)"
        )
    return (
        f"INSERT INTO {TABLE_DEVICE} (session_id, global_rank, local_rank,"
        " world_size, local_world_size, node_rank, hostname, pid, timestamp,"
        " device_id, device_kind, memory_used_bytes, memory_peak_bytes,"
        " memory_total_bytes, utilization_pct, temperature_c, power_w)"
        " VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?)"
    )


def build_rows(env: TelemetryEnvelope) -> Dict[str, List[Tuple]]:
    ident = identity_tuple(env)
    out: Dict[str, List[Tuple]] = {}
    v = env.column_view("system")
    if v:
        ts = v.floats("timestamp")
        cpu = v.floats("cpu_pct")
        used = v.ints("memory_used_bytes")
        total = v.ints("memory_total_bytes")
        pct = v.floats("memory_pct")
        l1 = v.floats("load_1m")
        l5 = v.floats("load_5m")
        l15 = v.floats("load_15m")
        out[TABLE_HOST] = [
            ident + (ts[i], cpu[i], used[i], total[i], pct[i], l1[i], l5[i], l15[i])
            for i in range(len(v))
        ]
    v = env.column_view("system_device")
    if v:
        ts = v.floats("timestamp")
        dev_id = v.ints("device_id")
        kind = v.strs("device_kind", "unknown")
        used = v.ints("memory_used_bytes")
        peak = v.ints("memory_peak_bytes")
        total = v.ints("memory_total_bytes")
        util = v.floats("utilization_pct")
        temp = v.floats("temperature_c")
        power = v.floats("power_w")
        out[TABLE_DEVICE] = [
            ident
            + (
                ts[i],
                dev_id[i],
                kind[i],
                used[i],
                peak[i],
                total[i],
                util[i],
                temp[i],
                power[i],
            )
            for i in range(len(v))
        ]
    return out
