"""Consistent-hash ring: session ids → aggregator shards
(docs/developer_guide/federation.md).

The r13 delta protocol made shard affinity *optional* — the version
token is entirely client-held and a garbled token means "full serve",
so any shard can answer any viewer — but affinity is still what makes
the edge cache and the per-shard publisher caches hot.  The ring gives
every router instance the same session→shard mapping with zero
coordination: hash points are derived from ``sha1("<shard>#<vnode>")``,
which is stable across processes and Python versions (never the
builtin ``hash()``, which is salted per process).

Virtual nodes smooth the distribution: with ``vnodes=64`` per shard,
a 4-shard ring keeps per-shard load within a few percent of even, and
adding/removing one shard remaps only ~1/N of the sessions (pinned by
tests/federation/test_hash_ring.py).
"""

from __future__ import annotations

import bisect
import hashlib
import json
import re
from pathlib import Path
from typing import List, Optional, Sequence

#: hash points per shard — enough to keep a small ring near-uniform
#: without making construction or the sorted-list bisect noticeable
DEFAULT_VNODES = 64

#: a shard address is host:port — the only shape the router dials;
#: IPv6 hosts must be bracketed (``[::1]:9001``)
_SHARD_RE = re.compile(
    r"^(?:[A-Za-z0-9._\-]+|\[[0-9A-Fa-f:.]+\]):\d{1,5}$"
)


def valid_shard(shard: str) -> bool:
    return bool(isinstance(shard, str) and _SHARD_RE.match(shard))


def parse_shard_spec(spec: Optional[str]) -> List[str]:
    """``TRACEML_FLEET_SHARDS`` value → ordered unique shard list.

    Two grammars:

    * a comma-separated ``host:port`` list (whitespace tolerated);
    * a path ending in ``.json`` — a discovery file holding either a
      bare list ``["h:p", ...]`` or ``{"shards": ["h:p", ...]}``, so an
      external placement system can own the shard set.

    Invalid entries are dropped (a fleet list with one typo must not
    take the whole router down); an unreadable file yields ``[]``.
    """
    if not spec:
        return []
    spec = str(spec).strip()
    if spec.endswith(".json"):
        try:
            data = json.loads(Path(spec).read_text(encoding="utf-8"))
        except (OSError, ValueError):
            return []
        if isinstance(data, dict):
            data = data.get("shards")
        if not isinstance(data, list):
            return []
        raw = [s for s in data if isinstance(s, str)]
    else:
        raw = spec.split(",")
    out: List[str] = []
    for entry in raw:
        entry = entry.strip()
        if valid_shard(entry) and entry not in out:
            out.append(entry)
    return out


def _point(shard: str, vnode: int) -> int:
    digest = hashlib.sha1(f"{shard}#{vnode}".encode("utf-8")).digest()
    return int.from_bytes(digest[:8], "big")


class HashRing:
    """Immutable after construction — the router swaps whole rings when
    the shard set changes, so lookups never need a lock."""

    def __init__(
        self, shards: Sequence[str], vnodes: int = DEFAULT_VNODES
    ) -> None:
        self.shards: List[str] = list(dict.fromkeys(shards))
        self.vnodes = max(1, int(vnodes))
        points = []
        for shard in self.shards:
            for v in range(self.vnodes):
                points.append((_point(shard, v), shard))
        points.sort()
        self._points = [p for p, _ in points]
        self._owners = [s for _, s in points]

    def __len__(self) -> int:
        return len(self.shards)

    def owner(self, session_id: str) -> Optional[str]:
        """The shard owning ``session_id`` (None on an empty ring)."""
        if not self._points:
            return None
        key = int.from_bytes(
            hashlib.sha1(str(session_id).encode("utf-8")).digest()[:8], "big"
        )
        idx = bisect.bisect_right(self._points, key)
        if idx == len(self._points):
            idx = 0  # wrap: the ring is circular
        return self._owners[idx]

    def counts(self, session_ids: Sequence[str]) -> dict:
        """Per-shard assignment counts — distribution diagnostics."""
        out = {s: 0 for s in self.shards}
        for sid in session_ids:
            owner = self.owner(sid)
            if owner is not None:
                out[owner] += 1
        return out
