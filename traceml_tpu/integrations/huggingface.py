"""Hugging Face Trainer integration
(reference: src/traceml_ai/integrations/huggingface.py:27-192).

``TraceMLTrainerCallback`` is a pure bracket: ``on_step_begin`` opens a
``trace_step``, ``on_step_end`` closes it.  Gradient-accumulation
micro-batches fold into ONE traced step because the Trainer only fires
step begin/end per optimizer step.  Self-healing: a leaked context
(exception between callbacks) is closed before opening the next.

Works with torch-CPU Trainers today and torch-xla TPU Trainers
unchanged (the callback never touches device APIs — the patches and
samplers do, through their own gated paths).
"""

from __future__ import annotations

from typing import Any, Optional

from traceml_tpu.sdk.initial import init as traceml_init
from traceml_tpu.sdk.instrumentation import trace_step
from traceml_tpu.utils.error_log import get_error_log

try:  # transformers is optional at import time
    from transformers import TrainerCallback  # type: ignore

    _HAVE_TRANSFORMERS = True
except Exception:  # pragma: no cover
    TrainerCallback = object  # type: ignore
    _HAVE_TRANSFORMERS = False


class TraceMLTrainerCallback(TrainerCallback):  # type: ignore[misc]
    """Attach to ``Trainer(callbacks=[TraceMLTrainerCallback()])``."""

    def __init__(self, auto_init: bool = True) -> None:
        self._ctx: Optional[trace_step] = None
        self._auto_init = auto_init

    # -- hooks ---------------------------------------------------------
    def on_train_begin(self, args: Any = None, state: Any = None, control: Any = None, **kw: Any):
        if self._auto_init:
            try:
                traceml_init(mode="auto")
            except Exception as exc:
                get_error_log().warning("hf callback init failed", exc)
        return control

    def on_step_begin(self, args: Any = None, state: Any = None, control: Any = None, **kw: Any):
        try:
            if self._ctx is not None:
                # self-heal a leaked context (reference behavior)
                self._ctx.__exit__(None, None, None)
            self._ctx = trace_step()
            self._ctx.__enter__()
        except Exception as exc:
            get_error_log().warning("hf on_step_begin failed", exc)
            self._ctx = None
        return control

    def on_step_end(self, args: Any = None, state: Any = None, control: Any = None, **kw: Any):
        try:
            if self._ctx is not None:
                self._ctx.__exit__(None, None, None)
                self._ctx = None
        except Exception as exc:
            get_error_log().warning("hf on_step_end failed", exc)
        return control

    def on_train_end(self, args: Any = None, state: Any = None, control: Any = None, **kw: Any):
        try:
            if self._ctx is not None:
                self._ctx.__exit__(None, None, None)
                self._ctx = None
        except Exception as exc:
            get_error_log().warning("hf on_train_end failed", exc)
        return control


def TraceMLTrainer(*args: Any, **kwargs: Any):
    """``Trainer`` subclass with the callback pre-installed
    (reference: huggingface.py:155)."""
    if not _HAVE_TRANSFORMERS:
        raise ImportError("transformers is required for TraceMLTrainer")
    from transformers import Trainer

    class _TraceMLTrainer(Trainer):
        def __init__(self, *a: Any, **kw: Any) -> None:
            callbacks = list(kw.pop("callbacks", None) or [])
            if not any(isinstance(c, TraceMLTrainerCallback) for c in callbacks):
                callbacks.append(TraceMLTrainerCallback())
            super().__init__(*a, callbacks=callbacks, **kw)

    return _TraceMLTrainer(*args, **kwargs)
