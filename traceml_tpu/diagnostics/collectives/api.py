"""Collectives diagnosis entrypoint.

The cross-domain join happens here via ``step_time_ms``: the caller
(renderers/compute.py, reporting/final.py) passes the mean step
duration from the step_time window so COMM_BOUND can express exposed
collective time as a share of the step.  Without it the comm/compute
ratio rules stay silent and only overlap-shape rules can fire.
"""

from __future__ import annotations

from typing import Any, Mapping, Optional, Sequence

from traceml_tpu.diagnostics.common import (
    DiagnosticIssue,
    DiagnosticResult,
    SEVERITY_INFO,
    run_rules,
)
from traceml_tpu.diagnostics.collectives.policy import policy_for
from traceml_tpu.diagnostics.collectives.rules import DEFAULT_RULES, build_context
from traceml_tpu.utils.columnar import (
    CollectivesWindow,
    build_collectives_window_rows,
)

DOMAIN = "collectives"


def diagnose_collectives_window(
    window: Optional[CollectivesWindow],
    mode: str = "summary",
    step_time_ms: Optional[float] = None,
    topology: Optional[Any] = None,
) -> DiagnosticResult:
    """``topology``: the captured mesh (or None).  Fired issues whose
    ranks map onto a host / axis / DCN-side grouping of per-rank
    exposed comm time gain an ``attribution`` block."""
    policy = policy_for(mode)
    if window is None or window.n_steps < policy.min_steps:
        return DiagnosticResult(
            domain=DOMAIN,
            issues=[
                DiagnosticIssue(
                    kind="INSUFFICIENT_COLLECTIVES_DATA",
                    severity=SEVERITY_INFO,
                    status="ok",
                    summary=(
                        "Not enough steps with collective telemetry for a "
                        "reliable overlap diagnosis (have "
                        f"{0 if window is None else window.n_steps}, "
                        f"need {policy.min_steps})."
                    ),
                )
            ],
        )
    ctx = build_context(window, policy, step_time_ms=step_time_ms)
    result = run_rules(DOMAIN, DEFAULT_RULES, ctx)
    if topology is not None:
        from traceml_tpu.diagnostics.attribution import attach_attribution

        result = attach_attribution(
            result,
            topology,
            {
                r: float(v.get("exposed_ms", 0.0) or 0.0)
                for r, v in window.per_rank.items()
            },
        )
    return result


def diagnose_rank_rows(
    rank_rows: Mapping[int, Sequence[Mapping[str, Any]]],
    mode: str = "summary",
    max_steps: int = 200,
    step_time_ms: Optional[float] = None,
    topology: Optional[Any] = None,
) -> DiagnosticResult:
    window = build_collectives_window_rows(rank_rows, max_steps=max_steps)
    return diagnose_collectives_window(
        window, mode=mode, step_time_ms=step_time_ms, topology=topology
    )
