"""Achieved-FLOP/s + MFU computation — THE shared formula.

One implementation consumed by both the final summary
(reporting/final.py) and the live views (renderers/views.py) so the
same-named ``efficiency`` block can never drift between surfaces.
"""

from __future__ import annotations

import statistics
from typing import Any, Dict, Mapping, Optional


def build_efficiency(
    stats: Optional[Mapping[int, Mapping[str, Any]]],
    per_rank_step_ms: Mapping[Any, Optional[float]],
) -> Optional[Dict[str, Any]]:
    """The ``efficiency`` block (SCHEMA.md) or None.

    ``stats`` is loaders.load_model_stats output: per rank, the MEDIAN
    ``flops_per_step`` over recent declarations (robust to the
    per-step ``set_step_flops`` pattern under variable sequence
    lengths — pairing only the LAST declaration with window-median
    step times would skew MFU by the last batch's size) plus the
    latest source/device_kind/peak.  ``per_rank_step_ms`` maps rank →
    representative step duration (steady-state median when available).
    """
    if not stats:
        return None
    ms0 = next(iter(stats.values()))
    flops = ms0.get("flops_per_step")
    peak = ms0.get("peak_flops")
    if not flops:
        return None
    achieved = {
        str(r): flops / (v / 1000.0) / 1e12
        for r, v in per_rank_step_ms.items()
        if v
    }
    if not achieved:
        return None
    med = statistics.median(achieved.values())
    return {
        "flops_per_step": flops,
        "flops_source": ms0.get("flops_source"),
        "device_kind": ms0.get("device_kind"),
        "peak_tflops": (peak / 1e12) if peak else None,
        "achieved_tflops_by_rank": {r: round(v, 3) for r, v in achieved.items()},
        "achieved_tflops_median": round(med, 3),
        "mfu_median": (med * 1e12 / peak) if peak else None,
    }
