"""``python -m traceml_tpu`` → the CLI."""

import sys

from traceml_tpu.launcher.cli import main

sys.exit(main())
