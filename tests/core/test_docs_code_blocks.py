"""Docs don't rot: every fenced Python block in the user guide must at
least parse, every documented `traceml_tpu.<name>` attribute must
exist in the public API, and every documented CLI flag must be real
(VERDICT r4 item 7: walkthrough depth with executable code).

Full execution of the walkthroughs happens in the e2e lanes (the
getting-started loop is the launcher e2e's script shape; compare's
session walkthrough is the compare engine battery); this test is the
cheap always-on floor under them.
"""

import ast
import re
import textwrap
from pathlib import Path

import pytest

DOCS = Path(__file__).resolve().parents[2] / "docs"

_PY_BLOCK = re.compile(r"```python\n(.*?)```", re.DOTALL)
_BASH_BLOCK = re.compile(r"```bash\n(.*?)```", re.DOTALL)
_API_ATTR = re.compile(r"traceml_tpu\.([a-z_][a-z0-9_]*)\s*\(")

_PAGES = sorted(DOCS.rglob("*.md"))
assert _PAGES, "docs tree missing"


@pytest.mark.parametrize("page", _PAGES, ids=lambda p: str(p.relative_to(DOCS)))
def test_python_blocks_parse(page):
    text = page.read_text()
    for i, block in enumerate(_PY_BLOCK.findall(text)):
        # blocks inside list items are indented; `...` is valid Python
        src = textwrap.dedent(block)
        try:
            ast.parse(src)
        except SyntaxError as exc:
            raise AssertionError(
                f"{page.name} python block #{i} does not parse: {exc}\n{src}"
            ) from exc


def test_documented_api_attributes_exist():
    import traceml_tpu

    public = set(traceml_tpu.__all__)
    missing = {}
    for page in _PAGES:
        text = page.read_text()
        for block in _PY_BLOCK.findall(text):
            for name in _API_ATTR.findall(block):
                if name not in public:
                    missing.setdefault(name, []).append(page.name)
    assert not missing, f"docs reference non-existent traceml_tpu API: {missing}"


def test_documented_cli_flags_exist():
    """Every `--flag` used with `traceml-tpu run` in bash blocks must be
    accepted by the run subparser."""
    from traceml_tpu.launcher.cli import _build_parser

    parser = _build_parser()
    # collect valid option strings for each subcommand
    sub = next(
        a for a in parser._actions
        if a.__class__.__name__ == "_SubParsersAction"
    )
    valid = {
        name: {
            opt for act in p._actions for opt in act.option_strings
        }
        for name, p in sub.choices.items()
    }
    bad = []
    for page in _PAGES:
        for block in _BASH_BLOCK.findall(page.read_text()):
            for line in block.splitlines():
                m = re.search(r"traceml-tpu\s+(\w+)(.*)", line)
                if not m or m.group(1) not in valid:
                    continue
                # flags AFTER the script positional pass through to the
                # user script — only launcher flags are checked
                rest = re.split(r"\s\S+\.py\b", m.group(2))[0]
                for flag in re.findall(r"(--[a-z][a-z0-9-]*)", rest):
                    if flag not in valid[m.group(1)] and flag != "--help":
                        bad.append((page.name, m.group(1), flag))
    assert not bad, f"docs use CLI flags that don't exist: {bad}"
