"""Deep-integration battery: Lightning callback owning phase timing
(stub Lightning), Ray actor-hosted aggregator (stub ray), and the
project-level AST scan (VERDICT r1 item 10)."""

import sys
import types

import pytest

from traceml_tpu.utils import timing as T


# --------------------------------------------------------------------------
# Lightning (stubbed base)
# --------------------------------------------------------------------------

@pytest.fixture()
def stub_lightning(monkeypatch):
    import traceml_tpu.integrations.lightning as L

    pl = types.ModuleType("pytorch_lightning")

    class Callback:
        pass

    pl.Callback = Callback
    monkeypatch.setitem(sys.modules, "pytorch_lightning", pl)
    monkeypatch.setattr(L, "_cached_callback_cls", None)
    yield L


class _Trainer:
    sanity_checking = False


class _Loss:
    """Loss stand-in with a readiness probe (device-marker carrier)."""

    size = 1

    def is_ready(self):
        return True


def _drive_one_batch(cb, trainer):
    """REAL Lightning automatic-optimization hook order:
    batch_start → training_step → before_zero_grad → zero_grad →
    before_backward → backward → after_backward →
    before_optimizer_step → step → batch_end."""
    cb.on_train_batch_start(trainer, None, batch=None, batch_idx=0)
    cb.on_before_zero_grad(trainer, None, optimizer=None)  # BEFORE backward!
    cb.on_before_backward(trainer, None, loss=_Loss())
    cb.on_after_backward(trainer, None)
    cb.on_before_optimizer_step(trainer, None, optimizer=None)
    cb.on_train_batch_end(trainer, None, outputs=None, batch=None, batch_idx=0)


def test_lightning_callback_owns_phase_timing(stub_lightning):
    from traceml_tpu.sdk.state import get_state

    cb = stub_lightning.TraceMLCallback(auto_init=False)
    st = get_state()
    captured = []
    st.on_batch_flushed.append(captured.append)
    try:
        trainer = _Trainer()
        _drive_one_batch(cb, trainer)
        events = captured[-1].events
        names = [e.name for e in events]
        assert T.FORWARD_TIME in names
        assert T.BACKWARD_TIME in names
        assert T.OPTIMIZER_STEP in names
        assert T.STEP_TIME in names
        # the early zero_grad must NOT have closed forward — forward ends
        # at before_backward and carries the loss device probe
        fwd = next(e for e in events if e.name == T.FORWARD_TIME)
        bwd = next(e for e in events if e.name == T.BACKWARD_TIME)
        assert fwd.marker is not None  # loss probe attached
        assert fwd.cpu_end <= bwd.cpu_start
        # duplicate-guard depths restored after the batch
        assert st.tls.forward_depth == 0
        assert st.tls.backward_depth == 0
    finally:
        st.on_batch_flushed.remove(captured.append)
        cb.teardown(trainer, None)


def test_lightning_manual_optimization_order(stub_lightning):
    """Manual-optimization order (zero_grad AFTER step) also maps cleanly."""
    from traceml_tpu.sdk.state import get_state

    cb = stub_lightning.TraceMLCallback(auto_init=False)
    st = get_state()
    captured = []
    st.on_batch_flushed.append(captured.append)
    try:
        trainer = _Trainer()
        cb.on_train_batch_start(trainer, None, batch=None, batch_idx=0)
        cb.on_before_backward(trainer, None, loss=_Loss())
        cb.on_after_backward(trainer, None)
        cb.on_before_optimizer_step(trainer, None, optimizer=None)
        cb.on_before_zero_grad(trainer, None, optimizer=None)  # closes optimizer
        cb.on_train_batch_end(trainer, None, outputs=None, batch=None, batch_idx=0)
        events = captured[-1].events
        opt = next(e for e in events if e.name == T.OPTIMIZER_STEP)
        assert opt.cpu_end is not None
    finally:
        st.on_batch_flushed.remove(captured.append)
        cb.teardown(trainer, None)


def test_lightning_sanity_check_not_timed(stub_lightning):
    from traceml_tpu.sdk.state import get_state

    cb = stub_lightning.TraceMLCallback(auto_init=False)
    st = get_state()
    captured = []
    st.on_batch_flushed.append(captured.append)
    try:
        trainer = _Trainer()
        trainer.sanity_checking = True
        before = len(captured)
        _drive_one_batch(cb, trainer)
        assert len(captured) == before  # nothing flushed
    finally:
        st.on_batch_flushed.remove(captured.append)


def test_lightning_survives_out_of_order_hooks(stub_lightning):
    cb = stub_lightning.TraceMLCallback(auto_init=False)
    trainer = _Trainer()
    # end without start, backward without step — all no-ops, no raise
    cb.on_train_batch_end(trainer, None, outputs=None, batch=None, batch_idx=0)
    cb.on_before_backward(trainer, None, loss=None)
    cb.on_train_end(trainer, None)


# --------------------------------------------------------------------------
# Ray (stubbed runtime)
# --------------------------------------------------------------------------

@pytest.fixture()
def stub_ray(monkeypatch):
    ray = types.ModuleType("ray")
    registry = {}

    class _Ref:
        def __init__(self, value):
            self.value = value

    class _Method:
        def __init__(self, fn):
            self._fn = fn

        def remote(self, *a, **k):
            return _Ref(self._fn(*a, **k))

    class _Handle:
        def __init__(self, impl):
            self._impl = impl

        def __getattr__(self, name):
            return _Method(getattr(self._impl, name))

    class _RemoteCls:
        def __init__(self, cls):
            self._cls = cls
            self._name = None

        def options(self, name=None, **kw):
            self._name = name
            return self

        def remote(self, *args, **kwargs):
            handle = _Handle(self._cls(*args, **kwargs))
            if self._name:
                registry[self._name] = handle
            return handle

    def get_actor(name):
        if name not in registry:
            raise ValueError(f"no actor {name}")
        return registry[name]

    ray.remote = lambda cls: _RemoteCls(cls)
    ray.get = lambda ref, timeout=None: ref.value
    ray.get_actor = get_actor
    ray.util = types.SimpleNamespace(get_node_ip_address=lambda: "127.0.0.1")
    ray._registry = registry
    monkeypatch.setitem(sys.modules, "ray", ray)
    yield ray


def test_ray_actor_hosted_aggregator(stub_ray, tmp_path):
    from traceml_tpu.integrations.ray import (
        actor_name_for,
        resolve_actor_endpoint,
        start_actor_aggregator,
    )
    from traceml_tpu.runtime.settings import TraceMLSettings

    settings = TraceMLSettings(
        session_id="rayrun", logs_dir=tmp_path, mode="summary",
        expected_world_size=1, finalize_timeout_sec=5.0,
    )
    name = actor_name_for(settings)
    assert name == "traceml_aggregator_rayrun"  # session-scoped
    actor = start_actor_aggregator(settings)
    assert actor is stub_ray.get_actor(name)
    endpoint = resolve_actor_endpoint(stub_ray, name=name, timeout=5)
    assert endpoint and endpoint["port"] > 0
    # a real TCP client can reach the actor-hosted aggregator
    from traceml_tpu.transport.tcp_transport import TCPClient
    from traceml_tpu.telemetry.envelope import (
        SenderIdentity,
        build_telemetry_envelope,
    )

    client = TCPClient(endpoint["host"], endpoint["port"])
    ident = SenderIdentity(session_id="rayrun", global_rank=0)
    assert client.send_batch(
        [build_telemetry_envelope("process", {"process": []}, ident)]
    )
    client.close()
    assert stub_ray.get(actor.finalize.remote()) is True
    assert (tmp_path / "rayrun" / "final_summary.json").exists()


def test_ray_settings_roundtrip(tmp_path):
    from traceml_tpu.runtime.settings import AggregatorEndpoint, TraceMLSettings

    s = TraceMLSettings(
        session_id="x", logs_dir=tmp_path, mode="summary",
        aggregator=AggregatorEndpoint(connect_host="10.0.0.9", port=777),
    )
    back = TraceMLSettings.from_dict(s.to_dict())
    assert back == s


# --------------------------------------------------------------------------
# project-level AST scan
# --------------------------------------------------------------------------

def _write(p, text):
    p.write_text(text, encoding="utf-8")
    return p


def test_analyze_project_traverses_local_imports(tmp_path):
    from traceml_tpu.launcher.ast_scan import analyze_project

    _write(tmp_path / "model.py", """
import jax
from jax.sharding import Mesh, PartitionSpec
def build():
    return jax.jit(lambda x: x, donate_argnums=(0,))
""")
    _write(tmp_path / "data.py", """
from torch.utils.data import DataLoader
def loader(ds):
    return DataLoader(ds, batch_size=32, num_workers=0)
""")
    (tmp_path / "helpers").mkdir()
    _write(tmp_path / "helpers" / "__init__.py", """
import entry  # circular — must not loop
""")
    entry = _write(tmp_path / "entry.py", """
import model
import data
import helpers
import optax
""")
    info = analyze_project(entry)
    assert info["modules_scanned"] == 4  # entry + model + data + helpers
    assert info["framework"] == "jax"
    assert "gspmd" in info["parallelism_hints"]
    assert "buffer_donation" in info["uses"]
    assert "single_worker_dataloader" in info["input_hints"]
    assert len(info["local_modules"]) == 3


def test_analyze_project_relative_imports(tmp_path):
    from traceml_tpu.launcher.ast_scan import analyze_project

    pkg = tmp_path / "pkg"
    pkg.mkdir()
    _write(pkg / "__init__.py", "")
    _write(pkg / "model.py", """
from . import layers
from .sharding import mesh_rules
""")
    _write(pkg / "layers.py", """
from torch.nn.parallel import DistributedDataParallel
""")
    _write(pkg / "sharding.py", """
from jax.sharding import Mesh, PartitionSpec
def mesh_rules(): ...
""")
    entry = _write(tmp_path / "train.py", "from pkg.model import build\n")
    info = analyze_project(entry)
    scanned = {p.rsplit("/", 1)[-1] for p in info["local_modules"]}
    assert {"model.py", "layers.py", "sharding.py"} <= scanned
    assert "gspmd" in info["parallelism_hints"]  # from pkg/sharding.py
    assert "ddp" in info["parallelism_hints"]    # from pkg/layers.py


def test_analyze_project_bounded(tmp_path):
    from traceml_tpu.launcher.ast_scan import analyze_project

    for i in range(30):
        nxt = f"import m{i + 1}" if i < 29 else ""
        _write(tmp_path / f"m{i}.py", nxt)
    entry = _write(tmp_path / "entry.py", "import m0")
    info = analyze_project(entry, max_modules=5)
    assert info["modules_scanned"] == 5


def test_strategy_and_qlora_detection(tmp_path):
    from traceml_tpu.launcher.ast_scan import analyze_script

    script = _write(tmp_path / "train.py", """
import torch
from lightning import Trainer
from transformers import TrainingArguments, BitsAndBytesConfig
from peft import LoraConfig

bnb = BitsAndBytesConfig(load_in_4bit=True, bnb_4bit_quant_type="nf4")
lora = LoraConfig(r=16, lora_alpha=32, target_modules=["q_proj", "v_proj"])
args = TrainingArguments(per_device_train_batch_size=8, bf16=True,
                         fsdp="full_shard")
trainer = Trainer(strategy="deepspeed_stage_3", devices=8, precision="bf16-mixed")
""")
    info = analyze_script(script)
    assert "fsdp" in info["parallelism_hints"]
    assert "deepspeed" in info["parallelism_hints"]
    assert info["trainer_strategy"] == "deepspeed_stage_3"
    assert info["trainer_args"]["devices"] == 8
    assert info["quantization"]["load_in_4bit"] is True
    assert info["quantization"]["lora"]["r"] == 16
    assert "lora/qlora" in info["uses"]
    assert info["hf_training_args"]["bf16"] is True


def test_broken_local_module_not_fatal(tmp_path):
    from traceml_tpu.launcher.ast_scan import analyze_project

    _write(tmp_path / "bad.py", "def broken(:\n")
    entry = _write(tmp_path / "entry.py", "import bad\nimport jax\n")
    info = analyze_project(entry)
    assert info["framework"] == "jax"
    assert info["modules_failed"]
