"""Run-level primary diagnosis
(reference: src/traceml_ai/reporting/primary_diagnosis.py:617-673).

Promotes the step-time finding to run level; falls back to
``NO_CLEAR_PERFORMANCE_BOTTLENECK`` / ``INSUFFICIENT_STEP_TIME_DATA``.
A non-healthy memory/system finding of higher severity can outrank an
info-grade step-time verdict.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from traceml_tpu.diagnostics.common import (
    SEVERITY_CRITICAL,
    SEVERITY_WARNING,
    DiagnosticResult,
)

_SEV_ORDER = {SEVERITY_CRITICAL: 2, SEVERITY_WARNING: 1}


def build_primary_diagnosis(
    step_time: Optional[DiagnosticResult],
    step_memory: Optional[DiagnosticResult] = None,
    system: Optional[DiagnosticResult] = None,
    process: Optional[DiagnosticResult] = None,
    step_time_error: Optional[str] = None,
    collectives: Optional[DiagnosticResult] = None,
    liveness: Optional[DiagnosticResult] = None,
    serving: Optional[DiagnosticResult] = None,
) -> Dict[str, Any]:
    candidates = []
    if liveness is not None and not liveness.healthy:
        # a lost rank trumps every performance story: the run's world
        # shrank, cross-rank metrics past the loss point cover
        # survivors only, and any perf verdict is computed on a
        # different machine count than the user asked for
        issue = liveness.diagnosis
        candidates.append(
            (_SEV_ORDER.get(issue.severity, 0) + 0.7, "liveness", issue)
        )
    if step_time is not None:
        issue = step_time.diagnosis
        if issue.kind == "INSUFFICIENT_STEP_TIME_DATA":
            candidates.append((0.5, "step_time", issue))
        elif not step_time.healthy or issue.kind == "COMPUTE_BOUND":
            # step-time issues get a priority bump: they ARE the
            # performance story (reference promotes step-time first)
            candidates.append(
                (_SEV_ORDER.get(issue.severity, 0) + 0.6, "step_time", issue)
            )
    if collectives is not None and not collectives.healthy:
        # collectives is a model domain too (the user's schedule causes
        # it): a COMM_BOUND verdict outranks environment findings of the
        # same severity but defers to a step-time verdict — step time is
        # where the comm tax is actually paid
        issue = collectives.diagnosis
        candidates.append(
            (_SEV_ORDER.get(issue.severity, 0) + 0.5, "collectives", issue)
        )
    if serving is not None and not serving.healthy:
        # serving sits at collectives priority: a saturated queue or a
        # pressured KV cache IS the workload's performance story, but a
        # step-time verdict (mixed training+serving sessions) still
        # names where the time is actually spent
        issue = serving.diagnosis
        candidates.append(
            (_SEV_ORDER.get(issue.severity, 0) + 0.5, "serving", issue)
        )
    for domain, result in (
        ("step_memory", step_memory),
        ("system", system),
        ("process", process),
    ):
        if result is not None and not result.healthy:
            issue = result.diagnosis
            candidates.append((_SEV_ORDER.get(issue.severity, 0), domain, issue))

    if not candidates:
        if step_time is None and step_time_error:
            # the section BUILDER failed — telemetry may exist; send the
            # user to the reported error, not to their instrumentation
            return {
                "kind": "INSUFFICIENT_STEP_TIME_DATA",
                "domain": "run",
                "severity": "info",
                "summary": (
                    f"Step-time analysis failed: {step_time_error}"
                ),
                "action": "See sections.step_time.error in the summary.",
            }
        if step_time is None:
            # nothing was even measured — "no bottleneck" would imply a
            # healthy run when there is simply no step data at all
            return {
                "kind": "INSUFFICIENT_STEP_TIME_DATA",
                "domain": "run",
                "severity": "info",
                "summary": "No step telemetry was recorded.",
                "action": (
                    "Check that trace_step() brackets the loop and the "
                    "runtime started (TRACEML_DISABLE unset)."
                ),
            }
        return {
            "kind": "NO_CLEAR_PERFORMANCE_BOTTLENECK",
            "domain": "run",
            "severity": "info",
            "summary": (
                "No dominant bottleneck or anomaly detected in the analyzed "
                "window."
            ),
            "action": "",
        }
    candidates.sort(key=lambda c: -c[0])
    _prio, domain, issue = candidates[0]
    out = issue.to_dict()
    out["domain"] = domain
    return out
