"""Control-plane messages sharing the telemetry channel
(reference: src/traceml_ai/telemetry/control.py:24-81).

Three control messages today:

* ``rank_finished`` — the end-of-run barrier marker the aggregator
  counts against ``expected_world_size`` before finalizing
  (reference: aggregator/trace_aggregator.py:440-499).
* ``producer_stats`` — periodic per-rank publisher self-observability
  (collect/encode/flush microseconds, idle-tick ratio; see
  docs/developer_guide/rank-producer-path.md).  Aggregated into
  ``ingest_stats.json`` under ``producers``.
* ``rank_heartbeat`` — periodic per-rank liveness beacon, sent even on
  idle ticks so a silent-but-alive rank stays distinguishable from a
  dead one (aggregator/liveness.py drives STALE→LOST transitions off
  last-seen; docs/developer_guide/fault-tolerance.md).
* ``mesh_topology`` — one-shot per-rank mesh placement (axis
  names/sizes, ICI/DCN kind per axis, this rank's coordinates),
  captured by utils/topology.py and persisted so diagnoses can be
  attributed to physical structure
  (docs/developer_guide/topology-attribution.md).
* ``transport_hello`` — one-shot per-rank announcement of the chosen
  transport tier (shm/uds/tcp) and compression codec, surfaced in
  ``ingest_stats.json`` → the report meta strip
  (docs/developer_guide/native-transport.md).  Observability only:
  the wire is self-describing, nothing is negotiated off this.

All are idempotent on replay (set-add / keep-latest / last-seen max),
so the durable-send spool may re-deliver them without a dedup table.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Mapping, Optional

CONTROL_KEY = "_traceml_control"
RANK_FINISHED = "rank_finished"
PRODUCER_STATS = "producer_stats"
RANK_HEARTBEAT = "rank_heartbeat"
MESH_TOPOLOGY = "mesh_topology"
TRANSPORT_HELLO = "transport_hello"


def build_rank_finished(identity_meta: Mapping[str, Any]) -> Dict[str, Any]:
    return {
        CONTROL_KEY: RANK_FINISHED,
        "meta": dict(identity_meta),
        "timestamp": time.time(),
    }


def build_rank_heartbeat(identity_meta: Mapping[str, Any]) -> Dict[str, Any]:
    return {
        CONTROL_KEY: RANK_HEARTBEAT,
        "meta": dict(identity_meta),
        "timestamp": time.time(),
    }


def build_producer_stats(
    identity_meta: Mapping[str, Any], stats: Mapping[str, Any]
) -> Dict[str, Any]:
    return {
        CONTROL_KEY: PRODUCER_STATS,
        "meta": dict(identity_meta),
        "stats": dict(stats),
        "timestamp": time.time(),
    }


def build_mesh_topology(
    identity_meta: Mapping[str, Any], topology: Mapping[str, Any]
) -> Dict[str, Any]:
    return {
        CONTROL_KEY: MESH_TOPOLOGY,
        "meta": dict(identity_meta),
        "topology": dict(topology),
        "timestamp": time.time(),
    }


def build_transport_hello(
    identity_meta: Mapping[str, Any],
    kind: Optional[str],
    compression: Optional[str],
    fallback_from: Optional[str] = None,
) -> Dict[str, Any]:
    msg: Dict[str, Any] = {
        CONTROL_KEY: TRANSPORT_HELLO,
        "meta": dict(identity_meta),
        "transport": kind,
        "compression": compression,
        "timestamp": time.time(),
    }
    if fallback_from:
        msg["fallback_from"] = fallback_from
    return msg


def is_control_message(payload: Any) -> bool:
    return isinstance(payload, Mapping) and CONTROL_KEY in payload


def control_kind(payload: Any) -> Optional[str]:
    if not is_control_message(payload):
        return None
    return str(payload[CONTROL_KEY])
