/* Lock-free SPSC ring operations over a shared memory-mapped buffer.
 *
 * Layout (all integers little-endian, header is 64 bytes):
 *
 *   off  size  field
 *     0     4  magic "TMR1"
 *     4     4  version (1)
 *     8     8  capacity          data-region bytes (buffer len - 64)
 *    16     8  head              producer-owned: total bytes published
 *    24     8  tail              consumer-owned: total bytes consumed
 *    32     8  producer_gen      stamped by the producer at create
 *    40     8  consumer_gen      stamped by the consumer at attach
 *    48     4  producer_pid
 *    52    12  reserved
 *    64     -  data region: u32-le length-prefixed frames, bytes wrap
 *              modulo capacity (a frame may straddle the wrap point)
 *
 * Single-producer (one rank's client thread), single-consumer (the
 * aggregator's selector tick).  Commit protocol: the producer memcpys
 * the length prefix + body into free space, then publishes by storing
 * head with release order.  A consumer never sees a torn frame — bytes
 * beyond head are invisible, and kill -9 mid-write just leaves
 * unpublished garbage that the next append overwrites.
 *
 *   ring_append(buf, payload) -> 0 (full) | 1 (published)
 *   ring_drain(buf, max_frames) -> list[bytes]     (advances tail)
 *   ring_peek(buf, cursor, max_frames) -> (list[bytes], new_cursor)
 *                                         (tail untouched)
 *   ring_set_tail(buf, value) -> None              (commit point)
 *   ring_readable(buf) -> int                      (bytes pending)
 *
 * Durable consumption is two-phase: the aggregator peeks frames from
 * an in-memory cursor and only stores tail (ring_set_tail) once the
 * envelopes are group-committed to sqlite.  A consumer crash between
 * peek and commit re-delivers the window to its successor, and the
 * writer's seq dedup drops the overlap — the ring is a replay buffer,
 * not just a queue.
 *
 * The Python mirror lives in transport/shm_ring.py; both sides of a
 * ring may independently be native or pure-Python — the layout is the
 * contract, not the code.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

#define RING_HDR 64
#define OFF_CAPACITY 8
#define OFF_HEAD 16
#define OFF_TAIL 24

static int check_buf(Py_buffer *view, uint64_t *capacity) {
    if (view->len < RING_HDR + 8) {
        PyErr_SetString(PyExc_ValueError, "ring buffer too small");
        return -1;
    }
    const unsigned char *p = (const unsigned char *)view->buf;
    if (memcmp(p, "TMR1", 4) != 0) {
        PyErr_SetString(PyExc_ValueError, "bad ring magic");
        return -1;
    }
    memcpy(capacity, p + OFF_CAPACITY, 8);
    if (*capacity == 0 || (Py_ssize_t)(*capacity + RING_HDR) > view->len) {
        PyErr_SetString(PyExc_ValueError, "ring capacity out of range");
        return -1;
    }
    return 0;
}

static inline uint64_t load_acquire_u64(const void *p) {
    uint64_t v;
    __atomic_load((const uint64_t *)p, &v, __ATOMIC_ACQUIRE);
    return v;
}

static inline void store_release_u64(void *p, uint64_t v) {
    __atomic_store((uint64_t *)p, &v, __ATOMIC_RELEASE);
}

/* copy n bytes into the data region at logical position pos (wraps) */
static void ring_write(unsigned char *data, uint64_t capacity, uint64_t pos,
                       const unsigned char *src, uint64_t n) {
    uint64_t at = pos % capacity;
    uint64_t first = capacity - at;
    if (first > n) first = n;
    memcpy(data + at, src, first);
    if (n > first) memcpy(data, src + first, n - first);
}

static void ring_read(const unsigned char *data, uint64_t capacity,
                      uint64_t pos, unsigned char *dst, uint64_t n) {
    uint64_t at = pos % capacity;
    uint64_t first = capacity - at;
    if (first > n) first = n;
    memcpy(dst, data + at, first);
    if (n > first) memcpy(dst + first, data, n - first);
}

static PyObject *ring_append(PyObject *self, PyObject *args) {
    Py_buffer view, payload;
    if (!PyArg_ParseTuple(args, "w*y*", &view, &payload)) {
        return NULL;
    }
    uint64_t capacity;
    if (check_buf(&view, &capacity) < 0) {
        PyBuffer_Release(&payload);
        PyBuffer_Release(&view);
        return NULL;
    }
    unsigned char *base = (unsigned char *)view.buf;
    uint64_t need = 4 + (uint64_t)payload.len;
    if (need > capacity) {
        PyBuffer_Release(&payload);
        PyBuffer_Release(&view);
        PyErr_SetString(PyExc_ValueError, "frame larger than ring");
        return NULL;
    }
    uint64_t head = load_acquire_u64(base + OFF_HEAD);
    uint64_t tail = load_acquire_u64(base + OFF_TAIL);
    if (head - tail + need > capacity) {
        PyBuffer_Release(&payload);
        PyBuffer_Release(&view);
        return PyLong_FromLong(0); /* full */
    }
    unsigned char prefix[4];
    uint32_t n32 = (uint32_t)payload.len;
    prefix[0] = (unsigned char)n32;
    prefix[1] = (unsigned char)(n32 >> 8);
    prefix[2] = (unsigned char)(n32 >> 16);
    prefix[3] = (unsigned char)(n32 >> 24);
    unsigned char *data = base + RING_HDR;
    ring_write(data, capacity, head, prefix, 4);
    ring_write(data, capacity, head + 4,
               (const unsigned char *)payload.buf, (uint64_t)payload.len);
    store_release_u64(base + OFF_HEAD, head + need);
    PyBuffer_Release(&payload);
    PyBuffer_Release(&view);
    return PyLong_FromLong(1);
}

static PyObject *ring_drain(PyObject *self, PyObject *args) {
    Py_buffer view;
    Py_ssize_t max_frames;
    if (!PyArg_ParseTuple(args, "w*n", &view, &max_frames)) {
        return NULL;
    }
    uint64_t capacity;
    if (check_buf(&view, &capacity) < 0) {
        PyBuffer_Release(&view);
        return NULL;
    }
    unsigned char *base = (unsigned char *)view.buf;
    const unsigned char *data = base + RING_HDR;
    PyObject *frames = PyList_New(0);
    if (frames == NULL) {
        PyBuffer_Release(&view);
        return NULL;
    }
    uint64_t tail = load_acquire_u64(base + OFF_TAIL);
    uint64_t head = load_acquire_u64(base + OFF_HEAD);
    Py_ssize_t emitted = 0;
    while ((max_frames <= 0 || emitted < max_frames) && head - tail >= 4) {
        unsigned char prefix[4];
        ring_read(data, capacity, tail, prefix, 4);
        uint32_t n = (uint32_t)prefix[0] | ((uint32_t)prefix[1] << 8) |
                     ((uint32_t)prefix[2] << 16) | ((uint32_t)prefix[3] << 24);
        if ((uint64_t)n + 4 > capacity || head - tail < 4 + (uint64_t)n) {
            /* corrupt length or incomplete publish (cannot happen with a
             * well-behaved producer): surface as ValueError so the
             * consumer quarantines the ring */
            if ((uint64_t)n + 4 > capacity) {
                Py_DECREF(frames);
                PyBuffer_Release(&view);
                PyErr_Format(PyExc_ValueError,
                             "ring frame length %u exceeds capacity", n);
                return NULL;
            }
            break;
        }
        PyObject *frame = PyBytes_FromStringAndSize(NULL, (Py_ssize_t)n);
        if (frame == NULL) {
            Py_DECREF(frames);
            PyBuffer_Release(&view);
            return NULL;
        }
        ring_read(data, capacity, tail + 4,
                  (unsigned char *)PyBytes_AS_STRING(frame), n);
        if (PyList_Append(frames, frame) < 0) {
            Py_DECREF(frame);
            Py_DECREF(frames);
            PyBuffer_Release(&view);
            return NULL;
        }
        Py_DECREF(frame);
        tail += 4 + (uint64_t)n;
        emitted++;
        store_release_u64(base + OFF_TAIL, tail);
    }
    PyBuffer_Release(&view);
    return frames;
}

static PyObject *ring_peek(PyObject *self, PyObject *args) {
    Py_buffer view;
    unsigned long long cursor_in;
    Py_ssize_t max_frames;
    if (!PyArg_ParseTuple(args, "w*Kn", &view, &cursor_in, &max_frames)) {
        return NULL;
    }
    uint64_t capacity;
    if (check_buf(&view, &capacity) < 0) {
        PyBuffer_Release(&view);
        return NULL;
    }
    unsigned char *base = (unsigned char *)view.buf;
    const unsigned char *data = base + RING_HDR;
    uint64_t cursor = (uint64_t)cursor_in;
    uint64_t head = load_acquire_u64(base + OFF_HEAD);
    if (cursor > head) {
        PyBuffer_Release(&view);
        PyErr_SetString(PyExc_ValueError, "ring cursor beyond head");
        return NULL;
    }
    PyObject *frames = PyList_New(0);
    if (frames == NULL) {
        PyBuffer_Release(&view);
        return NULL;
    }
    Py_ssize_t emitted = 0;
    while ((max_frames <= 0 || emitted < max_frames) && head - cursor >= 4) {
        unsigned char prefix[4];
        ring_read(data, capacity, cursor, prefix, 4);
        uint32_t n = (uint32_t)prefix[0] | ((uint32_t)prefix[1] << 8) |
                     ((uint32_t)prefix[2] << 16) | ((uint32_t)prefix[3] << 24);
        if ((uint64_t)n + 4 > capacity) {
            Py_DECREF(frames);
            PyBuffer_Release(&view);
            PyErr_Format(PyExc_ValueError,
                         "ring frame length %u exceeds capacity", n);
            return NULL;
        }
        if (head - cursor < 4 + (uint64_t)n) break; /* mid-publish */
        PyObject *frame = PyBytes_FromStringAndSize(NULL, (Py_ssize_t)n);
        if (frame == NULL) {
            Py_DECREF(frames);
            PyBuffer_Release(&view);
            return NULL;
        }
        ring_read(data, capacity, cursor + 4,
                  (unsigned char *)PyBytes_AS_STRING(frame), n);
        if (PyList_Append(frames, frame) < 0) {
            Py_DECREF(frame);
            Py_DECREF(frames);
            PyBuffer_Release(&view);
            return NULL;
        }
        Py_DECREF(frame);
        cursor += 4 + (uint64_t)n;
        emitted++;
    }
    PyBuffer_Release(&view);
    PyObject *cur = PyLong_FromUnsignedLongLong(cursor);
    if (cur == NULL) {
        Py_DECREF(frames);
        return NULL;
    }
    PyObject *out = PyTuple_Pack(2, frames, cur);
    Py_DECREF(frames);
    Py_DECREF(cur);
    return out;
}

static PyObject *ring_set_tail(PyObject *self, PyObject *args) {
    Py_buffer view;
    unsigned long long value;
    if (!PyArg_ParseTuple(args, "w*K", &view, &value)) {
        return NULL;
    }
    uint64_t capacity;
    if (check_buf(&view, &capacity) < 0) {
        PyBuffer_Release(&view);
        return NULL;
    }
    unsigned char *base = (unsigned char *)view.buf;
    store_release_u64(base + OFF_TAIL, (uint64_t)value);
    PyBuffer_Release(&view);
    Py_RETURN_NONE;
}

static PyObject *ring_readable(PyObject *self, PyObject *args) {
    Py_buffer view;
    if (!PyArg_ParseTuple(args, "y*", &view)) {
        return NULL;
    }
    uint64_t capacity;
    if (check_buf(&view, &capacity) < 0) {
        PyBuffer_Release(&view);
        return NULL;
    }
    const unsigned char *base = (const unsigned char *)view.buf;
    uint64_t head = load_acquire_u64(base + OFF_HEAD);
    uint64_t tail = load_acquire_u64(base + OFF_TAIL);
    PyBuffer_Release(&view);
    return PyLong_FromUnsignedLongLong(head - tail);
}

static PyMethodDef Methods[] = {
    {"ring_append", ring_append, METH_VARARGS,
     "ring_append(buf, payload) -> 0 if full else 1"},
    {"ring_drain", ring_drain, METH_VARARGS,
     "ring_drain(buf, max_frames) -> list[bytes]"},
    {"ring_peek", ring_peek, METH_VARARGS,
     "ring_peek(buf, cursor, max_frames) -> (list[bytes], new_cursor)"},
    {"ring_set_tail", ring_set_tail, METH_VARARGS,
     "ring_set_tail(buf, value) -> None (the durable-commit point)"},
    {"ring_readable", ring_readable, METH_VARARGS,
     "ring_readable(buf) -> pending byte count"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef module = {
    PyModuleDef_HEAD_INIT, "_ring",
    "C fast path for the SPSC shared-memory telemetry ring", -1, Methods,
};

PyMODINIT_FUNC PyInit__ring(void) { return PyModule_Create(&module); }
