"""Telemetry publisher (reference: src/traceml_ai/runtime/sender.py:17-174).

Per tick: flush disk writers, collect each sampler sender's incremental
payload, ship ONE batch over TCP.  Best-effort all the way down.
"""

from __future__ import annotations

from typing import Any, List, Optional

from traceml_tpu.samplers.base_sampler import BaseSampler
from traceml_tpu.telemetry.envelope import SenderIdentity
from traceml_tpu.transport.tcp_transport import TCPClient
from traceml_tpu.utils.error_log import get_error_log


class TelemetryPublisher:
    def __init__(
        self,
        samplers: List[BaseSampler],
        client: Optional[TCPClient],
        identity: SenderIdentity,
    ) -> None:
        self._samplers = samplers
        self._client = client
        self._identity = identity
        for s in samplers:
            s.sender.set_identity(identity)
        self.ticks = 0
        self.payloads_sent = 0

    def publish(self, extra_payloads: Optional[List[Any]] = None) -> int:
        """Collect + send; returns number of payloads in the batch."""
        self.ticks += 1
        batch: List[Any] = []
        for s in self._samplers:
            try:
                s.writer.flush()
                payload = s.sender.collect_payload()
                if payload is not None:
                    batch.append(payload)
            except Exception as exc:
                get_error_log().warning(
                    f"collect failed for sampler {s.name}", exc
                )
        if extra_payloads:
            batch.extend(extra_payloads)
        if batch and self._client is not None:
            if self._client.send_batch(batch):
                self.payloads_sent += len(batch)
        return len(batch)
