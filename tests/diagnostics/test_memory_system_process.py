from traceml_tpu.diagnostics.process.api import diagnose as diagnose_process
from traceml_tpu.diagnostics.step_memory.api import diagnose_rank_rows as diagnose_memory
from traceml_tpu.diagnostics.step_memory.policy import StepMemoryPolicy
from traceml_tpu.diagnostics.system.api import diagnose as diagnose_system

GiB = 1024**3


def _mem_row(step, cur, limit=16 * GiB, dev=0):
    return {
        "step": step,
        "device_id": dev,
        "current_bytes": cur,
        "step_peak_bytes": cur,
        "limit_bytes": limit,
    }


def test_memory_healthy():
    rows = {0: [_mem_row(s, 4 * GiB) for s in range(100)]}
    result = diagnose_memory(rows)
    assert result.healthy
    assert result.diagnosis.kind == "HEALTHY"


def test_memory_high_pressure():
    rows = {0: [_mem_row(s, int(15.8 * GiB)) for s in range(100)]}
    result = diagnose_memory(rows)
    assert result.diagnosis.kind == "HIGH_MEMORY_PRESSURE"
    assert result.diagnosis.severity == "critical"  # 98.75%


def test_memory_imbalance_requires_pressure():
    # big skew but low absolute pressure → no issue
    rows = {
        0: [_mem_row(s, 1 * GiB) for s in range(50)],
        1: [_mem_row(s, 2 * GiB) for s in range(50)],
    }
    assert diagnose_memory(rows).healthy
    # same skew with pressure → fires
    rows = {
        0: [_mem_row(s, 9 * GiB) for s in range(50)],
        1: [_mem_row(s, 14 * GiB) for s in range(50)],
    }
    result = diagnose_memory(rows)
    assert result.diagnosis.kind == "MEMORY_IMBALANCE"
    assert result.diagnosis.ranks == [1]


def test_memory_creep_confirmed():
    policy = StepMemoryPolicy(creep_min_steps=90)  # shrink for test speed
    rows = {0: []}
    base = 4 * GiB
    for s in range(900):
        rows[0].append(_mem_row(s, base + s * (2 * GiB // 900)))
    result = diagnose_memory(rows, policy=policy)
    assert result.diagnosis.kind == "MEMORY_CREEP_CONFIRMED"


def test_memory_creep_not_fired_on_recovery():
    policy = StepMemoryPolicy(creep_min_steps=90)
    rows = {0: []}
    base = 4 * GiB
    for s in range(900):
        # grows then recovers (cache warmup, not a leak)
        growth = min(s, 450) * (2 * GiB // 450)
        recovery = max(0, s - 600) * (3 * GiB // 300)
        rows[0].append(_mem_row(s, base + growth - recovery))
    result = diagnose_memory(rows, policy=policy)
    assert result.diagnosis.kind != "MEMORY_CREEP_CONFIRMED"


def test_system_rules():
    host = {0: [{"cpu_pct": 97.0, "memory_used_bytes": 90 * GiB,
                 "memory_total_bytes": 100 * GiB}] * 30}
    devices = {(0, 0): [{"memory_used_bytes": int(15.7 * GiB),
                         "memory_total_bytes": 16 * GiB}]}
    result = diagnose_system(host, devices)
    kinds = {i.kind for i in result.issues}
    assert "HIGH_HOST_CPU" in kinds
    assert "HIGH_HOST_MEMORY" in kinds
    assert "HIGH_DEVICE_MEMORY" in kinds
    # worst first: critical severity leads
    assert result.diagnosis.severity == "critical"


def test_system_healthy():
    host = {0: [{"cpu_pct": 30.0, "memory_used_bytes": 20 * GiB,
                 "memory_total_bytes": 100 * GiB}] * 30}
    result = diagnose_system(host, {})
    assert result.healthy


def test_process_rules():
    procs = {0: [{"rss_bytes": 50 * 1024**3}], 1: [{"rss_bytes": 1 * GiB}]}
    devices = {
        (0, 0): [{"memory_used_bytes": 14 * GiB, "memory_peak_bytes": 14 * GiB,
                  "memory_total_bytes": 16 * GiB}],
        (1, 0): [{"memory_used_bytes": 9 * GiB, "memory_peak_bytes": 9 * GiB,
                  "memory_total_bytes": 16 * GiB}],
    }
    result = diagnose_process(procs, devices)
    kinds = {i.kind for i in result.issues}
    assert "HIGH_PROCESS_RSS" in kinds
    assert "RANK_DEVICE_MEMORY_IMBALANCE" in kinds


def test_process_overhang():
    devices = {
        (0, 0): [{"memory_used_bytes": 3 * GiB, "memory_peak_bytes": 10 * GiB,
                  "memory_total_bytes": 16 * GiB}],
    }
    result = diagnose_process({}, devices)
    assert result.diagnosis.kind == "DEVICE_MEMORY_OVERHANG"


def test_rules_never_raise_on_garbage():
    result = diagnose_memory({0: [{"weird": True}]})
    assert result.diagnosis is not None
    result = diagnose_system({0: [{}]}, {(0, 0): [{}]})
    assert result.diagnosis is not None
