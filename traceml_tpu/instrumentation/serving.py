"""Inference/serving request-lifecycle capture
(motivated by the Gemma-on-TPU lifecycle framing, arXiv:2605.25645 —
fine-tuning and serving are one lifecycle on the same hardware).

Training telemetry is regular: one step, one row.  Serving traffic is
ragged — requests arrive whenever, prefill and decode have wildly
different shapes, and a replica's health is a property of the request
*population* (TTFT percentiles, queue depth, tokens/s), not of any one
call.  This module is the capture side of that population view: five
lifecycle recorders feed one bounded queue of per-event records that
:class:`~traceml_tpu.samplers.serving_sampler.ServingSampler` folds
into per-window aggregates on every tick.

Lifecycle (all timestamps are host wall clock, one record each)::

    record_request_enqueued(req)          # arrival, enters the queue
    record_prefill_start(req, tokens)     # leaves queue, prompt tokens
    record_prefill_end(req)               # first token ready (TTFT)
    record_decode_token(req, n)           # n tokens streamed
    record_request_finished(req, ok)      # leaves the system

Every record is a flat uniform dict (plays well with the r10 columnar
producer accumulators)::

    {"ev", "req", "ts", "tokens"}

:func:`instrument_generate` wraps a generate callable so call sites need
no per-event plumbing: streaming generators get a true prefill/decode
split (first yield == TTFT), one-shot jit'd generate loops are recorded
with prefill_end at call return (TTFT == e2e — the honest reading when
the loop is opaque).  :func:`sample_kv_cache` reads KV-cache/HBM
headroom from JAX live-array accounting, fail-open.

Kill switch: ``TRACEML_SERVING=0`` turns every entry point into a no-op
(and unregisters the sampler — see runtime/sampler_registry.py).
"""

from __future__ import annotations

import functools
import itertools
import threading
import time
from typing import Any, Callable, Dict, Optional

from traceml_tpu.config import flags
from traceml_tpu.utils.error_log import get_error_log
from traceml_tpu.utils.timing import BoundedDropQueue

# canonical event vocabulary — pinned by tests/samplers/test_serving_sampler.py
EV_ENQUEUED = "enq"
EV_PREFILL_START = "prefill_start"
EV_PREFILL_END = "prefill_end"
EV_DECODE = "decode"
EV_FINISHED = "finish"

EV_KINDS = (
    EV_ENQUEUED,
    EV_PREFILL_START,
    EV_PREFILL_END,
    EV_DECODE,
    EV_FINISHED,
)


def serving_enabled() -> bool:
    return flags.SERVING.enabled()


# Global queue shared by the recorders and ServingSampler.  Capacity is
# per-event (a request is ~4 + tokens/batch events), so the default 8192
# absorbs a deep burst before the drop counter starts ticking.
GLOBAL_SERVING_QUEUE = BoundedDropQueue(
    "serving", maxsize=flags.SERVING_QUEUE_MAX.get_int(8192)
)


def _record(ev: str, request_id: Any, tokens: int, ts: Optional[float]) -> bool:
    """Build + enqueue one lifecycle record.  Never raises; returns
    whether the record was enqueued (False: disabled or queue full)."""
    if not serving_enabled():
        return False
    try:
        rec = {
            "ev": ev,
            "req": str(request_id),
            "ts": float(ts) if ts is not None else time.time(),
            "tokens": max(0, int(tokens)),
        }
    except Exception as exc:
        get_error_log().warning("serving record failed", exc)
        return False
    return GLOBAL_SERVING_QUEUE.put(rec)


def record_request_enqueued(
    request_id: Any, ts: Optional[float] = None
) -> bool:
    """A request arrived and is waiting for a prefill slot."""
    return _record(EV_ENQUEUED, request_id, 0, ts)


def record_prefill_start(
    request_id: Any, prompt_tokens: int = 0, ts: Optional[float] = None
) -> bool:
    """The request left the queue; ``prompt_tokens`` sizes the prefill."""
    return _record(EV_PREFILL_START, request_id, prompt_tokens, ts)


def record_prefill_end(request_id: Any, ts: Optional[float] = None) -> bool:
    """Prefill done — the first token exists.  This stamp is TTFT."""
    return _record(EV_PREFILL_END, request_id, 0, ts)


def record_decode_token(
    request_id: Any, n: int = 1, ts: Optional[float] = None
) -> bool:
    """``n`` decode tokens were produced (batch decode may emit >1)."""
    return _record(EV_DECODE, request_id, n, ts)


def record_request_finished(
    request_id: Any, ok: bool = True, ts: Optional[float] = None
) -> bool:
    """The request left the system (``ok=False``: cancelled/errored)."""
    return _record(EV_FINISHED, request_id, 1 if ok else 0, ts)


# --- KV-cache / HBM headroom from live-array accounting ---------------------

#: substrings that mark a live array as KV-cache state.  Serving stacks
#: name their cache buffers; anything unnamed still counts toward the
#: total live bytes the headroom is computed from.
_KV_NAME_HINTS = ("kv_cache", "kvcache", "cache_k", "cache_v", "k_cache", "v_cache")


def sample_kv_cache() -> Optional[Dict[str, Any]]:
    """Best-effort ``{"kv_bytes", "kv_limit_bytes", "kv_headroom"}``
    from JAX live-array accounting: total live on-device bytes (the KV
    cache dominates a serving replica's steady state), the device memory
    limit, and the remaining headroom fraction.  Returns None when no
    JAX runtime (or no addressable device) is available — the domain
    keeps working without it, rows carry ``-1`` sentinels."""
    if not serving_enabled():
        return None
    try:
        import jax

        live = 0
        kv = 0
        for arr in jax.live_arrays():
            try:
                n = int(arr.nbytes)
            except Exception:
                continue
            live += n
            name = str(getattr(arr, "_traceml_name", "") or "").lower()
            if name and any(h in name for h in _KV_NAME_HINTS):
                kv += n
        limit = 0
        for dev in jax.local_devices():
            stats = getattr(dev, "memory_stats", None)
            if stats is None:
                continue
            try:
                s = stats() or {}
            except Exception:
                continue
            limit += int(s.get("bytes_limit", 0) or 0)
        headroom = (1.0 - live / limit) if limit > 0 else -1.0
        return {
            "kv_bytes": kv if kv > 0 else live,
            "kv_limit_bytes": limit,
            "kv_headroom": headroom,
        }
    except Exception:
        return None


# --- generate-loop wrapper --------------------------------------------------

_req_counter = itertools.count(1)
_req_lock = threading.Lock()


def _next_request_id() -> str:
    with _req_lock:
        return f"gen-{next(_req_counter)}"


def _count_tokens(out: Any) -> int:
    """Best-effort decoded-token count of a generate result: trailing
    array dim (the sequence axis of a (batch, seq) output), else len()."""
    shape = getattr(out, "shape", None)
    if shape:
        try:
            return max(0, int(shape[-1]))
        except Exception:
            pass
    try:
        return max(0, len(out))
    except Exception:
        return 0


def instrument_generate(
    fn: Callable,
    *,
    prompt_tokens: Optional[Callable[..., int]] = None,
    token_count: Optional[Callable[[Any], int]] = None,
) -> Callable:
    """Wrap a generate callable so every call records a full request
    lifecycle without per-event plumbing at the call site.

    * Generator results get the true phase split: prefill_end is stamped
      at the FIRST yield (TTFT), each subsequent yield records a decode
      token, exhaustion records finished.
    * Plain results (a jit'd generate loop returning the whole sequence)
      record prefill_end at call return and the decoded tokens in one
      decode record — TTFT equals end-to-end latency, the honest reading
      when the loop is opaque to the host.

    ``prompt_tokens(*args, **kwargs)`` sizes the prefill;
    ``token_count(result)`` overrides the decoded-token estimate.
    Idempotent; fail-open — recording errors never reach user code.
    """
    if getattr(fn, "_traceml_serving_instrumented", False):
        return fn

    @functools.wraps(fn)
    def wrapped(*args: Any, **kwargs: Any):
        if not serving_enabled():
            return fn(*args, **kwargs)
        req = _next_request_id()
        try:
            n_prompt = int(prompt_tokens(*args, **kwargs)) if prompt_tokens else 0
        except Exception:
            n_prompt = 0
        record_request_enqueued(req)
        record_prefill_start(req, prompt_tokens=n_prompt)
        try:
            out = fn(*args, **kwargs)
        except Exception:
            record_request_finished(req, ok=False)
            raise
        if hasattr(out, "__next__"):
            return _wrap_stream(out, req)
        try:
            record_prefill_end(req)
            n = token_count(out) if token_count else _count_tokens(out)
            if n > 0:
                record_decode_token(req, n)
            record_request_finished(req, ok=True)
        except Exception as exc:  # never raise into user code
            get_error_log().warning("instrument_generate record failed", exc)
        return out

    wrapped._traceml_serving_instrumented = True  # type: ignore[attr-defined]
    return wrapped


def _wrap_stream(it: Any, req: str):
    """Token-stream path: first yield stamps TTFT, each yield is one
    decode token, exhaustion (or caller abandonment) finishes."""
    first = True
    ok = True
    try:
        for item in it:
            if first:
                record_prefill_end(req)
                first = False
            record_decode_token(req, 1)
            yield item
    except Exception:
        ok = False
        raise
    finally:
        if first:
            # stream died before the first token — still close the request
            record_prefill_end(req)
        record_request_finished(req, ok=ok)
