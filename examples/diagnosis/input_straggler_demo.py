"""Injected input straggler (reference demo analogue):

    traceml-tpu run --nprocs 4 examples/diagnosis/input_straggler_demo.py

Expected verdict: INPUT_STRAGGLER on the last rank.
"""

from traceml_tpu.dev.demo.scenarios import run_scenario

run_scenario("input_straggler", steps=100)
