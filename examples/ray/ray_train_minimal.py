"""Minimal TraceML-TPU + Ray Train example
(reference role: examples/ray/torchtrainer_minimal.py — the Ray
integration's actor-hosted aggregator pattern, adapted to a jax/flax
worker loop).

Ray Train spawns worker processes across the cluster; there is no
launcher to own the aggregator, so TraceML hosts it inside a NAMED RAY
ACTOR that every worker — on any node — can resolve through Ray:

    python examples/ray/ray_train_minimal.py --num-workers 2

Ray data iterators are not torch DataLoaders, so wrap the batch
iterator with ``traceml_tpu.wrap_dataloader`` to get input timing in
the Step Time summary — shown below.

The dataset is synthetic so the example runs with zero downloads; it
still exercises the real systems: Ray workers, the actor-hosted
aggregator, per-worker runtimes, and the final summary.
"""

from __future__ import annotations

import argparse


def train_loop_per_worker(config: dict) -> None:
    """The per-worker loop Ray runs; TraceML wraps it (see main)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import traceml_tpu

    rng = np.random.default_rng(0)

    def batches(n: int):
        for _ in range(n):
            yield (
                rng.normal(size=(32, 128)).astype(np.float32),
                rng.integers(0, 10, size=(32,)),
            )

    w = jnp.zeros((128, 10))
    opt = optax.adamw(1e-3)
    opt_state = opt.init(w)

    @jax.jit
    def step(w, opt_state, x, y):
        def loss_fn(w):
            logits = x @ w
            onehot = jax.nn.one_hot(y, 10)
            return -jnp.mean(jnp.sum(onehot * jax.nn.log_softmax(logits), -1))

        loss, grads = jax.value_and_grad(loss_fn)(w)
        updates, opt_state = opt.update(grads, opt_state, w)
        return optax.apply_updates(w, updates), opt_state, loss

    # wrap_dataloader: Ray iterators aren't torch DataLoaders, so input
    # timing must be requested explicitly
    for x, y in traceml_tpu.wrap_dataloader(batches(config["steps"])):
        with traceml_tpu.trace_step():
            x, y = jax.device_put(x), jax.device_put(y)
            w, opt_state, loss = step(w, opt_state, x, y)
    print(f"final loss {float(loss):.4f}")


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--num-workers", type=int, default=2)
    parser.add_argument("--steps", type=int, default=60)
    parser.add_argument("--ray-address", type=str, default=None,
                        help="e.g. auto (defaults to a local cluster)")
    args = parser.parse_args()

    # imports AFTER argparse so --help works on machines without ray
    import ray
    from ray.train import ScalingConfig
    from ray.train.torch import TorchTrainer

    from traceml_tpu.integrations.ray import traceml_train_loop

    ray.init(address=args.ray_address)
    trainer = TorchTrainer(
        traceml_train_loop(train_loop_per_worker),
        train_loop_config={"steps": args.steps},
        scaling_config=ScalingConfig(num_workers=args.num_workers),
    )
    trainer.fit()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
