"""Shared machinery for the fake Lightning packages (both layouts —
``lightning.pytorch`` and ``pytorch_lightning``; VERDICT r2 item 8).

Each layout gets its OWN ``Callback`` base class (so the dual-base
construction in traceml's integration is observable) and a ``Trainer``
that drives a REAL torch model through Lightning's automatic-
optimization hook order, including the trap traceml's callback must
survive: ``on_before_zero_grad`` fires BEFORE backward while the
forward region is still open.

Hook order reproduced (lightning.pytorch.loops automatic optimization):
    on_train_batch_start → [training_step] → on_before_zero_grad →
    zero_grad → on_before_backward → backward → on_after_backward →
    on_before_optimizer_step → step → on_train_batch_end
"""

from typing import Any, List, Optional

_HOOKS = (
    "setup", "teardown",
    "on_train_batch_start", "on_before_zero_grad", "on_before_backward",
    "on_after_backward", "on_before_optimizer_step", "on_train_batch_end",
    "on_train_end",
)


def make_layout(layout_name: str):
    """Fresh (Callback, Trainer, LightningModule) triple for one layout."""
    import torch

    class LightningModule(torch.nn.Module):
        """Real-API subset: user modules override ``training_step`` and
        ``configure_optimizers`` (lightning.pytorch.core.module); the
        fake Trainer drives those when present."""

        def training_step(self, batch, batch_idx):  # pragma: no cover
            raise NotImplementedError

        def configure_optimizers(self):  # pragma: no cover
            raise NotImplementedError

    class Callback:
        _fake_lightning_layout = layout_name

        def setup(self, trainer, pl_module, stage=None):
            pass

        def teardown(self, trainer, pl_module, stage=None):
            pass

        def on_train_batch_start(self, trainer, pl_module, batch, batch_idx):
            pass

        def on_before_zero_grad(self, trainer, pl_module, optimizer):
            pass

        def on_before_backward(self, trainer, pl_module, loss):
            pass

        def on_after_backward(self, trainer, pl_module):
            pass

        def on_before_optimizer_step(self, trainer, pl_module, optimizer):
            pass

        def on_train_batch_end(
            self, trainer, pl_module, outputs, batch, batch_idx
        ):
            pass

        def on_train_end(self, trainer, pl_module):
            pass

    class Trainer:
        _fake_lightning_layout = layout_name

        def __init__(
            self,
            callbacks: Optional[List[Any]] = None,
            max_steps: int = -1,  # real Lightning's "unset" sentinel
            max_epochs: Optional[int] = None,
            num_sanity_val_steps: int = 2,
            enable_checkpointing: bool = True,
            logger: Any = None,
        ) -> None:
            self.callbacks = list(callbacks or [])
            self.max_epochs = max_epochs
            if max_steps == -1:
                # unset: epochs bound the run when given, else the
                # legacy fake default of 10 steps
                self.max_steps = 10**9 if max_epochs is not None else 10
            else:
                self.max_steps = int(max_steps)
            self.num_sanity_val_steps = int(num_sanity_val_steps)
            self.enable_checkpointing = enable_checkpointing
            self.logger = logger
            self.sanity_checking = False

        def _hook(self, name: str, *args: Any, **kwargs: Any) -> None:
            for cb in self.callbacks:
                getattr(cb, name)(*args, **kwargs)

        def fit(self, model, train_dataloader) -> None:
            import torch

            self._hook("setup", self, model, stage="fit")
            if isinstance(model, LightningModule):
                optimizer = model.configure_optimizers()
            else:
                optimizer = torch.optim.SGD(model.parameters(), lr=0.01)
            batches = iter(train_dataloader)

            # sanity-check pass: hooks fire with sanity_checking=True and
            # must produce NO timed rows
            self.sanity_checking = True
            for idx in range(self.num_sanity_val_steps):
                try:
                    batch = next(batches)
                except StopIteration:
                    break
                self._hook("on_train_batch_start", self, model, batch, idx)
                self._hook(
                    "on_train_batch_end", self, model, None, batch, idx
                )
            self.sanity_checking = False

            def _train_one(batch, idx) -> None:
                self._hook("on_train_batch_start", self, model, batch, idx)
                if isinstance(model, LightningModule):
                    loss = model.training_step(batch, idx)
                else:
                    loss = model(batch).pow(2).mean()  # "training_step"
                self._hook("on_before_zero_grad", self, model, optimizer)
                optimizer.zero_grad()
                self._hook("on_before_backward", self, model, loss)
                loss.backward()
                self._hook("on_after_backward", self, model)
                self._hook("on_before_optimizer_step", self, model, optimizer)
                optimizer.step()
                self._hook(
                    "on_train_batch_end", self, model, loss.detach(), batch, idx
                )

            done = 0
            for epoch in range(self.max_epochs or 1):
                it = batches if epoch == 0 else iter(train_dataloader)
                for idx, batch in enumerate(it):
                    if done >= self.max_steps:
                        break
                    _train_one(batch, idx)
                    done += 1
            self._hook("on_train_end", self, model)
            self._hook("teardown", self, model, stage="fit")

    return Callback, Trainer, LightningModule
