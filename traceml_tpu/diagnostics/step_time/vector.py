"""Vectorized gate arm for the step_time diagnosis pack.

``gate(window)`` is the single decision point: it returns the window's
column engine (``window.col``) when the vectorized arm may run —
``TRACEML_VECTOR_DIAGNOSIS`` enabled and the window actually carries a
cube — and ``None`` otherwise, which forces the scalar golden-reference
arm in ``rules.py``.  Every helper here is a bit-identical numpy
transcription of the scalar loop it replaces (same ``np.median`` ==
``statistics.median`` midpoint for float64, same first-max tie-breaks,
results cast back to native ``float`` before they land in evidence
dicts), so the two arms emit byte-identical ``DiagnosticIssue`` lists —
pinned by tests/diagnostics/test_vector_parity.py.

A helper that cannot reproduce the scalar loop exactly (shape surprise,
missing column) returns ``None`` and counts a fallback via
``note_vector_fallback`` instead of logging per tick (the r09
shed-warning pattern); the caller reruns the scalar arm.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from traceml_tpu.utils.columnar import (
    KEY_INDEX,
    note_vector_fallback,
    vector_diagnosis_enabled,
)

DOMAIN = "step_time"


def gate(window):
    """The vectorized-arm gate: ``window.col`` when the flag is on and
    the window is cube-backed, else ``None`` (scalar reference arm)."""
    if not vector_diagnosis_enabled():
        return None
    return getattr(window, "col", None)


def component_deltas(
    col,
    stat_name: str,
    keys: List[str],
    sync_phase: Optional[str],
    clean_sync: Dict[int, float],
    worst_rank: int,
) -> Optional[Dict[str, float]]:
    """Cube-native form of the CleanStragglerRule component-attribution
    loop: per-phase delta of the worst rank vs the cross-rank median,
    read straight from the (R, 11) per-rank statistic matrix instead of
    materializing every rank's ``RankWindow`` (the pre-r20 warm-tick
    hot spot at fleet scale)."""
    try:
        stats = col.medians if stat_name == "medians" else col.averages
        ranks = col.ranks
        widx = ranks.index(worst_rank)
        deltas: Dict[str, float] = {}
        for key in keys:
            if key == sync_phase:
                # the sync phase reads its CLEAN form, already computed
                # (native floats, in ranks order) by _clean_math
                vals = np.asarray(
                    [clean_sync[r] for r in ranks], dtype=np.float64
                )
                worst_v = clean_sync[worst_rank]
            else:
                vals = stats[:, KEY_INDEX[key]]
                worst_v = float(vals[widx])
            deltas[key] = max(0.0, worst_v - float(np.median(vals)))
        return deltas
    except Exception:
        note_vector_fallback(DOMAIN)
        return None
