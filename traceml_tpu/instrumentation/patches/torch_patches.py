"""Torch-path auto-timers (forward / backward / optimizer)
(reference: src/traceml_ai/instrumentation/patches/forward_auto_timer_patch.py:33-106,
backward_auto_timer_patch.py:26-104, hooks/optimizer_hooks.py:17-101).

The torch path exists for torch-xla jobs on TPU and for CPU smoke runs;
CUDA never enters the picture.  Timers are host-clock; on torch-xla the
step is lazily executed so the ``step_time`` envelope (plus xm.mark_step
boundaries) carries the device truth — phase times are dispatch-side,
matching how torch-xla jobs are actually diagnosed.

Gating mirrors the reference: TLS in-step flag, outermost-only depth
counters, optional target-model filter with DDP/FSDP unwrap.
"""

from __future__ import annotations

import threading
from typing import Any

from traceml_tpu.sdk.state import get_state
from traceml_tpu.utils.error_log import get_error_log
from traceml_tpu.utils.timing import (
    BACKWARD_TIME,
    FORWARD_TIME,
    OPTIMIZER_STEP,
    timed_region,
)

_lock = threading.Lock()
_originals: dict = {}
_traced_model_ids: set = set()


def set_traced_model(model: Any) -> None:
    """Restrict forward timing to one model (reference targets the traced
    model id + its DDP ``.module`` / FSDP ``_fsdp_wrapped_module``)."""
    ids = {id(model)}
    for attr in ("module", "_fsdp_wrapped_module"):
        inner = getattr(model, attr, None)
        if inner is not None:
            ids.add(id(inner))
    _traced_model_ids.update(ids)


def clear_traced_models() -> None:
    _traced_model_ids.clear()


def _is_target(module: Any) -> bool:
    return not _traced_model_ids or id(module) in _traced_model_ids


def patch_torch_forward() -> bool:
    try:
        import torch.nn as nn
    except Exception:
        return False
    with _lock:
        if "forward" in _originals:
            return True
        original = nn.Module.__call__

        def patched_call(self, *args, **kwargs):  # noqa: ANN001
            st = get_state()
            if (
                not st.tls.in_step
                or st.tls.forward_depth > 0
                or not _is_target(self)
            ):
                return original(self, *args, **kwargs)
            st.tls.forward_depth += 1
            try:
                with timed_region(
                    FORWARD_TIME, st.current_step, sink=st.buffer.add
                ):
                    return original(self, *args, **kwargs)
            finally:
                st.tls.forward_depth -= 1

        nn.Module.__call__ = patched_call
        _originals["forward"] = original
    return True


def patch_torch_backward() -> bool:
    try:
        import torch
    except Exception:
        return False
    with _lock:
        if "backward" in _originals:
            return True
        orig_tensor_bwd = torch.Tensor.backward
        orig_autograd_bwd = torch.autograd.backward

        def _timed(fn, *args, **kwargs):  # noqa: ANN001
            st = get_state()
            if not st.tls.in_step or st.tls.backward_depth > 0:
                return fn(*args, **kwargs)
            st.tls.backward_depth += 1
            try:
                with timed_region(
                    BACKWARD_TIME, st.current_step, sink=st.buffer.add
                ):
                    return fn(*args, **kwargs)
            finally:
                st.tls.backward_depth -= 1

        def patched_tensor_backward(self, *args, **kwargs):  # noqa: ANN001
            return _timed(orig_tensor_bwd, self, *args, **kwargs)

        def patched_autograd_backward(*args, **kwargs):  # noqa: ANN001
            return _timed(orig_autograd_bwd, *args, **kwargs)

        torch.Tensor.backward = patched_tensor_backward
        torch.autograd.backward = patched_autograd_backward
        _originals["backward"] = (orig_tensor_bwd, orig_autograd_bwd)
    return True


def install_torch_optimizer_hooks() -> bool:
    """Global pre/post optimizer-step hooks emitting ``optimizer_step``
    (reference: optimizer_hooks.py:17-101).  Idempotent."""
    try:
        import torch.optim as optim
    except Exception:
        return False
    with _lock:
        if "optimizer" in _originals:
            return True
        open_regions: dict = {}

        def pre_hook(optimizer, args, kwargs):  # noqa: ANN001
            st = get_state()
            try:
                if not st.tls.in_step:
                    return
                region = timed_region(
                    OPTIMIZER_STEP, st.current_step, sink=st.buffer.add
                )
                region.__enter__()
                open_regions[id(optimizer)] = region
            except Exception as exc:
                get_error_log().warning("optimizer pre-hook failed", exc)

        def post_hook(optimizer, args, kwargs):  # noqa: ANN001
            try:
                region = open_regions.pop(id(optimizer), None)
                if region is not None:
                    region.__exit__(None, None, None)
            except Exception as exc:
                get_error_log().warning("optimizer post-hook failed", exc)

        try:
            # global hooks live as module-level functions
            # (torch.optim.optimizer.register_optimizer_step_pre_hook)
            from torch.optim.optimizer import (
                register_optimizer_step_post_hook,
                register_optimizer_step_pre_hook,
            )

            h1 = register_optimizer_step_pre_hook(pre_hook)
            h2 = register_optimizer_step_post_hook(post_hook)
        except (AttributeError, ImportError):
            return False
        _originals["optimizer"] = (h1, h2)
    return True


def unpatch_all_torch() -> None:
    with _lock:
        try:
            import torch
            import torch.nn as nn

            if "forward" in _originals:
                nn.Module.__call__ = _originals.pop("forward")
            if "backward" in _originals:
                t_bwd, a_bwd = _originals.pop("backward")
                torch.Tensor.backward = t_bwd
                torch.autograd.backward = a_bwd
            if "optimizer" in _originals:
                h1, h2 = _originals.pop("optimizer")
                h1.remove()
                h2.remove()
        except Exception:
            _originals.clear()
    clear_traced_models()
