"""Window compute cost: scalar reference builder vs columnar engine.

Isolates the pure window math from SQLite/transport (bench_live_tick
measures the whole tick): per-rank rows are preloaded into both
representations, then each engine builds the aligned cross-rank window
from scratch.  The columnar engine must produce a payload
``window_to_plain``-identical to the scalar reference at every size —
speed means nothing if the numbers moved.

Emits bench_common JSON lines (collected into BENCH_LOCAL_* records):

* ``scalar_build`` / ``columnar_build``: best-of build latency, ms;
* ``speedup``: scalar / columnar;
* ``columnar_incr``: append one step per rank + rebuild, the live
  warm-tick shape.

Round 19 adds the incremental-cache arms (``_run_incr_case``): a
persistent :class:`StepTimeWindowCache` is primed cold, then timed on
warm steady-state ticks (one new step per rank between builds) against
the from-scratch rebuild it replaces.  Golden first, again: every warm
tick's decoded payload must equal a from-scratch build's, and the cache
stats must show every timed tick actually took the delta path.

* ``incr_warm_tick``: median warm incremental tick, ms;
* ``full_rebuild``: best-of from-scratch columnar build at the same
  size, ms;
* ``incr_speedup``: full_rebuild / incr_warm_tick.
"""

import statistics
import sys
import time
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).parent))
import bench_common  # noqa: E402

from traceml_tpu.utils import timing as T  # noqa: E402
from traceml_tpu.utils.columnar import (  # noqa: E402
    StepTimeColumns,
    StepTimeWindowCache,
    build_columnar_step_time_window,
    window_to_plain,
)
from traceml_tpu.utils.step_time_window import (  # noqa: E402
    build_step_time_window,
)

pytestmark = pytest.mark.slow

BENCH = "window_compute"
STEPS = 120


def _step_row(rank, step):
    base = 50.0 + (step % 7) * 0.5 + (rank % 5) * 0.3
    return {
        "step": step,
        "timestamp": float(step),
        "clock": "device",
        "late_markers": 0,
        "events": {
            T.STEP_TIME: {"cpu_ms": base, "device_ms": base, "count": 1},
            T.COMPUTE_TIME: {
                "cpu_ms": 1.0, "device_ms": base * 0.8, "count": 1,
            },
            T.DATALOADER_NEXT: {
                "cpu_ms": base * 0.1, "device_ms": 0.0, "count": 1,
            },
        },
    }


def _best_of(fn, reps):
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best * 1000.0


def _run_case(ranks, steps=STEPS):
    rank_rows = {
        r: [_step_row(r, s) for s in range(1, steps + 1)] for r in range(ranks)
    }
    cols = {}
    for r, rows in rank_rows.items():
        c = StepTimeColumns(steps + 16)
        for row in rows:
            c.append(row)
        cols[r] = c

    # golden first: equal payloads or the timings are meaningless
    scalar = build_step_time_window(rank_rows, max_steps=steps)
    columnar = build_columnar_step_time_window(cols, steps)
    assert window_to_plain(scalar) == window_to_plain(columnar)

    scalar_ms = _best_of(
        lambda: build_step_time_window(rank_rows, max_steps=steps), 3
    )
    columnar_ms = _best_of(
        lambda: build_columnar_step_time_window(cols, steps), 5
    )

    # live warm-tick shape: one appended step per rank, then a rebuild
    incr = []
    next_step = steps + 1
    for _ in range(5):
        for r in range(ranks):
            row = _step_row(r, next_step)
            rank_rows[r].append(row)
            cols[r].append(row)
        t0 = time.perf_counter()
        w = build_columnar_step_time_window(cols, steps)
        incr.append((time.perf_counter() - t0) * 1000.0)
        assert w.steps[-1] == next_step
        next_step += 1
    incr_ms = statistics.median(incr)

    extra = {"ranks": ranks, "steps": steps}
    bench_common.emit(BENCH, "scalar_build", scalar_ms, "ms", **extra)
    bench_common.emit(BENCH, "columnar_build", columnar_ms, "ms", **extra)
    bench_common.emit(BENCH, "columnar_incr", incr_ms, "ms", **extra)
    bench_common.emit(
        BENCH, "speedup", scalar_ms / max(columnar_ms, 1e-6), "x", **extra
    )
    return scalar_ms, columnar_ms, incr_ms


@pytest.mark.parametrize("ranks", [64, 256])
def test_window_compute_bench(ranks):
    scalar_ms, columnar_ms, _ = _run_case(ranks)
    if ranks == 256:
        # the engine must not merely match the scalar path — it must
        # leave it far behind (ISSUE 3 acceptance: ≥5× on the warm tick)
        assert scalar_ms / columnar_ms >= 5.0, (scalar_ms, columnar_ms)


INCR_STEPS = 240

#: memoized (incr_ms, full_ms) per rank count — the 1024-rank case is
#: expensive to set up, and both the gate test and the scaling test
#: need it
_incr_results = {}


def _run_incr_case(ranks, steps=INCR_STEPS):
    if ranks in _incr_results:
        return _incr_results[ranks]
    cols = {}
    for r in range(ranks):
        c = StepTimeColumns(steps + 32)
        for s in range(1, steps + 1):
            c.append(_step_row(r, s))
        cols[r] = c
    cache = StepTimeWindowCache()
    cache.build(cols, steps)  # cold tick primes the cache (full build)
    next_step = steps + 1

    # golden first: every warm tick's decoded payload must equal a
    # from-scratch rebuild's, or the timings below are meaningless
    for _ in range(3):
        for r in range(ranks):
            cols[r].append(_step_row(r, next_step))
        incr_w = cache.build(cols, steps)
        full_w = build_columnar_step_time_window(cols, steps)
        assert window_to_plain(incr_w) == window_to_plain(full_w)
        assert incr_w.steps[-1] == next_step
        next_step += 1

    # warm steady-state tick: one new step per rank between builds
    ticks = []
    for _ in range(7):
        for r in range(ranks):
            cols[r].append(_step_row(r, next_step))
        t0 = time.perf_counter()
        w = cache.build(cols, steps)
        ticks.append((time.perf_counter() - t0) * 1000.0)
        assert w.steps[-1] == next_step
        next_step += 1
    incr_ms = statistics.median(ticks)
    stats = cache.stats.snapshot()
    # every timed tick must actually have taken the delta path — a
    # silent invalidation would time full rebuilds and call them ticks
    assert stats["full_rebuilds"] == 1, stats
    assert stats["last_path"] == "incremental", stats

    full_ms = _best_of(
        lambda: build_columnar_step_time_window(cols, steps), 3
    )

    extra = {"ranks": ranks, "steps": steps}
    bench_common.emit(BENCH, "incr_warm_tick", incr_ms, "ms", **extra)
    bench_common.emit(BENCH, "full_rebuild", full_ms, "ms", **extra)
    bench_common.emit(
        BENCH, "incr_speedup", full_ms / max(incr_ms, 1e-6), "x", **extra
    )
    _incr_results[ranks] = (incr_ms, full_ms)
    return incr_ms, full_ms


@pytest.mark.parametrize("ranks", [256, 1024])
def test_incremental_tick_bench(ranks):
    incr_ms, full_ms = _run_incr_case(ranks)
    if ranks == 1024:
        # ISSUE 19 acceptance: the warm steady-state tick beats the
        # full rebuild it replaces by ≥5× at 1024 ranks × 240 steps
        assert full_ms / incr_ms >= 5.0, (incr_ms, full_ms)
    if ranks == 256:
        # and the 256-rank warm tick stays inside the r08 30 ms
        # live-tick envelope
        assert incr_ms <= 30.0, incr_ms


def test_incr_scaling_1024():
    """4× the ranks may cost ~4× the tick (the scan is O(ranks)) but
    never much more: super-linear growth would mean a hidden rebuild or
    a realignment leak on the warm path."""
    incr_256, _ = _run_incr_case(256)
    incr_1024, full_1024 = _run_incr_case(1024)
    assert incr_1024 / incr_256 <= 8.0, (incr_256, incr_1024)
    assert full_1024 / incr_1024 >= 5.0, (incr_1024, full_1024)


if __name__ == "__main__":
    for ranks in (64, 256):
        _run_case(ranks)
    for ranks in (256, 1024):
        _run_incr_case(ranks)
