"""Multi-rank summary pipeline over an injected SQLite DB
(reference trick: tests/reporting/summary/test_fixtures.py:20-31 —
multi-rank = data shape, not processes)."""

import json

from traceml_tpu.aggregator.sqlite_writer import SQLiteWriter
from traceml_tpu.reporting.final import generate_summary
from traceml_tpu.runtime.settings import TraceMLSettings
from traceml_tpu.telemetry.envelope import SenderIdentity, build_telemetry_envelope
from traceml_tpu.utils import timing as T


def _step_row(step, step_ms, input_ms, compute_ms):
    events = {
        T.STEP_TIME: {"cpu_ms": step_ms, "device_ms": step_ms, "count": 1},
        T.DATALOADER_NEXT: {"cpu_ms": input_ms, "device_ms": None, "count": 1},
        T.COMPUTE_TIME: {"cpu_ms": 0.5, "device_ms": compute_ms, "count": 1},
    }
    return {"step": step, "timestamp": float(step), "clock": "device", "events": events}


def _inject(db_path, n_ranks=2, n_steps=60, straggler_rank=None):
    w = SQLiteWriter(db_path)
    w.start()
    for rank in range(n_ranks):
        ident = SenderIdentity(
            session_id="s1", global_rank=rank, world_size=n_ranks,
            node_rank=rank // 4, hostname=f"host{rank // 4}", pid=100 + rank,
        )
        rows = []
        for step in range(1, n_steps + 1):
            if rank == straggler_rank:
                rows.append(_step_row(step, 300.0, 204.0, 90.0))
            else:
                rows.append(_step_row(step, 100.0, 4.0, 90.0))
        w.ingest(build_telemetry_envelope("step_time", {"step_time": rows}, ident))
        mem_rows = [
            {"step": s, "timestamp": float(s), "device_id": 0,
             "device_kind": "tpu", "current_bytes": 4 << 30,
             "peak_bytes": 5 << 30, "step_peak_bytes": 5 << 30,
             "limit_bytes": 16 << 30, "backend": "fake"}
            for s in range(1, n_steps + 1)
        ]
        w.ingest(build_telemetry_envelope("step_memory", {"step_memory": mem_rows}, ident))
    w.force_flush()
    w.finalize()


def test_summary_healthy_two_ranks(tmp_path):
    db = tmp_path / "telemetry.sqlite"
    _inject(db, n_ranks=2)
    settings = TraceMLSettings(session_id="s1", logs_dir=tmp_path, mode="summary")
    assert generate_summary(db, tmp_path, settings)
    payload = json.loads((tmp_path / "final_summary.json").read_text())
    assert payload["schema"].startswith("traceml-tpu/")
    assert payload["meta"]["topology"]["world_size"] == 2
    assert sorted(payload["meta"]["topology"]["ranks_seen"]) == [0, 1]
    st = payload["sections"]["step_time"]
    assert st["status"] == "OK"
    assert st["global"]["clock"] == "device"
    assert st["global"]["n_steps"] == 60
    assert payload["primary_diagnosis"]["kind"] == "COMPUTE_BOUND"
    txt = (tmp_path / "final_summary.txt").read_text()
    assert "VERDICT" in txt
    assert "COMPUTE_BOUND" in txt


def test_summary_input_straggler_detected(tmp_path):
    db = tmp_path / "telemetry.sqlite"
    _inject(db, n_ranks=4, straggler_rank=2)
    settings = TraceMLSettings(session_id="s1", logs_dir=tmp_path, mode="summary")
    assert generate_summary(db, tmp_path, settings)
    payload = json.loads((tmp_path / "final_summary.json").read_text())
    primary = payload["primary_diagnosis"]
    assert primary["kind"] == "INPUT_STRAGGLER"
    assert primary["ranks"] == [2]
    assert "rank 2" in primary["summary"].lower()


def test_summary_section_depth_fields(tmp_path):
    """The per-rank cards, occupancy, steady-state, and rollups the
    round-2 section build-out added (SCHEMA.md)."""
    db = tmp_path / "telemetry.sqlite"
    _inject(db, n_ranks=2)
    settings = TraceMLSettings(session_id="s1", logs_dir=tmp_path, mode="summary")
    assert generate_summary(db, tmp_path, settings)
    payload = json.loads((tmp_path / "final_summary.json").read_text())

    g = payload["sections"]["step_time"]["global"]
    # occupancy = Σ phase device (compute 90) / host step (100) = 0.9
    assert g["median_occupancy"] == 0.9
    assert g["occupancy_by_rank"]["0"] == 0.9
    # steady-state split present for a 60-step window
    steady = g["steady_state"]
    assert steady["warmup_steps_excluded"] == 15
    assert steady["median_ms"] == 100.0
    # per-rank cards carry phase averages + occupancy
    card = g["per_rank"]["1"]
    assert card["steps_seen"] == 60
    assert card["occupancy"] == 0.9
    assert card["avg_ms"]["step_time"] == 100.0

    sm = payload["sections"]["step_memory"]["global"]
    rank0 = sm["per_rank"]["0"]
    assert rank0["pressure"] == (5 << 30) / (16 << 30)
    assert rank0["growth_bytes"] == 0
    assert sm["rollup"]["max_peak_bytes"] == 5 << 30
    assert sm["rollup"]["total_current_bytes"] == 2 * (4 << 30)

    # text render surfaces the new aggregates
    text = (tmp_path / "final_summary.txt").read_text()
    assert "chip busy 90.0%" in text
    assert "steady-state median" in text
    assert "pressure" in text


def test_summary_no_db(tmp_path):
    settings = TraceMLSettings(session_id="s1", logs_dir=tmp_path, mode="summary")
    assert generate_summary(tmp_path / "missing.sqlite", tmp_path, settings)
    payload = json.loads((tmp_path / "final_summary.json").read_text())
    assert payload["sections"]["step_time"]["status"] == "NO_DATA"


def test_summary_sections_degrade_independently(tmp_path):
    db = tmp_path / "telemetry.sqlite"
    _inject(db, n_ranks=1)
    settings = TraceMLSettings(session_id="s1", logs_dir=tmp_path, mode="summary")
    assert generate_summary(db, tmp_path, settings)
    payload = json.loads((tmp_path / "final_summary.json").read_text())
    # no system/process telemetry injected → NO_DATA, but step_time OK
    assert payload["sections"]["system"]["status"] == "NO_DATA"
    assert payload["sections"]["process"]["status"] == "NO_DATA"
    assert payload["sections"]["step_time"]["status"] == "OK"
