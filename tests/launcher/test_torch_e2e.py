"""Torch-path E2E: the BASELINE.json `pytorch_minimal.py` config —
a tiny torch-CPU MLP through the full CLI with auto patches
(dataloader / forward / backward / optimizer phase split)."""

import json
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[2]

TORCH_SCRIPT = """
import time
import torch
import torch.nn as nn
from torch.utils.data import DataLoader, TensorDataset

import traceml_tpu

traceml_tpu.init(mode="auto")

model = nn.Sequential(nn.Linear(64, 128), nn.Tanh(), nn.Linear(128, 1))
opt = torch.optim.Adam(model.parameters(), lr=1e-3)
loss_fn = nn.MSELoss()

xs = torch.randn(640, 64)
ys = torch.randn(640, 1)
loader = DataLoader(TensorDataset(xs, ys), batch_size=8)

for epoch in range(2):
    for x, y in loader:
        with traceml_tpu.trace_step():
            opt.zero_grad()
            loss = loss_fn(model(x), y)
            loss.backward()
            opt.step()
print("torch train done", float(loss))
"""


def test_torch_mlp_phase_split(tmp_path):
    script = tmp_path / "torch_train.py"
    script.write_text(TORCH_SCRIPT)
    logs = tmp_path / "logs"
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO)
    proc = subprocess.run(
        [
            sys.executable, "-m", "traceml_tpu", "run",
            "--mode", "summary", "--logs-dir", str(logs),
            "--sampler-interval", "0.25", "--finalize-timeout", "30",
            str(script),
        ],
        env=env, capture_output=True, text=True, timeout=240, cwd=str(tmp_path),
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    session = next(p for p in logs.iterdir() if p.is_dir())
    payload = json.loads((session / "final_summary.json").read_text())
    st = payload["sections"]["step_time"]
    assert st["status"] == "OK"
    phases = st["global"]["phases"]
    # the torch path yields the classic per-phase split
    for phase in ("input", "forward", "backward", "optimizer"):
        assert phase in phases, sorted(phases)
        assert phases[phase]["median_ms"] >= 0
    # 160 steps recorded (2 epochs x 80 batches)
    assert st["global"]["n_steps"] >= 100
    # code manifest detected torch + DataLoader
    code = json.loads((session / "code_manifest.json").read_text())
    assert code["framework"] == "torch"
    assert "torch_dataloader" in code["input_hints"]
