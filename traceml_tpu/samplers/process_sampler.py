"""Process sampler — this rank's host + device footprint
(reference: src/traceml_ai/samplers/process_sampler.py:25-246).

Per tick: process CPU %, RSS, thread count, plus per-addressable-device
memory for THIS process (the reference's ``torch.cuda.memory_allocated``
analogue).  The reference's CUDA-safety gate (never touch CUDA before
``init_process_group``) maps to: never force jax backend init — only
sample devices once jax is already initialized in this process.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from traceml_tpu.samplers.base_sampler import BaseSampler

TABLE = "process"
TABLE_DEVICE = "process_device"


class ProcessSampler(BaseSampler):
    name = "process"

    def __init__(self, *args: Any, memory_backend: Any = None, **kw: Any) -> None:
        super().__init__(*args, **kw)
        self._backend_holder = {"backend": memory_backend}
        try:
            import psutil

            self._proc = psutil.Process()
            self._proc.cpu_percent(interval=None)
        except Exception:
            self._proc = None

    def _device_rows(self, ts: float) -> List[Dict[str, Any]]:
        from traceml_tpu.utils.step_memory import device_memory_rows

        return device_memory_rows(self._backend_holder, ts)

    def _sample(self) -> None:
        ts = time.time()
        if self._proc is not None:
            with self._proc.oneshot():
                mem = self._proc.memory_info()
                row = {
                    "timestamp": ts,
                    "pid": self._proc.pid,
                    "cpu_pct": self._proc.cpu_percent(interval=None),
                    "rss_bytes": mem.rss,
                    "vms_bytes": mem.vms,
                    "num_threads": self._proc.num_threads(),
                }
            self.db.add_record(TABLE, row)
        rows = self._device_rows(ts)
        if rows:
            self.db.add_records(TABLE_DEVICE, rows)
