"""Browser dashboard driver tests (VERDICT r2 item 4; reference analogue
tests/display/test_nicegui_driver.py — driver logic tested without a
browser).

Two layers:

* **payload→DOM contract** — every element id the page's JS touches
  exists in the markup, every phase key the views can emit has a chart
  color, and every ``d.<key>`` the JS reads exists in a REAL payload
  built from a REAL session DB (renderer and page can't drift apart
  silently);
* **server behavior** — /healthz readiness (wait_until_ready), the page,
  /api/live with live data, and /api/summary's 404→200 transition.
"""

import json
import re
import types
import urllib.request
from pathlib import Path

from traceml_tpu.aggregator.display_drivers.browser import (
    _PAGE,
    BrowserDisplayDriver,
    wait_until_ready,
)


def _make_session_db(tmp_path, n_ranks=2):
    from traceml_tpu.aggregator.sqlite_writer import SQLiteWriter
    from traceml_tpu.telemetry.envelope import (
        SenderIdentity,
        build_telemetry_envelope,
    )
    from traceml_tpu.utils import timing as T

    db = tmp_path / "telemetry.sqlite"
    # retention smaller than the 39 ingested steps (and zero hysteresis
    # slack) so the writer prunes — and therefore FOLDS — in-window:
    # the payload then carries the top-level `history` fragment the
    # page's JS reads, keeping the d.<key> contract check real
    w = SQLiteWriter(db, summary_window_rows=20)
    w._prune_slack = 0
    w.start()
    for rank in range(n_ranks):
        ident = SenderIdentity(
            session_id="dash", global_rank=rank, world_size=n_ranks
        )
        rows = [
            {"step": s, "timestamp": float(s), "clock": "device",
             "events": {
                 T.STEP_TIME: {"cpu_ms": 100.0 + rank * 30,
                               "device_ms": 100.0 + rank * 30, "count": 1},
                 T.DATALOADER_NEXT: {"cpu_ms": 40.0, "device_ms": None,
                                     "count": 1},
                 T.COMPUTE_TIME: {"cpu_ms": 1.0, "device_ms": 55.0,
                                  "count": 1},
             }}
            for s in range(1, 40)
        ]
        w.ingest(build_telemetry_envelope(
            "step_time", {"step_time": rows}, ident))
        w.ingest(build_telemetry_envelope("step_memory", {"step_memory": [
            {"step": 39, "timestamp": 39.0, "device_id": 0,
             "device_kind": "tpu", "current_bytes": (10 + rank) << 30,
             "peak_bytes": (10 + rank) << 30,
             "step_peak_bytes": (10 + rank) << 30,
             "limit_bytes": 16 << 30, "backend": "fake"}]}, ident))
        w.ingest(build_telemetry_envelope("process", {"process": [
            {"timestamp": 39.0, "pid": 100 + rank, "cpu_pct": 50.0 + rank,
             "rss_bytes": 1 << 30, "num_threads": 5}]}, ident))
    w.force_flush()
    w.finalize()
    return db


# -- payload→DOM contract --------------------------------------------------

def test_every_js_element_id_exists_in_markup():
    used = set(re.findall(r'getElementById\("([\w-]+)"\)', _PAGE))
    declared = set(re.findall(r'id="([\w-]+)"', _PAGE))
    missing = used - declared
    assert not missing, f"JS touches ids with no markup: {missing}"


def test_every_phase_key_has_a_chart_color():
    from traceml_tpu.utils.step_time_window import ACCOUNTED_PHASES, RESIDUAL_KEY

    m = re.search(r"const COLORS=\{(.*?)\};", _PAGE, re.S)
    assert m, "COLORS map missing from page"
    colors = set(re.findall(r"(\w+):\"", m.group(1)))
    needed = set(ACCOUNTED_PHASES) | {RESIDUAL_KEY}
    missing = needed - colors
    assert not missing, f"phases with no stack color: {missing}"


def test_js_payload_keys_exist_in_real_payload(tmp_path):
    """The page reads d.step_time.phase_stack/step_series/phases,
    d.memory.ranks[].pressure, d.process.ranks[].cpu_pct… — build a real
    payload and assert every one of those paths is present."""
    from traceml_tpu.renderers.web_payload import build_web_payload

    db = _make_session_db(tmp_path)
    d = build_web_payload(db, "dash")
    top_used = set(re.findall(r"\bd\.(\w+)", _PAGE))
    missing = top_used - set(d.keys())
    assert not missing, f"JS reads top-level payload keys that don't exist: {missing}"

    st = d["step_time"]
    for key in ("phase_stack", "step_series", "phases", "coverage",
                "n_steps", "clock", "latest_ts", "steps"):
        assert key in st, f"step_time view lost {key!r}"
    assert d["memory"]["ranks"] and "pressure" in d["memory"]["ranks"][0]
    assert d["process"]["ranks"] and "cpu_pct" in d["process"]["ranks"][0]
    assert "rss_bytes" in d["process"]["ranks"][0]


# -- server behavior -------------------------------------------------------

def _start_driver(tmp_path, db):
    ctx = types.SimpleNamespace(
        db_path=db,
        settings=types.SimpleNamespace(
            session_id="dash", session_dir=tmp_path
        ),
    )
    driver = BrowserDisplayDriver(port=0)
    driver.start(ctx)
    assert driver.port, "server failed to bind"
    return driver


def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=5
    ) as resp:
        return resp.status, resp.read()


def test_server_serves_page_live_and_summary_transition(tmp_path):
    db = _make_session_db(tmp_path)
    driver = _start_driver(tmp_path, db)
    try:
        assert wait_until_ready("127.0.0.1", driver.port, timeout=5.0)
        code, body = _get(driver.port, "/healthz")
        health = json.loads(body)
        assert code == 200 and health["ok"] and health["session"] == "dash"

        code, body = _get(driver.port, "/")
        assert code == 200 and b"TraceML-TPU" in body

        code, body = _get(driver.port, "/api/live")
        live = json.loads(body)
        assert code == 200 and live["session"] == "dash"
        assert live["step_time"]["n_steps"] > 0
        # two ranks with skewed step times: heatmap inputs present
        assert len(live["step_time"]["step_series"]) == 2

        # summary: 404 until the file exists, then served verbatim
        try:
            code, _ = _get(driver.port, "/api/summary")
        except urllib.error.HTTPError as e:
            code = e.code
        assert code == 404
        (tmp_path / "final_summary.json").write_text(
            json.dumps({"primary_diagnosis": {"kind": "INPUT_BOUND"},
                        "sections": {}, "meta": {}})
        )
        code, body = _get(driver.port, "/api/summary")
        assert code == 200
        assert json.loads(body)["primary_diagnosis"]["kind"] == "INPUT_BOUND"
    finally:
        driver.stop()
