"""step_time projection → ``step_time_samples``
(reference: aggregator/sqlite_writers/step_time.py:131-419).

One row per (rank, step): stable identity columns + ``events_json``
payload (the per-phase {cpu_ms, device_ms, count} dict from the
step-time sampler) + the selected clock.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from traceml_tpu.aggregator.sqlite_writers.common import (
    IDENTITY_SCHEMA,
    dumps,
    identity_tuple,
)
from traceml_tpu.telemetry.envelope import TelemetryEnvelope

TABLE = "step_time_samples"
MODEL_STATS_TABLE = "model_stats_samples"
# model_stats is one-row-per-change, but per-step set_step_flops calls
# (variable seq lengths) can make changes frequent — prune it like the
# sample tables so the db stays bounded (the loader reads latest-per-rank)
RETENTION_TABLES = (TABLE, MODEL_STATS_TABLE)


def accepts_sampler(name: str) -> bool:
    return name == "step_time"


def init_schema(conn) -> None:
    conn.execute(
        f"""CREATE TABLE IF NOT EXISTS {TABLE} (
            id INTEGER PRIMARY KEY AUTOINCREMENT,
            {IDENTITY_SCHEMA},
            step INTEGER,
            timestamp REAL,
            clock TEXT,
            late_markers INTEGER,
            events_json TEXT
        )"""
    )
    conn.execute(
        f"CREATE INDEX IF NOT EXISTS idx_{TABLE}_rank_step "
        f"ON {TABLE} (session_id, global_rank, step)"
    )
    conn.execute(
        f"""CREATE TABLE IF NOT EXISTS {MODEL_STATS_TABLE} (
            id INTEGER PRIMARY KEY AUTOINCREMENT,
            {IDENTITY_SCHEMA},
            timestamp REAL,
            flops_per_step REAL,
            flops_source TEXT,
            device_kind TEXT,
            peak_flops REAL,
            device_count INTEGER,
            tokens_per_step REAL
        )"""
    )


def insert_sql(table: str) -> str:
    if table == MODEL_STATS_TABLE:
        return (
            f"INSERT INTO {MODEL_STATS_TABLE} (session_id, global_rank,"
            " local_rank, world_size, local_world_size, node_rank, hostname,"
            " pid, timestamp, flops_per_step, flops_source, device_kind,"
            " peak_flops, device_count, tokens_per_step)"
            " VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?,?,?)"
        )
    return (
        f"INSERT INTO {TABLE} (session_id, global_rank, local_rank, world_size,"
        " local_world_size, node_rank, hostname, pid, step, timestamp, clock,"
        " late_markers, events_json) VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?)"
    )


def build_rows(env: TelemetryEnvelope) -> Dict[str, List[Tuple]]:
    ident = identity_tuple(env)
    tables: Dict[str, List[Tuple]] = {}
    v = env.column_view("step_time")
    if v:
        steps = v.ints("step")
        ts = v.floats("timestamp")
        clocks = v.strs("clock", "host")
        late = v.ints("late_markers")
        events = v.col("events")
        tables[TABLE] = [
            ident
            + (
                steps[i],
                ts[i],
                clocks[i],
                late[i] or 0,
                dumps(events[i] if events[i] is not None else {}),
            )
            for i in range(len(v))
        ]
    v = env.column_view("model_stats")
    if v:
        ts = v.floats("timestamp")
        flops = v.floats("flops_per_step")
        source = v.col("flops_source")
        kind = v.col("device_kind")
        peak = v.floats("peak_flops")
        count = v.ints("device_count")
        tokens = v.floats("tokens_per_step")
        tables[MODEL_STATS_TABLE] = [
            ident + (ts[i], flops[i], source[i], kind[i], peak[i], count[i], tokens[i])
            for i in range(len(v))
        ]
    return tables
