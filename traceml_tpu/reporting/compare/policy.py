"""Compare significance thresholds
(reference: src/traceml_ai/reporting/compare/policy.py:55-80 — the
conservative significance policy: small deltas are noise, not verdicts).
"""

from __future__ import annotations

import dataclasses

MiB = 1024 * 1024
GiB = 1024 * MiB


@dataclasses.dataclass(frozen=True)
class ComparePolicy:
    # step average: minor / major relative change
    step_avg_minor: float = 0.03
    step_avg_major: float = 0.08
    # phase share shift in percentage points
    phase_shift_minor_pp: float = 0.75
    phase_shift_major_pp: float = 2.0
    # memory deltas
    memory_minor_bytes: int = 256 * MiB
    memory_major_bytes: int = 1 * GiB


DEFAULT_POLICY = ComparePolicy()
