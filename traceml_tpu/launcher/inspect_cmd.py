"""``traceml-tpu inspect`` — decode per-rank msgpack backups
(reference: launcher/commands.py:580-616).

Handles both backup frame formats (see database/database_writer.py):
legacy per-row files print one JSON object per row; envelope files
(v2, ``envelopes.msgpack``) carry multiple tables per frame, so each
row is printed with a ``table`` field naming its origin.
"""

from __future__ import annotations

import json
from pathlib import Path

from traceml_tpu.database.database_writer import iter_backup_tables


def run_inspect(path: Path, limit: int = 20) -> int:
    path = Path(path)
    files = []
    if path.is_file():
        files = [path]
    elif path.is_dir():
        files = sorted(path.rglob("*.msgpack"))
    if not files:
        print(f"no .msgpack backups under {path}")
        return 1
    for f in files:
        print(f"── {f}")
        n = 0
        for table, row in iter_backup_tables(f):
            if table is None:
                print(json.dumps(row, default=str))
            else:
                print(json.dumps({"table": table, **row}, default=str))
            n += 1
            if n >= limit:
                print(f"… (showing first {limit})")
                break
    return 0
