from torch_xla.core import xla_model  # noqa: F401
