"""Federation bench: 4 aggregator shards × 64 sessions × 256 viewers
through one stateless fleet router (docs/developer_guide/federation.md).

Scenario: 64 session DBs split across 4 shard logs_dirs, one
``BrowserDisplayDriver`` per shard, one ``FleetRouter`` fronting all
four with the shared edge fragment cache.  A writer keeps advancing a
rotating subset of sessions between measurement rounds; 256 viewers
(per session: 3 on the SSE live channel, 1 delta-polling on a
keep-alive connection — the r13 serving tier's push path and its
polling fallback) all connect THROUGH the router.

Golden first: before any timing, a delta-replay viewer routed through
the fleet router (with a deliberately dropped round) must reconstruct
a payload canonically identical (``ts`` excluded) to a fresh full
``GET /api/live`` taken directly from the owning shard.

Asserted (the ISSUE 16 acceptance criteria):

* p99 version-advance → viewer-receipt staleness ≤ 250 ms on the SSE
  live channel proxied through the router;
* router overhead ≤ 10 ms p99 per hop on the edge-cache hit path;
* the edge cache makes shard upstream fetches independent of viewer
  count: fresh-content upstream fetches (status 200 — 204/304 probes
  are header exchanges) stay ≤ ~1 per session-version (slack 2×) under
  the steady polling load, and a 32-concurrent-poller burst per
  session costs the shards ≤ ~1 fresh fetch per session, not one per
  viewer.

Emits bench_common JSON lines (collected into BENCH_LOCAL_r17.json).
"""

import http.client
import json
import sys
import threading
import time
import types
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).parent))
import bench_common  # noqa: E402

from traceml_tpu.aggregator.display_drivers.browser import (  # noqa: E402
    BrowserDisplayDriver,
    wait_until_ready,
)
from traceml_tpu.aggregator.sqlite_writer import SQLiteWriter  # noqa: E402
from traceml_tpu.federation.router import FleetRouter  # noqa: E402
from traceml_tpu.renderers import serving  # noqa: E402
from traceml_tpu.utils import timing as T  # noqa: E402
from traceml_tpu.telemetry.envelope import (  # noqa: E402
    SenderIdentity,
    build_telemetry_envelope,
)

pytestmark = pytest.mark.slow

BENCH = "federation"
N_SHARDS = 4
SESSIONS_PER_SHARD = 16          # 4 × 16 = 64 sessions
SSE_PER_SESSION = 3
POLLERS_PER_SESSION = 1          # 64 × (3 + 1) = 256 viewers
N_RANKS = 2
WRITE_ROUNDS = 8
WRITES_PER_ROUND = 16            # rotating subset: every session ×2
ROUND_SPACING_S = 0.6
VIEWER_POLL_S = 0.4
CACHE_TTL_S = 0.08
BURST_VIEWERS = 32
BURST_SESSIONS = 8
STALENESS_P99_BUDGET_S = 0.250
HOP_OVERHEAD_P99_BUDGET_S = 0.010
FETCHES_PER_VERSION_SLACK = 2.0


def _rows(rank, start, n):
    return [
        {"step": s, "timestamp": float(s), "clock": "device",
         "events": {
             T.STEP_TIME: {"cpu_ms": 100.0 + (s % 9), "device_ms":
                           100.0 + (s % 9), "count": 1},
             T.DATALOADER_NEXT: {"cpu_ms": 30.0, "device_ms": None,
                                 "count": 1},
             T.COMPUTE_TIME: {"cpu_ms": 1.0, "device_ms": 60.0,
                              "count": 1},
         }}
        for s in range(start, start + n)
    ]


def _write(db, start, n=3):
    w = SQLiteWriter(db)
    w.start()
    for rank in range(N_RANKS):
        ident = SenderIdentity(
            session_id=db.parent.name, global_rank=rank,
            world_size=N_RANKS,
        )
        w.ingest(build_telemetry_envelope(
            "step_time", {"step_time": _rows(rank, start, n)}, ident))
    assert w.force_flush()
    w.finalize()


def _get(port, path, headers=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    try:
        conn.request("GET", path, headers=headers or {})
        resp = conn.getresponse()
        return resp.status, dict(resp.getheaders()), resp.read()
    finally:
        conn.close()


def _canon(payload):
    return json.dumps(
        {k: v for k, v in payload.items() if k != "ts"}, sort_keys=True
    )


def _start_shard(logs_dir, first_sid):
    # serve_max_sessions covers the WHOLE fleet, not one shard: the four
    # in-process drivers share one global publisher cache (in production
    # each shard is its own process), so a per-shard cap would evict —
    # and close — the other shards' publishers mid-stream
    ctx = types.SimpleNamespace(
        db_path=logs_dir / first_sid / "telemetry.sqlite",
        settings=types.SimpleNamespace(
            session_id=first_sid, session_dir=logs_dir / first_sid,
            logs_dir=logs_dir,
            serve_max_sessions=N_SHARDS * SESSIONS_PER_SHARD + 8,
        ),
    )
    driver = BrowserDisplayDriver(port=0)
    # frequent heartbeats are the SSE viewers' read wakeup: a client
    # socket timeout poisons http.client's response object, so the
    # stream itself must produce bytes at a steady cadence
    driver.sse_heartbeat_sec = 0.5
    driver.start(ctx)
    assert driver.port and wait_until_ready("127.0.0.1", driver.port, 5.0)
    return driver


def _replay_golden_routed(router_port, shard_port, sid, db, pub):
    """Delta replay THROUGH the router (with a dropped round) must equal
    a fresh full payload taken directly from the owning shard."""
    code, headers, body = _get(router_port, f"/api/live?session={sid}")
    assert code == 200
    state = json.loads(body)
    token = headers["X-TraceML-Token"]
    for round_i in range(3):
        _write(db, 2000 + round_i * 5)
        pub.poll(force=True)
        if round_i == 1:
            continue  # dropped round: the next delta must cover the gap
        time.sleep(CACHE_TTL_S + 0.02)  # let stale edge entries expire
        code, headers, body = _get(
            router_port, f"/api/live?session={sid}&since={token}"
        )
        token = headers.get("X-TraceML-Token", token)
        if code == 200:
            m = json.loads(body)
            for frag in m["fragments"].values():
                state.update(frag)
            token = m["token"]
    time.sleep(CACHE_TTL_S + 0.02)
    code, headers, body = _get(
        router_port, f"/api/live?session={sid}&since={token}"
    )
    if code == 200:
        for frag in json.loads(body)["fragments"].values():
            state.update(frag)
    code, _, full = _get(shard_port, f"/api/live?session={sid}")
    assert code == 200
    full_payload = json.loads(full)
    assert full_payload["session"] == sid
    assert full_payload["step_time"]["n_steps"] > 0
    assert _canon(state) == _canon(full_payload), (
        f"routed delta replay diverged from the shard's payload ({sid})"
    )


class _SSEViewer(threading.Thread):
    """One live-channel tab: holds ``/api/stream`` through the router,
    stamping receipt staleness when a fragment event's token matches a
    version-advance stamp."""

    def __init__(self, port, sid, stop_evt, token_pub_ts):
        super().__init__(daemon=True)
        self.port, self.sid = port, sid
        self.stop_evt = stop_evt
        self.token_pub_ts = token_pub_ts
        self.events = 0
        self.staleness = []
        self.errors = 0

    def run(self):
        # the timeout must exceed the heartbeat cadence: http.client
        # marks the response unreadable after ANY read timeout, so
        # heartbeats (not timeouts) are the idle-loop wakeup
        conn = http.client.HTTPConnection(
            "127.0.0.1", self.port, timeout=5.0
        )
        try:
            conn.request("GET", f"/api/stream?session={self.sid}")
            resp = conn.getresponse()
            if resp.status != 200:
                self.errors += 1
                return
            event_id = None
            is_fragment = False
            while not self.stop_evt.is_set():
                try:
                    line = resp.fp.readline()
                except OSError:
                    break
                if not line:
                    break
                line = line.strip()
                if line.startswith(b"id:"):
                    event_id = line[3:].strip().decode()
                elif line == b"event: fragment":
                    is_fragment = True
                elif not line:  # dispatch boundary
                    if is_fragment and event_id:
                        self.events += 1
                        pub_ts = self.token_pub_ts.get(
                            (self.sid, event_id)
                        )
                        if pub_ts is not None:
                            self.staleness.append(
                                time.monotonic() - pub_ts
                            )
                    is_fragment = False
        except OSError:
            self.errors += 1
        finally:
            conn.close()


class _PollViewer(threading.Thread):
    """The polling fallback: delta-polls its session on a persistent
    keep-alive connection, driving the edge cache's steady-state load."""

    def __init__(self, port, sid, stop_evt):
        super().__init__(daemon=True)
        self.port, self.sid = port, sid
        self.stop_evt = stop_evt
        self.requests = 0
        self.errors = 0

    def run(self):
        token = None
        conn = None
        while not self.stop_evt.is_set():
            try:
                if conn is None:
                    conn = http.client.HTTPConnection(
                        "127.0.0.1", self.port, timeout=10
                    )
                if token:
                    path = f"/api/live?session={self.sid}&since={token}"
                else:
                    path = f"/api/live?session={self.sid}"
                conn.request("GET", path)
                resp = conn.getresponse()
                headers = dict(resp.getheaders())
                resp.read()
                self.requests += 1
                token = headers.get("X-TraceML-Token") or token
            except (OSError, http.client.HTTPException):
                self.errors += 1
                if conn is not None:
                    conn.close()
                conn = None
            self.stop_evt.wait(VIEWER_POLL_S)
        if conn is not None:
            conn.close()


def _pctile(values, q):
    values = sorted(values)
    assert values
    return values[min(len(values) - 1, int(q * len(values)))]


def test_federation_bench(tmp_path):
    serving.close_all_publishers()
    shard_dirs = [tmp_path / f"shard{i}" for i in range(N_SHARDS)]
    sids, dbs, shard_of = [], {}, {}
    for i, logs in enumerate(shard_dirs):
        for j in range(SESSIONS_PER_SHARD):
            sid = f"sess{i:02d}x{j:02d}"
            (logs / sid).mkdir(parents=True)
            dbs[sid] = logs / sid / "telemetry.sqlite"
            _write(dbs[sid], 0, n=20)
            sids.append(sid)
            shard_of[sid] = i

    drivers = [
        _start_shard(shard_dirs[i], f"sess{i:02d}x00")
        for i in range(N_SHARDS)
    ]
    shard_addrs = [f"127.0.0.1:{d.port}" for d in drivers]
    router = FleetRouter(
        shards=shard_addrs, cache_ttl=CACHE_TTL_S, probe_s=600.0
    )
    router.start()
    for shard in shard_addrs:
        router.health.probe(shard)  # learn every session's location
    try:
        # default min_poll_interval stays: the shared 0.2 s refresh is
        # what keeps hundreds of SSE waiters from each re-polling the
        # store — forced polls at write time notify them instantly
        pubs = {
            sid: serving.publisher_for(
                dbs[sid], sid,
                max_publishers=N_SHARDS * SESSIONS_PER_SHARD + 8,
            )
            for sid in sids
        }

        # -- golden: routed delta replay == owning shard's payload ------
        golden_sids = [f"sess{i:02d}x00" for i in range(N_SHARDS)]
        for sid in golden_sids:
            _replay_golden_routed(
                router.port, drivers[shard_of[sid]].port,
                sid, dbs[sid], pubs[sid],
            )
        bench_common.emit(
            BENCH, "golden_sessions", len(golden_sids), "sessions",
            shards=N_SHARDS,
        )

        # -- rollup: one page covering all 64 sessions ------------------
        t0 = time.monotonic()
        code, _, body = _get(router.port, "/api/fleet?page_size=100")
        rollup_ms = (time.monotonic() - t0) * 1e3
        fleet = json.loads(body)
        assert code == 200
        assert fleet["totals"]["sessions"] == len(sids)
        bench_common.emit(
            BENCH, "fleet_rollup_ms", rollup_ms, "ms",
            sessions=len(sids), shards=N_SHARDS,
        )

        # -- hop overhead: edge-cache hit latency -----------------------
        warm_sid = golden_sids[0]
        _get(router.port, f"/api/live?session={warm_sid}")
        lat = []
        for _ in range(300):
            t0 = time.monotonic()
            code, headers, _b = _get(
                router.port, f"/api/live?session={warm_sid}"
            )
            dt = time.monotonic() - t0
            if headers.get("X-TraceML-Edge-Cache") == "hit":
                lat.append(dt)
        assert len(lat) >= 200, "cache-hit path barely exercised"
        hit_p50 = _pctile(lat, 0.50)
        hit_p99 = _pctile(lat, 0.99)
        bench_common.emit(
            BENCH, "edge_hit_p50_ms", hit_p50 * 1e3, "ms"
        )
        bench_common.emit(
            BENCH, "edge_hit_p99_ms", hit_p99 * 1e3, "ms",
            budget_ms=HOP_OVERHEAD_P99_BUDGET_S * 1e3,
        )
        assert hit_p99 <= HOP_OVERHEAD_P99_BUDGET_S, (
            f"router cache-hit p99 {hit_p99 * 1e3:.2f} ms exceeds the "
            f"{HOP_OVERHEAD_P99_BUDGET_S * 1e3:.0f} ms per-hop budget"
        )

        # -- staleness + upstream independence under 256 viewers --------
        stop_evt = threading.Event()
        token_pub_ts = {}
        sse_viewers = [
            _SSEViewer(router.port, sid, stop_evt, token_pub_ts)
            for sid in sids
            for _ in range(SSE_PER_SESSION)
        ]
        pollers = [
            _PollViewer(router.port, sid, stop_evt)
            for sid in sids
            for _ in range(POLLERS_PER_SESSION)
        ]
        viewers = sse_viewers + pollers
        assert len(viewers) == 256
        for v in viewers:
            v.start()
        time.sleep(1.5)  # SSE replay drained, pollers hold tokens
        fetches0 = router.upstream_fetches
        fetches0_200 = router.upstream_fetches_200
        requests0 = sum(p.requests for p in pollers)

        advances = 0
        for round_i in range(WRITE_ROUNDS):
            lo = (round_i * WRITES_PER_ROUND) % len(sids)
            batch = [
                sids[(lo + k) % len(sids)]
                for k in range(WRITES_PER_ROUND)
            ]
            for sid in batch:
                _write(dbs[sid], 3000 + round_i * 5)
                tok = pubs[sid].poll(force=True)
                token_pub_ts.setdefault(
                    (sid, tok), time.monotonic()
                )
                advances += 1
                # spread advances across the round — a fleet's shards
                # write independently, not in one process-hogging burst
                time.sleep(ROUND_SPACING_S / WRITES_PER_ROUND)
        time.sleep(CACHE_TTL_S + 2 * VIEWER_POLL_S)  # drain receipts
        fetches = router.upstream_fetches - fetches0
        fetches_200 = router.upstream_fetches_200 - fetches0_200
        viewer_requests = sum(p.requests for p in pollers) - requests0

        staleness = [s for v in sse_viewers for s in v.staleness]
        assert len(staleness) >= advances, (
            "too few receipt samples to trust the percentile"
        )
        stale_p50 = _pctile(staleness, 0.50)
        stale_p99 = _pctile(staleness, 0.99)
        bench_common.emit(
            BENCH, "staleness_p50_ms", stale_p50 * 1e3, "ms",
            viewers=len(viewers), sessions=len(sids),
            samples=len(staleness),
        )
        bench_common.emit(
            BENCH, "staleness_p99_ms", stale_p99 * 1e3, "ms",
            viewers=len(viewers), sessions=len(sids),
            budget_ms=STALENESS_P99_BUDGET_S * 1e3,
        )
        assert stale_p99 <= STALENESS_P99_BUDGET_S, (
            f"p99 staleness {stale_p99 * 1e3:.0f} ms through the router "
            f"exceeds the {STALENESS_P99_BUDGET_S * 1e3:.0f} ms budget"
        )

        per_version = fetches_200 / max(1, advances)
        bench_common.emit(
            BENCH, "upstream_fetches_per_version", per_version,
            "fetches", advances=advances, fresh_fetches=fetches_200,
            probe_fetches=fetches - fetches_200,
            viewer_requests=viewer_requests,
        )
        assert per_version <= FETCHES_PER_VERSION_SLACK, (
            f"{per_version:.2f} fresh upstream fetches per "
            f"session-version — the edge cache is not collapsing "
            f"viewers"
        )

        # -- burst: viewer count must not multiply shard fetches --------
        burst_sids = sids[:BURST_SESSIONS]
        b0_200 = router.upstream_fetches_200
        burst_threads = []
        burst_errors = []

        def _burst(sid):
            try:
                for _ in range(3):
                    _get(router.port, f"/api/live?session={sid}")
            except OSError as exc:
                burst_errors.append(exc)

        for sid in burst_sids:
            for _ in range(BURST_VIEWERS):
                t = threading.Thread(target=_burst, args=(sid,),
                                     daemon=True)
                burst_threads.append(t)
        for t in burst_threads:
            t.start()
        for t in burst_threads:
            t.join(timeout=30)
        assert not burst_errors
        burst_200 = router.upstream_fetches_200 - b0_200
        per_session = burst_200 / len(burst_sids)
        bench_common.emit(
            BENCH, "burst_fetches_per_session", per_session, "fetches",
            burst_viewers=BURST_VIEWERS, burst_requests=3,
            sessions=len(burst_sids),
        )
        assert per_session <= FETCHES_PER_VERSION_SLACK, (
            f"{BURST_VIEWERS} concurrent viewers cost the shard "
            f"{per_session:.2f} fresh fetches per session — fetches "
            f"scale with viewers, the edge cache is pass-through"
        )

        stop_evt.set()
        for v in viewers:
            v.join(timeout=10)
        assert sum(v.errors for v in viewers) == 0
    finally:
        router.stop()
        for d in drivers:
            d.stop()
        serving.close_all_publishers()
