"""Rich Live CLI display driver
(reference: src/traceml_ai/aggregator/display_drivers/cli.py:55-295).

Runs inside the aggregator process; each ``tick`` recomputes the live
payload from the session SQLite and refreshes a Rich Live group.
"""

from __future__ import annotations

from typing import Any, Optional

from traceml_tpu.aggregator.display_drivers.base import BaseDisplayDriver
from traceml_tpu.utils.error_log import get_error_log


class CLIDisplayDriver(BaseDisplayDriver):
    def __init__(self) -> None:
        self._live = None
        self._computer = None
        self._session = ""

    def start(self, context: Optional[Any] = None) -> None:
        try:
            from rich.console import Console
            from rich.live import Live

            from traceml_tpu.renderers.compute import LiveComputer

            if context is not None:
                self._computer = LiveComputer(context.db_path)
                self._session = context.settings.session_id
            self._live = Live(
                console=Console(stderr=False),
                refresh_per_second=4,
                transient=False,
            )
            self._live.start()
        except Exception as exc:
            get_error_log().warning("cli display start failed", exc)
            self._live = None

    def tick(self, context: Optional[Any] = None) -> None:
        if self._live is None or self._computer is None:
            return
        try:
            from traceml_tpu.renderers.panels import dashboard

            payload = self._computer.payload()
            self._live.update(dashboard(payload, self._session))
        except Exception as exc:
            get_error_log().warning("cli display tick failed", exc)

    def stop(self) -> None:
        if self._live is not None:
            try:
                self._live.stop()
            except Exception:
                pass
            self._live = None
        if self._computer is not None:
            try:
                self._computer.close()  # release the store's read connection
            except Exception:
                pass
            self._computer = None
