"""Selected-clock step-time window pipeline
(reference: src/traceml_ai/utils/step_time_window.py — the single most
load-bearing algorithm; see SURVEY.md §2.8).

Takes per-rank step rows (as produced by the step-time sampler /
``step_time_samples`` projection) and builds the window every renderer,
diagnostic and report consumes:

1. **suffix alignment** — compare ranks over the common suffix of steps
   all of them have reported (reference: utils/step_windows.py:14);
2. **clock selection** — "device" only if EVERY rank/step has device
   timing for the step envelope, else "host" (generalizes the
   reference's gpu-vs-cpu selection to host-vs-XLA-device);
3. **phase extraction + residual clamp** — per step:
   ``residual = max(0, step − Σ accounted phases)``;
4. **per-rank averages + cross-rank metrics** — median/worst/skew per
   phase, with per-step series.

Phase vocabulary: the reference's six phases plus the TPU-only
``compute`` (fused fwd+bwd+opt inside one jit), ``compile`` and
``collective``.  Durations are in milliseconds.
"""

from __future__ import annotations

import dataclasses
import statistics
from typing import Any, Dict, List, Mapping, Optional, Sequence

from traceml_tpu.utils import timing as T

# phase key → internal event name
PHASES: Dict[str, str] = {
    "input": T.DATALOADER_NEXT,
    "h2d": T.H2D_TIME,
    "forward": T.FORWARD_TIME,
    "backward": T.BACKWARD_TIME,
    "optimizer": T.OPTIMIZER_STEP,
    "compute": T.COMPUTE_TIME,
    "compile": T.COMPILE_TIME,
    "collective": T.COLLECTIVE_TIME,
    "checkpoint": T.CHECKPOINT_TIME,
}
STEP_KEY = "step_time"
RESIDUAL_KEY = "residual"
ACCOUNTED_PHASES = tuple(PHASES.keys())
ALL_KEYS = (STEP_KEY,) + ACCOUNTED_PHASES + (RESIDUAL_KEY,)


@dataclasses.dataclass
class RankWindow:
    """One rank's aligned window."""

    rank: int
    steps: List[int]
    # per phase key → per-step ms values (aligned with ``steps``)
    series: Dict[str, List[float]]
    # per phase key → window average ms
    averages: Dict[str, float]
    # per phase key → window MEDIAN ms — the contention-robust per-rank
    # statistic: a host burst covering a few steps inflates the mean
    # but barely moves the median, so cross-rank comparisons (the
    # straggler math) read medians to keep attribution stable when the
    # host is loaded (round-2 flake: INPUT_STRAGGLER degraded to
    # INPUT_BOUND under full-suite contention)
    medians: Dict[str, float]
    clock: str
    # device-busy share of the wall clock: Σ phase device durations /
    # Σ host(step envelope) over the window — the TPU stand-in for a
    # chip-utilization counter (phase readiness edges tile chip
    # occupancy; host envelopes tile wall)
    occupancy: Optional[float] = None


@dataclasses.dataclass
class StepCombinedTimeMetric:
    """Cross-rank stats for one phase
    (reference: renderers/step_time/schema.py:50)."""

    key: str
    per_rank_avg_ms: Dict[int, float]
    median_ms: float
    worst_ms: float
    worst_rank: int
    skew_pct: float  # (worst − median) / median, 0 when median==0

    @property
    def mean_ms(self) -> float:
        vals = list(self.per_rank_avg_ms.values())
        return sum(vals) / len(vals) if vals else 0.0


@dataclasses.dataclass
class StepTimeWindow:
    clock: str
    steps: List[int]  # the aligned step ids
    ranks: List[int]
    rank_windows: Dict[int, RankWindow]
    metrics: Dict[str, StepCombinedTimeMetric]
    phases_present: List[str]
    n_steps: int

    def metric(self, key: str) -> Optional[StepCombinedTimeMetric]:
        return self.metrics.get(key)

    @property
    def occupancy_by_rank(self) -> Dict[int, float]:
        return {
            r: w.occupancy
            for r, w in self.rank_windows.items()
            if w.occupancy is not None
        }

    @property
    def median_occupancy(self) -> Optional[float]:
        vals = list(self.occupancy_by_rank.values())
        return statistics.median(vals) if vals else None

    def share_of_step(self, key: str) -> Optional[float]:
        """median(phase) / median(step) — the phase-share statistic."""
        m = self.metrics.get(key)
        s = self.metrics.get(STEP_KEY)
        if m is None or s is None or s.median_ms <= 0:
            return None
        return m.median_ms / s.median_ms


def common_suffix_steps(per_rank_steps: Mapping[int, Sequence[int]], max_steps: int) -> List[int]:
    """Steps present in EVERY rank, newest-first truncated to max_steps,
    returned ascending (reference: utils/step_windows.py:14)."""
    if not per_rank_steps:
        return []
    common = None
    for steps in per_rank_steps.values():
        s = set(steps)
        common = s if common is None else (common & s)
    if not common:
        return []
    return sorted(common)[-max_steps:]


def _row_value(row: Mapping[str, Any], event_name: str, clock: str) -> Optional[float]:
    ev = (row.get("events") or {}).get(event_name)
    if not ev:
        return None
    if clock == "device":
        v = ev.get("device_ms")
        if v is not None:
            return float(v)
        # fall back to host for phases that have no device side (input)
        v = ev.get("cpu_ms")
        return float(v) if v is not None else None
    v = ev.get("cpu_ms")
    return float(v) if v is not None else None


def row_occupancy_parts(events: Mapping[str, Any]) -> Optional[tuple]:
    """(device_busy_ms, host_ms) for ONE step row, or None.

    THE chip-occupancy definition — every consumer (window builder,
    live_metrics) routes through here so the definition cannot fork:

    * numerator: Σ PHASE device durations (consecutive readiness edges
      are serial, so they tile device occupancy).  The ENVELOPE's device
      span is NOT used when phase timings exist — its start edge carries
      from the previous step's retirement, so it includes pre-dispatch
      idle (input wait) and reads ~100% busy on an input-bound run;
    * fallback: envelope-only instrumentation (no timed phase regions)
      uses the envelope span — an UPPER bound on busy, but far better
      than silencing the low-utilization rule entirely;
    * 0.0 is a legitimate duration (idle step); only None excludes.
    """
    env = events.get(T.STEP_TIME) or {}
    host = env.get("cpu_ms")
    if host is None:
        return None
    timed = [
        ev.get("device_ms")
        for name, ev in events.items()
        if name != T.STEP_TIME and ev and ev.get("device_ms") is not None
    ]
    if timed:
        return (float(sum(timed)), float(host))
    if env.get("device_ms") is not None:
        return (float(env["device_ms"]), float(host))
    return None


def select_clock(rank_rows: Mapping[int, Sequence[Mapping[str, Any]]]) -> str:
    """"device" only if every rank/step row carries device timing for the
    step envelope (reference: _select_clock_from_events:185)."""
    saw_any = False
    for rows in rank_rows.values():
        for row in rows:
            saw_any = True
            ev = (row.get("events") or {}).get(T.STEP_TIME) or {}
            if row.get("clock") != "device" or ev.get("device_ms") is None:
                return "host"
    return "device" if saw_any else "host"


def build_rank_window(
    rank: int,
    rows: Sequence[Mapping[str, Any]],
    steps: Sequence[int],
    clock: str,
) -> RankWindow:
    """Phase extraction + residual clamp (reference: _build_rank_timing)."""
    by_step = {int(r["step"]): r for r in rows if r.get("step") is not None}
    series: Dict[str, List[float]] = {k: [] for k in ALL_KEYS}
    dev_sum = host_sum = 0.0
    for step in steps:
        row = by_step.get(step)
        if row is None:
            for k in ALL_KEYS:
                series[k].append(0.0)
            continue
        parts = row_occupancy_parts(row.get("events") or {})
        if parts is not None:
            dev_sum += parts[0]
            host_sum += parts[1]
        step_ms = _row_value(row, T.STEP_TIME, clock) or 0.0
        accounted = 0.0
        for key, event_name in PHASES.items():
            v = _row_value(row, event_name, clock) or 0.0
            # clamp any phase to the step envelope (device quantization
            # can make a phase nominally exceed the step)
            v = min(v, step_ms) if step_ms > 0 else v
            series[key].append(v)
            accounted += v
        residual = max(0.0, step_ms - accounted)
        series[STEP_KEY].append(step_ms)
        series[RESIDUAL_KEY].append(residual)
    averages = {
        k: (sum(vs) / len(vs) if vs else 0.0) for k, vs in series.items()
    }
    medians = {
        k: (statistics.median(vs) if vs else 0.0) for k, vs in series.items()
    }
    return RankWindow(
        rank=rank,
        steps=list(steps),
        series=series,
        averages=averages,
        medians=medians,
        clock=clock,
        # cap: device readiness quantization can nominally exceed wall.
        # host_sum>0 alone gates (dual-clock rows existed): a fully idle
        # window must read 0.0, not None — None would silence the
        # LOW_DEVICE_UTILIZATION rule exactly when it matters most
        occupancy=min(dev_sum / host_sum, 1.0) if host_sum > 0 else None,
    )


def build_step_time_metrics(rank_windows: Mapping[int, RankWindow]) -> Dict[str, StepCombinedTimeMetric]:
    metrics: Dict[str, StepCombinedTimeMetric] = {}
    if not rank_windows:
        return metrics
    for key in ALL_KEYS:
        per_rank = {r: w.averages.get(key, 0.0) for r, w in rank_windows.items()}
        vals = list(per_rank.values())
        if not vals:  # empty-window early-out: never reach median([])
            continue
        med = statistics.median(vals)
        worst_rank = max(per_rank, key=lambda r: per_rank[r])
        worst = per_rank[worst_rank]
        skew = (worst - med) / med if med > 0 else 0.0
        metrics[key] = StepCombinedTimeMetric(
            key=key,
            per_rank_avg_ms=per_rank,
            median_ms=med,
            worst_ms=worst,
            worst_rank=worst_rank,
            skew_pct=skew,
        )
    return metrics


def build_step_time_window(
    rank_rows: Mapping[int, Sequence[Mapping[str, Any]]],
    max_steps: int = 200,
) -> Optional[StepTimeWindow]:
    """rank → step rows ⇒ aligned cross-rank window
    (reference: build_step_time_window_from_events:437)."""
    rank_rows = {r: list(rows) for r, rows in rank_rows.items() if rows}
    if not rank_rows:
        return None
    steps = common_suffix_steps(
        {r: [int(row["step"]) for row in rows if row.get("step") is not None]
         for r, rows in rank_rows.items()},
        max_steps,
    )
    if not steps:
        return None
    clock = select_clock(rank_rows)
    windows = {
        r: build_rank_window(r, rows, steps, clock)
        for r, rows in rank_rows.items()
    }
    metrics = build_step_time_metrics(windows)
    phases_present = [
        k
        for k in ACCOUNTED_PHASES
        if any(any(v > 0 for v in w.series[k]) for w in windows.values())
    ]
    return StepTimeWindow(
        clock=clock,
        steps=steps,
        ranks=sorted(windows),
        rank_windows=windows,
        metrics=metrics,
        phases_present=phases_present,
        n_steps=len(steps),
    )
