"""Hugging Face Trainer with the TraceML-TPU callback.

Run:  traceml-tpu run --mode summary \
          examples/quickstart/huggingface_trainer_minimal.py
"""

import numpy as np
import torch

from transformers import (
    BertConfig,
    BertForSequenceClassification,
    Trainer,
    TrainingArguments,
)

from traceml_tpu.integrations.huggingface import TraceMLTrainerCallback


class ToyDataset(torch.utils.data.Dataset):
    def __len__(self):
        return 256

    def __getitem__(self, i):
        rng = np.random.default_rng(i)
        return {
            "input_ids": torch.tensor(rng.integers(0, 1000, 32)),
            "attention_mask": torch.ones(32, dtype=torch.long),
            "labels": torch.tensor(i % 2),
        }


config = BertConfig(
    vocab_size=1000, hidden_size=64, num_hidden_layers=2,
    num_attention_heads=2, intermediate_size=128,
)
model = BertForSequenceClassification(config)

trainer = Trainer(
    model=model,
    args=TrainingArguments(
        output_dir="/tmp/traceml_hf_out", num_train_epochs=1,
        per_device_train_batch_size=8, logging_steps=50, report_to=[],
    ),
    train_dataset=ToyDataset(),
    callbacks=[TraceMLTrainerCallback()],
)
trainer.train()
