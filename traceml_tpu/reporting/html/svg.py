"""Inline-SVG chart builders for the HTML report
(reference role: reporting/html/svg.py — dependency-free charts that
open anywhere, survive email/ticket attachment, and print).
"""

from __future__ import annotations

import html
from typing import Any, Dict, List, Optional

from traceml_tpu.reporting.html.style import PHASE_COLORS

_HUES = [210, 0, 120, 280, 30, 170, 330, 60]


def _esc(x: Any) -> str:
    return html.escape(str(x))


def step_series_svg(
    series: Dict[str, Any], width: int = 900, height: int = 120
) -> str:
    """One polyline per rank over the aligned step window, shared scale."""
    all_vals = [v for vs in series.values() for v in vs if v is not None]
    if not all_vals:
        return ""
    vmax = max(all_vals) or 1.0
    lines = []
    for i, (rank, vs) in enumerate(
        sorted(series.items(), key=lambda kv: int(kv[0]))
    ):
        if not vs:
            continue
        n = len(vs)
        pts = " ".join(
            f"{(j / max(1, n - 1)) * width:.1f},"
            f"{height - 4 - (v / vmax) * (height - 10):.1f}"
            for j, v in enumerate(vs)
        )
        hue = _HUES[i % len(_HUES)]
        lines.append(
            f'<polyline fill="none" stroke="hsl({hue},65%,45%)" '
            f'stroke-width="1.2" points="{pts}"><title>rank {_esc(rank)}'
            f"</title></polyline>"
        )
    legend = " ".join(
        f'<tspan fill="hsl({_HUES[i % len(_HUES)]},65%,45%)">rank {_esc(r)}</tspan>'
        for i, r in enumerate(sorted(series, key=int))
    )
    return (
        f'<svg viewBox="0 0 {width} {height}" '
        f'style="width:100%;height:{height}px;background:#f4f4f8;'
        f'border-radius:6px">{"".join(lines)}'
        f'<text x="6" y="14" font-size="11">{legend} · max {vmax:.1f} ms</text>'
        f"</svg>"
    )


def phase_share_bar(phases: Dict[str, Any]) -> str:
    """One stacked horizontal share bar + legend."""
    parts: List[str] = []
    total = 0.0
    for key, info in phases.items():
        if key == "step_time":
            continue
        share = info.get("share_of_step")
        if not share or share <= 0:
            continue
        share = min(share, 1.0 - total)
        total += share
        color = PHASE_COLORS.get(key, "#888")
        parts.append(
            f'<span class="bar" title="{_esc(key)}: {share * 100:.1f}%" '
            f'style="width:{share * 100:.2f}%;background:{color}"></span>'
        )
    legend = " ".join(
        f'<span class="muted"><span class="bar" style="width:10px;'
        f'background:{PHASE_COLORS.get(k, "#888")}"></span> {_esc(k)}</span>'
        for k in phases
        if k != "step_time"
    )
    return (
        f'<div style="width:100%;background:#eee;border-radius:3px">'
        f'{"".join(parts)}</div><div>{legend}</div>'
    )


def median_worst_bars(
    rollup: Dict[str, Any],
    *,
    unit: str = "ms",
    width: int = 900,
    row_h: int = 22,
    exclude: tuple = ("step_time",),
) -> str:
    """Per-metric median→worst range bars from the uniform rollup:
    each row draws median (solid) and worst (hatched extension) on a
    shared scale with both ranks labeled — the spread AND its owners
    in one glance."""
    med = rollup.get("median") or {}
    wor = rollup.get("worst") or {}
    keys = [
        k for k in med
        if k not in exclude and (med[k] or {}).get("value") is not None
    ]
    if not keys:
        return ""
    vmax = max((wor.get(k) or {}).get("value") or 0 for k in keys) or 1.0
    rows = []
    label_w = 110
    bar_w = width - label_w - 180
    for i, k in enumerate(sorted(keys, key=lambda k: -(
        (wor.get(k) or {}).get("value") or 0
    ))):
        m, w = med[k], wor.get(k) or {}
        mv, wv = m.get("value") or 0.0, w.get("value") or 0.0
        y = i * row_h
        color = PHASE_COLORS.get(k, "#2d7dd2")
        m_px = bar_w * mv / vmax
        w_px = bar_w * max(wv - mv, 0) / vmax
        rows.append(
            f'<text x="0" y="{y + 14}" font-size="11">{_esc(k)}</text>'
            f'<rect x="{label_w}" y="{y + 4}" width="{m_px:.1f}" height="12" '
            f'rx="2" fill="{color}"><title>median {mv:.1f} {unit} '
            f"(r{_esc(m.get('idx'))})</title></rect>"
            f'<rect x="{label_w + m_px:.1f}" y="{y + 4}" width="{w_px:.1f}" '
            f'height="12" rx="2" fill="{color}" opacity="0.38">'
            f"<title>worst {wv:.1f} {unit} (r{_esc(w.get('idx'))})</title></rect>"
            f'<text x="{label_w + m_px + w_px + 6:.1f}" y="{y + 14}" '
            f'font-size="10" fill="#666">{mv:.1f}/{wv:.1f} {unit} · '
            f"r{_esc(m.get('idx'))}/r{_esc(w.get('idx'))}</text>"
        )
    h = len(keys) * row_h + 6
    return (
        f'<svg viewBox="0 0 {width} {h}" style="width:100%;height:{h}px">'
        f'{"".join(rows)}</svg>'
        '<div class="muted">solid = median rank · faded extension = worst '
        "rank (values and owning ranks in the hover/labels)</div>"
    )


def sparkline(
    values: List[float], width: int = 100, height: int = 18,
    color: str = "#2d7dd2", vmax: Optional[float] = None,
) -> str:
    """Tiny inline sparkline for table cells."""
    vals = [v for v in values if v is not None]
    if len(vals) < 2:
        return "—"
    m = vmax or max(vals) or 1.0
    pts = " ".join(
        f"{(i / (len(vals) - 1)) * width:.1f},"
        f"{height - 2 - (v / m) * (height - 4):.1f}"
        for i, v in enumerate(vals)
    )
    return (
        f'<svg width="{width}" height="{height}" '
        f'viewBox="0 0 {width} {height}"><polyline fill="none" '
        f'stroke="{color}" stroke-width="1" points="{pts}"/></svg>'
    )
