"""Summary fixture battery — end-state verdict coverage across many
injected data shapes (reference: tests/reporting/summary/
test_fixtures.py — schema stability under empty/partial/misaligned
inputs, single- and multi-rank coverage, per-section contracts).

Multi-rank is a DATA shape here (rows injected per rank through the
real SQLiteWriter), so the battery runs in milliseconds."""

import json

import pytest

from traceml_tpu.aggregator.sqlite_writer import SQLiteWriter
from traceml_tpu.reporting.final import generate_summary
from traceml_tpu.runtime.settings import TraceMLSettings
from traceml_tpu.telemetry.envelope import SenderIdentity, build_telemetry_envelope
from traceml_tpu.utils import timing as T

GiB = 1024**3

SECTIONS = ("system", "process", "step_time", "step_memory")


def _step_row(step, step_ms=100.0, input_ms=5.0, compute_ms=90.0,
              collective_ms=None, clock="device"):
    events = {
        T.STEP_TIME: {"cpu_ms": step_ms,
                      "device_ms": step_ms if clock == "device" else None,
                      "count": 1},
        T.DATALOADER_NEXT: {"cpu_ms": input_ms, "device_ms": None, "count": 1},
        T.COMPUTE_TIME: {"cpu_ms": 0.5,
                         "device_ms": compute_ms if clock == "device" else None,
                         "count": 1},
    }
    if collective_ms is not None:
        events[T.COLLECTIVE_TIME] = {
            "cpu_ms": collective_ms, "device_ms": collective_ms, "count": 1
        }
    return {"step": step, "timestamp": float(step), "clock": clock,
            "events": events}


class _Session:
    """One injected session: write envelopes, generate, read payload."""

    def __init__(self, tmp_path, session="fx"):
        self.dir = tmp_path
        self.session = session
        self.writer = SQLiteWriter(tmp_path / "telemetry.sqlite")
        self.writer.start()

    def ident(self, rank, world=1, node=0):
        return SenderIdentity(
            session_id=self.session, global_rank=rank, world_size=world,
            node_rank=node, hostname=f"host{node}", pid=100 + rank,
        )

    def inject(self, sampler, tables, ident):
        self.writer.ingest(build_telemetry_envelope(sampler, tables, ident))

    def payload(self):
        self.writer.force_flush()
        self.writer.finalize()
        settings = TraceMLSettings(session_id=self.session, logs_dir=self.dir)
        assert generate_summary(
            self.dir / "telemetry.sqlite", self.dir, settings, mode="summary"
        )
        return json.loads((self.dir / "final_summary.json").read_text())


def _assert_schema_stable(payload):
    """Every section exists with the status/diagnosis/issues contract
    regardless of what data arrived."""
    assert payload["schema"].startswith("traceml-tpu/")
    for key in SECTIONS:
        sec = payload["sections"][key]
        assert sec["status"] in ("OK", "NO_DATA")
        assert "issues" in sec
        if sec["status"] == "OK":
            assert sec["diagnosis"] is not None
            assert sec["issues"][0] == sec["diagnosis"]  # documented invariant
            assert "global" in sec


def test_empty_db_stable_schema(tmp_path):
    s = _Session(tmp_path)
    payload = s.payload()
    _assert_schema_stable(payload)
    assert all(
        payload["sections"][k]["status"] == "NO_DATA" for k in SECTIONS
    )
    assert payload["primary_diagnosis"]["kind"] == "INSUFFICIENT_STEP_TIME_DATA"


def test_step_time_only_other_sections_degrade(tmp_path):
    s = _Session(tmp_path)
    s.inject("step_time",
             {"step_time": [_step_row(i) for i in range(1, 61)]}, s.ident(0))
    payload = s.payload()
    _assert_schema_stable(payload)
    assert payload["sections"]["step_time"]["status"] == "OK"
    assert payload["sections"]["system"]["status"] == "NO_DATA"
    assert payload["sections"]["process"]["status"] == "NO_DATA"


def test_host_clock_run(tmp_path):
    """No device timing anywhere → host clock selected, still diagnosable."""
    s = _Session(tmp_path)
    rows = [_step_row(i, input_ms=60.0, clock="host") for i in range(1, 61)]
    s.inject("step_time", {"step_time": rows}, s.ident(0))
    payload = s.payload()
    g = payload["sections"]["step_time"]["global"]
    assert g["clock"] == "host"
    assert g["median_occupancy"] is None  # no device data → no occupancy
    assert payload["sections"]["step_time"]["diagnosis"]["kind"] == "INPUT_BOUND"


def test_misaligned_ranks_use_common_suffix(tmp_path):
    """Rank 1 joined late: the window is the common suffix only."""
    s = _Session(tmp_path)
    s.inject("step_time",
             {"step_time": [_step_row(i) for i in range(1, 81)]},
             s.ident(0, world=2))
    s.inject("step_time",
             {"step_time": [_step_row(i) for i in range(41, 81)]},
             s.ident(1, world=2))
    payload = s.payload()
    g = payload["sections"]["step_time"]["global"]
    assert g["step_range"][0] >= 41
    assert g["ranks"] == [0, 1]


def test_missing_rank_reported_in_topology(tmp_path):
    s = _Session(tmp_path)
    for rank in (0, 1, 3):  # rank 2 never reports
        s.inject("step_time",
                 {"step_time": [_step_row(i) for i in range(1, 41)]},
                 s.ident(rank, world=4))
    payload = s.payload()
    topo = payload["meta"]["topology"]
    assert topo["world_size"] == 4
    assert sorted(topo["ranks_seen"]) == [0, 1, 3]


def test_collective_phase_in_summary(tmp_path):
    s = _Session(tmp_path)
    rows = [_step_row(i, step_ms=120.0, compute_ms=60.0, collective_ms=50.0)
            for i in range(1, 61)]
    s.inject("step_time", {"step_time": rows}, s.ident(0))
    payload = s.payload()
    phases = payload["sections"]["step_time"]["global"]["phases"]
    assert phases["collective"]["median_ms"] == pytest.approx(50.0)
    assert phases["collective"]["share_of_step"] == pytest.approx(50.0 / 120.0)


def test_memory_without_limits(tmp_path):
    """CPU/tunneled runtimes have no bytes_limit — pressure is None, no
    pressure verdicts, schema intact."""
    s = _Session(tmp_path)
    mem = [{"step": i, "timestamp": float(i), "device_id": 0,
            "device_kind": "cpu", "current_bytes": 1 * GiB,
            "peak_bytes": 1 * GiB, "step_peak_bytes": 1 * GiB,
            "limit_bytes": None, "backend": "live_arrays"}
           for i in range(1, 61)]
    s.inject("step_memory", {"step_memory": mem}, s.ident(0))
    payload = s.payload()
    rank0 = payload["sections"]["step_memory"]["global"]["per_rank"]["0"]
    assert rank0["pressure"] is None
    kinds = {i["kind"] for i in payload["sections"]["step_memory"]["issues"]}
    assert "HIGH_MEMORY_PRESSURE" not in kinds


def test_multi_node_cluster_rollup_in_summary(tmp_path):
    s = _Session(tmp_path)
    for node, cpu in ((0, 20.0), (1, 80.0)):
        sysrows = [{"timestamp": float(i), "cpu_pct": cpu,
                    "memory_used_bytes": 4 * GiB, "memory_total_bytes": 16 * GiB,
                    "memory_pct": 25.0, "load_1m": 1.0}
                   for i in range(30)]
        s.inject("system", {"system": sysrows}, s.ident(node * 4, world=8, node=node))
    payload = s.payload()
    cluster = payload["sections"]["system"]["global"]["cluster"]
    assert cluster["n_nodes"] == 2
    assert cluster["cpu_pct_max"] == pytest.approx(80.0)
    assert cluster["busiest_node"] == "host1"


def test_per_rank_identity_blocks_two_nodes(tmp_path):
    """Section per-rank groups carry identity blocks (reference:
    SCHEMA.md groups.rows[*].identity) — hostname/node placement is
    readable straight off a rank row in a multi-node summary."""
    s = _Session(tmp_path)
    for rank, node in ((0, 0), (1, 0), (2, 1), (3, 1)):
        ident = s.ident(rank, world=4, node=node)
        s.inject(
            "step_time",
            {"step_time": [_step_row(i) for i in range(1, 25)]},
            ident,
        )
        s.inject(
            "process",
            {"process": [{"timestamp": 1.0, "cpu_pct": 10.0,
                          "rss_bytes": GiB, "num_threads": 8}]},
            ident,
        )
        s.inject(
            "step_memory",
            {"step_memory": [{"step": i, "timestamp": float(i),
                              "device_id": 0, "device_kind": "tpu",
                              "current_bytes": GiB, "peak_bytes": GiB,
                              "step_peak_bytes": GiB, "limit_bytes": 16 * GiB}
                             for i in range(1, 10)]},
            ident,
        )
    payload = s.payload()
    for section, rank_key in (
        ("step_time", "per_rank"),
        ("step_memory", "per_rank"),
        ("process", "per_rank"),
    ):
        per_rank = payload["sections"][section]["global"][rank_key]
        assert set(per_rank) == {"0", "1", "2", "3"}, section
        for rank, node in (("0", 0), ("2", 1)):
            ident = per_rank[rank]["identity"]
            assert ident is not None, (section, rank)
            assert ident["hostname"] == f"host{node}"
            assert ident["node_rank"] == node
            assert ident["world_size"] == 4
    # section-local text cards (reference SCHEMA `card`) carry the
    # per-rank detail including placement
    for section in ("step_time", "step_memory", "process"):
        card = payload["sections"][section]["card"]
        assert "rank 2" in card and "[host1#1]" in card, (section, card)


def test_mfu_in_step_time_section(tmp_path):
    """model_stats telemetry → achieved TFLOP/s + MFU in the summary.

    100 ms steps at 10 TFLOP/step → 100 TFLOP/s achieved; on a v5p
    (459 TFLOP/s peak) that is ~21.8% MFU."""
    s = _Session(tmp_path)
    ident = s.ident(0)
    s.inject(
        "step_time",
        {"step_time": [_step_row(i, step_ms=100.0) for i in range(1, 41)],
         "model_stats": [{"timestamp": 1.0, "flops_per_step": 10e12,
                          "flops_source": "cost_analysis",
                          "device_kind": "TPU v5p", "peak_flops": 459e12}]},
        ident,
    )
    payload = s.payload()
    eff = payload["sections"]["step_time"]["global"]["efficiency"]
    assert eff is not None
    assert eff["achieved_tflops_median"] == pytest.approx(100.0, rel=0.05)
    assert eff["mfu_median"] == pytest.approx(100.0 / 459.0, rel=0.05)
    assert eff["device_kind"] == "TPU v5p"
    txt = (tmp_path / "final_summary.txt").read_text()
    assert "TFLOP/s" in txt and "MFU" in txt


def test_no_model_stats_no_efficiency(tmp_path):
    s = _Session(tmp_path)
    s.inject("step_time", {"step_time": [_step_row(i) for i in range(1, 30)]},
             s.ident(0))
    payload = s.payload()
    assert payload["sections"]["step_time"]["global"]["efficiency"] is None


def test_garbage_rows_do_not_break_summary(tmp_path):
    """Rows with missing/None fields degrade gracefully, never throw."""
    s = _Session(tmp_path)
    rows = [
        {"step": 1, "timestamp": 1.0, "clock": "device", "events": {}},
        {"step": None, "timestamp": None, "clock": None, "events": None},
        _step_row(2),
    ]
    s.inject("step_time", {"step_time": rows}, s.ident(0))
    s.inject("step_memory", {"step_memory": [{"step": 1}]}, s.ident(0))
    payload = s.payload()
    _assert_schema_stable(payload)


def test_single_step_run(tmp_path):
    """One step: below every diagnosis gate, still schema-valid."""
    s = _Session(tmp_path)
    s.inject("step_time", {"step_time": [_step_row(1)]}, s.ident(0))
    payload = s.payload()
    _assert_schema_stable(payload)
    st = payload["sections"]["step_time"]
    assert st["global"]["n_steps"] == 1
    assert st["global"]["steady_state"] is None  # needs ≥12 steps
    assert payload["primary_diagnosis"]["kind"] in (
        "INSUFFICIENT_STEP_TIME_DATA", "NO_CLEAR_PERFORMANCE_BOTTLENECK",
        "HEALTHY", "COMPUTE_BOUND",
    )


def test_occupancy_low_run_yields_low_util_verdict(tmp_path):
    s = _Session(tmp_path)
    rows = []
    for i in range(1, 61):
        # chip busy = phase device (18) / host step (100) = 18%
        rows.append(_step_row(i, step_ms=100.0, compute_ms=18.0))
    s.inject("step_time", {"step_time": rows}, s.ident(0))
    payload = s.payload()
    g = payload["sections"]["step_time"]["global"]
    assert g["median_occupancy"] == pytest.approx(0.18)
    kinds = {i["kind"] for i in payload["sections"]["step_time"]["issues"]}
    assert "LOW_DEVICE_UTILIZATION" in kinds


# -- reference feature parity (VERDICT r3 item 3) --------------------------
# field-by-field against the reference builders' output features:
# sections/step_time/builder.py (card Stats/Ranks lines, BaseGlobal
# rollup), sections/step_memory/model.py (median/worst {value, idx}
# points with closest-rank-to-median), compare/verdict.py (ladder —
# covered by tests/reporting/test_compare_engine.py).  Intentional
# omissions are documented in PARITY.md §2.9.

def _multirank_session(tmp_path, n=4):
    s = _Session(tmp_path)
    for rank in range(n):
        rows = [
            _step_row(i, step_ms=100.0 + rank * 20, input_ms=5.0 + rank * 18)
            for i in range(1, 61)
        ]
        s.inject("step_time", {"step_time": rows}, s.ident(rank, world=n))
        s.inject("step_memory", {"step_memory": [
            {"step": 60, "timestamp": 60.0, "device_id": 0,
             "device_kind": "tpu", "current_bytes": (8 + rank) << 30,
             "peak_bytes": (9 + rank) << 30,
             "step_peak_bytes": (9 + rank) << 30,
             "limit_bytes": 16 << 30, "backend": "fake"}
        ]}, s.ident(rank, world=n))
        s.inject("process", {"process": [
            {"timestamp": 60.0, "pid": 100 + rank,
             "cpu_pct": 40.0 + rank * 10, "rss_bytes": (1 + rank) << 30,
             "num_threads": 5}
        ]}, s.ident(rank, world=n))
    return s.payload()


def test_step_time_rollup_has_median_and_worst_rank_attribution(tmp_path):
    payload = _multirank_session(tmp_path)
    rollup = payload["sections"]["step_time"]["global"]["rollup"]
    assert rollup["index_by"] == "global_rank"
    assert rollup["window"]["alignment"] == "common_steps"
    assert rollup["window"]["steps_analyzed"] > 0
    step = rollup["worst"]["step_time"]
    # rank 3 is slowest by construction; median idx must name a real rank
    assert step["idx"] == "3" and step["value"] > 150
    med = rollup["median"]["step_time"]
    assert med["idx"] in {"1", "2"} and med["value"] is not None
    assert rollup["average"]["step_time"] is not None


def test_step_time_card_has_stats_and_ranks_lines(tmp_path):
    payload = _multirank_session(tmp_path)
    card = payload["sections"]["step_time"]["card"]
    assert "stats (median/worst):" in card
    assert "ranks (median/worst):" in card
    # both ends name a concrete rank (rN/rM)
    import re
    assert re.search(r"step_time r\d+/r3", card), card


def test_step_memory_rollup_points(tmp_path):
    payload = _multirank_session(tmp_path)
    rollup = payload["sections"]["step_memory"]["global"]["rollup"]
    worst = rollup["worst"]["step_peak_bytes"]
    assert worst["idx"] == "3" and worst["value"] == (12 << 30)
    assert rollup["median"]["step_peak_bytes"]["idx"] is not None
    # pre-existing rollup fields retained alongside the uniform block
    assert rollup["max_peak_bytes"] == (12 << 30)


def test_process_rollup_points(tmp_path):
    payload = _multirank_session(tmp_path)
    rollup = payload["sections"]["process"]["global"]["rollup"]
    assert rollup["worst"]["rss_bytes"]["idx"] == "3"
    assert rollup["busiest_rank"] == "3"


def test_rollup_handles_missing_and_nonfinite():
    from traceml_tpu.reporting.rollup import build_rollup

    r = build_rollup({
        "m": {"0": 1.0, "1": float("nan"), "2": None, "3": 3.0},
        "empty": {},
    })
    assert r["worst"]["m"] == {"value": 3.0, "idx": "3"}
    assert r["average"]["m"] == 2.0
    assert r["median"]["empty"] == {"value": None, "idx": None}


def test_rollup_tie_breaks_deterministic():
    from traceml_tpu.reporting.rollup import build_rollup

    r = build_rollup({"m": {"5": 2.0, "1": 2.0, "3": 2.0}})
    # equal values: worst → smallest rank id; median idx likewise stable
    assert r["worst"]["m"]["idx"] == "1"
    assert r["median"]["m"]["idx"] == "1"


def test_tokens_per_step_flows_to_efficiency(tmp_path):
    """set_step_tokens → model_stats row → SQLite → loader → the
    efficiency block's tokens_per_sec_median (full pipeline)."""
    s = _Session(tmp_path)
    s.inject("step_time",
             {"step_time": [_step_row(i, step_ms=100.0) for i in range(1, 61)]},
             s.ident(0))
    s.inject("step_time", {"model_stats": [
        {"timestamp": 1.0, "flops_per_step": 50e12,
         "flops_source": "manual", "device_kind": "TPU v5p",
         "peak_flops": 459e12, "device_count": 1,
         "tokens_per_step": 8192.0}
    ]}, s.ident(0))
    payload = s.payload()
    eff = payload["sections"]["step_time"]["global"]["efficiency"]
    assert eff["tokens_per_step"] == 8192.0
    # steady-state median step is 100 ms → 81,920 tokens/s
    assert abs(eff["tokens_per_sec_median"] - 81920.0) < 1.0
    assert "tokens/s" in payload["sections"]["step_time"]["card"]
