"""The real ``traceml_tpu`` tree must pass its own linter, and the
runner's exit-code / JSON / baseline contract is what CI keys off."""

from __future__ import annotations

import io
import json
from pathlib import Path

from traceml_tpu.analysis.common import load_baseline
from traceml_tpu.analysis.runner import (
    default_baseline_path,
    default_package_root,
    run_lint,
    run_passes,
    summarize,
)

REPO_ROOT = Path(__file__).resolve().parents[2]


def test_real_tree_is_clean_against_checked_in_baseline():
    root = default_package_root()
    findings = run_passes(root)
    baseline = load_baseline(default_baseline_path(root))
    summary = summarize(findings, baseline)
    new = [
        f.format_text()
        for f in findings
        if f.severity == "error" and not f.suppressed and f.key not in baseline
    ]
    assert summary["new_errors"] == [], "un-baselined lint errors:\n" + "\n".join(new)
    # the baseline is a tolerance list, not a dumping ground: keep it to
    # a handful of triaged keys and never let it go stale
    assert len(baseline) <= 8, sorted(baseline)
    assert summary["stale_baseline_keys"] == []


def test_real_tree_suppressions_all_carry_reasons():
    findings = run_passes(default_package_root())
    suppressed = [f for f in findings if f.suppressed]
    assert suppressed, "expected the known inline unguarded() suppressions"
    for f in suppressed:
        assert f.suppress_reason and f.suppress_reason.strip(), f.format_text()


def test_run_lint_exit_codes_and_json(tmp_path, capsys):
    # a tiny real package with one planted race error
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("", encoding="utf-8")
    (pkg / "racy.py").write_text(
        "import threading\n"
        "\n"
        "\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.n = 0\n"
        "\n"
        "    def _locked(self):\n"
        "        with self._lock:\n"
        "            self.n += 1\n"
        "\n"
        "    def bump(self):\n"
        "        self.n += 1\n",
        encoding="utf-8",
    )
    baseline_path = tmp_path / "baseline.json"

    out = io.StringIO()
    rc = run_lint(
        package_root=pkg,
        passes=["race"],
        fmt="json",
        baseline_path=baseline_path,
        out=out,
    )
    assert rc == 1
    payload = json.loads(out.getvalue())
    assert payload["counts"]["errors"] == 1
    assert payload["counts"]["new_errors"] == 1
    assert len(payload["new_error_keys"]) == 1
    assert payload["new_error_keys"][0].startswith("TLR001:")

    # --update-baseline writes the key and exits 0
    out = io.StringIO()
    rc = run_lint(
        package_root=pkg,
        passes=["race"],
        baseline_path=baseline_path,
        update_baseline=True,
        out=out,
    )
    assert rc == 0
    assert set(load_baseline(baseline_path)) == set(payload["new_error_keys"])

    # with the baseline in place the same tree now gates clean
    out = io.StringIO()
    rc = run_lint(
        package_root=pkg, passes=["race"], baseline_path=baseline_path, out=out
    )
    assert rc == 0
    assert "[baselined]" in out.getvalue()

    # a missing package root is an analyzer failure, not "clean"
    assert run_lint(package_root=tmp_path / "nope", out=io.StringIO()) == 2


def test_run_lint_reports_stale_baseline_keys(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "clean.py").write_text("x = 1\n", encoding="utf-8")
    baseline_path = tmp_path / "baseline.json"
    baseline_path.write_text(
        json.dumps({"keys": {"TLR001:pkg/gone.py:C.m:attr": "fixed ages ago"}}),
        encoding="utf-8",
    )
    out = io.StringIO()
    rc = run_lint(
        package_root=pkg, passes=["race"], baseline_path=baseline_path, out=out
    )
    assert rc == 0
    assert "no longer fire" in out.getvalue()
