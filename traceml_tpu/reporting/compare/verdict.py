"""Priority verdict ladder
(reference: src/traceml_ai/reporting/compare/verdict.py:24-38 — a
sequential priority order chooses the PRIMARY finding; rebuilt against
our section-comparison shapes).

Ladder (first matching rung wins):

1. ``INSUFFICIENT_DATA``  — the primary signal (step time) is missing on
   both sides or too small a window on either;
2. ``PARTIAL_DATA``       — step time present, but a side lost a whole
   section (degraded run) — comparison continues, flagged;
3. ``REGRESSION``         — a major regression finding in step time,
   memory, or a diagnosis transition to a pathological state;
4. ``LIKELY_REGRESSION``  — minor regression findings only;
5. ``IMPROVEMENT``        — major improvement with no regression signal;
6. ``MIXED``              — significant findings pulling both ways;
7. ``EQUIVALENT``         — nothing significant anywhere.

Confidence weighting (VERDICT r4 item 9): findings that carry an
evidence-derived confidence label argue at reduced strength when that
label is "low" — a low-confidence major counts as minor in the ladder,
and ONLY regressions held with ≥medium confidence (or statistical
findings, which carry no label) can force MIXED against a major
improvement.  The demoted findings still appear in the ranked list,
sorted below confident peers of the same tier.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

from traceml_tpu.reporting.compare.sections import (
    INSUFFICIENT,
    MISSING_BASELINE,
    MISSING_CANDIDATE,
    NO_DATA,
    OK,
    SectionComparison,
)

_REGRESSION_KINDS = (
    "STEP_TIME_REGRESSION",
    "MEMORY_REGRESSION",
    "DIAGNOSIS_REGRESSION",
    "MEMORY_IMBALANCE_GREW",
    "RANK_DIVERGENCE",
    "PROCESS_RSS_GREW",
)
_IMPROVEMENT_KINDS = ("STEP_TIME_IMPROVEMENT", "MEMORY_IMPROVEMENT", "PROCESS_RSS_SHRANK")

# findings are ranked for display: regressions > improvements > context,
# major before minor within each class, low confidence last within a tier
_CLASS_ORDER = {"regression": 0, "improvement": 1, "context": 2}


def _finding_class(f: Dict[str, Any]) -> str:
    kind = f.get("kind", "")
    if kind in _REGRESSION_KINDS:
        return "regression"
    if kind in _IMPROVEMENT_KINDS:
        return "improvement"
    return "context"


def _effective_significance(f: Dict[str, Any]) -> str:
    """Significance weighted by evidence confidence: a major finding the
    engine itself only holds with LOW confidence argues like a minor one
    in the ladder (VERDICT r4 item 9 — an uncertain
    DIAGNOSIS_REGRESSION must not outrank a solid
    STEP_TIME_IMPROVEMENT).  Findings without a confidence label
    (statistical delta findings) keep their significance untouched."""
    sig = f.get("significance", "minor")
    if sig == "major" and f.get("confidence_label") == "low":
        return "minor"
    return sig


def rank_findings(findings: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    return sorted(
        findings,
        key=lambda f: (
            _CLASS_ORDER[_finding_class(f)],
            _effective_significance(f) != "major",
            f.get("confidence_label") == "low",
            f.get("section", ""),
        ),
    )


def decide_verdict(
    sections: Dict[str, SectionComparison],
    diagnosis_findings: List[Dict[str, Any]],
) -> Tuple[str, List[Dict[str, Any]]]:
    """(verdict, ranked findings) from the section comparisons."""
    all_findings: List[Dict[str, Any]] = list(diagnosis_findings)
    for comp in sections.values():
        all_findings.extend(comp.findings)
    ranked = rank_findings(all_findings)

    step = sections.get("step_time")
    # rung 1: primary signal unusable
    if step is None or step.status in (NO_DATA, INSUFFICIENT) or (
        step.status in (MISSING_BASELINE, MISSING_CANDIDATE)
    ):
        if step is not None and step.status == INSUFFICIENT:
            return "INSUFFICIENT_DATA", ranked
        if step is None or step.status == NO_DATA:
            return "INSUFFICIENT_DATA", ranked
        return "PARTIAL_DATA", ranked

    # rung 2: a secondary section lost a side
    partial = any(
        comp.status in (MISSING_BASELINE, MISSING_CANDIDATE)
        for name, comp in sections.items()
        if name != "step_time"
    )

    majors_reg = [
        f
        for f in ranked
        if _finding_class(f) == "regression"
        and _effective_significance(f) == "major"
    ]
    minors_reg = [f for f in ranked if _finding_class(f) == "regression"]
    # regressions the engine holds with at least medium confidence (or
    # no label at all — statistical findings): only these can force
    # MIXED against a major improvement
    confident_reg = [
        f for f in minors_reg if f.get("confidence_label") != "low"
    ]
    majors_imp = [
        f
        for f in ranked
        if _finding_class(f) == "improvement"
        and _effective_significance(f) == "major"
    ]
    improvements = [f for f in ranked if _finding_class(f) == "improvement"]

    step_major_reg = any(
        f.get("kind") == "STEP_TIME_REGRESSION"
        and _effective_significance(f) == "major"
        for f in ranked
    )
    step_major_imp = any(
        f.get("kind") == "STEP_TIME_IMPROVEMENT"
        and _effective_significance(f) == "major"
        for f in ranked
    )
    # the primary signal (step time) dominates; majors pulling against
    # it read as MIXED, not as whichever class sorts first
    if step_major_reg:
        verdict = "REGRESSION"
    elif majors_reg and step_major_imp:
        verdict = "MIXED"
    elif majors_reg:
        verdict = "REGRESSION"
    elif confident_reg and improvements:
        verdict = "MIXED"
    elif minors_reg and majors_imp:
        # only low-confidence regressions oppose a major improvement:
        # the improvement wins, the regressions stay listed below it
        verdict = "IMPROVEMENT"
    elif minors_reg and improvements:
        verdict = "MIXED"
    elif minors_reg:
        verdict = "LIKELY_REGRESSION"
    elif majors_imp:
        verdict = "IMPROVEMENT"
    elif improvements:
        verdict = "LIKELY_IMPROVEMENT"
    elif any(f.get("significance") == "major" for f in ranked):
        verdict = "MIXED"
    elif partial:
        verdict = "PARTIAL_DATA"
    else:
        verdict = "EQUIVALENT"
    return verdict, ranked


def verdict_is_usable(sections: Dict[str, SectionComparison]) -> bool:
    step = sections.get("step_time")
    return step is not None and step.status == OK
