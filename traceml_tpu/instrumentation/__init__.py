"""Instrumentation: measurement hooks around user training code
(reference: src/traceml_ai/instrumentation/)."""
