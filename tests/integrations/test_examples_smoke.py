"""Runnable examples don't rot: each script executes end-to-end on the
virtual CPU mesh in a subprocess (its own interpreter — examples call
init() and own their global state).  Scripts with heavyweight deps
(HF Trainer download, ray) or their own dedicated tests (lightning,
ddp via launcher e2e) are excluded.
"""

import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[2]

EXAMPLES = [
    "examples/quickstart/flax_minimal.py",
    "examples/quickstart/pytorch_minimal.py",
    "examples/distributed/sharded_llm.py",
    "examples/distributed/ring_attention_demo.py",
    "examples/distributed/moe_pipeline.py",
    "examples/advanced/grad_accum_mfu.py",
]


@pytest.mark.parametrize("script", EXAMPLES)
def test_example_runs(script, tmp_path):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "PYTHONPATH": str(REPO),
        # examples default to small loops; keep artifacts out of the repo
        "TRACEML_LOGS_DIR": str(tmp_path),
    })
    proc = subprocess.run(
        [sys.executable, str(REPO / script)],
        env=env, cwd=str(tmp_path), timeout=420,
        capture_output=True, text=True,
    )
    assert proc.returncode == 0, (
        f"{script} failed:\n{proc.stdout[-1500:]}\n{proc.stderr[-1500:]}"
    )
