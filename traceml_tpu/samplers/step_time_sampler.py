"""Step-time sampler
(reference: src/traceml_ai/samplers/step_time_sampler.py:33-169).

Drains the global step queue, resolves device markers **in step order**
(FIFO: a later step never emits before an earlier one — the window
builder depends on contiguous step rows), and aggregates each step's
events into ONE row:

    {step, timestamp, events: {name: {cpu_ms, device_ms, count}},
     clock: "device"|"host"}

Device durations come from consecutive readiness edges (serial TPU
execution — see utils/timing.py): for the events of one step ordered by
host start,

    device_ms(e) = ready(e) − max(ready(prev_marked), cpu_start(e))

and the ``step_time`` envelope's device duration is the span from its
host start to the LAST readiness edge in the step.

An unresolved step blocks emission (keeps FIFO) until
``resolve_timeout_s``; on timeout the step emits host-only (fail-open,
matches the reference's behavior when CUDA events never resolve).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from traceml_tpu.samplers.base_sampler import BaseSampler
from traceml_tpu.utils.timing import (
    GLOBAL_STEP_QUEUE,
    STEP_TIME,
    StepTimeBatch,
    TimeEvent,
)

TABLE = "step_time"
MODEL_STATS_TABLE = "model_stats"
_RESOLVE_TIMEOUT_S = 10.0


def _aggregate_step(
    events: List[TimeEvent], prev_last_ready: Optional[float] = None
) -> tuple:
    """One step's events → (aggregate row, last readiness edge).

    ``prev_last_ready`` is the previous STEP's final readiness edge.
    Under async dispatch the host runs ahead of the device, so step N's
    device work begins when step N−1's work retires — not at step N's
    host start.  Carrying the edge across steps turns dispatch-to-
    completion spans into true device occupancy (the CUDA analogue: an
    event pair brackets stream work regardless of when the host enqueued
    it).  The FIFO emission order of the sampler makes this well-defined.
    """
    # Events arrive in host-issue order in the common case (the SDK
    # appends as the step executes) — detect that in one pass and skip
    # the per-step sort + list copy entirely.
    ordered = events
    for i in range(1, len(events)):
        if events[i].cpu_start < events[i - 1].cpu_start:
            ordered = sorted(events, key=lambda e: e.cpu_start)
            break
    # Late stamps (shutdown drain / timeout) carry observation times far
    # from the true completion — their device durations would be fiction,
    # so they are excluded and counted instead.
    late_markers = sum(
        1 for e in ordered if e.marker is not None and e.marker.late_stamp
    )
    prev_ready: Optional[float] = prev_last_ready
    device_ms: Dict[int, float] = {}
    last_ready: Optional[float] = prev_last_ready
    for i, ev in enumerate(ordered):
        if ev.name == STEP_TIME:
            continue  # envelope handled after the last edge is known
        if ev.marker is not None and ev.marker.late_stamp:
            continue
        ready = ev.device_ready_at
        if ready is None:
            continue
        start_edge = ev.cpu_start if prev_ready is None else max(prev_ready, ev.cpu_start)
        device_ms[i] = max(0.0, (ready - start_edge) * 1000.0)
        prev_ready = ready
        last_ready = ready

    agg: Dict[str, Dict[str, Any]] = {}
    have_device = False
    for i, ev in enumerate(ordered):
        if ev.cpu_ms is None:
            continue
        d_ms: Optional[float] = None
        if ev.name == STEP_TIME:
            if ev.marker is not None and ev.marker.late_stamp:
                d_ms = None
            elif ev.device_ready_at is not None:
                start_edge = ev.cpu_start
                if prev_last_ready is not None:
                    start_edge = max(start_edge, prev_last_ready)
                d_ms = max(0.0, (ev.device_ready_at - start_edge) * 1000.0)
            elif last_ready is not None and last_ready != prev_last_ready:
                d_ms = max(ev.cpu_ms, (last_ready - ev.cpu_start) * 1000.0)
        else:
            d_ms = device_ms.get(i)
        # get-then-insert instead of setdefault: setdefault builds a
        # fresh dict literal per EVENT even when the slot already exists
        # (hot path — every event of every step passes through here)
        slot = agg.get(ev.name)
        if slot is None:
            slot = agg[ev.name] = {"cpu_ms": 0.0, "device_ms": None, "count": 0}
        slot["cpu_ms"] += ev.cpu_ms
        slot["count"] += 1
        if d_ms is not None:
            slot["device_ms"] = (slot["device_ms"] or 0.0) + d_ms
            have_device = True
        if ev.meta:
            slot.setdefault("meta", {}).update(ev.meta)
    row = {"events": agg, "clock": "device" if have_device else "host"}
    if late_markers:
        row["late_markers"] = late_markers
    return row, last_ready


class StepTimeSampler(BaseSampler):
    name = "step_time"

    def __init__(self, *args: Any, resolve_timeout_s: float = _RESOLVE_TIMEOUT_S, **kw: Any):
        super().__init__(*args, **kw)
        self._pending: List[StepTimeBatch] = []
        self._resolve_timeout = resolve_timeout_s
        self._last_ready: Optional[float] = None  # cross-step device edge
        self._flops_sent: Optional[float] = None
        self.steps_emitted = 0
        self.steps_timed_out = 0

    def _publish_model_stats(self) -> None:
        """One MODEL_STATS row whenever the declared/estimated per-step
        FLOPs change (the MFU numerator, shipped once — not per step)."""
        try:
            from traceml_tpu.sdk.state import get_state
            from traceml_tpu.utils.chip_specs import peak_flops_for

            st = get_state()
            flops = st.flops_per_step
            # keyed on the full declaration: a device_kind correction
            # with unchanged FLOPs must still republish
            sent_key = (
                flops, st.flops_source, st.flops_device_kind,
                st.flops_device_count, st.tokens_per_step,
            )
            if (
                flops is None and st.tokens_per_step is None
            ) or sent_key == self._flops_sent:
                return
            self._flops_sent = sent_key
            self.db.add_record(
                MODEL_STATS_TABLE,
                {
                    "timestamp": time.time(),
                    "flops_per_step": (
                        float(flops) if flops is not None else None
                    ),
                    "flops_source": st.flops_source,
                    "device_kind": st.flops_device_kind,
                    "peak_flops": peak_flops_for(st.flops_device_kind),
                    "device_count": st.flops_device_count,
                    "tokens_per_step": st.tokens_per_step,
                },
            )
        except Exception:
            pass  # fail-open: MFU is garnish, never breaks sampling

    def _sample(self) -> None:
        self._publish_model_stats()
        self._pending.extend(GLOBAL_STEP_QUEUE.drain())
        now = time.perf_counter()
        emit_upto = 0
        for batch in self._pending:
            if batch.resolved():
                emit_upto += 1
            elif now - batch.flushed_at > self._resolve_timeout:
                self.steps_timed_out += 1
                batch.force_resolve()  # stamps flagged late → host-only row
                emit_upto += 1
            else:
                break  # FIFO: wait for the earliest unresolved step
        for batch in self._pending[:emit_upto]:
            row, self._last_ready = _aggregate_step(batch.events, self._last_ready)
            row["step"] = batch.step
            row["timestamp"] = time.time()
            self.db.add_record(TABLE, row)
            self.steps_emitted += 1
        del self._pending[:emit_upto]

    def drain(self) -> None:
        """End-of-run: give the fine-cadence resolver one last bounded
        window, then stamp leftovers as late and emit."""
        from traceml_tpu.utils.marker_resolver import get_marker_resolver

        self._publish_model_stats()

        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            self._pending.extend(GLOBAL_STEP_QUEUE.drain())
            get_marker_resolver().sweep_inline(max_n=1024)
            if all(b.resolved() for b in self._pending):
                break
            time.sleep(0.02)
        for batch in self._pending:
            batch.force_resolve()
        for batch in self._pending:
            row, self._last_ready = _aggregate_step(batch.events, self._last_ready)
            row["step"] = batch.step
            row["timestamp"] = time.time()
            self.db.add_record(TABLE, row)
            self.steps_emitted += 1
        self._pending.clear()
