"""Live view renderers (reference: src/traceml_ai/renderers/)."""
