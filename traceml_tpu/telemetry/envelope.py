"""Telemetry envelope (reference: src/traceml_ai/telemetry/envelope.py:92-166).

Canonical shape on the wire::

    {
      "meta": {
        "schema": 1 | 2,
        "session_id": str,
        "sampler": str,                # e.g. "step_time"
        "timestamp": float,            # sender host unix time
        "rank": int,                   # == global_rank (back-compat alias)
        "global_rank": int,
        "local_rank": int,
        "world_size": int,
        "local_world_size": int,
        "node_rank": int,
        "hostname": str,
        "pid": int,
        "platform": str,               # "tpu" | "cpu" | "gpu"
        "device_kind": str,            # e.g. "TPU v5p"
        "seq": int,                    # per-rank monotonic (optional;
                                       # durable-replay dedup key)
      },
      "body": {"tables": {table_name: <table>}}
    }

Two table encodings are negotiated per-envelope via ``meta.schema``
(see docs/developer_guide/wire-schema-v2.md for the full layout):

* **schema 1 (row-list)** — ``[ {k: v, ...}, ... ]``: one dict per row,
  every string key repeated per row.
* **schema 2 (columnar / struct-of-arrays)** —
  ``{"cols": [k1, k2, ...], "vals": [[...], [...], ...]}``: keys encoded
  once per batch; ``vals[j]`` is the value array for column ``cols[j]``
  (missing keys are ``None``-filled).  This is what
  ``DBIncrementalSender`` ships — it removes the dominant per-row key
  bytes from the wire.

``normalize_telemetry_envelope`` accepts the canonical shape (either
table encoding, even mixed per-table), plus a legacy flat shape
``{"sampler":..., "tables":...}`` and always returns a canonical
:class:`TelemetryEnvelope`.  Columnar tables are kept columnar — the
``tables`` property materializes row dicts lazily, and the aggregator's
SQLite writers consume :meth:`TelemetryEnvelope.column_view` directly
without ever building per-row dicts.
"""

from __future__ import annotations

import dataclasses
import socket
import os
import time
from typing import Any, Dict, List, Mapping, Optional, Tuple

SCHEMA_VERSION = 1
SCHEMA_V2 = 2


@dataclasses.dataclass(frozen=True)
class SenderIdentity:
    """Identity attached to every envelope a rank emits
    (reference: runtime/identity.py:88-131; extended with TPU fields)."""

    session_id: str = "unknown"
    global_rank: int = 0
    local_rank: int = 0
    world_size: int = 1
    local_world_size: int = 1
    node_rank: int = 0
    hostname: str = dataclasses.field(default_factory=socket.gethostname)
    pid: int = dataclasses.field(default_factory=os.getpid)
    platform: str = "cpu"
    device_kind: str = "unknown"

    def to_meta(self) -> Dict[str, Any]:
        return {
            "schema": SCHEMA_VERSION,
            "session_id": self.session_id,
            "rank": self.global_rank,
            "global_rank": self.global_rank,
            "local_rank": self.local_rank,
            "world_size": self.world_size,
            "local_world_size": self.local_world_size,
            "node_rank": self.node_rank,
            "hostname": self.hostname,
            "pid": self.pid,
            "platform": self.platform,
            "device_kind": self.device_kind,
        }


# -- columnar (struct-of-arrays) table helpers ---------------------------

# Reserved marker key for a nested struct-of-arrays column: a column whose
# rows are dicts with an IDENTICAL key set (e.g. step_time "events") is
# encoded as {"\x00soa": [keys, [subcol, ...]]}, recursively — the inner
# keys hit the wire once per batch instead of once per row.  A single-key
# dict with this NUL-prefixed key cannot occur in sampler rows.
SOA_KEY = "\x00soa"


def _same_key_dicts(cells: List[Any]) -> Optional[List[str]]:
    """Key list when every cell is a dict with the same key set, else None."""
    if not cells or not isinstance(cells[0], dict):
        return None
    first = cells[0]
    for c in cells[1:]:
        if not isinstance(c, dict) or c.keys() != first.keys():
            return None
    return [str(k) for k in first]


def _encode_cells(cells: List[Any]) -> Any:
    keys = _same_key_dicts(cells)
    if keys is None:
        return cells
    return {
        SOA_KEY: [keys, [_encode_cells([c[k] for c in cells]) for k in keys]]
    }


def _decode_cells(col: Any, n: int) -> List[Any]:
    if isinstance(col, dict):
        marker = col.get(SOA_KEY)
        if (
            isinstance(marker, (list, tuple))
            and len(marker) == 2
            and isinstance(marker[0], list)
            and isinstance(marker[1], list)
        ):
            keys, subcols = marker
            if len(keys) == len(subcols):
                decoded = [_decode_cells(s, n) for s in subcols]
                return [
                    {keys[j]: decoded[j][i] for j in range(len(keys))}
                    for i in range(n)
                ]
        return [None] * n  # malformed nested column → null it out
    return col


def rows_to_columns(rows: List[Mapping[str, Any]]) -> Dict[str, Any]:
    """``[{k: v}, ...]`` → ``{"cols": [...], "vals": [...], "n": N}``.

    Column order is first-appearance order across the batch; rows missing
    a key get ``None`` in that column (telemetry consumers treat absent
    and ``None`` identically).  Dict-valued columns with a uniform key
    set are recursively transposed (see :data:`SOA_KEY`).
    """
    cols: List[str] = []
    index: Dict[str, int] = {}
    for row in rows:
        for k in row:
            if k not in index:
                index[k] = len(cols)
                cols.append(k)
    n = len(rows)
    vals: List[Any] = [[None] * n for _ in cols]
    for i, row in enumerate(rows):
        for k, v in row.items():
            vals[index[k]][i] = v
    return {"cols": cols, "vals": [_encode_cells(col) for col in vals], "n": n}


def encode_columns(table: Mapping[str, Any]) -> Dict[str, Any]:
    """Raw accumulated columns → wire columnar table.

    ``table`` is ``{"cols": [...], "vals": [...], "n": N}`` with plain
    value lists (what ``Database.collect_columns`` hands over); this
    applies the nested struct-of-arrays pass (:data:`SOA_KEY`) per
    column, producing exactly what :func:`rows_to_columns` would have
    built from the same batch of rows — without ever materializing the
    row dicts.
    """
    return {
        "cols": list(table["cols"]),
        "vals": [_encode_cells(col) for col in table["vals"]],
        "n": table["n"],
    }


def columns_to_rows(table: Mapping[str, Any]) -> List[Dict[str, Any]]:
    """Materialize row dicts from a columnar table (inverse of
    :func:`rows_to_columns` for batches with uniform keys)."""
    cols = table.get("cols") or []
    vals = table.get("vals") or []
    n = _columnar_n(table)
    decoded = [_decode_cells(col, n) for col in vals]
    return [{cols[j]: decoded[j][i] for j in range(len(cols))} for i in range(n)]


def _columnar_n(table: Mapping[str, Any]) -> int:
    n = table.get("n")
    if isinstance(n, int) and n >= 0:
        return n
    for col in table.get("vals") or ():
        if isinstance(col, list):
            return len(col)
    return 0


def is_columnar_table(obj: Any) -> bool:
    return (
        isinstance(obj, Mapping)
        and isinstance(obj.get("cols"), list)
        and isinstance(obj.get("vals"), list)
    )


def _validate_columnar(obj: Mapping[str, Any]) -> Optional[Dict[str, Any]]:
    """Sanitize a wire columnar table; None when structurally invalid."""
    cols = obj.get("cols")
    vals = obj.get("vals")
    if not isinstance(cols, list) or not isinstance(vals, list):
        return None
    if len(cols) != len(vals):
        return None
    n = obj.get("n") if isinstance(obj.get("n"), int) else None
    for col in vals:
        if isinstance(col, list):
            if n is None:
                n = len(col)
            elif len(col) != n:
                return None
        elif not isinstance(col, Mapping):
            return None  # nested SoA columns are dicts; anything else is junk
    if n is None:
        n = 0 if not vals else None
    if n is None or n < 0:
        return None
    return {"cols": [str(c) for c in cols], "vals": vals, "n": n}


def _to_float(v: Any) -> Optional[float]:
    if v is None:
        return None
    try:
        return float(v)
    except (TypeError, ValueError):
        return None


def _to_int(v: Any) -> Optional[int]:
    if v is None:
        return None
    try:
        return int(v)
    except (TypeError, ValueError):
        return None


class ColumnView:
    """Read-only columnar view over one table, regardless of how it
    arrived on the wire (v2 columns directly; v1 row dicts via a single
    transpose).  SQLite writers build parameter tuples from these column
    lists instead of per-row dict lookups.

    Truthiness is "has at least one row", so writers can guard with a
    plain ``if view:``.
    """

    __slots__ = ("_idx", "_vals", "_n")

    def __init__(
        self, cols: List[str], vals: List[Any], n: Optional[int] = None
    ) -> None:
        self._idx = {k: j for j, k in enumerate(cols)}
        self._vals = vals
        if n is None:
            n = 0
            for col in vals:
                if isinstance(col, list):
                    n = len(col)
                    break
        self._n = n

    @classmethod
    def from_rows(cls, rows: List[Mapping[str, Any]]) -> "ColumnView":
        ct = rows_to_columns(rows)
        return cls(ct["cols"], ct["vals"], ct["n"])

    def __len__(self) -> int:
        return self._n

    def col(self, key: str) -> List[Any]:
        """Raw value column (nested SoA columns are materialized back to
        per-row dicts); ``None``-filled when the column is absent."""
        j = self._idx.get(key)
        if j is None:
            return [None] * self._n
        return _decode_cells(self._vals[j], self._n)

    def floats(self, key: str) -> List[Optional[float]]:
        return [_to_float(v) for v in self.col(key)]

    def ints(self, key: str) -> List[Optional[int]]:
        return [_to_int(v) for v in self.col(key)]

    def strs(self, key: str, default: str = "") -> List[str]:
        return [default if v is None else str(v) for v in self.col(key)]


class TelemetryEnvelope:
    """Canonical in-memory envelope.

    Holds tables as row-lists (``tables=``), columnar tables
    (``columns=``), or both (a mixed canonical wire payload).  ``tables``
    materializes row dicts lazily and caches; :meth:`column_view` serves
    the aggregator hot path without materializing rows for v2 input.
    """

    __slots__ = ("meta", "_rows", "_columns", "_cache")

    def __init__(
        self,
        meta: Dict[str, Any],
        tables: Optional[Dict[str, List[Dict[str, Any]]]] = None,
        columns: Optional[Dict[str, Dict[str, Any]]] = None,
    ) -> None:
        self.meta = meta
        self._rows = tables
        self._columns = columns
        self._cache: Optional[Dict[str, List[Dict[str, Any]]]] = None

    @property
    def sampler(self) -> str:
        return str(self.meta.get("sampler", "unknown"))

    @property
    def global_rank(self) -> int:
        return int(self.meta.get("global_rank", self.meta.get("rank", 0)))

    @property
    def schema(self) -> int:
        try:
            return int(self.meta.get("schema", SCHEMA_VERSION))
        except (TypeError, ValueError):
            return SCHEMA_VERSION

    @property
    def seq(self) -> Optional[int]:
        """Per-rank monotonic sequence number stamped by the publisher
        (durable-replay dedup; docs/developer_guide/fault-tolerance.md).
        None for pre-seq producers — those envelopes bypass dedup."""
        v = self.meta.get("seq")
        if v is None:
            return None
        try:
            return int(v)
        except (TypeError, ValueError):
            return None

    @property
    def tables(self) -> Dict[str, List[Dict[str, Any]]]:
        if self._cache is None:
            if not self._columns:
                self._cache = self._rows if self._rows is not None else {}
            else:
                merged = {k: columns_to_rows(v) for k, v in self._columns.items()}
                if self._rows:
                    merged.update(self._rows)
                self._cache = merged
        return self._cache

    def table_names(self) -> List[str]:
        names = list(self._rows or ())
        for k in self._columns or ():
            if k not in names:
                names.append(k)
        return names

    def column_view(self, name: str) -> Optional[ColumnView]:
        """Columnar view of one table, or None when absent.  v2 tables
        are served zero-copy; v1 row-lists pay one transpose."""
        if self._columns is not None:
            ct = self._columns.get(name)
            if ct is not None:
                return ColumnView(ct["cols"], ct["vals"], _columnar_n(ct))
        rows = (self._rows or {}).get(name)
        if rows is None:
            return None
        return ColumnView.from_rows(rows)

    def table_columns(self, name: str) -> Optional[Tuple[List[str], List[List[Any]]]]:
        """Raw ``(cols, vals)`` when the table arrived columnar, else None."""
        if self._columns is None:
            return None
        ct = self._columns.get(name)
        if ct is None:
            return None
        return ct["cols"], ct["vals"]

    def to_wire(self) -> Dict[str, Any]:
        tables: Dict[str, Any] = {}
        if self._columns:
            tables.update(self._columns)
        if self._rows:
            tables.update(self._rows)
        return {"meta": dict(self.meta), "body": {"tables": tables}}


def build_telemetry_envelope(
    sampler: str,
    tables: Mapping[str, List[Dict[str, Any]]],
    identity: Optional[SenderIdentity] = None,
    timestamp: Optional[float] = None,
    copy: bool = True,
) -> TelemetryEnvelope:
    """Schema-1 (row-list) envelope.  ``copy=False`` is for trusted
    internal callers whose row lists are already fresh snapshots — it
    skips the defensive per-table list copy."""
    identity = identity or SenderIdentity()
    meta = identity.to_meta()
    meta["sampler"] = sampler
    meta["timestamp"] = time.time() if timestamp is None else timestamp
    if copy:
        body = {str(k): list(v) for k, v in tables.items()}
    else:
        body = dict(tables)
    return TelemetryEnvelope(meta=meta, tables=body)


def build_columnar_envelope(
    sampler: str,
    tables: Mapping[str, List[Dict[str, Any]]],
    identity: Optional[SenderIdentity] = None,
    timestamp: Optional[float] = None,
) -> TelemetryEnvelope:
    """Schema-2 (columnar) envelope: each table transposed to
    struct-of-arrays so string keys hit the wire once per batch."""
    identity = identity or SenderIdentity()
    meta = identity.to_meta()
    meta["schema"] = SCHEMA_V2
    meta["sampler"] = sampler
    meta["timestamp"] = time.time() if timestamp is None else timestamp
    return TelemetryEnvelope(
        meta=meta,
        columns={str(k): rows_to_columns(v) for k, v in tables.items()},
    )


def build_columnar_envelope_from_columns(
    sampler: str,
    tables: Mapping[str, Mapping[str, Any]],
    identity: Optional[SenderIdentity] = None,
    timestamp: Optional[float] = None,
) -> TelemetryEnvelope:
    """Schema-2 envelope from **wire-ready columnar tables** (already
    nested-SoA encoded — see :func:`encode_columns`).  The producer fast
    path: no row dicts exist at any point between ``add_record`` and the
    wire."""
    identity = identity or SenderIdentity()
    meta = identity.to_meta()
    meta["schema"] = SCHEMA_V2
    meta["sampler"] = sampler
    meta["timestamp"] = time.time() if timestamp is None else timestamp
    return TelemetryEnvelope(meta=meta, columns=dict(tables))


def _split_wire_tables(
    tables: Mapping[str, Any],
) -> Tuple[Dict[str, List[Dict[str, Any]]], Optional[Dict[str, Dict[str, Any]]]]:
    rows_t: Dict[str, List[Dict[str, Any]]] = {}
    cols_t: Dict[str, Dict[str, Any]] = {}
    for k, v in tables.items():
        if isinstance(v, list):
            rows_t[str(k)] = list(v)
        elif is_columnar_table(v):
            ct = _validate_columnar(v)
            if ct is not None:
                cols_t[str(k)] = ct
    return rows_t, (cols_t or None)


def normalize_telemetry_envelope(payload: Any) -> Optional[TelemetryEnvelope]:
    """Coerce a decoded wire payload into a canonical envelope.

    Accepts schema-1 row-list tables, schema-2 columnar tables (even
    mixed within one envelope), and the legacy flat shape.  Returns None
    for payloads that are not telemetry (e.g. control messages, garbage)
    — the caller decides what to do with those.
    """
    if not isinstance(payload, Mapping):
        return None
    if "meta" in payload and "body" in payload:
        meta = payload.get("meta")
        body = payload.get("body")
        if not isinstance(meta, Mapping) or not isinstance(body, Mapping):
            return None
        tables = body.get("tables")
        if not isinstance(tables, Mapping):
            return None
        meta = dict(meta)
        meta.setdefault("schema", SCHEMA_VERSION)
        meta.setdefault("global_rank", meta.get("rank", 0))
        meta.setdefault("rank", meta.get("global_rank", 0))
        rows_t, cols_t = _split_wire_tables(tables)
        return TelemetryEnvelope(meta=meta, tables=rows_t, columns=cols_t)
    # Legacy flat shape: {"sampler": ..., "tables": {...}, **identity}
    if "tables" in payload and "sampler" in payload:
        tables = payload.get("tables")
        if not isinstance(tables, Mapping):
            return None
        meta = {
            k: v
            for k, v in payload.items()
            if k not in ("tables",) and not isinstance(v, (dict, list))
        }
        meta.setdefault("schema", SCHEMA_VERSION)
        meta.setdefault("global_rank", meta.get("rank", 0))
        meta.setdefault("rank", meta.get("global_rank", 0))
        meta.setdefault("timestamp", time.time())
        rows_t, cols_t = _split_wire_tables(tables)
        return TelemetryEnvelope(meta=meta, tables=rows_t, columns=cols_t)
    return None
