"""Long-context demo: causal ring attention over a sequence-sharded mesh.

Run on N devices (or CPU with
XLA_FLAGS=--xla_force_host_platform_device_count=8):

    python examples/distributed/ring_attention_demo.py
"""

import time

import jax
import jax.numpy as jnp

from traceml_tpu.ops.attention import attention_reference
from traceml_tpu.ops.ring_attention import make_ring_attention
from traceml_tpu.parallel.mesh import make_mesh

n = len(jax.devices())
mesh = make_mesh({"context": n})
print(f"ring of {n} devices; sequence sharded {n}-way")

B, S, H, D = 1, 256 * n, 8, 64
ks = jax.random.split(jax.random.PRNGKey(0), 3)
q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.bfloat16) * 0.3 for kk in ks)

ring_fn = make_ring_attention(mesh, "context")
with mesh:
    out = ring_fn(q, k, v)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    out = ring_fn(q, k, v)
    jax.block_until_ready(out)
    ring_ms = (time.perf_counter() - t0) * 1000

ref = attention_reference(q, k, v, causal=True)
err = jnp.max(jnp.abs(out.astype(jnp.float32) - ref.astype(jnp.float32)))
print(f"S={S}: ring {ring_ms:.1f} ms, max |err| vs reference = {float(err):.2e}")
