"""End-to-end tick profiler + per-(domain, version) diagnosis cache.

Pins the r20 contracts (docs/developer_guide/diagnosis-engine.md):

* ``TICK_STAGES`` is a published vocabulary (dashboards and the bench
  key on the strings, like the INVALIDATE_* reasons);
* a tick whose diagnosis inputs did not change runs ZERO rules — the
  per-(domain, version) cache returns the previous DiagnosticResult
  object (``diag_cache_hits`` counts it, ``rule_eval_counts`` proves
  no rule evaluated);
* the profiler surfaces through ``window_build_stats()`` →
  ``window_build`` meta → the serving tier, including the per-fragment
  ``serialize`` stage;
* with ``TRACEML_VECTOR_DIAGNOSIS=0`` the served payload bytes are
  byte-identical to the scalar legacy path (twin-session pin, same
  pattern as the ``TRACEML_INCR_WINDOW=0`` pin in
  tests/utils/test_incremental_window.py).
"""

import json
import random

from traceml_tpu.aggregator.sqlite_writer import SQLiteWriter
from traceml_tpu.diagnostics.common import rule_eval_counts
from traceml_tpu.renderers.compute import LiveComputer
from traceml_tpu.telemetry.envelope import (
    SenderIdentity,
    build_telemetry_envelope,
)
from traceml_tpu.utils import timing as T
from traceml_tpu.utils.columnar import TICK_STAGES, TickProfile
from traceml_tpu.utils.step_time_window import PHASES


# -- fixtures ------------------------------------------------------------


def _step_row(step, rng, clock="device"):
    step_ms = rng.uniform(40.0, 150.0)
    events = {
        T.STEP_TIME: {
            "cpu_ms": step_ms,
            "device_ms": step_ms * 0.97 if clock == "device" else None,
            "count": 1,
        }
    }
    for key, name in PHASES.items():
        if rng.random() < 0.15:
            continue
        v = rng.uniform(0.0, 25.0)
        events[name] = {
            "cpu_ms": v,
            "device_ms": v * 0.95 if key != "input" else None,
            "count": 1,
        }
    return {
        "step": step,
        "timestamp": 100.0 + step,
        "clock": clock,
        "late_markers": 0,
        "events": events,
    }


def _coll_rows(step, rng):
    rows = []
    for op in ("all_reduce", "all_gather", "reduce_scatter"):
        if rng.random() < 0.3:
            continue
        dur = rng.uniform(0.0, 8.0)
        rows.append({
            "step": step,
            "timestamp": 100.0 + step,
            "op": op,
            "dtype": rng.choice(("float32", "bfloat16")),
            "count": rng.randint(1, 4),
            "bytes": rng.randint(0, 1 << 22),
            "group_size": rng.choice((4, 8)),
            "duration_ms": dur,
            "exposed_ms": dur * rng.random(),
        })
    return rows


def _ident(rank=0, world=2):
    return SenderIdentity(
        session_id="s1",
        global_rank=rank,
        local_rank=rank,
        world_size=world,
        node_rank=0,
        hostname="host-0",
        pid=100 + rank,
    )


def _seed_session(db, steps=25):
    w = SQLiteWriter(db)
    w.start()
    for rank in (0, 1):
        w.ingest(build_telemetry_envelope(
            "step_time",
            {"step_time": [_step_row(s, random.Random(100 * rank + s))
                           for s in range(1, steps)]},
            _ident(rank),
        ))
        w.ingest(build_telemetry_envelope(
            "collectives",
            {"collectives": [row for s in range(1, steps)
                             for row in _coll_rows(s, random.Random(s))]},
            _ident(rank),
        ))
    assert w.force_flush()
    return w


def _model_stats_row(ts=200.0):
    return {
        "timestamp": ts,
        "flops_per_step": 1.0e12,
        "flops_source": "manual",
        "device_kind": "tpu-v4",
        "peak_flops": 2.75e14,
        "device_count": 2,
        "tokens_per_step": 1024.0,
    }


# -- stage vocabulary ----------------------------------------------------


def test_tick_stage_vocabulary_pinned():
    assert TICK_STAGES == (
        "refresh", "build", "diagnose", "attribute", "view", "serialize",
    )


def test_tick_profile_accumulates_and_snapshots():
    p = TickProfile()
    p.note_tick()
    p.note_stage("step_time", "build", 100)
    p.note_stage("step_time", "build", 50)
    p.note_stage("step_time", "diagnose", 7)
    p.bump("diag_cache_hits")
    p.bump("rule_evals", 3)
    snap = p.snapshot()
    assert snap["ticks"] == 1
    assert snap["stage_ns"]["step_time"] == {"build": 150, "diagnose": 7}
    assert snap["counters"] == {"diag_cache_hits": 1, "rule_evals": 3}


# -- diagnosis cache -----------------------------------------------------


def test_version_idle_tick_runs_zero_rules(tmp_path, monkeypatch):
    """A tick whose domain went dirty WITHOUT its diagnosis inputs
    changing (here: a model_stats-only ingest re-dirties step_time for
    the MFU block) must reuse the cached DiagnosticResult and evaluate
    zero rules."""
    monkeypatch.setenv("TRACEML_VECTOR_DIAGNOSIS", "1")
    db = tmp_path / "t.sqlite"
    w = _seed_session(db)
    computer = LiveComputer(db, window_steps=50)
    try:
        p1 = computer.payload()
        assert p1["step_time"]["diagnosis"] is not None
        prof = computer.store.tick_profile
        misses_before = prof.counters.get("diag_cache_misses", 0)
        assert misses_before > 0  # first tick diagnosed every domain

        w.ingest(build_telemetry_envelope(
            "step_time", {"model_stats": [_model_stats_row()]}, _ident(0),
        ))
        assert w.force_flush()

        evals_before = sum(rule_eval_counts().values())
        hits_before = prof.counters.get("diag_cache_hits", 0)
        p2 = computer.payload()
        assert p2 is not p1  # step_time went dirty → payload rebuilt
        # ... but its diagnosis is the SAME object, with zero rule runs
        assert p2["step_time"]["diagnosis"] is p1["step_time"]["diagnosis"]
        assert sum(rule_eval_counts().values()) == evals_before
        assert prof.counters.get("diag_cache_hits", 0) > hits_before
        assert prof.counters.get("diag_cache_misses", 0) == misses_before
    finally:
        computer.close()
        w.finalize()


def test_new_rows_invalidate_diagnosis_cache(tmp_path):
    db = tmp_path / "t.sqlite"
    w = _seed_session(db)
    computer = LiveComputer(db, window_steps=50)
    try:
        p1 = computer.payload()
        d1 = p1["step_time"]["diagnosis"]
        for rank in (0, 1):
            w.ingest(build_telemetry_envelope(
                "step_time",
                {"step_time": [_step_row(s, random.Random(999 + s))
                               for s in range(25, 30)]},
                _ident(rank),
            ))
        assert w.force_flush()
        evals_before = sum(rule_eval_counts().values())
        p2 = computer.payload()
        assert p2["step_time"]["diagnosis"] is not d1
        assert sum(rule_eval_counts().values()) > evals_before
    finally:
        computer.close()
        w.finalize()


def test_kill_switch_disables_diagnosis_cache(tmp_path, monkeypatch):
    monkeypatch.setenv("TRACEML_VECTOR_DIAGNOSIS", "0")
    db = tmp_path / "t.sqlite"
    w = _seed_session(db)
    computer = LiveComputer(db, window_steps=50)
    try:
        p1 = computer.payload()
        w.ingest(build_telemetry_envelope(
            "step_time", {"model_stats": [_model_stats_row()]}, _ident(0),
        ))
        assert w.force_flush()
        p2 = computer.payload()
        # legacy behavior: the dirty domain re-diagnoses every tick
        assert p2["step_time"]["diagnosis"] is not p1["step_time"]["diagnosis"]
        prof = computer.store.tick_profile
        assert "diag_cache_hits" not in prof.counters
        assert "diag_cache_misses" not in prof.counters
    finally:
        computer.close()
        w.finalize()


# -- profiler surfacing --------------------------------------------------


def test_tick_profile_in_window_build_stats(tmp_path, monkeypatch):
    monkeypatch.setenv("TRACEML_VECTOR_DIAGNOSIS", "1")
    db = tmp_path / "t.sqlite"
    w = _seed_session(db)
    computer = LiveComputer(db, window_steps=50)
    try:
        computer.payload()
        stats = computer.store.window_build_stats()
        prof = stats["tick_profile"]
        assert prof["ticks"] >= 1
        assert set(prof["stage_ns"]["store"]) == {"refresh"}
        for domain in ("step_time", "collectives"):
            stages = prof["stage_ns"][domain]
            assert set(stages) <= set(TICK_STAGES)
            assert {"build", "diagnose", "attribute", "view"} <= set(stages)
        assert prof["counters"]["rule_evals"] > 0
        assert prof["counters"]["diag_cache_misses"] > 0
        # json-serializable end to end (meta fragment requirement)
        json.dumps(stats)
    finally:
        computer.close()
        w.finalize()


def test_serialize_stage_recorded_by_publisher(tmp_path):
    from traceml_tpu.renderers.serving import SessionPublisher

    db = tmp_path / "t.sqlite"
    w = _seed_session(db)
    pub = SessionPublisher(db, "s1", window_steps=50)
    try:
        pub.poll(force=True)
        prof = pub._computer.store.tick_profile.snapshot()
        ser_domains = [
            d for d, stages in prof["stage_ns"].items() if "serialize" in stages
        ]
        # every rebuilt fragment recorded its encode cost
        assert "step_time" in ser_domains and "meta" in ser_domains
    finally:
        pub.close()
        w.finalize()


# -- TRACEML_VECTOR_DIAGNOSIS=0 payload byte-pin -------------------------


def _payload_bytes(db, drop_stats=True):
    from traceml_tpu.renderers.web_payload import build_web_payload

    payload = build_web_payload(db, "s1")
    payload.pop("ts", None)  # wall-clock
    if drop_stats:
        payload.pop("window_build", None)  # timings differ run to run
    return json.dumps(payload, sort_keys=True).encode()


def test_vector_off_payload_bytes_identical(tmp_path, monkeypatch):
    """The vectorized arm must not change a single served byte: twin
    sessions, one polled with the kill switch off, one with it on —
    identical payloads (modulo wall-clock + the profiler block)."""
    db_a = tmp_path / "a" / "t.sqlite"
    db_b = tmp_path / "b" / "t.sqlite"
    db_a.parent.mkdir()
    db_b.parent.mkdir()
    _seed_session(db_a).finalize()
    _seed_session(db_b).finalize()

    monkeypatch.setenv("TRACEML_VECTOR_DIAGNOSIS", "0")
    off = _payload_bytes(db_a)
    monkeypatch.setenv("TRACEML_VECTOR_DIAGNOSIS", "1")
    on = _payload_bytes(db_b)
    assert off == on
