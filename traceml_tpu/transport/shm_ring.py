"""Same-host shared-memory ring transport (SPSC, zero-copy publish).

Every rank on a TPU host currently pays full TCP framing + two kernel
socket copies to reach an aggregator that lives on the *same machine*.
This module replaces that hop with one ``memcpy`` into a per-rank
file-backed mmap ring that the aggregator's selector tick drains
directly.

Layout (mirrors ``native/ring.c`` — the bytes are the contract)::

    64-byte header:
      0   magic  b"TMR1"
      4   u32    version (1)
      8   u64    capacity (data bytes)
      16  u64    head  — producer-owned, total bytes published
      24  u64    tail  — consumer-owned, total bytes consumed
      32  u64    producer_gen — stamped at ring creation
      40  u64    consumer_gen — stamped by the aggregator at attach
      48  u32    producer_pid
    data region: u32-le length-prefixed frames, wrapping modulo
    capacity (a frame may straddle the wrap point).

Commit protocol: write prefix + body into free space, then publish by
advancing ``head``.  Bytes past ``head`` are invisible to the
consumer, so a ``kill -9`` mid-write leaves only unpublished garbage —
no torn frame can ever be drained (exercised by the ``shm.write``
chaos point and tests/transport/test_shm_ring.py).

Why file-backed mmap rather than ``multiprocessing.shared_memory``:
on Python 3.10 the resource tracker in the *attaching* process unlinks
segments at interpreter exit and warns about leaks — fatal for an
aggregator that must be kill -9-able and re-attachable (r12 contract).
A plain file in ``/dev/shm`` (page cache; no disk I/O) has identical
performance and exactly the lifecycle we need: the launcher's rank dir
holds a small JSON descriptor pointing at the segment, and stale
segments are detected by generation counters rather than kernel
refcounts.

Restart correctness (docs/developer_guide/fault-tolerance.md):

* **Aggregator kill -9 → respawn:** the new process re-attaches the
  same segment, resumes from the persisted ``tail`` (ring-resident
  frames survive the crash — the ring doubles as a tiny replay
  window), and stamps a fresh ``consumer_gen``.  The producer notices
  the gen change on its next send, reports one failed send, and the
  :class:`~traceml_tpu.transport.spool.DurableSender` above it dumps
  its unacked window to the spool and replays — the aggregator's seq
  dedup then drops whatever the ring already delivered.  Exactly-once
  coverage, same as the TCP arm.
* **Rank kill -9:** published frames stay drainable; the half-written
  one was never published.  Liveness marks the rank lost as usual.
* **Torn/corrupt segment on re-attach:** header validation fails →
  the consumer quarantines the ring (counted in ingest stats) and the
  rank's sends fail over to the stream transport.
"""

from __future__ import annotations

import hashlib
import json
import mmap
import os
import struct
import threading
import time
from collections import deque
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

from traceml_tpu.config import flags
from traceml_tpu.dev import chaos
from traceml_tpu.utils import msgpack_codec
from traceml_tpu.utils.error_log import get_error_log

RING_MAGIC = b"TMR1"
RING_VERSION = 1
RING_HDR = 64
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")

_OFF_VERSION = 4
_OFF_CAPACITY = 8
_OFF_HEAD = 16
_OFF_TAIL = 24
_OFF_PRODUCER_GEN = 32
_OFF_CONSUMER_GEN = 40
_OFF_PRODUCER_PID = 48

DEFAULT_RING_BYTES = 4 * 1024 * 1024
MIN_RING_BYTES = 64 * 1024

#: descriptor file the producer drops in its rank dir so the aggregator
#: can discover the segment (launcher env carries only the session dir)
DESCRIPTOR_NAME = "shm_ring.json"


def _native_ring():
    from traceml_tpu.native import get_ring

    return get_ring()


def default_ring_dir() -> Optional[Path]:
    """Where segment files live: TRACEML_SHM_DIR override, else
    /dev/shm when present (page-cache backed), else None (caller falls
    back to the rank dir — still correct, maybe touching disk)."""
    override = flags.SHM_DIR.get_str()
    if override:
        return Path(override)
    shm = Path("/dev/shm")
    if shm.is_dir() and os.access(shm, os.W_OK):
        return shm
    return None


def ring_segment_path(
    session_dir: Path, global_rank: int, ring_dir: Optional[Path] = None
) -> Path:
    """Deterministic per-(session, rank) segment path, short enough for
    any filesystem and collision-free across sessions via digest."""
    base = ring_dir or default_ring_dir()
    if base is None:
        return Path(session_dir) / f"rank{global_rank}.ring"
    digest = hashlib.sha1(
        f"{Path(session_dir).resolve()}:{os.getuid()}".encode()
    ).hexdigest()[:12]
    return base / f"traceml-{digest}-r{global_rank}.ring"


# ---------------------------------------------------------------------
# header accessors (Python mirror of ring.c; used by both native and
# pure paths for setup/validation — only append/drain have a C twin)
# ---------------------------------------------------------------------


def _read_u64(buf, off: int) -> int:
    return _U64.unpack_from(buf, off)[0]


def _write_u64(buf, off: int, value: int) -> None:
    _U64.pack_into(buf, off, value)


def init_ring_buffer(buf, capacity: int, producer_gen: int) -> None:
    """Stamp a fresh header over a zeroed buffer of RING_HDR+capacity."""
    buf[0:4] = RING_MAGIC
    _U32.pack_into(buf, _OFF_VERSION, RING_VERSION)
    _write_u64(buf, _OFF_CAPACITY, capacity)
    _write_u64(buf, _OFF_HEAD, 0)
    _write_u64(buf, _OFF_TAIL, 0)
    _write_u64(buf, _OFF_PRODUCER_GEN, producer_gen)
    _write_u64(buf, _OFF_CONSUMER_GEN, 0)
    _U32.pack_into(buf, _OFF_PRODUCER_PID, os.getpid() & 0xFFFFFFFF)


def validate_ring_buffer(buf) -> int:
    """Return the capacity of a well-formed ring; ValueError otherwise."""
    if len(buf) < RING_HDR + 8:
        raise ValueError("ring buffer too small")
    if bytes(buf[0:4]) != RING_MAGIC:
        raise ValueError("bad ring magic")
    version = _U32.unpack_from(buf, _OFF_VERSION)[0]
    if version != RING_VERSION:
        raise ValueError(f"unsupported ring version {version}")
    capacity = _read_u64(buf, _OFF_CAPACITY)
    if capacity == 0 or capacity + RING_HDR > len(buf):
        raise ValueError("ring capacity out of range")
    head = _read_u64(buf, _OFF_HEAD)
    tail = _read_u64(buf, _OFF_TAIL)
    if head < tail or head - tail > capacity:
        raise ValueError("ring head/tail invariant violated")
    return capacity


def py_ring_append(buf, capacity: int, payload: bytes) -> bool:
    """Pure-Python twin of ring.c:ring_append (same commit protocol)."""
    need = 4 + len(payload)
    if need > capacity:
        raise ValueError("frame larger than ring")
    head = _read_u64(buf, _OFF_HEAD)
    tail = _read_u64(buf, _OFF_TAIL)
    if head - tail + need > capacity:
        return False
    data_off = RING_HDR
    blob = _U32.pack(len(payload)) + payload
    at = head % capacity
    first = min(capacity - at, need)
    buf[data_off + at : data_off + at + first] = blob[:first]
    if need > first:
        buf[data_off : data_off + need - first] = blob[first:]
    # publish: the head store is the commit point (CPython slice
    # assignment on mmap is a memcpy that completes before this line)
    _write_u64(buf, _OFF_HEAD, head + need)
    return True


def py_ring_drain(buf, capacity: int, max_frames: int) -> List[bytes]:
    """Pure-Python twin of ring.c:ring_drain (advances tail per frame)."""
    tail = _read_u64(buf, _OFF_TAIL)
    out, cursor = py_ring_peek(buf, capacity, tail, max_frames)
    if cursor != tail:
        _write_u64(buf, _OFF_TAIL, cursor)
    return out


def py_ring_peek(
    buf, capacity: int, cursor: int, max_frames: int
) -> Tuple[List[bytes], int]:
    """Pure-Python twin of ring.c:ring_peek — read frames from a
    caller-held cursor WITHOUT touching tail.  The caller advances tail
    (``commit``) only after the frames are durably processed, so a
    crash between peek and commit re-delivers the window."""
    out: List[bytes] = []
    data_off = RING_HDR
    head = _read_u64(buf, _OFF_HEAD)
    if cursor > head:
        raise ValueError("ring cursor beyond head")
    while (max_frames <= 0 or len(out) < max_frames) and head - cursor >= 4:
        at = cursor % capacity
        if capacity - at >= 4:
            n = _U32.unpack_from(buf, data_off + at)[0]
        else:
            split = capacity - at
            raw = bytes(buf[data_off + at : data_off + capacity])
            raw += bytes(buf[data_off : data_off + 4 - split])
            n = _U32.unpack(raw)[0]
        if n + 4 > capacity:
            raise ValueError(f"ring frame length {n} exceeds capacity")
        if head - cursor < 4 + n:
            break
        start = (cursor + 4) % capacity
        first = min(capacity - start, n)
        body = bytes(buf[data_off + start : data_off + start + first])
        if n > first:
            body += bytes(buf[data_off : data_off + n - first])
        out.append(body)
        cursor += 4 + n
    return out, cursor


class ShmRingClient:
    """Producer side: publishes length-prefixed frames into the ring.

    Quacks like :class:`~traceml_tpu.transport.tcp_transport.TCPClient`
    for everything the publisher and the durable sender touch:
    ``send_batch`` / ``send_encoded_body`` / ``close`` plus the
    ``reconnects`` / ``batches_sent`` / ``batches_dropped`` counters.

    Single caller by contract (the rank's publisher tick) — no locks.
    A consumer-generation change (aggregator restarted and re-attached)
    or a full ring reports the send as failed so the DurableSender
    spools and replays; seq dedup keeps delivery exactly-once.
    """

    kind = "shm"

    def __init__(
        self,
        path: Path,
        capacity: Optional[int] = None,
        session_dir: Optional[Path] = None,
        global_rank: Optional[int] = None,
    ) -> None:
        self.path = Path(path)
        cap = capacity or flags.SHM_RING_BYTES.get_int(DEFAULT_RING_BYTES)
        self._capacity = max(MIN_RING_BYTES, int(cap))
        self.reconnects = 0
        self.batches_sent = 0
        self.batches_dropped = 0
        self.frames_sent = 0
        self.ring_full_drops = 0
        self.consumer_gen_flips = 0
        self._last_consumer_gen = 0
        self._native = _native_ring()
        self._fd = -1
        self._mm: Optional[mmap.mmap] = None
        self._create()
        if session_dir is not None and global_rank is not None:
            self._write_descriptor(Path(session_dir), int(global_rank))

    # -- setup --------------------------------------------------------

    def _create(self) -> None:
        total = RING_HDR + self._capacity
        self.path.parent.mkdir(parents=True, exist_ok=True)
        # O_EXCL-free: a stale segment from a previous incarnation of
        # this rank is simply re-initialized (new producer_gen)
        fd = os.open(self.path, os.O_RDWR | os.O_CREAT, 0o600)
        try:
            os.ftruncate(fd, total)
            mm = mmap.mmap(fd, total)
        except Exception:
            os.close(fd)
            raise
        self._fd = fd
        self._mm = mm
        init_ring_buffer(mm, self._capacity, producer_gen=time.time_ns())

    def _write_descriptor(self, session_dir: Path, global_rank: int) -> None:
        """Drop the discovery breadcrumb the aggregator scans for."""
        # mirrors TraceMLSettings.rank_dir (rank_<n>)
        rank_dir = session_dir / f"rank_{global_rank}"
        rank_dir.mkdir(parents=True, exist_ok=True)
        desc = {
            "path": str(self.path),
            "capacity": self._capacity,
            "global_rank": global_rank,
            "producer_pid": os.getpid(),
        }
        tmp = rank_dir / (DESCRIPTOR_NAME + ".tmp")
        tmp.write_text(json.dumps(desc))
        tmp.replace(rank_dir / DESCRIPTOR_NAME)

    # -- send path ----------------------------------------------------

    def _consumer_bounced(self) -> bool:
        """True once per aggregator re-attach: the producer must treat
        the next send as failed so its durable window replays through
        the fresh consumer (seq dedup absorbs any overlap)."""
        assert self._mm is not None
        gen = _read_u64(self._mm, _OFF_CONSUMER_GEN)
        if gen != self._last_consumer_gen:
            first = self._last_consumer_gen == 0
            self._last_consumer_gen = gen
            if not first:
                self.consumer_gen_flips += 1
                self.reconnects += 1
                return True
        return False

    def _append(self, body: bytes) -> bool:
        assert self._mm is not None
        # kill9 executes inside fire() — dying here is "mid-ring-write":
        # head was not advanced, so the consumer never sees a torn frame
        fault = chaos.fire("shm.write")
        if fault is not None:
            if fault.action == "stall":
                time.sleep(float(fault.arg or 0.2))
            elif fault.action == "corrupt":
                # flip one byte in the body: the ring framing survives,
                # the aggregator's per-frame decode drops just this batch
                idx = len(body) // 2
                body = body[:idx] + bytes([body[idx] ^ 0xFF]) + body[idx + 1 :]
            elif fault.action in ("reset", "truncate"):
                return False
        if self._native is not None:
            return bool(self._native.ring_append(self._mm, body))
        return py_ring_append(self._mm, self._capacity, body)

    def send_encoded_body(self, body: bytes) -> bool:
        """Publish one already-framed batch body (the same bytes the
        TCP path would put after the 4-byte wire prefix)."""
        if self._mm is None:
            return False
        try:
            if self._consumer_bounced():
                return False
            if self._append(body):
                self.frames_sent += 1
                self.batches_sent += 1
                return True
            self.ring_full_drops += 1
            self.batches_dropped += 1
            return False
        except Exception as exc:
            get_error_log().warning("shm ring append failed", exc)
            self.batches_dropped += 1
            return False

    def send_batch(self, payloads: List[Any]) -> bool:
        if not payloads:
            return True
        try:
            body = msgpack_codec.encode_batch(payloads)
        except Exception as exc:
            get_error_log().warning("shm batch encode failed", exc)
            return False
        return self.send_encoded_body(body)

    def pending_bytes(self) -> int:
        """Unconsumed bytes in the ring (producer's view; benign-stale)."""
        if self._mm is None:
            return 0
        head = _read_u64(self._mm, _OFF_HEAD)
        tail = _read_u64(self._mm, _OFF_TAIL)
        return max(0, head - tail)

    def close(self) -> None:
        # the segment outlives the producer: the aggregator drains the
        # remaining frames, the launcher removes the file at teardown
        if self._mm is not None:
            try:
                self._mm.close()
            except Exception:
                pass
            self._mm = None
        if self._fd >= 0:
            try:
                os.close(self._fd)
            except Exception:
                pass
            self._fd = -1


class ShmRingConsumer:
    """Aggregator side: attaches a rank's segment and drains frames on
    the selector tick.  Single caller (the serve thread) — no locks.

    Consumption is two-phase: ``drain(commit=False)`` peeks frames from
    an in-memory ``_cursor`` and ``commit(upto)`` advances the shared
    ``tail`` only once those envelopes are durably written.  A crash
    between the two re-delivers the uncommitted window to the next
    incarnation; the writer's seq dedup absorbs the overlap.  The
    default ``commit=True`` keeps the old drain-and-advance semantics
    for standalone consumers (tests, one-shot tooling).
    """

    def __init__(self, path: Path, global_rank: int) -> None:
        self.path = Path(path)
        self.global_rank = int(global_rank)
        self.tag = f"shm:{global_rank}"
        self.frames = 0
        self.bytes = 0
        self._native = _native_ring()
        self._fd = -1
        self._mm: Optional[mmap.mmap] = None
        self._capacity = 0
        self._cursor = 0
        self._attach()

    def _attach(self) -> None:
        fault = chaos.fire("shm.attach")
        fd = os.open(self.path, os.O_RDWR)
        try:
            size = os.fstat(fd).st_size
            mm = mmap.mmap(fd, size)
            if fault is not None and fault.action == "corrupt":
                # simulate a torn header (host reboot mid-page-write)
                mm[0:4] = b"\x00\x00\x00\x00"
            self._capacity = validate_ring_buffer(mm)
        except Exception:
            os.close(fd)
            raise
        self._fd = fd
        self._mm = mm
        # resume reading where the previous incarnation durably stopped:
        # tail is only ever advanced post-commit, so everything past it
        # is the crash-replay window
        self._cursor = _read_u64(mm, _OFF_TAIL)
        # stamp a fresh consumer generation: the producer sees the flip
        # and fails one send so its durable window replays through us
        _write_u64(mm, _OFF_CONSUMER_GEN, time.time_ns())

    def readable(self) -> int:
        if self._mm is None:
            return 0
        head = _read_u64(self._mm, _OFF_HEAD)
        return max(0, head - self._cursor)

    def drain(self, max_frames: int = 0, commit: bool = True) -> List[bytes]:
        """All published frames past the cursor.  ``commit=True`` also
        advances the shared tail (standalone semantics); the registry
        passes ``commit=False`` and settles tails via :meth:`commit`."""
        if self._mm is None:
            return []
        if self._native is not None:
            frames, cursor = self._native.ring_peek(
                self._mm, self._cursor, max_frames
            )
        else:
            frames, cursor = py_ring_peek(
                self._mm, self._capacity, self._cursor, max_frames
            )
        self._cursor = cursor
        if commit and frames:
            self.commit(cursor)
        self.frames += len(frames)
        self.bytes += sum(len(f) for f in frames)
        return frames

    def cursor(self) -> int:
        return self._cursor

    def commit(self, upto: int) -> None:
        """Advance the shared tail to ``upto`` — frames at or before it
        are durably processed and their ring space is reclaimable."""
        if self._mm is None:
            return
        upto = min(int(upto), self._cursor)  # never past what we read
        if upto <= _read_u64(self._mm, _OFF_TAIL):
            return  # monotonic: late/duplicate watermarks are no-ops
        if self._native is not None:
            self._native.ring_set_tail(self._mm, upto)
        else:
            _write_u64(self._mm, _OFF_TAIL, upto)

    def close(self) -> None:
        if self._mm is not None:
            try:
                self._mm.close()
            except Exception:
                pass
            self._mm = None
        if self._fd >= 0:
            try:
                os.close(self._fd)
            except Exception:
                pass
            self._fd = -1


def scan_ring_descriptors(session_dir: Path) -> List[Dict[str, Any]]:
    """All rank ring descriptors currently present under a session dir."""
    out: List[Dict[str, Any]] = []
    try:
        for desc_path in sorted(Path(session_dir).glob(f"rank*/{DESCRIPTOR_NAME}")):
            try:
                desc = json.loads(desc_path.read_text())
            except (OSError, ValueError):
                continue
            if isinstance(desc, dict) and "path" in desc:
                desc["_descriptor"] = str(desc_path)
                out.append(desc)
    except OSError:
        pass
    return out


class ShmRingRegistry:
    """The aggregator's set of attached rank rings.

    Lives on the serve thread: ``poll()`` runs inside the selector tick
    (the ISSUE's futex/eventfd-free polling), rescanning the session
    dir at a low cadence for late-joining ranks and draining whatever
    is published.  Broken/torn segments are quarantined with counters
    rather than retried hot.
    """

    RESCAN_INTERVAL_S = 1.0

    def __init__(self, session_dir: Path) -> None:
        self.session_dir = Path(session_dir)
        self.consumers: Dict[str, ShmRingConsumer] = {}
        self.attach_failures = 0
        self.quarantined: Dict[str, str] = {}
        # cumulative across the registry's lifetime — per-consumer
        # counters die with detach, but the final ingest_stats write
        # happens after close()
        self.rings_attached_total = 0
        self.frames = 0
        self.bytes = 0
        self._last_scan = 0.0
        # durable-consumption marks: after each poll that peeked frames,
        # (cumulative frames polled, {path: cursor}) is queued.  The
        # aggregator counts shm frames it actually drained from the
        # server's pending buffer and pops marks once drained catches up
        # (take_marks) — pairing each cursor snapshot with exactly the
        # frames it covers even when drain slices are capped.
        self._marks: deque = deque()
        self._marks_lock = threading.Lock()

    def _maybe_scan(self) -> None:
        now = time.monotonic()
        if now - self._last_scan < self.RESCAN_INTERVAL_S:
            return
        self._last_scan = now
        for desc in scan_ring_descriptors(self.session_dir):
            path = str(desc["path"])
            if path in self.consumers or path in self.quarantined:
                continue
            try:
                consumer = ShmRingConsumer(
                    Path(path), int(desc.get("global_rank", -1))
                )
            except Exception as exc:
                self.attach_failures += 1
                self.quarantined[path] = str(exc)
                get_error_log().warning(
                    f"shm ring attach failed for {path}", exc
                )
                continue
            self.consumers[path] = consumer
            self.rings_attached_total += 1

    def poll(self, max_frames_per_ring: int = 256) -> List[Tuple[str, bytes]]:
        """One selector-tick poll: (tag, frame) pairs ready to ingest."""
        self._maybe_scan()
        out: List[Tuple[str, bytes]] = []
        dead: List[str] = []
        for path, consumer in self.consumers.items():
            try:
                if consumer.readable() < 4:
                    continue
                # peek-only: tails advance in commit() once the writer
                # durably lands these envelopes (crash → re-delivery)
                for frame in consumer.drain(max_frames_per_ring, commit=False):
                    out.append((consumer.tag, frame))
                    self.frames += 1
                    self.bytes += len(frame)
            except Exception as exc:
                # corrupt length / invariant break: quarantine the ring;
                # the producer fails over to the stream transport
                dead.append(path)
                self.quarantined[path] = str(exc)
                get_error_log().warning(
                    f"shm ring quarantined: {path}", exc
                )
        for path in dead:
            consumer = self.consumers.pop(path)
            consumer.close()
        if out:
            with self._marks_lock:
                self._marks.append((self.frames, self.cursors()))
        return out

    def take_marks(self, drained_frames: int) -> Optional[Dict[str, int]]:
        """Newest cursor snapshot fully covered by ``drained_frames``
        (cumulative shm frames the caller pulled out of the server's
        pending buffer), consuming every mark up to it.  None until a
        mark is covered."""
        cursors: Optional[Dict[str, int]] = None
        with self._marks_lock:
            while self._marks and self._marks[0][0] <= drained_frames:
                cursors = self._marks.popleft()[1]
        return cursors

    def cursors(self) -> Dict[str, int]:
        """Read cursor per attached ring — snapshot BEFORE handing its
        frames downstream, then pass back to :meth:`commit` once the
        writer settles everything drained up to that snapshot."""
        return {
            path: consumer.cursor()
            for path, consumer in self.consumers.items()
        }

    def commit(self, cursors: Dict[str, int]) -> None:
        """Advance ring tails to a settled cursor snapshot.  Stale paths
        (quarantined/detached since the snapshot) are skipped; commits
        are monotonic so reordered watermarks are harmless."""
        for path, upto in cursors.items():
            consumer = self.consumers.get(path)  # tracelint: unguarded(dict read racing serve-thread attach/quarantine; a miss or a just-closed consumer only defers the tail commit — replay + seq dedup absorb it)
            if consumer is None:
                continue
            try:
                consumer.commit(upto)
            except (ValueError, OSError):
                pass  # closed/quarantined underneath us: commit is moot

    def commit_all(self) -> None:
        """Finalize path: every peeked frame is downstream and flushed —
        settle all tails so nothing replays into a later attach."""
        for consumer in self.consumers.values():
            consumer.commit(consumer.cursor())

    def stats(self) -> Dict[str, Any]:
        return {
            "rings_attached": self.rings_attached_total,
            "attach_failures": self.attach_failures,
            "quarantined": len(self.quarantined),
            "frames": self.frames,
            "bytes": self.bytes,
        }

    def close(self) -> None:
        for consumer in self.consumers.values():
            consumer.close()
        self.consumers.clear()
