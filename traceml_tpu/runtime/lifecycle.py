"""In-process lifecycle entries (reference: src/traceml_ai/runtime/lifecycle.py).

``start_runtime`` / ``start_aggregator`` are the embedding API used by
the executor, the integrations (HF/Flax/Ray-style), and tests.  Both are
fail-open: any error returns a no-op object and logs.
"""

from __future__ import annotations

from typing import Optional

from traceml_tpu.runtime.identity import RuntimeIdentity, resolve_runtime_identity
from traceml_tpu.runtime.runtime import NoOpRuntime, TraceMLRuntime
from traceml_tpu.runtime.settings import TraceMLSettings, settings_from_env
from traceml_tpu.utils.error_log import get_error_log

_active_runtime: Optional[TraceMLRuntime] = None


def start_runtime(
    settings: Optional[TraceMLSettings] = None,
    identity: Optional[RuntimeIdentity] = None,
):
    """Start the per-rank agent; returns it (or NoOpRuntime on failure)."""
    global _active_runtime
    if _active_runtime is not None:
        return _active_runtime
    try:
        settings = settings or settings_from_env()
        if settings.disabled:
            return NoOpRuntime()
        if (
            not settings.aggregator.port
            and _active_aggregator is not None
            and getattr(_active_aggregator, "started", False)
            and getattr(_active_aggregator, "port", None)
        ):
            # the symmetric embedding pattern (start_aggregator →
            # start_runtime, same settings) just works: an in-process
            # aggregator bound an ephemeral port the caller's settings
            # can't know yet — wire it automatically
            import dataclasses

            from traceml_tpu.runtime.settings import AggregatorEndpoint

            settings = dataclasses.replace(
                settings,
                aggregator=AggregatorEndpoint(
                    connect_host=settings.aggregator.connect_host,
                    bind_host=settings.aggregator.bind_host,
                    port=int(_active_aggregator.port),
                ),
            )
        rt = TraceMLRuntime(settings, identity or resolve_runtime_identity())
        rt.start()
        _active_runtime = rt
        return rt
    except Exception as exc:
        get_error_log().error("start_runtime failed; tracing disabled", exc)
        return NoOpRuntime()


def stop_runtime() -> None:
    global _active_runtime
    rt = _active_runtime
    _active_runtime = None
    if rt is not None:
        try:
            rt.stop()
        except Exception as exc:
            get_error_log().warning("stop_runtime failed", exc)


def get_active_runtime():
    return _active_runtime


_active_aggregator = None


def start_aggregator(settings: Optional[TraceMLSettings] = None):
    """Start an in-process aggregator (the out-of-process entry is
    aggregator/aggregator_main.py).  Returns the aggregator or None."""
    global _active_aggregator
    try:
        from traceml_tpu.aggregator.trace_aggregator import TraceMLAggregator

        settings = settings or settings_from_env()
        agg = TraceMLAggregator(settings)
        agg.start()
        _active_aggregator = agg
        return agg
    except Exception as exc:
        get_error_log().error("start_aggregator failed", exc)
        return None


def stop_aggregator(finalize: bool = True) -> None:
    """Stop the in-process aggregator started by ``start_aggregator``.

    ``finalize=True`` (default) runs the shutdown under the settings'
    full finalize budget — settle, SQLite finalize, final-summary
    artifacts; ``False`` shrinks the budget to ~1 s (best-effort
    artifacts) for embedders that only wanted live telemetry.  The
    embedding API's symmetric half: notebooks and examples pair
    ``start_aggregator``/``stop_aggregator`` like
    ``start_runtime``/``stop_runtime``."""
    global _active_aggregator
    agg = _active_aggregator
    _active_aggregator = None
    if agg is not None:
        try:
            agg.stop(finalize_timeout=None if finalize else 1.0)
        except Exception as exc:
            get_error_log().warning("stop_aggregator failed", exc)
