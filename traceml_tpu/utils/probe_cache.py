"""Shared device-probe verdict cache (repo-root ``PROBE_CACHE.json``).

The axon TPU tunnel can wedge hard enough that ``jax.devices()`` blocks
for minutes inside C++, so every entry point (``bench.py``,
``__graft_entry__``, the watch daemon) probes in a bounded subprocess.
Paying that 45-90 s timeout once per *process* is unavoidable; paying it
once per process per *driver step* is not — the watch daemon refreshes
this cache every few minutes, and the other entry points consult it
first (VERDICT r2 item 10).

Staleness semantics: a stale "up" verdict is harmless (the device paths
behind it re-check physicality themselves and fall back); a stale "down"
verdict only costs a missed window, bounded by the watcher's refresh
interval.  Default freshness window is 600 s.
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Optional

from traceml_tpu.utils.atomic_io import atomic_write_json

DEFAULT_MAX_AGE_S = 600.0

_CACHE_NAME = "PROBE_CACHE.json"


def cache_path(repo_root: Optional[Path] = None) -> Path:
    root = repo_root or Path(__file__).resolve().parents[2]
    return root / _CACHE_NAME


def read_cache(
    repo_root: Optional[Path] = None, max_age_s: float = DEFAULT_MAX_AGE_S
) -> Optional[dict]:
    """The cached probe verdict, or None when absent/stale/corrupt."""
    try:
        raw = json.loads(cache_path(repo_root).read_text())
        if time.time() - float(raw["ts"]) <= max_age_s:
            return raw
    except (OSError, ValueError, KeyError, TypeError):
        pass
    return None


def write_cache(verdict: dict, repo_root: Optional[Path] = None) -> None:
    """Atomically persist a probe verdict (best-effort; never raises).

    atomic_write_json's per-writer mkstemp names matter here: the watch
    daemon, bench.py, and __graft_entry__ can all write concurrently."""
    try:
        atomic_write_json(cache_path(repo_root), dict(verdict, ts=time.time()))
    except OSError:
        pass
