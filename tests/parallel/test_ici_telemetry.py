"""ICI telemetry hook: straggler attribution from the ICI-gathered
matrix ALONE (no TCP anywhere in the path) — the SURVEY §2.5 wiring
VERDICT r1 flagged as missing."""

import jax
import numpy as np
import pytest

from traceml_tpu.parallel.ici_stats import IciStatAggregator, StatVector
from traceml_tpu.parallel.ici_telemetry import (
    IciTelemetryHook,
    batch_to_stat_vector,
    matrix_to_rank_rows,
)
from traceml_tpu.parallel.mesh import make_mesh
from traceml_tpu.utils import timing as T


def _mesh8():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return make_mesh({"data": 8})


def _vec(step, step_ms=100.0, input_ms=5.0, compute_ms=80.0):
    return StatVector(
        {
            "step": step,
            "step_ms": step_ms,
            "input_ms": input_ms,
            "compute_ms": compute_ms,
            "residual_ms": max(0.0, step_ms - input_ms - compute_ms),
        }
    )


def test_aggregate_many_distinct_vectors():
    mesh = _mesh8()
    agg = IciStatAggregator(mesh)
    vectors = [_vec(1, input_ms=float(r)) for r in range(8)]
    matrix = agg.aggregate_many(vectors)
    assert matrix.shape == (8, len(matrix[0]))
    # gathered order preserves participant order
    input_col = [StatVector.from_array(row).values["input_ms"] for row in matrix]
    assert input_col == [float(r) for r in range(8)]
    with pytest.raises(ValueError):
        agg.aggregate_many(vectors[:3])


def test_input_straggler_from_ici_matrix_alone():
    mesh = _mesh8()
    agg = IciStatAggregator(mesh)
    hook = IciTelemetryHook(aggregator=agg, every_n_steps=1)
    # physically consistent synchronous-training shape: every rank's step
    # envelope is gated by the slowest rank; fast ranks spend the
    # difference WAITING inside the sync (compute) phase, the straggler
    # spends it in input — exactly what the clean-straggler math untangles
    for step in range(1, 31):
        vectors = [
            _vec(
                step,
                step_ms=160.0,
                input_ms=60.0 if r == 3 else 5.0,
                compute_ms=95.0 if r == 3 else 150.0,
            )
            for r in range(8)
        ]
        hook.ingest_matrix(agg.aggregate_many(vectors))
    assert hook.gather_count == 30
    rows = hook.rank_rows()
    assert sorted(rows) == list(range(8))
    assert len(rows[0]) == 30
    result = hook.diagnose(mode="live")
    assert result.diagnosis.kind == "INPUT_STRAGGLER", result.diagnosis
    assert result.diagnosis.ranks == [3]


def test_aggregate_many_order_on_multi_axis_mesh():
    # chained all_gathers must preserve mesh-linear participant order —
    # a 2×2×2 mesh regressed this (rows came back axis-reversed)
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = make_mesh({"data": 2, "fsdp": 2, "tensor": 2})
    agg = IciStatAggregator(mesh)
    vectors = [_vec(1, input_ms=float(r)) for r in range(8)]
    matrix = agg.aggregate_many(vectors)
    input_col = [StatVector.from_array(row).values["input_ms"] for row in matrix]
    assert input_col == [float(r) for r in range(8)]


def test_matrix_to_rank_rows_shape():
    matrix = np.stack([_vec(7, input_ms=float(r + 1)).to_array() for r in range(4)])
    rows = matrix_to_rank_rows(matrix, timestamp=123.0)
    assert sorted(rows) == [0, 1, 2, 3]
    row = rows[2]
    assert row["step"] == 7
    assert row["clock"] == "device"
    assert row["events"][T.DATALOADER_NEXT]["cpu_ms"] == 3.0
    assert row["events"][T.STEP_TIME]["device_ms"] == 100.0


def test_batch_to_stat_vector_folds_forward_backward():
    events = []
    for name, cpu_ms in (
        (T.STEP_TIME, 100.0),
        (T.DATALOADER_NEXT, 20.0),
        (T.FORWARD_TIME, 30.0),
        (T.BACKWARD_TIME, 25.0),
        (T.OPTIMIZER_STEP, 5.0),
    ):
        ev = T.TimeEvent(name, step=4)
        ev.cpu_start = 0.0
        ev.cpu_end = cpu_ms / 1000.0
        events.append(ev)
    vec = batch_to_stat_vector(T.StepTimeBatch(4, events)).values
    assert vec["step"] == 4.0
    assert vec["step_ms"] == pytest.approx(100.0)
    assert vec["input_ms"] == pytest.approx(20.0)
    assert vec["compute_ms"] == pytest.approx(55.0)  # fwd+bwd folded
    assert vec["optimizer_ms"] == pytest.approx(5.0)
    assert vec["residual_ms"] == pytest.approx(20.0)


def test_hook_installs_on_batch_flush():
    mesh = _mesh8()
    from traceml_tpu.sdk.state import TraceState

    st = TraceState()
    hook = IciTelemetryHook(
        aggregator=IciStatAggregator(mesh), every_n_steps=2
    ).install(st)
    try:
        for step in (1, 2, 3, 4):
            ev = T.TimeEvent(T.STEP_TIME, step=step)
            ev.cpu_start, ev.cpu_end = 0.0, 0.1
            st.buffer.add(ev)
            st.flush_step(step)
        # every_n=2 → steps 2 and 4 gathered
        assert hook.gather_count == 2
        # single-controller broadcast: all 8 participants report
        assert sorted(hook.rank_rows()) == list(range(8))
    finally:
        hook.uninstall()
    st.flush_step(5)  # no crash after uninstall
