"""TelemetryPublisher single-encode path: idle gate, wire/disk byte
agreement, shutdown flush completeness, producer self-observability."""

import time
from pathlib import Path

from traceml_tpu.database.database_writer import ENVELOPE_FILE, iter_backup_tables
from traceml_tpu.runtime.sender import TelemetryPublisher
from traceml_tpu.samplers.base_sampler import BaseSampler
from traceml_tpu.telemetry.control import CONTROL_KEY, PRODUCER_STATS
from traceml_tpu.telemetry.envelope import SenderIdentity, normalize_telemetry_envelope
from traceml_tpu.utils import msgpack_codec


class FakeSampler(BaseSampler):
    name = "fake"

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self._i = 0

    def _sample(self):
        self.db.add_record("t", {"i": self._i})
        self._i += 1


class CapturingClient:
    """Stands in for TCPClient: records the exact frame bodies."""

    def __init__(self):
        self.bodies = []

    def send_batch(self, payloads):
        self.bodies.append(msgpack_codec.encode_batch(payloads))
        return True


def test_idle_tick_is_free_no_payload_no_disk(tmp_path):
    s = FakeSampler(disk_backup_dir=tmp_path)
    pub = TelemetryPublisher([s], None, SenderIdentity())
    for _ in range(50):
        assert pub.publish() == 0
    assert pub.idle_ticks == 50
    assert pub.stats()["idle_ratio"] == 1.0
    # no disk artifacts at all: nothing was collected or buffered
    assert not (tmp_path / "fake").exists()


def test_single_encode_wire_and_disk_share_bytes(tmp_path):
    s = FakeSampler(disk_backup_dir=tmp_path)
    client = CapturingClient()
    pub = TelemetryPublisher([s], client, SenderIdentity(global_rank=3))
    s.sample()
    s.sample()
    # 2 = one telemetry envelope + the one-shot transport_hello announce
    assert pub.publish() == 2
    pub.publish(final=True)  # force the backup buffer out
    # wire: one batch frame decoding to one envelope with both rows
    payloads, errors = msgpack_codec.decode_batch(client.bodies)
    assert errors == 0
    envs = [e for e in map(normalize_telemetry_envelope, payloads) if e]
    assert len(envs) == 1
    assert envs[0].tables["t"] == [{"i": 0}, {"i": 1}]
    assert envs[0].global_rank == 3
    # disk: the same envelope, same rows
    got = list(iter_backup_tables(tmp_path / "fake" / ENVELOPE_FILE))
    assert got == [("t", {"i": 0}), ("t", {"i": 1})]


def test_publisher_marks_envelope_mode_no_legacy_double_write(tmp_path):
    s = FakeSampler(disk_backup_dir=tmp_path)
    pub = TelemetryPublisher([s], None, SenderIdentity())
    assert s.writer.envelope_mode  # committed at construction
    s.sample()
    pub.publish(final=True)
    # only the envelope file exists — no per-row t.msgpack alongside it
    files = sorted(p.name for p in (tmp_path / "fake").iterdir())
    assert files == [ENVELOPE_FILE]


def test_midwindow_kill_backup_has_all_rows(tmp_path):
    """Regression (r10 satellite): rows published but throttled out of
    the backup buffer, plus rows never published at all, must BOTH reach
    disk when the sampler is stopped mid-window."""
    s = FakeSampler(disk_backup_dir=tmp_path)
    pub = TelemetryPublisher([s], None, SenderIdentity())
    s.sample()
    pub.publish()  # envelope buffered; flush_every=20 throttle → not on disk
    assert s.writer.has_pending()
    s.sample()  # lands AFTER the last publish; the publisher never sees it
    s.stop()  # kill: no final drain, no final publish
    got = list(iter_backup_tables(tmp_path / "fake" / ENVELOPE_FILE))
    assert got == [("t", {"i": 0}), ("t", {"i": 1})]


def test_base_sampler_stop_idempotent_after_final_publish(tmp_path):
    s = FakeSampler(disk_backup_dir=tmp_path)
    pub = TelemetryPublisher([s], None, SenderIdentity())
    s.sample()
    pub.publish(final=True)
    s.stop()  # nothing dirty, nothing pending — must not duplicate
    got = list(iter_backup_tables(tmp_path / "fake" / ENVELOPE_FILE))
    assert got == [("t", {"i": 0})]


def test_final_publish_force_flushes_every_sampler(tmp_path):
    a, b = FakeSampler(disk_backup_dir=tmp_path), FakeSampler(disk_backup_dir=tmp_path / "b")
    pub = TelemetryPublisher([a, b], None, SenderIdentity())
    a.sample()
    b.sample()
    pub.publish(final=True)
    assert not a.writer.has_pending() and not b.writer.has_pending()
    assert (tmp_path / "fake" / ENVELOPE_FILE).exists()
    assert (tmp_path / "b" / "fake" / ENVELOPE_FILE).exists()


def test_producer_stats_message_on_final():
    # no disk backup: the tick after a publish is genuinely idle (a
    # pending backup buffer intentionally keeps ticks non-idle until
    # the flush throttle writes it)
    s = FakeSampler(disk_backup_dir=None)
    client = CapturingClient()
    pub = TelemetryPublisher([s], client, SenderIdentity(global_rank=1))
    s.sample()
    pub.publish()
    pub.publish()  # idle
    pub.publish(final=True, extra_payloads=[{"hello": 1}])
    payloads, _ = msgpack_codec.decode_batch(client.bodies)
    stats_msgs = [p for p in payloads if p.get(CONTROL_KEY) == PRODUCER_STATS]
    assert stats_msgs, payloads
    st = stats_msgs[-1]["stats"]
    assert st["samplers"]["fake"]["envelopes"] == 1
    assert st["idle_ticks"] == 1
    assert st["samplers"]["fake"]["collect_us"] >= 0
    assert stats_msgs[-1]["meta"]["global_rank"] == 1


def test_stats_not_emitted_every_batch(tmp_path):
    s = FakeSampler(disk_backup_dir=tmp_path)
    client = CapturingClient()
    pub = TelemetryPublisher(
        [s], client, SenderIdentity(), stats_interval_s=3600.0
    )
    for _ in range(5):
        s.sample()
        pub.publish()
    payloads, _ = msgpack_codec.decode_batch(client.bodies)
    assert not any(p.get(CONTROL_KEY) == PRODUCER_STATS for p in payloads)
