"""Identity propagation through projections into summaries
(reference: tests/telemetry/test_system_projection_identity.py +
test_sender_sequence.py)."""

import sqlite3

from traceml_tpu.aggregator.sqlite_writer import SQLiteWriter
from traceml_tpu.database import Database, DBIncrementalSender
from traceml_tpu.telemetry.envelope import (
    SenderIdentity,
    build_telemetry_envelope,
    normalize_telemetry_envelope,
)


def test_sender_sequence_no_loss_no_duplication():
    db = Database()
    sender = DBIncrementalSender("s", db)
    sender.set_identity(SenderIdentity(session_id="x", global_rank=5))
    shipped = []
    for i in range(50):
        db.add_record("t", {"i": i})
        if i % 7 == 0:
            payload = sender.collect_payload()
            if payload:
                shipped.extend(normalize_telemetry_envelope(payload).tables["t"])
    payload = sender.collect_payload()
    if payload:
        shipped.extend(normalize_telemetry_envelope(payload).tables["t"])
    assert [r["i"] for r in shipped] == list(range(50))
    assert sender.collect_payload() is None


def test_identity_columns_survive_projection(tmp_path):
    db_path = tmp_path / "t.sqlite"
    w = SQLiteWriter(db_path)
    w.start()
    ident = SenderIdentity(
        session_id="sess-9",
        global_rank=6,
        local_rank=2,
        world_size=8,
        local_world_size=4,
        node_rank=1,
        hostname="node-b",
        pid=4242,
        platform="tpu",
        device_kind="TPU v5p",
    )
    env = build_telemetry_envelope(
        "process",
        {"process": [{"timestamp": 1.0, "cpu_pct": 1.0, "rss_bytes": 2,
                      "vms_bytes": 3, "num_threads": 4}]},
        identity=ident,
    )
    # wire roundtrip preserves identity meta
    norm = normalize_telemetry_envelope(env.to_wire())
    assert norm.meta["hostname"] == "node-b"
    assert norm.meta["local_world_size"] == 4
    w.ingest(norm)
    w.force_flush()
    w.finalize()
    conn = sqlite3.connect(db_path)
    row = conn.execute(
        "SELECT session_id, global_rank, local_rank, world_size,"
        " local_world_size, node_rank, hostname, pid FROM process_samples"
    ).fetchone()
    conn.close()
    assert row == ("sess-9", 6, 2, 8, 4, 1, "node-b", 4242)
