"""Delta-protocol correctness for the serving tier
(docs/developer_guide/serving-tier.md).

The core property: a viewer that consumes ANY interleaving of
``?since=<token>`` deltas — including missing whole rounds of updates,
as an SSE client does after a dropped connection — ends up with a
payload equivalent to a fresh full ``GET /api/live``.  Equivalence is
byte-identical on the canonical encoding (``json.dumps(sort_keys=True)``)
with the ``ts`` stamp excluded: ``ts`` is wall-clock serving time, baked
fresh into every full body, and deltas carry it in the envelope instead
of any fragment.

Also covered here: the 204 idle path, SSE framing + ``Last-Event-ID``
resume, and the gzip/strong-ETag conditional-request behavior of the
full endpoints.
"""

from __future__ import annotations

import gzip
import http.client
import json
import random
import types

import pytest

from traceml_tpu.aggregator.display_drivers.browser import (
    BrowserDisplayDriver,
    wait_until_ready,
)
from traceml_tpu.renderers import serving

from tests.display.test_browser_driver import _make_session_db


@pytest.fixture(autouse=True)
def _fresh_publishers():
    serving.close_all_publishers()
    yield
    serving.close_all_publishers()


def _write_rows(db, step0, n_ranks=2, n_steps=5):
    """Append more telemetry to an existing session DB."""
    from traceml_tpu.aggregator.sqlite_writer import SQLiteWriter
    from traceml_tpu.telemetry.envelope import (
        SenderIdentity,
        build_telemetry_envelope,
    )
    from traceml_tpu.utils import timing as T

    w = SQLiteWriter(db)
    w.start()
    for rank in range(n_ranks):
        ident = SenderIdentity(
            session_id="dash", global_rank=rank, world_size=n_ranks
        )
        rows = [
            {"step": s, "timestamp": float(s), "clock": "device",
             "events": {
                 T.STEP_TIME: {"cpu_ms": 100.0 + s, "device_ms": 100.0 + s,
                               "count": 1},
                 T.COMPUTE_TIME: {"cpu_ms": 1.0, "device_ms": 55.0,
                                  "count": 1},
             }}
            for s in range(step0, step0 + n_steps)
        ]
        w.ingest(build_telemetry_envelope(
            "step_time", {"step_time": rows}, ident))
    w.force_flush()
    w.finalize()


def _start_driver(logs_dir, session="dash"):
    db = logs_dir / session / "telemetry.sqlite"
    ctx = types.SimpleNamespace(
        db_path=db,
        settings=types.SimpleNamespace(
            session_id=session,
            session_dir=logs_dir / session,
            logs_dir=logs_dir,
            serve_max_sessions=8,
        ),
    )
    driver = BrowserDisplayDriver(port=0)
    driver.sse_wait_slice = 0.02
    driver.start(ctx)
    assert driver.port and wait_until_ready("127.0.0.1", driver.port, 5.0)
    # deterministic tests: no poll rate-limiting
    serving.publisher_for(db, session).min_poll_interval = 0
    return driver, db


def _get(port, path, headers=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    try:
        conn.request("GET", path, headers=headers or {})
        resp = conn.getresponse()
        return resp.status, dict(resp.getheaders()), resp.read()
    finally:
        conn.close()


def _canon(payload):
    return json.dumps(
        {k: v for k, v in payload.items() if k != "ts"}, sort_keys=True
    )


# -- delta replay ----------------------------------------------------------

def test_delta_replay_any_interleaving_matches_full(tmp_path):
    session_dir = tmp_path / "dash"
    session_dir.mkdir(parents=True)
    _make_session_db(session_dir)
    driver, db = _start_driver(tmp_path)
    try:
        rng = random.Random(1307)
        token = None
        state = {}
        step0 = 100
        for _ in range(6):
            _write_rows(db, step0)
            step0 += 5
            if rng.random() < 0.4:
                continue  # viewer misses this round entirely (dropped)
            q = f"?since={token}" if token else ""
            code, headers, body = _get(driver.port, f"/api/live{q}")
            token = headers.get("X-TraceML-Token", token)
            if code == 204:
                continue
            assert code == 200
            m = json.loads(body)
            if "fragments" in m:
                for frag in m["fragments"].values():
                    state.update(frag)
                token = m["token"]
            else:  # first fetch without a token: the flat full payload
                state = m
        # catch-up delta after the last write, then compare to a full GET
        code, headers, body = _get(
            driver.port, f"/api/live?since={token}" if token else "/api/live"
        )
        if code == 200:
            m = json.loads(body)
            if "fragments" in m:
                for frag in m["fragments"].values():
                    state.update(frag)
            else:
                state = m
        code, _, full = _get(driver.port, "/api/live")
        assert code == 200
        full_payload = json.loads(full)
        assert full_payload["step_time"]["n_steps"] > 0
        assert _canon(state) == _canon(full_payload)
    finally:
        driver.stop()


def test_idle_delta_is_204_with_stable_token(tmp_path):
    session_dir = tmp_path / "dash"
    session_dir.mkdir(parents=True)
    _make_session_db(session_dir)
    driver, db = _start_driver(tmp_path)
    try:
        code, headers, body = _get(driver.port, "/api/live")
        assert code == 200
        token = headers["X-TraceML-Token"]
        # nothing changed: empty 304-style body, token echoed
        code, headers, body = _get(driver.port, f"/api/live?since={token}")
        assert code == 204 and body == b""
        assert headers["X-TraceML-Token"] == token
        # garbled token: treated as no token → every fragment returned
        code, _, body = _get(driver.port, "/api/live?since=bogus")
        assert code == 200
        m = json.loads(body)
        assert set(m["fragments"]) >= {"header", "step_time", "diagnosis"}
    finally:
        driver.stop()


def test_full_payload_unchanged_shape_and_version(tmp_path):
    """Acceptance: the legacy full GET /api/live works unchanged —
    version bump only."""
    session_dir = tmp_path / "dash"
    session_dir.mkdir(parents=True)
    _make_session_db(session_dir)
    driver, db = _start_driver(tmp_path)
    try:
        code, _, body = _get(driver.port, "/api/live")
        d = json.loads(body)
        assert code == 200 and d["version"] == 3
        assert list(d.keys())[:3] == ["version", "session", "ts"]
        for key in ("step_time", "memory", "collectives", "system",
                    "process", "stdout", "diagnosis", "findings"):
            assert key in d
        assert d["session"] == "dash"
        assert d["step_time"]["n_steps"] > 0
    finally:
        driver.stop()


# -- SSE -------------------------------------------------------------------

def _read_sse_event(resp, timeout_lines=200):
    """Read one SSE event (dict of field → value) from a streaming
    response."""
    event = {}
    for _ in range(timeout_lines):
        line = resp.fp.readline()
        if not line:
            break
        line = line.decode().rstrip("\n")
        if line == "":
            if event:
                return event
            continue
        field, _, value = line.partition(": ")
        event[field] = value
    return event or None


def test_sse_stream_and_last_event_id_resume(tmp_path):
    session_dir = tmp_path / "dash"
    session_dir.mkdir(parents=True)
    _make_session_db(session_dir)
    driver, db = _start_driver(tmp_path)
    try:
        conn = http.client.HTTPConnection("127.0.0.1", driver.port, timeout=10)
        conn.request("GET", "/api/stream")
        resp = conn.getresponse()
        assert resp.status == 200
        assert resp.getheader("Content-Type") == "text/event-stream"
        first = _read_sse_event(resp)
        conn.close()  # dropped connection, mid-stream
        assert first["event"] == "fragment"
        token = first["id"]
        m = json.loads(first["data"])
        assert m["token"] == token
        assert set(m["fragments"]) >= {"header", "step_time"}

        # new data lands while the viewer is disconnected
        _write_rows(db, 500)

        # browser reconnect: Last-Event-ID carries the resume point —
        # only fragments whose version advanced come back
        conn = http.client.HTTPConnection("127.0.0.1", driver.port, timeout=10)
        conn.request("GET", "/api/stream", headers={"Last-Event-ID": token})
        resp = conn.getresponse()
        second = _read_sse_event(resp)
        conn.close()
        assert second["event"] == "fragment"
        m2 = json.loads(second["data"])
        assert "step_time" in m2["fragments"]
        assert "header" not in m2["fragments"]  # constant → never resent

        # merged state equals a fresh full GET (ts excluded)
        state = {}
        for frag in m["fragments"].values():
            state.update(frag)
        for frag in m2["fragments"].values():
            state.update(frag)
        code, _, full = _get(driver.port, "/api/live")
        assert code == 200
        assert _canon(state) == _canon(json.loads(full))
    finally:
        driver.stop()


# -- gzip + ETag conditional requests --------------------------------------

def test_live_etag_and_gzip(tmp_path):
    session_dir = tmp_path / "dash"
    session_dir.mkdir(parents=True)
    _make_session_db(session_dir)
    driver, db = _start_driver(tmp_path)
    try:
        code, headers, plain = _get(driver.port, "/api/live")
        assert code == 200
        etag = headers["ETag"]
        assert etag == '"' + headers["X-TraceML-Token"] + '"'
        # conditional revalidation: nothing changed → 304, no body
        code, headers, body = _get(
            driver.port, "/api/live", {"If-None-Match": etag}
        )
        assert code == 304 and body == b""
        # gzip negotiation: decoded bytes match the plain body (mod ts)
        code, headers, gz = _get(
            driver.port, "/api/live", {"Accept-Encoding": "gzip"}
        )
        assert code == 200 and headers.get("Content-Encoding") == "gzip"
        assert _canon(json.loads(gzip.decompress(gz))) == _canon(
            json.loads(plain)
        )
        # a write invalidates the ETag
        _write_rows(db, 900)
        code, headers, body = _get(
            driver.port, "/api/live", {"If-None-Match": etag}
        )
        assert code == 200 and headers["ETag"] != etag
    finally:
        driver.stop()


def test_summary_etag_and_gzip(tmp_path):
    session_dir = tmp_path / "dash"
    session_dir.mkdir(parents=True)
    _make_session_db(session_dir)
    driver, db = _start_driver(tmp_path)
    try:
        code, _, _ = _get(driver.port, "/api/summary")
        assert code == 404
        summary = {
            "primary_diagnosis": {"kind": "INPUT_BOUND", "severity": "warning",
                                  "summary": "input pipeline dominates"},
            "sections": {"pad": "x" * 600},  # over the gzip threshold
            "meta": {},
        }
        (session_dir / "final_summary.json").write_text(json.dumps(summary))
        code, headers, plain = _get(driver.port, "/api/summary")
        assert code == 200
        etag = headers["ETag"]
        assert json.loads(plain)["primary_diagnosis"]["kind"] == "INPUT_BOUND"
        code, _, body = _get(
            driver.port, "/api/summary", {"If-None-Match": etag}
        )
        assert code == 304 and body == b""
        code, headers, gz = _get(
            driver.port, "/api/summary", {"Accept-Encoding": "gzip"}
        )
        assert code == 200 and headers.get("Content-Encoding") == "gzip"
        assert gzip.decompress(gz) == plain
    finally:
        driver.stop()
