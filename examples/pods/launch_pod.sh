#!/usr/bin/env bash
# TPU-pod launch walkthrough (reference role: examples/slurm/launch.sh —
# the cluster-scheduler launch recipe, here for GCP TPU pod slices).
#
# Runs the SAME command on every pod worker via `gcloud ... ssh
# --worker=all`; worker 0 owns the aggregator, every worker resolves
# identity from the TPU env (TPU_WORKER_ID / TPU_WORKER_HOSTNAMES).
# See docs/user_guide/tpu-pods.md for the identity model.
#
# Usage:
#   TPU_NAME=my-v5p-64 ZONE=us-east5-a ./launch_pod.sh train.py
set -euo pipefail

TPU_NAME="${TPU_NAME:?set TPU_NAME to your TPU VM/slice name}"
ZONE="${ZONE:?set ZONE, e.g. us-east5-a}"
SCRIPT="${1:?usage: launch_pod.sh <train.py> [args...]}"
shift || true
AGG_PORT="${AGG_PORT:-9911}"

# worker 0's internal address — every rank connects its telemetry here
WORKER0_ADDR=$(gcloud compute tpus tpu-vm describe "$TPU_NAME" \
  --zone "$ZONE" \
  --format='value(networkEndpoints[0].ipAddress)')

echo "worker 0 at ${WORKER0_ADDR}; launching on all workers"

# Every worker runs the same line:
#  - node-rank comes from the TPU env on each worker;
#  - worker 0 (node-rank 0) binds the aggregator on $AGG_PORT;
#  - everyone else connects out to it over DCN.
gcloud compute tpus tpu-vm ssh "$TPU_NAME" --zone "$ZONE" --worker=all \
  --command "cd ~/app && \
    traceml-tpu run \
      --nnodes \${TPU_WORKER_COUNT:-1} \
      --node-rank \${TPU_WORKER_ID:-0} \
      --aggregator-host ${WORKER0_ADDR} \
      --aggregator-port ${AGG_PORT} \
      --mode summary \
      ${SCRIPT} $*"

# Artifacts land on worker 0 under ./traceml_logs/<session>/:
#   final_summary.json / .txt / .html, telemetry.sqlite, manifests.
# Pull them back with:
#   gcloud compute tpus tpu-vm scp --zone "$ZONE" --worker=0 \
#     "$TPU_NAME":~/app/traceml_logs ./pod_logs --recurse
