"""torch-xla (TPU) support — gated; torch_xla is not in this image.

What it adds when torch_xla IS present (BASELINE configs: BERT-base and
Llama-3-8B FSDP via torch-xla on TPU slices):

* ``patch_mark_step()`` — wraps ``torch_xla.core.xla_model.mark_step``
  (and ``torch_xla.sync`` on newer versions) in a timed region named
  ``collective``: under torch-xla the lazy graph executes AT the step
  barrier, so mark_step wall time IS the device execution + collective
  wait for the step — the torch-xla analogue of our JAX readiness edges.
* ``XlaMemoryBackend`` — per-device memory via
  ``torch_xla.core.xla_model.get_memory_info`` (kb fields), plugged into
  the standard StepMemoryTracker backend protocol.
* identity: torch-xla jobs run one process per host with torchrun-style
  env, which ``runtime/identity.py`` already resolves.

The generic torch patches (DataLoader/forward/backward/optimizer —
instrumentation/patches/torch_patches.py) apply unchanged: they are
host-clock dispatch timers, which is exactly what is observable under
lazy execution; the mark_step region carries the device truth.
"""

from __future__ import annotations

from typing import Any, List, Optional

from traceml_tpu.sdk.state import get_state
from traceml_tpu.utils.error_log import get_error_log
from traceml_tpu.utils.timing import COLLECTIVE_TIME, timed_region

_original_mark_step: Optional[Any] = None
_original_sync: Optional[Any] = None
_hook: Any = None


def torch_xla_loaded() -> bool:
    """True only when the PROCESS already imported torch_xla — the
    touch-nothing policy: importing torch_xla on the user's behalf can
    initialize the XLA runtime in jobs that never wanted it."""
    import sys

    return "torch_xla" in sys.modules


def torch_xla_available() -> bool:
    try:
        import torch_xla  # noqa: F401

        return True
    except Exception:
        return False


def install_torch_xla_patch() -> str:
    """Patch now if torch_xla is loaded, else arm a post-import hook
    (the launcher initializes tracing BEFORE the user script imports
    its stack — same gap the orbax patch closes; shared arming logic
    lives next to _PostImportHook).
    Returns "patched" | "deferred" | "noop"."""
    global _hook
    from traceml_tpu.instrumentation.orbax_patch import arm_post_import_patch

    outcome, _hook = arm_post_import_patch(
        "torch_xla",
        "torch_xla",
        "torch_xla.core.xla_model",
        patch_mark_step,
        _hook,
    )
    return outcome


def remove_torch_xla_hook() -> None:
    global _hook
    if _hook is not None:
        _hook.remove()
        _hook = None


def patch_mark_step() -> bool:
    """Time the lazy-execution barrier.  Idempotent; False when gated.

    Patches BOTH public spellings: ``xm.mark_step`` and the 2.x
    top-level ``torch_xla.sync`` — the newer function does NOT route
    through the ``xm.mark_step`` module attribute on real torch-xla,
    so patching only one would leave modern loops untimed (FAKES.md
    B1-B2).
    """
    global _original_mark_step, _original_sync
    if _original_mark_step is not None:
        return True
    try:
        import torch_xla
        import torch_xla.core.xla_model as xm
    except Exception:
        return False

    def _timed(original):
        def timed_barrier(*args: Any, **kwargs: Any):
            st = get_state()
            # reentrancy guard: the two public barrier spellings
            # delegate to each other (xm.mark_step ↔ torch_xla.sync,
            # direction depends on version) — without the guard one
            # user barrier would sink TWO collective samples
            if not st.tls.in_step or getattr(st.tls, "in_xla_barrier", False):
                return original(*args, **kwargs)
            st.tls.in_xla_barrier = True
            try:
                with timed_region(
                    COLLECTIVE_TIME, st.current_step, sink=st.buffer.add
                ):
                    return original(*args, **kwargs)
            finally:
                st.tls.in_xla_barrier = False

        timed_barrier._traceml_original = original  # type: ignore[attr-defined]
        return timed_barrier

    original = xm.mark_step
    xm.mark_step = _timed(original)
    _original_mark_step = original
    sync = getattr(torch_xla, "sync", None)
    if callable(sync) and not hasattr(sync, "_traceml_original"):
        torch_xla.sync = _timed(sync)
        _original_sync = sync
    return True


def unpatch_mark_step() -> None:
    global _original_mark_step, _original_sync
    if _original_mark_step is None:
        return
    try:
        import torch_xla.core.xla_model as xm

        xm.mark_step = _original_mark_step
    except Exception:
        pass
    if _original_sync is not None:
        try:
            import torch_xla

            torch_xla.sync = _original_sync
        except Exception:
            pass
        _original_sync = None
    _original_mark_step = None


class XlaMemoryBackend:
    """StepMemoryTracker backend over torch-xla memory info."""

    name = "torch_xla"

    def __init__(self) -> None:
        import torch_xla.core.xla_model as xm

        self._xm = xm
        devices = xm.get_xla_supported_devices()
        if not devices:
            raise RuntimeError("no xla devices")
        self._devices = devices

    def sample(self) -> List[dict]:
        out = []
        for i, dev in enumerate(self._devices):
            try:
                info = self._xm.get_memory_info(dev)
            except Exception as exc:
                get_error_log().warning(f"xla memory info failed for {dev}", exc)
                continue
            # two real return shapes (FAKES.md M1-M2): the documented
            # XRT-era {"kb_total", "kb_free"} and the PJRT-era
            # {"bytes_used", "bytes_limit"[, "peak_bytes"]}
            if "bytes_used" in info or "bytes_limit" in info:
                used = int(info.get("bytes_used", 0))
                total = int(info.get("bytes_limit", 0))
                peak = int(info.get("peak_bytes", used))
            else:
                total = int(info.get("kb_total", 0)) * 1024
                free = int(info.get("kb_free", 0)) * 1024
                used = max(0, total - free)
                peak = used
            out.append(
                {
                    "device_id": i,
                    "device_kind": str(dev),
                    "current_bytes": used,
                    "peak_bytes": peak,
                    "limit_bytes": total or None,
                }
            )
        return out
