import jax
import jax.numpy as jnp
import numpy as np
import pytest

from traceml_tpu.models import (
    DecoderLM,
    ModelConfig,
    init_train_state,
    make_train_step,
    param_shardings,
)
from traceml_tpu.parallel import make_mesh, batch_sharding


def test_forward_shapes_and_dtype():
    cfg = ModelConfig.tiny()
    model = DecoderLM(cfg)
    tokens = jnp.zeros((2, 16), dtype=jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    logits = model.apply({"params": params}, tokens)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert logits.dtype == jnp.float32  # lm_head in fp32 for stable CE


def test_train_step_reduces_loss():
    cfg = ModelConfig.tiny()
    model, state, tx = init_train_state(cfg, jax.random.PRNGKey(0))
    step = jax.jit(make_train_step(model, tx), donate_argnums=(0,))
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 32)), jnp.int32)
    _, first = step(state, tokens)
    model, state, tx = init_train_state(cfg, jax.random.PRNGKey(0))
    step = jax.jit(make_train_step(model, tx), donate_argnums=(0,))
    losses = []
    for _ in range(20):
        state, metrics = step(state, tokens)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.9
    assert int(state["step"]) == 20


def test_causal_masking():
    """Changing a future token must not change earlier logits."""
    cfg = ModelConfig.tiny()
    model = DecoderLM(cfg)
    tokens = jnp.zeros((1, 8), dtype=jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    t1 = jnp.asarray([[1, 2, 3, 4, 5, 6, 7, 8]], jnp.int32)
    t2 = t1.at[0, 7].set(9)
    l1 = model.apply({"params": params}, t1)
    l2 = model.apply({"params": params}, t2)
    np.testing.assert_allclose(l1[0, :7], l2[0, :7], rtol=2e-2, atol=2e-3)


def test_sharded_train_step_on_8_device_mesh():
    """Full sharded step on the virtual CPU mesh: dp×fsdp×tensor."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = make_mesh({"data": 2, "fsdp": 2, "tensor": 2})
    cfg = ModelConfig.tiny()
    model, state, tx = init_train_state(cfg, jax.random.PRNGKey(0), mesh=mesh)
    # params actually sharded
    flat = jax.tree_util.tree_leaves(state["params"])
    assert any(
        len(l.sharding.device_set) > 1 for l in flat if hasattr(l, "sharding")
    )
    step = jax.jit(make_train_step(model, tx), donate_argnums=(0,))
    rng = np.random.default_rng(0)
    tokens = jax.device_put(
        jnp.asarray(rng.integers(0, cfg.vocab_size, (8, 32)), jnp.int32),
        batch_sharding(mesh),
    )
    state, metrics = step(state, tokens)
    assert np.isfinite(float(metrics["loss"]))
    state, metrics2 = step(state, tokens)
    assert float(metrics2["loss"]) < float(metrics["loss"]) + 1.0


def test_param_shardings_cover_all_leaves():
    mesh = make_mesh({"fsdp": 4, "tensor": 2})
    cfg = ModelConfig.tiny()
    model = DecoderLM(cfg)
    tokens = jnp.zeros((1, 8), dtype=jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    specs = param_shardings(params, mesh)
    n_params = len(jax.tree_util.tree_leaves(params))
    n_specs = len(jax.tree_util.tree_leaves(specs, is_leaf=lambda x: hasattr(x, "spec")))
    assert n_params == n_specs


# -- sequence-parallel attention in the full model (r4) --------------------

def test_model_ring_and_ulysses_match_dense():
    """The flagship decoder produces the same logits whether attention
    runs dense (GSPMD), as ring attention, or as Ulysses all-to-all —
    sequence parallelism is a config switch, not a different model."""
    import dataclasses

    import numpy as np

    from traceml_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"context": 4}, devices=jax.devices()[:4])
    base = ModelConfig(vocab_size=128, hidden=64, n_layers=2, n_heads=4,
                       n_kv_heads=4, max_seq_len=64, dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 64), 0, 128)

    model = DecoderLM(base)
    params = model.init(jax.random.PRNGKey(1), tokens)
    dense = model.apply(params, tokens)

    for impl in ("ring", "ulysses"):
        cfg = dataclasses.replace(
            base, attention_impl=impl, context_axis="context", mesh=mesh)
        out = DecoderLM(cfg).apply(params, tokens)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(dense), atol=2e-4, rtol=2e-4,
            err_msg=impl,
        )


def test_seq_parallel_spec_shards_batch_and_heads():
    """The shard_map spec must shard batch over the data-parallel axes
    (not leave it replicated — advisor r4: replication all-gathers the
    global batch per data group) and heads over tensor when it divides."""
    import dataclasses

    from traceml_tpu.models.transformer import seq_parallel_spec
    from traceml_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"data": 2, "tensor": 2, "context": 2})
    cfg = dataclasses.replace(
        ModelConfig.tiny(), attention_impl="ring",
        context_axis="context", mesh=mesh)
    spec = seq_parallel_spec(cfg)
    assert spec[0] == ("data", "fsdp")   # batch sharded, not replicated
    assert spec[1] == "context"
    assert spec[2] == "tensor"           # tiny() n_heads=4 % tensor=2 == 0
    assert spec[3] is None

    # heads NOT divisible by tensor → heads stay unsharded, rest holds
    cfg3 = dataclasses.replace(
        ModelConfig.tiny(), n_heads=3, n_kv_heads=3, hidden=96,
        attention_impl="ring", context_axis="context", mesh=mesh)
    spec3 = seq_parallel_spec(cfg3)
    assert spec3[0] == ("data", "fsdp") and spec3[2] is None

    # batch NOT divisible by the batch axes' product (B=1 eval on a
    # training mesh) → batch replicates as before instead of erroring
    spec_b1 = seq_parallel_spec(cfg, batch_size=1)
    # data (size 2) must drop; size-1 fsdp may stay (no-op shard)
    assert spec_b1[0] in (None, ("fsdp",), "fsdp") and spec_b1[1] == "context"
    spec_b4 = seq_parallel_spec(cfg, batch_size=4)
    assert spec_b4[0] == ("data", "fsdp")
    # partial divisibility keeps the largest dividing subset: mesh
    # {data:2, fsdp:2(implicit 1 here)...} — build one where data=2,
    # fsdp=2 and B=2 shards over 'data' only
    mesh22 = make_mesh({"data": 2, "fsdp": 2, "context": 2})
    cfg22 = dataclasses.replace(
        ModelConfig.tiny(), attention_impl="ring",
        context_axis="context", mesh=mesh22)
    assert seq_parallel_spec(cfg22, batch_size=2)[0] in (("data",), "data")
    # ...and is truly the LARGEST subset, not a greedy prefix: with
    # data=2, fsdp=4 and B=4, fsdp alone (4-way) beats data (2-way)
    mesh24 = make_mesh({"data": 2, "fsdp": 4})
    cfg24 = dataclasses.replace(
        ModelConfig.tiny(), attention_impl="ring",
        context_axis="context", mesh=mesh24)
    assert seq_parallel_spec(cfg24, batch_size=4)[0] in (("fsdp",), "fsdp")

    # ulysses: heads shard over tensor ONLY if the per-shard head count
    # still divides the context axis (the all-to-all redistributes
    # heads) — n_heads=8, tensor=4, context=4 → local heads 2 % 4 != 0
    mesh44 = make_mesh({"tensor": 4, "context": 2})
    cfg_u = dataclasses.replace(
        ModelConfig.tiny(), n_heads=8, n_kv_heads=8, hidden=128,
        attention_impl="ulysses", context_axis="context", mesh=mesh44)
    assert seq_parallel_spec(cfg_u)[2] == "tensor"  # 8/4=2 % 2 == 0
    mesh44b = make_mesh({"tensor": 2, "context": 4})
    cfg_u2 = dataclasses.replace(cfg_u, mesh=mesh44b)
    assert seq_parallel_spec(cfg_u2)[2] == "tensor"  # 8/2=4 % 4 == 0
    cfg_u3 = dataclasses.replace(
        cfg_u, n_heads=4, n_kv_heads=4, hidden=64, mesh=mesh44b)
    assert seq_parallel_spec(cfg_u3)[2] is None      # 4/2=2 % 4 != 0
    # ring has no head all-to-all: same shape shards fine
    assert seq_parallel_spec(
        dataclasses.replace(cfg_u3, attention_impl="ring"))[2] == "tensor"


def test_model_seq_parallel_train_step_on_data_context_mesh():
    """Full train step with ring attention on a data×context mesh where
    BOTH axes are >1 — the regime the advisor flagged (batch must shard
    over 'data' inside the shard_map, not be redundantly recomputed)."""
    import dataclasses

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    mesh = make_mesh({"data": 2, "context": 2}, devices=jax.devices()[:4])
    cfg = dataclasses.replace(
        ModelConfig.tiny(), attention_impl="ring",
        context_axis="context", mesh=mesh)
    model, state, tx = init_train_state(cfg, jax.random.PRNGKey(0), mesh=mesh)
    step = jax.jit(make_train_step(model, tx), donate_argnums=(0,))
    rng = np.random.default_rng(0)
    tokens = jax.device_put(
        jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 33)), jnp.int32),
        batch_sharding(mesh),
    )
    state, metrics = step(state, tokens)
    assert np.isfinite(float(metrics["loss"]))


def test_model_parallel_impl_without_mesh_raises():
    """ring/ulysses without a mesh must raise, not silently fall back to
    dense (advisor r4: silent fallback hides the misconfiguration until
    the long-context run OOMs)."""
    import dataclasses

    import pytest as _pytest

    cfg = dataclasses.replace(ModelConfig.tiny(), attention_impl="ring")
    tokens = jax.random.randint(jax.random.PRNGKey(0), (1, 16), 0, 256)
    with _pytest.raises(Exception, match="requires cfg.mesh"):
        DecoderLM(cfg).init(jax.random.PRNGKey(1), tokens)


def test_model_unknown_attention_impl_raises():
    import dataclasses

    import pytest as _pytest

    from traceml_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"context": 2}, devices=jax.devices()[:2])
    cfg = dataclasses.replace(
        ModelConfig.tiny(), attention_impl="flashinfer",
        context_axis="context", mesh=mesh)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (1, 128), 0, 256)
    model = DecoderLM(cfg)
    with _pytest.raises(Exception, match="attention_impl"):
        model.init(jax.random.PRNGKey(1), tokens)


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_model_gqa_seq_parallel_matches_dense(impl):
    """GQA (n_kv_heads < n_heads) through BOTH sequence-parallel paths:
    the kv-repeat happens BEFORE the shard_map boundary, so grouped
    heads must produce identical logits to the dense path — ulysses is
    the riskier interaction (its all-to-all redistributes the repeated
    heads across devices)."""
    import dataclasses

    mesh = make_mesh({"context": 4}, devices=jax.devices()[:4])
    base = ModelConfig(vocab_size=128, hidden=64, n_layers=1, n_heads=4,
                       n_kv_heads=2, max_seq_len=64, dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(5), (2, 64), 0, 128)
    model = DecoderLM(base)
    params = model.init(jax.random.PRNGKey(6), tokens)
    dense = model.apply(params, tokens)
    cfg = dataclasses.replace(
        base, attention_impl=impl, context_axis="context", mesh=mesh)
    out = DecoderLM(cfg).apply(params, tokens)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dense), atol=2e-4, rtol=2e-4,
        err_msg=impl)
