"""Generic band-based trend analysis
(reference: src/traceml_ai/analytics/trends/core.py:50-146).

Splits a series into baseline / mid / recent thirds and compares band
means — robust to noise, cheap, explainable.  Used by the memory-creep
rules and the compare verdicts.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence


@dataclasses.dataclass
class TrendEvidence:
    n: int
    baseline_mean: float
    mid_mean: float
    recent_mean: float
    delta: float  # recent − baseline
    growth_pct: float  # delta / max(baseline, eps)
    slope_per_100: float  # least-squares slope × 100 samples
    monotonic_band_growth: bool  # baseline ≤ mid ≤ recent
    weak_recovery: bool  # recent dipped below mid (recovering)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _mean(xs: Sequence[float]) -> float:
    return sum(xs) / len(xs) if xs else 0.0


def compute_trend_evidence(series: Sequence[float]) -> Optional[TrendEvidence]:
    xs: List[float] = [float(v) for v in series if v is not None]
    n = len(xs)
    if n < 9:  # need ≥3 per band
        return None
    third = n // 3
    baseline = xs[:third]
    mid = xs[third : 2 * third]
    recent = xs[2 * third :]
    b, m, r = _mean(baseline), _mean(mid), _mean(recent)
    delta = r - b
    growth = delta / b if b > 0 else (0.0 if delta == 0 else float("inf"))
    # least-squares slope per sample, scaled to per-100-samples
    mean_i = (n - 1) / 2.0
    mean_x = _mean(xs)
    num = sum((i - mean_i) * (x - mean_x) for i, x in enumerate(xs))
    den = sum((i - mean_i) ** 2 for i in range(n))
    slope = (num / den if den else 0.0) * 100.0
    return TrendEvidence(
        n=n,
        baseline_mean=b,
        mid_mean=m,
        recent_mean=r,
        delta=delta,
        growth_pct=growth,
        slope_per_100=slope,
        monotonic_band_growth=(b <= m <= r),
        weak_recovery=(r < m),
    )
