"""Federated fleet page: every session across every aggregator shard
(docs/developer_guide/federation.md).

Served by the fleet router at ``GET /fleet`` (and ``/``); polls
``GET /api/fleet`` — the aggregator-of-aggregators rollup — and renders
the shard health strip, fleet totals (rank states, lost ranks, worst
diagnosis), and the paginated session table.  Session rows link to the
*owning shard's* per-session dashboard: the router is a read-path
front-end, the shard page stays the deep-dive surface.

Session ids, diagnosis strings, and workload tags are telemetry-derived
(the shard ingest ports are unauthenticated) and shard names come from
operator config that still must not break markup — EVERY interpolation
routes through ``esc()`` (ids in URL position additionally through
``encodeURIComponent()``), under the same escape-coverage contract as
the single-shard fleet page (tests/display/test_section_contracts.py).
"""

from __future__ import annotations

from traceml_tpu.aggregator.display_drivers.browser_sections import theme

FEDERATION_HTML = """
<div class="wrap">
 <div class="card reveal" style="padding:13px 20px">
  <div style="display:flex;align-items:center;gap:14px;flex-wrap:wrap">
    <span class="wm">TraceML<b>-TPU</b></span>
    <span class="eyebrow">federated fleet</span>
    <span style="flex:1"></span>
    <span class="muted" id="fed-meta">connecting…</span>
    <span class="livedot"></span>
  </div>
 </div>
 <div class="card reveal d1">
  <div class="chead"><h2 class="ctitle">Shards</h2><span class="sp"></span>
    <span class="cmeta" id="fed-totals"></span></div>
  <table><thead><tr>
    <th>shard</th><th>status</th><th class="num">sessions</th>
  </tr></thead><tbody id="fed-shards">
    <tr><td colspan="3" class="muted">no shards yet</td></tr>
  </tbody></table>
  <div class="muted" id="fed-worst" style="margin-top:8px"></div>
 </div>
 <div class="card reveal d2">
  <div class="chead"><h2 class="ctitle">Sessions</h2><span class="sp"></span>
    <span class="cmeta" id="fed-count"></span></div>
  <table><thead><tr>
    <th>session</th><th>shard</th><th>ranks</th><th>state</th>
    <th>diagnosis</th><th class="num">updated</th>
  </tr></thead><tbody id="fed-rows">
    <tr><td colspan="6" class="muted">no sessions yet</td></tr>
  </tbody></table>
  <div style="display:flex;gap:10px;align-items:center;margin-top:8px">
    <button class="badge" id="fed-prev" type="button">&#8592; prev</button>
    <span class="cmeta" id="fed-page"></span>
    <button class="badge" id="fed-next" type="button">next &#8594;</button>
  </div>
 </div>
</div>
<div id="tip"></div>
"""

FEDERATION_JS = """
let fedPageNo=0,fedPages=0;
function fedRanks(r){
  const order=["ACTIVE","STALE","LOST","FINISHED"];
  const keys=Object.keys(r||{});
  keys.sort((a,b)=>(order.indexOf(a)+1||99)-(order.indexOf(b)+1||99));
  return keys.map(k=>`${esc(k.toLowerCase())} ${esc(r[k])}`).join(" · ");
}
function fedDiag(p){
  if(!p)return'<span class="muted">—</span>';
  return`<span class="sevpill" style="background:${SEV[p.severity]||SEV.info}">${
    esc(p.severity||"info")}</span> ${esc(p.summary||p.kind||"")}`;
}
function fedState(s){
  const base=s.finished?'<span class="badge">finished</span>':
    (s.db_exists?'<span class="badge" style="color:var(--good)">live</span>':
     '<span class="badge stale">no data</span>');
  return base+(s.stale?' <span class="badge stale">stale</span>':"");
}
function fedWorkload(s){
  if(!s.workload)return"";
  return '<br><span class="muted">workload '+esc(s.workload)+'</span>';
}
function fedRow(s){
  const total=Object.values(s.ranks||{}).reduce((a,n)=>a+n,0);
  const upd=s.last_update_ts?
    new Date(s.last_update_ts*1000).toLocaleTimeString():"—";
  return`<tr>
    <td><a style="color:var(--accent)" href="http://${esc(s.shard)}/?session=${
      encodeURIComponent(s.session)}">${esc(s.session)}</a>${
      fedWorkload(s)}</td>
    <td class="cmeta">${esc(s.shard)}</td>
    <td>${total?esc(total):'<span class="muted">—</span>'}
      <span class="muted">${fedRanks(s.ranks)}</span></td>
    <td>${fedState(s)}</td>
    <td>${fedDiag(s.primary_diagnosis)}</td>
    <td class="num cmeta">${esc(upd)}</td></tr>`;
}
function fedShardRow(sh){
  const status=sh.alive?
    '<span class="badge" style="color:var(--good)">up</span>':
    (sh.stale&&sh.sessions?
      '<span class="badge stale">stale</span>':
      '<span class="badge stale">down</span>');
  return`<tr>
    <td><a style="color:var(--accent)" href="http://${esc(sh.shard)}/fleet">${
      esc(sh.shard)}</a></td>
    <td>${status}</td>
    <td class="num">${esc(sh.sessions)}</td></tr>`;
}
function fedTotals(t){
  const states=fedRanks(t.rank_states);
  return`${esc(t.sessions)} session(s) · ${esc(t.live)} live · ${
    esc(t.finished)} finished${
    t.lost_ranks?` · ${esc(t.lost_ranks)} lost rank(s)`:""}${
    states?` · ${states}`:""}`;
}
async function tick(){
 try{
  const r=await fetch(`/api/fleet?page=${esc(fedPageNo)}`);
  const x=await r.json();
  fedPages=x.pages||0;
  if(fedPageNo>0&&fedPageNo>=fedPages)fedPageNo=Math.max(0,fedPages-1);
  document.getElementById("fed-shards").innerHTML=
    (x.shards||[]).map(fedShardRow).join("")||
    '<tr><td colspan="3" class="muted">no shards yet</td></tr>';
  document.getElementById("fed-rows").innerHTML=
    (x.sessions||[]).map(fedRow).join("")||
    '<tr><td colspan="6" class="muted">no sessions yet</td></tr>';
  document.getElementById("fed-totals").innerHTML=
    fedTotals(x.totals||{});
  const worst=document.getElementById("fed-worst");
  if(x.worst_diagnosis){
    worst.innerHTML=`worst: ${fedDiag(x.worst_diagnosis)} <span
      class="cmeta">(${esc(x.worst_diagnosis.session||"?")} @ ${
      esc(x.worst_diagnosis.shard||"?")})</span>`;
  }else{worst.textContent="";}
  document.getElementById("fed-count").textContent=
    `${(x.totals||{}).sessions||0} session(s)`;
  document.getElementById("fed-page").textContent=
    fedPages>1?`page ${esc(fedPageNo+1)} / ${esc(fedPages)}`:"";
  const meta=document.getElementById("fed-meta");
  meta.textContent=`updated ${new Date(x.ts*1000).toLocaleTimeString()}`;
  meta.className="muted";
 }catch(e){const meta=document.getElementById("fed-meta");
   meta.textContent="poll failed: "+e;meta.className="err"}
 setTimeout(tick,2000);
}
document.getElementById("fed-prev").addEventListener("click",()=>{
  fedPageNo=Math.max(0,fedPageNo-1);});
document.getElementById("fed-next").addEventListener("click",()=>{
  if(fedPageNo+1<fedPages)fedPageNo=fedPageNo+1;});
tick();
"""


def build_federation_page() -> str:
    return (
        "<!doctype html><html><head><meta charset=\"utf-8\">\n"
        "<title>TraceML-TPU federated fleet</title>\n"
        f"{theme.head()}\n</head><body>\n"
        + FEDERATION_HTML
        + "\n<script>"
        + f"{theme.HELPERS_JS}\n{FEDERATION_JS}"
        + "</script></body></html>"
    )


_PAGE_CACHE: dict = {}


def federation_page() -> str:
    """The assembled page, built once per process (the router serves it
    on every ``/fleet`` hit)."""
    page = _PAGE_CACHE.get("page")
    if page is None:
        page = build_federation_page()
        _PAGE_CACHE["page"] = page
    return page
