"""Telemetry transport (reference: src/traceml_ai/transport/)."""

from traceml_tpu.transport.tcp_transport import TCPServer, TCPClient  # noqa: F401
