import jax
import jax.numpy as jnp
import numpy as np
import pytest

from traceml_tpu.models import (
    DecoderLM,
    ModelConfig,
    init_train_state,
    make_train_step,
    param_shardings,
)
from traceml_tpu.parallel import make_mesh, batch_sharding


def test_forward_shapes_and_dtype():
    cfg = ModelConfig.tiny()
    model = DecoderLM(cfg)
    tokens = jnp.zeros((2, 16), dtype=jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    logits = model.apply({"params": params}, tokens)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert logits.dtype == jnp.float32  # lm_head in fp32 for stable CE


def test_train_step_reduces_loss():
    cfg = ModelConfig.tiny()
    model, state, tx = init_train_state(cfg, jax.random.PRNGKey(0))
    step = jax.jit(make_train_step(model, tx), donate_argnums=(0,))
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 32)), jnp.int32)
    _, first = step(state, tokens)
    model, state, tx = init_train_state(cfg, jax.random.PRNGKey(0))
    step = jax.jit(make_train_step(model, tx), donate_argnums=(0,))
    losses = []
    for _ in range(20):
        state, metrics = step(state, tokens)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.9
    assert int(state["step"]) == 20


def test_causal_masking():
    """Changing a future token must not change earlier logits."""
    cfg = ModelConfig.tiny()
    model = DecoderLM(cfg)
    tokens = jnp.zeros((1, 8), dtype=jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    t1 = jnp.asarray([[1, 2, 3, 4, 5, 6, 7, 8]], jnp.int32)
    t2 = t1.at[0, 7].set(9)
    l1 = model.apply({"params": params}, t1)
    l2 = model.apply({"params": params}, t2)
    np.testing.assert_allclose(l1[0, :7], l2[0, :7], rtol=2e-2, atol=2e-3)


def test_sharded_train_step_on_8_device_mesh():
    """Full sharded step on the virtual CPU mesh: dp×fsdp×tensor."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = make_mesh({"data": 2, "fsdp": 2, "tensor": 2})
    cfg = ModelConfig.tiny()
    model, state, tx = init_train_state(cfg, jax.random.PRNGKey(0), mesh=mesh)
    # params actually sharded
    flat = jax.tree_util.tree_leaves(state["params"])
    assert any(
        len(l.sharding.device_set) > 1 for l in flat if hasattr(l, "sharding")
    )
    step = jax.jit(make_train_step(model, tx), donate_argnums=(0,))
    rng = np.random.default_rng(0)
    tokens = jax.device_put(
        jnp.asarray(rng.integers(0, cfg.vocab_size, (8, 32)), jnp.int32),
        batch_sharding(mesh),
    )
    state, metrics = step(state, tokens)
    assert np.isfinite(float(metrics["loss"]))
    state, metrics2 = step(state, tokens)
    assert float(metrics2["loss"]) < float(metrics["loss"]) + 1.0


def test_param_shardings_cover_all_leaves():
    mesh = make_mesh({"fsdp": 4, "tensor": 2})
    cfg = ModelConfig.tiny()
    model = DecoderLM(cfg)
    tokens = jnp.zeros((1, 8), dtype=jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    specs = param_shardings(params, mesh)
    n_params = len(jax.tree_util.tree_leaves(params))
    n_specs = len(jax.tree_util.tree_leaves(specs, is_leaf=lambda x: hasattr(x, "spec")))
    assert n_params == n_specs
