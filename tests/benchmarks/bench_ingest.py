"""Ingest write path: seed windowed-prune writer vs watermark writer.

Measures the PRUNE-HEAVY STEADY STATE — the regime a long training run
lives in: both DBs are pre-filled to exactly ``retention`` rows per
(session, rank) partition (byte-identical copies of one file), then the
same envelope stream (R ranks x B rounds of step_time rows, fixed-size
write batches) is driven through each writer design synchronously (no
queue/thread noise) and timed.  Every new row is overflow, so retention
does real work throughout the timed phase.

The seed design re-resolves ``writer_for``/``insert_sql`` per envelope
and every 50 batches runs the full-table ``ROW_NUMBER() OVER
(PARTITION BY session_id, global_rank)`` prune, whose scan covers
ranks x retention live rows — the stall this round's watermark
retention removes (indexed per-partition deletes, bounded slice per
batch).

Golden first: both final DBs must hold byte-identical surviving rows
per partition (same ids, same columns) before any timing is reported —
speed means nothing if the retained rows moved.

Emits bench_common JSON lines (collected into BENCH_LOCAL_* records):

* ``seed_envelopes_per_s`` / ``wm_envelopes_per_s`` and
  ``throughput_speedup`` (sustained, steady-state);
* ``seed_batch_p99_ms`` / ``wm_batch_p99_ms`` (per-batch write+prune
  latency — the seed's tail IS the prune stall) and
  ``p99_improvement``;
* ``seed_batch_max_ms`` / ``wm_batch_max_ms``.

Pytest lane runs 256 ranks with conservative floors; the 1024-rank
acceptance numbers (>=5x throughput, >=20x p99) are produced by
``python tests/benchmarks/bench_ingest.py --ranks 1024`` and recorded
in BENCH_LOCAL_r09.json.
"""

import json
import shutil
import sqlite3
import sys
import time
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).parent))
# standalone `python tests/benchmarks/bench_ingest.py` support
sys.path.insert(1, str(Path(__file__).parent.parent.parent))
import bench_common  # noqa: E402

from traceml_tpu.aggregator.sqlite_writer import SQLiteWriter  # noqa: E402
from traceml_tpu.aggregator.sqlite_writers import (  # noqa: E402
    ALL_WRITERS,
    step_time_writer,
    writer_for,
)
from traceml_tpu.telemetry.envelope import (  # noqa: E402
    SenderIdentity,
    build_telemetry_envelope,
)

pytestmark = pytest.mark.slow

BENCH = "ingest"
# one step per envelope — the live-streaming shape (each rank flushes a
# step as it completes)
ROWS_PER_ENV = 1
BATCH_ENVELOPES = 64
TIMED_BATCHES = 400  # 8 seed prune cycles (one per 50 batches)
REPEATS = 2  # min-of-N per writer; both DBs end identical every repeat
_SEED_PRUNE_EVERY_BATCHES = 50  # the seed writer's cadence, verbatim

# ranks -> summary_window_rows (retention = 1.5x); rounds are derived so
# every case drives the same number of timed batches
_WINDOW_ROWS = {256: 400, 1024: 1000}

RETENTION_TABLES = sorted(
    t for w in ALL_WRITERS for t in getattr(w, "RETENTION_TABLES", ())
)


def _rounds(ranks):
    return max(1, TIMED_BATCHES * BATCH_ENVELOPES // (ranks * 1))


def _env(rank, start):
    ident = SenderIdentity(
        session_id="bench", global_rank=rank, local_rank=rank % 4,
        world_size=1024, node_rank=rank // 4, hostname=f"h{rank // 4}",
        pid=100 + rank,
    )
    rows = [
        {"step": s, "timestamp": float(s), "clock": "device",
         "events": {"_traceml_internal:step_time":
                    {"cpu_ms": 100.0 + s, "device_ms": 101.0 + s, "count": 1}}}
        for s in range(start, start + ROWS_PER_ENV)
    ]
    return build_telemetry_envelope("step_time", {"step_time": rows}, ident)


def _batches(ranks, rounds, start_step):
    """R envelopes per round (one per rank), flattened into fixed-size
    write batches — the drain granularity both writers see."""
    batch = []
    for rnd in range(rounds):
        start = start_step + rnd * ROWS_PER_ENV
        for rank in range(ranks):
            batch.append(_env(rank, start))
            if len(batch) == BATCH_ENVELOPES:
                yield batch
                batch = []
    if batch:
        yield batch


def _prefill(db_path, ranks, retention):
    """Fill step_time_samples to exactly ``retention`` rows per rank
    (steps 1..retention, rank-interleaved arrival) with raw inserts —
    the steady-state starting line both writers copy."""
    conn = sqlite3.connect(str(db_path))
    conn.execute("PRAGMA journal_mode=WAL")
    conn.execute("PRAGMA synchronous=NORMAL")
    for w in ALL_WRITERS:
        w.init_schema(conn)
    sql = step_time_writer.insert_sql(step_time_writer.TABLE)
    events = json.dumps(
        {"_traceml_internal:step_time":
         {"cpu_ms": 100.0, "device_ms": 101.0, "count": 1}}
    )
    conn.execute("BEGIN")
    for step_base in range(1, retention + 1, 50):
        hi = min(step_base + 50, retention + 1)
        for rank in range(ranks):
            conn.executemany(sql, [
                ("bench", rank, rank % 4, 1024, 1, rank // 4,
                 f"h{rank // 4}", 100 + rank, s, float(s), "device", 0,
                 events)
                for s in range(step_base, hi)
            ])
    conn.commit()
    conn.execute("PRAGMA wal_checkpoint(TRUNCATE)")
    conn.commit()
    conn.close()


class _SeedWriterSim:
    """The pre-change write path, driven synchronously: per-envelope
    ``writer_for``/``insert_sql`` resolution, one transaction per batch,
    full-table ``ROW_NUMBER()`` prune every 50 batches (vendored from
    the seed ``SQLiteWriter`` so the comparison survives the rewrite)."""

    def __init__(self, db_path, retention_rows):
        self._retention_rows = retention_rows
        self._batches = 0
        self.conn = sqlite3.connect(str(db_path))
        self.conn.execute("PRAGMA journal_mode=WAL")
        self.conn.execute("PRAGMA synchronous=NORMAL")
        for w in ALL_WRITERS:
            w.init_schema(self.conn)
        self.conn.commit()

    def write_batch(self, batch):
        grouped = {}
        for env in batch:
            writer = writer_for(env.sampler)
            if writer is None:
                continue
            for table, rows in writer.build_rows(env).items():
                if rows:
                    grouped.setdefault(writer.insert_sql(table), []).extend(rows)
        self.conn.execute("BEGIN")
        for sql, rows in grouped.items():
            self.conn.executemany(sql, rows)
        self.conn.commit()
        self._batches += 1
        if self._batches % _SEED_PRUNE_EVERY_BATCHES == 0:
            self.prune()

    def prune(self):
        for table in RETENTION_TABLES:
            self.conn.execute(
                f"""DELETE FROM {table} WHERE id IN (
                    SELECT id FROM (
                        SELECT id, ROW_NUMBER() OVER (
                            PARTITION BY session_id, global_rank
                            ORDER BY id DESC
                        ) AS rn FROM {table}
                    ) WHERE rn > ?
                )""",
                (self._retention_rows,),
            )
            self.conn.commit()

    def finalize(self):
        self.prune()
        self.conn.execute("PRAGMA wal_checkpoint(TRUNCATE)")
        self.conn.commit()
        self.conn.close()


class _WatermarkDrive:
    """This round's writer, driven synchronously through the same
    internals the writer thread uses: cached-lookup ``_write_batch``,
    bounded ``_prune_slice`` per batch, ``_prune_all`` at finalize.
    Opening the pre-filled DB exercises ``_seed_partition_counts``."""

    def __init__(self, db_path, summary_window_rows, prune_slack):
        self.w = SQLiteWriter(db_path, summary_window_rows=summary_window_rows)
        # shrink the hysteresis slack so every partition overflows and
        # is pruned ONLINE inside the bench window (the production
        # slack trades prune frequency for disk headroom; at that
        # setting a window this short would see almost no prunes and
        # the comparison would flatter the new design)
        self.w._prune_slack = prune_slack
        self.conn = self.w._connect()

    def write_batch(self, batch):
        # _write_batch folds the retention prune slice into the batch
        # transaction, exactly as the writer thread does
        self.w._write_batch(self.conn, batch)

    def finalize(self):
        self.w._prune_all(self.conn)
        self.conn.execute("PRAGMA wal_checkpoint(TRUNCATE)")
        self.conn.commit()
        self.conn.close()


def _drive(writer, ranks, rounds, start_step):
    """Feed every batch, timing each write_batch call.  The sustained
    phase is the batch loop; finalize (one-time shutdown prune +
    checkpoint) runs before the golden compare but is timed separately
    so a short bench window doesn't amplify a once-per-session cost.
    Returns (sustained_s, finalize_s, per-batch latencies ms)."""
    lat = []
    t_start = time.perf_counter()
    for batch in _batches(ranks, rounds, start_step):
        t0 = time.perf_counter()
        writer.write_batch(batch)
        lat.append((time.perf_counter() - t0) * 1000.0)
    sustained = time.perf_counter() - t_start
    t0 = time.perf_counter()
    writer.finalize()
    return sustained, time.perf_counter() - t0, lat


def _p99(lat):
    s = sorted(lat)
    return s[min(len(s) - 1, int(len(s) * 0.99))]


def _table_dump(db, table):
    conn = sqlite3.connect(db)
    try:
        return conn.execute(f"SELECT * FROM {table} ORDER BY id").fetchall()
    finally:
        conn.close()


def _run_case(tmp, ranks):
    window_rows = _WINDOW_ROWS.get(ranks, 400)
    retention = int(window_rows * 1.5)
    rounds = _rounds(ranks)
    n_envelopes = ranks * rounds
    start_step = retention + 1

    base_db = Path(tmp) / f"base_{ranks}.sqlite"
    _prefill(base_db, ranks, retention)

    # each partition gains rounds*ROWS_PER_ENV rows in-window; this
    # slack makes every partition overflow (and get pruned online) at
    # least twice during the timed phase
    prune_slack = max(4, rounds * ROWS_PER_ENV // 2)

    # min-of-N repeats, each from a fresh copy of the pre-filled DB:
    # the timed work is deterministic, so noise (shared-host CPU) only
    # ever ADDS time and min is the faithful estimator (timeit's rule).
    # Both writers get the same treatment.
    seed_s = wm_s = seed_fin_s = wm_fin_s = None
    seed_lat = wm_lat = None
    seed_db = Path(tmp) / f"seed_{ranks}.sqlite"
    wm_db = Path(tmp) / f"wm_{ranks}.sqlite"
    for _ in range(REPEATS):
        shutil.copy(base_db, seed_db)
        s, fin, lat = _drive(
            _SeedWriterSim(seed_db, retention), ranks, rounds, start_step
        )
        if seed_s is None or s < seed_s:
            seed_s, seed_fin_s, seed_lat = s, fin, lat
        shutil.copy(base_db, wm_db)
        s, fin, lat = _drive(
            _WatermarkDrive(wm_db, window_rows, prune_slack),
            ranks, rounds, start_step,
        )
        if wm_s is None or s < wm_s:
            wm_s, wm_fin_s, wm_lat = s, fin, lat

    # golden before reporting: identical surviving rows per partition
    for table in RETENTION_TABLES:
        assert _table_dump(wm_db, table) == _table_dump(seed_db, table), (
            f"surviving rows diverge in {table}"
        )

    seed_eps = n_envelopes / seed_s
    wm_eps = n_envelopes / wm_s
    seed_p99 = _p99(seed_lat)
    wm_p99 = _p99(wm_lat)
    extra = {"ranks": ranks, "rounds": rounds,
             "rows_per_env": ROWS_PER_ENV, "batch_envelopes": BATCH_ENVELOPES,
             "retention_rows": retention,
             "prefill_rows": ranks * retention,
             "prune_slack": prune_slack}
    bench_common.emit(BENCH, "seed_envelopes_per_s", seed_eps, "env/s", **extra)
    bench_common.emit(BENCH, "wm_envelopes_per_s", wm_eps, "env/s", **extra)
    bench_common.emit(
        BENCH, "throughput_speedup", wm_eps / seed_eps, "x", **extra
    )
    bench_common.emit(BENCH, "seed_batch_p99_ms", seed_p99, "ms", **extra)
    bench_common.emit(BENCH, "wm_batch_p99_ms", wm_p99, "ms", **extra)
    bench_common.emit(
        BENCH, "p99_improvement", seed_p99 / max(wm_p99, 1e-6), "x", **extra
    )
    bench_common.emit(BENCH, "seed_batch_max_ms", max(seed_lat), "ms", **extra)
    bench_common.emit(BENCH, "wm_batch_max_ms", max(wm_lat), "ms", **extra)
    bench_common.emit(
        BENCH, "seed_finalize_ms", seed_fin_s * 1000.0, "ms", **extra
    )
    bench_common.emit(
        BENCH, "wm_finalize_ms", wm_fin_s * 1000.0, "ms", **extra
    )
    return wm_eps / seed_eps, seed_p99 / max(wm_p99, 1e-6)


def test_ingest_bench_256_ranks(tmp_path):
    speedup, p99_impr = _run_case(tmp_path, 256)
    # conservative floors for the shared-CI lane; the 1024-rank
    # acceptance numbers live in BENCH_LOCAL_r09.json
    assert speedup >= 1.5, speedup
    assert p99_impr >= 5.0, p99_impr


if __name__ == "__main__":
    import argparse
    import tempfile

    ap = argparse.ArgumentParser()
    ap.add_argument("--ranks", type=int, default=1024)
    args = ap.parse_args()
    with tempfile.TemporaryDirectory() as tmp:
        speedup, p99_impr = _run_case(tmp, args.ranks)
        print(f"# throughput {speedup:.1f}x, p99 {p99_impr:.1f}x",
              file=sys.stderr)
