"""``traceml-tpu inspect`` — decode per-rank msgpack backups
(reference: launcher/commands.py:580-616).

Handles both backup frame formats (see database/database_writer.py):
legacy per-row files print one JSON object per row; envelope files
(v2, ``envelopes.msgpack``) carry multiple tables per frame, so each
row is printed with a ``table`` field naming its origin.

``--domain`` filters to one telemetry domain (table name, e.g.
``collectives``); collectives rows additionally get a derived
``overlap_efficiency`` column (``1 − exposed_ms/duration_ms``, 1.0 for
zero-duration rows) so overlap quality is readable straight off the
backups.

``--domain topology`` is special: instead of scanning msgpack backups
it reads the captured mesh out of the session's ``telemetry.sqlite``
(the one-shot ``mesh_topology`` control rows) and prints axis
names/sizes, interconnect kind per axis, and the rank→host→coords
table — with a clean message for pre-topology session DBs.

``--domain serving`` is special the same way: it folds the session's
``serving_samples`` rows through the shared window build and prints the
pooled request/latency totals plus a per-replica table (requests,
TTFT p99, tokens/s, queue depth, KV headroom) — with a clean message
for training-only sessions.

``--domain rollup`` reads the stitched full-run series out of the
rollup tier tables (reporting/tiers.py): per source/metric coverage
plus the tail of the step-time series at whatever resolution survives
(raw/10s/1m).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Dict, Optional

from traceml_tpu.database.database_writer import iter_backup_tables


def _enrich_row(table: Optional[str], row: Dict[str, Any]) -> Dict[str, Any]:
    """Derived columns per domain.  Collectives: overlap efficiency."""
    if table == "collectives" or (table is None and "exposed_ms" in row):
        try:
            dur = float(row.get("duration_ms", 0.0) or 0.0)
            exp = float(row.get("exposed_ms", 0.0) or 0.0)
            row = dict(row)
            row["overlap_efficiency"] = (
                round(1.0 - exp / dur, 4) if dur > 0 else 1.0
            )
        except Exception:
            pass
    return row


def _find_session_db(path: Path) -> Optional[Path]:
    """telemetry.sqlite at/under ``path``: the path itself, a session
    dir holding one, or the first one found below (logs dirs)."""
    if path.is_file() and path.suffix == ".sqlite":
        return path
    if path.is_dir():
        direct = path / "telemetry.sqlite"
        if direct.exists():
            return direct
        hits = sorted(path.rglob("telemetry.sqlite"))
        if hits:
            return hits[0]
    return None


def _inspect_topology(path: Path) -> int:
    from traceml_tpu.reporting.loaders import load_mesh_topology

    db = _find_session_db(path)
    if db is None:
        print(f"no telemetry.sqlite at or under {path}")
        return 1
    try:
        topo = load_mesh_topology(db)
    except Exception as exc:
        print(f"failed to read mesh topology from {db}: {exc}")
        return 1
    if topo is None:
        print(
            f"no mesh topology captured in {db}\n"
            "(pre-topology session, or the run never built a mesh — "
            "set TRACEML_MESH or call parallel.mesh.make_mesh)"
        )
        return 1
    print(f"── mesh topology ({db})")
    print(f"source: {topo.source}")
    axes = "  ·  ".join(
        f"{a.name}×{a.size} [{a.kind}]" for a in topo.axes
    )
    print(f"axes:   {axes}")
    hosts = sorted(set(topo.rank_hosts.values()))
    if hosts:
        print(f"hosts:  {len(hosts)}")
    print(f"ranks:  {len(topo.rank_coords)}")
    coord_hdr = ",".join(a.name for a in topo.axes)
    print(f"{'rank':>6}  {'host':>6}  hostname{'':<12} ({coord_hdr})")
    for rank in sorted(topo.rank_coords):
        host = topo.rank_hosts.get(rank)
        name = topo.rank_hostnames.get(rank, "")
        coords = ",".join(str(c) for c in topo.rank_coords[rank])
        print(
            f"{rank:>6}  {'' if host is None else host:>6}  "
            f"{name:<20} ({coords})"
        )
    return 0


def _inspect_serving(path: Path) -> int:
    from traceml_tpu.reporting.snapshot_store import LiveSnapshotStore

    db = _find_session_db(path)
    if db is None:
        print(f"no telemetry.sqlite at or under {path}")
        return 1
    store = LiveSnapshotStore(db, window_steps=600)
    try:
        store.refresh()
        if not store.has_serving_rows():
            print(
                f"no serving telemetry in {db}\n"
                "(training-only session, or the run set TRACEML_SERVING=0)"
            )
            return 1
        window = store.build_serving_window(max_steps=600)
    finally:
        store.close()
    if window is None:
        print(f"no serving windows could be folded from {db}")
        return 1
    t = window.totals
    print(f"── serving ({db})")
    print(
        f"windows: {window.n_steps}   replicas: {len(window.ranks)}   "
        f"requests: {t.get('requests_completed', 0)}/"
        f"{t.get('requests_enqueued', 0)} done/enqueued"
    )
    print(
        f"tokens/s: {t.get('tokens_per_s', 0.0):.1f}   "
        f"decode share: {t.get('decode_share', 0.0):.0%}   "
        f"queue depth: {t.get('queue_depth_last', 0)} last / "
        f"{t.get('queue_depth_max', 0)} max"
    )
    print(
        f"TTFT p50/p95/p99: {t.get('ttft_p50_ms', 0.0):.1f} / "
        f"{t.get('ttft_p95_ms', 0.0):.1f} / "
        f"{t.get('ttft_p99_ms', 0.0):.1f} ms   "
        f"e2e p99: {t.get('e2e_p99_ms', 0.0):.1f} ms"
    )
    kvh = float(t.get("kv_headroom_min", -1.0))
    if kvh >= 0.0:
        print(f"min KV-cache headroom: {kvh:.1%}")
    print(
        f"{'replica':>8}  {'done':>6}  {'active':>6}  {'tok/s':>9}  "
        f"{'ttft p99':>10}  {'queue':>6}  {'kv hdrm':>8}"
    )
    for rank in sorted(window.per_rank):
        v = window.per_rank[rank]
        h = float(v.get("kv_headroom", -1.0))
        print(
            f"{rank:>8}  {int(v.get('requests_completed', 0)):>6}  "
            f"{int(v.get('requests_active', 0)):>6}  "
            f"{float(v.get('tokens_per_s', 0.0)):>9.1f}  "
            f"{float(v.get('ttft_p99_ms', 0.0)):>7.1f} ms  "
            f"{int(v.get('queue_depth', 0)):>6}  "
            f"{(f'{h:.0%}' if h >= 0.0 else 'n/a'):>8}"
        )
    return 0


def _inspect_rollup(path: Path, limit: int = 20) -> int:
    """Stitched full-run series (reporting/tiers.py): per source/metric,
    the bucket coverage, resolutions in play, and the last ``limit``
    stitched buckets of the step-time series — the from-the-terminal
    answer to "did the retention prune keep my history?"."""
    import sqlite3

    from traceml_tpu.reporting import tiers

    db = _find_session_db(path)
    if db is None:
        print(f"no telemetry.sqlite at or under {path}")
        return 1
    conn = sqlite3.connect(f"file:{db}?mode=ro", uri=True)
    conn.row_factory = sqlite3.Row
    try:
        if not tiers.has_rollups(conn):
            print(
                f"no rollup tiers in {db}\n"
                "(run too short for a watermark prune, or TRACEML_ROLLUP=0)"
            )
            return 1
        print(f"── rollup tiers ({db})")
        for source in tiers.ROLLUP_SOURCES:
            for metric in tiers.SOURCE_METRICS.get(source, ()):
                series = tiers.load_stitched_series(conn, source, metric)
                if not series:
                    continue
                n_pts = sum(len(p) for p in series.values())
                t_lo = min(p[0]["t"] for p in series.values())
                t_hi = max(p[-1]["t"] for p in series.values())
                res = sorted(
                    {pt["res"] for p in series.values() for pt in p}
                )
                print(
                    f"{source.replace('_samples', ''):>12}.{metric:<18} "
                    f"{len(series)} rank(s)  {n_pts} buckets  "
                    f"{(t_hi - t_lo) / 60.0:8.1f} min span  "
                    f"res {'/'.join(res)}"
                )
        series = tiers.load_stitched_series(
            conn, "step_time_samples", "step_ms"
        )
    finally:
        conn.close()
    if series:
        print(f"\nstep_ms tail (last {limit} buckets per rank):")
        print(
            f"{'rank':>6}  {'bucket':>12}  {'res':>4}  {'n':>5}  "
            f"{'mean':>10}  {'min':>10}  {'max':>10}  steps"
        )
        for rank in sorted(series, key=lambda r: int(r) if r.isdigit() else 0):
            for p in series[rank][-limit:]:
                steps = (
                    f"{p['step_min']}–{p['step_max']}"
                    if p.get("step_min") is not None
                    else "n/a"
                )
                print(
                    f"{rank:>6}  {p['t']:>12.1f}  {p['res']:>4}  "
                    f"{p['n']:>5}  {p['mean']:>8.2f}ms  "
                    f"{p['min']:>8.2f}ms  {p['max']:>8.2f}ms  {steps}"
                )
    return 0


def run_inspect(
    path: Path, limit: int = 20, domain: Optional[str] = None
) -> int:
    path = Path(path)
    if domain == "topology":
        return _inspect_topology(path)
    if domain == "serving":
        return _inspect_serving(path)
    if domain == "rollup":
        return _inspect_rollup(path, limit=limit)
    files = []
    if path.is_file():
        files = [path]
    elif path.is_dir():
        files = sorted(path.rglob("*.msgpack"))
    if not files:
        print(f"no .msgpack backups under {path}")
        return 1
    matched = 0
    for f in files:
        printed_header = False
        n = 0
        for table, row in iter_backup_tables(f):
            # legacy per-row files carry no table tag; fall back to the
            # file stem so --domain still works on old backups
            effective = table if table is not None else f.stem
            if domain is not None and effective != domain:
                continue
            if not printed_header:
                print(f"── {f}")
                printed_header = True
            row = _enrich_row(effective, row)
            if table is None:
                print(json.dumps(row, default=str))
            else:
                print(json.dumps({"table": table, **row}, default=str))
            matched += 1
            n += 1
            if n >= limit:
                print(f"… (showing first {limit})")
                break
        if domain is None and not printed_header:
            print(f"── {f}")
    if domain is not None and matched == 0:
        print(f"no rows for domain {domain!r} under {path}")
        return 1
    return 0
