"""JSON-able live payload for the browser dashboard
(reference pattern: renderers/<domain>/dashboard_compute.py).

One pipeline, N surfaces: the payload is derived from the SAME
``LiveComputer`` the CLI renders from (one load→views→diagnose pass per
TTL regardless of how many dashboard tabs poll), with the typed views
serialized verbatim via ``as_dict()``.
"""

from __future__ import annotations

import time
from pathlib import Path
from typing import Any, Dict

from traceml_tpu.renderers.compute import LiveComputer

PAYLOAD_VERSION = 2

_computers: Dict[str, LiveComputer] = {}


def _computer_for(db_path: Path, window_steps: int) -> LiveComputer:
    key = str(db_path)
    comp = _computers.get(key)
    if comp is None or comp.window_steps != window_steps:
        for old in _computers.values():  # one session per aggregator process
            old.close()  # the computer holds a live sqlite connection now
        _computers.clear()
        comp = _computers[key] = LiveComputer(db_path, window_steps=window_steps)
    return comp


def _issue_dict(issue: Any) -> Dict[str, Any]:
    from traceml_tpu.diagnostics.common import confidence_label

    return {
        "kind": issue.kind,
        "severity": issue.severity,
        "summary": issue.summary,
        "action": issue.action,
        "confidence": getattr(issue, "confidence", None),
        "confidence_label": confidence_label(
            getattr(issue, "confidence", None)
        ),
    }


def build_web_payload(
    db_path: Path, session: str, window_steps: int = 150
) -> Dict[str, Any]:
    out: Dict[str, Any] = {
        "version": PAYLOAD_VERSION,
        "session": session,
        "ts": time.time(),
        "step_time": None,
        "memory": None,
        "collectives": None,
        "system": None,
        "process": None,
        "stdout": [],
        "diagnosis": None,
        "findings": [],
    }
    payload = _computer_for(Path(db_path), window_steps).payload()
    if not payload.get("db_exists"):
        return out

    views = payload.get("views") or {}
    for key, payload_key in (
        ("step_time", "step_time"),
        ("memory", "memory"),
        ("collectives", "collectives"),
        ("system", "system"),
        ("process", "process"),
    ):
        view = views.get(key)
        if view is not None:
            out[payload_key] = view.as_dict()

    st_result = (payload.get("step_time") or {}).get("diagnosis")
    if st_result is not None:
        out["diagnosis"] = _issue_dict(st_result.diagnosis)

    domain_results = {
        "step_time": st_result,
        "step_memory": payload.get("step_memory_diagnosis"),
        "collectives": (payload.get("collectives") or {}).get("diagnosis"),
        "system": payload.get("system_diagnosis"),
        "process": payload.get("process_diagnosis"),
    }
    try:
        from traceml_tpu.diagnostics.model_diagnostics import compose

        composed = compose(domain_results)
        out["findings"] = [
            dict(_issue_dict(i), domain=i.evidence.get("domain", "?"))
            for i in composed.issues[:8]
        ]
    except Exception:
        pass
    out["stdout"] = [
        {"stream": s, "line": l} for s, l in (payload.get("stdout") or [])
    ]
    # aggregator self-metrics for the dashboard meta strip: backpressure
    # (queue depth/hwm, per-domain sheds) and writer latency live, not
    # just in the post-run summary
    try:
        from traceml_tpu.reporting.loaders import (
            load_ingest_stats,
            load_rank_status,
        )

        stats = load_ingest_stats(Path(db_path).parent)
        if stats:
            out["ingest"] = {
                k: stats[k]
                for k in (
                    "envelopes_ingested", "rows_dropped", "drop_warnings",
                    "dropped_by_domain", "unknown_domain_drops", "queues",
                    "group_commit", "prune", "corrupt_frame_drops",
                    "replay_duplicates",
                    "pending_frames_hwm", "producers", "ts",
                )
                if k in stats
            }
        # per-rank liveness strip (ACTIVE/STALE/LOST/FINISHED): the
        # dashboard shows which ranks a live dip is actually averaging
        status = load_rank_status(Path(db_path).parent)
        if status and isinstance(status.get("ranks"), dict):
            out["rank_status"] = {
                "ts": status.get("ts"),
                "thresholds": status.get("thresholds"),
                "states": {
                    r: (info or {}).get("state")
                    for r, info in status["ranks"].items()
                    if isinstance(info, dict)
                },
            }
    except Exception:
        pass
    return out
