"""Checkpoint phase: manual wrapper + REAL orbax save through the
auto-patch (orbax is in the image) — a blocking save inside a step must
appear as the first-class ``checkpoint`` phase, not residual."""

import jax.numpy as jnp
import pytest

import traceml_tpu
from traceml_tpu.sdk.state import get_state
from traceml_tpu.utils import timing as T
from traceml_tpu.utils.step_time_window import build_step_time_window


def test_wrap_checkpoint_emits_phase():
    st = get_state()
    captured = []
    st.on_batch_flushed.append(captured.append)
    try:
        saver = traceml_tpu.wrap_checkpoint(lambda tree: len(tree))
        with traceml_tpu.trace_step():
            assert saver({"a": 1, "b": 2}) == 2
    finally:
        st.on_batch_flushed.remove(captured.append)
    names = [e.name for e in captured[-1].events]
    assert T.CHECKPOINT_TIME in names


def test_orbax_save_auto_patched(tmp_path):
    ocp = pytest.importorskip("orbax.checkpoint")
    from traceml_tpu.instrumentation.orbax_patch import patch_orbax

    assert patch_orbax() or getattr(
        ocp.Checkpointer.__dict__.get("save"), "_traceml_wrapped", False
    )
    st = get_state()
    captured = []
    st.on_batch_flushed.append(captured.append)
    try:
        ckptr = ocp.PyTreeCheckpointer()
        tree = {"w": jnp.ones((8, 8)), "step": jnp.asarray(3)}
        with traceml_tpu.trace_step():
            ckptr.save(tmp_path / "ckpt", tree)
        names = [e.name for e in captured[-1].events]
        assert T.CHECKPOINT_TIME in names
        ev = next(e for e in captured[-1].events if e.name == T.CHECKPOINT_TIME)
        assert ev.cpu_ms is not None and ev.cpu_ms > 0
        # the save actually happened
        restored = ocp.PyTreeCheckpointer().restore(tmp_path / "ckpt")
        assert restored["w"].shape == (8, 8)
    finally:
        st.on_batch_flushed.remove(captured.append)
        # the patch is deliberately LEFT applied: unpatching here would
        # drain the module-global patch list and silently un-instrument
        # saves for the rest of the pytest process (auto-patches from an
        # earlier init() share that list); a wrapped save is harmless


def test_orbax_deferred_patch_launcher_order(tmp_path):
    """The LAUNCHER order: init() runs before the user script imports
    orbax — the post-import hook must patch it when the import happens."""
    import os
    import subprocess
    import sys
    from pathlib import Path

    script = tmp_path / "deferred.py"
    script.write_text("""
import sys
sys.path.insert(0, %r)
import traceml_tpu
traceml_tpu.init(mode="auto")           # BEFORE orbax is imported
assert "orbax.checkpoint" not in sys.modules
import orbax.checkpoint as ocp          # hook fires here
assert getattr(ocp.Checkpointer.__dict__["save"], "_traceml_wrapped", False), \\
    "deferred patch did not apply"
from traceml_tpu.sdk.state import get_state
import jax.numpy as jnp
captured = []
get_state().on_batch_flushed.append(captured.append)
with traceml_tpu.trace_step():
    ocp.PyTreeCheckpointer().save(%r + "/ck", {"w": jnp.ones((4,))})
names = [e.name for e in captured[-1].events]
assert any(n.endswith("checkpoint_time") for n in names), names
print("DEFERRED-OK")
""" % (str(Path(__file__).resolve().parents[2]), str(tmp_path)))
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, str(script)], capture_output=True, text=True,
        timeout=180, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "DEFERRED-OK" in proc.stdout


def test_checkpoint_phase_flows_to_window():
    rows = {0: [
        {"step": s, "timestamp": float(s), "clock": "device",
         "events": {
             T.STEP_TIME: {"cpu_ms": 100.0, "device_ms": 100.0, "count": 1},
             T.COMPUTE_TIME: {"cpu_ms": 1.0, "device_ms": 60.0, "count": 1},
             T.CHECKPOINT_TIME: {"cpu_ms": 30.0, "device_ms": None, "count": 1},
         }}
        for s in range(1, 31)
    ]}
    window = build_step_time_window(rows)
    assert "checkpoint" in window.phases_present
    assert window.metric("checkpoint").median_ms == pytest.approx(30.0)
    assert window.share_of_step("checkpoint") == pytest.approx(0.3)
