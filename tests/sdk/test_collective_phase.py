"""The ``collective`` phase, measured for real
(VERDICT r1 item 6: the phase existed only as vocabulary — no code path
emitted it on JAX and the torch-xla emitter was never exercised).

Covers: wrap_collective emission → window → COLLECTIVE_STRAGGLER rule,
and the torch-xla mark_step emitter + memory backend via a stub module.
"""

import sys
import time
import types

import pytest

from traceml_tpu.diagnostics.step_time.api import diagnose_rank_rows
from traceml_tpu.utils import timing as T
from traceml_tpu.utils.step_time_window import build_step_time_window


def test_wrap_collective_emits_phase():
    import traceml_tpu
    from traceml_tpu.sdk.state import get_state

    st = get_state()
    captured = []
    st.on_batch_flushed.append(captured.append)
    try:
        sync = traceml_tpu.wrap_collective(lambda v: v * 2)
        with traceml_tpu.trace_step():
            out = sync(21)
        assert out == 42
    finally:
        st.on_batch_flushed.remove(captured.append)
    names = [e.name for e in captured[-1].events]
    assert T.COLLECTIVE_TIME in names


def test_wrap_collective_duplicate_guard():
    import traceml_tpu
    from traceml_tpu.sdk.state import get_state

    st = get_state()
    captured = []
    st.on_batch_flushed.append(captured.append)
    try:
        inner = traceml_tpu.wrap_collective(lambda v: v + 1)
        outer = traceml_tpu.wrap_collective(lambda v: inner(v))
        with traceml_tpu.trace_step():
            assert outer(1) == 2
    finally:
        st.on_batch_flushed.remove(captured.append)
    collectives = [
        e for e in captured[-1].events if e.name == T.COLLECTIVE_TIME
    ]
    assert len(collectives) == 1  # nested wrapper timed exactly once


def _rows_with_collective(collective_ms, n=30, step_ms=100.0):
    return [
        {
            "step": s,
            "timestamp": float(s),
            "clock": "device",
            "events": {
                T.STEP_TIME: {"cpu_ms": step_ms, "device_ms": step_ms, "count": 1},
                T.COMPUTE_TIME: {"cpu_ms": 1.0, "device_ms": 60.0, "count": 1},
                T.COLLECTIVE_TIME: {
                    "cpu_ms": collective_ms,
                    "device_ms": collective_ms,
                    "count": 1,
                },
            },
        }
        for s in range(1, n + 1)
    ]


def test_window_carries_collective_phase():
    window = build_step_time_window({0: _rows_with_collective(20.0)})
    assert "collective" in window.phases_present
    m = window.metric("collective")
    assert m.median_ms == pytest.approx(20.0)
    assert window.share_of_step("collective") == pytest.approx(0.2)


def test_collective_straggler_rule_fires():
    # subgroup collectives (pipeline stages / sharded groups, NOT one
    # globally-gating allreduce): rank 3's group hop is genuinely slow,
    # so its step stretches while other ranks run free — the clean-sync
    # discount finds no cross-rank wait to subtract and the collective
    # delta dominates
    slow = _rows_with_collective(55.0, step_ms=120.0)      # 60+55+5
    normal = _rows_with_collective(15.0, step_ms=80.0)     # 60+15+5
    rank_rows = {0: normal, 1: normal, 2: normal, 3: slow}
    result = diagnose_rank_rows(rank_rows, mode="live")
    kinds = {i.kind for i in result.issues}
    assert "COLLECTIVE_STRAGGLER" in kinds or result.diagnosis.kind == "COLLECTIVE_STRAGGLER", (
        result.diagnosis,
        kinds,
    )


def test_intra_step_device_edges_are_timely():
    """Markers must be submitted AT DISPATCH so the resolver stamps each
    phase's readiness while the step runs — deferring submission to step
    exit collapses the edges and zeroes phase durations (regression:
    the collective scenario once read 0.05 ms instead of ~30 ms)."""
    import jax
    import jax.numpy as jnp

    import traceml_tpu
    from traceml_tpu.samplers.step_time_sampler import _aggregate_step
    from traceml_tpu.sdk.state import get_state

    st = get_state()
    captured = []
    st.on_batch_flushed.append(captured.append)
    try:
        fn = traceml_tpu.wrap_step_fn(lambda x: (x * 2).sum())
        sync_op = jax.jit(lambda t: t * 0.5)

        def gradient_sync(t):
            time.sleep(0.06)  # the "slow link"
            return sync_op(t)

        timed_sync = traceml_tpu.wrap_collective(gradient_sync)
        x = jnp.ones((16, 16))
        with traceml_tpu.trace_step():
            out = fn(x)
            out = timed_sync(out)
        jax.block_until_ready(out)
        time.sleep(0.05)  # let the resolver stamp
        batch = captured[-1]
        batch.force_resolve()
        row, _ = _aggregate_step(batch.events, None)
        coll = row["events"][T.COLLECTIVE_TIME]
        assert coll["device_ms"] is not None
        assert coll["device_ms"] >= 45.0, coll  # ≈ the 60 ms sleep window
    finally:
        st.on_batch_flushed.remove(captured.append)


# --- torch-xla emitter via stub --------------------------------------------

@pytest.fixture()
def stub_torch_xla(monkeypatch):
    torch_xla = types.ModuleType("torch_xla")
    core = types.ModuleType("torch_xla.core")
    xm = types.ModuleType("torch_xla.core.xla_model")

    def mark_step(*a, **k):
        time.sleep(0.003)  # the lazy-execution barrier "runs the graph"

    xm.mark_step = mark_step
    xm.get_xla_supported_devices = lambda: ["xla:0", "xla:1"]
    xm.get_memory_info = lambda dev: {"kb_total": 16 << 20, "kb_free": 12 << 20}
    torch_xla.core = core
    core.xla_model = xm
    monkeypatch.setitem(sys.modules, "torch_xla", torch_xla)
    monkeypatch.setitem(sys.modules, "torch_xla.core", core)
    monkeypatch.setitem(sys.modules, "torch_xla.core.xla_model", xm)
    yield xm


def test_torch_xla_mark_step_emits_collective(stub_torch_xla):
    import traceml_tpu
    from traceml_tpu.instrumentation.torch_xla_support import (
        patch_mark_step,
        torch_xla_available,
        unpatch_mark_step,
    )
    from traceml_tpu.sdk.state import get_state

    assert torch_xla_available()
    assert patch_mark_step() is True
    st = get_state()
    captured = []
    st.on_batch_flushed.append(captured.append)
    try:
        with traceml_tpu.trace_step():
            stub_torch_xla.mark_step()
        names = [e.name for e in captured[-1].events]
        assert T.COLLECTIVE_TIME in names
        ev = next(e for e in captured[-1].events if e.name == T.COLLECTIVE_TIME)
        assert ev.cpu_ms >= 2.0  # the barrier's wall time was captured
        # outside a step: passthrough, no event
        before = len(captured)
        stub_torch_xla.mark_step()
        assert len(captured) == before
    finally:
        st.on_batch_flushed.remove(captured.append)
        unpatch_mark_step()


def test_torch_xla_memory_backend(stub_torch_xla):
    from traceml_tpu.instrumentation.torch_xla_support import XlaMemoryBackend

    rows = XlaMemoryBackend().sample()
    assert len(rows) == 2
    assert rows[0]["limit_bytes"] == (16 << 20) * 1024
    assert rows[0]["current_bytes"] == (4 << 20) * 1024
