"""Crash-path parity (VERDICT r2 item 5; reference
src/traceml_ai/launcher/process.py:30-300): a child that dies before —
or in a way that bypasses — the in-process crash hooks must still leave
a diagnosable artifact.  The launcher keeps a 64 KiB stderr ring per
supervised child and flushes it to ``rank_<r>/crash_stderr.log`` on
abnormal exit; SIGTERM to the launcher tears down the tree like Ctrl-C.
"""

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[2]

ABORT_SCRIPT = """
import os, sys
sys.stderr.write("EARLY-NOISE\\n" * 4000)      # ~48 KiB of prelude
sys.stderr.write("BOOM-MARKER before abort\\n")
sys.stderr.flush()
os.abort()  # SIGABRT: bypasses every Python-level crash hook
"""

HANG_SCRIPT = """
import sys, time
sys.stderr.write("rank started\\n"); sys.stderr.flush()
time.sleep(120)
"""


def _launch(tmp_path, script_text, name, wait=True, extra=()):
    script = tmp_path / f"{name}.py"
    script.write_text(script_text)
    logs = tmp_path / "logs"
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(REPO)
    argv = [
        sys.executable, "-m", "traceml_tpu", "run",
        "--mode", "summary", "--logs-dir", str(logs),
        "--run-name", name, "--sampler-interval", "0.25",
        "--finalize-timeout", "20", *extra, str(script),
    ]
    if wait:
        proc = subprocess.run(
            argv, env=env, capture_output=True, text=True,
            timeout=180, cwd=str(tmp_path),
        )
        session = next(p for p in logs.iterdir() if p.is_dir())
        return proc, session
    return subprocess.Popen(
        argv, env=env, cwd=str(tmp_path),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    ), logs


def test_sigabrt_child_leaves_crash_stderr(tmp_path):
    proc, session = _launch(tmp_path, ABORT_SCRIPT, "crash")
    assert proc.returncode not in (0, None)
    log = session / "rank_0" / "crash_stderr.log"
    assert log.exists(), sorted(p.name for p in session.rglob("*"))[:20]
    text = log.read_text(errors="replace")
    assert "SIGABRT" in text, text[:300]
    # the ring keeps the NEWEST bytes: the marker written right before
    # death survives even after ~48 KiB of earlier noise
    assert "BOOM-MARKER before abort" in text
    assert log.stat().st_size <= 64 * 1024 + 512  # ring + header
    # the manifest points at the artifact
    manifest = json.loads((session / "manifest.json").read_text())
    assert any("crash_stderr.log" in p for p in manifest.get("crash_logs", []))


def test_healthy_run_leaves_no_crash_log(tmp_path):
    proc, session = _launch(
        tmp_path,
        "import traceml_tpu\n"
        "with traceml_tpu.trace_step():\n"
        "    pass\n"
        "print('ok')\n",
        "healthy",
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert not list(session.rglob("crash_stderr.log"))


def test_sigterm_to_launcher_tears_down_tree(tmp_path):
    proc, logs = _launch(tmp_path, HANG_SCRIPT, "hang", wait=False)
    # wait until the rank process is actually up (session dir + manifest)
    deadline = time.monotonic() + 60
    session = None
    while time.monotonic() < deadline:
        sessions = (
            [p for p in logs.iterdir() if p.is_dir()] if logs.exists() else []
        )
        if sessions:
            session = sessions[0]
            manifest = json.loads((session / "manifest.json").read_text())
            if manifest.get("status") == "running":
                break
        time.sleep(0.2)
    assert session is not None, "launcher never reached running state"
    time.sleep(1.0)
    proc.send_signal(signal.SIGTERM)
    out, _ = proc.communicate(timeout=90)
    assert proc.returncode == 130, (proc.returncode, out[-2000:])
    manifest = json.loads((session / "manifest.json").read_text())
    assert manifest.get("status") == "failed"


def test_systemexit_message_reaches_crash_log(tmp_path):
    """SystemExit("message") must die loudly: the interpreter prints the
    message to stderr before exiting 1, and the executor must too — a
    swallowed message left an empty crash_stderr.log (found in r4
    verification when a demo scenario name was misspelled)."""
    proc, session = _launch(
        tmp_path,
        'raise SystemExit("unknown scenario \'slow_input\'")\n',
        "sysexit",
    )
    crash = session / "rank_0" / "crash_stderr.log"
    assert crash.exists(), "abnormal exit must leave a crash artifact"
    text = crash.read_text()
    assert "unknown scenario" in text, (
        f"SystemExit message swallowed; crash log was:\n{text}"
    )
