"""Fault-injection demo scenarios — the diagnosis acceptance harness
(reference: src/dev/demo/ mlp_ddp_input_straggler.py etc.; these are the
ground-truth precision/recall scenarios for the rule engine).
"""
