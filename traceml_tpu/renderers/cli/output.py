"""Rank-0 output tail panel (reference: renderers/stdout_stderr_renderer.py)."""

from __future__ import annotations

from typing import Any, Dict

from rich.panel import Panel
from rich.text import Text


def stdout_panel(payload: Dict[str, Any]) -> Panel:
    lines = payload.get("stdout") or []
    if not lines:
        return Panel(Text("—", style="dim"), title="rank 0 output")
    text = Text()
    for stream, line in lines[-10:]:
        style = "red" if stream == "stderr" else ""
        text.append(line[:160] + "\n", style=style)
    return Panel(text, title="rank 0 output")
