"""Optional per-rank on-disk telemetry backup
(reference: src/traceml_ai/database/database_writer.py:28-137).

Append-only files under ``<logs>/<session>/rank_N/data/<sampler>/``,
used for post-mortem `inspect` when the aggregator was unreachable.
Two frame formats coexist (see docs/developer_guide/rank-producer-path.md):

* **v1 (per-row)** — ``u32_be(len) + codec(row)``, one file per table
  (``<table>.msgpack``).  Written by the legacy collect path
  (:meth:`DatabaseWriter.flush` on a writer that was never fed
  envelopes).
* **v2 (envelope frame)** — ``b"TMB2" + u32_be(len) + codec(envelope)``
  appended to ``envelopes.msgpack``.  The envelope body is the SAME
  pre-encoded bytes the wire ships (single-encode contract); the magic
  reads as a ~1.4 GB length to a v1 reader, beyond its 64 MiB
  corruption bound, so old readers stop cleanly instead of misparsing.

:func:`iter_backup_file` reads both formats, in any mix within one
file.  Flushes are throttled; failures are logged and swallowed.
"""

from __future__ import annotations

import struct
from pathlib import Path
from typing import Dict, Iterator, Optional, Tuple

from traceml_tpu.database.database import Database
from traceml_tpu.utils import msgpack_codec
from traceml_tpu.utils.error_log import get_error_log

_LEN = struct.Struct(">I")
V2_MAGIC = b"TMB2"  # 0x544D4232 ≈ 1.4 GB as a big-endian length
ENVELOPE_FILE = "envelopes.msgpack"
_MAX_FRAME = 64 * 1024 * 1024
# envelope-buffer high-water mark: a burst between flush ticks must not
# hold unbounded encoded bytes in memory
_BUF_FLUSH_BYTES = 512 * 1024
_PREFIX_LEN = len(msgpack_codec.MSGPACK_PREFIX)


class DatabaseWriter:
    def __init__(
        self,
        sampler_name: str,
        db: Database,
        out_dir: Optional[Path],
        flush_every: int = 20,
    ) -> None:
        self._sampler = sampler_name
        self._db = db
        self._dir = Path(out_dir) / sampler_name if out_dir else None
        self._cursors: Dict[str, int] = {}
        self._flush_every = max(1, flush_every)
        self._calls = 0
        # v2 path: pre-encoded envelope frames buffered until the flush
        # throttle (or force, or the byte HWM) writes them in one append
        self._buf = bytearray()
        self._buf_envelopes = 0
        self._envelope_mode = False
        self.envelopes_written = 0

    @property
    def envelope_mode(self) -> bool:
        """True once the writer has been fed a pre-encoded envelope —
        the legacy per-row collect path is retired for its lifetime (the
        publisher owns collection; re-collecting here would double-write
        every row)."""
        return self._envelope_mode

    def mark_envelope_mode(self) -> None:
        """Commit to the envelope path up front.  The runtime publisher
        calls this at startup so a throttle-aligned ``flush`` can never
        race the sender into a legacy row collection (which would put
        the same rows on disk twice — once per-row, once in an
        envelope)."""
        self._envelope_mode = True

    def has_pending(self) -> bool:
        """O(1): buffered envelope bytes awaiting a disk write."""
        return bool(self._buf)

    def append_envelope(self, enc: "msgpack_codec.EncodedPayload") -> None:
        """Buffer one pre-encoded envelope as a v2 backup frame.

        The bytes are the same single encode the wire reuses — this is
        a length-prefix + concatenation, never a re-encode."""
        if self._dir is None:
            return
        self._envelope_mode = True
        buf = self._buf
        raw = enc.raw
        if raw is not None:
            # splice prefix + raw straight into the frame buffer — no
            # intermediate body concatenation
            buf += V2_MAGIC
            buf += _LEN.pack(len(raw) + _PREFIX_LEN)
            buf += msgpack_codec.MSGPACK_PREFIX
            buf += raw
        else:
            body = enc.body()
            buf += V2_MAGIC
            buf += _LEN.pack(len(body))
            buf += body
        self._buf_envelopes += 1
        if len(self._buf) >= _BUF_FLUSH_BYTES:
            self._write_buffer()

    def _write_buffer(self) -> int:
        if not self._buf:
            return 0
        n = self._buf_envelopes
        try:
            self._dir.mkdir(parents=True, exist_ok=True)
            with open(self._dir / ENVELOPE_FILE, "ab") as fh:
                fh.write(self._buf)
        except Exception as exc:
            get_error_log().warning(
                f"disk backup flush failed for sampler={self._sampler}", exc
            )
            return 0
        # buffer cleared only after a successful write: an OSError keeps
        # the frames for the next attempt instead of dropping them
        del self._buf[:]
        self._buf_envelopes = 0
        self.envelopes_written += n
        return n

    def flush(self, force: bool = False) -> int:
        """Write pending data to disk; returns rows (v1) or envelope
        frames (v2) written.  Throttled to every ``flush_every`` calls
        unless ``force``."""
        if self._dir is None:
            return 0
        self._calls += 1
        if not force and self._calls % self._flush_every:
            return 0
        if self._envelope_mode:
            return self._write_buffer()
        return self._flush_rows()

    def _flush_rows(self) -> int:
        """Legacy v1 path: collect rows from the database and write one
        per-row frame each (only for writers never fed envelopes —
        standalone tooling; the runtime publisher always pre-encodes)."""
        written = 0
        try:
            self._dir.mkdir(parents=True, exist_ok=True)
            for table in self._db.table_names():
                cursor = self._cursors.get(table, 0)
                rows, new_cursor = self._db.collect_since(table, cursor)
                if not rows:
                    self._cursors[table] = new_cursor
                    continue
                # One buffer, one write: a crash can only tear the final
                # frame, and the cursor advances only after a successful
                # write so no rows are silently dropped on OSError.
                buf = bytearray()
                for row in rows:
                    frame = msgpack_codec.encode(row)
                    buf += _LEN.pack(len(frame))
                    buf += frame
                path = self._dir / f"{table}.msgpack"
                with open(path, "ab") as fh:
                    fh.write(buf)
                self._cursors[table] = new_cursor
                written += len(rows)
        except Exception as exc:
            get_error_log().warning(
                f"disk backup flush failed for sampler={self._sampler}", exc
            )
        return written


def iter_backup_tables(
    path: Path,
) -> Iterator[Tuple[Optional[str], dict]]:
    """Decode an append-only backup file → yields ``(table, row)``.

    Handles both frame formats, freely mixed within one file: v1
    per-row frames yield ``(None, row)`` (their table is the file
    name); v2 envelope frames are unpacked into their tables and yield
    ``(table_name, row)`` per materialized row.  A torn/corrupt tail
    frame (crash mid-write) terminates iteration instead of raising —
    post-mortem inspection must work on exactly the runs that crashed.
    """
    from traceml_tpu.telemetry.envelope import normalize_telemetry_envelope

    with open(path, "rb") as fh:
        while True:
            hdr = fh.read(_LEN.size)
            if len(hdr) < _LEN.size:
                return
            if hdr == V2_MAGIC:
                hdr = fh.read(_LEN.size)
                if len(hdr) < _LEN.size:
                    return
                (n,) = _LEN.unpack(hdr)
                if n > _MAX_FRAME:
                    return
                body = fh.read(n)
                if len(body) < n:
                    return
                try:
                    payload = msgpack_codec.decode(body)
                except msgpack_codec.CodecError:
                    return
                env = normalize_telemetry_envelope(payload)
                if env is None:
                    continue  # decodable but not an envelope: skip frame
                for table in env.table_names():
                    for row in env.tables.get(table, []):
                        yield table, row
                continue
            (n,) = _LEN.unpack(hdr)
            if n > _MAX_FRAME:  # corrupt length → stop
                return
            body = fh.read(n)
            if len(body) < n:
                return
            try:
                yield None, msgpack_codec.decode(body)
            except msgpack_codec.CodecError:
                return


def iter_backup_file(path: Path):
    """Decode an append-only backup file → yields rows (used by
    `inspect`).  v2 envelope frames are flattened into their rows; use
    :func:`iter_backup_tables` when the table attribution matters."""
    for _table, row in iter_backup_tables(path):
        yield row
