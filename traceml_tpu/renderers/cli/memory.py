"""Device-memory CLI panel
(reference: renderers/step_memory/renderer.py — per-rank rows with
pressure highlighting and window growth)."""

from __future__ import annotations

from typing import Any, Dict, Optional

from rich.panel import Panel
from rich.table import Table
from rich.text import Text

from traceml_tpu.renderers.views import MemoryView
from traceml_tpu.utils.formatting import fmt_bytes, fmt_pct

_PRESSURE_WARN = 0.92
_PRESSURE_CRIT = 0.97


def step_memory_panel(payload: Dict[str, Any]) -> Panel:
    view: Optional[MemoryView] = (payload.get("views") or {}).get("memory")
    if view is None:
        return Panel(Text("no memory telemetry", style="dim"), title="device memory")
    table = Table(expand=True, box=None)
    table.add_column("rank", justify="right")
    table.add_column("device")
    table.add_column("current", justify="right")
    table.add_column("step peak", justify="right")
    table.add_column("limit", justify="right")
    table.add_column("pressure", justify="right")
    table.add_column("growth", justify="right")
    for s in view.ranks:
        style = ""
        if s.pressure is not None and s.pressure >= _PRESSURE_WARN:
            style = "bold red" if s.pressure >= _PRESSURE_CRIT else "yellow"
        growth = ""
        if s.growth_bytes:
            sign = "+" if s.growth_bytes > 0 else ""
            growth = f"{sign}{fmt_bytes(abs(s.growth_bytes))}"
            if s.growth_bytes < 0:
                growth = "-" + fmt_bytes(abs(s.growth_bytes))
        table.add_row(
            str(s.rank),
            s.device_kind,
            fmt_bytes(s.current_bytes),
            fmt_bytes(s.step_peak_bytes),
            fmt_bytes(s.limit_bytes),
            Text(fmt_pct(s.pressure) if s.pressure else "—", style=style),
            growth or "—",
        )
    sub = f"total {fmt_bytes(view.total_current_bytes)}"
    if view.worst_pressure_rank is not None:
        sub += f" · worst pressure rank {view.worst_pressure_rank}"
    # multi-rank: median/worst peak + skew (reference formatter's
    # summary rows, step_memory/formatter.py:102-166, as one line)
    peaks = {
        s.rank: s.step_peak_bytes
        for s in view.ranks
        if s.step_peak_bytes
    }
    if len(peaks) > 1:
        import statistics

        from traceml_tpu.utils.rankstats import worst_rank

        med = statistics.median(peaks.values())
        wr = worst_rank(peaks)
        if med > 0:
            skew = (peaks[wr] - med) / med
            sub += (
                f" · peak med {fmt_bytes(int(med))} / worst "
                f"{fmt_bytes(peaks[wr])} (r{wr}, +{skew * 100:.0f}%)"
            )
    return Panel(table, title="device memory", subtitle=sub)
