"""Vision Transformer encoder — the second model family.

Exists for the stress-scenario suite (the reference ships BERT/ViT
stress variants under dev/scenarios) and to exercise the NON-causal
attention path.  Same TPU-first conventions as the decoder: bf16
compute, static shapes, einsum attention, MXU-friendly dims.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from traceml_tpu.models.transformer import RMSNorm
from traceml_tpu.ops.attention import attention_reference


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 64
    patch_size: int = 8
    hidden: int = 256
    n_layers: int = 4
    n_heads: int = 4
    ffn_mult: float = 4.0
    n_classes: int = 10
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @classmethod
    def tiny(cls) -> "ViTConfig":
        return cls(image_size=32, patch_size=8, hidden=64, n_layers=2, n_heads=2)


class EncoderBlock(nn.Module):
    cfg: ViTConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        B, S, H = x.shape
        hd = cfg.hidden // cfg.n_heads
        y = RMSNorm(dtype=cfg.dtype, name="attn_norm")(x)
        q = nn.Dense(cfg.hidden, use_bias=False, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="wq")(y)
        k = nn.Dense(cfg.hidden, use_bias=False, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="wk")(y)
        v = nn.Dense(cfg.hidden, use_bias=False, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="wv")(y)
        q, k, v = (t.reshape(B, S, cfg.n_heads, hd) for t in (q, k, v))
        att = attention_reference(q, k, v, causal=False).reshape(B, S, cfg.hidden)
        x = x + nn.Dense(cfg.hidden, use_bias=False, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype, name="wo")(att)
        y = RMSNorm(dtype=cfg.dtype, name="mlp_norm")(x)
        h = nn.Dense(int(cfg.hidden * cfg.ffn_mult), dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="w_up")(y)
        x = x + nn.Dense(cfg.hidden, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype, name="w_down")(nn.gelu(h))
        return x


class ViT(nn.Module):
    cfg: ViTConfig

    @nn.compact
    def __call__(self, images):
        """images: (B, H, W, C) → logits (B, n_classes)."""
        cfg = self.cfg
        B = images.shape[0]
        p = cfg.patch_size
        x = nn.Conv(cfg.hidden, kernel_size=(p, p), strides=(p, p),
                    dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                    name="patch_embed")(images.astype(cfg.dtype))
        x = x.reshape(B, -1, cfg.hidden)  # (B, n_patches, hidden)
        pos = self.param(
            "pos_embed", nn.initializers.normal(0.02),
            (1, cfg.n_patches, cfg.hidden), cfg.param_dtype,
        )
        x = x + pos.astype(cfg.dtype)
        for i in range(cfg.n_layers):
            x = EncoderBlock(cfg, name=f"layer_{i}")(x)
        x = RMSNorm(dtype=cfg.dtype, name="final_norm")(x)
        x = x.mean(axis=1)  # mean-pool patches
        return nn.Dense(cfg.n_classes, dtype=jnp.float32,
                        param_dtype=cfg.param_dtype, name="head")(x)


def make_vit_train_step(model: ViT, learning_rate: float = 1e-3):
    import optax

    tx = optax.adamw(learning_rate)

    def init(rng, sample_images):
        params = model.init(rng, sample_images)["params"]
        return {"params": params, "opt_state": tx.init(params)}

    def train_step(state, images, labels):
        def loss_fn(p):
            logits = model.apply({"params": p}, images)
            onehot = jax.nn.one_hot(labels, logits.shape[-1])
            return -jnp.mean(jnp.sum(onehot * jax.nn.log_softmax(logits), -1))

        loss, grads = jax.value_and_grad(loss_fn)(state["params"])
        updates, opt_state = tx.update(grads, state["opt_state"], state["params"])
        return {
            "params": optax.apply_updates(state["params"], updates),
            "opt_state": opt_state,
        }, {"loss": loss}

    return init, train_step
