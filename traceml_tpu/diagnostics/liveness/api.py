"""Liveness diagnosis entrypoint.

Consumes a persisted ``rank_status.json`` snapshot (written by the
aggregator on the ingest-stats cadence and at settle-end).  The states
are used exactly as written — at report time every rank is silent, so
re-deriving from wall clock would mark the whole world LOST.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from traceml_tpu.diagnostics.common import (
    DiagnosticIssue,
    DiagnosticResult,
    SEVERITY_INFO,
    run_rules,
)
from traceml_tpu.diagnostics.liveness.policy import policy_for
from traceml_tpu.diagnostics.liveness.rules import DEFAULT_RULES, build_context

DOMAIN = "liveness"


def diagnose_rank_status(
    snapshot: Optional[Dict[str, Any]],
    mode: str = "summary",
    topology: Optional[Any] = None,
) -> DiagnosticResult:
    """``topology``: the captured mesh (or None).  A lost/stale cohort
    that maps onto one host or one DCN side gains an ``attribution``
    block (a whole host dropping is a very different page than eight
    scattered ranks)."""
    policy = policy_for(mode)
    if not snapshot or not isinstance(snapshot.get("ranks"), dict):
        return DiagnosticResult(
            domain=DOMAIN,
            issues=[
                DiagnosticIssue(
                    kind="NO_LIVENESS_DATA",
                    severity=SEVERITY_INFO,
                    status="ok",
                    summary=(
                        "No rank_status.json snapshot — liveness tracking "
                        "was unavailable (pre-heartbeat producers or an "
                        "untraced run)."
                    ),
                )
            ],
        )
    ctx = build_context(snapshot, policy)
    if len(ctx.ranks) < policy.min_ranks:
        return DiagnosticResult(domain=DOMAIN, issues=[])
    result = run_rules(DOMAIN, DEFAULT_RULES, ctx)
    if topology is not None:
        from traceml_tpu.diagnostics.attribution import attach_attribution

        # binary per-rank indicator: unhealthy (lost/stale) vs fine —
        # η² then measures how cleanly the dead set tiles a grouping
        values = {}
        for rank_s, info in (snapshot.get("ranks") or {}).items():
            try:
                rank = int(rank_s)
            except (TypeError, ValueError):
                continue
            state = str((info or {}).get("state", "")).upper()
            values[rank] = 1.0 if state in ("LOST", "STALE") else 0.0
        result = attach_attribution(result, topology, values)
    return result
