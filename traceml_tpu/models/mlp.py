"""Tiny MLP for demos/smoke tests (reference scenario model:
src/dev/demo uses a small DDP MLP)."""

from __future__ import annotations

from typing import Any, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


class TinyMLP(nn.Module):
    hidden: int = 128
    depth: int = 2
    out: int = 1

    @nn.compact
    def __call__(self, x):
        for _ in range(self.depth):
            x = nn.tanh(nn.Dense(self.hidden)(x))
        return nn.Dense(self.out)(x)


def make_mlp_train_step(model: TinyMLP, learning_rate: float = 1e-3):
    import optax

    tx = optax.adam(learning_rate)

    def init(rng, sample_x) -> Tuple[Any, Any]:
        params = model.init(rng, sample_x)["params"]
        return params, tx.init(params)

    def train_step(params, opt_state, x, y):
        def loss_fn(p):
            pred = model.apply({"params": p}, x)
            return jnp.mean((pred - y) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return init, train_step
