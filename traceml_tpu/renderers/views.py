"""Typed per-domain view schema — the shared contract between every
render surface (CLI panels, browser payload, report sections)
(reference pattern: renderers/step_time/schema.py:50 ``StepCombinedTimeMetric``
and the per-domain computer modules; rebuilt here as one schema module
because all our surfaces consume identical shapes).

Each domain exposes a ``build_*_view()`` that turns loader output into a
frozen view object.  ALL metric math lives here; render surfaces only
format.  Views are plain dataclasses with an ``as_dict()`` so the browser
payload is literally the same object the CLI renders — one computation,
N surfaces.
"""

from __future__ import annotations

import dataclasses
import statistics
import time
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from traceml_tpu.utils.columnar import (
    KEY_INDEX,
    MemoryColumns,
    note_vector_fallback,
    vector_diagnosis_enabled,
)
from traceml_tpu.utils.step_time_window import (
    ALL_KEYS,
    RESIDUAL_KEY,
    STEP_KEY,
    StepTimeWindow,
)

_STALE_AFTER_S = 5.0


def _fast_asdict(obj: Any) -> Any:
    """Value-identical replacement for the ``dataclasses.asdict`` walk.

    ``asdict`` routes every leaf through ``copy.deepcopy`` — ~100 ms per
    tick at 1024 ranks for view payloads that are pure primitives.  This
    walk builds fresh dicts/lists (callers may cache the result) but
    passes primitives through untouched; the inline float/int test keeps
    the numeric-series whale (rank → per-step ms lists) out of the
    recursion.  json output is byte-identical to the asdict path."""
    if type(obj) is dict:
        return {k: _fast_asdict(v) for k, v in obj.items()}
    if type(obj) is list or type(obj) is tuple:
        return [
            v if type(v) is float or type(v) is int else _fast_asdict(v)
            for v in obj
        ]
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {
            f.name: _fast_asdict(getattr(obj, f.name))
            for f in dataclasses.fields(obj)
        }
    return obj


def _asdict(obj: Any) -> Any:
    if vector_diagnosis_enabled():
        return _fast_asdict(obj)
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {k: _asdict(v) for k, v in dataclasses.asdict(obj).items()}
    return obj


# ---------------------------------------------------------------------------
# step time
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class PhaseStat:
    """Cross-rank stats for one phase over the aligned window."""

    key: str
    median_ms: float
    worst_ms: float
    worst_rank: int
    skew_pct: float
    share: Optional[float]  # median(phase)/median(step); None for step itself
    # rank whose window avg sits closest to the cross-rank median —
    # both ends of the spread name a concrete rank (report parity)
    median_rank: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class Coverage:
    """How much of the run the window actually covers
    (reference: StepCombinedTimeCoverage)."""

    world_size: int
    ranks_present: int
    steps_used: int
    last_step: Optional[int]
    incomplete: bool  # fewer ranks reporting than the declared world


@dataclasses.dataclass(frozen=True)
class StepTimeView:
    clock: str
    n_steps: int
    coverage: Coverage
    phases: List[PhaseStat]                      # step first, residual last
    per_rank_avg_ms: Dict[int, Dict[str, float]]  # rank → phase → window avg
    steps: List[int]                              # aligned step ids (tail)
    step_series: Dict[str, List[float]]           # rank(str) → per-step step_ms
    phase_stack: Dict[str, List[float]]           # phase → cross-rank median/step
    occupancy_by_rank: Dict[str, float]           # device-busy share of wall
    median_occupancy: Optional[float]
    # MFU block (achieved TFLOP/s + mfu vs chip peak) when model FLOPs
    # were declared; None otherwise
    efficiency: Optional[Dict[str, Any]]
    latest_ts: Optional[float]

    def as_dict(self) -> Dict[str, Any]:
        return _asdict(self)


def _step_time_tables(
    window: StepTimeWindow, series_tail: int
) -> Dict[str, Any]:
    """Every window-derived table in the step_time view — pure function
    of the window, so LiveComputer memoizes the result per step_time
    store version (``table_cache``): a model_stats-only tick then
    rebuilds only the MFU block instead of re-reducing the cube."""
    from traceml_tpu.utils.rankstats import closest_rank_to_median

    phases: List[PhaseStat] = []
    for key in [STEP_KEY] + window.phases_present + [RESIDUAL_KEY]:
        m = window.metric(key)
        if m is None:
            continue
        med_rank = closest_rank_to_median(m.per_rank_avg_ms)
        phases.append(
            PhaseStat(
                key=key,
                median_ms=m.median_ms,
                worst_ms=m.worst_ms,
                worst_rank=m.worst_rank,
                median_rank=(
                    int(med_rank) if med_rank is not None else None
                ),
                skew_pct=m.skew_pct,
                share=window.share_of_step(key) if key != STEP_KEY else None,
            )
        )
    tail = window.steps[-series_tail:]
    offset = len(window.steps) - len(tail)
    col = getattr(window, "col", None)
    phase_stack: Dict[str, List[float]] = {}
    if col is not None:
        # columnar fast path: series / per-phase cross-rank medians /
        # per-rank averages straight off the cube (tolist() BEFORE
        # round() so the values are native floats, identical to scalar)
        step_series = {
            str(r): [round(v, 4) for v in row]
            for r, row in zip(
                col.ranks, col.series_cube[:, KEY_INDEX[STEP_KEY], offset:].tolist()
            )
        }
        for key in window.phases_present + [RESIDUAL_KEY]:
            med = np.median(col.series_cube[:, KEY_INDEX[key], offset:], axis=0)
            phase_stack[key] = [round(v, 4) for v in med.tolist()]
        per_rank_avg = {
            r: {k: round(v, 4) for k, v in zip(ALL_KEYS, row)}
            for r, row in zip(col.ranks, col.averages.tolist())
        }
    else:
        step_series = {
            str(r): [round(v, 4) for v in w.series[STEP_KEY][offset:]]
            for r, w in window.rank_windows.items()
        }
        # cross-rank median per phase per step — the stacking series the
        # dashboard charts consume (reference: StepCombinedTimeSeries)
        rw = list(window.rank_windows.values())
        for key in window.phases_present + [RESIDUAL_KEY]:
            per_step = []
            for i in range(offset, len(window.steps)):
                vals = [w.series[key][i] for w in rw if i < len(w.series[key])]
                per_step.append(
                    round(statistics.median(vals), 4) if vals else 0.0
                )
            phase_stack[key] = per_step
        per_rank_avg = {
            r: {k: round(v, 4) for k, v in w.averages.items()}
            for r, w in window.rank_windows.items()
        }
    return {
        "phases": phases,
        "tail": tail,
        "step_series": step_series,
        "phase_stack": phase_stack,
        "per_rank_avg": per_rank_avg,
        "occupancy": {
            str(r): round(v, 4) for r, v in window.occupancy_by_rank.items()
        },
        "median_occupancy": window.median_occupancy,
    }


def build_step_time_view(
    window: Optional[StepTimeWindow],
    *,
    world_size: Optional[int] = None,
    latest_ts: Optional[float] = None,
    series_tail: int = 60,
    model_stats: Optional[Dict[int, Dict[str, Any]]] = None,
    table_cache: Optional[Dict[str, Any]] = None,
) -> Optional[StepTimeView]:
    if window is None:
        return None
    if table_cache is not None and "tables" in table_cache:
        t = table_cache["tables"]
    else:
        t = _step_time_tables(window, series_tail)
        if table_cache is not None:
            table_cache["tables"] = t
    per_rank_avg = t["per_rank_avg"]
    world = max(world_size or 0, len(window.ranks))
    return StepTimeView(
        clock=window.clock,
        n_steps=window.n_steps,
        coverage=Coverage(
            world_size=world,
            ranks_present=len(window.ranks),
            steps_used=window.n_steps,
            last_step=window.steps[-1] if window.steps else None,
            incomplete=len(window.ranks) < world,
        ),
        phases=t["phases"],
        per_rank_avg_ms=per_rank_avg,
        steps=t["tail"],
        step_series=t["step_series"],
        phase_stack=t["phase_stack"],
        occupancy_by_rank=t["occupancy"],
        median_occupancy=t["median_occupancy"],
        efficiency=_efficiency_from_stats(model_stats, per_rank_avg),
        latest_ts=latest_ts,
    )


def _efficiency_from_stats(model_stats, per_rank_avg) -> Optional[Dict[str, Any]]:
    """Live MFU from model_stats + the window's per-rank step averages
    (the live view has no steady-state split — the rolling window is
    already recent steps only).  Formula shared with the final summary
    via analytics/efficiency.py."""
    from traceml_tpu.analytics.efficiency import build_efficiency

    return build_efficiency(
        model_stats,
        {r: avgs.get(STEP_KEY) for r, avgs in per_rank_avg.items()},
    )


# ---------------------------------------------------------------------------
# step memory
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class MemoryRankStat:
    rank: int
    device_id: Optional[int]
    device_kind: str
    current_bytes: Optional[int]
    step_peak_bytes: Optional[int]
    alloc_peak_bytes: Optional[int]   # allocator cumulative peak
    limit_bytes: Optional[int]
    pressure: Optional[float]         # step_peak/limit when limit known
    growth_bytes: Optional[int]       # last − first current in window
    history: List[int]                # per-sample current_bytes tail


@dataclasses.dataclass(frozen=True)
class MemoryView:
    ranks: List[MemoryRankStat]
    worst_pressure_rank: Optional[int]
    total_current_bytes: int
    latest_ts: Optional[float]

    def as_dict(self) -> Dict[str, Any]:
        return _asdict(self)


def build_memory_view(
    rows_by_rank: Mapping[int, Sequence[Mapping[str, Any]]],
    *,
    history_tail: int = 60,
    columns: Optional[Mapping[int, MemoryColumns]] = None,
) -> Optional[MemoryView]:
    if not isinstance(rows_by_rank, Mapping) or not rows_by_rank:
        return None
    stats: List[MemoryRankStat] = []
    latest_ts: Optional[float] = None
    for rank in sorted(rows_by_rank):
        rows = [r for r in rows_by_rank[rank] if r]
        if not rows:
            continue
        last = rows[-1]
        cur = last.get("current_bytes")
        step_peak = last.get("step_peak_bytes")
        limit = last.get("limit_bytes")
        # the per-row walk (first non-null current + history tail) has a
        # columnar fast path over the rank's ring buffer; -1 == NULL,
        # arrival order matches the row list exactly
        col = columns.get(rank) if columns is not None else None
        if col is not None and len(col) == len(rows) and col.columnar_ok:
            cur_col = col.column(2)  # C_CUR
            nn = np.flatnonzero(cur_col >= 0)
            first_cur = int(cur_col[nn[0]]) if nn.size else None
            history = np.maximum(cur_col[-history_tail:], 0).tolist()
        else:
            first_cur = next(
                (
                    r.get("current_bytes")
                    for r in rows
                    if r.get("current_bytes") is not None
                ),
                None,
            )
            history = [
                int(r.get("current_bytes") or 0) for r in rows[-history_tail:]
            ]
        ts = last.get("timestamp")
        if ts is not None:
            latest_ts = max(latest_ts or 0.0, float(ts))
        stats.append(
            MemoryRankStat(
                rank=int(rank),
                device_id=last.get("device_id"),
                device_kind=str(last.get("device_kind") or "unknown"),
                current_bytes=cur,
                step_peak_bytes=step_peak,
                alloc_peak_bytes=last.get("peak_bytes"),
                limit_bytes=limit,
                pressure=((step_peak or cur or 0) / limit) if limit else None,
                growth_bytes=(cur - first_cur)
                if cur is not None and first_cur is not None
                else None,
                history=history,
            )
        )
    if not stats:
        return None
    with_pressure = [s for s in stats if s.pressure is not None]
    worst = max(with_pressure, key=lambda s: s.pressure).rank if with_pressure else None
    return MemoryView(
        ranks=stats,
        worst_pressure_rank=worst,
        total_current_bytes=sum(s.current_bytes or 0 for s in stats),
        latest_ts=latest_ts,
    )


# ---------------------------------------------------------------------------
# collectives (compute/comm overlap)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class CollectiveOpStat:
    """Window totals for one collective op kind."""

    op: str
    count: int
    bytes: int
    duration_ms: float
    exposed_ms: float
    overlap_efficiency: float


@dataclasses.dataclass(frozen=True)
class CollectivesView:
    n_steps: int
    ranks_present: int
    group_size: int
    steps: List[int]                      # aligned step ids (tail)
    comm_ms_series: List[float]           # per-step total collective time
    exposed_ms_series: List[float]        # per-step exposed (un-overlapped)
    overlap_series: List[float]           # per-step 1 − exposed/total
    comm_ms_per_step: float
    exposed_ms_per_step: float
    bytes_per_step: float
    overlap_efficiency: float             # window total
    # shares of the mean step time, when step_time telemetry is present
    comm_share: Optional[float]
    exposed_share: Optional[float]
    ops: List[CollectiveOpStat]           # sorted by duration desc
    per_rank_efficiency: Dict[str, float]
    worst_overlap_rank: Optional[int]
    latest_ts: Optional[float]

    def as_dict(self) -> Dict[str, Any]:
        return _asdict(self)


def _collectives_rank_table(
    per_rank: Mapping[int, Mapping[str, float]],
) -> Optional[Tuple[Dict[str, float], Optional[int]]]:
    """Vectorized per-rank table: gather the window's per-rank dicts
    into rank-slot arrays once, then do the cross-rank reductions
    (sort, masked first-min) in numpy.  tolist() BEFORE round() so the
    values are native floats, identical to the scalar twin.  None on
    any surprise — the caller falls back to the scalar arm."""
    try:
        items = list(per_rank.items())
        ranks = np.asarray([r for r, _ in items], dtype=np.int64)
        eff = np.asarray(
            [float(v["overlap_efficiency"]) for _, v in items],
            dtype=np.float64,
        )
        dur = np.asarray(
            [float(v.get("duration_ms", 0.0)) for _, v in items],
            dtype=np.float64,
        )
        order = np.argsort(ranks, kind="stable")
        table = {
            str(r): round(v, 4)
            for r, v in zip(ranks[order].tolist(), eff[order].tolist())
        }
        # first minimum among comm-active ranks in insertion order ==
        # the scalar arm's min()-with-key tie-break
        mask = dur > 0.0
        worst = None
        if bool(mask.any()):
            idx = np.flatnonzero(mask)
            worst = int(ranks[idx[int(np.argmin(eff[idx]))]])
        return table, worst
    except Exception:
        note_vector_fallback("collectives_view")
        return None


def build_collectives_view(
    window: Any,
    *,
    step_time_ms: Optional[float] = None,
    latest_ts: Optional[float] = None,
    series_tail: int = 60,
) -> Optional[CollectivesView]:
    """``window`` is a :class:`~traceml_tpu.utils.columnar.CollectivesWindow`;
    ``step_time_ms`` is the mean step duration from the step_time window so
    the view can express comm as a share of the step."""
    if window is None or not window.n_steps:
        return None
    n = window.n_steps
    offset = max(0, n - series_tail)
    dur = window.per_step["duration_ms"]
    exp = window.per_step["exposed_ms"]
    eff = window.per_step["overlap_efficiency"]
    comm_per_step = window.totals["duration_ms"] / n
    exposed_per_step = window.totals["exposed_ms"] / n
    comm_share = exposed_share = None
    if step_time_ms is not None and step_time_ms > 0:
        comm_share = round(comm_per_step / step_time_ms, 4)
        exposed_share = round(exposed_per_step / step_time_ms, 4)
    ops = [
        CollectiveOpStat(
            op=op,
            count=int(v.get("count", 0)),
            bytes=int(v.get("bytes", 0)),
            duration_ms=round(float(v.get("duration_ms", 0.0)), 4),
            exposed_ms=round(float(v.get("exposed_ms", 0.0)), 4),
            overlap_efficiency=round(
                1.0 - v["exposed_ms"] / v["duration_ms"]
                if v.get("duration_ms", 0.0) > 0
                else 1.0,
                4,
            ),
        )
        for op, v in window.per_op.items()
    ]
    ops.sort(key=lambda o: -o.duration_ms)
    vec = (
        _collectives_rank_table(window.per_rank)
        if vector_diagnosis_enabled()
        else None
    )
    if vec is not None:
        per_rank_eff, worst = vec
    else:  # scalar golden-reference arm (TRACEML_VECTOR_DIAGNOSIS=0)
        per_rank_eff = {
            str(r): round(float(v["overlap_efficiency"]), 4)
            for r, v in sorted(window.per_rank.items())
        }
        comm_ranks = [
            (r, v)
            for r, v in window.per_rank.items()
            if v.get("duration_ms", 0.0) > 0
        ]
        worst = (
            min(comm_ranks, key=lambda kv: kv[1]["overlap_efficiency"])[0]
            if comm_ranks
            else None
        )
    return CollectivesView(
        n_steps=n,
        ranks_present=len(window.ranks),
        group_size=int(window.group_size),
        steps=list(window.steps[offset:]),
        comm_ms_series=[round(float(v), 4) for v in dur[offset:]],
        exposed_ms_series=[round(float(v), 4) for v in exp[offset:]],
        overlap_series=[round(float(v), 4) for v in eff[offset:]],
        comm_ms_per_step=round(comm_per_step, 4),
        exposed_ms_per_step=round(exposed_per_step, 4),
        bytes_per_step=round(window.totals["bytes"] / n, 1),
        overlap_efficiency=round(window.totals["overlap_efficiency"], 4),
        comm_share=comm_share,
        exposed_share=exposed_share,
        ops=ops,
        per_rank_efficiency=per_rank_eff,
        worst_overlap_rank=int(worst) if worst is not None else None,
        latest_ts=latest_ts,
    )


# ---------------------------------------------------------------------------
# serving (inference request lifecycle)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ServingReplicaStat:
    """Window aggregates for one serving replica."""

    rank: int
    requests_completed: int
    requests_active: int
    decode_tokens: int
    tokens_per_s: float
    queue_depth: int
    ttft_p99_ms: float
    kv_headroom: Optional[float]  # None when never sampled


@dataclasses.dataclass(frozen=True)
class ServingView:
    n_steps: int
    replicas_present: int
    steps: List[int]                      # window seqs (tail)
    queue_depth_series: List[int]         # per-window cluster backlog
    completed_series: List[int]           # per-window completed requests
    tokens_per_s_series: List[float]      # per-window cluster tokens/s
    requests_enqueued: int
    requests_completed: int
    decode_tokens: int
    tokens_per_s: float                   # cluster throughput
    queue_depth: int                      # backlog at window close
    queue_depth_max: int
    prefill_ms: float
    decode_ms: float
    decode_share: float
    ttft_p50_ms: float
    ttft_p95_ms: float
    ttft_p99_ms: float
    e2e_p50_ms: float
    e2e_p95_ms: float
    e2e_p99_ms: float
    kv_headroom_min: Optional[float]      # None when never sampled
    replicas: List[ServingReplicaStat]    # sorted by tokens/s asc (worst first)
    slowest_replica: Optional[int]
    latest_ts: Optional[float]

    def as_dict(self) -> Dict[str, Any]:
        return _asdict(self)


def _serving_replica_table(
    per_rank: Mapping[int, Mapping[str, float]],
) -> Optional[Tuple[List["ServingReplicaStat"], Optional[int]]]:
    """Vectorized replica table: per-field rank-slot arrays, stable
    argsort on the ROUNDED throughput (the scalar twin sorts the
    already-rounded dataclasses, and stable order among ties is
    ascending rank).  None on any surprise — caller falls back."""
    try:
        items = sorted(per_rank.items())
        ranks = [int(r) for r, _ in items]
        comp = np.asarray(
            [v.get("requests_completed", 0) for _, v in items], dtype=np.int64
        ).tolist()
        act = np.asarray(
            [v.get("requests_active", 0) for _, v in items], dtype=np.int64
        ).tolist()
        dtok = np.asarray(
            [v.get("decode_tokens", 0) for _, v in items], dtype=np.int64
        ).tolist()
        qd = np.asarray(
            [v.get("queue_depth", 0) for _, v in items], dtype=np.int64
        ).tolist()
        tps = [
            round(v, 3)
            for v in np.asarray(
                [float(v.get("tokens_per_s", 0.0)) for _, v in items],
                dtype=np.float64,
            ).tolist()
        ]
        p99 = [
            round(v, 3)
            for v in np.asarray(
                [float(v.get("ttft_p99_ms", 0.0)) for _, v in items],
                dtype=np.float64,
            ).tolist()
        ]
        kv = np.asarray(
            [float(v.get("kv_headroom", -1.0)) for _, v in items],
            dtype=np.float64,
        ).tolist()
        order = np.argsort(
            np.asarray(tps, dtype=np.float64), kind="stable"
        ).tolist()
        replicas = [
            ServingReplicaStat(
                rank=ranks[i],
                requests_completed=comp[i],
                requests_active=act[i],
                decode_tokens=dtok[i],
                tokens_per_s=tps[i],
                queue_depth=qd[i],
                ttft_p99_ms=p99[i],
                kv_headroom=round(kv[i], 4) if kv[i] >= 0.0 else None,
            )
            for i in order
        ]
        slowest = (
            replicas[0].rank
            if replicas and any(t > 0 for t in tps)
            else None
        )
        return replicas, slowest
    except Exception:
        note_vector_fallback("serving_view")
        return None


def build_serving_view(
    window: Any,
    *,
    latest_ts: Optional[float] = None,
    series_tail: int = 60,
) -> Optional[ServingView]:
    """``window`` is a :class:`~traceml_tpu.utils.columnar.ServingWindow`
    (TTFT/e2e percentiles already re-ranked over the raw populations)."""
    if window is None or not window.n_steps:
        return None
    n = window.n_steps
    offset = max(0, n - series_tail)
    t = window.totals
    kv_min = float(t.get("kv_headroom_min", -1.0))
    vec = (
        _serving_replica_table(window.per_rank)
        if vector_diagnosis_enabled()
        else None
    )
    if vec is not None:
        replicas, slowest = vec
    else:  # scalar golden-reference arm (TRACEML_VECTOR_DIAGNOSIS=0)
        replicas = [
            ServingReplicaStat(
                rank=int(r),
                requests_completed=int(v.get("requests_completed", 0)),
                requests_active=int(v.get("requests_active", 0)),
                decode_tokens=int(v.get("decode_tokens", 0)),
                tokens_per_s=round(float(v.get("tokens_per_s", 0.0)), 3),
                queue_depth=int(v.get("queue_depth", 0)),
                ttft_p99_ms=round(float(v.get("ttft_p99_ms", 0.0)), 3),
                kv_headroom=(
                    round(float(v["kv_headroom"]), 4)
                    if float(v.get("kv_headroom", -1.0)) >= 0.0
                    else None
                ),
            )
            for r, v in sorted(window.per_rank.items())
        ]
        replicas.sort(key=lambda s: s.tokens_per_s)
        slowest = (
            replicas[0].rank
            if replicas and any(s.tokens_per_s > 0 for s in replicas)
            else None
        )
    return ServingView(
        n_steps=n,
        replicas_present=len(window.ranks),
        steps=list(window.steps[offset:]),
        queue_depth_series=[
            int(v) for v in window.per_step["queue_depth"][offset:]
        ],
        completed_series=[
            int(v) for v in window.per_step["requests_completed"][offset:]
        ],
        tokens_per_s_series=[
            round(float(v), 3) for v in window.per_step["tokens_per_s"][offset:]
        ],
        requests_enqueued=int(t.get("requests_enqueued", 0)),
        requests_completed=int(t.get("requests_completed", 0)),
        decode_tokens=int(t.get("decode_tokens", 0)),
        tokens_per_s=round(float(t.get("tokens_per_s", 0.0)), 3),
        queue_depth=int(t.get("queue_depth_last", 0)),
        queue_depth_max=int(t.get("queue_depth_max", 0)),
        prefill_ms=round(float(t.get("prefill_ms", 0.0)), 3),
        decode_ms=round(float(t.get("decode_ms", 0.0)), 3),
        decode_share=round(float(t.get("decode_share", 0.0)), 4),
        ttft_p50_ms=round(float(t.get("ttft_p50_ms", 0.0)), 3),
        ttft_p95_ms=round(float(t.get("ttft_p95_ms", 0.0)), 3),
        ttft_p99_ms=round(float(t.get("ttft_p99_ms", 0.0)), 3),
        e2e_p50_ms=round(float(t.get("e2e_p50_ms", 0.0)), 3),
        e2e_p95_ms=round(float(t.get("e2e_p95_ms", 0.0)), 3),
        e2e_p99_ms=round(float(t.get("e2e_p99_ms", 0.0)), 3),
        kv_headroom_min=round(kv_min, 4) if kv_min >= 0.0 else None,
        replicas=replicas,
        slowest_replica=slowest,
        latest_ts=latest_ts,
    )


# ---------------------------------------------------------------------------
# system (host + devices), incl. the multi-node cluster rollup
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class DeviceStat:
    device_id: int
    device_kind: str
    memory_used_bytes: Optional[int]
    memory_total_bytes: Optional[int]
    utilization_pct: Optional[float]
    temperature_c: Optional[float]
    power_w: Optional[float]


@dataclasses.dataclass(frozen=True)
class NodeSystemStat:
    node_rank: int
    hostname: str
    cpu_pct: Optional[float]
    memory_used_bytes: Optional[int]
    memory_total_bytes: Optional[int]
    memory_pct: Optional[float]
    load_1m: Optional[float]
    devices: List[DeviceStat]
    cpu_history: List[float]
    latest_ts: Optional[float]
    stale: bool


@dataclasses.dataclass(frozen=True)
class ClusterRollup:
    """min/median/max of one metric across nodes
    (reference: system/cli_cluster.py _MetricRollup)."""

    metric: str
    min_value: float
    median_value: float
    max_value: float
    min_node: str
    max_node: str


@dataclasses.dataclass(frozen=True)
class SystemView:
    nodes: List[NodeSystemStat]
    rollups: List[ClusterRollup]      # non-empty only in multi-node runs
    expected_nodes: int
    missing_nodes: int
    latest_ts: Optional[float]

    @property
    def is_cluster(self) -> bool:
        return len(self.nodes) > 1 or self.expected_nodes > 1

    def as_dict(self) -> Dict[str, Any]:
        d = _asdict(self)
        d["is_cluster"] = self.is_cluster
        return d


def _rollup(metric: str, values: List[Tuple[str, float]]) -> Optional[ClusterRollup]:
    vals = [(n, v) for n, v in values if v is not None]
    if not vals:
        return None
    ordered = sorted(vals, key=lambda t: t[1])
    return ClusterRollup(
        metric=metric,
        min_value=ordered[0][1],
        median_value=statistics.median(v for _, v in ordered),
        max_value=ordered[-1][1],
        min_node=ordered[0][0],
        max_node=ordered[-1][0],
    )


def build_system_view(
    host_rows: Mapping[int, Sequence[Mapping[str, Any]]],
    device_rows: Mapping[tuple, Sequence[Mapping[str, Any]]] | None = None,
    *,
    expected_nodes: Optional[int] = None,
    now: Optional[float] = None,
    history_tail: int = 60,
) -> Optional[SystemView]:
    if not host_rows:
        return None
    now = time.time() if now is None else now
    device_rows = device_rows or {}
    nodes: List[NodeSystemStat] = []
    latest_ts: Optional[float] = None
    for node in sorted(host_rows):
        rows = [r for r in host_rows[node] if r]
        if not rows:
            continue
        last = rows[-1]
        ts = last.get("timestamp")
        if ts is not None:
            latest_ts = max(latest_ts or 0.0, float(ts))
        devices: List[DeviceStat] = []
        for (dnode, did), drows in sorted(device_rows.items()):
            if dnode != node or not drows:
                continue
            dlast = drows[-1]
            devices.append(
                DeviceStat(
                    device_id=int(did),
                    device_kind=str(dlast.get("device_kind") or "unknown"),
                    memory_used_bytes=dlast.get("memory_used_bytes"),
                    memory_total_bytes=dlast.get("memory_total_bytes"),
                    utilization_pct=dlast.get("utilization_pct"),
                    temperature_c=dlast.get("temperature_c"),
                    power_w=dlast.get("power_w"),
                )
            )
        nodes.append(
            NodeSystemStat(
                node_rank=int(node),
                hostname=str(last.get("hostname") or f"node{node}"),
                cpu_pct=last.get("cpu_pct"),
                memory_used_bytes=last.get("memory_used_bytes"),
                memory_total_bytes=last.get("memory_total_bytes"),
                memory_pct=last.get("memory_pct"),
                load_1m=last.get("load_1m"),
                devices=devices,
                cpu_history=[
                    float(r.get("cpu_pct") or 0.0) for r in rows[-history_tail:]
                ],
                latest_ts=float(ts) if ts is not None else None,
                stale=(now - float(ts)) > _STALE_AFTER_S if ts is not None else False,
            )
        )
    if not nodes:
        return None
    rollups: List[ClusterRollup] = []
    if len(nodes) > 1:
        for metric, getter in (
            ("cpu_pct", lambda n: n.cpu_pct),
            ("memory_pct", lambda n: n.memory_pct),
            ("load_1m", lambda n: n.load_1m),
        ):
            r = _rollup(metric, [(n.hostname, getter(n)) for n in nodes])
            if r is not None:
                rollups.append(r)
    expected = max(expected_nodes or 0, len(nodes))
    return SystemView(
        nodes=nodes,
        rollups=rollups,
        expected_nodes=expected,
        missing_nodes=max(0, expected - len(nodes)),
        latest_ts=latest_ts,
    )


# ---------------------------------------------------------------------------
# process
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ProcessRankStat:
    rank: int
    hostname: str
    pid: Optional[int]
    cpu_pct: Optional[float]
    rss_bytes: Optional[int]
    vms_bytes: Optional[int]
    num_threads: Optional[int]
    cpu_history: List[float]
    latest_ts: Optional[float]
    stale: bool


@dataclasses.dataclass(frozen=True)
class ProcessView:
    ranks: List[ProcessRankStat]
    busiest_rank: Optional[int]
    total_rss_bytes: int
    latest_ts: Optional[float]

    def as_dict(self) -> Dict[str, Any]:
        return _asdict(self)


def build_process_view(
    procs: Mapping[int, Sequence[Mapping[str, Any]]],
    *,
    now: Optional[float] = None,
    history_tail: int = 60,
) -> Optional[ProcessView]:
    if not procs:
        return None
    now = time.time() if now is None else now
    stats: List[ProcessRankStat] = []
    latest_ts: Optional[float] = None
    for rank in sorted(procs):
        rows = [r for r in procs[rank] if r]
        if not rows:
            continue
        last = rows[-1]
        ts = last.get("timestamp")
        if ts is not None:
            latest_ts = max(latest_ts or 0.0, float(ts))
        stats.append(
            ProcessRankStat(
                rank=int(rank),
                hostname=str(last.get("hostname") or ""),
                pid=last.get("pid"),
                cpu_pct=last.get("cpu_pct"),
                rss_bytes=last.get("rss_bytes"),
                vms_bytes=last.get("vms_bytes"),
                num_threads=last.get("num_threads"),
                cpu_history=[
                    float(r.get("cpu_pct") or 0.0) for r in rows[-history_tail:]
                ],
                latest_ts=float(ts) if ts is not None else None,
                stale=(now - float(ts)) > _STALE_AFTER_S if ts is not None else False,
            )
        )
    if not stats:
        return None
    with_cpu = [s for s in stats if s.cpu_pct is not None]
    busiest = max(with_cpu, key=lambda s: s.cpu_pct).rank if with_cpu else None
    return ProcessView(
        ranks=stats,
        busiest_rank=busiest,
        total_rss_bytes=sum(s.rss_bytes or 0 for s in stats),
        latest_ts=latest_ts,
    )
