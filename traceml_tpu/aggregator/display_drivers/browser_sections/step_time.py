"""Step-time detail section (reference role: the chart half of
nicegui_sections/model_combined_section.py plus the per-rank series the
reference's step-time renderer draws).

Adds the interactivity the round-3 page lacked (VERDICT r3 item 2):
* stacked per-step phase chart with a crosshair TOOLTIP (hover shows
  the step id and each phase's ms at that step);
* per-rank sparkline with clickable legend chips — a rank toggle that
  hides/shows individual ranks (state survives repaints);
* the phase table (median / share / worst rank / skew) as before.
"""

from __future__ import annotations

from traceml_tpu.aggregator.display_drivers.browser_sections import Section

_HTML = """
<div class="chead"><h2 class="ctitle">Phases</h2><span class="sp"></span>
  <span class="cmeta" id="st-occ"></span><span id="st-badge"></span></div>
<div class="legend" id="st-legend"></div>
<svg id="st-stack" class="chart" viewBox="0 0 600 120" preserveAspectRatio="none"></svg>
<div id="st-table"></div>
<div class="legend" id="st-ranks" style="margin-top:.5rem"></div>
<svg id="st-spark" class="spark" viewBox="0 0 600 64" preserveAspectRatio="none"></svg>
<div class="muted">per-rank step time (window tail) — click a rank chip to toggle</div>
<div id="st-history-wrap" style="display:none">
  <svg id="st-history" class="spark" viewBox="0 0 600 48" preserveAspectRatio="none"></svg>
  <div class="muted" id="st-history-meta">full-run history (stitched rollup tiers)</div>
</div>
"""

_JS = r"""
const rankHidden=new Set();
let stLast=null,stLastTs=null,stHistLast=null;
function render_step_time(d){
  const st=d.step_time;badge("st-badge",d.ts,st&&st.latest_ts);
  if(d.history)stHistLast=d.history;
  renderStHistory((d.history||stHistLast||{}).step_time);
  if(!st)return;
  stLast=st;stLastTs=d.ts;
  document.getElementById("st-occ").textContent=
    (st.median_occupancy!=null?`chip busy ${(st.median_occupancy*100).toFixed(0)}%`:"")+
    (st.efficiency&&st.efficiency.achieved_tflops_median!=null?
      ` · ${st.efficiency.achieved_tflops_median.toFixed(1)} TFLOP/s`:"")+
    (st.efficiency&&st.efficiency.tokens_per_sec_median!=null?
      ` · ${Math.round(st.efficiency.tokens_per_sec_median).toLocaleString()} tok/s`:"");
  // stacked per-step phase chart (cross-rank medians)
  const stack=st.phase_stack||{};const keys=Object.keys(stack);
  const n=keys.length?stack[keys[0]].length:0;
  let maxTot=1;const totals=[];
  for(let i=0;i<n;i++){let t=0;for(const k of keys)t+=stack[k][i]||0;
    totals.push(t);maxTot=Math.max(maxTot,t)}
  let bars="";const bw=600/Math.max(1,n);
  for(let i=0;i<n;i++){let y=118;
    for(const k of keys){const h=(stack[k][i]||0)/maxTot*112;y-=h;
      bars+=`<rect x="${(i*bw).toFixed(1)}" y="${y.toFixed(1)}"
        width="${Math.max(0.5,bw-0.6).toFixed(1)}" height="${h.toFixed(1)}"
        fill="${COLORS[k]||"#888"}"></rect>`}}
  document.getElementById("st-stack").innerHTML=bars;
  document.getElementById("st-legend").innerHTML=keys.map(k=>
    `<span><i style="background:${COLORS[k]||"#888"}"></i>${esc(k)}</span>`).join("");
  hookTip("st-stack",frac=>{
    if(!stLast)return null;
    const stk=stLast.phase_stack||{};const ks=Object.keys(stk);
    const m=ks.length?stk[ks[0]].length:0;if(!m)return null;
    const i=Math.min(m-1,Math.floor(frac*m));
    const stepId=(stLast.steps||[])[i];
    let h=`<b>step ${esc(stepId!=null?stepId:i)}</b>`;
    for(const k of ks)if(stk[k][i])h+=`<br><i style="display:inline-block;width:8px;height:8px;border-radius:2px;background:${COLORS[k]||"#888"};margin-right:4px"></i>${esc(k)} ${fmtMs(stk[k][i])}`;
    return h});
  // phase table — both ends of the spread name a rank (median-closest
  // / worst), same pairing as the CLI and report
  let rows=`<table><tr><th>phase</th><th class="num">median</th>
    <th class="num">share</th><th class="num">rank m/w</th>
    <th class="num">skew</th></tr>`;
  for(const p of st.phases||[]){
    const rankPair=p.median_rank!=null
      ?`r${esc(p.median_rank)}/r${esc(p.worst_rank)}`:esc(p.worst_rank);
    rows+=`<tr><td>${esc(p.key)}</td><td class="num">${fmtMs(p.median_ms)}</td>
      <td class="num">${pct(p.share)}</td><td class="num">${rankPair}</td>
      <td class="num">${pct(p.skew_pct)}</td></tr>`}
  document.getElementById("st-table").innerHTML=rows+"</table>";
  // per-rank sparkline with rank toggle
  const series=st.step_series||{};const ranks=Object.keys(series);
  document.getElementById("st-ranks").innerHTML=ranks.map((r,ri)=>
    `<span class="toggle${rankHidden.has(r)?" off":""}" data-rank="${esc(r)}"
       onclick="stToggleRank(this.dataset.rank)">
       <i style="background:${rankColor(ri)}"></i>r${esc(r)}</span>`).join("");
  let max=1;
  for(const r of ranks){if(rankHidden.has(r))continue;
    for(const v of series[r])max=Math.max(max,v)}
  let paths="";
  ranks.forEach((r,ri)=>{const s=series[r];
    if(!s.length||rankHidden.has(r))return;
    paths+=`<polyline fill="none" stroke="${rankColor(ri)}"
      stroke-width="1.5" points="${sparkPath(s,600,64,max)}"/>`});
  document.getElementById("st-spark").innerHTML=paths;
  hookTip("st-spark",frac=>{
    if(!stLast)return null;
    const ser=stLast.step_series||{};const rs=Object.keys(ser);
    if(!rs.length)return null;
    let h="";
    for(const r of rs){if(rankHidden.has(r))continue;
      const s=ser[r];if(!s.length)continue;
      const i=Math.min(s.length-1,Math.floor(frac*s.length));
      h+=`${h?"<br>":""}r${esc(r)}: ${fmtMs(s[i])}`}
    return h||null});
}
function stToggleRank(r){
  if(rankHidden.has(r))rankHidden.delete(r);else rankHidden.add(r);
  // repaint with the SERVER timestamp of the cached payload — a client
  // clock here would cross clocks in the staleness badge
  if(stLast)render_step_time({step_time:stLast,ts:stLastTs})}
// full-run history strip: stitched rollup tiers (raw/10s/1m) as a
// min–max band + cross-rank mean line over the WHOLE run, not just the
// live window tail.  Hidden until the first fold lands in the payload.
function renderStHistory(hist){
  const wrap=document.getElementById("st-history-wrap");
  const pts=hist&&hist.points;
  if(!pts||pts.length<2){wrap.style.display="none";return}
  wrap.style.display="";
  const t0=pts[0].t,t1=pts[pts.length-1].t,span=Math.max(1e-9,t1-t0);
  let hmax=1;for(const p of pts)hmax=Math.max(hmax,p.max_ms||0);
  const X=p=>(p.t-t0)/span*600;
  const Y=v=>46-(v/hmax*44);
  let band="";for(const p of pts)band+=`${X(p).toFixed(1)},${Y(p.max_ms).toFixed(1)} `;
  for(let i=pts.length-1;i>=0;i--)band+=`${X(pts[i]).toFixed(1)},${Y(pts[i].min_ms).toFixed(1)} `;
  let mean="";for(const p of pts)mean+=`${X(p).toFixed(1)},${Y(p.mean_ms).toFixed(1)} `;
  document.getElementById("st-history").innerHTML=
    `<polygon points="${band}" fill="rgba(110,145,220,.22)" stroke="none"></polygon>`+
    `<polyline fill="none" stroke="#6e91dc" stroke-width="1.2" points="${mean}"></polyline>`;
  const res=[...new Set(pts.map(p=>p.res))].join("/");
  document.getElementById("st-history-meta").textContent=
    `full-run history: ${pts.length} buckets · ${(span/3600).toFixed(1)} h · `+
    `${Math.round(hist.ranks||0)} rank(s) · ${esc(res)} resolution (stitched rollup tiers)`;
  hookTip("st-history",frac=>{
    const i=Math.min(pts.length-1,Math.floor(frac*pts.length));
    const p=pts[i];
    return `<b>+${((p.t-t0)/60).toFixed(1)} min</b> (${esc(p.res)})`+
      `<br>mean ${fmtMs(p.mean_ms)}<br>min ${fmtMs(p.min_ms)} · max ${fmtMs(p.max_ms)}`});
}
"""

SECTION = Section(
    id="step_time",
    title="Phases",
    html=_HTML,
    js=_JS,
    contract=(
        "ts",
        "step_time.latest_ts",
        "step_time.median_occupancy",
        "step_time.efficiency.achieved_tflops_median",
        "step_time.efficiency.tokens_per_sec_median",
        "step_time.phase_stack",
        "step_time.steps",
        "step_time.phases.key",
        "step_time.phases.median_ms",
        "step_time.phases.share",
        "step_time.phases.worst_rank",
        "step_time.phases.median_rank",
        "step_time.phases.skew_pct",
        "step_time.step_series",
        "history.step_time.points.t",
        "history.step_time.points.mean_ms",
        "history.step_time.points.min_ms",
        "history.step_time.points.max_ms",
        "history.step_time.points.res",
        "history.step_time.ranks",
    ),
)
