import time

import pytest

from traceml_tpu.runtime.identity import RuntimeIdentity
from traceml_tpu.runtime.runtime import TraceMLRuntime
from traceml_tpu.runtime.settings import AggregatorEndpoint, TraceMLSettings
from traceml_tpu.runtime.state import COMPLETE, DRAINING, RECORDING, RecordingState
from traceml_tpu.sdk import state as state_mod
from traceml_tpu.sdk.instrumentation import trace_step
from traceml_tpu.telemetry import is_control_message, normalize_telemetry_envelope
from traceml_tpu.transport import TCPServer
from traceml_tpu.utils.step_memory import FakeMemoryBackend, StepMemoryTracker
from traceml_tpu.utils.timing import GLOBAL_STEP_QUEUE, drain_step_memory_rows


@pytest.fixture(autouse=True)
def fresh_state():
    st = state_mod.reset_state_for_tests()
    st.mem_tracker = StepMemoryTracker(
        FakeMemoryBackend([[{"device_id": 0, "device_kind": "fake",
                             "current_bytes": 50, "peak_bytes": 60,
                             "limit_bytes": 100}]])
    )
    GLOBAL_STEP_QUEUE.drain()
    drain_step_memory_rows()
    yield st
    GLOBAL_STEP_QUEUE.drain()
    drain_step_memory_rows()


def test_recording_state_lifecycle():
    rs = RecordingState(max_steps=3)
    assert rs.phase == RECORDING
    rs.on_step_flushed(1)
    rs.on_step_flushed(2)
    assert rs.recording
    rs.on_step_flushed(3)
    assert rs.phase == DRAINING
    rs.mark_drained()
    assert rs.phase == COMPLETE


def test_recording_state_unbounded():
    rs = RecordingState(None)
    rs.on_step_flushed(10000)
    assert rs.recording


def _run_runtime_session(tmp_path, max_steps=None, steps=4):
    server = TCPServer()
    server.start()
    settings = TraceMLSettings(
        session_id="t",
        logs_dir=tmp_path,
        mode="summary",
        aggregator=AggregatorEndpoint(port=server.port),
        sampler_interval_sec=0.05,
        trace_max_steps=max_steps,
        # the harness emulates the aggregator with a bare TCPServer (no
        # ring registry), so pin the tcp arm — auto would pick shm on
        # loopback and publish into a ring nothing drains
        transport="tcp",
    )
    rt = TraceMLRuntime(settings, RuntimeIdentity(global_rank=0))
    rt.start()
    try:
        for _ in range(steps):
            with trace_step():
                time.sleep(0.01)
        time.sleep(0.3)  # a few ticks
    finally:
        rt.stop()
    # collect everything the server saw
    deadline = time.monotonic() + 2
    got = []
    while time.monotonic() < deadline:
        server.wait_for_data(0.05)
        got.extend(server.drain_decoded())
        if any(is_control_message(p) for p in got):
            break
    server.stop()
    return got


def test_runtime_ships_step_rows_and_rank_finished(tmp_path, fresh_state):
    got = _run_runtime_session(tmp_path, steps=4)
    envs = [normalize_telemetry_envelope(p) for p in got]
    envs = [e for e in envs if e is not None]
    samplers = {e.sampler for e in envs}
    assert "step_time" in samplers
    assert "step_memory" in samplers
    assert "process" in samplers
    assert "system" in samplers
    step_rows = [
        r
        for e in envs
        if e.sampler == "step_time"
        for r in e.tables.get("step_time", [])
    ]
    assert [r["step"] for r in step_rows] == [1, 2, 3, 4]
    assert any(is_control_message(p) for p in got)


def test_runtime_max_steps_drains_and_finishes(tmp_path, fresh_state):
    got = _run_runtime_session(tmp_path, max_steps=2, steps=5)
    controls = [p for p in got if is_control_message(p)]
    assert controls, "rank_finished must be sent when max-steps reached"
    envs = [e for e in (normalize_telemetry_envelope(p) for p in got) if e]
    step_rows = [
        r
        for e in envs
        if e.sampler == "step_time"
        for r in e.tables.get("step_time", [])
    ]
    # recording stopped after step 2 drained; steps 3-5 may or may not be
    # recorded depending on drain timing, but 1 and 2 must be present
    steps_seen = {r["step"] for r in step_rows}
    assert {1, 2}.issubset(steps_seen)


def test_runtime_without_aggregator_never_raises(tmp_path, fresh_state):
    settings = TraceMLSettings(
        session_id="t2",
        logs_dir=tmp_path,
        mode="summary",
        aggregator=AggregatorEndpoint(port=1),  # nothing listens
        sampler_interval_sec=0.05,
        transport="tcp",  # the point is a dead TCP endpoint, not a ring
    )
    rt = TraceMLRuntime(settings, RuntimeIdentity(global_rank=0))
    rt.start()
    with trace_step():
        pass
    time.sleep(0.15)
    rt.stop()  # no exception = pass


def test_forced_final_memory_sample_bypasses_throttle():
    """A run shorter than the sampling throttle still records its end
    state: record(force=True) must emit past the min-interval gate —
    the shutdown path relies on it so growth (last − first) is never
    measured over a single row (r4 memory_creep flake fix)."""
    rows = [
        [{"device_id": 0, "device_kind": "fake",
          "current_bytes": 10 * (i + 1), "peak_bytes": 10 * (i + 1),
          "limit_bytes": 1000}]
        for i in range(4)
    ]
    tracker = StepMemoryTracker(
        FakeMemoryBackend(rows), min_sample_interval_s=60.0
    )
    drain_step_memory_rows()
    tracker.reset(1)
    assert tracker.record(1), "first sample must pass the throttle"
    assert tracker.record(2) == [], "inside throttle window → skipped"
    forced = tracker.record(2, force=True)
    assert forced and forced[0]["current_bytes"] > 0
    emitted = drain_step_memory_rows()
    assert len(emitted) == 2  # first + forced, the throttled one dropped
