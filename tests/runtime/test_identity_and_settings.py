from pathlib import Path

from traceml_tpu.runtime.identity import resolve_runtime_identity
from traceml_tpu.runtime.session import generate_session_id
from traceml_tpu.runtime.settings import (
    TraceMLSettings,
    settings_from_env,
    settings_to_env,
)


def test_identity_torchrun_env():
    env = {
        "RANK": "5",
        "WORLD_SIZE": "8",
        "LOCAL_RANK": "1",
        "LOCAL_WORLD_SIZE": "4",
        "GROUP_RANK": "1",
    }
    ident = resolve_runtime_identity(env)
    assert ident.global_rank == 5
    assert ident.local_rank == 1
    assert ident.world_size == 8
    assert ident.node_rank == 1
    assert ident.source == "env:torchrun"
    assert not ident.is_global_primary
    assert not ident.is_node_primary


def test_identity_tpu_worker_env():
    env = {"TPU_WORKER_ID": "2", "TPU_WORKER_HOSTNAMES": "h0,h1,h2,h3"}
    ident = resolve_runtime_identity(env)
    assert ident.global_rank == 2
    assert ident.world_size == 4
    assert ident.local_world_size == 1
    assert ident.source == "env:tpu_worker"


def test_identity_megascale_env():
    env = {"MEGASCALE_SLICE_ID": "1", "MEGASCALE_NUM_SLICES": "2"}
    ident = resolve_runtime_identity(env)
    assert ident.global_rank == 1
    assert ident.world_size == 2
    assert ident.source == "env:megascale"


def test_identity_defaults():
    ident = resolve_runtime_identity({})
    assert ident.global_rank == 0
    assert ident.world_size == 1
    assert ident.is_global_primary


def test_identity_bad_env_falls_through():
    ident = resolve_runtime_identity({"RANK": "x", "WORLD_SIZE": "y"})
    assert ident.source == "defaults"


def test_settings_env_roundtrip(tmp_path):
    s = TraceMLSettings(
        session_id="sess1",
        logs_dir=tmp_path,
        mode="summary",
        sampler_interval_sec=0.5,
        trace_max_steps=100,
        run_name="exp-1",
        expected_world_size=8,
        disk_backup=True,
    )
    env = settings_to_env(s)
    s2 = settings_from_env(env)
    assert s2.session_id == "sess1"
    assert s2.mode == "summary"
    assert s2.sampler_interval_sec == 0.5
    assert s2.trace_max_steps == 100
    assert s2.run_name == "exp-1"
    assert s2.expected_world_size == 8
    assert s2.disk_backup is True
    assert s2.session_dir == Path(tmp_path) / "sess1"
    assert s2.rank_dir(3).name == "rank_3"


def test_settings_defaults_from_empty_env():
    s = settings_from_env({})
    assert s.session_id == "local"
    assert s.mode == "cli"
    assert s.trace_max_steps is None
    assert not s.disabled


def test_session_id_generation():
    a = generate_session_id()
    b = generate_session_id()
    assert a != b
    c = generate_session_id("my run/exp#1")
    assert c.startswith("my-run-exp-1_")
