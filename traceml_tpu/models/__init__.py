"""Reference workloads: the flagship decoder LM + a tiny MLP.

These exist so the framework ships with realistic, shardable TPU
training jobs for its bench, demos, fault-injection scenarios, and the
driver's compile checks — the observability stack itself is
workload-agnostic.
"""

from traceml_tpu.models.transformer import (  # noqa: F401
    DecoderLM,
    ModelConfig,
    make_train_step,
    init_train_state,
    param_shardings,
)
from traceml_tpu.models.mlp import TinyMLP  # noqa: F401
