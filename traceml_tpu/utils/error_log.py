"""File-backed, never-raising error logger
(reference: src/traceml_ai/loggers/error_log.py:16-115).

Instrumentation must never break user training; every internal failure is
appended to ``logs/<session>/[component_]error.log`` with a ``[TraceML]``
prefix and swallowed.
"""

from __future__ import annotations

import datetime
import os
import threading
import traceback
from pathlib import Path
from typing import Optional

_PREFIX = "[TraceML]"


class ErrorLog:
    def __init__(self, path: Optional[os.PathLike] = None, component: str = "runtime"):
        self._path = Path(path) if path else None
        self._component = component
        self._lock = threading.Lock()
        self._fallback_count = 0

    def set_path(self, path: os.PathLike) -> None:
        with self._lock:
            self._path = Path(path)

    @property
    def path(self) -> Optional[Path]:
        return self._path  # tracelint: unguarded(single ref read; set_path happens once at startup and a stale None only delays first log line)

    def error(self, message: str, exc: Optional[BaseException] = None) -> None:
        self._write("ERROR", message, exc)

    def warning(self, message: str, exc: Optional[BaseException] = None) -> None:
        self._write("WARN", message, exc)

    def info(self, message: str) -> None:
        self._write("INFO", message, None)

    def _write(self, level: str, message: str, exc: Optional[BaseException]) -> None:
        try:
            ts = datetime.datetime.now().isoformat(timespec="milliseconds")
            lines = [f"{_PREFIX} {ts} {level} [{self._component}] {message}"]
            if exc is not None:
                lines.append(
                    "".join(
                        traceback.format_exception(type(exc), exc, exc.__traceback__)
                    ).rstrip()
                )
            text = "\n".join(lines) + "\n"
            with self._lock:
                if self._path is None:
                    self._fallback_count += 1
                    return
                self._path.parent.mkdir(parents=True, exist_ok=True)
                with open(self._path, "a", encoding="utf-8") as fh:
                    fh.write(text)
        except Exception:
            # Never raise from the error logger itself.
            pass


_global_log = ErrorLog()


def get_error_log() -> ErrorLog:
    return _global_log
