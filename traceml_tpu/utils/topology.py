"""Mesh topology capture + axis-group attribution engine.

The diagnostics packs end every finding in a rank list; at fleet scale
the actionable unit is physical structure — a host, one side of a DCN
boundary, a model-axis shard (Xu et al., arXiv:2004.13336 frames jobs
as a device mesh with named axes; T3, arXiv:2401.16677, attributes
compute/comm anomalies to the interconnect).  This module owns the
three pieces the attribution layer shares:

* **capture** — :func:`record_mesh` (called by ``parallel/mesh.py``)
  keeps the last ``jax.sharding.Mesh`` built in-process;
  :func:`capture_local_topology` turns it (or the ``TRACEML_MESH`` env
  override, for meshes built outside our helper) into THIS rank's
  topology payload: axis names/sizes, per-axis interconnect kind
  (ICI vs DCN), and this rank's mesh coordinates.  Each rank ships its
  own coords — correct in both single- and multi-controller setups.
* **axis reduction** — :func:`reduce_cube` reshapes a (rank × step)
  cube into (group × step) aggregates (sum/count/mean/min/max) with
  the exact accumulation order of :func:`reduce_cube_reference`, the
  scalar left-fold in rank order (``np.add.at`` applies updates in
  first-axis element order), so the two are bit-equal — golden-pinned
  by tests/utils/test_topology_attribution.py.
* **attribution** — :func:`attribute_ranks` scores candidate groupings
  (host / per-axis coordinate / DCN side) by the share of cross-rank
  anomaly variance each explains (η², between-group over total sum of
  squares) and names the outlier group when the best grouping clears
  the explanation threshold; otherwise returns None and callers keep
  their flat rank lists.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from traceml_tpu.config import flags

#: minimum share of anomaly variance a grouping must explain before a
#: finding is attributed to it (below: flat rank list, no false blame)
EXPLAIN_THRESHOLD = 0.6

KIND_ICI = "ici"
KIND_DCN = "dcn"


@dataclasses.dataclass
class AxisInfo:
    name: str
    size: int
    kind: str = KIND_ICI  # "ici" | "dcn"

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "size": int(self.size), "kind": self.kind}


@dataclasses.dataclass
class MeshTopology:
    """The merged, aggregator-side view: global axes + per-rank placement."""

    axes: List[AxisInfo]
    rank_coords: Dict[int, Tuple[int, ...]]
    rank_hosts: Dict[int, int] = dataclasses.field(default_factory=dict)
    rank_hostnames: Dict[int, str] = dataclasses.field(default_factory=dict)
    source: str = "mesh"

    @property
    def axis_names(self) -> List[str]:
        return [a.name for a in self.axes]

    def to_payload(self) -> Dict[str, Any]:
        return {
            "axes": [a.to_dict() for a in self.axes],
            "source": self.source,
            "ranks": {
                str(r): {
                    "coords": list(c),
                    "host": self.rank_hosts.get(r),
                    "hostname": self.rank_hostnames.get(r),
                }
                for r, c in sorted(self.rank_coords.items())
            },
        }

    @classmethod
    def from_payload(cls, payload: Mapping[str, Any]) -> Optional["MeshTopology"]:
        axes = parse_axes(payload.get("axes"))
        if not axes:
            return None
        coords: Dict[int, Tuple[int, ...]] = {}
        hosts: Dict[int, int] = {}
        hostnames: Dict[int, str] = {}
        for rank_s, info in (payload.get("ranks") or {}).items():
            try:
                rank = int(rank_s)
            except (TypeError, ValueError):
                continue
            if not isinstance(info, Mapping):
                continue
            c = info.get("coords")
            if isinstance(c, (list, tuple)) and len(c) == len(axes):
                coords[rank] = tuple(int(v) for v in c)
            if info.get("host") is not None:
                try:
                    hosts[rank] = int(info["host"])
                except (TypeError, ValueError):
                    pass
            if info.get("hostname"):
                hostnames[rank] = str(info["hostname"])
        if not coords:
            return None
        return cls(
            axes=axes,
            rank_coords=coords,
            rank_hosts=hosts,
            rank_hostnames=hostnames,
            source=str(payload.get("source") or "mesh"),
        )


def parse_axes(raw: Any) -> List[AxisInfo]:
    """Validate an axes list (``[{"name","size","kind"}, ...]``)."""
    out: List[AxisInfo] = []
    if not isinstance(raw, (list, tuple)):
        return out
    for a in raw:
        if not isinstance(a, Mapping):
            return []
        try:
            name = str(a["name"])
            size = int(a["size"])
        except (KeyError, TypeError, ValueError):
            return []
        if size < 1:
            return []
        kind = str(a.get("kind") or KIND_ICI)
        out.append(
            AxisInfo(name=name, size=size, kind=kind if kind == KIND_DCN else KIND_ICI)
        )
    return out


# -- capture (rank side) -------------------------------------------------

_RECORDED: Dict[str, Any] = {"mesh": None}


def record_mesh(mesh: Any) -> None:
    """Remember the last Mesh built in this process (fail-open hook
    called by ``parallel/mesh.make_mesh``; users building their own
    ``jax.sharding.Mesh`` can call this directly or set
    ``TRACEML_MESH``)."""
    _RECORDED["mesh"] = mesh


def recorded_mesh() -> Any:
    return _RECORDED["mesh"]


def reset_recorded_mesh_for_tests() -> None:
    _RECORDED["mesh"] = None


def parse_mesh_spec(spec: str) -> List[AxisInfo]:
    """``TRACEML_MESH`` grammar: ``name:size[@kind],...`` — e.g.
    ``data:4@dcn,fsdp:8``.  Returns [] on any malformed entry (the
    override must be all-or-nothing, a half-parsed mesh would
    mis-place every rank)."""
    axes: List[AxisInfo] = []
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        name, _, rest = part.partition(":")
        size_s, _, kind = rest.partition("@")
        try:
            size = int(size_s)
        except (TypeError, ValueError):
            return []
        if not name or size < 1:
            return []
        kind = (kind or KIND_ICI).strip().lower()
        if kind not in (KIND_ICI, KIND_DCN):
            return []
        axes.append(AxisInfo(name=name.strip(), size=size, kind=kind))
    return axes


def _coords_for_rank(rank: int, sizes: Sequence[int]) -> List[int]:
    """Row-major placement (matches ``np.reshape`` of the device list in
    ``parallel/mesh.make_mesh``)."""
    total = 1
    for s in sizes:
        total *= int(s)
    return [int(v) for v in np.unravel_index(int(rank) % max(total, 1), tuple(sizes))]


def _axis_kinds_from_mesh(devs: np.ndarray) -> List[str]:
    """ICI vs DCN per mesh axis, probed from the device grid: moving
    along an axis that changes ``slice_index`` crosses the data-center
    network; staying within a slice (even across hosts) is ICI."""
    kinds: List[str] = []
    for axis in range(devs.ndim):
        index: List[Any] = [0] * devs.ndim
        index[axis] = slice(None)
        line = devs[tuple(index)].ravel()
        slice_ids = {getattr(d, "slice_index", 0) or 0 for d in line}
        kinds.append(KIND_DCN if len(slice_ids) > 1 else KIND_ICI)
    return kinds


def _topology_from_mesh(mesh: Any) -> Optional[Dict[str, Any]]:
    import jax

    devs = np.asarray(mesh.devices)
    names = [str(n) for n in mesh.axis_names]
    axes = [
        AxisInfo(name=n, size=int(s), kind=k)
        for n, s, k in zip(names, devs.shape, _axis_kinds_from_mesh(devs))
    ]
    # this rank's coords: the grid position of the first device this
    # process owns (multi-controller meshes place each process's local
    # devices contiguously; single-controller sees everything and rank 0
    # speaks for the whole grid, which is still a correct global view)
    pid = int(jax.process_index())
    coords: Optional[List[int]] = None
    for idx in np.ndindex(devs.shape):
        if int(devs[idx].process_index) == pid:
            coords = [int(v) for v in idx]
            break
    if coords is None:
        return None
    return {
        "axes": [a.to_dict() for a in axes],
        "coords": coords,
        "source": "mesh",
    }


def capture_local_topology(
    global_rank: int, world_size: int
) -> Optional[Dict[str, Any]]:
    """THIS rank's mesh-topology payload, or None when no mesh is
    discoverable yet (callers retry on later ticks; never forces jax
    initialization).  Precedence: ``TRACEML_MESH`` env override (CI,
    meshes built outside our helper), then the recorded Mesh."""
    spec = flags.MESH.raw()
    if spec:
        axes = parse_mesh_spec(spec)
        if axes:
            return {
                "axes": [a.to_dict() for a in axes],
                "coords": _coords_for_rank(global_rank, [a.size for a in axes]),
                "source": "env",
            }
    mesh = _RECORDED["mesh"]
    if mesh is None:
        return None
    try:
        return _topology_from_mesh(mesh)
    except Exception:
        return None


# -- axis reduction ------------------------------------------------------


def reduce_cube(
    cube: np.ndarray,
    group_index: np.ndarray,
    n_groups: int,
    mask: Optional[np.ndarray] = None,
) -> Dict[str, np.ndarray]:
    """(rank × step) → (group × step) aggregates.

    ``cube`` is (R, S) float64; ``group_index`` maps row r to its group;
    ``mask`` (R, S) bool marks present entries (ragged windows / missing
    ranks) — absent entries contribute nothing.  Accumulation uses the
    unbuffered ``np.*.at`` ufuncs, which apply updates in first-axis
    element order, i.e. the same left-fold in ascending-rank order as
    :func:`reduce_cube_reference` — the two are bit-equal by contract.

    Returns ``sum``/``count``/``mean``/``min``/``max``, each (G, S);
    ``mean`` is NaN and min/max ±inf where a group has no entries.
    """
    cube = np.asarray(cube, dtype=np.float64)
    group_index = np.asarray(group_index, dtype=np.int64)
    r, s = cube.shape
    if mask is None:
        mask = np.ones((r, s), dtype=bool)
    else:
        mask = np.asarray(mask, dtype=bool)
    sums = np.zeros((n_groups, s), dtype=np.float64)
    counts = np.zeros((n_groups, s), dtype=np.int64)
    mins = np.full((n_groups, s), np.inf, dtype=np.float64)
    maxs = np.full((n_groups, s), -np.inf, dtype=np.float64)
    np.add.at(sums, group_index, np.where(mask, cube, 0.0))
    np.add.at(counts, group_index, mask.astype(np.int64))
    np.minimum.at(mins, group_index, np.where(mask, cube, np.inf))
    np.maximum.at(maxs, group_index, np.where(mask, cube, -np.inf))
    with np.errstate(invalid="ignore", divide="ignore"):
        means = sums / counts
    return {"sum": sums, "count": counts, "mean": means, "min": mins, "max": maxs}


def reduce_cube_reference(
    cube: np.ndarray,
    group_index: Sequence[int],
    n_groups: int,
    mask: Optional[np.ndarray] = None,
) -> Dict[str, np.ndarray]:
    """Scalar reference fold for :func:`reduce_cube`: plain Python
    loops, ranks in ascending row order — the accumulation-order
    authority the vectorized path must match bit-for-bit."""
    cube = np.asarray(cube, dtype=np.float64)
    r, s = cube.shape
    if mask is None:
        mask = np.ones((r, s), dtype=bool)
    sums = np.zeros((n_groups, s), dtype=np.float64)
    counts = np.zeros((n_groups, s), dtype=np.int64)
    mins = np.full((n_groups, s), np.inf, dtype=np.float64)
    maxs = np.full((n_groups, s), -np.inf, dtype=np.float64)
    for row in range(r):
        g = int(group_index[row])
        for col in range(s):
            if not mask[row, col]:
                continue
            v = float(cube[row, col])
            sums[g, col] = sums[g, col] + v
            counts[g, col] += 1
            if v < mins[g, col]:
                mins[g, col] = v
            if v > maxs[g, col]:
                maxs[g, col] = v
    with np.errstate(invalid="ignore", divide="ignore"):
        means = sums / counts
    return {"sum": sums, "count": counts, "mean": means, "min": mins, "max": maxs}


# -- attribution ---------------------------------------------------------


@dataclasses.dataclass
class Grouping:
    kind: str  # "host" | "axis" | "dcn_side"
    label: str  # e.g. "host", "axis data"
    axis: Optional[str]  # axis name for axis/dcn_side groupings
    groups: Dict[Any, List[int]]  # group key → member ranks


@dataclasses.dataclass
class Attribution:
    kind: str  # "host" | "axis" | "dcn_side"
    label: str  # human phrase naming the structure
    group: str  # the outlier group's key, stringified
    axis: Optional[str]
    ranks: List[int]
    explained: float  # η² of the winning grouping, 0..1

    def to_dict(self) -> Dict[str, Any]:
        return {
            "kind": self.kind,
            "label": self.label,
            "group": self.group,
            "axis": self.axis,
            "ranks": list(self.ranks),
            "explained": round(float(self.explained), 4),
        }


# grouping-memo counters, surfaced through the tick profiler: the memo
# lives on the MeshTopology instance and the snapshot store caches ONE
# instance per topology version, so a hit means "same (topology
# version, rank set) as an earlier diagnose call" — the per-call
# host/axis scans the r20 satellite removes from the warm tick
_GROUPING_CACHE_STATS: Dict[str, int] = {"hits": 0, "misses": 0}


def grouping_cache_counts() -> Dict[str, int]:
    return dict(_GROUPING_CACHE_STATS)


def candidate_groupings(
    topo: MeshTopology, ranks: Sequence[int]
) -> List[Grouping]:
    """Host grouping (from identity node_rank) + one grouping per mesh
    axis of size > 1 (DCN axes become boundary-side groupings).  Only
    ranks present in ``ranks`` participate.

    Memoized per (topology instance, rank tuple) when the vectorized
    diagnosis arm is on — groupings depend on nothing else, and every
    window domain asks with the same rank set tick after tick.  The
    callers only read the returned Grouping objects."""
    from traceml_tpu.utils.columnar import vector_diagnosis_enabled

    key: Optional[Tuple[int, ...]] = None
    cache: Optional[Dict[Tuple[int, ...], List[Grouping]]] = None
    if vector_diagnosis_enabled():
        key = tuple(int(r) for r in ranks)
        cache = topo.__dict__.get("_groupings_cache")
        if cache is not None and key in cache:
            _GROUPING_CACHE_STATS["hits"] += 1
            return cache[key]
    out: List[Grouping] = []
    hosts: Dict[Any, List[int]] = {}
    for r in ranks:
        h = topo.rank_hosts.get(int(r))
        if h is not None:
            hosts.setdefault(int(h), []).append(int(r))
    if len(hosts) > 1:
        out.append(Grouping(kind="host", label="host", axis=None, groups=hosts))
    for i, axis in enumerate(topo.axes):
        if axis.size <= 1:
            continue
        groups: Dict[Any, List[int]] = {}
        for r in ranks:
            c = topo.rank_coords.get(int(r))
            if c is None or i >= len(c):
                continue
            groups.setdefault(int(c[i]), []).append(int(r))
        if len(groups) > 1:
            out.append(
                Grouping(
                    kind="dcn_side" if axis.kind == KIND_DCN else "axis",
                    label=f"axis {axis.name}",
                    axis=axis.name,
                    groups=groups,
                )
            )
    if key is not None:
        if cache is None:
            cache = topo.__dict__["_groupings_cache"] = {}
        elif len(cache) >= 8:  # rank-set churn: keep the memo bounded
            cache.clear()
        cache[key] = out
        _GROUPING_CACHE_STATS["misses"] += 1
    return out


def _eta_squared(
    values: Mapping[int, float], groups: Mapping[Any, List[int]]
) -> Optional[Tuple[float, Any]]:
    """(η², outlier group key): share of total variance explained by
    the grouping, and the group whose mean sits farthest from the
    grand mean.  None when degenerate (no spread, singleton-only
    groups, fewer members than groups).

    Deviation ties (exact with two equal-size groups — both sit the
    same distance from the grand mean) break toward the HIGHER group
    mean: every pack's anomaly value is higher-is-worse (step ms,
    exposed comm ms, bytes used, lost/stale flag), so the slow side is
    the outlier, never the fast one."""
    members = [r for g in groups.values() for r in g]
    if len(members) <= len(groups):
        return None  # singleton groups explain anything — meaningless
    vals = np.array([float(values[r]) for r in members], dtype=np.float64)
    grand = float(vals.mean())
    ss_total = float(((vals - grand) ** 2).sum())
    if ss_total <= 0.0:
        return None
    ss_between = 0.0
    worst_key, worst_dev, worst_mean = None, -1.0, -np.inf
    for key in sorted(groups, key=str):
        gvals = np.array(
            [float(values[r]) for r in groups[key]], dtype=np.float64
        )
        gmean = float(gvals.mean())
        dev = abs(gmean - grand)
        ss_between += len(gvals) * (gmean - grand) ** 2
        if dev > worst_dev or (dev == worst_dev and gmean > worst_mean):
            worst_dev, worst_key, worst_mean = dev, key, gmean
    return ss_between / ss_total, worst_key


def _phrase(kind: str, axis: Optional[str], key: Any, ranks: List[int],
            topo: MeshTopology) -> str:
    n = len(ranks)
    if kind == "host":
        name = topo.rank_hostnames.get(ranks[0]) if ranks else None
        host = f"host {key}" + (f" ({name})" if name else "")
        return f"all {n} ranks of {host}" if n > 1 else f"rank {ranks[0]} on {host}"
    if kind == "dcn_side":
        return (
            f"one side of the DCN boundary on axis '{axis}' "
            f"({axis}={key}, {n} rank{'s' if n != 1 else ''})"
        )
    return (
        f"'{axis}'-axis shard imbalance "
        f"({axis}={key}, {n} rank{'s' if n != 1 else ''})"
    )


def attribute_ranks(
    per_rank_values: Mapping[int, float],
    topo: Optional[MeshTopology],
    threshold: float = EXPLAIN_THRESHOLD,
) -> Optional[Attribution]:
    """Score every candidate grouping on the per-rank anomaly values
    and return the best one clearing ``threshold``, or None (callers
    then keep the flat rank list).  Deterministic: ties break toward
    the earlier grouping in ``candidate_groupings`` order (host first,
    then axes in mesh order)."""
    if topo is None or not per_rank_values or len(per_rank_values) < 3:
        return None
    ranks = sorted(
        int(r) for r in per_rank_values
        if int(r) in topo.rank_coords or int(r) in topo.rank_hosts
    )
    if len(ranks) < 3:
        return None
    values = {r: float(per_rank_values[r]) for r in ranks}
    best: Optional[Attribution] = None
    for grouping in candidate_groupings(topo, ranks):
        scored = _eta_squared(values, grouping.groups)
        if scored is None:
            continue
        eta, key = scored
        if eta < threshold:
            continue
        if best is not None and eta <= best.explained:
            continue
        members = sorted(grouping.groups[key])
        best = Attribution(
            kind=grouping.kind,
            label=_phrase(grouping.kind, grouping.axis, key, members, topo),
            group=str(key),
            axis=grouping.axis,
            ranks=members,
            explained=eta,
        )
    return best


# -- convenience for DB round-trips --------------------------------------


def topology_from_rank_rows(
    rows: Sequence[Mapping[str, Any]],
) -> Optional[MeshTopology]:
    """Merge per-rank ``mesh_topology`` DB rows (keep-latest per rank —
    rows must be in insertion order) into one :class:`MeshTopology`."""
    axes: List[AxisInfo] = []
    ranks: Dict[str, Dict[str, Any]] = {}
    source = "mesh"
    for r in rows:
        try:
            parsed = parse_axes(json.loads(r["axes_json"] or "[]"))
            coords = json.loads(r["coords_json"] or "null")
        except (KeyError, TypeError, ValueError):
            continue
        if not parsed or not isinstance(coords, list):
            continue
        axes = parsed  # later rows win (restart with a new mesh)
        source = str(r["source"] or source) if "source" in r.keys() else source
        rank = int(r["global_rank"])
        ranks[str(rank)] = {
            "coords": coords,
            "host": r["node_rank"] if "node_rank" in r.keys() else None,
            "hostname": r["hostname"] if "hostname" in r.keys() else None,
        }
    if not axes or not ranks:
        return None
    return MeshTopology.from_payload(
        {"axes": [a.to_dict() for a in axes], "ranks": ranks, "source": source}
    )
