"""step_memory projection → ``step_memory_samples``
(reference: aggregator/sqlite_writers/step_memory.py)."""

from __future__ import annotations

from typing import Dict, List, Tuple

from traceml_tpu.aggregator.sqlite_writers.common import (
    IDENTITY_SCHEMA,
    identity_tuple,
)
from traceml_tpu.telemetry.envelope import TelemetryEnvelope

TABLE = "step_memory_samples"
RETENTION_TABLES = (TABLE,)


def accepts_sampler(name: str) -> bool:
    return name == "step_memory"


def init_schema(conn) -> None:
    conn.execute(
        f"""CREATE TABLE IF NOT EXISTS {TABLE} (
            id INTEGER PRIMARY KEY AUTOINCREMENT,
            {IDENTITY_SCHEMA},
            step INTEGER,
            timestamp REAL,
            device_id INTEGER,
            device_kind TEXT,
            current_bytes INTEGER,
            peak_bytes INTEGER,
            step_peak_bytes INTEGER,
            limit_bytes INTEGER,
            backend TEXT
        )"""
    )
    conn.execute(
        f"CREATE INDEX IF NOT EXISTS idx_{TABLE}_rank_step "
        f"ON {TABLE} (session_id, global_rank, step)"
    )


def insert_sql(table: str) -> str:
    return (
        f"INSERT INTO {TABLE} (session_id, global_rank, local_rank, world_size,"
        " local_world_size, node_rank, hostname, pid, step, timestamp, device_id,"
        " device_kind, current_bytes, peak_bytes, step_peak_bytes, limit_bytes,"
        " backend) VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?)"
    )


def build_rows(env: TelemetryEnvelope) -> Dict[str, List[Tuple]]:
    v = env.column_view("step_memory")
    if not v:
        return {}
    ident = identity_tuple(env)
    steps = v.ints("step")
    ts = v.floats("timestamp")
    dev_id = v.ints("device_id")
    kind = v.strs("device_kind", "unknown")
    current = v.ints("current_bytes")
    peak = v.ints("peak_bytes")
    step_peak = v.ints("step_peak_bytes")
    limit = v.ints("limit_bytes")
    backend = v.strs("backend", "unknown")
    out = [
        ident
        + (
            steps[i],
            ts[i],
            dev_id[i],
            kind[i],
            current[i],
            peak[i],
            step_peak[i],
            limit[i],
            backend[i],
        )
        for i in range(len(v))
    ]
    return {TABLE: out}
