"""Incremental live read path: cursor-based snapshot store.

The seed live path re-read the entire SQLite session every UI tick —
~7 fresh connections, a ``SELECT DISTINCT global_rank`` full scan plus
one query per rank (N+1), and a fresh ``json.loads`` of every
``events_json`` blob, even when zero new rows had arrived.  At target
scale (hundreds of ranks × 120-step windows) that is O(ranks × window)
of redundant I/O and decode per tick, charged to the same host the
training job runs on.

:class:`LiveSnapshotStore` sits between SQLite and the renderers /
diagnostics and makes the tick cost proportional to *what changed*:

* one persistent read-only connection (read-tuning PRAGMAs, shared by
  every table and reusable by the one-shot loaders);
* a per-table ``max(id)`` cursor — each refresh fetches only
  ``id > cursor`` rows in a single query ordered by
  ``(global_rank, step)``, killing the DISTINCT + per-rank N+1 pattern;
* each ``events_json`` blob is decoded exactly once, into bounded
  per-rank deques mirroring the loader windows;
* ``PRAGMA data_version`` gates the whole refresh: an idle tick (no
  commits since the last one) performs zero table reads;
* a monotonically increasing :attr:`data_version` plus per-domain
  versions let callers (``LiveComputer``) dirty-gate window
  construction and diagnosis instead of blind TTL caching.

Retention interaction: the writer's watermark prune (``DELETE`` of one
``(session_id, global_rank)`` partition's overflow below an indexed
watermark id, ``aggregator/sqlite_writer.py``) only ever removes ids
*below* every cursor, so cursors survive trims.  Trims are detected by
reading the writer's ``retention_watermarks`` journal incrementally
(one cursor query per refresh): each journal row names exactly which
``(table, global_rank)`` partition was trimmed and the watermark id it
was trimmed below, so the deques evict precisely the rows SQLite
deleted — per-partition deletes do not move the global ``MIN(id)``,
which is why the journal replaced the old MIN-movement detection.
Legacy DBs without the journal (sessions recorded before the watermark
writer) fall back to the MIN-movement + per-rank ``GROUP BY`` minima
path.

Contract note: accumulated identity sets (topology) never shrink on
trim — a rank observed once stays in ``ranks_seen`` even if all its
rows age out, which is the desired live semantic (the loader's DISTINCT
scan would forget it).
"""

from __future__ import annotations

import json
import sqlite3
import threading
from collections import deque
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

from traceml_tpu.utils.columnar import (
    CollectivesColumns,
    CollectivesWindow,
    CollectivesWindowCache,
    ColumnarFallback,
    MemoryColumns,
    RaggedEventColumns,
    ServingWindow,
    ServingWindowCache,
    StepTimeColumns,
    StepTimeWindowCache,
    TickProfile,
    build_collectives_window_rows,
    build_columnar_collectives_window,
    build_columnar_serving_window,
    build_columnar_step_time_window,
    build_serving_window_rows,
    columnar_window_enabled,
    incr_window_enabled,
    vector_fallback_counts,
)
from traceml_tpu.aggregator.rollup import ROLLUP_SOURCES as _ROLLUP_SOURCES
from traceml_tpu.utils.error_log import get_error_log
from traceml_tpu.utils.step_time_window import (
    StepTimeWindow,
    build_step_time_window as _build_window_from_rows,
)

_ROLLUP_SOURCE_SET = frozenset(_ROLLUP_SOURCES)

_READ_PRAGMAS = (
    "PRAGMA busy_timeout=200",
    "PRAGMA cache_size=-8192",      # 8 MiB page cache
    "PRAGMA temp_store=MEMORY",
    "PRAGMA mmap_size=134217728",   # 128 MiB, no-op where unsupported
)

# domains exposed through per-domain versions (what dirty-gating keys on)
DOMAINS = (
    "step_time",
    "step_memory",
    "collectives",
    "serving",
    "system",
    "process",
    "stdout",
    "model_stats",
    "topology",
    "rollup",
)


class _RankBuffer:
    """Bounded row window: ids / ranks / decoded rows evict in lockstep
    (same ``maxlen`` on all three deques), so a retention trim can drop
    exactly the rows SQLite dropped."""

    __slots__ = ("ids", "ranks", "rows")

    def __init__(self, maxlen: int) -> None:
        self.ids: deque = deque(maxlen=maxlen)
        self.ranks: deque = deque(maxlen=maxlen)
        self.rows: deque = deque(maxlen=maxlen)

    def append(self, row_id: int, rank: Optional[int], row: Any) -> None:
        self.ids.append(row_id)
        self.ranks.append(rank)
        self.rows.append(row)

    def clear(self) -> bool:
        had = bool(self.ids)
        self.ids.clear()
        self.ranks.clear()
        self.rows.clear()
        return had

    def evict_below(self, min_id: int) -> bool:
        """Prefix eviction for single-rank buffers (ids ascending)."""
        changed = False
        while self.ids and self.ids[0] < min_id:
            self.ids.popleft()
            self.ranks.popleft()
            self.rows.popleft()
            changed = True
        return changed

    def filter_watermarks(self, watermarks: Dict[int, int]) -> bool:
        """Drop every held row at or below its rank's trim watermark
        (journal mode).  Ranks without a journal entry keep all rows —
        the writer only journals partitions it actually pruned."""
        keep = [
            (i, rk, rw)
            for i, rk, rw in zip(self.ids, self.ranks, self.rows)
            if rk not in watermarks or i > watermarks[rk]
        ]
        if len(keep) == len(self.ids):
            return False
        self.ids.clear()
        self.ranks.clear()
        self.rows.clear()
        for i, rk, rw in keep:
            self.append(i, rk, rw)
        return True

    def filter_trimmed(self, per_rank_min: Dict[int, int]) -> bool:
        """Drop every held row the writer's PER-RANK retention trim
        deleted: a row survives iff its id is >= its rank's current
        MIN(id) in the table (a rank absent from the table lost all its
        rows).  Mixed-rank buffers need this full filter — a trim can
        delete mid-buffer rows of one rank while older rows of another
        rank survive."""
        keep = [
            (i, rk, rw)
            for i, rk, rw in zip(self.ids, self.ranks, self.rows)
            if rk in per_rank_min and i >= per_rank_min[rk]
        ]
        if len(keep) == len(self.ids):
            return False
        self.ids.clear()
        self.ranks.clear()
        self.rows.clear()
        for i, rk, rw in keep:
            self.append(i, rk, rw)
        return True


class _StepTimeBuffer(_RankBuffer):
    """Row deque + columnar ring in lockstep: every append lands in
    both, ``clear``/``evict_below`` keep the ring's live span 1:1 with
    the deque (the ring self-evicts on overflow exactly like the
    deque's ``maxlen``), so the columnar window build always sees the
    same rows the scalar fallback would."""

    __slots__ = ("cols",)

    def __init__(self, maxlen: int) -> None:
        super().__init__(maxlen)
        self.cols = StepTimeColumns(maxlen)

    def append(self, row_id: int, rank: Optional[int], row: Any) -> None:
        super().append(row_id, rank, row)
        self.cols.append(row)

    def clear(self) -> bool:
        had = super().clear()
        self.cols.clear()
        return had

    def evict_below(self, min_id: int) -> bool:
        changed = super().evict_below(min_id)
        self.cols.evict_head(len(self.cols) - len(self.ids))
        return changed


class _MemoryBuffer(_RankBuffer):
    __slots__ = ("cols",)

    def __init__(self, maxlen: int) -> None:
        super().__init__(maxlen)
        self.cols = MemoryColumns(maxlen)

    def append(self, row_id: int, rank: Optional[int], row: Any) -> None:
        super().append(row_id, rank, row)
        self.cols.append(row)

    def clear(self) -> bool:
        had = super().clear()
        self.cols.clear()
        return had

    def evict_below(self, min_id: int) -> bool:
        changed = super().evict_below(min_id)
        self.cols.evict_head(len(self.cols) - len(self.ids))
        return changed


class _CollectivesBuffer(_RankBuffer):
    """Row deque + collectives columnar ring in lockstep (same contract
    as :class:`_StepTimeBuffer`)."""

    __slots__ = ("cols",)

    def __init__(self, maxlen: int) -> None:
        super().__init__(maxlen)
        self.cols = CollectivesColumns(maxlen)

    def append(self, row_id: int, rank: Optional[int], row: Any) -> None:
        super().append(row_id, rank, row)
        self.cols.append(row)

    def clear(self) -> bool:
        had = super().clear()
        self.cols.clear()
        return had

    def evict_below(self, min_id: int) -> bool:
        changed = super().evict_below(min_id)
        self.cols.evict_head(len(self.cols) - len(self.ids))
        return changed


class _ServingBuffer(_RankBuffer):
    """Row deque + ragged serving ring in lockstep (same contract as
    :class:`_StepTimeBuffer`; the CSR value buffers evict with the
    ring's head — see ``utils/columnar.RaggedEventColumns``)."""

    __slots__ = ("cols",)

    def __init__(self, maxlen: int) -> None:
        super().__init__(maxlen)
        self.cols = RaggedEventColumns(maxlen)

    def append(self, row_id: int, rank: Optional[int], row: Any) -> None:
        super().append(row_id, rank, row)
        self.cols.append(row)

    def clear(self) -> bool:
        had = super().clear()
        self.cols.clear()
        return had

    def evict_below(self, min_id: int) -> bool:
        changed = super().evict_below(min_id)
        self.cols.evict_head(len(self.cols) - len(self.ids))
        return changed


class _TopologySource:
    """Accumulated identity sets for one projection table."""

    __slots__ = ("ranks", "nodes", "hostnames", "world")

    def __init__(self) -> None:
        self.ranks: set = set()
        self.nodes: set = set()
        self.hostnames: set = set()
        self.world: int = 0

    def update(self, rank, node, hostname, world) -> bool:
        before = (len(self.ranks), len(self.nodes), len(self.hostnames), self.world)
        if rank is not None:
            self.ranks.add(int(rank))
        if node is not None:
            self.nodes.add(int(node))
        if hostname is not None:
            self.hostnames.add(str(hostname))
        if world:
            self.world = max(self.world, int(world))
        return before != (
            len(self.ranks), len(self.nodes), len(self.hostnames), self.world
        )


class LiveSnapshotStore:
    """Incremental, bounded, decode-once snapshot of a session DB.

    ``refresh()`` advances the snapshot; accessors return loader-shaped
    structures (same keys/grouping as ``reporting/loaders.py``) so the
    window builders, views and diagnostics consume them unchanged.
    Thread-safe: one lock serializes refresh and accessors (the
    connection is shared across display-driver threads).
    """

    def __init__(
        self,
        db_path: Path,
        window_steps: int = 120,
        memory_rows_per_rank: Optional[int] = None,
        collectives_rows_per_rank: Optional[int] = None,
        serving_rows_per_rank: Optional[int] = None,
        system_rows: int = 300,
        process_rows: int = 300,
        stdout_rows: int = 64,
        model_stats_rows: int = 64,
    ) -> None:
        self.db_path = Path(db_path)
        self.window_steps = int(window_steps)
        self.memory_rows_per_rank = int(
            memory_rows_per_rank
            if memory_rows_per_rank is not None
            else window_steps * 4
        )
        # several (op, dtype) rows share one step — 8x headroom matches
        # the bench workload (8 collectives/step) without unbounded growth
        self.collectives_rows_per_rank = int(
            collectives_rows_per_rank
            if collectives_rows_per_rank is not None
            else window_steps * 8
        )
        # one aggregate row per sampler window per replica — the window
        # index is the alignment key, so window_steps bounds it directly
        self.serving_rows_per_rank = int(
            serving_rows_per_rank
            if serving_rows_per_rank is not None
            else window_steps
        )
        self.max_system_rows = int(system_rows)
        self.max_process_rows = int(process_rows)
        self.max_stdout_rows = int(stdout_rows)
        self.max_model_stats_rows = int(model_stats_rows)

        self._lock = threading.RLock()
        self._conn: Optional[sqlite3.Connection] = None
        self._primed = False
        self._last_db_dv: Optional[int] = None
        self._data_version = 0
        self._versions: Dict[str, int] = {d: 0 for d in DOMAINS}
        self._cursors: Dict[str, int] = {}
        self._min_seen: Dict[str, Optional[int]] = {}
        self._tables_seen: set = set()
        # journal mode: table → {rank: trim watermark id} accumulated
        # from retention_watermarks rows, consumed by each table reader
        self._journal_mode = False
        self._pending_trims: Dict[str, Dict[int, int]] = {}
        # tiered rollups: every fold commits with its prune's journal
        # row, so journal rows naming a rollup source ARE the rollup
        # dirty signal; stitched reads cache per (rollup, raw) version
        self._rollup_dirty = False
        self._stitched_cache: Dict[Tuple, Tuple[Tuple[int, ...], Any]] = {}

        # step_time / step_memory: per-rank bounded windows (row deque
        # + columnar ring per rank, kept in lockstep)
        self._step_time: Dict[int, _StepTimeBuffer] = {}
        self._step_memory: Dict[int, _MemoryBuffer] = {}
        self._collectives: Dict[int, _CollectivesBuffer] = {}
        self._serving: Dict[int, _ServingBuffer] = {}
        # incremental window caches (round 19): per-domain persistent
        # aligned-cube/slot caches fed by the rings' monotone counters;
        # created lazily on the first columnar build of each domain
        self._window_caches: Dict[str, Any] = {}
        # per-stage warm-tick profiler (refresh/build/diagnose/attribute/
        # view/serialize ns + cache counters): LiveComputer and the
        # serving tier write into it; window_build_stats surfaces it
        self.tick_profile = TickProfile()
        # system / process: globally-bounded (loader semantics), keyed rows
        self._system_host = _RankBuffer(self.max_system_rows)
        self._system_dev = _RankBuffer(self.max_system_rows)
        self._process = _RankBuffer(self.max_process_rows)
        self._process_dev = _RankBuffer(self.max_process_rows)
        self._stdout = _RankBuffer(self.max_stdout_rows)
        self._model_stats = _RankBuffer(self.max_model_stats_rows)
        self._model_stats_cols: Optional[List[str]] = None

        self._topology: Dict[str, _TopologySource] = {
            "step_time_samples": _TopologySource(),
            "process_samples": _TopologySource(),
            "system_samples": _TopologySource(),
        }
        self._topology_cache: Optional[Dict[str, Any]] = None
        self._topology_cache_version = -1

        # mesh_topology control rows: keep-latest per rank (replay may
        # append duplicates; table is never trimmed)
        self._mesh_rows: Dict[int, Dict[str, Any]] = {}
        self._mesh_cache: Any = None
        self._mesh_cache_version = -1

    # -- connection ------------------------------------------------------

    @property
    def connection(self) -> Optional[sqlite3.Connection]:
        """The shared read-only connection (None until the DB exists).
        One-shot loaders may reuse it via their ``conn=`` parameter;
        hold no expectations about transactions — autocommit reads."""
        with self._lock:
            return self._conn

    @property
    def connected(self) -> bool:
        with self._lock:
            return self._conn is not None

    def _connect(self) -> Optional[sqlite3.Connection]:
        if self._conn is not None:
            return self._conn
        if not self.db_path.exists():
            return None
        try:
            conn = sqlite3.connect(
                f"file:{self.db_path}?mode=ro",
                uri=True,
                check_same_thread=False,
            )
        except sqlite3.Error:
            return None
        conn.row_factory = sqlite3.Row
        for pragma in _READ_PRAGMAS:
            try:
                conn.execute(pragma)
            except sqlite3.Error:
                pass
        self._conn = conn
        return conn

    def close(self) -> None:
        with self._lock:
            if self._conn is not None:
                try:
                    self._conn.close()
                except sqlite3.Error:
                    pass
                self._conn = None

    # -- versions --------------------------------------------------------

    @property
    def data_version(self) -> int:
        """Monotonically increasing; bumps once per refresh that
        observed any change (new rows or a retention trim)."""
        with self._lock:
            return self._data_version

    @property
    def versions(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._versions)

    # -- refresh ---------------------------------------------------------

    def refresh(self) -> bool:
        """Advance the snapshot.  Returns True when anything changed.

        Idle fast path: ``PRAGMA data_version`` is a header-counter
        read — when it matches the last refresh, no table is queried at
        all and the call is near-free.
        """
        with self._lock:
            conn = self._connect()
            if conn is None:
                return False
            try:
                db_dv = conn.execute("PRAGMA data_version").fetchone()[0]
            except sqlite3.Error:
                return False
            if self._primed and db_dv == self._last_db_dv:
                return False

            try:
                self._journal_mode = self._read_watermark_journal(conn)
            except sqlite3.Error:
                self._journal_mode = False

            dirty: set = set()
            clean_scan = True
            readers = (
                ("step_time_samples", self._read_step_time, "step_time"),
                ("step_memory_samples", self._read_step_memory, "step_memory"),
                ("collectives_samples", self._read_collectives, "collectives"),
                ("serving_samples", self._read_serving, "serving"),
                ("system_samples", self._read_system_host, "system"),
                ("system_device_samples", self._read_system_dev, "system"),
                ("process_samples", self._read_process, "process"),
                ("process_device_samples", self._read_process_dev, "process"),
                ("stdout_samples", self._read_stdout, "stdout"),
                ("model_stats_samples", self._read_model_stats, "model_stats"),
                ("mesh_topology", self._read_mesh_topology, "topology"),
            )
            for table, reader, domain in readers:
                try:
                    if not self._table_exists(conn, table):
                        continue
                    if reader(conn, table, dirty):
                        dirty.add(domain)
                except sqlite3.Error as exc:
                    get_error_log().warning(
                        f"snapshot refresh failed for {table}", exc
                    )
                    clean_scan = False
            if self._rollup_dirty:
                # folds commit atomically with their prune's journal
                # row, so the journal naming a rollup source is the
                # exact "tier tables changed" signal — no tier scan
                dirty.add("rollup")
                self._rollup_dirty = False
            if clean_scan:
                # only mark the DB state consumed when every table
                # scanned cleanly — a busy/locked table retries next tick
                # (cursors make the retry incremental, not a re-read)
                self._last_db_dv = db_dv
                self._primed = True
            if dirty:
                self._data_version += 1
                for domain in dirty:
                    self._versions[domain] = self._data_version
            return bool(dirty)

    def _table_exists(self, conn: sqlite3.Connection, table: str) -> bool:
        if table in self._tables_seen:
            return True
        row = conn.execute(
            "SELECT name FROM sqlite_master WHERE type='table' AND name=?",
            (table,),
        ).fetchone()
        if row is not None:
            self._tables_seen.add(table)
            return True
        return False

    def _advance_cursor(self, table: str, rows) -> None:
        if rows:
            self._cursors[table] = max(
                max(r["id"] for r in rows), self._cursors.get(table, 0)
            )

    def _read_watermark_journal(self, conn: sqlite3.Connection) -> bool:
        """Incremental read of the writer's ``retention_watermarks``
        journal (one cursor query per non-idle refresh).  Returns True
        when the journal exists — per-rank watermark detection replaces
        the MIN-movement heuristic entirely, including its per-table
        ``MIN(id)`` query and the trim-event ``GROUP BY`` aggregate.

        Accumulated watermarks persist in ``_pending_trims`` until the
        owning table's reader consumes them, so a journal row observed
        while that reader errors (busy/locked) is applied on the retry
        refresh rather than lost.  Applying a watermark is always safe:
        it only evicts rows the writer committed deleting before it
        journaled the trim (same transaction).
        """
        if not self._table_exists(conn, "retention_watermarks"):
            return False
        cur = self._cursors.get("retention_watermarks", 0)
        rows = conn.execute(
            "SELECT id, table_name, global_rank, watermark_id"
            " FROM retention_watermarks WHERE id > ? ORDER BY id",
            (cur,),
        ).fetchall()
        for r in rows:
            table_name = str(r["table_name"])
            trims = self._pending_trims.setdefault(table_name, {})
            rank = int(r["global_rank"])
            wm = int(r["watermark_id"])
            if wm > trims.get(rank, -1):
                trims[rank] = wm
            if table_name in _ROLLUP_SOURCE_SET:
                self._rollup_dirty = True
        self._advance_cursor("retention_watermarks", rows)
        return True

    def _begin_trim_check(
        self, conn: sqlite3.Connection, table: str
    ) -> bool:
        """Legacy-mode trim pre-check (global ``MIN(id)`` movement).
        In journal mode this is a no-op — the journal already told us
        exactly which partitions trimmed."""
        if self._journal_mode:
            return False
        return self._observe_min(conn, table)

    def _apply_trims(
        self,
        conn: sqlite3.Connection,
        table: str,
        legacy_trimmed: bool,
        rank_bufs: Optional[Dict[int, "_RankBuffer"]] = None,
        flat_bufs: Tuple["_RankBuffer", ...] = (),
    ) -> bool:
        """Evict exactly the rows the writer's retention prune deleted.

        Journal mode: each pending watermark names its partition — rank
        buffers prefix-evict below ``watermark + 1``, mixed-rank buffers
        filter per (rank, id).  Legacy mode falls back to the per-rank
        ``GROUP BY`` minima reconciliation.
        """
        if self._journal_mode:
            watermarks = self._pending_trims.pop(table, None)
            if not watermarks:
                return False
            changed = False
            if rank_bufs is not None:
                for rank, wm in watermarks.items():
                    buf = rank_bufs.get(rank)
                    if buf is not None:
                        changed |= buf.evict_below(wm + 1)
            for buf in flat_bufs:
                changed |= buf.filter_watermarks(watermarks)
            return changed
        if not legacy_trimmed:
            return False
        return self._reconcile_trim(
            conn, table, rank_bufs=rank_bufs, flat_bufs=flat_bufs
        )

    def _observe_min(self, conn: sqlite3.Connection, table: str) -> bool:
        """LEGACY detection (DBs recorded before the watermark journal):
        record the table's current ``MIN(id)`` and report whether a
        retention trim happened since the last refresh (the minimum
        moved forward, or the table emptied while we hold rows).

        Called BEFORE the incremental row fetch so a trim racing the
        fetch is observed at the latest on the next refresh.  Detection
        bound: a prune that deletes rows WITHOUT moving the global
        minimum (only possible when the globally-oldest rank is under
        its retention cap while another rank trims) is caught at the
        next minimum-moving prune; until then the store may briefly
        hold more per-rank history than a cold reload would — benign
        for live windows (see docs/developer_guide/live-read-path.md).
        """
        row = conn.execute(f"SELECT MIN(id) FROM {table}").fetchone()
        min_id = row[0] if row and row[0] is not None else None
        last = self._min_seen.get(table)
        self._min_seen[table] = min_id
        if min_id is None:
            return last is not None
        return last is not None and min_id > last

    def _reconcile_trim(
        self,
        conn: sqlite3.Connection,
        table: str,
        rank_bufs: Optional[Dict[int, _RankBuffer]] = None,
        flat_bufs: Tuple[_RankBuffer, ...] = (),
    ) -> bool:
        """Evict exactly the rows the writer's retention prune deleted.

        The prune partitions by ``(session_id, global_rank)``, so a
        single global ``MIN(id)`` prefix eviction is NOT sufficient:
        with rank-interleaved inserts, one rank's deleted ids sit above
        another rank's surviving minimum.  On trim detection we fetch
        per-rank minima (one indexed aggregate, amortized over trim
        events — never on idle or new-rows-only ticks) and evict each
        rank's rows below its own minimum; ranks absent from the table
        lost all their rows.
        """
        mins: Dict[int, int] = {}
        for r in conn.execute(
            f"SELECT global_rank, MIN(id) FROM {table} GROUP BY global_rank"
        ):
            if r[0] is not None:
                mins[int(r[0])] = int(r[1])
        changed = False
        if rank_bufs is not None:
            for rank, buf in rank_bufs.items():
                m = mins.get(rank)
                if m is None:
                    changed |= buf.clear()
                else:
                    changed |= buf.evict_below(m)
        for buf in flat_bufs:
            changed |= buf.filter_trimmed(mins)
        return changed

    # -- per-table readers ----------------------------------------------

    def _read_step_time(self, conn, table, dirty) -> bool:
        trimmed = self._begin_trim_check(conn, table)
        cur = self._cursors.get(table, 0)
        rows = conn.execute(
            "SELECT id, global_rank, node_rank, hostname, world_size,"
            " step, timestamp, clock, late_markers, events_json"
            f" FROM {table} WHERE id > ? ORDER BY global_rank, step, id",
            (cur,),
        ).fetchall()
        topo = self._topology["step_time_samples"]
        for r in rows:
            if topo.update(
                r["global_rank"], r["node_rank"], r["hostname"], r["world_size"]
            ):
                dirty.add("topology")
            try:
                events = json.loads(r["events_json"] or "{}")
            except ValueError:
                events = {}
            rank = int(r["global_rank"])
            buf = self._step_time.get(rank)
            if buf is None:
                buf = self._step_time[rank] = _StepTimeBuffer(self.window_steps)
            buf.append(
                r["id"],
                rank,
                {
                    "step": r["step"],
                    "timestamp": r["timestamp"],
                    "clock": r["clock"],
                    "late_markers": r["late_markers"],
                    "events": events,
                },
            )
        self._advance_cursor(table, rows)
        evicted = self._apply_trims(
            conn, table, trimmed, rank_bufs=self._step_time
        )
        return bool(rows) or evicted

    def _read_collectives(self, conn, table, dirty) -> bool:
        trimmed = self._begin_trim_check(conn, table)
        cur = self._cursors.get(table, 0)
        rows = conn.execute(
            "SELECT id, global_rank, step, timestamp, op, dtype, count,"
            " bytes, group_size, duration_ms, exposed_ms"
            f" FROM {table} WHERE id > ? ORDER BY global_rank, step, id",
            (cur,),
        ).fetchall()
        for r in rows:
            rank = int(r["global_rank"])
            buf = self._collectives.get(rank)
            if buf is None:
                buf = self._collectives[rank] = _CollectivesBuffer(
                    self.collectives_rows_per_rank
                )
            row = dict(r)
            del row["id"], row["global_rank"]
            buf.append(r["id"], rank, row)
        self._advance_cursor(table, rows)
        evicted = self._apply_trims(
            conn, table, trimmed, rank_bufs=self._collectives
        )
        return bool(rows) or evicted

    def _read_serving(self, conn, table, dirty) -> bool:
        trimmed = self._begin_trim_check(conn, table)
        cur = self._cursors.get(table, 0)
        rows = conn.execute(
            "SELECT id, global_rank, step, timestamp, requests_enqueued,"
            " requests_completed, requests_active, queue_depth, decode_tokens,"
            " prefill_ms, decode_ms, tokens_per_s, batch_occupancy,"
            " ttft_p50_ms, ttft_p95_ms, ttft_p99_ms, e2e_p50_ms, e2e_p95_ms,"
            " e2e_p99_ms, kv_bytes, kv_limit_bytes, kv_headroom,"
            " ttft_ms_list, e2e_ms_list, tokens_list"
            f" FROM {table} WHERE id > ? ORDER BY global_rank, step, id",
            (cur,),
        ).fetchall()
        for r in rows:
            rank = int(r["global_rank"])
            buf = self._serving.get(rank)
            if buf is None:
                buf = self._serving[rank] = _ServingBuffer(
                    self.serving_rows_per_rank
                )
            row = dict(r)
            del row["id"], row["global_rank"]
            buf.append(r["id"], rank, row)
        self._advance_cursor(table, rows)
        evicted = self._apply_trims(
            conn, table, trimmed, rank_bufs=self._serving
        )
        return bool(rows) or evicted

    def _read_step_memory(self, conn, table, dirty) -> bool:
        trimmed = self._begin_trim_check(conn, table)
        cur = self._cursors.get(table, 0)
        rows = conn.execute(
            "SELECT id, global_rank, step, timestamp, device_id, device_kind,"
            " current_bytes, peak_bytes, step_peak_bytes, limit_bytes"
            f" FROM {table} WHERE id > ? ORDER BY global_rank, step, id",
            (cur,),
        ).fetchall()
        for r in rows:
            rank = int(r["global_rank"])
            buf = self._step_memory.get(rank)
            if buf is None:
                buf = self._step_memory[rank] = _MemoryBuffer(
                    self.memory_rows_per_rank
                )
            row = dict(r)
            del row["id"], row["global_rank"]
            buf.append(r["id"], rank, row)
        self._advance_cursor(table, rows)
        evicted = self._apply_trims(
            conn, table, trimmed, rank_bufs=self._step_memory
        )
        return bool(rows) or evicted

    def _read_keyed(self, conn, table, buf, key_fn, topo_source=None, dirty=None):
        trimmed = self._begin_trim_check(conn, table)
        cur = self._cursors.get(table, 0)
        rows = conn.execute(
            f"SELECT * FROM {table} WHERE id > ? ORDER BY id", (cur,)
        ).fetchall()
        for r in rows:
            if topo_source is not None:
                if topo_source.update(
                    r["global_rank"], r["node_rank"], r["hostname"],
                    r["world_size"],
                ) and dirty is not None:
                    dirty.add("topology")
            buf.append(r["id"], int(r["global_rank"]), (key_fn(r), dict(r)))
        self._advance_cursor(table, rows)
        evicted = self._apply_trims(conn, table, trimmed, flat_bufs=(buf,))
        return bool(rows) or evicted

    def _read_system_host(self, conn, table, dirty) -> bool:
        return self._read_keyed(
            conn, table, self._system_host,
            lambda r: int(r["node_rank"]),
            topo_source=self._topology["system_samples"], dirty=dirty,
        )

    def _read_system_dev(self, conn, table, dirty) -> bool:
        return self._read_keyed(
            conn, table, self._system_dev,
            lambda r: (int(r["node_rank"]), int(r["device_id"] or 0)),
        )

    def _read_process(self, conn, table, dirty) -> bool:
        return self._read_keyed(
            conn, table, self._process,
            lambda r: int(r["global_rank"]),
            topo_source=self._topology["process_samples"], dirty=dirty,
        )

    def _read_process_dev(self, conn, table, dirty) -> bool:
        return self._read_keyed(
            conn, table, self._process_dev,
            lambda r: (int(r["global_rank"]), int(r["device_id"] or 0)),
        )

    def _read_stdout(self, conn, table, dirty) -> bool:
        trimmed = self._begin_trim_check(conn, table)
        cur = self._cursors.get(table, 0)
        rows = conn.execute(
            f"SELECT id, global_rank, stream, line FROM {table}"
            " WHERE id > ? ORDER BY id",
            (cur,),
        ).fetchall()
        for r in rows:
            self._stdout.append(
                r["id"], int(r["global_rank"]), (r["stream"], r["line"])
            )
        self._advance_cursor(table, rows)
        evicted = self._apply_trims(
            conn, table, trimmed, flat_bufs=(self._stdout,)
        )
        return bool(rows) or evicted

    def _model_stats_select(self, conn, table) -> str:
        """Column list probed once — archived sessions may predate the
        tokens_per_step / device_count columns (same back-compat as
        ``loaders.load_model_stats``)."""
        if self._model_stats_cols is None:
            have = {
                r[1] for r in conn.execute(f"PRAGMA table_info({table})")
            }
            cols = []
            for c in (
                "global_rank", "flops_per_step", "flops_source",
                "device_kind", "peak_flops", "device_count",
                "tokens_per_step",
            ):
                cols.append(c if c in have else f"NULL AS {c}")
            self._model_stats_cols = cols
        return ", ".join(self._model_stats_cols)

    def _read_model_stats(self, conn, table, dirty) -> bool:
        trimmed = self._begin_trim_check(conn, table)
        cur = self._cursors.get(table, 0)
        rows = conn.execute(
            f"SELECT id, {self._model_stats_select(conn, table)}"
            f" FROM {table} WHERE id > ? ORDER BY id",
            (cur,),
        ).fetchall()
        for r in rows:
            self._model_stats.append(r["id"], int(r["global_rank"]), dict(r))
        self._advance_cursor(table, rows)
        evicted = self._apply_trims(
            conn, table, trimmed, flat_bufs=(self._model_stats,)
        )
        return bool(rows) or evicted

    def _read_mesh_topology(self, conn, table, dirty) -> bool:
        """One-shot per-rank mesh placement; keep the latest row per
        rank (no trims — the table is not retained-pruned)."""
        cur = self._cursors.get(table, 0)
        rows = conn.execute(
            f"SELECT id, global_rank, node_rank, hostname, source,"
            f" axes_json, coords_json FROM {table}"
            " WHERE id > ? ORDER BY id",
            (cur,),
        ).fetchall()
        for r in rows:
            self._mesh_rows[int(r["global_rank"])] = dict(r)
        self._advance_cursor(table, rows)
        return bool(rows)

    # -- accessors (loader-shaped) --------------------------------------

    def step_time_rows(self) -> Dict[int, List[Dict[str, Any]]]:
        """global_rank → decoded step rows, loader-shaped
        (``loaders.load_step_time_rows``)."""
        with self._lock:
            return {
                rank: list(buf.rows)
                for rank, buf in sorted(self._step_time.items())
                if buf.rows
            }

    def step_memory_rows(self) -> Dict[int, List[Dict[str, Any]]]:
        with self._lock:
            return {
                rank: list(buf.rows)
                for rank, buf in sorted(self._step_memory.items())
                if buf.rows
            }

    def has_step_time_rows(self) -> bool:
        with self._lock:
            return any(buf.rows for buf in self._step_time.values())

    def latest_step_time_ts(self) -> Optional[float]:
        """max over ranks of the newest row's timestamp (the freshness
        stamp the live step-time view displays)."""
        with self._lock:
            vals = [
                buf.rows[-1].get("timestamp") or 0.0
                for buf in self._step_time.values()
                if buf.rows
            ]
        return max(vals) if vals else None

    def _window_cache(self, domain: str, factory):
        """Lazily create the domain's incremental window cache (caller
        holds the lock).  The cache survives for the store's lifetime:
        every structural change it cannot follow (rank churn, eviction
        into the window, clock flip, fallback) self-invalidates via the
        rings' monotone counters, so no explicit reset hooks exist."""
        cache = self._window_caches.get(domain)
        if cache is None:
            cache = self._window_caches[domain] = factory()
        return cache

    def window_build_stats(self) -> Dict[str, Dict[str, Any]]:
        """Per-domain incremental-vs-full build counters (empty until a
        columnar build ran with the incremental engine enabled), plus —
        once the tick profiler saw a tick — a ``tick_profile`` entry
        holding the per-stage ns breakdown and cache counters (r20)."""
        with self._lock:
            out: Dict[str, Dict[str, Any]] = {
                domain: cache.stats.snapshot()
                for domain, cache in sorted(self._window_caches.items())
            }
            if self.tick_profile.ticks or self.tick_profile.stage_ns:
                from traceml_tpu.utils.topology import grouping_cache_counts

                prof = self.tick_profile.snapshot()
                for domain, n in sorted(vector_fallback_counts().items()):
                    prof["counters"][f"vector_fallbacks_{domain}"] = n
                for k, n in sorted(grouping_cache_counts().items()):
                    prof["counters"][f"grouping_cache_{k}"] = n
                out["tick_profile"] = prof
            return out

    def build_step_time_window(
        self, max_steps: Optional[int] = None
    ) -> Optional[StepTimeWindow]:
        """Build the aligned cross-rank window straight from the store.

        Fast path: the vectorized columnar engine over the per-rank ring
        buffers.  Falls back to the scalar reference
        (``step_time_window.build_step_time_window`` over the row
        deques) when any rank's buffer is flagged un-columnar — or when
        ``TRACEML_COLUMNAR_WINDOW=0``.  Both paths produce identical
        windows (golden-pinned by tests/utils/test_columnar_window.py).
        """
        limit = self.window_steps if max_steps is None else int(max_steps)
        with self._lock:
            if columnar_window_enabled():
                try:
                    cols = {
                        rank: buf.cols
                        for rank, buf in self._step_time.items()
                        if buf.rows
                    }
                    if incr_window_enabled():
                        return self._window_cache(
                            "step_time", StepTimeWindowCache
                        ).build(cols, limit)
                    return build_columnar_step_time_window(cols, limit)
                except ColumnarFallback:
                    pass
            rank_rows = {
                rank: list(buf.rows)
                for rank, buf in sorted(self._step_time.items())
                if buf.rows
            }
        return _build_window_from_rows(rank_rows, max_steps=limit)

    def collectives_rows(self) -> Dict[int, List[Dict[str, Any]]]:
        """global_rank → decoded (step, op, dtype) aggregate rows."""
        with self._lock:
            return {
                rank: list(buf.rows)
                for rank, buf in sorted(self._collectives.items())
                if buf.rows
            }

    def has_collectives_rows(self) -> bool:
        with self._lock:
            return any(buf.rows for buf in self._collectives.values())

    def build_collectives_window(
        self, max_steps: Optional[int] = None
    ) -> Optional[CollectivesWindow]:
        """Cross-rank collectives window (overlap efficiency per step).

        Columnar fast path over the per-rank rings; scalar reference
        fold over the row deques when a buffer is flagged or the
        columnar engine is disabled.  Both paths are golden-pinned
        bit-identical (tests/utils/test_collectives_window.py).
        """
        limit = self.window_steps if max_steps is None else int(max_steps)
        with self._lock:
            if columnar_window_enabled():
                try:
                    cols = {
                        rank: buf.cols
                        for rank, buf in self._collectives.items()
                        if buf.rows
                    }
                    if incr_window_enabled():
                        return self._window_cache(
                            "collectives", CollectivesWindowCache
                        ).build(cols, limit)
                    return build_columnar_collectives_window(cols, limit)
                except ColumnarFallback:
                    pass
            rank_rows = {
                rank: list(buf.rows)
                for rank, buf in sorted(self._collectives.items())
                if buf.rows
            }
        return build_collectives_window_rows(rank_rows, max_steps=limit)

    def serving_rows(self) -> Dict[int, List[Dict[str, Any]]]:
        """global_rank → decoded per-window serving aggregate rows."""
        with self._lock:
            return {
                rank: list(buf.rows)
                for rank, buf in sorted(self._serving.items())
                if buf.rows
            }

    def has_serving_rows(self) -> bool:
        with self._lock:
            return any(buf.rows for buf in self._serving.values())

    # -- stitched rollup reads (reporting/tiers.py) ----------------------

    def has_rollups(self) -> bool:
        """True when the session DB carries folded history — the
        omit-when-empty gate for the history fragment / final block."""
        from traceml_tpu.reporting import tiers

        with self._lock:
            conn = self._conn
            if conn is None:
                return False
            try:
                return tiers.has_rollups(conn)
            except sqlite3.Error:
                return False

    def stitched_series(
        self, source_table: str, metric: str, grain: str = "rank"
    ) -> Dict[str, List[Dict[str, Any]]]:
        """Full-run resolution-aware series (raw tail + 10s + 1m tiers)
        per grain key.  Cached per (rollup version, raw-domain version)
        — a refresh that touched neither returns the cached stitch."""
        from traceml_tpu.reporting import tiers

        domain = source_table.replace("_samples", "")
        with self._lock:
            conn = self._conn
            if conn is None:
                return {}
            vkey = (
                self._versions.get("rollup", 0),
                self._versions.get(domain, 0),
            )
            ckey = (source_table, metric, grain)
            hit = self._stitched_cache.get(ckey)
            if hit is not None and hit[0] == vkey:
                return hit[1]
            try:
                result = tiers.load_stitched_series(
                    conn, source_table, metric, grain=grain
                )
            except sqlite3.Error as exc:
                get_error_log().warning(
                    f"stitched read failed for {source_table}/{metric}", exc
                )
                return {}
            self._stitched_cache[ckey] = (vkey, result)
            return result

    def stitched_overview(self) -> Dict[str, Any]:
        """Per-source stitched series for every served metric (the
        final report's ``history`` block shape); {} when no rollups."""
        from traceml_tpu.reporting import tiers

        with self._lock:
            conn = self._conn
        if conn is None:
            return {}
        out: Dict[str, Any] = {}
        try:
            if not tiers.has_rollups(conn):
                return {}
        except sqlite3.Error:
            return {}
        for source in _ROLLUP_SOURCES:
            per_metric: Dict[str, Any] = {}
            for metric in tiers.SOURCE_METRICS.get(source, ()):
                series = self.stitched_series(source, metric)
                if series:
                    per_metric[metric] = series
            if per_metric:
                out[source.replace("_samples", "")] = per_metric
        return out

    def latest_serving_ts(self) -> Optional[float]:
        with self._lock:
            vals = [
                buf.rows[-1].get("timestamp") or 0.0
                for buf in self._serving.values()
                if buf.rows
            ]
        return max(vals) if vals else None

    def build_serving_window(
        self, max_steps: Optional[int] = None
    ) -> Optional[ServingWindow]:
        """Cross-replica serving window (TTFT/e2e percentiles over the
        raw ragged populations).  Columnar fast path over the per-replica
        ragged rings; scalar reference fold over the row deques when a
        buffer is flagged or the columnar engine is disabled.  Both
        paths are golden-pinned bit-identical
        (tests/utils/test_serving_window.py).
        """
        limit = self.window_steps if max_steps is None else int(max_steps)
        with self._lock:
            if columnar_window_enabled():
                try:
                    cols = {
                        rank: buf.cols
                        for rank, buf in self._serving.items()
                        if buf.rows
                    }
                    if incr_window_enabled():
                        return self._window_cache(
                            "serving", ServingWindowCache
                        ).build(cols, limit)
                    return build_columnar_serving_window(cols, limit)
                except ColumnarFallback:
                    pass
            rank_rows = {
                rank: list(buf.rows)
                for rank, buf in sorted(self._serving.items())
                if buf.rows
            }
        return build_serving_window_rows(rank_rows, max_steps=limit)

    def step_memory_columns(self) -> Optional[Dict[int, MemoryColumns]]:
        """rank → memory ring buffer, or None when any rank's buffer is
        flagged (caller must use ``step_memory_rows`` instead) or the
        columnar engine is disabled."""
        if not columnar_window_enabled():
            return None
        with self._lock:
            out: Dict[int, MemoryColumns] = {}
            for rank, buf in sorted(self._step_memory.items()):
                if not buf.rows:
                    continue
                if not buf.cols.columnar_ok:
                    return None
                out[rank] = buf.cols
            return out or None

    @staticmethod
    def _group(buf: _RankBuffer) -> Dict[Any, List[Dict[str, Any]]]:
        out: Dict[Any, List[Dict[str, Any]]] = {}
        for key, row in buf.rows:
            out.setdefault(key, []).append(row)
        return out

    def system_rows(self) -> Tuple[Dict, Dict]:
        with self._lock:
            return self._group(self._system_host), self._group(self._system_dev)

    def process_rows(self) -> Tuple[Dict, Dict]:
        with self._lock:
            return self._group(self._process), self._group(self._process_dev)

    def stdout_tail(self, n: int = 12) -> List[Tuple[str, str]]:
        with self._lock:
            rows = list(self._stdout.rows)
        return rows[-int(n):]

    def model_stats(self) -> Dict[int, Dict[str, Any]]:
        """Same aggregation contract as ``loaders.load_model_stats``:
        median flops/tokens over the recent declarations, newest row
        wins for source/device_kind/peak."""
        import statistics

        with self._lock:
            rows = list(self._model_stats.rows)
        out: Dict[int, Dict[str, Any]] = {}
        per_rank_flops: Dict[int, List[float]] = {}
        per_rank_tokens: Dict[int, List[float]] = {}
        for r in rows:
            rank = int(r["global_rank"])
            if r["flops_per_step"]:
                per_rank_flops.setdefault(rank, []).append(
                    float(r["flops_per_step"])
                )
            if r["tokens_per_step"]:
                per_rank_tokens.setdefault(rank, []).append(
                    float(r["tokens_per_step"])
                )
            out[rank] = {  # ascending id order → the newest row wins
                "flops_source": r["flops_source"],
                "device_kind": r["device_kind"],
                "peak_flops": r["peak_flops"],
                "device_count": r["device_count"],
            }
        for rank, vals in per_rank_flops.items():
            out[rank]["flops_per_step"] = statistics.median(vals)
        for rank, vals in per_rank_tokens.items():
            out[rank]["tokens_per_step"] = statistics.median(vals)
        return {
            r: v for r, v in out.items()
            if v.get("flops_per_step") or v.get("tokens_per_step")
        }

    def topology(self) -> Dict[str, Any]:
        """Same source-preference contract as ``loaders.load_topology``:
        step_time identity columns when that table exists, else
        process, else system."""
        with self._lock:
            if self._topology_cache_version == self._versions["topology"] and (
                self._topology_cache is not None
            ):
                return self._topology_cache
            src = None
            for table in (
                "step_time_samples", "process_samples", "system_samples"
            ):
                if table in self._tables_seen:
                    src = self._topology[table]
                    break
            if src is None:
                out = {"mode": "unknown", "world_size": 0, "nodes": 0}
            else:
                ranks = sorted(src.ranks)
                out = {
                    "mode": "multi_node" if len(src.nodes) > 1 else "single_node",
                    "world_size": max(src.world, len(ranks)),
                    "ranks_seen": ranks,
                    "nodes": len(src.nodes),
                    "hostnames": sorted(src.hostnames),
                }
            mesh = self._mesh_topology_locked()
            if mesh is not None:
                # only-when-captured: pre-topology sessions keep the
                # exact historical dict shape (back-compat pin in
                # tests/utils/test_topology_attribution.py)
                out["mesh"] = {
                    "axes": [a.to_dict() for a in mesh.axes],
                    "source": mesh.source,
                    "ranks": len(mesh.rank_coords),
                    "hosts": len(set(mesh.rank_hosts.values())),
                }
            self._topology_cache = out
            self._topology_cache_version = self._versions["topology"]
            return out

    def _mesh_topology_locked(self):
        if (
            self._mesh_cache_version == self._versions["topology"]
            and self._mesh_cache is not None
        ):
            return self._mesh_cache
        if not self._mesh_rows:
            return None
        from traceml_tpu.utils.topology import topology_from_rank_rows

        self._mesh_cache = topology_from_rank_rows(
            [self._mesh_rows[r] for r in sorted(self._mesh_rows)]
        )
        self._mesh_cache_version = self._versions["topology"]
        return self._mesh_cache

    def mesh_topology(self):
        """The merged :class:`~traceml_tpu.utils.topology.MeshTopology`,
        or None when no rank ever shipped a ``mesh_topology`` message —
        the signal every diagnose call site uses to stay on flat rank
        lists."""
        with self._lock:
            return self._mesh_topology_locked()
