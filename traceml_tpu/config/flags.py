"""Single declared registry of every ``TRACEML_*`` environment flag.

Every kill switch, tuning knob, and launcher→child contract variable is
declared HERE — name, default, one-line doc — and read through the
:class:`Flag` accessors.  ``traceml lint``'s env-flag registry pass
(``traceml_tpu/analysis/flags_pass.py``) enforces the contract
mechanically:

* a ``TRACEML_*`` string literal anywhere else in the package that is
  not declared here is an error (``TLF001``);
* a declared flag with an empty doc line is an error (``TLF002``);
* a declared flag referenced nowhere outside this module is a dead
  flag (``TLF003``);
* an ``os.environ``/``getenv`` read of a ``TRACEML_*`` name outside
  this module bypasses the registry (``TLF004``) — call
  ``<FLAG>.raw()/enabled()/truthy()/get_*()`` instead.

``runtime/settings.py`` keeps its ``ENV_*`` aliases (the
launcher↔child env contract is expressed as plain names there) but
derives them from these declarations, so the name exists in exactly
one place.

The module is intentionally stdlib-only and import-cheap: it is read
on hot fail-open paths (sampler builds, transport setup) and by the
zero-dependency static analyzer.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Optional

# values meaning "explicitly off" / "explicitly on" — shared by every
# boolean flag so kill switches behave uniformly
_FALSY = ("0", "false", "off")
_TRUTHY = ("1", "true", "yes", "on")


@dataclasses.dataclass(frozen=True)
class Flag:
    """One declared ``TRACEML_*`` variable.

    ``default`` is the *raw string* default (or ``None`` for unset) so
    the declaration mirrors what a shell would export; typed accessors
    coerce on read and fall back to the default on malformed values
    (env flags must never raise into training code).
    """

    name: str
    default: Optional[str]
    doc: str

    def raw(self, env: Optional[Dict[str, str]] = None) -> Optional[str]:
        """The raw value, or the declared default when unset."""
        e = os.environ if env is None else env
        v = e.get(self.name)
        return self.default if v is None else v

    def is_set(self, env: Optional[Dict[str, str]] = None) -> bool:
        e = os.environ if env is None else env
        return self.name in e

    def enabled(self, env: Optional[Dict[str, str]] = None) -> bool:
        """Kill-switch reading: on unless explicitly ``0/false/off``."""
        v = self.raw(env)
        if v is None:
            return True
        return str(v).strip().lower() not in _FALSY

    def truthy(self, env: Optional[Dict[str, str]] = None) -> bool:
        """Opt-in reading: off unless explicitly ``1/true/yes/on``."""
        v = self.raw(env)
        if v is None:
            return False
        return str(v).strip().lower() in _TRUTHY

    def get_str(self, env: Optional[Dict[str, str]] = None) -> Optional[str]:
        return self.raw(env)

    def get_float(
        self, fallback: float, env: Optional[Dict[str, str]] = None
    ) -> float:
        v = self.raw(env)
        if v is None:
            return fallback
        try:
            return float(v)
        except (TypeError, ValueError):
            return fallback

    def get_int(
        self, fallback: int, env: Optional[Dict[str, str]] = None
    ) -> int:
        v = self.raw(env)
        if v is None:
            return fallback
        try:
            return int(str(v).strip())
        except (TypeError, ValueError):
            return fallback


REGISTRY: Dict[str, Flag] = {}


def declare(name: str, default: Optional[str], doc: str) -> Flag:
    """Register one flag.  ``traceml lint`` parses these calls, so the
    name and doc must be literals."""
    if name in REGISTRY:
        raise ValueError(f"duplicate flag declaration: {name}")
    flag = Flag(name=name, default=default, doc=doc)
    REGISTRY[name] = flag
    return flag


def get(name: str) -> Flag:
    """Look up a declared flag by env-var name (KeyError on undeclared
    names — an undeclared flag is a bug the lint gate also catches)."""
    return REGISTRY[name]


# --------------------------------------------------------------------
# launcher ↔ child contract (mirrored as ENV_* in runtime/settings.py)
# --------------------------------------------------------------------
SESSION_ID = declare(
    "TRACEML_SESSION_ID", "local",
    "session id: names <logs_dir>/<session> and every artifact in it")
LOGS_DIR = declare(
    "TRACEML_LOGS_DIR", "./traceml_logs",
    "root directory sessions are written under")
MODE = declare(
    "TRACEML_MODE", "cli",
    "display mode the launcher selected: cli | summary | dashboard")
AGGREGATOR_HOST = declare(
    "TRACEML_AGGREGATOR_HOST", "127.0.0.1",
    "address workers dial to reach the aggregator (owner node address)")
AGGREGATOR_BIND_HOST = declare(
    "TRACEML_AGGREGATOR_BIND_HOST", None,
    "address the aggregator binds (defaults to the connect host)")
AGGREGATOR_PORT = declare(
    "TRACEML_AGGREGATOR_PORT", "0",
    "aggregator TCP port; 0 = off/unassigned (ranks run untraced)")
SAMPLER_INTERVAL_SEC = declare(
    "TRACEML_SAMPLER_INTERVAL_SEC", "1.0",
    "seconds between sampler ticks on every rank")
TRACE_MAX_STEPS = declare(
    "TRACEML_TRACE_MAX_STEPS", None,
    "stop recording step telemetry after this many steps (unset = all)")
DISABLE = declare(
    "TRACEML_DISABLE", None,
    "master kill switch: 1 = run the script entirely untraced")
DISK_BACKUP = declare(
    "TRACEML_DISK_BACKUP", None,
    "1 = every rank also spools envelopes to per-rank msgpack backups")
CAPTURE_STDERR = declare(
    "TRACEML_CAPTURE_STDERR", "1",
    "mirror rank stderr into the stdout capture stream (0 to opt out)")
RUN_NAME = declare(
    "TRACEML_RUN_NAME", None,
    "human-readable run name recorded in the manifest and reports")
EXPECTED_WORLD_SIZE = declare(
    "TRACEML_EXPECTED_WORLD_SIZE", None,
    "world size the launcher promised; liveness flags ranks never seen")
FINALIZE_TIMEOUT_SEC = declare(
    "TRACEML_FINALIZE_TIMEOUT_SEC", "300.0",
    "seconds the launcher waits for the final drain + summary write")
SUMMARY_WINDOW_ROWS = declare(
    "TRACEML_SUMMARY_WINDOW_ROWS", "10000",
    "per-table per-rank row retention bound in the session DB")
SERVE_MAX_SESSIONS = declare(
    "TRACEML_SERVE_MAX_SESSIONS", "8",
    "serving tier: max concurrently-open session publishers (LRU bound)")
SCRIPT = declare(
    "TRACEML_SCRIPT", None,
    "path of the user training script the rank executor should run")
SCRIPT_ARGS = declare(
    "TRACEML_SCRIPT_ARGS", None,
    "shell-quoted argv tail for the user training script")

# --------------------------------------------------------------------
# transport tier (docs/developer_guide/native-transport.md)
# --------------------------------------------------------------------
TRANSPORT = declare(
    "TRACEML_TRANSPORT", "auto",
    "telemetry transport: auto | shm | uds | tcp (auto = same-host shm)")
TRANSPORT_COMPRESS = declare(
    "TRACEML_TRANSPORT_COMPRESS", "auto",
    "cross-host envelope compression: auto | zstd | zlib | 0 (off)")
SHM_RING_BYTES = declare(
    "TRACEML_SHM_RING_BYTES", "4194304",
    "per-rank shared-memory ring capacity in bytes (same-host transport)")
SHM_DIR = declare(
    "TRACEML_SHM_DIR", None,
    "directory for ring segment files (default /dev/shm, else rank dir)")
UDS_PATH = declare(
    "TRACEML_UDS_PATH", None,
    "Unix-domain socket path for the uds transport (default derived)")

# --------------------------------------------------------------------
# fleet federation (docs/developer_guide/federation.md)
# --------------------------------------------------------------------
FLEET_SHARDS = declare(
    "TRACEML_FLEET_SHARDS", None,
    "fleet router: comma-separated host:port shard list, or a shards.json path")
FLEET_PORT = declare(
    "TRACEML_FLEET_PORT", "0",
    "fleet router: HTTP port the router front-end binds (0 = ephemeral)")
FLEET_HOST = declare(
    "TRACEML_FLEET_HOST", "127.0.0.1",
    "fleet router: address the router front-end binds")
FLEET_CACHE_TTL = declare(
    "TRACEML_FLEET_CACHE_TTL", "0.5",
    "fleet tier: edge-cache + fleet-index reuse window in seconds")
FLEET_PROBE_S = declare(
    "TRACEML_FLEET_PROBE_S", "2.0",
    "fleet router: base shard health-probe interval (capped backoff on failure)")
FLEET_STATE_DIR = declare(
    "TRACEML_FLEET_STATE_DIR", None,
    "fleet router: directory fleet_router_ready.json is written to (launcher contract)")

# --------------------------------------------------------------------
# fault tolerance / liveness
# --------------------------------------------------------------------
AGG_MAX_RESTARTS = declare(
    "TRACEML_AGG_MAX_RESTARTS", "3",
    "bounded aggregator crash-resume: respawns before degrading untraced")
FAULT_PLAN = declare(
    "TRACEML_FAULT_PLAN", None,
    "JSON fault-injection plan for the deterministic chaos harness")
HEARTBEAT_INTERVAL_SEC = declare(
    "TRACEML_HEARTBEAT_INTERVAL_SEC", "3.0",
    "seconds between rank_heartbeat control messages (liveness input)")
LIVENESS_STALE_SEC = declare(
    "TRACEML_LIVENESS_STALE_SEC", "10.0",
    "silence age after which a rank is marked stale (~3 heartbeats)")
LIVENESS_LOST_SEC = declare(
    "TRACEML_LIVENESS_LOST_SEC", "30.0",
    "silence age after which a stale rank is marked lost")

# --------------------------------------------------------------------
# kill switches / opt-ins
# --------------------------------------------------------------------
COLLECTIVES = declare(
    "TRACEML_COLLECTIVES", "1",
    "0 turns every collectives-capture entry point into a no-op")
COLUMNAR_WINDOW = declare(
    "TRACEML_COLUMNAR_WINDOW", "1",
    "0 forces the scalar window-build reference path")
INCR_WINDOW = declare(
    "TRACEML_INCR_WINDOW", "1",
    "0 disables the incremental window caches (full rebuild every tick)")
SERVING = declare(
    "TRACEML_SERVING", "1",
    "0 turns every serving-capture entry point into a no-op")
VECTOR_DIAGNOSIS = declare(
    "TRACEML_VECTOR_DIAGNOSIS", "1",
    "0 forces the scalar rule-evaluation reference arm in diagnosis")
SERVING_QUEUE_MAX = declare(
    "TRACEML_SERVING_QUEUE_MAX", "8192",
    "serving domain: bounded request-event queue capacity per rank")
NO_NATIVE = declare(
    "TRACEML_NO_NATIVE", None,
    "1 skips the optional C framing extension (pure-Python fallback)")
NO_PPID_WATCH = declare(
    "TRACEML_NO_PPID_WATCH", None,
    "1 disarms the orphan watchdog (deliberate daemonization)")
NO_FLOPS_ESTIMATE = declare(
    "TRACEML_NO_FLOPS_ESTIMATE", None,
    "1 skips the one-time XLA cost-analysis FLOPs estimate at first step")
PIN_RANK_CPUS = declare(
    "TRACEML_PIN_RANK_CPUS", None,
    "1 pins each local rank to its own core slice (skew isolation)")
OVERHEAD_BUDGET = declare(
    "TRACEML_OVERHEAD_BUDGET", None,
    "tracer overhead budget as a fraction of step time (default 0.01)")
MESH = declare(
    "TRACEML_MESH", None,
    "mesh override grammar name:size[@kind],... for topology capture")
ROLLUP = declare(
    "TRACEML_ROLLUP", "1",
    "0 disables tiered rollup decay (watermark prunes discard history)")
ROLLUP_TIERS = declare(
    "TRACEML_ROLLUP_TIERS", None,
    "rollup tier grammar width[:horizon],... seconds (default 10:21600,60:1209600)")
BASELINE_MAX_RUNS = declare(
    "TRACEML_BASELINE_MAX_RUNS", "20",
    "cross-run baseline store: matching sessions kept per fingerprint")

# --------------------------------------------------------------------
# dev / CI tooling
# --------------------------------------------------------------------
BENCH_NO_PROBE = declare(
    "TRACEML_BENCH_NO_PROBE", None,
    "1 makes bench.py skip the hardware probe (CI determinism)")
AXON_SAVED_POOL_IPS = declare(
    "TRACEML_AXON_SAVED_POOL_IPS", None,
    "pool IPs tpu_watch saved from the scrubbed launcher environment")
