"""Occupancy-derived utilization + new system/process rule battery
(VERDICT r1 items 6/8: the chip-busy signal and the counter-gated
utilization/temperature/power and HIGH_PROCESS_CPU rules)."""

from traceml_tpu.diagnostics.process.api import diagnose as diagnose_process
from traceml_tpu.diagnostics.step_time.api import diagnose_rank_rows
from traceml_tpu.diagnostics.system.api import diagnose as diagnose_system
from traceml_tpu.diagnostics.system.rules import SystemPolicy
from traceml_tpu.utils import timing as T
from traceml_tpu.utils.step_time_window import build_step_time_window

GiB = 1024**3


def _rows(device_busy_ms, host_step_ms=100.0, n=60):
    """Occupancy numerator = Σ PHASE device durations (here: one compute
    phase of ``device_busy_ms``); the envelope's own device span is
    deliberately larger (it includes pre-dispatch idle) and must NOT
    drive occupancy."""
    return [
        {
            "step": s,
            "timestamp": float(s),
            "clock": "device",
            "events": {
                T.STEP_TIME: {
                    "cpu_ms": host_step_ms,
                    "device_ms": host_step_ms,  # span ≈ wall; not busy!
                    "count": 1,
                },
                T.COMPUTE_TIME: {
                    "cpu_ms": 1.0,
                    "device_ms": device_busy_ms,
                    "count": 1,
                },
            },
        }
        for s in range(1, n + 1)
    ]


def test_window_occupancy_computed():
    window = build_step_time_window({0: _rows(20.0), 1: _rows(40.0)})
    occ = window.occupancy_by_rank
    assert abs(occ[0] - 0.2) < 1e-6
    assert abs(occ[1] - 0.4) < 1e-6
    assert abs(window.median_occupancy - 0.3) < 1e-6


def test_window_occupancy_ignores_envelope_span():
    """An input-bound shape: envelope device span ≈ wall (the edges
    carry across the idle input wait) but the only device-executing
    phase is 30 ms — occupancy must read 0.3, not 1.0."""
    rows = [
        {
            "step": s, "timestamp": float(s), "clock": "device",
            "events": {
                T.STEP_TIME: {"cpu_ms": 100.0, "device_ms": 98.0, "count": 1},
                T.DATALOADER_NEXT: {"cpu_ms": 65.0, "device_ms": None, "count": 1},
                T.COMPUTE_TIME: {"cpu_ms": 1.0, "device_ms": 30.0, "count": 1},
            },
        }
        for s in range(1, 31)
    ]
    w = build_step_time_window({0: rows})
    assert abs(w.occupancy_by_rank[0] - 0.3) < 1e-6


def test_window_occupancy_capped_and_absent():
    # device busy nominally exceeding wall clips to 1.0
    w = build_step_time_window({0: _rows(130.0)})
    assert w.occupancy_by_rank[0] == 1.0
    # host-only rows → no occupancy
    rows = [
        {"step": s, "timestamp": float(s), "clock": "host",
         "events": {T.STEP_TIME: {"cpu_ms": 100.0, "device_ms": None, "count": 1}}}
        for s in range(1, 30)
    ]
    w = build_step_time_window({0: rows})
    assert w.occupancy_by_rank == {}
    assert w.median_occupancy is None


def test_low_occupancy_fires_live_and_summary():
    rank_rows = {0: _rows(20.0)}  # 20% busy
    for mode in ("live", "summary"):
        result = diagnose_rank_rows(rank_rows, mode=mode)
        kinds = {i.kind for i in result.issues}
        assert "LOW_DEVICE_UTILIZATION" in kinds, mode
        issue = next(i for i in result.issues if i.kind == "LOW_DEVICE_UTILIZATION")
        assert issue.severity == "warning"
        assert "20%" in issue.summary


def test_very_low_occupancy_critical():
    result = diagnose_rank_rows({0: _rows(10.0)}, mode="live")
    issue = next(i for i in result.issues if i.kind == "LOW_DEVICE_UTILIZATION")
    assert issue.severity == "critical"


def test_high_occupancy_no_fire():
    result = diagnose_rank_rows({0: _rows(90.0)}, mode="live")
    assert "LOW_DEVICE_UTILIZATION" not in {i.kind for i in result.issues}


# --- system counter rules (data-gated) -------------------------------------

def _dev_rows(**kw):
    base = {"memory_used_bytes": 1 * GiB, "memory_total_bytes": 16 * GiB,
            "utilization_pct": None, "temperature_c": None, "power_w": None}
    base.update(kw)
    return {(0, 0): [dict(base) for _ in range(12)]}


def test_utilization_counter_rule():
    result = diagnose_system({}, _dev_rows(utilization_pct=15.0))
    assert "LOW_DEVICE_UTILIZATION" in {i.kind for i in result.issues}
    # the 30–70% band is informational, not a warning
    # (reference: MODERATE_GPU_UTILIZATION)
    result = diagnose_system({}, _dev_rows(utilization_pct=50.0))
    issue = next(
        i for i in result.issues if i.kind == "MODERATE_DEVICE_UTILIZATION"
    )
    assert issue.severity == "info"
    assert "LOW_DEVICE_UTILIZATION" not in {i.kind for i in result.issues}
    # healthy util → silent
    result = diagnose_system({}, _dev_rows(utilization_pct=85.0))
    kinds = {i.kind for i in result.issues}
    assert "LOW_DEVICE_UTILIZATION" not in kinds
    assert "MODERATE_DEVICE_UTILIZATION" not in kinds
    # null columns (current TPU runtime) → gated off, no crash
    result = diagnose_system({}, _dev_rows())
    assert "LOW_DEVICE_UTILIZATION" not in {i.kind for i in result.issues}


def test_temperature_rule_tiers():
    result = diagnose_system({}, _dev_rows(temperature_c=86.0))
    issue = next(i for i in result.issues if i.kind == "HIGH_DEVICE_TEMPERATURE")
    assert issue.severity == "warning"
    result = diagnose_system({}, _dev_rows(temperature_c=96.0))
    issue = next(i for i in result.issues if i.kind == "HIGH_DEVICE_TEMPERATURE")
    assert issue.severity == "critical"


def test_power_rule_needs_rated_power():
    # default policy: rated unknown → rule silent even at high draw
    result = diagnose_system({}, _dev_rows(power_w=500.0))
    assert "HIGH_DEVICE_POWER" not in {i.kind for i in result.issues}
    # with rated power configured the rule engages
    policy = SystemPolicy(device_power_rated_w=400.0)
    result = diagnose_system({}, _dev_rows(power_w=390.0), policy=policy)
    assert "HIGH_DEVICE_POWER" in {i.kind for i in result.issues}


# --- process CPU tiers ------------------------------------------------------

def _proc(cpu):
    return {0: [{"cpu_pct": cpu, "rss_bytes": 1 * GiB, "num_threads": 8}] * 30}


def test_process_cpu_tiers():
    assert "HIGH_PROCESS_CPU" not in {
        i.kind for i in diagnose_process(_proc(200.0), {}).issues
    }
    warn = diagnose_process(_proc(400.0), {})
    issue = next(i for i in warn.issues if i.kind == "HIGH_PROCESS_CPU")
    assert issue.severity == "warning"
    crit = diagnose_process(_proc(900.0), {})
    issue = next(i for i in crit.issues if i.kind == "HIGH_PROCESS_CPU")
    assert issue.severity == "critical"
