"""Mixture-of-Experts FFN with expert parallelism
(SURVEY §2.13 / driver mandate: the ``ep`` axis of tp/pp/dp/sp/ep —
the reference has no MoE; this is the TPU-native design for one).

Design — dense-dispatch MoE, XLA-first:

* routing and dispatch are ONE pair of einsums over a static expert
  dimension — no top-k scatter, no capacity overflow, no dynamic
  shapes.  Every expert sees every token, weighted by its gate
  probability (soft-MoE style).  For the small expert counts the test
  meshes carry this is FLOP-comparable to capacity-based dispatch and
  maps straight onto the MXU; the point here is the SHARDING pattern,
  which is identical to a capacity-based implementation's:
* the expert dimension of ``w_in (E, H, F)`` / ``w_out (E, F, H)`` is
  sharded over the mesh's ``expert`` axis.  Under GSPMD the dispatch
  einsum partitions by expert and the combine einsum inserts the
  reduce over the expert axis automatically — each chip computes only
  its local experts' contributions and the partial sums ride ICI.
* an auxiliary load-balance loss (squared-importance, the
  switch-transformer shape: Σ_e mean_gate_e² · E, minimized by uniform
  routing) keeps the router from collapsing onto one expert.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


class MoEFFN(nn.Module):
    """Expert-parallel feed-forward block."""

    n_experts: int
    hidden: int
    ffn_hidden: int

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """x: (batch, seq, hidden) → (out, aux_loss)."""
        e, h, f = self.n_experts, self.hidden, self.ffn_hidden
        router = self.param(
            "router", nn.initializers.normal(0.02), (h, e), jnp.float32
        )
        w_in = self.param(
            "w_in", nn.initializers.normal(0.02), (e, h, f), jnp.float32
        )
        w_out = self.param(
            "w_out", nn.initializers.normal(0.02), (e, f, h), jnp.float32
        )
        # router probabilities per token
        gates = jax.nn.softmax(
            jnp.einsum("bsh,he->bse", x, router), axis=-1
        )  # (B, S, E)
        # dense dispatch: every expert computes, gated combine reduces
        # over the expert dim (GSPMD turns this into a psum over the
        # 'expert' mesh axis when w_in/w_out are expert-sharded)
        inner = jax.nn.silu(jnp.einsum("bsh,ehf->ebsf", x, w_in))
        expert_out = jnp.einsum("ebsf,efh->ebsh", inner, w_out)
        out = jnp.einsum("bse,ebsh->bsh", gates, expert_out)
        # load-balance aux: squared mean gate per expert (switch-style
        # importance loss; uniform routing minimizes it)
        importance = gates.mean(axis=(0, 1))  # (E,)
        aux = (importance**2).sum() * e
        return out, aux


class MoEBlock(nn.Module):
    """Pre-norm residual block around the expert FFN."""

    n_experts: int
    hidden: int
    ffn_hidden: int

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        scale = self.param("norm_scale", nn.initializers.ones, (self.hidden,))
        normed = x * jax.lax.rsqrt(
            jnp.mean(x * x, axis=-1, keepdims=True) + 1e-6
        ) * scale
        out, aux = MoEFFN(
            n_experts=self.n_experts,
            hidden=self.hidden,
            ffn_hidden=self.ffn_hidden,
        )(normed)
        return x + out, aux


def moe_param_shardings(params, mesh, expert_axis: str = "expert") -> Any:
    """Expert-parallel sharding specs: expert dim over the expert axis,
    inner dims over fsdp/tensor where they exist."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    have_fsdp = "fsdp" in mesh.axis_names
    fsdp = "fsdp" if have_fsdp else None

    def spec_for(path: Tuple[str, ...], leaf) -> Any:
        if "w_in" in path or "w_out" in path:
            return NamedSharding(mesh, P(expert_axis, fsdp, None))
        if "router" in path:
            return NamedSharding(mesh, P(fsdp, None))
        return NamedSharding(mesh, P())

    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    treedef = jax.tree_util.tree_structure(params)
    specs = []
    for path, leaf in flat:
        keys = tuple(
            getattr(k, "key", getattr(k, "name", str(k))) for k in path
        )
        specs.append(spec_for(keys, leaf))
    return jax.tree_util.tree_unflatten(treedef, specs)


def make_moe_train_step(
    model: MoEBlock, learning_rate: float = 1e-3, aux_weight: float = 0.01
):
    """(params, opt_state, x, y) → (params, opt_state, metrics) — simple
    regression objective over the block, aux-loss regularized."""
    import optax

    tx = optax.adam(learning_rate)

    def loss_fn(params, x, y):
        out, aux = model.apply({"params": params}, x)
        mse = jnp.mean((out - y) ** 2)
        return mse + aux_weight * aux, (mse, aux)

    def train_step(params, opt_state, x, y):
        (loss, (mse, aux)), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, x, y
        )
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, {"loss": loss, "mse": mse, "aux": aux}

    def init(rng, x):
        params = model.init(rng, x)["params"]
        return params, tx.init(params)

    return init, train_step


def init_expert_parallel(
    model: MoEBlock,
    mesh,
    rng: Optional[jax.Array] = None,
    sample: Optional[jnp.ndarray] = None,
    expert_axis: str = "expert",
) -> Dict[str, Any]:
    """Initialize params and place them expert-sharded over the mesh."""
    rng = jax.random.PRNGKey(0) if rng is None else rng
    if sample is None:
        sample = jnp.zeros((2, 8, model.hidden), jnp.float32)
    params = model.init(rng, sample)["params"]
    shardings = moe_param_shardings(params, mesh, expert_axis)
    params = jax.tree_util.tree_map(
        lambda leaf, s: jax.device_put(leaf, s), params, shardings
    )
    return {"params": params, "shardings": shardings}
