"""HTML report chrome (reference role: reporting/html/style.py —
single source of truth for the report's CSS and functional colors).

Print-friendly light theme (the report is attached to tickets and
printed), with the SAME functional phase/severity palette as the CLI
renderers and the live dashboard — colors encode meaning across
surfaces and must not be re-hued here.
"""

from __future__ import annotations

SEV_COLOR = {"critical": "#c0392b", "warning": "#e67e22", "info": "#2d7dd2"}

PHASE_COLORS = {
    "input": "#e74c3c",
    "h2d": "#e67e22",
    "forward": "#2d7dd2",
    "backward": "#2255a4",
    "optimizer": "#7d3dd2",
    "compute": "#2d7dd2",
    "compile": "#f1c40f",
    "collective": "#16a085",
    "checkpoint": "#8e5a2b",
    "residual": "#95a5a6",
}

CSS = """
body{font-family:system-ui,-apple-system,sans-serif;margin:2rem auto;
     max-width:980px;color:#1a1a2e;background:#fafafa;padding:0 1rem}
h1{font-size:1.4rem}
h2{font-size:1.1rem;margin-top:2rem;border-bottom:1px solid #ddd;
   padding-bottom:.3rem}
.verdict{border-radius:10px;padding:1rem 1.25rem;color:#fff;margin:1rem 0}
.verdict small{opacity:.85}
.verdict .ev{margin-top:.5rem;font-size:.8rem;opacity:.92;
  font-family:ui-monospace,Menlo,monospace}
table{border-collapse:collapse;width:100%;font-size:.9rem}
th,td{text-align:left;padding:.35rem .6rem;border-bottom:1px solid #eee}
th{background:#f0f0f5;font-weight:600}
td.num,th.num{text-align:right;font-variant-numeric:tabular-nums}
.bar{height:18px;border-radius:3px;display:inline-block;vertical-align:middle}
.muted{color:#777;font-size:.85rem}
code{background:#eee;padding:.05rem .3rem;border-radius:3px}
.kpis{display:flex;gap:10px;flex-wrap:wrap;margin:.8rem 0}
.kpi{background:#fff;border:1px solid #e4e4ec;border-left:4px solid
  var(--acc,#2d7dd2);border-radius:8px;padding:.5rem .8rem;min-width:110px}
.klab{font-size:.65rem;letter-spacing:.08em;text-transform:uppercase;
  color:#888;font-weight:600}
.kval{font-size:1.15rem;font-weight:600;font-variant-numeric:tabular-nums}
.kunit{font-size:.7em;color:#999;margin-left:2px}
.chips{margin:.6rem 0}
.chip{display:inline-block;font-size:.72rem;border-radius:999px;
  padding:.15rem .6rem;background:#ececf2;margin-right:.35rem}
.pill{display:inline-block;font-size:.7rem;font-weight:600;color:#fff;
  border-radius:999px;padding:.1rem .55rem;text-transform:uppercase}
@media print{body{background:#fff}.kpi{break-inside:avoid}}
"""


def kpi(label: str, value: str, unit: str = "", accent: str = "#2d7dd2") -> str:
    """One KPI tile (matches the dashboard's tile treatment)."""
    u = f"<span class='kunit'>{unit}</span>" if unit else ""
    return (
        f"<div class='kpi' style='--acc:{accent}'>"
        f"<div class='klab'>{label}</div>"
        f"<div class='kval'>{value}{u}</div></div>"
    )
