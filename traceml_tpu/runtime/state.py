"""Recording lifecycle (reference: src/traceml_ai/runtime/state.py:94-152).

``--trace-max-steps N`` stops *recording* after N steps while the user
job keeps training: RECORDING → DRAINING (samplers flush what is
buffered) → COMPLETE (runtime sends ``rank_finished`` and goes quiet).
"""

from __future__ import annotations

import threading
from typing import Optional

RECORDING = "RECORDING"
DRAINING = "DRAINING"
COMPLETE = "COMPLETE"


class RecordingState:
    def __init__(self, max_steps: Optional[int] = None) -> None:
        self._lock = threading.Lock()
        self._max_steps = max_steps
        self._phase = RECORDING
        self._flushed_steps = 0

    @property
    def phase(self) -> str:
        with self._lock:
            return self._phase

    @property
    def recording(self) -> bool:
        return self.phase == RECORDING

    def on_step_flushed(self, step: int) -> None:
        with self._lock:
            self._flushed_steps = max(self._flushed_steps, step)
            if (
                self._phase == RECORDING
                and self._max_steps is not None
                and self._flushed_steps >= self._max_steps
            ):
                self._phase = DRAINING

    def mark_drained(self) -> None:
        with self._lock:
            if self._phase == DRAINING:
                self._phase = COMPLETE

    def force_complete(self) -> None:
        with self._lock:
            self._phase = COMPLETE
