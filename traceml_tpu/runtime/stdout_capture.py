"""stdout/stderr tee (reference: src/traceml_ai/runtime/stdout_stderr_capture.py:6-50)."""

from __future__ import annotations

import sys
import threading
from typing import List, Optional, Tuple


class StreamCapture:
    """Tees sys.stdout/sys.stderr into a bounded in-memory buffer while
    passing everything through to the original streams."""

    def __init__(self, max_lines: int = 2000, capture_stderr: bool = True) -> None:
        self._max = max_lines
        self._lock = threading.Lock()
        self._lines: List[Tuple[str, str]] = []  # (stream, line)
        self._orig_stdout: Optional[object] = None
        self._orig_stderr: Optional[object] = None
        self._capture_stderr = capture_stderr

    class _Tee:
        def __init__(self, orig, cap: "StreamCapture", label: str) -> None:
            self._orig = orig
            self._cap = cap
            self._label = label
            self._partial = ""

        def write(self, data: str) -> int:
            try:
                n = self._orig.write(data)
            except Exception:
                n = len(data)
            try:
                self._partial += data
                while "\n" in self._partial:
                    line, self._partial = self._partial.split("\n", 1)
                    self._cap._add(self._label, line)
            except Exception:
                pass
            return n if isinstance(n, int) else len(data)

        def flush(self) -> None:
            try:
                self._orig.flush()
            except Exception:
                pass

        def isatty(self) -> bool:
            try:
                return self._orig.isatty()
            except Exception:
                return False

        def fileno(self) -> int:
            return self._orig.fileno()

        @property
        def encoding(self):
            return getattr(self._orig, "encoding", "utf-8")

        def __getattr__(self, name):
            # Proxy everything else (buffer, writable, readable, mode, …)
            # so user code poking sys.stdout keeps working under capture.
            return getattr(self._orig, name)

    def _add(self, label: str, line: str) -> None:
        with self._lock:
            self._lines.append((label, line))
            if len(self._lines) > self._max:
                del self._lines[: len(self._lines) - self._max]

    def start(self) -> None:
        if self._orig_stdout is not None:
            return
        self._orig_stdout = sys.stdout
        sys.stdout = self._Tee(sys.stdout, self, "stdout")
        if self._capture_stderr:
            self._orig_stderr = sys.stderr
            sys.stderr = self._Tee(sys.stderr, self, "stderr")

    def stop(self) -> None:
        if self._orig_stdout is not None:
            sys.stdout = self._orig_stdout
            self._orig_stdout = None
        if self._orig_stderr is not None:
            sys.stderr = self._orig_stderr
            self._orig_stderr = None

    def drain(self) -> List[Tuple[str, str]]:
        with self._lock:
            out, self._lines = self._lines, []
        return out
