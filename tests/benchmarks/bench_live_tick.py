"""Live tick cost: full-reload baseline vs incremental snapshot store.

The pre-change live path re-read the whole session DB every tick —
``SELECT DISTINCT global_rank`` + one query per rank (N+1), a fresh
``json.loads`` of every events_json blob, and a second window build
inside ``diagnose_rank_rows`` — even when nothing changed.  The
incremental path (``LiveSnapshotStore`` + dirty-gated ``LiveComputer``)
must beat it by construction:

* warm no-new-data tick: ≥ 10× faster (one ``PRAGMA data_version``);
* warm incremental tick (one new step per rank): ≥ 3× faster;
* identical window / diagnosis / per-domain output (golden comparison
  against the vendored pre-change loader path).

Asserted at 256 ranks × 120 steps; 64 ranks is emitted for scaling
context.  Results print as bench_common JSON lines (collected into the
BENCH_LOCAL_* records at the repo root).
"""

import json
import sqlite3
import statistics
import sys
import time
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).parent))
import bench_common  # noqa: E402

from traceml_tpu.aggregator.sqlite_writer import SQLiteWriter  # noqa: E402
from traceml_tpu.diagnostics.step_memory.api import (  # noqa: E402
    diagnose_rank_rows as diagnose_memory,
)
from traceml_tpu.diagnostics.process.api import (  # noqa: E402
    diagnose as diagnose_process,
)
from traceml_tpu.diagnostics.step_time.api import diagnose_rank_rows  # noqa: E402
from traceml_tpu.diagnostics.system.api import (  # noqa: E402
    diagnose as diagnose_system,
)
from traceml_tpu.renderers import views as V  # noqa: E402
from traceml_tpu.renderers.compute import LiveComputer  # noqa: E402
from traceml_tpu.reporting import loaders  # noqa: E402
from traceml_tpu.telemetry.envelope import (  # noqa: E402
    SenderIdentity,
    build_telemetry_envelope,
)
from traceml_tpu.utils import timing as T  # noqa: E402
from traceml_tpu.utils.step_time_window import build_step_time_window  # noqa: E402

pytestmark = pytest.mark.slow

BENCH = "live_tick"
WINDOW = 120
RANKS_PER_NODE = 8


# -- synthetic session -----------------------------------------------------


def _ident(rank, world):
    node = rank // RANKS_PER_NODE
    return SenderIdentity(
        session_id="bench",
        global_rank=rank,
        local_rank=rank % RANKS_PER_NODE,
        world_size=world,
        node_rank=node,
        hostname=f"host-{node}",
        pid=1000 + rank,
    )


def _step_rows(rank, start, n):
    rows = []
    for s in range(start, start + n):
        # deterministic variation so the window/diagnosis is non-trivial
        base = 50.0 + (s % 7) * 0.5 + (rank % 5) * 0.3
        rows.append({
            "step": s,
            "timestamp": float(s),
            "clock": "device",
            "events": {
                T.STEP_TIME: {"cpu_ms": base, "device_ms": base, "count": 1},
                T.COMPUTE_TIME: {
                    "cpu_ms": 1.0, "device_ms": base * 0.8, "count": 1,
                },
                T.DATALOADER_NEXT: {
                    "cpu_ms": base * 0.1, "device_ms": 0.0, "count": 1,
                },
            },
        })
    return rows


def _mem_rows(start, n):
    return [
        {"step": s, "timestamp": float(s), "device_id": 0, "device_kind": "tpu",
         "current_bytes": 1 << 30, "peak_bytes": (1 << 30) + s,
         "step_peak_bytes": 1 << 30, "limit_bytes": 16 << 30, "backend": "fake"}
        for s in range(start, start + n)
    ]


def _seed_db(db, ranks, steps):
    w = SQLiteWriter(db)
    w.start()
    for rank in range(ranks):
        ident = _ident(rank, ranks)
        w.ingest(build_telemetry_envelope(
            "step_time",
            {
                "step_time": _step_rows(rank, 1, steps),
                "model_stats": [{
                    "timestamp": 1.0, "flops_per_step": 1.2e12,
                    "flops_source": "estimated", "device_kind": "tpu",
                    "peak_flops": 1.97e14, "device_count": 1,
                    "tokens_per_step": 4096.0,
                }],
            },
            ident,
        ))
        w.ingest(build_telemetry_envelope(
            "step_memory", {"step_memory": _mem_rows(1, steps)}, ident,
        ))
        w.ingest(build_telemetry_envelope(
            "process",
            {"process": [
                {"timestamp": float(i), "cpu_pct": 40.0, "rss_bytes": 2 << 30,
                 "vms_bytes": 4 << 30, "num_threads": 8}
                for i in range(2)
            ]},
            ident,
        ))
        if rank % RANKS_PER_NODE == 0:
            w.ingest(build_telemetry_envelope(
                "system",
                {"system": [
                    {"timestamp": float(i), "cpu_pct": 30.0,
                     "memory_used_bytes": 8 << 30,
                     "memory_total_bytes": 32 << 30, "memory_pct": 25.0}
                    for i in range(4)
                ],
                 "system_device": [
                    {"timestamp": float(i), "device_id": 0,
                     "device_kind": "tpu", "memory_used_bytes": 4 << 30,
                     "memory_peak_bytes": 5 << 30,
                     "memory_total_bytes": 16 << 30}
                    for i in range(4)
                ]},
                ident,
            ))
    w.ingest(build_telemetry_envelope(
        "stdout_stderr",
        {"stdout_stderr": [
            {"timestamp": float(i), "stream": "stdout", "line": f"log {i}"}
            for i in range(64)
        ]},
        _ident(0, ranks),
    ))
    assert w.force_flush()
    return w


# -- vendored pre-change read path -----------------------------------------
# The seed loaders (commit 27c2b0c): DISTINCT global_rank scan + one
# query per rank + per-tick json decode of every blob.  Kept verbatim so
# the baseline stays honest after the shipped loaders were collapsed.


def _seed_load_step_time_rows(db_path, max_steps_per_rank):
    out = {}
    with sqlite3.connect(f"file:{db_path}?mode=ro", uri=True) as conn:
        conn.row_factory = sqlite3.Row
        ranks = [
            r[0]
            for r in conn.execute(
                "SELECT DISTINCT global_rank FROM step_time_samples"
            )
        ]
        for rank in ranks:
            rows = conn.execute(
                "SELECT step, timestamp, clock, late_markers, events_json "
                "FROM step_time_samples WHERE global_rank=? "
                "ORDER BY step DESC LIMIT ?",
                (rank, max_steps_per_rank),
            ).fetchall()
            decoded = []
            for r in reversed(rows):
                try:
                    events = json.loads(r["events_json"] or "{}")
                except ValueError:
                    events = {}
                decoded.append({
                    "step": r["step"],
                    "timestamp": r["timestamp"],
                    "clock": r["clock"],
                    "late_markers": r["late_markers"],
                    "events": events,
                })
            out[int(rank)] = decoded
    return out


def _seed_load_step_memory_rows(db_path, max_rows_per_rank):
    out = {}
    with sqlite3.connect(f"file:{db_path}?mode=ro", uri=True) as conn:
        conn.row_factory = sqlite3.Row
        ranks = [
            r[0]
            for r in conn.execute(
                "SELECT DISTINCT global_rank FROM step_memory_samples"
            )
        ]
        for rank in ranks:
            rows = conn.execute(
                "SELECT step, timestamp, device_id, device_kind, current_bytes,"
                " peak_bytes, step_peak_bytes, limit_bytes FROM"
                " step_memory_samples WHERE global_rank=?"
                " ORDER BY step DESC LIMIT ?",
                (rank, max_rows_per_rank),
            ).fetchall()
            out[int(rank)] = [dict(r) for r in reversed(rows)]
    return out


def _baseline_tick(db):
    """The pre-change ``LiveComputer.payload()`` body: fresh connection
    per loader, full re-read + re-decode of every domain, second window
    build inside ``diagnose_rank_rows`` (max_steps=200)."""
    out = {"views": {}}
    out["topology"] = loaders.load_topology(db)
    world = int(out["topology"].get("world_size") or 0)
    nodes = int(out["topology"].get("nodes") or 0)
    rank_rows = _seed_load_step_time_rows(db, WINDOW)
    window = build_step_time_window(rank_rows, max_steps=WINDOW)
    latest = max(
        (row.get("timestamp") or 0.0
         for rows in rank_rows.values() for row in rows[-1:]),
        default=None,
    )
    model_stats = loaders.load_model_stats(db)
    out["views"]["step_time"] = V.build_step_time_view(
        window, world_size=world, latest_ts=latest, model_stats=model_stats,
    )
    out["step_time"] = {
        "window": window,
        "diagnosis": diagnose_rank_rows(rank_rows, mode="live"),
    }
    mem_rows = _seed_load_step_memory_rows(db, WINDOW * 4)
    out["views"]["memory"] = V.build_memory_view(mem_rows)
    out["step_memory"] = mem_rows
    out["step_memory_diagnosis"] = diagnose_memory(mem_rows) if mem_rows else None
    host, devices = loaders.load_system_rows(db, max_rows=300)
    out["views"]["system"] = V.build_system_view(host, devices, expected_nodes=nodes)
    out["system"] = {"host": host, "devices": devices}
    out["system_diagnosis"] = (
        diagnose_system(host, devices) if host or devices else None
    )
    procs, pdevs = loaders.load_process_rows(db, max_rows=300)
    out["views"]["process"] = V.build_process_view(procs)
    out["process"] = {"procs": procs, "devices": pdevs}
    out["process_diagnosis"] = (
        diagnose_process(procs, pdevs) if procs or pdevs else None
    )
    out["stdout"] = loaders.load_stdout_tail(db)
    return out


def _kinds(diag):
    return [] if diag is None else sorted(i.kind for i in diag.issues)


def _golden_compare(inc, base):
    """Incremental payload must match the pre-change path: same window,
    same diagnosis verdicts, same per-domain row data.

    window_to_plain canonicalizes both sides: the incremental path now
    returns a ColumnarStepTimeWindow whose dataclass __eq__ would reject
    the scalar window on class identity alone."""
    from traceml_tpu.utils.columnar import window_to_plain

    assert window_to_plain(inc["step_time"]["window"]) == window_to_plain(
        base["step_time"]["window"]
    )
    assert _kinds(inc["step_time"]["diagnosis"]) == _kinds(
        base["step_time"]["diagnosis"]
    )
    assert inc["step_memory"] == base["step_memory"]
    assert _kinds(inc["step_memory_diagnosis"]) == _kinds(
        base["step_memory_diagnosis"]
    )
    assert inc["system"] == base["system"]
    assert inc["process"] == base["process"]
    assert inc["stdout"] == base["stdout"]
    assert inc["topology"] == base["topology"]


# -- timing ----------------------------------------------------------------


def _best_of(fn, reps):
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best * 1000.0


def _run_case(tmp_path, ranks, steps):
    db = tmp_path / f"bench_{ranks}.sqlite"
    w = _seed_db(db, ranks, steps)

    full_ms = _best_of(lambda: _baseline_tick(db), 3)
    base = _baseline_tick(db)

    computer = LiveComputer(db, window_steps=WINDOW)
    t0 = time.perf_counter()
    inc = computer.payload()
    cold_ms = (time.perf_counter() - t0) * 1000.0
    _golden_compare(inc, base)

    # warm idle tick: no commits since the last refresh
    noop = [
        _best_of(computer.payload, 1)
        for _ in range(50)
    ]
    noop_ms = statistics.median(noop)

    # warm incremental tick: one new step per rank lands, then one tick
    incr = []
    next_step = steps + 1
    for _ in range(5):
        for rank in range(ranks):
            w.ingest(build_telemetry_envelope(
                "step_time",
                {"step_time": _step_rows(rank, next_step, 1)},
                _ident(rank, ranks),
            ))
        assert w.force_flush()
        t0 = time.perf_counter()
        p = computer.payload()
        incr.append((time.perf_counter() - t0) * 1000.0)
        assert p["step_time"]["window"].steps[-1] == next_step
        next_step += 1
    incr_ms = statistics.median(incr)

    extra = {"ranks": ranks, "steps": steps, "window": WINDOW}
    bench_common.emit(BENCH, "full_reload_tick", full_ms, "ms", **extra)
    bench_common.emit(BENCH, "cold_tick", cold_ms, "ms", **extra)
    bench_common.emit(BENCH, "warm_noop_tick", noop_ms, "ms", **extra)
    bench_common.emit(BENCH, "warm_incr_tick", incr_ms, "ms", **extra)
    bench_common.emit(
        BENCH, "speedup_noop", full_ms / max(noop_ms, 1e-6), "x", **extra
    )
    bench_common.emit(
        BENCH, "speedup_incr", full_ms / max(incr_ms, 1e-6), "x", **extra
    )

    w.finalize()
    computer.close()
    return full_ms, noop_ms, incr_ms


@pytest.mark.parametrize("ranks", [64, 256])
def test_live_tick_bench(tmp_path, ranks):
    full_ms, noop_ms, incr_ms = _run_case(tmp_path, ranks, WINDOW)
    if ranks == 256:
        # the acceptance floors (ISSUE: perf_opt PR 2)
        assert full_ms / noop_ms >= 10.0, (full_ms, noop_ms)
        assert full_ms / incr_ms >= 3.0, (full_ms, incr_ms)


if __name__ == "__main__":
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        for ranks in (64, 256):
            _run_case(Path(d), ranks, WINDOW)
