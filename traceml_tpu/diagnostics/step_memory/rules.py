"""Step-memory rules
(reference: src/traceml_ai/diagnostics/step_memory/rules.py:60-196,
trend.py:31-376).

Context shape: per-rank per-device step series of
``{step, current_bytes, step_peak_bytes, limit_bytes}``.
"""

from __future__ import annotations

import dataclasses
import statistics
from typing import Any, Dict, List, Mapping, Optional, Sequence

from traceml_tpu.analytics.trends.core import compute_trend_evidence
from traceml_tpu.diagnostics.common import (
    SEVERITY_CRITICAL,
    SEVERITY_WARNING,
    DiagnosticIssue,
)
from traceml_tpu.diagnostics.step_memory.policy import DEFAULT_POLICY, StepMemoryPolicy
from traceml_tpu.utils.formatting import fmt_bytes


@dataclasses.dataclass
class MemoryContext:
    # (rank, device_id) → ordered step rows
    series: Dict[tuple, List[Dict[str, Any]]]
    policy: StepMemoryPolicy = DEFAULT_POLICY

    @property
    def ranks(self) -> List[int]:
        return sorted({r for r, _ in self.series})


def build_memory_context(
    rank_rows: Mapping[int, Sequence[Mapping[str, Any]]],
    policy: StepMemoryPolicy = DEFAULT_POLICY,
) -> MemoryContext:
    series: Dict[tuple, List[Dict[str, Any]]] = {}
    for rank, rows in rank_rows.items():
        for row in rows:
            key = (int(rank), int(row.get("device_id", 0)))
            series.setdefault(key, []).append(dict(row))
    for rows in series.values():
        rows.sort(key=lambda r: (r.get("step") or 0))
    return MemoryContext(series=series, policy=policy)


def _latest_pressure(rows: List[Dict[str, Any]]) -> Optional[float]:
    for row in reversed(rows):
        used = row.get("step_peak_bytes") or row.get("current_bytes")
        limit = row.get("limit_bytes")
        if used and limit:
            return float(used) / float(limit)
    return None


class HighPressureRule:
    def evaluate(self, ctx: MemoryContext) -> List[DiagnosticIssue]:
        issues = []
        p = ctx.policy
        for (rank, dev), rows in ctx.series.items():
            pressure = _latest_pressure(rows)
            if pressure is None or pressure < p.pressure_warn:
                continue
            severity = (
                SEVERITY_CRITICAL
                if pressure >= p.pressure_critical
                else SEVERITY_WARNING
            )
            last = rows[-1]
            issues.append(
                DiagnosticIssue(
                    kind="HIGH_MEMORY_PRESSURE",
                    severity=severity,
                    summary=(
                        f"Rank {rank} device {dev} at {pressure * 100:.0f}% of "
                        f"HBM capacity "
                        f"({fmt_bytes(last.get('step_peak_bytes') or last.get('current_bytes'))}"
                        f" / {fmt_bytes(last.get('limit_bytes'))})."
                    ),
                    action=(
                        "Reduce per-chip footprint: smaller microbatch, "
                        "jax.checkpoint/remat, optimizer-state sharding "
                        "(ZeRO-style), bf16 activations, or shard the model "
                        "further."
                    ),
                    metric="memory_pressure",
                    score=pressure,
                    share_pct=pressure,
                    ranks=[rank],
                    evidence={"device_id": dev},
                )
            )
        return issues


class ImbalanceRule:
    def evaluate(self, ctx: MemoryContext) -> List[DiagnosticIssue]:
        p = ctx.policy
        # latest used bytes per rank (max over that rank's devices)
        per_rank: Dict[int, float] = {}
        per_rank_pressure: Dict[int, float] = {}
        for (rank, _dev), rows in ctx.series.items():
            if not rows:
                continue
            last = rows[-1]
            used = last.get("step_peak_bytes") or last.get("current_bytes") or 0
            per_rank[rank] = max(per_rank.get(rank, 0.0), float(used))
            pres = _latest_pressure(rows)
            if pres is not None:
                per_rank_pressure[rank] = max(
                    per_rank_pressure.get(rank, 0.0), pres
                )
        if len(per_rank) < 2:
            return []
        med = statistics.median(per_rank.values())
        if med <= 0:
            return []
        worst_rank = max(per_rank, key=lambda r: per_rank[r])
        skew = (per_rank[worst_rank] - med) / med
        if skew < p.imbalance_warn:
            return []
        # only interesting when somebody is actually under pressure
        if max(per_rank_pressure.values(), default=0.0) < p.imbalance_pressure_gate:
            return []
        severity = (
            SEVERITY_CRITICAL if skew >= p.imbalance_critical else SEVERITY_WARNING
        )
        return [
            DiagnosticIssue(
                kind="MEMORY_IMBALANCE",
                severity=severity,
                summary=(
                    f"Rank {worst_rank} holds {skew * 100:.0f}% more device "
                    f"memory than the median rank "
                    f"({fmt_bytes(per_rank[worst_rank])} vs {fmt_bytes(med)})."
                ),
                action=(
                    "Check sharding balance: uneven parameter/optimizer "
                    "partitions, rank-0-only buffers (eval/logging replicas), "
                    "or padding asymmetries."
                ),
                metric="memory_skew",
                score=skew,
                skew_pct=skew,
                ranks=[worst_rank],
                evidence={"per_rank_bytes": {str(r): v for r, v in per_rank.items()}},
            )
        ]


class CreepRule:
    """CREEP_EARLY / CREEP_CONFIRMED
    (reference heuristics: ≥800 steps, ≥512 MiB delta, ≥6% growth, slope
    gate, weak-recovery check; confirmed at ≥1 GiB)."""

    def evaluate(self, ctx: MemoryContext) -> List[DiagnosticIssue]:
        p = ctx.policy
        issues = []
        for (rank, dev), rows in ctx.series.items():
            if len(rows) < p.creep_min_steps:
                continue
            series = [float(r.get("current_bytes") or 0) for r in rows]
            ev = compute_trend_evidence(series)
            if ev is None:
                continue
            limit = next(
                (r.get("limit_bytes") for r in reversed(rows) if r.get("limit_bytes")),
                None,
            )
            slope_frac = (
                (ev.slope_per_100 / float(limit)) if limit else
                (ev.slope_per_100 / ev.baseline_mean if ev.baseline_mean else 0.0)
            )
            if (
                ev.delta < p.creep_min_delta_bytes
                or ev.growth_pct < p.creep_min_growth_pct
                or slope_frac < p.creep_min_slope_per_100
                or ev.weak_recovery
            ):
                continue
            confirmed = ev.delta >= p.creep_confirmed_delta_bytes and ev.monotonic_band_growth
            issues.append(
                DiagnosticIssue(
                    kind="MEMORY_CREEP_CONFIRMED" if confirmed else "MEMORY_CREEP_EARLY",
                    severity=SEVERITY_CRITICAL if confirmed else SEVERITY_WARNING,
                    summary=(
                        f"Rank {rank} device {dev} memory grew "
                        f"{fmt_bytes(ev.delta)} (+{ev.growth_pct * 100:.1f}%) "
                        f"over {ev.n} steps"
                        + (" — sustained, likely a leak." if confirmed else ".")
                    ),
                    action=(
                        "Hunt Python-side references to device arrays "
                        "(growing metric lists, retained batches), "
                        "check for per-step recompiles creating executables, "
                        "and confirm donated buffers are actually donated."
                    ),
                    metric="memory_creep",
                    score=ev.growth_pct,
                    ranks=[rank],
                    evidence={"device_id": dev, "trend": ev.to_dict()},
                )
            )
        return issues


DEFAULT_RULES = (HighPressureRule(), ImbalanceRule(), CreepRule())
