"""Display drivers (reference: src/traceml_ai/aggregator/display_drivers/)."""

from traceml_tpu.aggregator.display_drivers.base import (  # noqa: F401
    BaseDisplayDriver,
    SummaryDisplayDriver,
)


def resolve_display_driver(mode: str):
    """cli → live Rich display; dashboard → browser server;
    summary/other → no live UI
    (reference: trace_aggregator.py:65 _resolve_display_driver)."""
    if mode == "cli":
        try:
            from traceml_tpu.aggregator.display_drivers.cli import CLIDisplayDriver

            return CLIDisplayDriver()
        except Exception:
            return SummaryDisplayDriver()
    if mode == "dashboard":
        try:
            from traceml_tpu.aggregator.display_drivers.browser import (
                BrowserDisplayDriver,
            )

            return BrowserDisplayDriver()
        except Exception:
            return SummaryDisplayDriver()
    return SummaryDisplayDriver()
