"""``python -m traceml_tpu.analysis`` — same gate as ``traceml lint``,
importable from a bare checkout (the CI lint job runs it without
installing the package).

``--self-time`` is the perf smoke: run the full-package analysis and
fail if it exceeds the budget (default 5s) — the gate must stay cheap
enough to run on every PR.
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m traceml_tpu.analysis",
        description="traceml static analyzer (race/wiring/flags/escape)",
    )
    p.add_argument(
        "--root",
        type=Path,
        default=None,
        help="package root to analyze (default: the installed traceml_tpu)",
    )
    p.add_argument(
        "--pass",
        dest="passes",
        action="append",
        choices=("race", "wiring", "flags", "escape"),
        default=None,
        help="run only this pass (repeatable; default: all four)",
    )
    p.add_argument(
        "--format",
        choices=("text", "json"),
        default="text",
        help="output format (default: text)",
    )
    p.add_argument(
        "--baseline",
        type=Path,
        default=None,
        help="baseline file (default: tracelint_baseline.json at repo root)",
    )
    p.add_argument(
        "--update-baseline",
        action="store_true",
        help="rewrite the baseline from current findings and exit 0",
    )
    p.add_argument(
        "--show-suppressed",
        action="store_true",
        help="include suppressed findings in text output",
    )
    p.add_argument(
        "--self-time",
        nargs="?",
        type=float,
        const=5.0,
        default=None,
        metavar="BUDGET_SEC",
        help=(
            "perf smoke: run the full analysis and fail if it takes "
            "longer than BUDGET_SEC (default 5.0)"
        ),
    )
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from traceml_tpu.analysis.runner import run_lint, run_passes
    from traceml_tpu.analysis.runner import default_package_root

    if args.self_time is not None:
        root = args.root or default_package_root()
        t0 = time.monotonic()
        findings = run_passes(root)
        elapsed = time.monotonic() - t0
        ok = elapsed <= args.self_time
        print(
            f"traceml lint --self-time: {len(findings)} finding(s) in "
            f"{elapsed:.2f}s (budget {args.self_time:.1f}s) — "
            f"{'OK' if ok else 'OVER BUDGET'}"
        )
        return 0 if ok else 1

    return run_lint(
        package_root=args.root,
        passes=args.passes,
        fmt=args.format,
        baseline_path=args.baseline,
        update_baseline=args.update_baseline,
        show_suppressed=args.show_suppressed,
    )


if __name__ == "__main__":
    sys.exit(main())
