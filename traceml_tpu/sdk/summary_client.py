"""Worker-side summary client
(reference: src/traceml_ai/sdk/summary_client.py:56-153).

``final_summary()``: primary-rank-gated file IPC with the aggregator —
return the existing artifact if present, else drop a request file, poll
for the response, read ``final_summary.json``.

``summary()``: flattens the artifact into tracker-friendly
``traceml/...`` scalars (reference: sdk/summary_projection.py:14-102).
"""

from __future__ import annotations

import time
from pathlib import Path
from typing import Any, Dict, Optional

from traceml_tpu.runtime.identity import resolve_runtime_identity
from traceml_tpu.runtime.settings import settings_from_env
from traceml_tpu.sdk import protocol
from traceml_tpu.utils.atomic_io import read_json
from traceml_tpu.utils.error_log import get_error_log


def _session_dir() -> Path:
    return settings_from_env().session_dir


def final_summary(
    timeout: float = 120.0, session_dir: Optional[Path] = None
) -> Optional[Dict[str, Any]]:
    """Request + fetch the final summary dict (None on failure)."""
    try:
        sdir = Path(session_dir) if session_dir else _session_dir()
        identity = resolve_runtime_identity()
        if not identity.is_global_primary:
            return None
        existing = read_json(protocol.get_final_summary_json_path(sdir))
        if existing is not None:
            return existing
        protocol.write_summary_request(sdir, identity.global_rank)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            resp = protocol.read_summary_response(sdir)
            if resp is not None:
                if not resp.get("ok"):
                    get_error_log().warning(
                        f"final summary failed: {resp.get('error')}"
                    )
                    return None
                return read_json(protocol.get_final_summary_json_path(sdir))
            time.sleep(0.25)
        return None
    except Exception as exc:
        get_error_log().warning("final_summary client failed", exc)
        return None


def _flatten(prefix: str, obj: Any, out: Dict[str, Any]) -> None:
    if isinstance(obj, dict):
        for k, v in obj.items():
            _flatten(f"{prefix}/{k}", v, out)
    elif isinstance(obj, (int, float)) and not isinstance(obj, bool):
        out[prefix] = obj


def summary(
    timeout: float = 120.0, session_dir: Optional[Path] = None
) -> Dict[str, Any]:
    """Flat ``{"traceml/...": scalar}`` dict for W&B/MLflow-style loggers."""
    data = final_summary(timeout=timeout, session_dir=session_dir)
    if not data:
        return {}
    out: Dict[str, Any] = {}
    _flatten("traceml", data, out)
    return out
