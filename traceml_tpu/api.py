"""Public API surface (reference: src/traceml_ai/api.py:12-131).

Everything here is lazily resolved through ``traceml_tpu.__getattr__`` so
``import traceml_tpu`` stays free of jax/torch imports.
"""

from __future__ import annotations

from traceml_tpu.sdk.initial import init, start  # noqa: F401
from traceml_tpu.sdk.instrumentation import trace_step, trace_time  # noqa: F401
from traceml_tpu.sdk.step_fn import wrap_step_fn  # noqa: F401
from traceml_tpu.sdk.wrappers import (  # noqa: F401
    wrap_backward,
    wrap_checkpoint,
    wrap_collective,
    wrap_forward,
    wrap_h2d,
    wrap_optimizer,
)
from traceml_tpu.instrumentation.dataloader import wrap_dataloader  # noqa: F401
from traceml_tpu.instrumentation.collectives import (  # noqa: F401
    instrument_collective,
    patch_lax_collectives,
    record_collective,
)
from traceml_tpu.instrumentation.serving import (  # noqa: F401
    instrument_generate,
    record_decode_token,
    record_prefill_end,
    record_prefill_start,
    record_request_enqueued,
    record_request_finished,
)
from traceml_tpu.sdk.summary_client import (  # noqa: F401
    final_summary,
    live_metrics,
    summary,
)
from traceml_tpu.sdk.profile_capture import (  # noqa: F401
    request_profile_and_wait as request_profile,
)


def set_step_flops(flops: float, device_kind=None, device_count=None) -> None:
    """Declare the model FLOPs of ONE training step (fwd+bwd+optimizer)
    — the MFU numerator.  Overrides wrap_step_fn's cost-analysis
    estimate; use for grad-accum loops (sum the micro-batch dispatches)
    or models traced outside wrap_step_fn.

    Declare the GLOBAL program's FLOPs: the MFU denominator becomes
    ``device_count`` × chip peak.  ``device_count`` defaults to
    ``jax.device_count()`` — the GLOBAL chip count, because
    cost-analysis FLOPs describe the whole pre-partition program; in
    multi-process SPMD every rank declares the same global FLOPs, so
    judging against only local chips would inflate MFU by the process
    count (advisor r3)."""
    from traceml_tpu.sdk.state import get_state

    st = get_state()
    st.flops_per_step = float(flops)
    st.flops_source = "manual"
    if device_kind is not None:
        st.flops_device_kind = str(device_kind)
    elif st.flops_device_kind is None:
        try:
            import jax

            st.flops_device_kind = str(jax.devices()[0].device_kind)
        except Exception:
            pass
    if device_count is not None:
        st.flops_device_count = int(device_count)
    elif st.flops_device_count is None:
        try:
            import jax

            st.flops_device_count = int(jax.device_count())
        except Exception:
            pass


def set_step_tokens(tokens: float) -> None:
    """Declare the tokens consumed by ONE training step (global batch ×
    sequence length) — the tokens/s numerator.  Optional and
    independent of ``set_step_flops``; with it declared, the step-time
    efficiency block reports ``tokens_per_sec_median`` alongside
    achieved TFLOP/s and MFU."""
    from traceml_tpu.sdk.state import get_state

    get_state().tokens_per_step = float(tokens)


def current_step() -> int:
    """The current trace step counter (0 before the first step)."""
    from traceml_tpu.sdk.state import get_state

    return get_state().current_step


def enable_ici_stats(mesh=None, *, every_n_steps: int = 10, window_steps: int = 120):
    """Opt-in: all-gather per-chip stat vectors over the mesh every N
    steps and keep a local cross-rank window for diagnosis — the
    ICI-path rank source that bypasses TCP (SURVEY §2.5).

    Returns the installed :class:`~traceml_tpu.parallel.ici_telemetry.
    IciTelemetryHook`; call ``hook.diagnose()`` for a straggler verdict
    from the gathered matrices, ``hook.uninstall()`` to detach.
    """
    from traceml_tpu.parallel.ici_telemetry import IciTelemetryHook

    return IciTelemetryHook(
        mesh, every_n_steps=every_n_steps, window_steps=window_steps
    ).install()
