"""Shared identity-column helpers for projection writers
(reference: aggregator/sqlite_writers/step_time.py:131-419 shows the
stable-identity-columns + payload-json pattern).

Writers consume tables through ``TelemetryEnvelope.column_view`` — a
:class:`~traceml_tpu.telemetry.envelope.ColumnView` whose ``ints`` /
``floats`` / ``strs`` accessors mirror the row-dict coercions below
(``fnum``/``inum`` are kept for row-oriented callers), so schema-v2
columnar envelopes build executemany parameter tuples without ever
materializing per-row dicts."""

from __future__ import annotations

import json
from typing import Any, Dict, Tuple

from traceml_tpu.telemetry.envelope import TelemetryEnvelope

IDENTITY_COLS = (
    "session_id",
    "global_rank",
    "local_rank",
    "world_size",
    "local_world_size",
    "node_rank",
    "hostname",
    "pid",
)

IDENTITY_SCHEMA = """
    session_id TEXT,
    global_rank INTEGER,
    local_rank INTEGER,
    world_size INTEGER,
    local_world_size INTEGER,
    node_rank INTEGER,
    hostname TEXT,
    pid INTEGER
"""


def identity_tuple(env: TelemetryEnvelope) -> Tuple[Any, ...]:
    m = env.meta
    return (
        str(m.get("session_id", "unknown")),
        int(m.get("global_rank", m.get("rank", 0))),
        int(m.get("local_rank", 0)),
        int(m.get("world_size", 1)),
        int(m.get("local_world_size", 1)),
        int(m.get("node_rank", 0)),
        str(m.get("hostname", "")),
        int(m.get("pid", 0)),
    )


def dumps(obj: Any) -> str:
    try:
        return json.dumps(obj)
    except (TypeError, ValueError):
        return json.dumps(str(obj))


def fnum(row: Dict[str, Any], key: str):
    v = row.get(key)
    if v is None:
        return None
    try:
        return float(v)
    except (TypeError, ValueError):
        return None


def inum(row: Dict[str, Any], key: str):
    v = row.get(key)
    if v is None:
        return None
    try:
        return int(v)
    except (TypeError, ValueError):
        return None
