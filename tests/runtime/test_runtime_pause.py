import time
import pytest


def test_runtime_pause_resume(tmp_path):
    from traceml_tpu.runtime.runtime import TraceMLRuntime
    from traceml_tpu.runtime.identity import RuntimeIdentity
    from traceml_tpu.runtime.settings import AggregatorEndpoint, TraceMLSettings

    rt = TraceMLRuntime(
        TraceMLSettings(session_id="p", logs_dir=tmp_path, mode="summary",
                        aggregator=AggregatorEndpoint(port=1),
                        sampler_interval_sec=0.05),
        RuntimeIdentity(global_rank=0),
    )
    rt.start()
    try:
        time.sleep(0.3)
        step_sampler = next(s for s in rt.samplers if s.name == "system")
        # pause FIRST (it blocks on any in-flight tick), then read the
        # baseline — reading before pausing races the 50ms tick thread
        rt.pause()
        before = step_sampler.db.append_count("system")
        time.sleep(0.4)
        paused_count = step_sampler.db.append_count("system")
        assert paused_count == before  # no sampling while paused
        rt.resume()
        time.sleep(0.4)
        assert step_sampler.db.append_count("system") > paused_count
    finally:
        rt.stop()
