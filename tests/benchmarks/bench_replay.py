"""Durable-replay path micro-bench: spool append, reconnect drain,
crash-recovery scan, and the healthy-path overhead of the durable
wrapper (transport/spool.py; docs/developer_guide/fault-tolerance.md).

Golden first, like bench_rank_producer: the identical pre-encoded
envelope stream is spooled, crash-recovered (a fresh ``ReplaySpool``
over the same directory), and replayed — and every decoded replayed
envelope must equal its original, in order, before any timing is
reported.  A replay path that is fast but reorders or re-encodes is
worthless.

Timed regimes (min over repeats, fresh spool dir each):

* **append** — spooling N already-encoded envelopes (the link-down hot
  path: the publisher tick must not stall while the aggregator is gone);
* **drain** — ``DurableSender.replay()`` of N spooled frames through a
  sink client: raw-body splice via ``pack_array_header`` in groups of
  64, zero re-encode.  ``replay_vs_reencode`` compares that splice
  against ``encode_batch`` re-encoding the same payload objects — the
  whole point of spooling post-encode bytes;
* **recovery** — ``ReplaySpool.__init__`` over an existing multi-segment
  spool (the restarted rank's header-walk scan, no body decode);
* **healthy overhead** — ``DurableSender.send`` with an empty spool vs
  the bare client: the per-batch cost of the pending check + unacked
  ring, which is the price every fault-free run pays.

Pytest floors are conservative CI gates; acceptance numbers come from
``python tests/benchmarks/bench_replay.py`` (BENCH_LOCAL records).
"""

import json
import shutil
import sys
import time
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).parent))
sys.path.insert(1, str(Path(__file__).parent.parent.parent))
import bench_common  # noqa: E402

from traceml_tpu.transport.spool import DurableSender, ReplaySpool  # noqa: E402
from traceml_tpu.utils import msgpack_codec  # noqa: E402

pytestmark = [
    pytest.mark.slow,
    pytest.mark.skipif(
        msgpack_codec.preencode({}).raw is None,
        reason="JSON-fallback host: no raw bodies to spool",
    ),
]

N_ENVELOPES = 20_000
REPEATS = 3
HEALTHY_BATCHES = 2_000
BATCH = 8


def _payload(seq):
    return {
        "meta": {
            "seq": seq,
            "session_id": "bench",
            "sampler": "step_time",
            "schema": 2,
            "global_rank": 0,
            "timestamp": 1700000000.0 + seq * 0.015,
        },
        "columns": {
            "step_time": {
                "step": [seq],
                "timestamp": [1700000000.0 + seq * 0.015],
                "clock": ["device"],
                "events": [
                    {"step_time": {"cpu_ms": 15.2, "device_ms": 14.8,
                                   "count": 1}}
                ],
            }
        },
    }


def _encoded_stream(n):
    return [msgpack_codec.preencode(_payload(seq)) for seq in range(n)]


class _SinkClient:
    """Counts bytes; replay groups are kept for the golden decode."""

    __slots__ = ("bodies", "batches", "keep")

    def __init__(self, keep=False):
        self.bodies = []
        self.batches = 0
        self.keep = keep

    def send_batch(self, payloads):
        self.batches += 1
        return True

    def send_encoded_body(self, body):
        if self.keep:
            self.bodies.append(bytes(body))
        else:
            self.bodies.append(len(body))
        return True


# -- golden --------------------------------------------------------------


def _golden(tmp):
    stream = _encoded_stream(500)
    spool = ReplaySpool(tmp / "golden", segment_bytes=64 * 1024)
    for enc in stream:
        assert spool.append(enc.obj["meta"]["seq"], enc.raw)
    spool.close()

    # crash-recover: a FRESH spool over the same directory must replay
    # the identical stream (this is the restarted-rank path)
    recovered = ReplaySpool(tmp / "golden", segment_bytes=64 * 1024)
    assert recovered.torn_tails == 0
    client = _SinkClient(keep=True)
    sender = DurableSender(client, recovered)
    assert sender.replay()
    got = []
    for body in client.bodies:
        decoded = msgpack_codec.decode(body)
        assert isinstance(decoded, list)
        got.extend(decoded)
    assert len(got) == len(stream), (len(got), len(stream))
    for enc, out in zip(stream, got):
        assert out == enc.obj
    sender.close()
    return len(got)


# -- timed regimes -------------------------------------------------------


def _time_append(tmp, stream):
    spool = ReplaySpool(tmp, segment_bytes=4 * 1024 * 1024)
    pairs = [(enc.obj["meta"]["seq"], enc.raw) for enc in stream]
    t0 = time.perf_counter()
    for seq, raw in pairs:
        spool.append(seq, raw)
    elapsed = time.perf_counter() - t0
    spool.close()
    return elapsed


def _time_drain(tmp, stream):
    spool = ReplaySpool(tmp, segment_bytes=4 * 1024 * 1024)
    for enc in stream:
        spool.append(enc.obj["meta"]["seq"], enc.raw)
    sender = DurableSender(_SinkClient(), spool)
    t0 = time.perf_counter()
    assert sender.replay()
    elapsed = time.perf_counter() - t0
    sender.close()
    return elapsed


def _time_recovery(tmp, stream):
    spool = ReplaySpool(tmp, segment_bytes=256 * 1024)
    for enc in stream:
        spool.append(enc.obj["meta"]["seq"], enc.raw)
    spool.close()
    t0 = time.perf_counter()
    recovered = ReplaySpool(tmp, segment_bytes=256 * 1024)
    elapsed = time.perf_counter() - t0
    assert recovered.pending_frames() == len(stream)
    recovered.close()
    return elapsed


def _time_reencode(stream):
    objs = [enc.obj for enc in stream]
    t0 = time.perf_counter()
    for i in range(0, len(objs), 64):
        msgpack_codec.encode_batch(objs[i : i + 64])
    return time.perf_counter() - t0


def _time_healthy(tmp, durable):
    stream = _encoded_stream(HEALTHY_BATCHES * BATCH)
    batches = [
        stream[i : i + BATCH] for i in range(0, len(stream), BATCH)
    ]
    client = _SinkClient()
    if durable:
        sender = DurableSender(client, ReplaySpool(tmp))
        send = sender.send
    else:
        send = client.send_batch
    t0 = time.perf_counter()
    for batch in batches:
        send(batch)
    elapsed = time.perf_counter() - t0
    if durable:
        sender.close()
    return elapsed


def _best(fn, tmp, tag, *args):
    times = []
    for r in range(REPEATS):
        d = tmp / f"{tag}_{r}"
        times.append(fn(d, *args))
        shutil.rmtree(d, ignore_errors=True)
    return min(times)


def _run_case(tmp):
    golden_n = _golden(tmp)
    bench_common.emit("replay", "golden_envelopes", golden_n, "envelopes")

    stream = _encoded_stream(N_ENVELOPES)
    raw_mb = sum(len(e.raw) for e in stream) / 1e6

    append_s = _best(_time_append, tmp, "append", stream)
    drain_s = _best(_time_drain, tmp, "drain", stream)
    recovery_s = _best(_time_recovery, tmp, "recover", stream)
    reencode_s = min(_time_reencode(stream) for _ in range(REPEATS))
    bare_s = _best(lambda d: _time_healthy(d, False), tmp, "bare")
    durable_s = _best(lambda d: _time_healthy(d, True), tmp, "durable")

    r = {
        "append_us_per_envelope": append_s / N_ENVELOPES * 1e6,
        "append_mb_s": raw_mb / append_s,
        "drain_us_per_envelope": drain_s / N_ENVELOPES * 1e6,
        "drain_envelopes_per_s": N_ENVELOPES / drain_s,
        "recovery_scan_ms": recovery_s * 1e3,
        "replay_vs_reencode_speedup": reencode_s / drain_s,
        "healthy_bare_us_per_batch": bare_s / HEALTHY_BATCHES * 1e6,
        "healthy_durable_us_per_batch": durable_s / HEALTHY_BATCHES * 1e6,
        "healthy_overhead_us_per_batch": (durable_s - bare_s)
        / HEALTHY_BATCHES * 1e6,
    }
    units = {
        "append_mb_s": "MB/s",
        "drain_envelopes_per_s": "envelopes/s",
        "recovery_scan_ms": "ms",
        "replay_vs_reencode_speedup": "x",
    }
    for metric, value in r.items():
        bench_common.emit(
            "replay", metric, value, units.get(metric, "us"),
            envelopes=N_ENVELOPES,
        )
    return r


def test_replay_bench(tmp_path):
    r = _run_case(tmp_path)
    # conservative CI floors — acceptance numbers live in BENCH_LOCAL
    assert r["drain_envelopes_per_s"] > 20_000, r
    assert r["append_us_per_envelope"] < 50, r
    assert r["recovery_scan_ms"] < 500, r
    # the raw-splice replay must beat re-encoding the same objects —
    # that is the reason the spool stores post-encode bytes
    assert r["replay_vs_reencode_speedup"] > 1.0, r
    # fault-free runs pay only the pending check + unacked ring
    assert r["healthy_overhead_us_per_batch"] < 100, r


if __name__ == "__main__":
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        results = _run_case(Path(td))
    print(json.dumps(results, indent=2, sort_keys=True))
