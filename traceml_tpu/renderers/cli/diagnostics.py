"""Composed cross-domain diagnostics card
(reference: renderers/model_diagnostics/renderer.py:94 — the single place
the live view lists findings from every domain)."""

from __future__ import annotations

from typing import Any, Dict

from rich.panel import Panel
from rich.text import Text

_SEV_STYLE = {"critical": "bold red", "warning": "yellow", "info": "cyan"}


def diagnostics_panel(payload: Dict[str, Any]) -> Panel:
    from traceml_tpu.diagnostics.model_diagnostics import compose

    results = {
        "step_time": (payload.get("step_time") or {}).get("diagnosis"),
        "step_memory": payload.get("step_memory_diagnosis"),
        "system": payload.get("system_diagnosis"),
        "process": payload.get("process_diagnosis"),
    }
    try:
        composed = compose(results)
    except Exception:
        return Panel(Text("—", style="dim"), title="diagnostics")
    if not composed.issues:
        return Panel(
            Text("no active findings", style="dim green"), title="diagnostics"
        )
    from traceml_tpu.diagnostics.common import confidence_label

    text = Text()
    for issue in composed.issues[:6]:
        domain = issue.evidence.get("domain", "?")
        text.append(
            f"[{issue.severity:>8}] {domain}/{issue.kind}: ",
            style=_SEV_STYLE.get(issue.severity, "white"),
        )
        text.append(issue.summary)
        label = confidence_label(getattr(issue, "confidence", None))
        if label:
            text.append(f"  ({label} confidence)", style="dim")
        text.append("\n")
    return Panel(text, title="diagnostics")
