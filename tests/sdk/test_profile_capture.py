"""On-demand XLA profiler capture (sdk/profile_capture.py).

The operator drops ``control/profile_request.json``; the per-rank
service — driven by step-flush callbacks on the training thread —
brackets the next N steps with the XLA profiler and answers via
``control/profile_response.json``.  No reference counterpart (TPU-first
capability).
"""

import json
import os
import time
from pathlib import Path

import pytest

from traceml_tpu.sdk.profile_capture import (
    ProfileCaptureService,
    profile_request_path,
    profile_response_path,
    read_profile_response,
    write_profile_request,
)


def _drive(svc, steps):
    for s in range(steps):
        svc.on_step_flushed(s)


def test_idle_without_request(tmp_path):
    svc = ProfileCaptureService(tmp_path, rank=0, check_every=1)
    _drive(svc, 20)
    assert not profile_response_path(tmp_path).exists()


def test_capture_cycle_real_profiler(tmp_path):
    """Full cycle against the real jax.profiler on CPU: request → N
    traced steps → response + trace artifacts on disk."""
    import jax
    import jax.numpy as jnp

    svc = ProfileCaptureService(tmp_path, rank=0, check_every=1)
    ts = write_profile_request(tmp_path, steps=3)
    f = jax.jit(lambda a: a @ a / 16.0)
    x = jnp.ones((16, 16))
    # the hook starts the trace at a flush edge; subsequent flushes
    # count down while real device work happens in between
    for s in range(8):
        x = f(x)
        jax.block_until_ready(x)
        svc.on_step_flushed(s)
    resp = read_profile_response(tmp_path, for_request=ts)
    assert resp is not None and resp["ok"], resp
    trace_root = Path(resp["trace_dir"])
    assert trace_root.is_dir()
    rank_dir = trace_root / "rank_0"
    produced = [str(p) for p in rank_dir.rglob("*") if p.is_file()]
    assert produced, "profiler produced no artifacts"


def test_rank_filter(tmp_path):
    svc = ProfileCaptureService(tmp_path, rank=2, check_every=1)
    write_profile_request(tmp_path, steps=2, ranks=[0, 1])
    _drive(svc, 10)
    # rank 2 is excluded: no capture, no response
    assert not profile_response_path(tmp_path).exists()
    assert not (tmp_path / "profiles").exists()


def test_non_primary_rank_stays_silent_on_response(tmp_path, monkeypatch):
    """Both ranks capture, only the primary writes the response file."""
    calls = []

    class _FakeProfiler:
        def start_trace(self, d):
            calls.append(("start", d))

        def stop_trace(self):
            calls.append(("stop",))

    import jax

    monkeypatch.setattr(jax, "profiler", _FakeProfiler())
    # services exist BEFORE the request (a request predating the service
    # is treated as stale — see test_stale_request_not_replayed)
    svc1 = ProfileCaptureService(tmp_path, rank=1, check_every=1)
    svc0 = ProfileCaptureService(tmp_path, rank=0, check_every=1)
    time.sleep(0.02)
    write_profile_request(tmp_path, steps=1, ranks=[0, 1])
    _drive(svc1, 4)
    assert ("stop",) in calls  # rank 1 captured…
    assert not profile_response_path(tmp_path).exists()  # …but didn't respond
    _drive(svc0, 4)
    resp = json.loads(profile_response_path(tmp_path).read_text())
    assert resp["ok"] and resp["rank"] == 0


def test_ranks_share_one_trace_dir(tmp_path, monkeypatch):
    """The stamp derives from the request, not each rank's clock — all
    ranks land under ONE profiles/<stamp>/ even if their flush edges
    straddle a second boundary."""
    starts = []

    class _FakeProfiler:
        def start_trace(self, d):
            starts.append(d)

        def stop_trace(self):
            pass

    import jax

    monkeypatch.setattr(jax, "profiler", _FakeProfiler())
    svcs = [
        ProfileCaptureService(tmp_path, rank=r, check_every=1)
        for r in range(3)
    ]
    time.sleep(0.02)
    write_profile_request(tmp_path, steps=1)
    for svc in svcs:
        _drive(svc, 3)
    parents = {Path(d).parent for d in starts}
    assert len(starts) == 3 and len(parents) == 1, starts


def test_same_request_not_replayed(tmp_path, monkeypatch):
    starts = []

    class _FakeProfiler:
        def start_trace(self, d):
            starts.append(d)

        def stop_trace(self):
            pass

    import jax

    monkeypatch.setattr(jax, "profiler", _FakeProfiler())
    svc = ProfileCaptureService(tmp_path, rank=0, check_every=1)
    write_profile_request(tmp_path, steps=2)
    _drive(svc, 10)
    assert len(starts) == 1  # handled once, mtime remembered
    # a NEW request (newer mtime) re-engages
    time.sleep(0.02)
    write_profile_request(tmp_path, steps=2)
    os.utime(profile_request_path(tmp_path))
    _drive(svc, 10)
    assert len(starts) == 2


def test_answered_request_not_replayed_after_restart(tmp_path, monkeypatch):
    """A request that was already ANSWERED in a previous life of this
    session dir must not replay as an unsolicited capture on restart;
    an unanswered request, by contrast, is honored whenever the job
    starts stepping (the CLI may file it before the first step)."""
    starts = []

    class _FakeProfiler:
        def start_trace(self, d):
            starts.append(d)

        def stop_trace(self):
            pass

    import jax

    monkeypatch.setattr(jax, "profiler", _FakeProfiler())
    # previous life: request + full capture + response
    write_profile_request(tmp_path, steps=1)
    svc_old = ProfileCaptureService(tmp_path, rank=0, check_every=1)
    _drive(svc_old, 3)
    assert len(starts) == 1
    assert profile_response_path(tmp_path).exists()
    # restart: same files on disk, fresh service → no replay
    svc_new = ProfileCaptureService(tmp_path, rank=0, check_every=1)
    _drive(svc_new, 10)
    assert len(starts) == 1


def test_close_finishes_inflight_capture(tmp_path, monkeypatch):
    """Shutdown mid-capture stops the profiler and answers with a
    truncated response instead of leaving the operator to time out."""
    calls = []

    class _FakeProfiler:
        def start_trace(self, d):
            calls.append("start")

        def stop_trace(self):
            calls.append("stop")

    import jax

    monkeypatch.setattr(jax, "profiler", _FakeProfiler())
    svc = ProfileCaptureService(tmp_path, rank=0, check_every=1)
    time.sleep(0.02)
    ts = write_profile_request(tmp_path, steps=100)
    _drive(svc, 5)  # capture starts, far from finishing
    assert calls == ["start"]
    svc.close()
    assert calls == ["start", "stop"]
    resp = read_profile_response(tmp_path, for_request=ts)
    assert resp is not None and resp["ok"] and resp["truncated"]
    svc.close()  # idempotent
    assert calls == ["start", "stop"]


def test_response_matching_is_exact(tmp_path, monkeypatch):
    """A second request must not be satisfied by the first request's
    response (exact requested_at match, no clock-slack window)."""

    class _FakeProfiler:
        def start_trace(self, d):
            pass

        def stop_trace(self):
            pass

    import jax

    monkeypatch.setattr(jax, "profiler", _FakeProfiler())
    svc = ProfileCaptureService(tmp_path, rank=0, check_every=1)
    time.sleep(0.02)
    ts_a = write_profile_request(tmp_path, steps=1)
    _drive(svc, 3)
    assert read_profile_response(tmp_path, for_request=ts_a) is not None
    # request B issued immediately after: A's response must not match
    ts_b = ts_a + 0.5
    assert read_profile_response(tmp_path, for_request=ts_b) is None


def test_broken_profiler_answers_error(tmp_path, monkeypatch):
    class _Broken:
        def start_trace(self, d):
            raise RuntimeError("unsupported runtime")

        def stop_trace(self):  # pragma: no cover
            pass

    import jax

    monkeypatch.setattr(jax, "profiler", _Broken())
    svc = ProfileCaptureService(tmp_path, rank=0, check_every=1)
    ts = write_profile_request(tmp_path, steps=2)
    _drive(svc, 5)
    resp = read_profile_response(tmp_path, for_request=ts)
    assert resp is not None and not resp["ok"]
    assert "unsupported" in (resp["error"] or "")


def test_steps_bounded_against_typo(tmp_path, monkeypatch):
    class _FakeProfiler:
        def start_trace(self, d):
            pass

        def stop_trace(self):
            pass

    import jax

    monkeypatch.setattr(jax, "profiler", _FakeProfiler())
    svc = ProfileCaptureService(tmp_path, rank=0, check_every=1)
    ts = write_profile_request(tmp_path, steps=10_000_000)
    _drive(svc, 250)  # > _MAX_STEPS flushes
    resp = read_profile_response(tmp_path, for_request=ts)
    assert resp is not None and resp["ok"]  # finished within the bound


def test_empty_ranks_rejected_at_write(tmp_path):
    """ranks=[] names no captor — reject up front instead of letting
    the operator's poll time out (ADVICE r2)."""
    with pytest.raises(ValueError):
        write_profile_request(tmp_path, steps=2, ranks=[])
    assert not profile_request_path(tmp_path).exists()


def test_dead_ranks_get_error_response(tmp_path, monkeypatch):
    """A request naming only nonexistent ranks is answered with an
    error by rank 0 (the conventional responder) — never a timeout."""
    svc = ProfileCaptureService(tmp_path, rank=0, check_every=1, world_size=2)
    ts = write_profile_request(tmp_path, steps=2, ranks=[5, 9])
    _drive(svc, 6)
    resp = read_profile_response(tmp_path, for_request=ts)
    assert resp is not None and not resp["ok"]
    assert "no live rank" in resp["error"]
    assert not (tmp_path / "profiles").exists()


def test_dead_primary_live_secondary_still_answers(tmp_path, monkeypatch):
    """ranks=[dead, live]: the live rank captures AND responds (the
    primary is the min of the LIVE set, not of the raw request)."""
    calls = []

    class _FakeProfiler:
        def start_trace(self, d):
            calls.append(("start", d))

        def stop_trace(self):
            calls.append(("stop",))

    import jax

    monkeypatch.setattr(jax, "profiler", _FakeProfiler())
    svc = ProfileCaptureService(tmp_path, rank=1, check_every=1, world_size=2)
    ts = write_profile_request(tmp_path, steps=1, ranks=[1, 7])
    _drive(svc, 4)
    resp = read_profile_response(tmp_path, for_request=ts)
    assert resp is not None and resp["ok"] and resp["rank"] == 1
    assert ("stop",) in calls


def test_response_echoes_clamped_steps(tmp_path, monkeypatch):
    class _FakeProfiler:
        def start_trace(self, d):
            pass

        def stop_trace(self):
            pass

    import jax

    monkeypatch.setattr(jax, "profiler", _FakeProfiler())
    svc = ProfileCaptureService(tmp_path, rank=0, check_every=1)
    ts = write_profile_request(tmp_path, steps=10_000_000)
    _drive(svc, 250)
    resp = read_profile_response(tmp_path, for_request=ts)
    assert resp is not None and resp["ok"]
    assert resp["steps"] == 200  # _MAX_STEPS, not the typo'd request
