"""Native framing extension: build, parity with the Python path, and
fallback behavior."""

import struct

import pytest

from traceml_tpu.native import get_framing

native = get_framing()


@pytest.mark.skipif(native is None, reason="C toolchain unavailable")
class TestNativeFraming:
    def test_pack_and_drain_roundtrip(self):
        bodies = [b"hello", b"", b"x" * 10000, bytes(range(256))]
        blob = native.pack_frames(bodies)
        frames, consumed = native.drain_frames(blob, 0, 1 << 20)
        assert frames == bodies
        assert consumed == len(blob)

    def test_partial_frame_stops_cleanly(self):
        blob = native.pack_frames([b"abc", b"defg"])
        # cut into the middle of the second frame
        cut = blob[: len(blob) - 2]
        frames, consumed = native.drain_frames(cut, 0, 1 << 20)
        assert frames == [b"abc"]
        assert consumed == 4 + 3

    def test_offset_resume(self):
        blob = native.pack_frames([b"one", b"two"])
        frames1, consumed1 = native.drain_frames(blob[: 4 + 3], 0, 1 << 20)
        assert frames1 == [b"one"]
        frames2, consumed2 = native.drain_frames(blob, consumed1, 1 << 20)
        assert frames2 == [b"two"]
        assert consumed2 == len(blob)

    def test_oversized_frame_raises(self):
        bad = struct.pack(">I", 1 << 30) + b"xx"
        with pytest.raises(ValueError):
            native.drain_frames(bad, 0, 1 << 20)

    def test_parity_with_python_framing(self):
        from traceml_tpu.transport.tcp_transport import _LEN

        bodies = [b"a" * n for n in (0, 1, 7, 1000)]
        py_blob = b"".join(_LEN.pack(len(b)) + b for b in bodies)
        assert native.pack_frames(bodies) == py_blob
        frames, consumed = native.drain_frames(py_blob, 0, 1 << 20)
        assert frames == bodies


def test_transport_works_regardless_of_native():
    """The TCP path must work with whatever get_framing() returned."""
    from traceml_tpu.transport.tcp_transport import _ClientBuffer, encode_frame

    buf = _ClientBuffer()
    frame = encode_frame({"k": list(range(50))})
    out = []
    for i in range(0, len(frame), 11):
        out.extend(buf.feed(frame[i : i + 11]))
    assert len(out) == 1


def test_no_native_env_disables(monkeypatch):
    import traceml_tpu.native as nat

    monkeypatch.setenv("TRACEML_NO_NATIVE", "1")
    monkeypatch.setattr(nat, "_cached", {})
    assert nat.get_framing() is None
    assert nat.get_ring() is None
