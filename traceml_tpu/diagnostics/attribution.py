"""Shared topology attribution for diagnostics packs
(docs/developer_guide/topology-attribution.md).

Every pack ends its findings in a flat rank list.  When the session
captured a mesh topology, :func:`attach_attribution` re-reads each
fired issue against the per-rank anomaly values the pack already
computed, asks :func:`traceml_tpu.utils.topology.attribute_ranks`
whether a physical grouping (host / mesh-axis coordinate / DCN side)
explains enough of the cross-rank variance, and when one does:

* sets ``issue.attribution`` to the grouping dict, and
* appends the human phrase to ``issue.summary``
  ("… — all 8 ranks of host 3").

With ``topology=None`` (no mesh captured — every pre-topology session)
the function returns the result UNCHANGED, object-identical, so the
serialized output stays byte-identical to the pre-topology contract
(pinned by tests/utils/test_topology_attribution.py).  Everything here
is fail-open: attribution is garnish, never a reason to lose a
diagnosis.
"""

from __future__ import annotations

import time
from typing import Mapping, Optional

from traceml_tpu.diagnostics.common import DiagnosticResult, STATUS_ISSUE
from traceml_tpu.utils.topology import MeshTopology, attribute_ranks

# lifetime nanoseconds spent attributing: the tick profiler reads the
# delta around each diagnose call to split "attribute" out of the
# "diagnose" stage without threading a profiler through the pack APIs
_ATTR_NS = 0


def attribution_ns_total() -> int:
    return _ATTR_NS


def attach_attribution(
    result: DiagnosticResult,
    topology: Optional[MeshTopology],
    per_rank_values: Optional[Mapping[int, float]],
) -> DiagnosticResult:
    """Annotate fired issues in ``result`` with the best-explaining
    physical grouping; no-op without a topology or per-rank values."""
    global _ATTR_NS
    if topology is None or not per_rank_values:
        return result
    t0 = time.perf_counter_ns()
    try:
        attr = attribute_ranks(per_rank_values, topology)
    except Exception:
        _ATTR_NS += time.perf_counter_ns() - t0
        return result
    _ATTR_NS += time.perf_counter_ns() - t0
    if attr is None:
        return result
    attr_dict = attr.to_dict()
    for issue in result.issues:
        if issue.status != STATUS_ISSUE or not issue.ranks:
            continue
        # only attribute issues whose flagged ranks live inside the
        # outlier group — a grouping that explains the window's variance
        # says nothing about an issue pointing elsewhere
        if not set(issue.ranks) <= set(attr.ranks):
            continue
        issue.attribution = dict(attr_dict)
        if attr.label and attr.label not in issue.summary:
            issue.summary = f"{issue.summary.rstrip()} — {attr.label}."
    return result
