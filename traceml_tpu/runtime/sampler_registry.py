"""Declarative sampler registry
(reference: src/traceml_ai/runtime/sampler_registry.py:20-88).

Each spec declares which profiles/modes it applies to, whether it is
rank-0-per-node only, and whether it drains on recording stop.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Any, Callable, List, Optional

from traceml_tpu.core.registry import Registry
from traceml_tpu.runtime.identity import RuntimeIdentity
from traceml_tpu.runtime.settings import TraceMLSettings
from traceml_tpu.samplers.base_sampler import BaseSampler


@dataclasses.dataclass(frozen=True)
class SamplerSpec:
    key: str
    factory: Callable[..., BaseSampler]
    node_primary_only: bool = False
    cli_mode_only: bool = False
    drain_on_recording_stop: bool = False


SAMPLER_REGISTRY = Registry("samplers")


def register_default_samplers() -> None:
    from traceml_tpu.samplers.collectives_sampler import CollectivesSampler
    from traceml_tpu.samplers.process_sampler import ProcessSampler
    from traceml_tpu.samplers.serving_sampler import ServingSampler
    from traceml_tpu.samplers.step_memory_sampler import StepMemorySampler
    from traceml_tpu.samplers.step_time_sampler import StepTimeSampler
    from traceml_tpu.samplers.system_sampler import SystemSampler

    defaults = [
        SamplerSpec("system", SystemSampler, node_primary_only=True),
        SamplerSpec("process", ProcessSampler),
        SamplerSpec("step_time", StepTimeSampler, drain_on_recording_stop=True),
        SamplerSpec("step_memory", StepMemorySampler, drain_on_recording_stop=True),
        SamplerSpec("collectives", CollectivesSampler, drain_on_recording_stop=True),
        SamplerSpec("serving", ServingSampler, drain_on_recording_stop=True),
    ]
    for spec in defaults:
        if spec.key not in SAMPLER_REGISTRY:
            SAMPLER_REGISTRY.register(spec.key, spec)


def build_samplers(
    settings: TraceMLSettings,
    identity: RuntimeIdentity,
    capture: Any = None,
) -> List[BaseSampler]:
    """Instantiate the samplers this rank should run."""
    register_default_samplers()
    backup_dir: Optional[Path] = None
    if settings.disk_backup:
        backup_dir = settings.rank_dir(identity.global_rank) / "data"

    out: List[BaseSampler] = []
    for key in SAMPLER_REGISTRY.keys():
        spec: SamplerSpec = SAMPLER_REGISTRY.require(key)
        if key == "collectives":
            # TRACEML_COLLECTIVES=0 kill switch — checked per build (not
            # at registration) so tests toggling the env see it live
            from traceml_tpu.instrumentation.collectives import collectives_enabled

            if not collectives_enabled():
                continue
        if key == "serving":
            # TRACEML_SERVING=0 kill switch, same per-build contract
            from traceml_tpu.instrumentation.serving import serving_enabled

            if not serving_enabled():
                continue
        if spec.node_primary_only and not identity.is_node_primary:
            continue
        if spec.cli_mode_only and settings.mode != "cli":
            continue
        kwargs: dict = {"disk_backup_dir": backup_dir}
        if key == "system":
            kwargs["manifest_path"] = (
                settings.session_dir / "system_manifest.json"
            )
        sampler = spec.factory(**kwargs)
        sampler._spec = spec  # type: ignore[attr-defined]
        out.append(sampler)

    # stdout capture is wired explicitly (needs the StreamCapture object)
    if capture is not None and settings.mode in ("cli", "dashboard"):
        from traceml_tpu.samplers.stdout_stderr_sampler import StdoutStderrSampler

        sampler = StdoutStderrSampler(
            capture,
            disk_backup_dir=backup_dir,
            log_path=settings.rank_dir(identity.global_rank) / "stdout.log",
            mirror_to_db=identity.is_global_primary,
        )
        sampler._spec = SamplerSpec("stdout_stderr", StdoutStderrSampler)  # type: ignore[attr-defined]
        out.append(sampler)
    return out
