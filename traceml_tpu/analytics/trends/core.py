"""Generic trend analysis — banded AND windowed evidence
(reference: src/traceml_ai/analytics/trends/core.py:50-146 banded engine;
diagnostics/step_memory/trend.py:31-376 short/long-window heuristics).

Two complementary evidence shapes over one numeric series:

* **banded** (:func:`compute_trend_evidence`) — baseline / mid / recent
  thirds with band means, least-squares slope, monotonicity.  Robust to
  noise, explains *the whole history*.
* **windowed** (:func:`compute_window_trend`) — short-window mean vs
  long-window mean over the TAIL, relative slope, and peak-pullback
  recovery detection.  Explains *what is happening now* and rejects
  sawtooth allocators (grow → GC → grow) via the pullback check.

Cross-series rollup (:func:`summarize_across`) gives worst/median stats
over per-rank evidences so rules can demand "worst rank clears the high
bar AND the median rank clears the low bar" — a cluster-wide creep is a
different finding from one leaking rank.
"""

from __future__ import annotations

import dataclasses
import statistics
from typing import Dict, List, Optional, Sequence


@dataclasses.dataclass
class TrendEvidence:
    n: int
    baseline_mean: float
    mid_mean: float
    recent_mean: float
    delta: float  # recent − baseline
    growth_pct: float  # delta / max(baseline, eps)
    slope_per_100: float  # least-squares slope × 100 samples
    monotonic_band_growth: bool  # baseline ≤ mid ≤ recent
    weak_recovery: bool  # recent dipped below mid (recovering)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _mean(xs: Sequence[float]) -> float:
    return sum(xs) / len(xs) if xs else 0.0


def compute_trend_evidence(series: Sequence[float]) -> Optional[TrendEvidence]:
    xs: List[float] = [float(v) for v in series if v is not None]
    n = len(xs)
    if n < 9:  # need ≥3 per band
        return None
    third = n // 3
    baseline = xs[:third]
    mid = xs[third : 2 * third]
    recent = xs[2 * third :]
    b, m, r = _mean(baseline), _mean(mid), _mean(recent)
    delta = r - b
    growth = delta / b if b > 0 else (0.0 if delta == 0 else float("inf"))
    # least-squares slope per sample, scaled to per-100-samples
    mean_i = (n - 1) / 2.0
    mean_x = _mean(xs)
    num = sum((i - mean_i) * (x - mean_x) for i, x in enumerate(xs))
    den = sum((i - mean_i) ** 2 for i in range(n))
    slope = (num / den if den else 0.0) * 100.0
    return TrendEvidence(
        n=n,
        baseline_mean=b,
        mid_mean=m,
        recent_mean=r,
        delta=delta,
        growth_pct=growth,
        slope_per_100=slope,
        monotonic_band_growth=(b <= m <= r),
        weak_recovery=(r < m),
    )


@dataclasses.dataclass
class WindowTrendEvidence:
    """Short-vs-long tail-window evidence
    (reference concept: diagnostics/step_memory/trend.py:42-55 —
    short_window/long_window means, relative slope, pullback recovery).
    """

    n: int
    short_n: int
    long_n: int
    short_mean: float
    long_mean: float
    trend_pct: float          # short/long − 1 (what is happening NOW)
    slope_pct_per_100: float  # LS slope over the long window / its mean
    peak: float
    pullback_pct: float       # (peak − recent) / peak; sawtooth detector
    recovered: bool           # pullback exceeded the tolerance

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def compute_window_trend(
    series: Sequence[float],
    short_n: int = 100,
    long_n: int = 400,
    pullback_tolerance: float = 0.02,
) -> Optional[WindowTrendEvidence]:
    """Tail-window trend: is the series STILL rising, and has it ever
    meaningfully pulled back from its peak (allocator recovery)?"""
    xs: List[float] = [float(v) for v in series if v is not None]
    n = len(xs)
    if n < max(8, short_n // 4):
        return None
    short = xs[-min(short_n, n):]
    long = xs[-min(long_n, n):]
    s_mean, l_mean = _mean(short), _mean(long)
    trend_pct = (s_mean / l_mean - 1.0) if l_mean > 0 else 0.0
    # least-squares slope over the long window, relative to its mean
    ln = len(long)
    mean_i = (ln - 1) / 2.0
    num = sum((i - mean_i) * (x - l_mean) for i, x in enumerate(long))
    den = sum((i - mean_i) ** 2 for i in range(ln))
    slope = (num / den if den else 0.0) * 100.0
    slope_pct = slope / l_mean if l_mean > 0 else 0.0
    peak = max(xs)
    # compare against the recent MAX, not mean: a monotonically rising
    # series' recent mean always lags its own tip and would read as a
    # false pullback
    recent = max(xs[-max(3, len(short) // 4):])
    pullback = (peak - recent) / peak if peak > 0 else 0.0
    return WindowTrendEvidence(
        n=n,
        short_n=len(short),
        long_n=ln,
        short_mean=s_mean,
        long_mean=l_mean,
        trend_pct=trend_pct,
        slope_pct_per_100=slope_pct,
        peak=peak,
        pullback_pct=pullback,
        recovered=pullback > pullback_tolerance,
    )


@dataclasses.dataclass
class CrossSeriesSummary:
    """Worst/median rollup over per-key scalar evidence values
    (reference concept: worst vs median creep thresholds)."""

    n_series: int
    worst_key: Optional[object]
    worst: float
    median: float

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["worst_key"] = str(self.worst_key)
        return d


def summarize_across(values: Dict[object, float]) -> Optional[CrossSeriesSummary]:
    vals = {k: float(v) for k, v in values.items() if v is not None}
    if not vals:
        return None
    worst_key = max(vals, key=lambda k: vals[k])
    return CrossSeriesSummary(
        n_series=len(vals),
        worst_key=worst_key,
        worst=vals[worst_key],
        median=statistics.median(vals.values()),
    )
