"""Env-flag registry pass (rules ``TLF001``–``TLF004``).

``traceml_tpu/config/flags.py`` is the single declared registry of
every ``TRACEML_*`` environment variable: name, raw-string default, and
a one-line doc.  This pass closes the loop mechanically:

* ``TLF001`` (error) — a ``TRACEML_*`` string literal anywhere else in
  the package that is not declared in the registry.  Covers reads
  *and* writes (the launcher exporting an undeclared name into child
  env is the same contract rot as reading one).
* ``TLF002`` (error) — a declared flag whose doc line is empty.
* ``TLF003`` (warning) — a declared flag referenced nowhere outside
  ``flags.py``: neither by literal name nor through its flag object —
  a dead kill switch nobody can trip.
* ``TLF004`` (error) — an ``os.environ`` / ``os.getenv`` read of a
  ``TRACEML_*`` name outside ``flags.py``: the read bypasses the
  registry's defaults and typed coercion; call
  ``<FLAG>.raw()/enabled()/truthy()/get_*()`` instead.

Flag-object references are tracked through both import styles
(``from traceml_tpu.config.flags import COLLECTIVES`` and
``from traceml_tpu.config import flags; flags.COLLECTIVES``), so
migrated call sites keep their flags "alive" without any literal.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set

from traceml_tpu.analysis.common import (
    Finding,
    SEVERITY_ERROR,
    SEVERITY_WARNING,
    SourceFile,
)

RULE_UNDECLARED = "TLF001"
RULE_UNDOCUMENTED = "TLF002"
RULE_DEAD_FLAG = "TLF003"
RULE_BYPASS_READ = "TLF004"

_FLAG_NAME_RE = re.compile(r"^TRACEML_[A-Z0-9][A-Z0-9_]*$")
_FLAGS_MODULE_SUFFIX = "config/flags.py"


def _is_flags_module(src: SourceFile) -> bool:
    return src.rel.endswith(_FLAGS_MODULE_SUFFIX)


def parse_registry(src: SourceFile) -> Dict[str, Dict[str, object]]:
    """``declare("NAME", default, "doc")`` calls → {name: {doc, line,
    var}} where ``var`` is the module-level name the Flag is bound to."""
    out: Dict[str, Dict[str, object]] = {}
    if src.tree is None:
        return out
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Assign):
            continue
        call = node.value
        if not (
            isinstance(call, ast.Call)
            and isinstance(call.func, ast.Name)
            and call.func.id == "declare"
        ):
            continue
        args = list(call.args)
        for kw in call.keywords:
            if kw.arg == "name":
                args.insert(0, kw.value)
            elif kw.arg == "doc":
                args.append(kw.value)
        if not args or not isinstance(args[0], ast.Constant):
            continue
        name = args[0].value
        if not isinstance(name, str):
            continue
        doc = ""
        if len(args) >= 3 and isinstance(args[2], ast.Constant):
            doc = str(args[2].value or "")
        var = None
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                var = tgt.id
        out[name] = {"doc": doc.strip(), "line": node.lineno, "var": var}
    return out


def _env_read_call_names(node: ast.Call) -> Optional[ast.AST]:
    """For ``os.getenv(X)`` / ``os.environ.get(X)``, the name arg."""
    fn = node.func
    if isinstance(fn, ast.Attribute):
        # os.getenv(X) / environ.get(X) / os.environ.get(X)
        if fn.attr == "getenv":
            if isinstance(fn.value, ast.Name) and fn.value.id == "os":
                return node.args[0] if node.args else None
        if fn.attr in ("get", "pop"):
            recv = fn.value
            if (
                isinstance(recv, ast.Attribute)
                and recv.attr == "environ"
                and isinstance(recv.value, ast.Name)
                and recv.value.id == "os"
            ):
                return node.args[0] if node.args else None
            if isinstance(recv, ast.Name) and recv.id == "environ":
                return node.args[0] if node.args else None
    elif isinstance(fn, ast.Name) and fn.id == "getenv":
        return node.args[0] if node.args else None
    return None


def _env_subscript_name(node: ast.Subscript) -> Optional[ast.AST]:
    recv = node.value
    if (
        isinstance(recv, ast.Attribute)
        and recv.attr == "environ"
        and isinstance(recv.value, ast.Name)
        and recv.value.id == "os"
    ) or (isinstance(recv, ast.Name) and recv.id == "environ"):
        return node.slice
    return None


class _ModuleScan(ast.NodeVisitor):
    """Collects TRACEML_* literals, env-read sites, and flag-object
    references in one module."""

    def __init__(self, flag_vars: Dict[str, str]) -> None:
        # module-level string constants, for resolving ENV_X = "TRACEML_X"
        self.const_names: Dict[str, str] = {}
        self.literals: List[tuple] = []        # (name, line)
        self.env_reads: List[tuple] = []       # (name, line)
        self.flag_vars = flag_vars             # var name → flag name
        self.local_flag_vars: Dict[str, str] = {}  # imported alias → flag
        self.flags_module_aliases: Set[str] = set()
        self.flag_refs: Set[str] = set()       # flag names referenced

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        mod = node.module or ""
        if mod.endswith("config.flags"):
            for alias in node.names:
                flag_name = self.flag_vars.get(alias.name)
                if flag_name is not None:
                    self.local_flag_vars[alias.asname or alias.name] = (
                        flag_name
                    )
        elif mod.endswith("traceml_tpu.config") or mod == "config":
            for alias in node.names:
                if alias.name == "flags":
                    self.flags_module_aliases.add(alias.asname or "flags")
        self.generic_visit(node)

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            if alias.name.endswith("config.flags"):
                self.flags_module_aliases.add(
                    alias.asname or alias.name.split(".")[0]
                )
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        if isinstance(node.value, ast.Constant) and isinstance(
            node.value.value, str
        ):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    self.const_names[tgt.id] = node.value.value
        self.generic_visit(node)

    def visit_Constant(self, node: ast.Constant) -> None:
        if isinstance(node.value, str) and _FLAG_NAME_RE.match(node.value):
            self.literals.append((node.value, node.lineno))

    def visit_Name(self, node: ast.Name) -> None:
        if isinstance(node.ctx, ast.Load):
            flag = self.local_flag_vars.get(node.id)
            if flag is not None:
                self.flag_refs.add(flag)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if (
            isinstance(node.value, ast.Name)
            and node.value.id in self.flags_module_aliases
        ):
            flag = self.flag_vars.get(node.attr)
            if flag is not None:
                self.flag_refs.add(flag)
        self.generic_visit(node)

    def _resolve(self, arg: Optional[ast.AST]) -> Optional[tuple]:
        if arg is None:
            return None
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return (arg.value, arg.lineno)
        if isinstance(arg, ast.Name):
            v = self.const_names.get(arg.id)
            if v is not None:
                return (v, arg.lineno)
        return None

    def visit_Call(self, node: ast.Call) -> None:
        resolved = self._resolve(_env_read_call_names(node))
        if resolved is not None and _FLAG_NAME_RE.match(resolved[0]):
            self.env_reads.append(resolved)
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        if isinstance(node.ctx, ast.Load):
            resolved = self._resolve(_env_subscript_name(node))
            if resolved is not None and _FLAG_NAME_RE.match(resolved[0]):
                self.env_reads.append(resolved)
        self.generic_visit(node)


def run_flags_pass(files: List[SourceFile]) -> List[Finding]:
    findings: List[Finding] = []
    flags_src: Optional[SourceFile] = None
    for src in files:
        if _is_flags_module(src):
            flags_src = src
            break

    registry: Dict[str, Dict[str, object]] = {}
    flag_vars: Dict[str, str] = {}
    if flags_src is not None:
        registry = parse_registry(flags_src)
        flag_vars = {
            str(meta["var"]): name
            for name, meta in registry.items()
            if meta["var"]
        }

    # TLF002: declared but undocumented
    for name, meta in sorted(registry.items()):
        if not meta["doc"]:
            findings.append(
                Finding(
                    rule=RULE_UNDOCUMENTED,
                    severity=SEVERITY_ERROR,
                    path=flags_src.rel,
                    line=int(meta["line"]),
                    message=(
                        f"flag {name} is declared without a doc line — "
                        f"every TRACEML_* variable must say what it does"
                    ),
                    key=f"{RULE_UNDOCUMENTED}:{name}",
                )
            )

    referenced: Set[str] = set()
    for src in files:
        if src.tree is None or _is_flags_module(src):
            continue
        scan = _ModuleScan(flag_vars)
        scan.visit(src.tree)
        referenced.update(scan.flag_refs)
        referenced.update(name for name, _line in scan.literals)

        seen_undeclared: Set[str] = set()
        for name, line in scan.literals:
            if name not in registry and name not in seen_undeclared:
                seen_undeclared.add(name)
                findings.append(
                    Finding(
                        rule=RULE_UNDECLARED,
                        severity=SEVERITY_ERROR,
                        path=src.rel,
                        line=line,
                        message=(
                            f"{name} is not declared in "
                            f"traceml_tpu/config/flags.py — declare it "
                            f"(name, default, doc) before use"
                        ),
                        key=f"{RULE_UNDECLARED}:{src.rel}:{name}",
                    )
                )
        for name, line in scan.env_reads:
            findings.append(
                Finding(
                    rule=RULE_BYPASS_READ,
                    severity=SEVERITY_ERROR,
                    path=src.rel,
                    line=line,
                    message=(
                        f"direct environ read of {name} bypasses the "
                        f"flag registry — use the declared Flag's "
                        f".raw()/.enabled()/.truthy()/.get_*() accessor"
                    ),
                    key=f"{RULE_BYPASS_READ}:{src.rel}:{name}",
                )
            )

    # TLF003: declared but referenced nowhere outside flags.py
    for name, meta in sorted(registry.items()):
        if name not in referenced:
            findings.append(
                Finding(
                    rule=RULE_DEAD_FLAG,
                    severity=SEVERITY_WARNING,
                    path=flags_src.rel,
                    line=int(meta["line"]),
                    message=(
                        f"flag {name} is declared but never referenced "
                        f"outside the registry — dead flag (delete the "
                        f"declaration or wire the feature)"
                    ),
                    key=f"{RULE_DEAD_FLAG}:{name}",
                )
            )
    return findings
