"""Rollup tier goldens (ISSUE r18): bit-exact fold, prune atomicity,
and stitched reads matching an unbounded reference.

The fold runs vectorized (numpy) on the hot prune path with the scalar
Python loop as its golden reference — the ColumnarFallback discipline:
the vectorized sums are cumsum prefix-differences, the exact IEEE
left-fold the scalar loop replays, so equality below is ``==``, not
``approx``.
"""

from __future__ import annotations

import random
import sqlite3

import pytest

from traceml_tpu.aggregator import rollup
from traceml_tpu.aggregator.rollup import (
    DEFAULT_TIERS,
    RollupEngine,
    fold_buckets,
    fold_buckets_reference,
    parse_tiers,
)
from traceml_tpu.aggregator.sqlite_writer import SQLiteWriter
from traceml_tpu.telemetry.envelope import (
    SenderIdentity,
    build_telemetry_envelope,
)


# -- fold goldens ----------------------------------------------------------


def _ragged_arrivals(rng, n):
    """Out-of-order, duplicate-timestamp, cluster-y arrivals — the shape
    retries and multi-rank interleave actually produce."""
    ts, steps, vals = [], [], []
    t = rng.uniform(0, 50)
    for i in range(n):
        if rng.random() < 0.15:
            t -= rng.uniform(0, 5)  # out-of-order replay
        elif rng.random() < 0.2:
            pass  # duplicate timestamp
        else:
            t += rng.expovariate(1.0)
        ts.append(t)
        steps.append(i if rng.random() > 0.1 else None)
        vals.append(rng.uniform(-1e3, 1e6))
    return ts, steps, vals


@pytest.mark.parametrize("width", [10.0, 60.0, 7.5])
def test_fold_bit_exact_on_ragged_arrivals(width):
    rng = random.Random(20260808)
    for _ in range(60):
        ts, steps, vals = _ragged_arrivals(rng, rng.randrange(1, 400))
        fast = fold_buckets(ts, steps, vals, width)
        ref = fold_buckets_reference(ts, steps, vals, width)
        assert len(fast) == len(ref)
        for f, r in zip(fast, ref):
            # tuple-wide equality: bucket_ts, count, sum, min, max,
            # sumsq, step_min, step_max — all bit-exact
            assert f == r


def test_fold_empty_and_singleton():
    assert fold_buckets([], [], [], 10.0) == []
    one = fold_buckets([12.3], [7], [4.5], 10.0)
    assert one == fold_buckets_reference([12.3], [7], [4.5], 10.0)
    assert one[0][0] == 10.0  # bucket floor
    assert one[0][1] == 1
    assert one[0][6] == 7 and one[0][7] == 7


def test_fold_all_none_steps_keeps_value_stats():
    ts = [1.0, 2.0, 11.0]
    vals = [3.0, 4.0, 5.0]
    out = fold_buckets(ts, [None] * 3, vals, 10.0)
    assert [b[1] for b in out] == [2, 1]
    assert all(b[6] is None and b[7] is None for b in out)
    assert out == fold_buckets_reference(ts, [None] * 3, vals, 10.0)


def test_parse_tiers_grammar_and_fallback():
    assert parse_tiers("10:21600,60:1209600") == DEFAULT_TIERS
    assert parse_tiers("5:100") == ((5.0, 100.0),)
    # malformed → defaults, never raises (env flags must not throw)
    for bad in ("", "abc", "10:-5", "0:100", "10:100,junk", None):
        assert parse_tiers(bad) == DEFAULT_TIERS


# -- writer integration: fold-at-prune invariant ---------------------------


def _ident(session, rank):
    return SenderIdentity(
        session_id=session, global_rank=rank, local_rank=rank,
        world_size=2, node_rank=0, hostname="host-0", pid=100 + rank,
    )


def _ingest_steps(w, session, rank, n, base_ms=100.0, dt=0.4):
    for step in range(1, n + 1):
        w.ingest(build_telemetry_envelope(
            "step_time",
            {"step_time": [{
                "step": step, "timestamp": step * dt, "clock": "host",
                "events": {"_traceml_internal:step_time": {
                    "cpu_ms": base_ms + (step % 7) * 0.3, "count": 1,
                }},
            }]},
            identity=_ident(session, rank),
        ))


def test_prune_folds_doomed_rows_every_row_raw_or_rolled(tmp_path):
    db = tmp_path / "t.sqlite"
    w = SQLiteWriter(db, summary_window_rows=20, retention_factor=1.5)
    w.start()
    for rank in (0, 1):
        _ingest_steps(w, "s1", rank, 200)
    w.force_flush()
    assert w.finalize()

    conn = sqlite3.connect(db)
    for rank in (0, 1):
        raw = conn.execute(
            "SELECT COUNT(*) FROM step_time_samples WHERE global_rank=?",
            (rank,),
        ).fetchone()[0]
        folded = conn.execute(
            "SELECT COALESCE(SUM(count), 0) FROM rollup_samples_10s"
            " WHERE grain='rank' AND grain_key=? AND metric='step_ms'",
            (str(rank),),
        ).fetchone()[0]
        # THE invariant: every ingested row is raw or rolled up, never
        # neither (the fold commits in the prune's transaction)
        assert raw + folded == 200
        assert raw == 30  # 20 × 1.5
    # both tiers written by every fold (1m decay-safety)
    m1 = conn.execute(
        "SELECT COALESCE(SUM(count), 0) FROM rollup_samples_1m"
        " WHERE grain='rank' AND metric='step_ms'"
    ).fetchone()[0]
    assert m1 == 2 * 170
    # host grain merges both ranks via the UPSERT
    host = conn.execute(
        "SELECT COALESCE(SUM(count), 0) FROM rollup_samples_10s"
        " WHERE grain='host' AND grain_key='host-0' AND metric='step_ms'"
    ).fetchone()[0]
    assert host == 2 * 170
    conn.close()


def test_rollup_kill_switch_discards_history(tmp_path, monkeypatch):
    monkeypatch.setenv("TRACEML_ROLLUP", "0")
    db = tmp_path / "t.sqlite"
    w = SQLiteWriter(db, summary_window_rows=20, retention_factor=1.5)
    w.start()
    _ingest_steps(w, "s1", 0, 200)
    w.force_flush()
    assert w.finalize()
    assert w.stats()["rollup"] == {"enabled": False}
    conn = sqlite3.connect(db)
    assert conn.execute(
        "SELECT name FROM sqlite_master WHERE name='rollup_samples_10s'"
    ).fetchone() is None
    conn.close()


def test_crash_atomicity_rollback_leaves_raw_rows_intact(tmp_path):
    """A crash between fold and delete must never surface: both ride
    one transaction, so a rollback restores 'all rows raw' and a commit
    lands 'doomed rows rolled + deleted' — never neither."""
    db = tmp_path / "t.sqlite"
    w = SQLiteWriter(db, summary_window_rows=500, retention_factor=1.0)
    w.start()
    _ingest_steps(w, "s1", 0, 100)  # under retention: no prune yet
    w.force_flush()
    assert w.finalize()

    conn = sqlite3.connect(db)
    conn.row_factory = sqlite3.Row
    engine = RollupEngine()
    engine.init_schema(conn)
    conn.commit()
    watermark = conn.execute(
        "SELECT id FROM step_time_samples ORDER BY id LIMIT 1 OFFSET 59"
    ).fetchone()[0]

    def prune_txn(c):
        engine.fold_doomed(c, "step_time_samples", "s1", 0, watermark)
        c.execute(
            "DELETE FROM step_time_samples WHERE session_id='s1'"
            " AND global_rank=0 AND id<=?", (watermark,)
        )

    # simulated crash: the transaction never commits
    prune_txn(conn)
    conn.rollback()
    assert conn.execute(
        "SELECT COUNT(*) FROM step_time_samples"
    ).fetchone()[0] == 100
    assert conn.execute(
        "SELECT COUNT(*) FROM rollup_samples_10s"
    ).fetchone()[0] == 0

    # the retried prune commits: folded + surviving == everything
    prune_txn(conn)
    conn.commit()
    raw = conn.execute(
        "SELECT COUNT(*) FROM step_time_samples"
    ).fetchone()[0]
    folded = conn.execute(
        "SELECT COALESCE(SUM(count), 0) FROM rollup_samples_10s"
        " WHERE grain='rank' AND grain_key='0'"
    ).fetchone()[0]
    assert raw == 40 and folded == 60
    conn.close()


# -- stitched reads vs unbounded reference ---------------------------------


def test_stitched_series_matches_unbounded_reference(tmp_path):
    """With aggressive retention, the stitched read must still equal the
    reference fold over ALL rows ever ingested: counts/min/max exact,
    sums bit-exact per disjoint contribution (tier buckets hold only
    deleted rows; raw folds through the same math)."""
    from traceml_tpu.reporting import tiers

    db = tmp_path / "t.sqlite"
    w = SQLiteWriter(db, summary_window_rows=20, retention_factor=1.5)
    w.start()
    full_log = {0: [], 1: []}
    for rank in (0, 1):
        for step in range(1, 301):
            ms = 90.0 + rank * 2.0 + (step % 11) * 0.7
            ts = step * 0.4
            full_log[rank].append((ts, step, ms))
            w.ingest(build_telemetry_envelope(
                "step_time",
                {"step_time": [{
                    "step": step, "timestamp": ts, "clock": "host",
                    "events": {"_traceml_internal:step_time": {
                        "cpu_ms": ms, "count": 1,
                    }},
                }]},
                identity=_ident("s1", rank),
            ))
    w.force_flush()
    assert w.finalize()

    conn = sqlite3.connect(f"file:{db}?mode=ro", uri=True)
    conn.row_factory = sqlite3.Row
    assert tiers.has_rollups(conn)
    stitched = tiers.load_stitched_series(
        conn, "step_time_samples", "step_ms"
    )
    conn.close()

    for rank in (0, 1):
        log = full_log[rank]
        ref = fold_buckets_reference(
            [r[0] for r in log], [r[1] for r in log], [r[2] for r in log],
            10.0,
        )
        got = stitched[str(rank)]
        assert [p["t"] for p in got] == [b[0] for b in ref]
        for p, b in zip(got, ref):
            assert p["n"] == b[1]
            assert p["min"] == b[3] and p["max"] == b[4]
            assert p["step_min"] == b[6] and p["step_max"] == b[7]
            # the stitched sum merges two disjoint exact folds; the
            # reference folds everything in one sequence — identical
            # row sets, possibly one extra addition at the seam
            assert p["sum"] == pytest.approx(b[2], rel=1e-12)
        # the whole run is covered even though raw keeps only 30 rows
        assert got[0]["t"] == ref[0][0]
        assert {p["res"] for p in got} <= {"raw", "10s"}


def test_tier_decay_keeps_db_bounded_but_stitched_covers_run(
    tmp_path, monkeypatch
):
    """A short 10s horizon forces decay; the 1m tier (long horizon)
    backfills the decayed region in the stitched read — bounded bytes,
    unbounded coverage."""
    monkeypatch.setenv("TRACEML_ROLLUP_TIERS", "10:120,60:1209600")
    from traceml_tpu.reporting import tiers

    db = tmp_path / "t.sqlite"
    w = SQLiteWriter(db, summary_window_rows=20, retention_factor=1.5)
    w.start()
    # 2s per step → 1200s of run, 10× the 10s-tier horizon
    _ingest_steps(w, "s1", 0, 600, dt=2.0)
    w.force_flush()
    assert w.finalize()

    conn = sqlite3.connect(f"file:{db}?mode=ro", uri=True)
    conn.row_factory = sqlite3.Row
    lo, hi = conn.execute(
        "SELECT MIN(bucket_ts), MAX(bucket_ts) FROM rollup_samples_10s"
        " WHERE grain='rank'"
    ).fetchone()
    # decay is amortized (re-checked when the cutoff advances ≥16
    # widths), so allow that slack beyond the 120s horizon
    assert hi - lo <= 120 + 16 * 10
    stitched = tiers.load_stitched_series(
        conn, "step_time_samples", "step_ms"
    )["0"]
    conn.close()
    # coverage from the first bucket of the run
    assert stitched[0]["t"] == 0.0
    assert stitched[0]["res"] == "1m"
    assert {p["res"] for p in stitched} >= {"1m"}
    total = sum(p["n"] for p in stitched)
    assert total == 600
