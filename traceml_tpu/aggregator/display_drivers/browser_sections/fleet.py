"""Fleet index page: one row per session the registry serves
(docs/developer_guide/serving-tier.md).

Served at ``GET /fleet``; polls ``GET /api/sessions`` and renders the
session id (linking to the per-session dashboard via ``?session=``),
the rank-liveness summary, and the primary diagnosis.  Session ids and
diagnosis strings are telemetry-derived (the ingest port is
unauthenticated), so EVERY interpolation goes through ``esc()`` — and
ids placed into URLs additionally through ``encodeURIComponent()`` —
enforced by the escape-coverage contract test alongside the section
pages.
"""

from __future__ import annotations

from traceml_tpu.aggregator.display_drivers.browser_sections import theme

FLEET_HTML = """
<div class="wrap">
 <div class="card reveal" style="padding:13px 20px">
  <div style="display:flex;align-items:center;gap:14px;flex-wrap:wrap">
    <span class="wm">TraceML<b>-TPU</b></span>
    <span class="eyebrow">fleet</span>
    <span style="flex:1"></span>
    <span class="muted" id="fleet-meta">connecting…</span>
    <span class="livedot"></span>
  </div>
 </div>
 <div class="card reveal d1">
  <div class="chead"><h2 class="ctitle">Sessions</h2><span class="sp"></span>
    <span class="cmeta" id="fleet-count"></span></div>
  <table><thead><tr>
    <th>session</th><th>ranks</th><th>state</th><th>diagnosis</th>
    <th class="num">updated</th>
  </tr></thead><tbody id="fleet-rows">
    <tr><td colspan="5" class="muted">no sessions yet</td></tr>
  </tbody></table>
 </div>
</div>
<div id="tip"></div>
"""

FLEET_JS = """
function fleetRanks(r){
  const order=["ACTIVE","STALE","LOST","FINISHED"];
  const keys=Object.keys(r||{});
  keys.sort((a,b)=>(order.indexOf(a)+1||99)-(order.indexOf(b)+1||99));
  return keys.map(k=>`${esc(k.toLowerCase())} ${esc(r[k])}`).join(" · ");
}
function fleetMesh(s){
  const m=s.mesh;
  if(!m||!m.axes||!m.axes.length)return"";
  const axes=m.axes.map(a=>esc(a.name)+"×"+esc(a.size)+
    (a.kind==="dcn"?" (dcn)":"")).join(" · ");
  const hosts=m.hosts?
    (" · "+esc(m.hosts)+" host"+(m.hosts!==1?"s":"")):"";
  return '<br><span class="muted">mesh '+axes+hosts+'</span>';
}
function fleetWorkload(s){
  if(!s.workload)return"";
  return '<br><span class="muted">workload '+esc(s.workload)+'</span>';
}
function fleetDiag(s){
  const p=s.primary_diagnosis;
  if(!p)return'<span class="muted">—</span>';
  return`<span class="sevpill" style="background:${SEV[p.severity]||SEV.info}">${
    esc(p.severity||"info")}</span> ${esc(p.summary||p.kind||"")}`;
}
function fleetRow(s){
  const total=Object.values(s.ranks||{}).reduce((a,n)=>a+n,0);
  const state=s.finished?'<span class="badge">finished</span>':
    (s.db_exists?'<span class="badge" style="color:var(--good)">live</span>':
     '<span class="badge stale">no data</span>');
  const upd=s.last_update_ts?
    new Date(s.last_update_ts*1000).toLocaleTimeString():"—";
  return`<tr>
    <td><a style="color:var(--accent)" href="/?session=${
      encodeURIComponent(s.session)}">${esc(s.session)}</a>${
      fleetWorkload(s)}</td>
    <td>${total?esc(total):'<span class="muted">—</span>'}
      <span class="muted">${fleetRanks(s.ranks)}</span>${fleetMesh(s)}</td>
    <td>${state}</td>
    <td>${fleetDiag(s)}</td>
    <td class="num cmeta">${esc(upd)}</td></tr>`;
}
async function tick(){
 try{
  const r=await fetch("/api/sessions");const x=await r.json();
  const rows=(x.sessions||[]).map(fleetRow).join("");
  document.getElementById("fleet-rows").innerHTML=
    rows||'<tr><td colspan="5" class="muted">no sessions yet</td></tr>';
  document.getElementById("fleet-count").textContent=
    `${(x.sessions||[]).length} session(s)`;
  const meta=document.getElementById("fleet-meta");
  meta.textContent=`updated ${new Date(x.ts*1000).toLocaleTimeString()}`;
  meta.className="muted";
 }catch(e){const meta=document.getElementById("fleet-meta");
   meta.textContent="poll failed: "+e;meta.className="err"}
 setTimeout(tick,2000);
}
tick();
"""


def build_fleet_page() -> str:
    return (
        "<!doctype html><html><head><meta charset=\"utf-8\">\n"
        "<title>TraceML-TPU fleet</title>\n"
        f"{theme.head()}\n</head><body>\n"
        + FLEET_HTML
        + f"\n<script>{theme.HELPERS_JS}\n{FLEET_JS}</script></body></html>"
    )
