"""Memory-creep threshold matrix + trend-engine units
(mirrors the reference's heuristic bars: ≥row gate, 512 MiB/1 GiB delta,
6%/4% worst/median growth, slope gates, ≤2% pullback tolerance —
reference: diagnostics/step_memory/trend.py:31-57, policy.py:13-93)."""

from traceml_tpu.analytics.trends.core import (
    compute_trend_evidence,
    compute_window_trend,
    summarize_across,
)
from traceml_tpu.diagnostics.step_memory.api import diagnose_rank_rows as diagnose
from traceml_tpu.diagnostics.step_memory.policy import StepMemoryPolicy

GiB = 1024**3
MiB = 1024**2

POLICY = StepMemoryPolicy(creep_min_steps=90)  # row gate shrunk for speed


def _row(step, cur, limit=16 * GiB, dev=0):
    return {
        "step": step,
        "device_id": dev,
        "current_bytes": cur,
        "step_peak_bytes": cur,
        "limit_bytes": limit,
    }


def _linear(base, delta, n=900):
    return [_row(s, base + s * delta // n) for s in range(n)]


def _kinds(result):
    return {i.kind for i in result.issues}


# --- trend engine units ----------------------------------------------------

def test_window_trend_rising():
    ev = compute_window_trend([float(i) for i in range(500)], 100, 400)
    assert ev.trend_pct > 0
    assert ev.slope_pct_per_100 > 0
    assert not ev.recovered


def test_window_trend_flat_tail_after_growth():
    # grew early, flat for the whole long window → slope ~0 (plateau)
    series = [float(min(i, 100)) for i in range(600)]
    ev = compute_window_trend(series, 100, 400)
    assert abs(ev.slope_pct_per_100) < 0.001
    assert abs(ev.trend_pct) < 0.01


def test_window_trend_pullback_detected():
    series = [float(i) for i in range(400)] + [200.0] * 50
    ev = compute_window_trend(series, 100, 400, pullback_tolerance=0.02)
    assert ev.recovered
    assert ev.pullback_pct > 0.4


def test_summarize_across():
    s = summarize_across({0: 0.10, 1: 0.02, 2: 0.05})
    assert s.worst_key == 0 and s.worst == 0.10
    assert s.median == 0.05
    assert summarize_across({}) is None


def test_banded_evidence_monotonic():
    ev = compute_trend_evidence([float(i) for i in range(90)])
    assert ev.monotonic_band_growth
    assert not ev.weak_recovery
    assert ev.delta > 0


# --- creep threshold matrix ------------------------------------------------

def test_below_delta_bar_no_creep():
    rows = {0: _linear(4 * GiB, 300 * MiB)}  # < 512 MiB
    assert not _kinds(diagnose(rows, policy=POLICY)) & {
        "MEMORY_CREEP_EARLY", "MEMORY_CREEP_CONFIRMED"
    }


def test_below_growth_pct_no_creep():
    # 600 MiB over a 14 GiB base ≈ 4.2% < 6% growth bar
    rows = {0: _linear(14 * GiB, 600 * MiB, n=900)}
    assert not _kinds(diagnose(rows, policy=POLICY)) & {
        "MEMORY_CREEP_EARLY", "MEMORY_CREEP_CONFIRMED"
    }


def test_plateau_no_creep():
    # grew 2 GiB early, flat for the rest → tail slope gate rejects
    rows = {0: [
        _row(s, 4 * GiB + min(s, 150) * (2 * GiB // 150)) for s in range(900)
    ]}
    assert not _kinds(diagnose(rows, policy=POLICY)) & {
        "MEMORY_CREEP_EARLY", "MEMORY_CREEP_CONFIRMED"
    }


def test_early_creep_between_bars():
    # 900 MiB endpoint growth → banded delta (recent band mean − baseline
    # band mean) ≈ ⅔·900 = 600 MiB: ≥512 MiB early bar, <1 GiB confirmed
    rows = {0: _linear(4 * GiB, 900 * MiB)}
    result = diagnose(rows, policy=POLICY)
    assert "MEMORY_CREEP_EARLY" in _kinds(result)
    assert "MEMORY_CREEP_CONFIRMED" not in _kinds(result)
    early = next(i for i in result.issues if i.kind == "MEMORY_CREEP_EARLY")
    assert early.severity == "warning"


def test_confirmed_creep_above_bar():
    rows = {0: _linear(4 * GiB, 2 * GiB)}
    result = diagnose(rows, policy=POLICY)
    assert result.diagnosis.kind == "MEMORY_CREEP_CONFIRMED"
    assert result.diagnosis.severity == "critical"
    assert "MEMORY_CREEP_EARLY" not in _kinds(result)  # no double report
    ev = result.diagnosis.evidence
    assert "trend" in ev and "window" in ev


def test_row_gate_blocks_short_series():
    rows = {0: _linear(4 * GiB, 2 * GiB, n=80)}  # < 90-row gate
    assert not _kinds(diagnose(rows, policy=POLICY)) & {
        "MEMORY_CREEP_EARLY", "MEMORY_CREEP_CONFIRMED"
    }


def test_pullback_vetoes_creep():
    rows = {0: []}
    for s in range(900):
        growth = min(s, 600) * (2 * GiB // 600)
        recovery = max(0, s - 700) * (1 * GiB // 100)
        rows[0].append(_row(s, 4 * GiB + growth - recovery))
    assert not _kinds(diagnose(rows, policy=POLICY)) & {
        "MEMORY_CREEP_EARLY", "MEMORY_CREEP_CONFIRMED"
    }


def test_cluster_wide_flag():
    rows = {
        0: _linear(4 * GiB, 2 * GiB),
        1: _linear(4 * GiB, int(1.8 * GiB)),
    }
    result = diagnose(rows, policy=POLICY)
    confirmed = [i for i in result.issues if i.kind == "MEMORY_CREEP_CONFIRMED"]
    assert confirmed
    assert all(i.evidence["cluster_wide"] for i in confirmed)


def test_single_rank_creep_not_cluster_wide():
    rows = {
        0: _linear(4 * GiB, 2 * GiB),
        1: [_row(s, 4 * GiB) for s in range(900)],
        2: [_row(s, 4 * GiB) for s in range(900)],
    }
    result = diagnose(rows, policy=POLICY)
    confirmed = [i for i in result.issues if i.kind == "MEMORY_CREEP_CONFIRMED"]
    assert confirmed
    assert confirmed[0].ranks == [0]
    assert not confirmed[0].evidence["cluster_wide"]
