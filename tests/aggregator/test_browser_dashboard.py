"""Browser dashboard endpoints (no browser: urllib against the server)."""

import json
import urllib.request

from traceml_tpu.aggregator.display_drivers.browser import BrowserDisplayDriver
from traceml_tpu.aggregator.sqlite_writer import SQLiteWriter
from traceml_tpu.runtime.settings import TraceMLSettings
from traceml_tpu.telemetry.envelope import SenderIdentity, build_telemetry_envelope
from traceml_tpu.utils import timing as T
from traceml_tpu.utils.atomic_io import atomic_write_json


class _Ctx:
    def __init__(self, db_path, settings):
        self.db_path = db_path
        self.settings = settings


def _inject(db_path):
    w = SQLiteWriter(db_path)
    w.start()
    ident = SenderIdentity(session_id="web", global_rank=0)
    rows = [
        {"step": s, "timestamp": float(s), "clock": "device",
         "events": {
             T.STEP_TIME: {"cpu_ms": 50.0, "device_ms": 50.0, "count": 1},
             T.COMPUTE_TIME: {"cpu_ms": 1.0, "device_ms": 45.0, "count": 1},
         }}
        for s in range(1, 40)
    ]
    w.ingest(build_telemetry_envelope("step_time", {"step_time": rows}, ident))
    w.force_flush()
    w.finalize()


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.status, resp.read()


def test_dashboard_endpoints(tmp_path):
    db = tmp_path / "telemetry.sqlite"
    _inject(db)
    settings = TraceMLSettings(session_id="web", logs_dir=tmp_path.parent)
    driver = BrowserDisplayDriver()
    driver.start(_Ctx(db, settings))
    try:
        assert driver.port
        base = f"http://127.0.0.1:{driver.port}"
        status, body = _get(base + "/")
        assert status == 200
        assert b"TraceML-TPU" in body
        status, body = _get(base + "/api/live")
        assert status == 200
        payload = json.loads(body)
        assert payload["session"] == "web"
        assert payload["version"] == 3
        assert payload["step_time"]["n_steps"] == 39
        phase_keys = [p["key"] for p in payload["step_time"]["phases"]]
        assert "compute" in phase_keys
        assert "compute" in payload["step_time"]["phase_stack"]
        cov = payload["step_time"]["coverage"]
        assert cov["ranks_present"] == 1 and not cov["incomplete"]
        # summary 404 until the artifact exists
        try:
            status, _ = _get(base + "/api/summary")
        except urllib.error.HTTPError as e:
            status = e.code
        assert status == 404
        atomic_write_json(
            settings.session_dir / "final_summary.json", {"ok": True}
        )
        status, body = _get(base + "/api/summary")
        assert status == 200
        assert json.loads(body) == {"ok": True}
        # unknown path
        try:
            status, _ = _get(base + "/bogus")
        except urllib.error.HTTPError as e:
            status = e.code
        assert status == 404
    finally:
        driver.stop()


def test_torch_xla_support_gated():
    from traceml_tpu.instrumentation.torch_xla_support import (
        patch_mark_step,
        torch_xla_available,
    )

    assert not torch_xla_available()  # not in this image
    assert patch_mark_step() is False  # clean gate, no exception
