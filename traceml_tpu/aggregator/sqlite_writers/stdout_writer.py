"""stdout/stderr projection → ``stdout_samples``
(reference: aggregator/sqlite_writers/stdout_stderr.py)."""

from __future__ import annotations

from typing import Dict, List, Tuple

from traceml_tpu.aggregator.sqlite_writers.common import (
    IDENTITY_SCHEMA,
    identity_tuple,
)
from traceml_tpu.telemetry.envelope import TelemetryEnvelope

TABLE = "stdout_samples"
RETENTION_TABLES = (TABLE,)


def accepts_sampler(name: str) -> bool:
    return name == "stdout_stderr"


def init_schema(conn) -> None:
    conn.execute(
        f"""CREATE TABLE IF NOT EXISTS {TABLE} (
            id INTEGER PRIMARY KEY AUTOINCREMENT,
            {IDENTITY_SCHEMA},
            timestamp REAL,
            stream TEXT,
            line TEXT
        )"""
    )


def insert_sql(table: str) -> str:
    return (
        f"INSERT INTO {TABLE} (session_id, global_rank, local_rank, world_size,"
        " local_world_size, node_rank, hostname, pid, timestamp, stream, line)"
        " VALUES (?,?,?,?,?,?,?,?,?,?,?)"
    )


def build_rows(env: TelemetryEnvelope) -> Dict[str, List[Tuple]]:
    v = env.column_view("stdout_stderr")
    if not v:
        return {}
    ident = identity_tuple(env)
    ts = v.floats("timestamp")
    streams = v.strs("stream", "stdout")
    lines = v.strs("line", "")
    out = [
        ident + (ts[i], streams[i], lines[i][:4096])
        for i in range(len(v))
    ]
    return {TABLE: out}
