"""Serving rules: QUEUE_SATURATED, KV_CACHE_PRESSURE, DECODE_BOUND,
REPLICA_SKEW.

All four consume one :class:`ServingContext` built from the
cross-replica :class:`~traceml_tpu.utils.columnar.ServingWindow` —
queue depth and KV headroom are state signals, the decode share and
per-replica tokens/s are rate signals over the same window."""

from __future__ import annotations

import dataclasses
import statistics
from typing import Any, Dict, List, Optional

from traceml_tpu.diagnostics.common import (
    DiagnosticIssue,
    SEVERITY_CRITICAL,
    SEVERITY_WARNING,
    confidence_from,
)
from traceml_tpu.diagnostics.serving import vector
from traceml_tpu.diagnostics.serving.policy import ServingPolicy
from traceml_tpu.utils.columnar import ServingWindow


@dataclasses.dataclass
class ServingContext:
    window: ServingWindow
    policy: ServingPolicy
    n_steps: int = 0
    queue_depth_last: int = 0
    queue_depth_max: int = 0
    backlog_share: float = 0.0
    requests_enqueued: int = 0
    requests_completed: int = 0
    decode_tokens: int = 0
    decode_share: float = 0.0
    kv_headroom_min: float = -1.0
    tokens_per_s: float = 0.0
    coverage: float = 0.0


def build_context(window: ServingWindow, policy: ServingPolicy) -> ServingContext:
    qd = window.per_step.get("queue_depth") or []
    backlog_share = vector.backlog_share(qd) if vector.enabled() else None
    if backlog_share is None:  # scalar golden-reference arm
        backlog_share = (
            sum(1 for v in qd if v > 0) / len(qd) if qd else 0.0
        )
    t = window.totals
    return ServingContext(
        window=window,
        policy=policy,
        n_steps=window.n_steps,
        queue_depth_last=int(t.get("queue_depth_last", 0)),
        queue_depth_max=int(t.get("queue_depth_max", 0)),
        backlog_share=backlog_share,
        requests_enqueued=int(t.get("requests_enqueued", 0)),
        requests_completed=int(t.get("requests_completed", 0)),
        decode_tokens=int(t.get("decode_tokens", 0)),
        decode_share=float(t.get("decode_share", 0.0)),
        kv_headroom_min=float(t.get("kv_headroom_min", -1.0)),
        tokens_per_s=float(t.get("tokens_per_s", 0.0)),
        coverage=min(1.0, window.n_steps / max(1, policy.full_window_steps)),
    )


class QueueSaturatedRule:
    """Requests arrive faster than replicas drain them: a persistent
    backlog at window close plus backlog across most of the window —
    TTFT is queue wait, not model speed."""

    def evaluate(self, ctx: ServingContext) -> List[DiagnosticIssue]:
        p = ctx.policy
        depth = ctx.queue_depth_last
        if depth < p.queue_depth_warn or ctx.backlog_share < p.backlog_share_gate:
            return []
        severity = (
            SEVERITY_CRITICAL
            if depth >= p.queue_depth_critical
            else SEVERITY_WARNING
        )
        t = ctx.window.totals
        return [
            DiagnosticIssue(
                kind="QUEUE_SATURATED",
                severity=severity,
                summary=(
                    f"{depth} request(s) queued at window close with backlog "
                    f"in {ctx.backlog_share:.0%} of windows "
                    f"({ctx.requests_enqueued} arrived vs "
                    f"{ctx.requests_completed} completed) — arrival rate "
                    "exceeds service rate and TTFT is queue wait."
                ),
                action=(
                    "Add replicas or shed load: scale the serving pool, "
                    "enable continuous batching, or cap admission — the "
                    f"p99 TTFT ({t.get('ttft_p99_ms', 0.0):.0f} ms) is "
                    "dominated by queueing, not compute."
                ),
                metric="queue_depth",
                score=float(depth) / max(1.0, float(p.queue_depth_warn)),
                confidence=confidence_from(
                    float(depth),
                    float(p.queue_depth_warn),
                    coverage=ctx.coverage,
                ),
                evidence={
                    "queue_depth_last": depth,
                    "queue_depth_max": ctx.queue_depth_max,
                    "backlog_share": round(ctx.backlog_share, 4),
                    "requests_enqueued": ctx.requests_enqueued,
                    "requests_completed": ctx.requests_completed,
                    "ttft_p99_ms": round(float(t.get("ttft_p99_ms", 0.0)), 3),
                },
            )
        ]


class KvCachePressureRule:
    """Live KV-cache bytes leave single-digit HBM headroom: the next
    long prompt forces preemption/eviction (or OOMs outright)."""

    def evaluate(self, ctx: ServingContext) -> List[DiagnosticIssue]:
        p = ctx.policy
        headroom = ctx.kv_headroom_min
        if headroom < 0.0 or headroom > p.kv_headroom_warn:
            return []
        severity = (
            SEVERITY_CRITICAL
            if headroom <= p.kv_headroom_critical
            else SEVERITY_WARNING
        )
        pressure = 1.0 - headroom
        return [
            DiagnosticIssue(
                kind="KV_CACHE_PRESSURE",
                severity=severity,
                summary=(
                    f"HBM headroom bottomed at {headroom:.1%} — the KV "
                    "cache is within one long prompt of eviction or OOM."
                ),
                action=(
                    "Free cache headroom: shorten max context, enable "
                    "paged/quantized KV cache, lower max batch size, or "
                    "shard sessions across more replicas."
                ),
                metric="kv_headroom",
                score=float(pressure),
                confidence=confidence_from(
                    pressure,
                    1.0 - p.kv_headroom_warn,
                    coverage=ctx.coverage,
                ),
                evidence={
                    "kv_headroom_min": round(headroom, 4),
                },
            )
        ]


class DecodeBoundRule:
    """Almost all service time is the sequential decode loop — prefill
    is a rounding error, so throughput scales with batching and
    speculative decoding, not with a faster prefill."""

    def evaluate(self, ctx: ServingContext) -> List[DiagnosticIssue]:
        p = ctx.policy
        if (
            ctx.decode_tokens < p.min_decode_tokens
            or ctx.requests_completed <= 0
        ):
            return []
        share = ctx.decode_share
        if share < p.decode_share_warn:
            return []
        severity = (
            SEVERITY_CRITICAL
            if share >= p.decode_share_critical
            else SEVERITY_WARNING
        )
        t = ctx.window.totals
        return [
            DiagnosticIssue(
                kind="DECODE_BOUND",
                severity=severity,
                summary=(
                    f"{share:.0%} of serving time is the decode loop "
                    f"({t.get('decode_ms', 0.0):.0f} ms decode vs "
                    f"{t.get('prefill_ms', 0.0):.0f} ms prefill) — "
                    "throughput is bounded by sequential token generation."
                ),
                action=(
                    "Raise decode parallelism: grow the decode batch "
                    "(continuous batching), add speculative decoding, or "
                    "cap output lengths — prefill optimization will not "
                    "move tokens/s here."
                ),
                metric="decode_share",
                score=float(share),
                share_pct=float(share),
                confidence=confidence_from(
                    share, p.decode_share_warn, coverage=ctx.coverage
                ),
                evidence={
                    "decode_share": round(share, 4),
                    "decode_tokens": ctx.decode_tokens,
                    "tokens_per_s": round(ctx.tokens_per_s, 3),
                },
            )
        ]


class ReplicaSkewRule:
    """Replicas serving the same traffic disagree on tokens/s: the slow
    replica drags the pool's tail latency — a host or interconnect
    problem, not a traffic problem (topology attribution names it)."""

    def evaluate(self, ctx: ServingContext) -> List[DiagnosticIssue]:
        p = ctx.policy
        per_rank = ctx.window.per_rank
        if len(per_rank) < 2:
            return []
        stats = (
            vector.replica_skew(per_rank, p.skew_warn)
            if vector.enabled()
            else None
        )
        if stats is not None:
            med, worst, lag = stats
            if med <= 0.0:
                return []
        else:  # scalar golden-reference arm
            rank_tps = {
                r: float(v.get("tokens_per_s", 0.0) or 0.0)
                for r, v in per_rank.items()
            }
            med = statistics.median(rank_tps.values())
            if med <= 0.0:
                return []
            worst = min(rank_tps.values())
            lag = sorted(
                r
                for r, v in rank_tps.items()
                if (med - v) / med >= p.skew_warn
            )
        skew = (med - worst) / med
        if skew < p.skew_warn:
            return []
        severity = (
            SEVERITY_CRITICAL if skew >= p.skew_critical else SEVERITY_WARNING
        )
        evidence: Dict[str, Any] = {
            "median_tokens_per_s": round(med, 3),
            "min_tokens_per_s": round(worst, 3),
            "skew": round(skew, 4),
            "lagging_replicas": lag[:16],
        }
        return [
            DiagnosticIssue(
                kind="REPLICA_SKEW",
                severity=severity,
                summary=(
                    f"{len(lag)} replica(s) decode {skew:.0%} below the "
                    f"median ({worst:.1f} vs {med:.1f} tokens/s) — the "
                    "pool's tail latency is one slow replica."
                ),
                action=(
                    "Inspect the lagging replica's host (thermal "
                    "throttling, noisy neighbor, NUMA/IRQ placement) and "
                    "its interconnect path; drain and replace it if the "
                    "deficit persists."
                ),
                metric="tokens_per_s_skew",
                score=float(skew),
                skew_pct=float(skew),
                ranks=lag,
                confidence=confidence_from(
                    skew, p.skew_warn, coverage=ctx.coverage
                ),
                evidence=evidence,
            )
        ]


DEFAULT_RULES = (
    QueueSaturatedRule(),
    KvCachePressureRule(),
    DecodeBoundRule(),
    ReplicaSkewRule(),
)
