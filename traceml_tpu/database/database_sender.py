"""Incremental envelope builder over a Database
(reference: src/traceml_ai/database/database_sender.py:29-188).

Keeps a per-table cursor on the append counter; ``collect_payload`` ships
only rows appended since the previous call, wrapped in a canonical
telemetry envelope.  Returns ``None`` when there is nothing new (so the
publisher can skip the network entirely on idle ticks).

Envelopes go out as **schema v2 (columnar)** — each table transposed to
struct-of-arrays so table keys are encoded once per batch instead of
once per row (see docs/developer_guide/wire-schema-v2.md).  The
aggregator still accepts v1 row-lists from older senders.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from traceml_tpu.database.database import Database
from traceml_tpu.telemetry.envelope import (
    SenderIdentity,
    TelemetryEnvelope,
    build_columnar_envelope,
)


class DBIncrementalSender:
    def __init__(self, sampler_name: str, db: Database) -> None:
        self._sampler = sampler_name
        self._db = db
        self._cursors: Dict[str, int] = {}
        self._identity: Optional[SenderIdentity] = None

    @property
    def sampler_name(self) -> str:
        return self._sampler

    def set_identity(self, identity: SenderIdentity) -> None:
        self._identity = identity

    def collect_payload(self) -> Optional[Dict[str, Any]]:
        tables: Dict[str, List[Dict[str, Any]]] = {}
        for table in self._db.table_names():
            cursor = self._cursors.get(table, 0)
            rows, new_cursor = self._db.collect_since(table, cursor)
            if rows:
                tables[table] = rows
            self._cursors[table] = new_cursor
        if not tables:
            return None
        env: TelemetryEnvelope = build_columnar_envelope(
            self._sampler, tables, identity=self._identity
        )
        return env.to_wire()

    def reset(self) -> None:
        self._cursors.clear()
