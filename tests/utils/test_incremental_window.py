"""Incremental window engine: the delta tick is bit-identical, always.

Contract (docs/developer_guide/columnar-window-engine.md): the per-domain
window caches (``StepTimeWindowCache`` / ``CollectivesWindowCache`` /
``ServingWindowCache``) either produce a window bit-identical to the
from-scratch columnar build — itself golden-pinned against the scalar
reference — or invalidate back to that full build.  The randomized suite
below drives ~200 seeded interleavings of append / ring-eviction /
retention-trim / clock-flip / ragged-arrival / fallback across all three
domains through ONE persistent cache per run, comparing

    incremental == full rebuild == scalar reference

(plain-dict forms) after EVERY operation.  Deterministic fixtures then
pin each invalidation reason, the build-stats counters, and the
``TRACEML_INCR_WINDOW=0`` payload byte-pin.
"""

import json
import random

import pytest

from traceml_tpu.aggregator.sqlite_writer import SQLiteWriter
from traceml_tpu.samplers.serving_sampler import pack_floats
from traceml_tpu.telemetry.envelope import SenderIdentity, build_telemetry_envelope
from traceml_tpu.utils import timing as T
from traceml_tpu.utils.columnar import (
    CollectivesColumns,
    CollectivesWindowCache,
    ColumnarFallback,
    RaggedEventColumns,
    ServingWindowCache,
    StepTimeColumns,
    StepTimeWindowCache,
    build_collectives_window_rows,
    build_columnar_collectives_window,
    build_columnar_serving_window,
    build_columnar_step_time_window,
    build_serving_window_rows,
    collectives_window_to_plain,
    incr_window_enabled,
    serving_window_to_plain,
    window_to_plain,
)
from traceml_tpu.utils.step_time_window import PHASES, build_step_time_window


# -- row factories -------------------------------------------------------


def _step_row(step, rng, clock="device"):
    step_ms = rng.uniform(40.0, 150.0)
    events = {
        T.STEP_TIME: {
            "cpu_ms": step_ms,
            "device_ms": step_ms * 0.97 if clock == "device" else None,
            "count": 1,
        }
    }
    for key, name in PHASES.items():
        if rng.random() < 0.15:
            continue  # phase missing on this rank/step
        v = rng.uniform(0.0, 25.0)
        events[name] = {
            "cpu_ms": v,
            "device_ms": v * 0.95 if key != "input" else None,
            "count": 1,
        }
    return {
        "step": step,
        "timestamp": 100.0 + step,
        "clock": clock,
        "late_markers": 0,
        "events": events,
    }


def _coll_rows(step, rng):
    rows = []
    for op in ("all_reduce", "all_gather", "reduce_scatter"):
        if rng.random() < 0.3:
            continue
        dur = rng.uniform(0.0, 8.0)
        rows.append({
            "step": step,
            "timestamp": 100.0 + step,
            "op": op,
            "dtype": rng.choice(("float32", "bfloat16")),
            "count": rng.randint(1, 4),
            "bytes": rng.randint(0, 1 << 22),
            "group_size": rng.choice((4, 8)),
            "duration_ms": dur,
            "exposed_ms": dur * rng.random(),
        })
    return rows


def _serving_row(step, rng):
    done = rng.randint(0, 5)
    ttft = [rng.uniform(1.0, 500.0) for _ in range(done)]
    e2e = [rng.uniform(1.0, 1000.0) for _ in range(done)]
    kvh = rng.uniform(0.0, 0.9) if rng.random() < 0.5 else None
    return {
        "step": step,
        "timestamp": 100.0 + step,
        "requests_enqueued": rng.randint(0, 6),
        "requests_completed": done,
        "requests_active": rng.randint(0, 4),
        "queue_depth": rng.randint(0, 8),
        "decode_tokens": rng.randint(0, 256),
        "prefill_ms": rng.uniform(0.0, 50.0),
        "decode_ms": rng.uniform(0.0, 200.0),
        "tokens_per_s": rng.uniform(0.0, 500.0),
        "batch_occupancy": 0.4,
        "kv_bytes": -1 if kvh is None else 1 << 30,
        "kv_limit_bytes": -1 if kvh is None else 2 << 30,
        "kv_headroom": -1.0 if kvh is None else kvh,
        "ttft_ms_list": pack_floats(ttft),
        "e2e_ms_list": pack_floats(e2e),
        "tokens_list": ",".join("16" for _ in range(done)),
    }


# -- domain harnesses ----------------------------------------------------
#
# Each harness mirrors the snapshot store's lockstep (row deque, columnar
# ring) pair per rank plus ONE persistent incremental cache, and knows
# how to compare the three paths after an operation.


class _Domain:
    ring_cls = None
    cache_cls = None

    def __init__(self, ranks, cap, rng):
        self.rng = rng
        self.cap = cap
        self.rows = {r: [] for r in ranks}
        self.cols = {r: self.ring_cls(cap) for r in ranks}
        self.cache = self.cache_cls()
        self.gstep = 0

    def _mirror_append(self, rank, row):
        self.rows[rank].append(row)
        if len(self.rows[rank]) > self.cap:  # deque(maxlen=cap) semantics
            self.rows[rank] = self.rows[rank][-self.cap:]
        self.cols[rank].append(row)

    def append_step(self, ranks):
        raise NotImplementedError

    def evict(self, rank, n):
        self.rows[rank] = self.rows[rank][n:]
        self.cols[rank].evict_head(n)

    def clear(self, rank):
        self.rows[rank] = []
        self.cols[rank].clear()

    def poison(self, rank):
        """Append a row the ring cannot represent (flags the buffer)."""
        raise NotImplementedError

    def scalar(self, max_steps):
        raise NotImplementedError

    def full(self, max_steps):
        raise NotImplementedError

    def plain(self, w):
        raise NotImplementedError

    def tick_assert(self, max_steps, compare_scalar=True):
        live = {r: c for r, c in self.cols.items() if len(c)}
        try:
            full_plain = self.plain(self.full(live, max_steps))
            full_raised = False
        except ColumnarFallback:
            full_raised = True
        try:
            inc_plain = self.plain(self.cache.build(live, max_steps))
            inc_raised = False
        except ColumnarFallback:
            inc_raised = True
        assert inc_raised == full_raised
        if full_raised:
            return
        assert inc_plain == full_plain
        if compare_scalar:
            assert inc_plain == self.plain(self.scalar(max_steps))

    def scalar_rows(self):
        return {r: list(rows) for r, rows in self.rows.items() if rows}


class _StepTimeDomain(_Domain):
    ring_cls = StepTimeColumns
    cache_cls = StepTimeWindowCache

    def __init__(self, ranks, cap, rng):
        super().__init__(ranks, cap, rng)
        self.clock = "device"

    def append_step(self, ranks):
        self.gstep += 1
        for r in ranks:
            self._mirror_append(r, _step_row(self.gstep, self.rng, self.clock))

    def poison(self, rank):
        # duplicate step: ring flags, sticky
        last = self.rows[rank][-1]["step"] if self.rows[rank] else 1
        row = _step_row(last, self.rng, self.clock)
        self.rows[rank].append(row)
        self.cols[rank].append(row)

    def scalar(self, max_steps):
        return build_step_time_window(self.scalar_rows(), max_steps=max_steps)

    def full(self, live, max_steps):
        return build_columnar_step_time_window(live, max_steps)

    def plain(self, w):
        return window_to_plain(w)


class _CollectivesDomain(_Domain):
    ring_cls = CollectivesColumns
    cache_cls = CollectivesWindowCache

    def append_step(self, ranks):
        self.gstep += 1
        for r in ranks:
            for row in _coll_rows(self.gstep, self.rng):
                self._mirror_append(r, row)

    def poison(self, rank):
        last = self.rows[rank][-1]["step"] if self.rows[rank] else 5
        row = _coll_rows(last, self.rng) or _coll_rows(last, random.Random(0))
        row = dict(row[0], step=last - 3)  # out-of-order step
        self.rows[rank].append(row)
        self.cols[rank].append(row)

    def scalar(self, max_steps):
        return build_collectives_window_rows(
            self.scalar_rows(), max_steps=max_steps
        )

    def full(self, live, max_steps):
        return build_columnar_collectives_window(live, max_steps)

    def plain(self, w):
        return collectives_window_to_plain(w)


class _ServingDomain(_Domain):
    ring_cls = RaggedEventColumns
    cache_cls = ServingWindowCache

    def append_step(self, ranks):
        self.gstep += 1
        for r in ranks:
            self._mirror_append(r, _serving_row(self.gstep, self.rng))

    def poison(self, rank):
        last = self.rows[rank][-1]["step"] if self.rows[rank] else 5
        row = _serving_row(last, self.rng)  # duplicate window seq
        self.rows[rank].append(row)
        self.cols[rank].append(row)

    def scalar(self, max_steps):
        return build_serving_window_rows(self.scalar_rows(), max_steps=max_steps)

    def full(self, live, max_steps):
        return build_columnar_serving_window(live, max_steps)

    def plain(self, w):
        return serving_window_to_plain(w)


def _run_interleaving(domain_cls, seed):
    rng = random.Random(seed)
    R = rng.randint(1, 4)
    cap = rng.randint(8, 24)
    max_steps = rng.randint(4, 12)
    dom = domain_cls(list(range(R)), cap, rng)

    # warm up with a few aligned steps so the first tick has a window
    for _ in range(rng.randint(1, 6)):
        dom.append_step(range(R))
    dom.tick_assert(max_steps)

    for _ in range(22):
        op = rng.random()
        if op < 0.45:
            # append; sometimes ragged (a strict subset of ranks)
            if R > 1 and rng.random() < 0.35:
                ranks = rng.sample(range(R), rng.randint(1, R - 1))
            else:
                ranks = range(R)
            dom.append_step(ranks)
        elif op < 0.60:
            # burst of aligned appends (drives ring eviction past cap)
            for _ in range(rng.randint(2, cap)):
                dom.append_step(range(R))
        elif op < 0.75:
            # retention trim (head eviction, deque/ring lockstep)
            r = rng.randrange(R)
            dom.evict(r, rng.randint(1, max(1, len(dom.rows[r]) or 1)))
        elif op < 0.80 and isinstance(dom, _StepTimeDomain):
            dom.clock = "host" if dom.clock == "device" else "device"
            dom.append_step(range(R))
        elif op < 0.85:
            # empty-delta double tick (idle rebuild must also match)
            dom.tick_assert(max_steps)
        elif op < 0.90:
            # window resize mid-run
            dom.tick_assert(max(2, max_steps // 2))
        elif op < 0.95:
            r = rng.randrange(R)
            dom.poison(r)
            dom.tick_assert(max_steps, compare_scalar=False)
            dom.clear(r)  # store-reconnect semantics: ring + deque reset
            dom.append_step(range(R))
        else:
            r = rng.randrange(R)
            dom.clear(r)
            dom.append_step(range(R))
        dom.tick_assert(max_steps)

    stats = dom.cache.stats.snapshot()
    assert stats["incr_ticks"] + stats["full_rebuilds"] > 0


# ~200 seeded interleavings across the three domains
@pytest.mark.parametrize("seed", range(67))
def test_step_time_interleavings(seed):
    _run_interleaving(_StepTimeDomain, 1000 + seed)


@pytest.mark.parametrize("seed", range(67))
def test_collectives_interleavings(seed):
    _run_interleaving(_CollectivesDomain, 2000 + seed)


@pytest.mark.parametrize("seed", range(66))
def test_serving_interleavings(seed):
    _run_interleaving(_ServingDomain, 3000 + seed)


# -- invalidation-reason fixtures ---------------------------------------


def _aligned_step_time(n, ranks=2, cap=64, clock="device", start=1):
    rng = random.Random(7)
    cols = {r: StepTimeColumns(cap) for r in range(ranks)}
    for s in range(start, start + n):
        for c in cols.values():
            c.append(_step_row(s, rng, clock))
    return cols


def test_cold_start_then_steady_incremental_ticks():
    cache = StepTimeWindowCache()
    cols = _aligned_step_time(10)
    cache.build(cols, 8)
    assert cache.stats.invalidations == {"cold_start": 1}
    rng = random.Random(9)
    for s in range(11, 31):
        for c in cols.values():
            c.append(_step_row(s, rng))
        cache.build(cols, 8)
    st = cache.stats.snapshot()
    assert st["full_rebuilds"] == 1 and st["incr_ticks"] == 20
    assert st["last_path"] == "incremental" and st["last_build_ms"] >= 0.0


def test_window_size_change_invalidates():
    cache = StepTimeWindowCache()
    cols = _aligned_step_time(10)
    cache.build(cols, 8)
    cache.build(cols, 4)
    assert cache.stats.invalidations.get("window_size_changed") == 1


def test_rank_set_change_invalidates():
    cache = StepTimeWindowCache()
    cols = _aligned_step_time(10, ranks=2)
    cache.build(cols, 8)
    rng = random.Random(3)
    extra = StepTimeColumns(64)
    for s in range(1, 11):
        extra.append(_step_row(s, rng))
    cols[2] = extra
    cache.build(cols, 8)
    assert cache.stats.invalidations.get("rank_set_changed") == 1


def test_clock_flip_invalidates():
    cache = StepTimeWindowCache()
    cols = _aligned_step_time(10, clock="device")
    cache.build(cols, 8)
    rng = random.Random(5)
    for c in cols.values():
        c.append(_step_row(11, rng, clock="host"))
    w = cache.build(cols, 8)
    assert w.clock == "host"
    assert cache.stats.invalidations.get("clock_flip") == 1


def test_eviction_into_window_invalidates():
    cache = CollectivesWindowCache()
    rng = random.Random(13)
    cols = {0: CollectivesColumns(64), 1: CollectivesColumns(64)}
    for s in range(1, 11):
        for c in cols.values():
            c.append({"step": s, "timestamp": 1.0, "op": "all_reduce",
                      "dtype": "float32", "count": 1, "bytes": 100,
                      "group_size": 2, "duration_ms": 1.0,
                      "exposed_ms": 0.5})
    cache.build(cols, 4)  # window = steps 7..10
    cols[0].evict_head(8)  # surviving head step 9 >= window lo 7
    cache.build(cols, 4)
    assert cache.stats.invalidations.get("window_evicted") == 1
    # eviction strictly below the window is absorbed incrementally
    cols[1].evict_head(2)  # steps 1..2 < lo — harmless, no invalidation
    cache.build(cols, 4)
    assert cache.stats.snapshot()["incr_ticks"] >= 1
    assert rng  # silence unused warning on minimal interpreters


def test_mid_window_union_insert_realigns():
    cache = CollectivesWindowCache()

    def _row_at(s):
        return {"step": s, "timestamp": 1.0, "op": "all_gather",
                "dtype": "bfloat16", "count": 1, "bytes": 10,
                "group_size": 2, "duration_ms": 1.0, "exposed_ms": 0.0}

    cols = {0: CollectivesColumns(64), 1: CollectivesColumns(64)}
    for s in (2, 4):
        cols[0].append(_row_at(s))
    for s in (2, 4, 6):
        cols[1].append(_row_at(s))
    cache.build(cols, 8)  # union {2, 4, 6}
    cols[0].append(_row_at(5))  # lands inside the cached union
    w = cache.build(cols, 8)
    assert w.steps == [2, 4, 5, 6]
    assert cache.stats.invalidations.get("realigned") == 1
    ref = build_columnar_collectives_window(cols, 8)
    assert collectives_window_to_plain(w) == collectives_window_to_plain(ref)


def test_fallback_counts_and_propagates():
    cache = StepTimeWindowCache()
    cols = _aligned_step_time(10)
    cache.build(cols, 8)
    rng = random.Random(2)
    cols[0].append(_step_row(5, rng))  # duplicate step → sticky flag
    with pytest.raises(ColumnarFallback):
        cache.build(cols, 8)
    assert cache.stats.invalidations.get("fallback") == 1
    assert cache.stats.snapshot()["last_path"] == "full"


def test_kill_switch_bypasses_cache(monkeypatch):
    monkeypatch.setenv("TRACEML_INCR_WINDOW", "0")
    assert not incr_window_enabled()
    monkeypatch.setenv("TRACEML_INCR_WINDOW", "1")
    assert incr_window_enabled()


# -- TRACEML_INCR_WINDOW=0 payload byte-pin ------------------------------


def _ident(rank=0, world=2):
    return SenderIdentity(
        session_id="s1",
        global_rank=rank,
        local_rank=rank,
        world_size=world,
        node_rank=0,
        hostname="host-0",
        pid=100 + rank,
    )


def _seed_session(db):
    rng = random.Random(21)
    w = SQLiteWriter(db)
    w.start()
    for rank in (0, 1):
        w.ingest(build_telemetry_envelope(
            "step_time",
            {"step_time": [_step_row(s, random.Random(100 * rank + s))
                           for s in range(1, 25)]},
            _ident(rank),
        ))
        w.ingest(build_telemetry_envelope(
            "collectives",
            {"collectives": [row for s in range(1, 25)
                             for row in _coll_rows(s, random.Random(s))]},
            _ident(rank),
        ))
    assert w.force_flush()
    w.finalize()
    assert rng  # deterministic seeds only


def _payload_bytes(db, drop_stats=False):
    from traceml_tpu.renderers.web_payload import build_web_payload

    payload = build_web_payload(db, "s1")
    payload.pop("ts", None)  # wall-clock
    if drop_stats:
        payload.pop("window_build", None)
    return json.dumps(payload, sort_keys=True).encode()


def test_incr_off_payload_bytes_identical(tmp_path, monkeypatch):
    """With the kill switch off the served payload must be byte-identical
    to the full-rebuild (pre-r19) output: no window_build meta block
    anywhere, every window identical — the incremental engine may add
    its meta block only when enabled."""
    db_a = tmp_path / "a" / "t.sqlite"
    db_b = tmp_path / "b" / "t.sqlite"
    db_a.parent.mkdir()
    db_b.parent.mkdir()
    _seed_session(db_a)
    _seed_session(db_b)

    monkeypatch.setenv("TRACEML_INCR_WINDOW", "0")
    off = _payload_bytes(db_a)
    assert b"window_build" not in off

    monkeypatch.setenv("TRACEML_INCR_WINDOW", "1")
    on_raw = _payload_bytes(db_b)
    assert b'"window_build"' in on_raw
    on = _payload_bytes(db_b, drop_stats=True)
    assert off == on
