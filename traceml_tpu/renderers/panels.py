"""Rich panel renderers per domain
(reference pattern: renderers/<domain>/renderer.py get_panel_renderable).
"""

from __future__ import annotations

from typing import Any, Dict

from rich.console import Group
from rich.panel import Panel
from rich.table import Table
from rich.text import Text

from traceml_tpu.utils.formatting import fmt_bytes, fmt_ms, fmt_pct
from traceml_tpu.utils.step_time_window import RESIDUAL_KEY, STEP_KEY

_SEV_STYLE = {"critical": "bold red", "warning": "yellow", "info": "cyan"}


def step_time_panel(payload: Dict[str, Any]) -> Panel:
    st = payload.get("step_time") or {}
    window = st.get("window")
    if window is None:
        return Panel(
            Text("waiting for step telemetry…", style="dim"),
            title="step time",
        )
    table = Table(expand=True, box=None, pad_edge=False)
    table.add_column("phase")
    table.add_column("median", justify="right")
    table.add_column("share", justify="right")
    table.add_column("worst rank", justify="right")
    table.add_column("skew", justify="right")
    for key in [STEP_KEY] + window.phases_present + [RESIDUAL_KEY]:
        m = window.metric(key)
        if m is None:
            continue
        share = window.share_of_step(key) if key != STEP_KEY else None
        table.add_row(
            key,
            fmt_ms(m.median_ms),
            fmt_pct(share) if share is not None else "—",
            str(m.worst_rank),
            fmt_pct(m.skew_pct),
        )
    parts = [table]
    sub = (
        f"{window.n_steps} steps · {window.clock} clock · "
        f"ranks {window.ranks[0]}–{window.ranks[-1]}"
        if window.ranks
        else ""
    )
    return Panel(Group(*parts), title="step time", subtitle=sub)


def step_memory_panel(payload: Dict[str, Any]) -> Panel:
    rows_by_rank = payload.get("step_memory") or {}
    if not isinstance(rows_by_rank, dict) or not rows_by_rank or "error" in rows_by_rank:
        return Panel(Text("no memory telemetry", style="dim"), title="device memory")
    table = Table(expand=True, box=None)
    table.add_column("rank", justify="right")
    table.add_column("current", justify="right")
    table.add_column("step peak", justify="right")
    table.add_column("limit", justify="right")
    table.add_column("pressure", justify="right")
    for rank in sorted(rows_by_rank):
        rows = rows_by_rank[rank]
        if not rows:
            continue
        last = rows[-1]
        cur = last.get("current_bytes")
        peak = last.get("step_peak_bytes")
        limit = last.get("limit_bytes")
        pressure = (peak or cur or 0) / limit if limit else None
        style = ""
        if pressure is not None and pressure >= 0.92:
            style = "bold red" if pressure >= 0.97 else "yellow"
        table.add_row(
            str(rank),
            fmt_bytes(cur),
            fmt_bytes(peak),
            fmt_bytes(limit),
            Text(fmt_pct(pressure) if pressure else "—", style=style),
        )
    return Panel(table, title="device memory")


def system_panel(payload: Dict[str, Any]) -> Panel:
    sysd = payload.get("system") or {}
    host = sysd.get("host") or {}
    if not host:
        return Panel(Text("no system telemetry", style="dim"), title="system")
    table = Table(expand=True, box=None)
    table.add_column("node", justify="right")
    table.add_column("cpu", justify="right")
    table.add_column("host mem", justify="right")
    for node in sorted(host):
        rows = host[node]
        if not rows:
            continue
        last = rows[-1]
        used, total = last.get("memory_used_bytes"), last.get("memory_total_bytes")
        frac = used / total if used and total else None
        table.add_row(
            str(node),
            f"{last.get('cpu_pct', 0):.0f}%",
            f"{fmt_bytes(used)} / {fmt_bytes(total)}"
            + (f" ({fmt_pct(frac)})" if frac else ""),
        )
    return Panel(table, title="system")


def process_panel(payload: Dict[str, Any]) -> Panel:
    proc = payload.get("process") or {}
    procs = proc.get("procs") or {}
    if not procs:
        return Panel(Text("no process telemetry", style="dim"), title="processes")
    table = Table(expand=True, box=None)
    table.add_column("rank", justify="right")
    table.add_column("pid", justify="right")
    table.add_column("cpu", justify="right")
    table.add_column("rss", justify="right")
    table.add_column("threads", justify="right")
    for rank in sorted(procs):
        rows = procs[rank]
        if not rows:
            continue
        last = rows[-1]
        table.add_row(
            str(rank),
            str(last.get("pid", "—")),
            f"{last.get('cpu_pct') or 0:.0f}%",
            fmt_bytes(last.get("rss_bytes")),
            str(last.get("num_threads", "—")),
        )
    return Panel(table, title="processes")


def diagnostics_panel(payload: Dict[str, Any]) -> Panel:
    """Composed cross-domain diagnostics card (reference:
    renderers/model_diagnostics/renderer.py:94) — the single place the
    live view lists findings from every domain."""
    from traceml_tpu.diagnostics.model_diagnostics import compose

    results = {
        "step_time": (payload.get("step_time") or {}).get("diagnosis"),
        "step_memory": payload.get("step_memory_diagnosis"),
        "system": payload.get("system_diagnosis"),
        "process": payload.get("process_diagnosis"),
    }
    try:
        composed = compose(results)
    except Exception:
        return Panel(Text("—", style="dim"), title="diagnostics")
    if not composed.issues:
        return Panel(
            Text("no active findings", style="dim green"),
            title="diagnostics",
        )
    text = Text()
    for issue in composed.issues[:6]:
        domain = issue.evidence.get("domain", "?")
        text.append(
            f"[{issue.severity:>8}] {domain}/{issue.kind}: ",
            style=_SEV_STYLE.get(issue.severity, "white"),
        )
        text.append(issue.summary + "\n")
    return Panel(text, title="diagnostics")


def stdout_panel(payload: Dict[str, Any]) -> Panel:
    lines = payload.get("stdout") or []
    if not lines:
        return Panel(Text("—", style="dim"), title="rank 0 output")
    text = Text()
    for stream, line in lines[-10:]:
        style = "red" if stream == "stderr" else ""
        text.append(line[:160] + "\n", style=style)
    return Panel(text, title="rank 0 output")


def dashboard(payload: Dict[str, Any], session: str) -> Group:
    import time as _time

    header = Text(f"TraceML-TPU — live · session {session}", style="bold")
    # staleness = age of the NEWEST telemetry row, not of the payload
    # (the payload is recomputed every tick regardless)
    ts = payload.get("latest_row_ts")
    if ts:
        age = _time.time() - ts
        if age > 5.0:  # staleness badge (reference: display staleness)
            header.append(f"   ⚠ telemetry {age:.0f}s stale", style="yellow")
    return Group(
        header,
        step_time_panel(payload),
        diagnostics_panel(payload),
        step_memory_panel(payload),
        system_panel(payload),
        process_panel(payload),
        stdout_panel(payload),
    )
