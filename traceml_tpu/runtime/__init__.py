"""Per-rank runtime agent (reference: src/traceml_ai/runtime/)."""
