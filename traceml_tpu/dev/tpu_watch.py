"""Opportunistic on-chip capture daemon.

The axon TPU tunnel flaps: down for hours, up for minutes, and a wedged
client blocks ``jax.devices()`` inside C++.  A bench that runs once at
round end therefore almost never lands on a healthy chip (rounds 1-2
both fell back to the CPU proxy).  This daemon inverts the schedule
(VERDICT r2 item 1): it probes device health on a timer through the
WHOLE round and, the moment a probe comes back healthy AND physical, it
captures everything the round needs from real hardware:

* the full paired tracer-overhead bench (``bench.py --interleaved``,
  which carries its own physicality gate) → ``TPU_BENCH_RESULT.json``;
* the on-chip acceptance tier (``dev/tpu_acceptance.py``)
  → ``TPU_ACCEPTANCE.json``;
* the utilization-counter probe (``dev/libtpu_probe.py``)
  → ``TPU_UTIL_PROBE.json``.

Every probe attempt is appended to ``TPU_WATCH.jsonl`` — if the tunnel
never comes up, that file IS the round's evidence artifact.  Each probe
also refreshes ``PROBE_CACHE.json`` so ``bench.py`` and
``__graft_entry__`` never pay the wedged-tunnel timeout themselves
(VERDICT r2 item 10).

Physicality: a tunneled PJRT client can report buffers ready on enqueue
(observed: 1.9 PFLOP/s implied — impossible), so "backend == tpu" is
not enough.  The probe times a 4096³ bf16 matmul under
``block_until_ready`` and requires the implied FLOP/s to be achievable
by one real chip before any heavy capture is triggered.

Run detached for the round::

    python -m traceml_tpu.dev.tpu_watch --duration-s 39600 &
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[2]

from traceml_tpu.config import flags  # noqa: E402
from traceml_tpu.utils.atomic_io import atomic_write_json  # noqa: E402
from traceml_tpu.utils.probe_cache import write_cache  # noqa: E402

_PROBE_TIMEOUT_S = 75
_BENCH_TIMEOUT_S = 1500
_ACCEPT_TIMEOUT_S = 900
_UTIL_TIMEOUT_S = 300

# one real chip cannot exceed this (fastest shipping chip + headroom);
# a probe implying more means block_until_ready is not waiting
_PHYSICAL_PEAK_FLOPS = 1.2e15
_PROBE_MATMUL_N = 4096
_PROBE_MATMUL_FLOPS = 2.0 * _PROBE_MATMUL_N**3
_PROBE_MIN_STEP_S = 2e-4

_PROBE_SRC = r"""
import json, time, sys
import jax, jax.numpy as jnp
devs = jax.devices()
out = {
    "backend": jax.default_backend(),
    "n_devices": len(devs),
    "device_kind": devs[0].device_kind,
}
if out["backend"] != "cpu":
    x = jnp.ones((%(n)d, %(n)d), jnp.bfloat16)
    f = jax.jit(lambda a: a @ a)
    jax.block_until_ready(f(x)); jax.block_until_ready(f(x))
    best = min(
        (lambda t0: (jax.block_until_ready(f(x)), time.perf_counter() - t0)[1])(
            time.perf_counter()
        )
        for _ in range(8)
    )
    out["matmul_min_s"] = best
    out["implied_tflops"] = %(flops)r / best / 1e12
    out["physical"] = best >= %(min_step)r and (%(flops)r / best) <= %(peak)r
else:
    out["physical"] = False
print(json.dumps(out))
""" % {
    "n": _PROBE_MATMUL_N,
    "flops": _PROBE_MATMUL_FLOPS,
    "min_step": _PROBE_MIN_STEP_S,
    "peak": _PHYSICAL_PEAK_FLOPS,
}


def _device_env() -> dict:
    """Env for children that must SEE the tunnel (restores the axon
    trigger the daemon's own launcher scrubbed to keep itself safe)."""
    env = dict(os.environ)
    saved = env.pop(flags.AXON_SAVED_POOL_IPS.name, None)
    if saved and "PALLAS_AXON_POOL_IPS" not in env:
        env["PALLAS_AXON_POOL_IPS"] = saved
    return env


def _probe() -> dict:
    t0 = time.time()
    verdict: dict = {"backend": "", "physical": False}
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _PROBE_SRC],
            timeout=_PROBE_TIMEOUT_S, capture_output=True, text=True,
            env=_device_env(), cwd=str(REPO),
        )
        if proc.returncode == 0:
            verdict = json.loads(proc.stdout.strip().splitlines()[-1])
        else:
            # a probe child can die AFTER printing its verdict (tunnel
            # teardown crash) — salvage any JSON line before recording
            # the failure, and keep enough stderr to diagnose the new
            # failure mode (the 400-char tail hid the real error behind
            # the axon-platform warning in r3)
            for line in reversed((proc.stdout or "").strip().splitlines()):
                try:
                    candidate = json.loads(line)
                except ValueError:
                    continue
                # a bare scalar line ('4', 'null') parses too — only a
                # dict is a salvageable verdict
                if isinstance(candidate, dict):
                    verdict = candidate
                    break
            verdict["rc"] = proc.returncode
            verdict["error"] = (proc.stderr or "")[-1500:]
    except subprocess.TimeoutExpired:
        verdict["error"] = f"probe timeout ({_PROBE_TIMEOUT_S}s)"
    except (OSError, ValueError, IndexError) as exc:
        verdict["error"] = repr(exc)
    verdict["probe_s"] = round(time.time() - t0, 2)
    return verdict


def _append_log(path: Path, row: dict) -> None:
    with path.open("a") as fh:
        fh.write(json.dumps(row) + "\n")


def _load_state(path: Path) -> dict:
    try:
        return json.loads(path.read_text())
    except (OSError, ValueError):
        return {}


def _save_state(path: Path, state: dict) -> None:
    atomic_write_json(path, state, indent=1)


def _capture_bench(verdict: dict) -> bool:
    """Full paired overhead bench on the live chip; persists the JSON row
    (plus provenance) iff bench certifies the timings physical (rc 0)."""
    try:
        proc = subprocess.run(
            [sys.executable, str(REPO / "bench.py"), "--interleaved"],
            timeout=_BENCH_TIMEOUT_S, capture_output=True, text=True,
            env=_device_env(), cwd=str(REPO),
        )
    except subprocess.TimeoutExpired:
        return False
    if proc.returncode != 0:
        return False
    try:
        row = json.loads(proc.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return False
    out = {
        "captured_at": time.time(),
        "captured_at_iso": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "device_kind": verdict.get("device_kind"),
        "probe": verdict,
        "result": row,
        "stderr_tail": (proc.stderr or "")[-2000:],
    }
    atomic_write_json(REPO / "TPU_BENCH_RESULT.json", out, indent=1)
    return True


def _capture_child(argv: list, out_name: str, timeout_s: float,
                   ok_rcs: tuple = (0,)) -> bool:
    try:
        proc = subprocess.run(
            argv, timeout=timeout_s, capture_output=True, text=True,
            env=_device_env(), cwd=str(REPO),
        )
        return proc.returncode in ok_rcs and (REPO / out_name).exists()
    except subprocess.TimeoutExpired:
        return False


def run(duration_s: float, interval_s: float, settle_interval_s: float) -> int:
    log = REPO / "TPU_WATCH.jsonl"
    state_path = REPO / "TPU_WATCH_STATE.json"
    state = _load_state(state_path)
    state.setdefault("attempts", 0)
    state.setdefault("healthy", 0)
    state["pid"] = os.getpid()
    deadline = time.time() + duration_s

    while time.time() < deadline:
        verdict = _probe()
        state["attempts"] += 1
        # any non-cpu backend counts: the tunnel may register its PJRT
        # platform as "axon" rather than "tpu"
        on_chip = verdict.get("backend") not in ("", "cpu", None)
        physical = bool(verdict.get("physical"))
        if on_chip and physical:
            state["healthy"] += 1
        write_cache(verdict, REPO)
        row = dict(verdict)
        row["ts"] = time.time()
        row["iso"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())

        if on_chip and physical:
            # cheapest-first (VERDICT r3 item 1): the tunnel's healthy
            # windows are minutes long — a short window must still
            # yield partial on-chip evidence, so the 5-minute util
            # probe and 15-minute acceptance tier run BEFORE the
            # 25-minute bench, each persisting its artifact on its own
            if not state.get("util_done"):
                state["util_done"] = _capture_child(
                    [sys.executable, "-m", "traceml_tpu.dev.libtpu_probe",
                     "--out", "TPU_UTIL_PROBE.json"],
                    "TPU_UTIL_PROBE.json", _UTIL_TIMEOUT_S, ok_rcs=(0, 2),
                )
                row["util_captured"] = state.get("util_done", False)
            if not state.get("acceptance_done"):
                state["acceptance_done"] = _capture_child(
                    [sys.executable, "-m", "traceml_tpu.dev.tpu_acceptance",
                     "--out", "TPU_ACCEPTANCE.json"],
                    "TPU_ACCEPTANCE.json", _ACCEPT_TIMEOUT_S,
                )
                row["acceptance_captured"] = state.get("acceptance_done", False)
            if not state.get("bench_done"):
                state["bench_done"] = _capture_bench(verdict)
                row["bench_captured"] = state.get("bench_done", False)

        _append_log(log, row)
        _save_state(state_path, state)
        all_done = all(
            state.get(k) for k in ("bench_done", "util_done", "acceptance_done")
        )
        time.sleep(settle_interval_s if all_done else interval_s)
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--duration-s", type=float, default=39600.0)
    parser.add_argument("--interval-s", type=float, default=180.0)
    parser.add_argument(
        "--settle-interval-s", type=float, default=480.0,
        help="probe cadence after every capture has succeeded — kept "
             "UNDER probe_cache.DEFAULT_MAX_AGE_S (600 s) so the cache "
             "never expires between refreshes",
    )
    args = parser.parse_args(argv)
    return run(args.duration_s, args.interval_s, args.settle_interval_s)


if __name__ == "__main__":
    sys.exit(main())
