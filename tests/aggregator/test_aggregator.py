"""Aggregator loop + finalization barrier tests.

The settle/finalize paths run against the real TCP server with scripted
clients (reference pattern: tests/aggregator/test_finalization.py uses
fakes; here the transport is cheap enough to use for real).
"""

import json
import time

from traceml_tpu.aggregator.trace_aggregator import TraceMLAggregator
from traceml_tpu.runtime.settings import AggregatorEndpoint, TraceMLSettings
from traceml_tpu.telemetry.control import build_rank_finished
from traceml_tpu.telemetry.envelope import SenderIdentity, build_telemetry_envelope
from traceml_tpu.transport import TCPClient
from traceml_tpu.utils import timing as T


def _settings(tmp_path, expected_ws=None):
    return TraceMLSettings(
        session_id="agg-test",
        logs_dir=tmp_path,
        mode="summary",
        aggregator=AggregatorEndpoint(port=0),
        expected_world_size=expected_ws,
        finalize_timeout_sec=3.0,
    )


def _send_rank(port, rank, n_steps=60, finish=True):
    ident = SenderIdentity(session_id="agg-test", global_rank=rank, world_size=2)
    client = TCPClient("127.0.0.1", port)
    rows = [
        {"step": s, "timestamp": float(s), "clock": "device",
         "events": {
             T.STEP_TIME: {"cpu_ms": 100.0, "device_ms": 100.0, "count": 1},
             T.COMPUTE_TIME: {"cpu_ms": 1.0, "device_ms": 92.0, "count": 1},
         }}
        for s in range(1, n_steps + 1)
    ]
    batch = [build_telemetry_envelope("step_time", {"step_time": rows}, ident).to_wire()]
    if finish:
        batch.append(build_rank_finished(ident.to_meta()))
    assert client.send_batch(batch)
    client.close()


def test_aggregator_end_to_end_with_summary(tmp_path):
    settings = _settings(tmp_path, expected_ws=2)
    agg = TraceMLAggregator(settings)
    agg.start()
    try:
        for rank in (0, 1):
            _send_rank(agg.port, rank)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and len(agg._finished_ranks) < 2:
            time.sleep(0.05)
    finally:
        agg.stop()
    payload = json.loads((settings.session_dir / "final_summary.json").read_text())
    assert payload["sections"]["step_time"]["status"] == "OK"
    assert payload["meta"]["topology"]["world_size"] == 2
    assert not (settings.session_dir / "finalization_warning.json").exists()
    stats = payload["meta"]["telemetry_stats"]
    assert stats["envelopes_ingested"] >= 2
    assert stats["rows_dropped"] == 0


def test_aggregator_missing_rank_warning(tmp_path):
    settings = _settings(tmp_path, expected_ws=2)
    agg = TraceMLAggregator(settings)
    agg.start()
    try:
        _send_rank(agg.port, 0)  # rank 1 never reports
        time.sleep(0.3)
    finally:
        agg.stop(finalize_timeout=1.0)
    warning = json.loads(
        (settings.session_dir / "finalization_warning.json").read_text()
    )
    assert warning["missing_ranks"] == [1]
    # summary still generated from what arrived
    assert (settings.session_dir / "final_summary.json").exists()


def test_summary_service_file_ipc(tmp_path):
    settings = _settings(tmp_path, expected_ws=1)
    agg = TraceMLAggregator(settings)
    agg.start()
    try:
        _send_rank(agg.port, 0, finish=False)
        time.sleep(0.3)
        from traceml_tpu.sdk import protocol

        protocol.write_summary_request(settings.session_dir)
        deadline = time.monotonic() + 5
        resp = None
        while time.monotonic() < deadline:
            resp = protocol.read_summary_response(settings.session_dir)
            if resp:
                break
            time.sleep(0.1)
        assert resp is not None and resp["ok"]
        assert (settings.session_dir / "final_summary.json").exists()
    finally:
        agg.stop(finalize_timeout=1.0)


def test_sdk_summary_client_roundtrip(tmp_path):
    settings = _settings(tmp_path, expected_ws=1)
    agg = TraceMLAggregator(settings)
    agg.start()
    try:
        _send_rank(agg.port, 0, finish=False)
        time.sleep(0.3)
        from traceml_tpu.sdk.summary_client import final_summary, summary

        data = final_summary(timeout=10, session_dir=settings.session_dir)
        assert data is not None
        assert data["sections"]["step_time"]["status"] == "OK"
        flat = summary(timeout=10, session_dir=settings.session_dir)
        assert any(k.startswith("traceml/") for k in flat)
    finally:
        agg.stop(finalize_timeout=1.0)
