"""torch-xla (TPU) support — gated; torch_xla is not in this image.

What it adds when torch_xla IS present (BASELINE configs: BERT-base and
Llama-3-8B FSDP via torch-xla on TPU slices):

* ``patch_mark_step()`` — wraps ``torch_xla.core.xla_model.mark_step``
  (and ``torch_xla.sync`` on newer versions) in a timed region named
  ``collective``: under torch-xla the lazy graph executes AT the step
  barrier, so mark_step wall time IS the device execution + collective
  wait for the step — the torch-xla analogue of our JAX readiness edges.
* ``XlaMemoryBackend`` — per-device memory via
  ``torch_xla.core.xla_model.get_memory_info`` (kb fields), plugged into
  the standard StepMemoryTracker backend protocol.
* identity: torch-xla jobs run one process per host with torchrun-style
  env, which ``runtime/identity.py`` already resolves.

The generic torch patches (DataLoader/forward/backward/optimizer —
instrumentation/patches/torch_patches.py) apply unchanged: they are
host-clock dispatch timers, which is exactly what is observable under
lazy execution; the mark_step region carries the device truth.
"""

from __future__ import annotations

from typing import Any, List, Optional

from traceml_tpu.sdk.state import get_state
from traceml_tpu.utils.error_log import get_error_log
from traceml_tpu.utils.timing import COLLECTIVE_TIME, timed_region

_original_mark_step: Optional[Any] = None
_hook: Any = None


def torch_xla_loaded() -> bool:
    """True only when the PROCESS already imported torch_xla — the
    touch-nothing policy: importing torch_xla on the user's behalf can
    initialize the XLA runtime in jobs that never wanted it."""
    import sys

    return "torch_xla" in sys.modules


def torch_xla_available() -> bool:
    try:
        import torch_xla  # noqa: F401

        return True
    except Exception:
        return False


def install_torch_xla_patch() -> str:
    """Patch now if torch_xla is loaded, else arm a post-import hook
    (the launcher initializes tracing BEFORE the user script imports
    its stack — same gap the orbax patch closes; shared arming logic
    lives next to _PostImportHook).
    Returns "patched" | "deferred" | "noop"."""
    global _hook
    from traceml_tpu.instrumentation.orbax_patch import arm_post_import_patch

    outcome, _hook = arm_post_import_patch(
        "torch_xla",
        "torch_xla",
        "torch_xla.core.xla_model",
        patch_mark_step,
        _hook,
    )
    return outcome


def remove_torch_xla_hook() -> None:
    global _hook
    if _hook is not None:
        _hook.remove()
        _hook = None


def patch_mark_step() -> bool:
    """Time the lazy-execution barrier.  Idempotent; False when gated."""
    global _original_mark_step
    if _original_mark_step is not None:
        return True
    try:
        import torch_xla.core.xla_model as xm
    except Exception:
        return False
    original = xm.mark_step

    def timed_mark_step(*args: Any, **kwargs: Any):
        st = get_state()
        if not st.tls.in_step:
            return original(*args, **kwargs)
        with timed_region(COLLECTIVE_TIME, st.current_step, sink=st.buffer.add):
            return original(*args, **kwargs)

    timed_mark_step._traceml_original = original  # type: ignore[attr-defined]
    xm.mark_step = timed_mark_step
    _original_mark_step = original
    return True


def unpatch_mark_step() -> None:
    global _original_mark_step
    if _original_mark_step is None:
        return
    try:
        import torch_xla.core.xla_model as xm

        xm.mark_step = _original_mark_step
    except Exception:
        pass
    _original_mark_step = None


class XlaMemoryBackend:
    """StepMemoryTracker backend over torch-xla memory info."""

    name = "torch_xla"

    def __init__(self) -> None:
        import torch_xla.core.xla_model as xm

        self._xm = xm
        devices = xm.get_xla_supported_devices()
        if not devices:
            raise RuntimeError("no xla devices")
        self._devices = devices

    def sample(self) -> List[dict]:
        out = []
        for i, dev in enumerate(self._devices):
            try:
                info = self._xm.get_memory_info(dev)
            except Exception as exc:
                get_error_log().warning(f"xla memory info failed for {dev}", exc)
                continue
            total = int(info.get("kb_total", 0)) * 1024
            free = int(info.get("kb_free", 0)) * 1024
            used = max(0, total - free)
            out.append(
                {
                    "device_id": i,
                    "device_kind": str(dev),
                    "current_bytes": used,
                    "peak_bytes": used,
                    "limit_bytes": total or None,
                }
            )
        return out
