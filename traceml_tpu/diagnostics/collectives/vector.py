"""Vectorized gate arm for the collectives diagnosis pack.

The collectives window carries per-step slot series (lists emitted from
the r19 slot arrays) and a small per-rank aggregate dict; the helpers
here lift the PoorOverlap / AllreduceQuantizable per-step and per-rank
loops into numpy while reproducing the scalar arm bit-for-bit:
``np.median`` matches ``statistics.median`` for float64, boolean masks
match the ``if d > 0.0`` filters, and ``np.cumsum(...)[-1]`` matches
the left-fold ``sum()`` exactly (``statistics.pstdev`` stays scalar —
its exact-Fraction arithmetic has no numpy twin — fed the identical
float population either way).

``enabled()`` is the pack's kill-switch gate
(``TRACEML_VECTOR_DIAGNOSIS=0`` forces the scalar reference arm); a
helper that cannot reproduce its loop returns ``None`` and counts a
fallback instead of logging per tick.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from traceml_tpu.utils.columnar import (
    note_vector_fallback,
    vector_diagnosis_enabled,
)

DOMAIN = "collectives"


def enabled() -> bool:
    return vector_diagnosis_enabled()


def poor_overlap_stats(
    per_step: Dict[str, List[float]],
    per_rank: Dict[int, Dict[str, float]],
    headroom_gate: float,
) -> Optional[Tuple[Optional[float], Optional[float], List[int]]]:
    """PoorOverlapRule's two scalar scans as masked reductions:
    (best-steps 75th-pct efficiency, median rank efficiency, lagging
    ranks sorted).  ``None`` → rerun the scalar arm."""
    try:
        eff = np.asarray(
            per_step.get("overlap_efficiency") or [], dtype=np.float64
        )
        dur = np.asarray(per_step.get("duration_ms") or [], dtype=np.float64)
        best_eff: Optional[float] = None
        if eff.shape == dur.shape:
            sel = eff[dur > 0.0]
            if sel.size:
                ranked = np.sort(sel)
                best_eff = float(
                    ranked[min(ranked.size - 1, int(ranked.size * 0.75))]
                )
        elif eff.size or dur.size:
            raise ValueError("ragged per-step series")
        median_rank_eff: Optional[float] = None
        lag_ranks: List[int] = []
        if per_rank:
            ranks = np.asarray(list(per_rank), dtype=np.int64)
            vals = np.asarray(
                [v["overlap_efficiency"] for v in per_rank.values()],
                dtype=np.float64,
            )
            median_rank_eff = float(np.median(vals))
            lag_ranks = np.sort(
                ranks[median_rank_eff - vals >= headroom_gate]
            ).tolist()
        return best_eff, median_rank_eff, lag_ranks
    except Exception:
        note_vector_fallback(DOMAIN)
        return None


def fp32_allreduce_stats(
    series: List[float],
) -> Optional[Tuple[int, float, List[float]]]:
    """AllreduceQuantizableRule's payload scan: (non-zero count, mean
    bytes via the exact left-fold cumsum, the non-zero population as
    native floats for ``statistics.pstdev``).  ``None`` → scalar arm."""
    try:
        arr = np.asarray(series, dtype=np.float64)
        nz = arr[arr > 0]
        if nz.size == 0:
            return 0, 0.0, []
        mean_bytes = float(np.cumsum(nz)[-1]) / nz.size
        return int(nz.size), mean_bytes, nz.tolist()
    except Exception:
        note_vector_fallback(DOMAIN)
        return None
