"""Dependency-free browser dashboard
(reference role: the NiceGUI dashboard, display_drivers/nicegui.py —
rebuilt on the stdlib since this image ships no web framework; a single
HTML page polls ``/api/live`` and renders with vanilla JS + inline SVG).

Serves:

* ``GET /``          — the dashboard page (self-contained HTML/JS/CSS)
* ``GET /api/live``  — live JSON payload (renderers/web_payload.py)
* ``GET /api/summary`` — final_summary.json once it exists
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from typing import Any, Optional

from traceml_tpu.aggregator.display_drivers.base import BaseDisplayDriver
from traceml_tpu.utils.atomic_io import read_json
from traceml_tpu.utils.error_log import get_error_log

_PAGE = """<!doctype html><html><head><meta charset="utf-8">
<title>TraceML-TPU live</title>
<style>
body{font-family:system-ui,sans-serif;margin:1.5rem auto;max-width:1000px;
     background:#12121a;color:#e8e8f0;padding:0 1rem}
h1{font-size:1.2rem} .muted{color:#9a9ab0;font-size:.85rem}
.card{background:#1c1c28;border-radius:10px;padding:1rem;margin:.8rem 0}
.verdict-info{border-left:5px solid #2d7dd2}
.verdict-warning{border-left:5px solid #e67e22}
.verdict-critical{border-left:5px solid #c0392b}
table{border-collapse:collapse;width:100%;font-size:.88rem}
th,td{text-align:left;padding:.3rem .55rem;border-bottom:1px solid #2c2c3c}
.bar{height:16px;display:inline-block;vertical-align:middle;border-radius:2px}
pre{white-space:pre-wrap;font-size:.8rem;color:#b8e0b8;margin:0}
.err{color:#f0a0a0}
svg{width:100%;height:70px;background:#15151f;border-radius:6px}
</style></head><body>
<h1>TraceML-TPU — live dashboard</h1>
<div class="muted" id="meta">connecting…</div>
<div id="verdict"></div>
<div class="card"><b>Step time</b><div id="phases"></div>
<svg id="spark" viewBox="0 0 600 70" preserveAspectRatio="none"></svg></div>
<div class="card"><b>Device memory</b><div id="memory"></div></div>
<div class="card"><b>System</b><div id="system"></div></div>
<div class="card"><b>Rank 0 output</b><pre id="stdout"></pre></div>
<script>
const COLORS={input:"#e74c3c",h2d:"#e67e22",forward:"#2d7dd2",
backward:"#2255a4",optimizer:"#7d3dd2",compute:"#2d7dd2",
compile:"#f1c40f",collective:"#16a085",residual:"#95a5a6"};
// telemetry strings (hostnames, diagnosis text, phase/rank keys) arrive
// from an unauthenticated ingest port — escape EVERY interpolation.
const esc=s=>String(s).replace(/[&<>"']/g,
  c=>({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;","'":"&#39;"}[c]));
const fmtB=n=>{if(n==null)return"n/a";const u=["B","KiB","MiB","GiB","TiB"];
let i=0;while(n>=1024&&i<u.length-1){n/=1024;i++}return n.toFixed(i?2:0)+" "+u[i]};
const fmtMs=v=>v==null?"n/a":(v<1?(v*1000).toFixed(0)+" µs":
v<1000?v.toFixed(1)+" ms":(v/1000).toFixed(2)+" s");
async function tick(){
 try{
  const r=await fetch("/api/live");const d=await r.json();
  const meta=document.getElementById("meta");
  meta.textContent=
    `session ${d.session} · updated ${new Date(d.ts*1000).toLocaleTimeString()}`;
  meta.className="muted";
  const v=document.getElementById("verdict");
  if(d.diagnosis){v.innerHTML=`<div class="card verdict-${esc(d.diagnosis.severity)}">
    <b>${esc(d.diagnosis.kind)}</b> <span class="muted">[${esc(d.diagnosis.severity)}]</span><br>
    ${esc(d.diagnosis.summary)}<br><span class="muted">→ ${esc(d.diagnosis.action||"")}</span></div>`}
  const st=d.step_time;
  if(st){
   let rows=`<div class="muted">${esc(st.n_steps)} steps · ${esc(st.clock)} clock</div>
     <div style="margin:.4rem 0">`;
   for(const[k,p]of Object.entries(st.phases)){
     if(k==="step_time"||!p.share)continue;
     rows+=`<span class="bar" title="${esc(k)} ${(p.share*100).toFixed(1)}%"
       style="width:${(p.share*100).toFixed(1)}%;background:${COLORS[k]||"#888"}"></span>`}
   rows+=`</div><table><tr><th>phase</th><th>median</th><th>share</th>
     <th>worst rank</th><th>skew</th></tr>`;
   for(const[k,p]of Object.entries(st.phases)){
     rows+=`<tr><td>${esc(k)}</td><td>${fmtMs(p.median_ms)}</td>
       <td>${p.share==null?"—":(p.share*100).toFixed(1)+"%"}</td>
       <td>${esc(p.worst_rank)}</td><td>${(p.skew_pct*100).toFixed(1)}%</td></tr>`}
   document.getElementById("phases").innerHTML=rows+"</table>";
   const svg=document.getElementById("spark");
   let paths="";const ranks=Object.keys(st.step_series);
   let max=1;for(const r of ranks)for(const v of st.step_series[r])max=Math.max(max,v);
   ranks.forEach((r,ri)=>{const s=st.step_series[r];if(!s.length)return;
     const pts=s.map((v,i)=>`${(i/(s.length-1||1))*600},${68-(v/max)*62}`).join(" ");
     paths+=`<polyline fill="none" stroke="hsl(${(ri*67)%360},70%,60%)"
       stroke-width="1.5" points="${pts}"><title>rank ${esc(r)}</title></polyline>`});
   svg.innerHTML=paths;
  }
  let mem="<table><tr><th>rank</th><th>current</th><th>peak</th><th>limit</th></tr>";
  for(const m of d.memory){mem+=`<tr><td>${esc(m.rank)}</td><td>${fmtB(m.current_bytes)}</td>
    <td>${fmtB(m.step_peak_bytes)}</td><td>${fmtB(m.limit_bytes)}</td></tr>`}
  document.getElementById("memory").innerHTML=mem+"</table>";
  let sys="<table><tr><th>node</th><th>cpu</th><th>host mem</th></tr>";
  for(const s of d.system){sys+=`<tr><td>${esc(s.node)}</td>
    <td>${s.cpu_pct==null?"n/a":s.cpu_pct.toFixed(0)+"%"}</td>
    <td>${fmtB(s.memory_used_bytes)} / ${fmtB(s.memory_total_bytes)}</td></tr>`}
  document.getElementById("system").innerHTML=sys+"</table>";
  document.getElementById("stdout").textContent=
    d.stdout.map(l=>l.line).join("\\n");
 }catch(e){document.getElementById("meta").textContent="poll failed: "+e;
   document.getElementById("meta").className="err"}
 setTimeout(tick,1000);
}
tick();
</script></body></html>"""


class BrowserDisplayDriver(BaseDisplayDriver):
    """Serves the dashboard from inside the aggregator process."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self._host = host
        self._requested_port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.port: Optional[int] = None
        self._db_path: Optional[Path] = None
        self._session = ""
        self._session_dir: Optional[Path] = None

    def start(self, context: Optional[Any] = None) -> None:
        try:
            if context is not None:
                self._db_path = context.db_path
                self._session = context.settings.session_id
                self._session_dir = context.settings.session_dir
            driver = self

            class Handler(BaseHTTPRequestHandler):
                def log_message(self, fmt, *args):  # silence
                    pass

                def _send(self, code: int, body: bytes, ctype: str) -> None:
                    self.send_response(code)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)

                def do_GET(self):  # noqa: N802
                    try:
                        if self.path == "/" or self.path.startswith("/index"):
                            self._send(200, _PAGE.encode(), "text/html; charset=utf-8")
                        elif self.path.startswith("/api/live"):
                            from traceml_tpu.renderers.web_payload import (
                                build_web_payload,
                            )

                            payload = build_web_payload(
                                driver._db_path, driver._session
                            ) if driver._db_path else {}
                            self._send(
                                200,
                                json.dumps(payload).encode(),
                                "application/json",
                            )
                        elif self.path.startswith("/api/summary"):
                            data = None
                            if driver._session_dir is not None:
                                data = read_json(
                                    driver._session_dir / "final_summary.json"
                                )
                            self._send(
                                200 if data else 404,
                                json.dumps(data or {"error": "not ready"}).encode(),
                                "application/json",
                            )
                        else:
                            self._send(404, b"not found", "text/plain")
                    except BrokenPipeError:
                        pass
                    except Exception as exc:
                        try:
                            self._send(
                                500, str(exc).encode(), "text/plain"
                            )
                        except Exception:
                            pass

            self._httpd = ThreadingHTTPServer(
                (self._host, self._requested_port), Handler
            )
            self.port = self._httpd.server_address[1]
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                name="traceml-dashboard",
                daemon=True,
            )
            self._thread.start()
            print(f"[TraceML] dashboard: http://{self._host}:{self.port}/")
        except Exception as exc:
            get_error_log().warning("browser dashboard start failed", exc)
            self._httpd = None

    def tick(self, context: Optional[Any] = None) -> None:
        pass  # pull-based: the page polls /api/live

    def stop(self) -> None:
        if self._httpd is not None:
            try:
                self._httpd.shutdown()
                self._httpd.server_close()
            except Exception:
                pass
            self._httpd = None
