"""Columnar window engine: golden equivalence vs the scalar path.

The contract (docs/developer_guide/columnar-window-engine.md): for any
input the scalar builder accepts, the columnar engine either produces a
byte-identical window (``window_to_plain`` on both sides compares the
full payload — steps, series, averages, medians, metrics, occupancy) or
raises ``ColumnarFallback`` so the caller reruns the scalar reference.
Edge cases exercised here are the ones that historically bend alignment
math: ragged suffixes, single-rank worlds, a phase missing on one rank
only, and a host/device clock flip mid-window.
"""

import random
from collections import deque

from traceml_tpu.aggregator.sqlite_writer import SQLiteWriter
from traceml_tpu.diagnostics.step_memory.api import (
    diagnose_columns,
    diagnose_rank_rows,
)
from traceml_tpu.reporting.snapshot_store import LiveSnapshotStore
from traceml_tpu.telemetry.envelope import SenderIdentity, build_telemetry_envelope
from traceml_tpu.utils import timing as T
from traceml_tpu.utils.columnar import (
    ColumnarFallback,
    MemoryColumns,
    StepTimeColumns,
    build_columnar_step_time_window,
    columnar_window_enabled,
    window_to_plain,
)
from traceml_tpu.utils.step_time_window import (
    PHASES,
    build_step_time_metrics,
    build_step_time_window,
)

import pytest


# -- row factories -------------------------------------------------------


def _step_row(step, rng, clock="device", missing_phases=()):
    step_ms = rng.uniform(40.0, 150.0)
    events = {
        T.STEP_TIME: {
            "cpu_ms": step_ms,
            "device_ms": step_ms * 0.97 if clock == "device" else None,
            "count": 1,
        }
    }
    for key, name in PHASES.items():
        if key in missing_phases:
            continue
        v = rng.uniform(0.0, 25.0)
        events[name] = {
            "cpu_ms": v,
            # input has no device side (host-only phase), like real rows
            "device_ms": v * 0.95 if key != "input" else None,
            "count": 1,
        }
    return {
        "step": step,
        "timestamp": 100.0 + step,
        "clock": clock,
        "late_markers": 0,
        "events": events,
    }


def _mem_row(step, sp, cur, lim=16_000_000_000, dev=0):
    return {
        "step": step,
        "timestamp": 10.0 + step,
        "device_id": dev,
        "device_kind": "tpu-v4",
        "current_bytes": cur,
        "peak_bytes": sp + 128,
        "step_peak_bytes": sp,
        "limit_bytes": lim,
    }


def _cols_for(rank_rows, cap=256):
    out = {}
    for rank, rows in rank_rows.items():
        c = StepTimeColumns(cap)
        for row in rows:
            c.append(row)
        out[rank] = c
    return out


def _assert_golden(rank_rows, max_steps, cap=256):
    scalar = build_step_time_window(rank_rows, max_steps=max_steps)
    columnar = build_columnar_step_time_window(_cols_for(rank_rows, cap), max_steps)
    assert window_to_plain(scalar) == window_to_plain(columnar)
    return columnar


# -- golden edge cases ---------------------------------------------------


def test_ragged_suffixes_identical():
    rng = random.Random(11)
    rank_rows = {
        r: [_step_row(s, rng) for s in range(rng.randint(0, 7), 48)]
        for r in range(8)
    }
    w = _assert_golden(rank_rows, max_steps=30)
    assert w is not None and w.n_steps == 30 and w.clock == "device"


def test_single_rank_world():
    rng = random.Random(12)
    rank_rows = {0: [_step_row(s, rng) for s in range(25)]}
    w = _assert_golden(rank_rows, max_steps=10)
    assert w.ranks == [0] and w.n_steps == 10


def test_phase_missing_on_one_rank_only():
    rng = random.Random(13)
    rank_rows = {
        0: [_step_row(s, rng, missing_phases=("collective",)) for s in range(20)],
        1: [_step_row(s, rng) for s in range(20)],
    }
    w = _assert_golden(rank_rows, max_steps=30)
    # the phase still counts as present (rank 1 reports it)
    assert "collective" in w.phases_present


def test_clock_flip_mid_window_selects_host():
    rng = random.Random(14)
    rank_rows = {
        0: [
            _step_row(s, rng, clock="device" if s < 20 else "host")
            for s in range(40)
        ],
        1: [_step_row(s, rng) for s in range(40)],
    }
    w = _assert_golden(rank_rows, max_steps=30)
    assert w.clock == "host"


def test_no_overlap_and_empty_inputs():
    rng = random.Random(15)
    # disjoint step ranges: no common suffix on either path
    rank_rows = {
        0: [_step_row(s, rng) for s in range(0, 10)],
        1: [_step_row(s, rng) for s in range(20, 30)],
    }
    assert build_step_time_window(rank_rows, max_steps=30) is None
    assert build_columnar_step_time_window(_cols_for(rank_rows), 30) is None
    assert build_columnar_step_time_window({}, 30) is None
    # satellite guard: metrics over zero ranks must not call median([])
    assert build_step_time_metrics({}) == {}


def test_ring_eviction_matches_deque_maxlen():
    rng = random.Random(16)
    cap = 16
    cols = StepTimeColumns(cap)
    rows = deque(maxlen=cap)
    for s in range(3 * cap + 5):  # force several compactions
        row = _step_row(s, rng)
        cols.append(row)
        rows.append(row)
        scalar = build_step_time_window({0: list(rows)}, max_steps=12)
        columnar = build_columnar_step_time_window({0: cols}, 12)
        assert window_to_plain(scalar) == window_to_plain(columnar)
    assert len(cols) == cap


# -- fallback flagging ---------------------------------------------------


def test_duplicate_step_flags_fallback():
    rng = random.Random(17)
    cols = StepTimeColumns(32)
    cols.append(_step_row(5, rng))
    cols.append(_step_row(5, rng))  # duplicate
    assert not cols.columnar_ok
    with pytest.raises(ColumnarFallback):
        build_columnar_step_time_window({0: cols}, 10)


def test_out_of_order_and_malformed_rows_flag_fallback():
    rng = random.Random(18)
    for bad in (
        [_step_row(5, rng), _step_row(3, rng)],  # out of order
        [{"step": None, "events": {}}],  # no step id
        [{"step": 1, "events": {T.STEP_TIME: {"cpu_ms": "NaN-ish"}}}],
    ):
        cols = StepTimeColumns(32)
        for row in bad:
            cols.append(row)
        assert not cols.columnar_ok
        with pytest.raises(ColumnarFallback):
            build_columnar_step_time_window({0: cols}, 10)


def test_memory_negative_or_huge_values_flag_fallback():
    good = MemoryColumns(8)
    good.append(_mem_row(1, 100, 90))
    assert good.columnar_ok
    for row in (
        _mem_row(1, -5, 90),  # negative would alias the NULL sentinel
        _mem_row(1, 2**60, 90),  # beyond float64-exact integers
        dict(_mem_row(1, 100, 90), device_id=None),
    ):
        cols = MemoryColumns(8)
        cols.append(row)
        assert not cols.columnar_ok


# -- memory diagnosis equality -------------------------------------------


def _diag_plain(result):
    import dataclasses

    return (
        dataclasses.asdict(result.diagnosis),
        [dataclasses.asdict(i) for i in result.issues],
    )


def _mem_cols_for(rank_rows, cap=256):
    out = {}
    for rank, rows in rank_rows.items():
        c = MemoryColumns(cap)
        for row in rows:
            c.append(row)
        out[rank] = c
    return out


@pytest.mark.parametrize(
    "scenario",
    ["healthy", "pressure", "imbalance", "multi_device", "null_fields"],
)
def test_memory_diagnosis_rows_vs_columns(scenario):
    G = 1_000_000_000
    if scenario == "healthy":
        rank_rows = {
            r: [_mem_row(s, 8 * G, 7 * G) for s in range(30)] for r in range(3)
        }
    elif scenario == "pressure":
        rank_rows = {
            0: [_mem_row(s, int(15.6 * G), 15 * G) for s in range(30)],
            1: [_mem_row(s, 9 * G, 8 * G) for s in range(30)],
        }
    elif scenario == "imbalance":
        rank_rows = {
            0: [_mem_row(s, 14 * G, 13 * G) for s in range(30)],
            1: [_mem_row(s, 4 * G, 3 * G) for s in range(30)],
        }
    elif scenario == "multi_device":
        rows = [_mem_row(s, 8 * G, 7 * G, dev=0) for s in range(30)]
        rows += [_mem_row(s, 6 * G, 5 * G, dev=1) for s in range(30)]
        rows.sort(key=lambda r: r["step"])
        rank_rows = {0: rows, 1: [_mem_row(s, 8 * G, 7 * G) for s in range(30)]}
    else:  # null_fields: Nones scattered through optional columns
        rank_rows = {
            0: [
                dict(
                    _mem_row(s, 9 * G, 7 * G),
                    limit_bytes=None,
                    step_peak_bytes=None if s % 3 else 9 * G,
                )
                for s in range(20)
            ]
        }
    a = diagnose_rank_rows(rank_rows)
    b = diagnose_columns(_mem_cols_for(rank_rows))
    assert _diag_plain(a) == _diag_plain(b)


# -- store-level integration ---------------------------------------------


def _ident(rank=0, node=0, world=2):
    return SenderIdentity(
        session_id="s1",
        global_rank=rank,
        local_rank=rank % 4,
        world_size=world,
        node_rank=node,
        hostname=f"host-{node}",
        pid=100 + rank,
    )


def _ingest_step_time(w, rank, rows):
    w.ingest(
        build_telemetry_envelope("step_time", {"step_time": rows}, _ident(rank))
    )


def test_store_columnar_window_matches_scalar_rows(tmp_path):
    rng = random.Random(19)
    db = tmp_path / "t.sqlite"
    w = SQLiteWriter(db)
    w.start()
    store = LiveSnapshotStore(db, window_steps=40)
    for rank in (0, 1):
        _ingest_step_time(
            w, rank, [_step_row(s, rng) for s in range(1, 31)]
        )
    assert w.force_flush()
    store.refresh()

    win = store.build_step_time_window(max_steps=20)
    assert getattr(win, "col", None) is not None  # columnar path taken
    scalar = build_step_time_window(store.step_time_rows(), max_steps=20)
    assert window_to_plain(win) == window_to_plain(scalar)

    # incremental append advances the window identically
    for rank in (0, 1):
        _ingest_step_time(w, rank, [_step_row(s, rng) for s in range(31, 41)])
    assert w.force_flush()
    store.refresh()
    win2 = store.build_step_time_window(max_steps=20)
    scalar2 = build_step_time_window(store.step_time_rows(), max_steps=20)
    assert window_to_plain(win2) == window_to_plain(scalar2)
    assert win2.steps[-1] == 40

    assert store.latest_step_time_ts() == pytest.approx(140.0)
    assert store.has_step_time_rows()
    assert w.finalize()
    store.close()


def test_store_trim_keeps_ring_in_lockstep(tmp_path):
    rng = random.Random(20)
    db = tmp_path / "t.sqlite"
    w = SQLiteWriter(db, summary_window_rows=10, retention_factor=1.5)
    w.start()
    store = LiveSnapshotStore(db, window_steps=50)
    for start in (1, 26, 51, 76):
        for rank in (0, 1):
            _ingest_step_time(
                w, rank, [_step_row(s, rng) for s in range(start, start + 25)]
            )
        assert w.force_flush()
        store.refresh()
    # finalize runs the retention prune; refresh must evict the ring
    # prefix in lockstep with the row deques
    assert w.finalize()
    assert store.refresh() is True
    win = store.build_step_time_window(max_steps=50)
    scalar = build_step_time_window(store.step_time_rows(), max_steps=50)
    assert window_to_plain(win) == window_to_plain(scalar)
    assert win.steps[0] == 86 and win.steps[-1] == 100
    store.close()


def test_env_kill_switch_forces_scalar(tmp_path, monkeypatch):
    rng = random.Random(21)
    db = tmp_path / "t.sqlite"
    w = SQLiteWriter(db)
    w.start()
    store = LiveSnapshotStore(db, window_steps=40)
    _ingest_step_time(w, 0, [_step_row(s, rng) for s in range(1, 11)])
    assert w.force_flush()
    store.refresh()

    monkeypatch.setenv("TRACEML_COLUMNAR_WINDOW", "0")
    assert not columnar_window_enabled()
    win = store.build_step_time_window(max_steps=20)
    assert getattr(win, "col", None) is None  # plain scalar window
    assert store.step_memory_columns() is None

    monkeypatch.setenv("TRACEML_COLUMNAR_WINDOW", "1")
    win2 = store.build_step_time_window(max_steps=20)
    assert getattr(win2, "col", None) is not None
    assert window_to_plain(win) == window_to_plain(win2)
    assert w.finalize()
    store.close()
