"""Step-memory sampler
(reference: src/traceml_ai/samplers/step_memory_sampler.py:12-65).

Drains the step-memory queue verbatim — rows were fully formed by
StepMemoryTracker at the step edges; no aggregation here.
"""

from __future__ import annotations

from traceml_tpu.samplers.base_sampler import BaseSampler
from traceml_tpu.utils.timing import drain_step_memory_rows

TABLE = "step_memory"


class StepMemorySampler(BaseSampler):
    name = "step_memory"

    def _sample(self) -> None:
        rows = drain_step_memory_rows()
        if rows:
            self.db.add_records(TABLE, rows)
