"""Core abstractions shared by every layer (reference: src/traceml_ai/core/)."""
