"""CLI launcher (reference: src/traceml_ai/launcher/)."""
