"""SQLite read paths for reporting
(reference: reporting/sections/*/loader.py, e.g. step_time/loader.py:41-90
pulls bounded events_json rows per global rank).

These are the ONE-SHOT readers (final report, compare, ad-hoc view
commands).  The live tick path reads through
:class:`~traceml_tpu.reporting.snapshot_store.LiveSnapshotStore`
instead, which keeps cursors and decodes incrementally; the loaders
here stay full-load but single-query — per-rank bounding happens via a
``ROW_NUMBER() OVER (PARTITION BY global_rank …)`` window instead of
the former ``SELECT DISTINCT global_rank`` + one query per rank (N+1).

Every loader accepts an optional ``conn`` to reuse a shared read
connection (e.g. the snapshot store's) instead of opening a fresh
``sqlite3.connect`` per call.
"""

from __future__ import annotations

import json
import sqlite3
from contextlib import contextmanager
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple


def _connect_ro(db_path: Path) -> sqlite3.Connection:
    conn = sqlite3.connect(f"file:{db_path}?mode=ro", uri=True)
    conn.row_factory = sqlite3.Row
    return conn


@contextmanager
def _reading(db_path: Path, conn: Optional[sqlite3.Connection] = None):
    """Yield a usable read connection: the caller-provided shared one
    (left open) or a fresh one (closed on exit — the seed's
    ``with sqlite3.connect(...)`` only committed, it never closed)."""
    if conn is not None:
        yield conn
        return
    fresh = _connect_ro(db_path)
    try:
        yield fresh
    finally:
        fresh.close()


def _table_exists(conn: sqlite3.Connection, table: str) -> bool:
    row = conn.execute(
        "SELECT name FROM sqlite_master WHERE type='table' AND name=?", (table,)
    ).fetchone()
    return row is not None


def load_stitched_history(
    db_path: Path,
    conn: Optional[sqlite3.Connection] = None,
) -> Dict[str, Any]:
    """One-shot resolution-aware full-run read (``reporting/tiers.py``):
    per-source stitched rank-grain series — raw rows where they
    survive, 10s buckets beyond the watermark, 1m beyond the 10s
    horizon.  ``{}`` when the DB holds no rollups (short runs, or
    ``TRACEML_ROLLUP=0``)."""
    from traceml_tpu.reporting import tiers

    with _reading(db_path, conn) as c:
        try:
            return tiers.stitched_overview(c)
        except sqlite3.Error:
            return {}


def load_step_time_rows(
    db_path: Path,
    max_steps_per_rank: int = 600,
    conn: Optional[sqlite3.Connection] = None,
) -> Dict[int, List[Dict[str, Any]]]:
    """global_rank → step rows (events decoded), ascending by step."""
    out: Dict[int, List[Dict[str, Any]]] = {}
    with _reading(db_path, conn) as c:
        if not _table_exists(c, "step_time_samples"):
            return out
        rows = c.execute(
            "SELECT global_rank, step, timestamp, clock, late_markers,"
            " events_json FROM ("
            "  SELECT global_rank, step, timestamp, clock, late_markers,"
            "   events_json, ROW_NUMBER() OVER ("
            "    PARTITION BY global_rank ORDER BY step DESC, id DESC"
            "   ) AS rn FROM step_time_samples"
            " ) WHERE rn <= ? ORDER BY global_rank, step, rn DESC",
            (int(max_steps_per_rank),),
        ).fetchall()
    for r in rows:
        try:
            events = json.loads(r["events_json"] or "{}")
        except ValueError:
            events = {}
        out.setdefault(int(r["global_rank"]), []).append(
            {
                "step": r["step"],
                "timestamp": r["timestamp"],
                "clock": r["clock"],
                "late_markers": r["late_markers"],
                "events": events,
            }
        )
    return out


def load_step_memory_rows(
    db_path: Path,
    max_rows_per_rank: int = 20000,
    conn: Optional[sqlite3.Connection] = None,
) -> Dict[int, List[Dict[str, Any]]]:
    out: Dict[int, List[Dict[str, Any]]] = {}
    with _reading(db_path, conn) as c:
        if not _table_exists(c, "step_memory_samples"):
            return out
        rows = c.execute(
            "SELECT global_rank, step, timestamp, device_id, device_kind,"
            " current_bytes, peak_bytes, step_peak_bytes, limit_bytes FROM ("
            "  SELECT global_rank, step, timestamp, device_id, device_kind,"
            "   current_bytes, peak_bytes, step_peak_bytes, limit_bytes,"
            "   ROW_NUMBER() OVER ("
            "    PARTITION BY global_rank ORDER BY step DESC, id DESC"
            "   ) AS rn FROM step_memory_samples"
            " ) WHERE rn <= ? ORDER BY global_rank, step, rn DESC",
            (int(max_rows_per_rank),),
        ).fetchall()
    for r in rows:
        rank = int(r["global_rank"])
        row = dict(r)
        del row["global_rank"]
        out.setdefault(rank, []).append(row)
    return out


def load_system_rows(
    db_path: Path,
    max_rows: int = 2000,
    conn: Optional[sqlite3.Connection] = None,
) -> Tuple[Dict[int, List[Dict[str, Any]]], Dict[tuple, List[Dict[str, Any]]]]:
    host: Dict[int, List[Dict[str, Any]]] = {}
    devices: Dict[tuple, List[Dict[str, Any]]] = {}
    with _reading(db_path, conn) as c:
        if _table_exists(c, "system_samples"):
            for r in c.execute(
                "SELECT * FROM (SELECT * FROM system_samples ORDER BY id DESC"
                f" LIMIT {int(max_rows)}) ORDER BY id ASC"
            ):
                host.setdefault(int(r["node_rank"]), []).append(dict(r))
        if _table_exists(c, "system_device_samples"):
            for r in c.execute(
                "SELECT * FROM (SELECT * FROM system_device_samples ORDER BY id"
                f" DESC LIMIT {int(max_rows)}) ORDER BY id ASC"
            ):
                devices.setdefault(
                    (int(r["node_rank"]), int(r["device_id"] or 0)), []
                ).append(dict(r))
    return host, devices


def load_process_rows(
    db_path: Path,
    max_rows: int = 2000,
    conn: Optional[sqlite3.Connection] = None,
) -> Tuple[Dict[int, List[Dict[str, Any]]], Dict[tuple, List[Dict[str, Any]]]]:
    procs: Dict[int, List[Dict[str, Any]]] = {}
    devices: Dict[tuple, List[Dict[str, Any]]] = {}
    with _reading(db_path, conn) as c:
        if _table_exists(c, "process_samples"):
            for r in c.execute(
                "SELECT * FROM (SELECT * FROM process_samples ORDER BY id DESC"
                f" LIMIT {int(max_rows)}) ORDER BY id ASC"
            ):
                procs.setdefault(int(r["global_rank"]), []).append(dict(r))
        if _table_exists(c, "process_device_samples"):
            for r in c.execute(
                "SELECT * FROM (SELECT * FROM process_device_samples ORDER BY"
                f" id DESC LIMIT {int(max_rows)}) ORDER BY id ASC"
            ):
                devices.setdefault(
                    (int(r["global_rank"]), int(r["device_id"] or 0)), []
                ).append(dict(r))
    return procs, devices


def load_topology(
    db_path: Path, conn: Optional[sqlite3.Connection] = None
) -> Dict[str, Any]:
    """Run topology from identity columns (reference: reporting/topology.py:63)."""
    with _reading(db_path, conn) as c:
        if not _table_exists(c, "step_time_samples"):
            tables = [
                t
                for t in ("process_samples", "system_samples")
                if _table_exists(c, t)
            ]
            if not tables:
                return {"mode": "unknown", "world_size": 0, "nodes": 0}
            table = tables[0]
        else:
            table = "step_time_samples"
        rows = c.execute(
            f"SELECT DISTINCT global_rank, node_rank, hostname, world_size"
            f" FROM {table}"
        ).fetchall()
    ranks = sorted({int(r["global_rank"]) for r in rows})
    nodes = sorted({int(r["node_rank"]) for r in rows})
    world = max((int(r["world_size"]) for r in rows), default=len(ranks))
    return {
        "mode": "multi_node" if len(nodes) > 1 else "single_node",
        "world_size": max(world, len(ranks)),
        "ranks_seen": ranks,
        "nodes": len(nodes),
        "hostnames": sorted({str(r["hostname"]) for r in rows}),
    }


def load_mesh_topology(
    db_path: Path, conn: Optional[sqlite3.Connection] = None
):
    """The merged mesh topology from the one-shot ``mesh_topology``
    control rows, or None for pre-topology session DBs (the table never
    existed) and sessions that captured no mesh.  Keep-latest per rank:
    ascending-id scan, later rows overwrite."""
    from traceml_tpu.utils.topology import topology_from_rank_rows

    with _reading(db_path, conn) as c:
        if not _table_exists(c, "mesh_topology"):
            return None
        latest: Dict[int, Dict[str, Any]] = {}
        for r in c.execute(
            "SELECT global_rank, node_rank, hostname, source,"
            " axes_json, coords_json FROM mesh_topology ORDER BY id ASC"
        ):
            latest[int(r["global_rank"])] = dict(r)
    if not latest:
        return None
    return topology_from_rank_rows([latest[r] for r in sorted(latest)])


def load_rank_identities(
    db_path: Path, conn: Optional[sqlite3.Connection] = None
) -> Dict[int, Dict[str, Any]]:
    """global_rank → identity block (reference contract:
    ``groups.rows[*].identity`` — SCHEMA.md field rules).  Pulled from
    whichever projection tables exist; across tables the row with the
    newest telemetry timestamp wins, so a rank that moved hosts
    (restart/resume) reports its current placement even if its newest
    rows live in a different sampler's table."""
    identity: Dict[int, Dict[str, Any]] = {}
    newest: Dict[int, float] = {}
    with _reading(db_path, conn) as c:
        for table in ("step_time_samples", "process_samples",
                      "step_memory_samples"):
            if not _table_exists(c, table):
                continue
            # SQLite bare-column semantics: with MAX(id) the other
            # selected columns come from that same max-id row
            rows = c.execute(
                f"SELECT global_rank, local_rank, node_rank, hostname, pid,"
                f" world_size, local_world_size, timestamp, MAX(id)"
                f" FROM {table} GROUP BY global_rank"
            ).fetchall()
            for r in rows:
                rank = int(r["global_rank"])
                ts = float(r["timestamp"] or 0.0)
                if rank in identity and ts <= newest[rank]:
                    continue
                newest[rank] = ts
                identity[rank] = {
                    "global_rank": rank,
                    "local_rank": r["local_rank"],
                    "node_rank": r["node_rank"],
                    "hostname": r["hostname"],
                    "pid": r["pid"],
                    "world_size": r["world_size"],
                    "local_world_size": r["local_world_size"],
                }
    return identity


def load_model_stats(
    db_path: Path,
    recent_rows: int = 64,
    conn: Optional[sqlite3.Connection] = None,
) -> Dict[int, Dict[str, Any]]:
    """global_rank → model-FLOPs declaration (the MFU numerator + the
    chip peak captured at estimation time).

    ``flops_per_step`` is the MEDIAN over the rank's recent
    declarations: under per-step ``set_step_flops`` with variable
    sequence lengths the declarations vary per batch, and pairing only
    the last one with window-median step times would skew MFU by the
    final batch's size.  Source/device_kind/peak come from the newest
    row (a device_kind correction should win immediately)."""
    import statistics

    out: Dict[int, Dict[str, Any]] = {}
    per_rank_flops: Dict[int, List[float]] = {}
    with _reading(db_path, conn) as c:
        if not _table_exists(c, "model_stats_samples"):
            return out
        try:
            rows = c.execute(
                "SELECT * FROM (SELECT global_rank, flops_per_step,"
                " flops_source, device_kind, peak_flops, device_count,"
                " tokens_per_step, id"
                " FROM model_stats_samples"
                f" ORDER BY id DESC LIMIT {int(recent_rows)}) ORDER BY id ASC"
            ).fetchall()
        except sqlite3.OperationalError:
            try:
                # archived sessions without the tokens column
                rows = c.execute(
                    "SELECT *, NULL AS tokens_per_step FROM (SELECT"
                    " global_rank, flops_per_step, flops_source,"
                    " device_kind, peak_flops, device_count, id"
                    " FROM model_stats_samples"
                    f" ORDER BY id DESC LIMIT {int(recent_rows)})"
                    " ORDER BY id ASC"
                ).fetchall()
            except sqlite3.OperationalError:
                # …or before the device_count column either
                rows = c.execute(
                    "SELECT *, NULL AS device_count, NULL AS tokens_per_step"
                    " FROM (SELECT global_rank, flops_per_step,"
                    " flops_source, device_kind, peak_flops, id"
                    " FROM model_stats_samples"
                    f" ORDER BY id DESC LIMIT {int(recent_rows)})"
                    " ORDER BY id ASC"
                ).fetchall()
    per_rank_tokens: Dict[int, List[float]] = {}
    for r in rows:
        rank = int(r["global_rank"])
        if r["flops_per_step"]:
            per_rank_flops.setdefault(rank, []).append(float(r["flops_per_step"]))
        if r["tokens_per_step"]:
            per_rank_tokens.setdefault(rank, []).append(
                float(r["tokens_per_step"])
            )
        out[rank] = {  # ascending order → the newest row wins
            "flops_source": r["flops_source"],
            "device_kind": r["device_kind"],
            "peak_flops": r["peak_flops"],
            "device_count": r["device_count"],
        }
    for rank, vals in per_rank_flops.items():
        out[rank]["flops_per_step"] = statistics.median(vals)
    for rank, vals in per_rank_tokens.items():
        out[rank]["tokens_per_step"] = statistics.median(vals)
    return {
        r: v for r, v in out.items()
        if v.get("flops_per_step") or v.get("tokens_per_step")
    }


def load_stdout_tail(
    db_path: Path, n: int = 12, conn: Optional[sqlite3.Connection] = None
) -> List[Tuple[str, str]]:
    """Last n (stream, line) pairs from the stdout projection."""
    with _reading(db_path, conn) as c:
        if not _table_exists(c, "stdout_samples"):
            return []
        rows = c.execute(
            "SELECT stream, line FROM stdout_samples ORDER BY id DESC LIMIT ?",
            (int(n),),
        ).fetchall()
    return [(r["stream"], r["line"]) for r in reversed(rows)]


# ingest_stats.json is rewritten atomically every few seconds by the
# aggregator loop; cache on (mtime, size) so live pollers don't re-parse
# an unchanged file every tick.
_INGEST_STATS_CACHE: Dict[str, Tuple[Tuple[float, int], Dict[str, Any]]] = {}


def load_ingest_stats(session_dir: Path) -> Dict[str, Any]:
    """Aggregator self-metrics (queue depths/HWMs, per-domain shed
    counts, group-commit and prune latency) from ``ingest_stats.json``.
    Returns ``{}`` when the file is missing or unreadable."""
    from traceml_tpu.utils.atomic_io import read_json

    path = Path(session_dir) / "ingest_stats.json"
    try:
        st = path.stat()
    except OSError:
        return {}
    stamp = (st.st_mtime, st.st_size)
    cached = _INGEST_STATS_CACHE.get(str(path))
    if cached is not None and cached[0] == stamp:
        return cached[1]
    data = read_json(path)
    if not isinstance(data, dict):
        return {}
    _INGEST_STATS_CACHE[str(path)] = (stamp, data)
    return data


# rank_status.json shares the ingest-stats write cadence; same
# (mtime, size) cache so the live web poller stays O(1) per tick.
_RANK_STATUS_CACHE: Dict[str, Tuple[Tuple[float, int], Dict[str, Any]]] = {}


def load_rank_status(session_dir: Path) -> Dict[str, Any]:
    """Rank liveness snapshot (per-rank ACTIVE/STALE/LOST/FINISHED,
    last-seen, thresholds) from ``rank_status.json`` — states as
    written by the aggregator (aggregator/liveness.py).  Returns ``{}``
    when the file is missing or unreadable."""
    from traceml_tpu.utils.atomic_io import read_json

    path = Path(session_dir) / "rank_status.json"
    try:
        st = path.stat()
    except OSError:
        return {}
    stamp = (st.st_mtime, st.st_size)
    cached = _RANK_STATUS_CACHE.get(str(path))
    if cached is not None and cached[0] == stamp:
        return cached[1]
    data = read_json(path)
    if not isinstance(data, dict):
        return {}
    _RANK_STATUS_CACHE[str(path)] = (stamp, data)
    return data


# regressions.json is written once at finalize (analytics/baselines.py)
# but polled live by the dashboard meta fragment; same (mtime, size)
# cache as the other file-backed meta inputs.
_REGRESSIONS_CACHE: Dict[str, Tuple[Tuple[float, int], Dict[str, Any]]] = {}


def load_regressions(session_dir: Path) -> Dict[str, Any]:
    """Cross-run regression verdict (``regressions.json``: status,
    fingerprint, per-metric checks against the baseline bands, issues)
    as written at finalize.  Returns ``{}`` when the file is missing or
    unreadable — pre-baseline sessions have no ``regressions`` key."""
    from traceml_tpu.utils.atomic_io import read_json

    path = Path(session_dir) / "regressions.json"
    try:
        st = path.stat()
    except OSError:
        return {}
    stamp = (st.st_mtime, st.st_size)
    cached = _REGRESSIONS_CACHE.get(str(path))
    if cached is not None and cached[0] == stamp:
        return cached[1]
    data = read_json(path)
    if not isinstance(data, dict):
        return {}
    _REGRESSIONS_CACHE[str(path)] = (stamp, data)
    return data
