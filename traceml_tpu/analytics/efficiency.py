"""Achieved-FLOP/s + MFU computation — THE shared formula.

One implementation consumed by both the final summary
(reporting/final.py) and the live views (renderers/views.py) so the
same-named ``efficiency`` block can never drift between surfaces.
"""

from __future__ import annotations

import statistics
from typing import Any, Dict, Mapping, Optional


def _rank_key(stats: Mapping[int, Mapping[str, Any]], rank: Any):
    """Stats key for a per-step-ms rank id (int keys in stats, str or
    int in step-ms maps), or None when that rank never declared."""
    try:
        r = int(rank)
    except (TypeError, ValueError):
        return None
    return r if r in stats else None


def build_efficiency(
    stats: Optional[Mapping[int, Mapping[str, Any]]],
    per_rank_step_ms: Mapping[Any, Optional[float]],
) -> Optional[Dict[str, Any]]:
    """The ``efficiency`` block (SCHEMA.md) or None.

    ``stats`` is loaders.load_model_stats output: per rank, the MEDIAN
    ``flops_per_step`` over recent declarations (robust to the
    per-step ``set_step_flops`` pattern under variable sequence
    lengths — pairing only the LAST declaration with window-median
    step times would skew MFU by the last batch's size) plus the
    latest source/device_kind/peak/device_count.  ``per_rank_step_ms``
    maps rank → representative step duration (steady-state median when
    available).

    Each rank's achieved FLOP/s uses that rank's OWN declaration
    (pipeline stages and mixed chip generations declare different
    values), falling back to the first declaring rank for ranks without
    one.  The MFU denominator per rank is chip peak × the rank's
    addressable-device count: lowered cost_analysis() FLOPs are for the
    whole pre-partition program, so a process driving N chips must be
    judged against N chips' peak.
    """
    if not stats:
        return None
    ms0 = next(iter(stats.values()))
    if not ms0.get("flops_per_step"):
        # the fallback declaration is unusable; require per-rank ones
        ms0 = next(
            (
                v for v in stats.values()
                if v.get("flops_per_step") or v.get("tokens_per_step")
            ),
            None,
        )
        if ms0 is None:
            return None

    achieved: Dict[str, float] = {}
    mfu: Dict[str, float] = {}
    tokens_ps: Dict[str, float] = {}
    for rank, step_ms in per_rank_step_ms.items():
        if not step_ms:
            continue
        key = _rank_key(stats, rank)
        decl = stats[key] if key is not None else ms0
        tokens = decl.get("tokens_per_step") or ms0.get("tokens_per_step")
        if tokens:
            tokens_ps[str(rank)] = tokens / (step_ms / 1000.0)
        flops = decl.get("flops_per_step") or ms0.get("flops_per_step")
        if not flops:
            continue
        tflops = flops / (step_ms / 1000.0) / 1e12
        achieved[str(rank)] = tflops
        peak = decl.get("peak_flops")
        if peak:
            n_dev = int(decl.get("device_count") or 1)
            mfu[str(rank)] = tflops * 1e12 / (peak * max(n_dev, 1))
    if not achieved and not tokens_ps:
        return None
    # report the numerator AND its metadata from the same declaration:
    # with mixed declarations (one rank flops-only, another tokens-only)
    # ms0 alone would report null for a numerator whose per-rank rate IS
    # populated (review r4) — and splitting numerator/metadata across
    # declarations could pair a real FLOPs value with another rank's
    # source/chip/peak (advisor r4)
    flops_decl = next(
        (v for v in stats.values() if v.get("flops_per_step")), ms0
    )
    tokens0 = next(
        (v["tokens_per_step"] for v in stats.values()
         if v.get("tokens_per_step")),
        None,
    )
    peak0 = flops_decl.get("peak_flops")
    return {
        "flops_per_step": flops_decl.get("flops_per_step"),
        "flops_source": flops_decl.get("flops_source"),
        "device_kind": flops_decl.get("device_kind"),
        "device_count": flops_decl.get("device_count"),
        "peak_tflops": (peak0 / 1e12) if peak0 else None,
        "achieved_tflops_by_rank": {r: round(v, 3) for r, v in achieved.items()},
        "achieved_tflops_median": (
            round(statistics.median(achieved.values()), 3)
            if achieved else None
        ),
        "mfu_median": statistics.median(mfu.values()) if mfu else None,
        # tokens/s (set_step_tokens): per-step declarations × the same
        # steady-state step time the FLOPs path uses
        "tokens_per_step": tokens0,
        "tokens_per_sec_median": (
            round(statistics.median(tokens_ps.values()), 1)
            if tokens_ps else None
        ),
    }
