"""Bounded in-memory table store
(reference: src/traceml_ai/database/database.py:7-186).

Each sampler owns one ``Database``: a dict of named tables, each a
``deque(maxlen=N)`` of row dicts plus a **monotonic append counter** so an
incremental sender can detect new rows in O(1) without scanning
(rows may have been evicted from the left; the counter never decreases).
"""

from __future__ import annotations

import threading
from collections import deque
from itertools import islice
from typing import Any, Deque, Dict, List, Optional

DEFAULT_MAX_ROWS = 3000


class _Table:
    __slots__ = ("rows", "appended")

    def __init__(self, maxlen: int) -> None:
        self.rows: Deque[Dict[str, Any]] = deque(maxlen=maxlen)
        self.appended: int = 0  # total rows ever appended


class Database:
    def __init__(self, max_rows_per_table: int = DEFAULT_MAX_ROWS) -> None:
        self._max_rows = int(max_rows_per_table)
        self._tables: Dict[str, _Table] = {}
        self._lock = threading.Lock()

    def add_record(self, table: str, row: Dict[str, Any]) -> None:
        with self._lock:
            t = self._tables.get(table)
            if t is None:
                t = self._tables[table] = _Table(self._max_rows)
            t.rows.append(row)
            t.appended += 1

    def add_records(self, table: str, rows: List[Dict[str, Any]]) -> None:
        if not rows:
            return
        with self._lock:
            t = self._tables.get(table)
            if t is None:
                t = self._tables[table] = _Table(self._max_rows)
            t.rows.extend(rows)
            t.appended += len(rows)

    def table_names(self) -> List[str]:
        with self._lock:
            return list(self._tables.keys())

    def append_count(self, table: str) -> int:
        with self._lock:
            t = self._tables.get(table)
            return t.appended if t else 0

    def tail(self, table: str, n: Optional[int] = None) -> List[Dict[str, Any]]:
        with self._lock:
            t = self._tables.get(table)
            if t is None:
                return []
            rows = list(t.rows)
        return rows if n is None else rows[-n:]

    def rows_since(self, table: str, cursor: int) -> List[Dict[str, Any]]:
        """Rows appended after append-count ``cursor``.

        If more rows were appended than the table retains, the evicted ones
        are silently lost (bounded-memory contract); callers get what is
        still buffered.
        """
        rows, _ = self.collect_since(table, cursor)
        return rows

    def collect_since(self, table: str, cursor: int):
        """Atomic (rows, new_cursor) snapshot.

        Senders MUST use this (not rows_since + append_count) so a row
        appended between the two reads cannot be skipped.
        """
        with self._lock:
            t = self._tables.get(table)
            if t is None:
                return [], cursor
            new = t.appended - cursor
            new_cursor = t.appended
            if new <= 0:
                return [], new_cursor
            take = min(new, len(t.rows))
            # Slice from the tail via reversed() so the lock-held work is
            # O(new rows), not O(retained rows) — a sender collecting a
            # handful of fresh rows must not copy the whole deque.
            rows = list(islice(reversed(t.rows), take))
        rows.reverse()
        return rows, new_cursor

    def clear(self) -> None:
        with self._lock:
            self._tables.clear()
