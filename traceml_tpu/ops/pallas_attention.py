"""Flash attention (causal) as a pallas TPU kernel.

Why: the reference jnp path materializes the (S, S) score matrix per
head — at S=8k, bf16, that is 128 MiB per (batch, head) of pure HBM
traffic.  The flash pattern streams K/V blocks through VMEM with an
online softmax, keeping the working set at O(BLK_Q × S/BLK_K) and the
matmuls MXU-shaped.

Kernel layout (one program per (batch*head, q-block)):

* q block  (BLK_Q, D)  resident in VMEM,
* K and V  (S, D)      resident in VMEM (fits comfortably: 2×S×D×2 B —
  8k×128 bf16 is 2 MiB each against ~16 MiB VMEM),
* ``fori_loop`` over k-blocks with a DYNAMIC trip count — causality
  bounds the loop at the q block's diagonal, so the lower triangle does
  ~half the work instead of masking it away,
* online softmax in f32 (m, l, acc carries), one write of the output
  block at the end.

On non-TPU backends the kernel runs in interpret mode (CI numerics);
``ops.attention.causal_attention`` handles selection and fail-open.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, blk_q: int, blk_k: int, scale: float):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale  # (BLK_Q, D)
    d = q.shape[-1]
    q_start = qi * blk_q

    m = jnp.full((blk_q, 1), _NEG_INF, jnp.float32)
    l = jnp.zeros((blk_q, 1), jnp.float32)
    acc = jnp.zeros((blk_q, d), jnp.float32)

    # causal bound: last k block that any row of this q block can see
    n_kv = (q_start + blk_q + blk_k - 1) // blk_k

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(j * blk_k, blk_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * blk_k, blk_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (BLK_Q, BLK_K)
        q_ids = q_start + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)
        k_ids = j * blk_k + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
        s = jnp.where(q_ids >= k_ids, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + p.sum(axis=1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return m_new, l, acc

    m, l, acc = jax.lax.fori_loop(0, n_kv, body, (m, l, acc))
    o_ref[0] = (acc / l).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("blk_q", "blk_k", "interpret"))
def _flash_bhsd(q, k, v, blk_q: int, blk_k: int, interpret: bool):
    """q,k,v: (BH, S, D) → (BH, S, D)."""
    BH, S, D = q.shape
    scale = 1.0 / (D ** 0.5)
    grid = (BH, S // blk_q)
    kernel = functools.partial(
        _flash_kernel, blk_q=blk_q, blk_k=blk_k, scale=scale
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, blk_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, blk_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, S, D), q.dtype),
        interpret=interpret,
    )(q, k, v)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    blk_q: int = 128,
    blk_k: int = 128,
) -> jnp.ndarray:
    """Causal flash attention; q,k,v: (B, S, H, D) → (B, S, H, D).

    Constraints (caller falls back to the reference path otherwise):
    S divisible by the block sizes; same S for q and k/v (self-attention).
    """
    B, S, H, D = q.shape
    blk_q = min(blk_q, S)
    blk_k = min(blk_k, S)
    if S % blk_q or S % blk_k:
        raise ValueError(f"S={S} not divisible by blocks ({blk_q},{blk_k})")
    interpret = jax.default_backend() != "tpu"

    def to_bhsd(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, S, D)

    out = _flash_bhsd(to_bhsd(q), to_bhsd(k), to_bhsd(v), blk_q, blk_k, interpret)
    return out.reshape(B, H, S, D).transpose(0, 2, 1, 3)
