"""JSON-able live payload for the browser dashboard."""

from __future__ import annotations

import time
from pathlib import Path
from typing import Any, Dict

from traceml_tpu.diagnostics.step_time.api import diagnose_rank_rows
from traceml_tpu.reporting import loaders
from traceml_tpu.utils.step_time_window import (
    RESIDUAL_KEY,
    STEP_KEY,
    build_step_time_window,
)


def build_web_payload(db_path: Path, session: str, window_steps: int = 150) -> Dict[str, Any]:
    out: Dict[str, Any] = {
        "session": session,
        "ts": time.time(),
        "step_time": None,
        "memory": [],
        "system": [],
        "stdout": [],
        "diagnosis": None,
    }
    db_path = Path(db_path)
    if not db_path.exists():
        return out
    try:
        rank_rows = loaders.load_step_time_rows(db_path, max_steps_per_rank=window_steps)
        window = build_step_time_window(rank_rows, max_steps=window_steps)
        if window is not None:
            phases = {}
            for key in [STEP_KEY] + window.phases_present + [RESIDUAL_KEY]:
                m = window.metric(key)
                if m is None:
                    continue
                phases[key] = {
                    "median_ms": m.median_ms,
                    "worst_ms": m.worst_ms,
                    "worst_rank": m.worst_rank,
                    "skew_pct": m.skew_pct,
                    "share": window.share_of_step(key),
                }
            # per-rank step series for the sparkline
            series = {
                str(r): w.series[STEP_KEY][-60:]
                for r, w in window.rank_windows.items()
            }
            out["step_time"] = {
                "clock": window.clock,
                "n_steps": window.n_steps,
                "steps": window.steps[-60:],
                "phases": phases,
                "step_series": series,
            }
            result = diagnose_rank_rows(rank_rows, mode="live")
            d = result.diagnosis
            out["diagnosis"] = {
                "kind": d.kind,
                "severity": d.severity,
                "summary": d.summary,
                "action": d.action,
            }
    except Exception as exc:
        out["step_time_error"] = str(exc)
    try:
        mem = loaders.load_step_memory_rows(db_path, max_rows_per_rank=window_steps)
        for rank in sorted(mem):
            rows = mem[rank]
            if not rows:
                continue
            last = rows[-1]
            out["memory"].append(
                {
                    "rank": rank,
                    "current_bytes": last.get("current_bytes"),
                    "step_peak_bytes": last.get("step_peak_bytes"),
                    "limit_bytes": last.get("limit_bytes"),
                    "series": [r.get("current_bytes") or 0 for r in rows[-60:]],
                }
            )
    except Exception:
        pass
    try:
        host, _devices = loaders.load_system_rows(db_path, max_rows=120)
        for node in sorted(host):
            rows = host[node]
            if not rows:
                continue
            last = rows[-1]
            out["system"].append(
                {
                    "node": node,
                    "cpu_pct": last.get("cpu_pct"),
                    "memory_used_bytes": last.get("memory_used_bytes"),
                    "memory_total_bytes": last.get("memory_total_bytes"),
                }
            )
    except Exception:
        pass
    try:
        out["stdout"] = [
            {"stream": s, "line": l}
            for s, l in loaders.load_stdout_tail(db_path, n=14)
        ]
    except Exception:
        pass
    return out
