"""Background device-marker resolver.

The reference resolves CUDA events on the 1 Hz sampler thread because the
events carry exact device timestamps (timing.py:66).  On TPU the
readiness *observation time* IS the timestamp, so resolution cadence
bounds timing accuracy.  This daemon polls pending
:class:`~traceml_tpu.utils.timing.DeviceMarker`s at millisecond cadence
while work is in flight and parks when idle — ~hundreds of cheap local
PJRT ``is_ready()`` calls per second, no device sync, no GIL-heavy work.

This replaces the reference's CUDA event pool (cuda_event_pool.py): there
is nothing to pool — markers are just array refs — but the *resolution
service* is the shared infrastructure both designs need.
"""

from __future__ import annotations

import threading
from typing import List, Optional

from traceml_tpu.utils.error_log import get_error_log
from traceml_tpu.utils.timing import DeviceMarker

_DEFAULT_INTERVAL = 0.002  # 2 ms poll while young markers are pending
_IDLE_TIMEOUT = 0.25  # park after this long with nothing pending
_FINE_WINDOW_S = 0.020  # markers younger than this get the fine cadence
_MAX_BACKOFF_S = 0.025  # cadence ceiling for long-running markers


class MarkerResolver:
    def __init__(self, poll_interval: float = _DEFAULT_INTERVAL) -> None:
        self._interval = poll_interval
        self._pending: List[DeviceMarker] = []
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="traceml-marker-resolver", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2)
        self._thread = None

    def submit(self, marker: DeviceMarker) -> None:
        if marker.resolved or marker.submitted:
            return
        marker.submitted = True
        with self._lock:
            self._pending.append(marker)
        self._wake.set()
        # Lazy-start so merely importing the sdk never spawns threads.
        if self._thread is None or not self._thread.is_alive():
            self.start()

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)

    def sweep_inline(self, max_n: int = 64) -> int:
        """Opportunistic poll on the CALLER thread; returns #resolved.

        Called at step boundaries (trace_step.__enter__): in a hot
        training loop the GIL can starve the resolver thread for tens of
        ms, so the main thread stamps the previous step's markers itself
        — the stamp error is then bounded by one inter-step gap instead
        of the resolver's scheduling luck.  Cost: a handful of local
        ``is_ready()`` calls, microseconds.
        """
        with self._lock:
            pending = list(self._pending[:max_n])
        if not pending:
            return 0
        resolved = 0
        for m in pending:
            try:
                if m.poll():
                    resolved += 1
            except Exception:
                pass
        if resolved:
            with self._lock:
                self._pending = [m for m in self._pending if not m.resolved]
        return resolved

    def _delay_for(self, age_s: float) -> float:
        """Age-proportional poll backoff.

        Young markers (short phases) are polled at the fine cadence so
        their stamps stay ~2 ms accurate.  A marker that has been in
        flight for a while is a long device phase; polling it every 2 ms
        buys nothing but wakeups — on a 1-core host those wakeups alone
        cost ~2% of a 150 ms step.  Back off to 10% of the marker's age,
        capped: relative stamp error stays ≤10% (absolute ≤25 ms), and in
        bracketed loops sweep_inline() at the next step boundary usually
        stamps first anyway, at inter-step precision.
        """
        if age_s < _FINE_WINDOW_S:
            return self._interval
        return min(_MAX_BACKOFF_S, max(self._interval, 0.1 * age_s))

    def _run(self) -> None:
        import time as _time

        try:
            while not self._stop.is_set():
                with self._lock:
                    pending = list(self._pending)
                if not pending:
                    fired = self._wake.wait(timeout=_IDLE_TIMEOUT)
                    if fired:
                        self._wake.clear()
                    continue
                for m in pending:
                    try:
                        m.poll()
                    except Exception:
                        pass  # poll() itself fails open, but belt+braces
                now = _time.perf_counter()
                with self._lock:
                    # Identity-based prune: concurrent submits and
                    # sweep_inline() prunes both mutate _pending, so a
                    # slice-by-stale-length merge would drop markers.
                    self._pending = [m for m in self._pending if not m.resolved]
                    unresolved = list(self._pending)
                if unresolved:
                    delay = min(
                        self._delay_for(now - m.dispatched_at) for m in unresolved
                    )
                else:
                    delay = self._interval
                # waiting on _wake (not _stop) lets a fresh submit
                # re-tighten the cadence mid-backoff
                fired = self._wake.wait(timeout=delay)
                if fired:
                    self._wake.clear()
        except Exception as exc:  # pragma: no cover
            get_error_log().error("marker resolver crashed", exc)


_resolver = MarkerResolver()


def get_marker_resolver() -> MarkerResolver:
    return _resolver
