"""Stress scenarios on realistic model families
(reference: src/dev/scenarios/ BERT/ViT stress variants)."""
