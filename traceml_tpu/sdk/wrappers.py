"""Manual-mode wrappers (reference: src/traceml_ai/sdk/wrappers.py:78-330).

For users who opt out of auto-patching (``init(mode="manual")``): each
wrapper times one phase explicitly.  All wrappers are duplicate-guarded:
the TLS depth gates shared with the auto-patches mean a manually wrapped
call under an active auto-patch is timed exactly once.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

from traceml_tpu.sdk.state import TraceState, get_state
from traceml_tpu.utils.marker_resolver import get_marker_resolver
from traceml_tpu.utils.timing import (
    BACKWARD_TIME,
    CHECKPOINT_TIME,
    COLLECTIVE_TIME,
    FORWARD_TIME,
    H2D_TIME,
    OPTIMIZER_STEP,
    timed_region,
)


def _timed_call(
    phase: str,
    depth_attr: str,
    fn: Callable,
    st: TraceState,
    mark_output: bool,
    *args: Any,
    **kwargs: Any,
):
    tls = st.tls
    depth = getattr(tls, depth_attr)
    if depth > 0:  # auto-patch (or outer wrapper) already timing
        return fn(*args, **kwargs)
    setattr(tls, depth_attr, depth + 1)
    try:
        region = timed_region(phase, st.current_step, sink=st.buffer.add)
        with region as tr:
            out = fn(*args, **kwargs)
            if mark_output and st.markers_enabled():
                tr.mark(out)
        publish_region_marker(region.event, st)
        return out
    finally:
        setattr(tls, depth_attr, depth)


def publish_region_marker(ev, st: TraceState) -> None:
    """Post-close marker publication, shared by every phase owner
    (manual wrappers here, the Lightning callback, wrap_step_fn): hand
    the marker to the open step envelope — last dispatch wins, or a
    post-compute collective/h2d would fall outside the envelope and get
    clamped away by the window builder — and route it to the resolver.

    Submission happens AT DISPATCH on purpose: the resolver's fine
    cadence stamps each phase's readiness WHILE the step runs, which is
    what gives intra-step device edges (compute → collective → …) their
    timeliness.  Deferring submission to step exit collapses every
    edge onto the exit sweep's observation instant and zeroes the
    phase durations (regression caught by the collective-straggler
    scenario E2E) — the per-dispatch wake is the price of observation.

    This is also the overhead-governor chokepoint: on a step the
    governor chose not to device-sample, the marker is dropped HERE —
    whichever site created it (h2d patch, dataloader device_put,
    Lightning, trace_time) — so unsampled steps are uniformly host-only
    and no RPC-priced readiness probe escapes the budget.  Out-of-step
    regions (eval loops) are never gated.
    """
    if ev.marker is None:
        return
    if st.tls.in_step:
        if not st.sample_markers:
            ev.marker = None  # governor: unsampled step, drop the probe
            return
        env = st.active_step_event
        if env is not None:
            env.marker = ev.marker
    if not ev.marker.resolved:
        get_marker_resolver().submit(ev.marker)


def wrap_forward(fn: Callable, state: Optional[TraceState] = None) -> Callable:
    """Time a forward callable (a flax ``apply``, torch module, …)."""
    st = state or get_state()

    @functools.wraps(fn)
    def wrapped(*args: Any, **kwargs: Any):
        return _timed_call(FORWARD_TIME, "forward_depth", fn, st, True, *args, **kwargs)

    wrapped._traceml_wrapped = True  # type: ignore[attr-defined]
    return wrapped


def wrap_backward(fn: Callable, state: Optional[TraceState] = None) -> Callable:
    st = state or get_state()

    @functools.wraps(fn)
    def wrapped(*args: Any, **kwargs: Any):
        return _timed_call(
            BACKWARD_TIME, "backward_depth", fn, st, True, *args, **kwargs
        )

    wrapped._traceml_wrapped = True  # type: ignore[attr-defined]
    return wrapped


def wrap_optimizer(optimizer: Any, state: Optional[TraceState] = None) -> Any:
    """Wrap a torch-style optimizer's ``.step`` in-place.

    (Optax updates run inside the jitted step — they are part of
    ``compute_time`` and need no wrapper; see sdk/step_fn.py.)
    """
    st = state or get_state()
    if getattr(optimizer, "_traceml_wrapped", False):
        return optimizer
    original_step = optimizer.step

    @functools.wraps(original_step)
    def step(*args: Any, **kwargs: Any):
        if not st.tls.in_step:
            return original_step(*args, **kwargs)
        with timed_region(OPTIMIZER_STEP, st.current_step, sink=st.buffer.add):
            return original_step(*args, **kwargs)

    optimizer.step = step
    optimizer._traceml_wrapped = True
    return optimizer


def wrap_collective(fn: Callable, state: Optional[TraceState] = None) -> Callable:
    """Time an explicit collective (gradient sync, all-gather, psum
    dispatched OUTSIDE the fused step) as the first-class ``collective``
    phase.

    Inside one fused ``wrap_step_fn`` program the collectives are part of
    ``compute`` — XLA schedules them and there is no host-visible
    boundary.  This wrapper is for the loops that DO dispatch them
    separately: manual pipeline schedules, ring-attention hops driven
    from the host, parameter syncs between microbatch groups, or the
    torch-xla path (where ``patch_mark_step`` emits this phase
    automatically).  Feeds COLLECTIVE_STRAGGLER attribution
    (diagnostics/step_time/rules.py).
    """
    st = state or get_state()

    @functools.wraps(fn)
    def wrapped(*args: Any, **kwargs: Any):
        return _timed_call(
            COLLECTIVE_TIME, "collective_depth", fn, st, True, *args, **kwargs
        )

    wrapped._traceml_wrapped = True  # type: ignore[attr-defined]
    return wrapped


def wrap_checkpoint(fn: Callable, state: Optional[TraceState] = None) -> Callable:
    """Time a checkpoint save as the first-class ``checkpoint`` phase.

    Checkpoint stalls are a classic TPU training pathology — a blocking
    save gates every synchronous step, and without this phase the time
    lands in ``residual``.  The orbax auto-patch
    (instrumentation/orbax_patch.py) applies this automatically; wrap a
    custom saver manually for other checkpointing stacks.
    """
    st = state or get_state()

    @functools.wraps(fn)
    def wrapped(*args: Any, **kwargs: Any):
        return _timed_call(
            CHECKPOINT_TIME, "checkpoint_depth", fn, st, False, *args, **kwargs
        )

    wrapped._traceml_wrapped = True  # type: ignore[attr-defined]
    return wrapped


def wrap_h2d(value: Any, device: Any = None, state: Optional[TraceState] = None) -> Any:
    """Explicitly timed host→device transfer (JAX ``device_put``)."""
    import jax

    st = state or get_state()
    return _timed_call(
        H2D_TIME,
        "h2d_depth",
        (lambda v: jax.device_put(v) if device is None else jax.device_put(v, device)),
        st,
        True,
        value,
    )
