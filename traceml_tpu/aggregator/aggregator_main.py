"""Standalone aggregator process entry
(reference: src/traceml_ai/aggregator/aggregator_main.py:86-280).

Launched as ``python -m traceml_tpu.aggregator.aggregator_main`` with
TRACEML_* env config.  Binds the TCP server (port 0 → ephemeral, the
bound port is advertised via ``aggregator_ready.json``), then runs until
SIGTERM/SIGINT, finalizing on the way out.  Fatal errors land in
``aggregator_error.log``.
"""

from __future__ import annotations

import signal
import sys
import threading
import traceback

from traceml_tpu.aggregator.trace_aggregator import TraceMLAggregator, write_ready_file
from traceml_tpu.runtime.settings import settings_from_env
from traceml_tpu.utils.error_log import get_error_log


def main() -> int:
    settings = settings_from_env()
    stop_evt = threading.Event()

    def _on_signal(signum, frame):  # noqa: ANN001
        stop_evt.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)

    # a launcher that dies WITHOUT signaling (SIGKILLed test runner)
    # must not leave this process looping forever — treat parent death
    # like SIGTERM (finalize + exit); see utils/orphan_watch.py
    from traceml_tpu.utils.orphan_watch import arm_parent_death_watch

    arm_parent_death_watch(stop_evt.set)

    try:
        agg = TraceMLAggregator(settings)
        agg.start()
        assert agg.port is not None
        write_ready_file(
            settings,
            agg.port,
            display_port=getattr(agg.display, "port", None),
        )
        while not stop_evt.wait(0.25):
            pass
        agg.stop()
        return 0
    except Exception as exc:
        get_error_log().error("aggregator fatal", exc)
        try:
            path = settings.session_dir / "aggregator_error.log"
            path.parent.mkdir(parents=True, exist_ok=True)
            with open(path, "a", encoding="utf-8") as fh:
                fh.write("".join(traceback.format_exception(type(exc), exc, exc.__traceback__)))
        except Exception:
            pass
        return 1


if __name__ == "__main__":
    sys.exit(main())
