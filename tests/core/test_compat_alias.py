import warnings


def test_traceml_alias_top_level():
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", DeprecationWarning)
        import traceml

    assert callable(traceml.trace_step)
    assert callable(traceml.init)
    assert traceml.__version__ == __import__("traceml_tpu").__version__


def test_traceml_alias_submodules():
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", DeprecationWarning)
        import traceml  # noqa: F401
        from traceml.utils.timing import STEP_TIME
        import traceml.diagnostics.common as common

    from traceml_tpu.utils.timing import STEP_TIME as REAL

    assert STEP_TIME == REAL
    import traceml_tpu.diagnostics.common as real_common

    assert common is real_common
