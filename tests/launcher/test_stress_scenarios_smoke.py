"""Smoke the dev stress scenarios (ViT/BERT-style) — real subprocess,
few steps; these scripts are the reference-parity stress harness and
were previously never executed in CI."""

import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[2]


def _run(module, *args, timeout=240):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(REPO)
    return subprocess.run(
        [sys.executable, "-m", module, *args],
        capture_output=True, text=True, timeout=timeout, env=env,
    )


@pytest.mark.parametrize("module", [
    "traceml_tpu.dev.scenarios.vit_stress",
    "traceml_tpu.dev.scenarios.bert_stress",
])
def test_stress_scenario_runs(module):
    proc = _run(module, "6", "none")
    assert proc.returncode == 0, proc.stderr[-2000:]


def test_vit_stress_with_fault():
    proc = _run("traceml_tpu.dev.scenarios.vit_stress", "6", "input_bound")
    assert proc.returncode == 0, proc.stderr[-2000:]
