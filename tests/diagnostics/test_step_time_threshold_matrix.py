"""Step-time policy threshold matrix — exact boundary behavior for
every numeric gate, live vs summary (reference style: the rule-threshold
matrices VERDICT r1 flagged as thin).

The window is built from hand-rows where the target share is exact, so
each case sits just under / at / above a policy constant."""

import pytest

from traceml_tpu.diagnostics.step_time.api import diagnose_rank_rows
from traceml_tpu.diagnostics.step_time.policy import LIVE_POLICY, SUMMARY_POLICY
from traceml_tpu.utils import timing as T


def _row(step, step_ms, input_ms=0.0, compute_ms=0.0, residual_share=None,
         compile_ms=0.0):
    events = {
        T.STEP_TIME: {"cpu_ms": step_ms, "device_ms": step_ms, "count": 1},
    }
    if input_ms:
        events[T.DATALOADER_NEXT] = {
            "cpu_ms": input_ms, "device_ms": None, "count": 1
        }
    if compute_ms:
        events[T.COMPUTE_TIME] = {
            "cpu_ms": 0.5, "device_ms": compute_ms, "count": 1
        }
    if compile_ms:
        events[T.COMPILE_TIME] = {
            "cpu_ms": compile_ms, "device_ms": None, "count": 1
        }
    return {"step": step, "clock": "device", "events": events}


def _world(n_steps=60, **kw):
    return {0: [_row(s, **kw) for s in range(1, n_steps + 1)]}


def _kinds(rows, mode):
    return {i.kind for i in diagnose_rank_rows(rows, mode=mode).issues}


# --- INPUT_BOUND boundaries -------------------------------------------------

@pytest.mark.parametrize("mode,policy", [
    ("live", LIVE_POLICY), ("summary", SUMMARY_POLICY),
])
def test_input_bound_boundaries(mode, policy):
    step = 100.0
    just_under = _world(step_ms=step,
                        input_ms=step * (policy.input_share_warn - 0.02),
                        compute_ms=50.0)
    assert "INPUT_BOUND" not in _kinds(just_under, mode)

    at_warn = _world(step_ms=step,
                     input_ms=step * (policy.input_share_warn + 0.01),
                     compute_ms=50.0)
    result = diagnose_rank_rows(at_warn, mode=mode)
    issue = next(i for i in result.issues if i.kind == "INPUT_BOUND")
    assert issue.severity == "warning"

    at_crit = _world(step_ms=step,
                     input_ms=step * (policy.input_share_critical + 0.01),
                     compute_ms=40.0)
    result = diagnose_rank_rows(at_crit, mode=mode)
    issue = next(i for i in result.issues if i.kind == "INPUT_BOUND")
    assert issue.severity == "critical"


# --- RESIDUAL_HEAVY boundaries ----------------------------------------------

@pytest.mark.parametrize("mode,policy", [
    ("live", LIVE_POLICY), ("summary", SUMMARY_POLICY),
])
def test_residual_boundaries(mode, policy):
    step = 100.0
    # residual = step − compute (no other phases)
    ok = _world(step_ms=step,
                compute_ms=step * (1 - policy.residual_share_warn + 0.02))
    assert "RESIDUAL_HEAVY" not in _kinds(ok, mode)

    warn = _world(step_ms=step,
                  compute_ms=step * (1 - policy.residual_share_warn - 0.01))
    result = diagnose_rank_rows(warn, mode=mode)
    issue = next(i for i in result.issues if i.kind == "RESIDUAL_HEAVY")
    assert issue.severity == "warning"

    crit = _world(step_ms=step,
                  compute_ms=step * (1 - policy.residual_share_critical - 0.01))
    result = diagnose_rank_rows(crit, mode=mode)
    issue = next(i for i in result.issues if i.kind == "RESIDUAL_HEAVY")
    assert issue.severity == "critical"


# --- straggler score + dominance boundaries ---------------------------------

def _straggler_world(slow_extra_input, n_ranks=4, step=100.0):
    """Sync-consistent shape: every rank's step is gated at step+e; the
    slow rank spends the extra in input, fast ranks wait in the sync
    (compute) phase.  Clean-straggler score ≈ e / (step+e), and the
    input delta is the ONLY clean component → INPUT attribution."""
    e = slow_extra_input
    rows = {}
    for r in range(n_ranks):
        slow = r == n_ranks - 1
        rows[r] = [
            _row(s, step_ms=step + e,
                 input_ms=(5.0 + e) if slow else 5.0,
                 compute_ms=90.0 if slow else 90.0 + e)
            for s in range(1, 41)
        ]
    return rows


def test_straggler_score_boundary():
    below = _straggler_world(slow_extra_input=8.0)   # score ≈ 0.074 < 0.10
    kinds = _kinds(below, "live")
    assert not kinds & {"INPUT_STRAGGLER", "STRAGGLER"}

    above = _straggler_world(slow_extra_input=13.0)  # score ≈ 0.115
    result = diagnose_rank_rows(above, mode="live")
    issue = next(
        i for i in result.issues if i.kind in ("INPUT_STRAGGLER", "STRAGGLER")
    )
    assert issue.kind == "INPUT_STRAGGLER"  # input delta dominates
    assert issue.severity == "warning"

    critical = _straggler_world(slow_extra_input=36.0)  # score ≈ 0.26
    result = diagnose_rank_rows(critical, mode="live")
    issue = next(i for i in result.issues if i.kind == "INPUT_STRAGGLER")
    assert issue.severity == "critical"


def test_straggler_mixed_when_no_dominant_component():
    # sync-consistent world (every rank's step gated at 130): the slow
    # rank lags equally in input and residual (+15/+15), fast ranks park
    # the wait in the sync (compute) phase — dominance 1.0 < 1.25 →
    # mixed STRAGGLER
    rows = {}
    for r in range(4):
        slow = r == 3
        rows[r] = [
            _row(s, step_ms=130.0,
                 input_ms=20.0 if slow else 5.0,      # +15 input
                 compute_ms=80.0 if slow else 110.0)  # fast: 80 + 30 wait
            # residual: slow 30, fast 15 → +15
            for s in range(1, 41)
        ]
    result = diagnose_rank_rows(rows, mode="live")
    issue = next(
        i for i in result.issues
        if i.kind in ("STRAGGLER", "INPUT_STRAGGLER", "RESIDUAL_STRAGGLER")
    )
    assert issue.kind == "STRAGGLER"
    assert issue.ranks == [3]


# --- compile warmup boundary ------------------------------------------------

def test_compile_warmup_steps_not_counted():
    policy_warmup = LIVE_POLICY.compile_warmup_steps

    def world(recompile_pred):
        # a recompiling step really TAKES the compile time (the window
        # clamps any phase to its step envelope, so an un-stretched step
        # would swallow the compile)
        rows = {0: []}
        for s in range(1, 61):
            compiling = recompile_pred(s)
            rows[0].append(_row(
                s,
                step_ms=600.0 if compiling else 100.0,
                compute_ms=90.0,
                compile_ms=500.0 if compiling else 0.0,
            ))
        return rows

    # big compiles ONLY within the warmup steps → not pathological
    warmup_only = world(lambda s: s <= policy_warmup)
    assert "COMPILE_BOUND" not in _kinds(warmup_only, "live")

    # the same compile mass AFTER warmup fires
    recompiles = world(lambda s: policy_warmup < s <= policy_warmup + 3)
    assert "COMPILE_BOUND" in _kinds(recompiles, "live")


# --- min-steps gates --------------------------------------------------------

@pytest.mark.parametrize("mode,policy", [
    ("live", LIVE_POLICY), ("summary", SUMMARY_POLICY),
])
def test_min_steps_gate(mode, policy):
    under = _world(n_steps=policy.min_steps - 1, step_ms=100.0,
                   input_ms=60.0, compute_ms=30.0)
    result = diagnose_rank_rows(under, mode=mode)
    assert result.diagnosis.kind == "INSUFFICIENT_STEP_TIME_DATA"

    at = _world(n_steps=policy.min_steps, step_ms=100.0,
                input_ms=60.0, compute_ms=30.0)
    result = diagnose_rank_rows(at, mode=mode)
    assert result.diagnosis.kind == "INPUT_BOUND"
