"""Public SDK (reference: src/traceml_ai/sdk/)."""
