"""``traceml-tpu compare a.json b.json``
(reference: src/traceml_ai/reporting/compare/ — command.py:19,
verdict.py:24-38 priority ladder, policy.py:55-80 significance tiers).

Pipeline: per-section comparers (sections.py) → diagnosis transitions →
priority verdict ladder (verdict.py) → payload + text render.  The
payload schema is ``traceml-tpu-compare/2``: per-section blocks with
named metric rows, per-rank deltas, and ranked findings.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Dict, Optional

from traceml_tpu.reporting.compare.policy import DEFAULT_POLICY, ComparePolicy
from traceml_tpu.reporting.compare.sections import ALL_COMPARERS, compare_diagnoses
from traceml_tpu.reporting.compare.verdict import decide_verdict
from traceml_tpu.utils.atomic_io import atomic_write_json, atomic_write_text, read_json
from traceml_tpu.utils.formatting import fmt_ms


def build_compare_payload(
    baseline: Dict[str, Any],
    candidate: Dict[str, Any],
    policy: ComparePolicy = DEFAULT_POLICY,
) -> Dict[str, Any]:
    sections = {
        name: comparer(baseline, candidate, policy)
        for name, comparer in ALL_COMPARERS.items()
    }
    diag_findings = compare_diagnoses(baseline, candidate)
    verdict, findings = decide_verdict(sections, diag_findings)

    step = sections.get("step_time")
    step_metric = (step.metrics.get("step_median_ms") or {}) if step else {}
    payload = {
        "schema": "traceml-tpu-compare/2",
        "verdict": verdict,
        "baseline": {
            "session_id": (baseline.get("meta") or {}).get("session_id"),
            "step_median_ms": step_metric.get("baseline"),
        },
        "candidate": {
            "session_id": (candidate.get("meta") or {}).get("session_id"),
            "step_median_ms": step_metric.get("candidate"),
        },
        "step_delta_rel": step_metric.get("delta_rel"),
        "findings": findings,
        "sections": {name: comp.as_dict() for name, comp in sections.items()},
    }
    # the candidate's own cross-run verdict (analytics/baselines.py):
    # a pairwise compare answers "vs THIS baseline run"; the baseline
    # store answers "vs the fleet of matching runs" — both belong in
    # the report.  Key absent when the candidate predates baselines.
    reg = candidate.get("regressions")
    if isinstance(reg, dict) and reg.get("checks"):
        payload["candidate_baseline"] = {
            "status": reg.get("status"),
            "baseline_runs": reg.get("baseline_runs"),
            "regressed_metrics": [
                c.get("metric")
                for c in reg.get("checks") or []
                if c.get("status") == "regression"
            ],
            "issues": [
                i.get("summary") for i in reg.get("issues") or []
            ],
        }
    return payload


def render_compare_text(payload: Dict[str, Any]) -> str:
    lines = [
        f"VERDICT: {payload['verdict']}",
        f"baseline:  {payload['baseline']['session_id']}  "
        f"step {fmt_ms(payload['baseline']['step_median_ms'])}",
        f"candidate: {payload['candidate']['session_id']}  "
        f"step {fmt_ms(payload['candidate']['step_median_ms'])}",
        "",
    ]
    for f in payload["findings"]:
        lines.append(f"[{f['significance']}] {f['section']}: {f['summary']}")
    if not payload["findings"]:
        lines.append("No significant differences.")
    cb = payload.get("candidate_baseline")
    if cb:
        if cb.get("status") == "regression":
            lines.append(
                "candidate vs its baseline store "
                f"({cb.get('baseline_runs')} matching runs): REGRESSION "
                f"in {', '.join(cb.get('regressed_metrics') or [])}"
            )
            for s in cb.get("issues") or []:
                lines.append(f"  {s}")
        else:
            lines.append(
                "candidate vs its baseline store "
                f"({cb.get('baseline_runs')} matching runs): ok"
            )
    # section status footer — says which domains actually compared
    lines.append("")
    for name, sec in (payload.get("sections") or {}).items():
        status = sec.get("status")
        note = f" — {sec['note']}" if sec.get("note") else ""
        lines.append(f"  {name}: {status}{note}")
    return "\n".join(lines) + "\n"


def _resolve_summary(path: Path) -> Optional[Dict[str, Any]]:
    """Accept a final_summary.json OR a session directory."""
    path = Path(path)
    if path.is_dir():
        path = path / "final_summary.json"
    return read_json(path)


def compare_summaries(
    baseline_path: Path,
    candidate_path: Path,
    policy: ComparePolicy = DEFAULT_POLICY,
) -> Optional[Dict[str, Any]]:
    baseline = _resolve_summary(baseline_path)
    candidate = _resolve_summary(candidate_path)
    if baseline is None or candidate is None:
        return None
    return build_compare_payload(baseline, candidate, policy)


def run_compare(
    baseline_path: Path, candidate_path: Path, output: Optional[Path] = None
) -> int:
    payload = compare_summaries(baseline_path, candidate_path)
    if payload is None:
        print("could not read one of the summaries")
        return 1
    text = render_compare_text(payload)
    print(text)
    if output:
        atomic_write_json(output, payload)
        atomic_write_text(Path(str(output)).with_suffix(".txt"), text)
    return 0
