"""Fleet rollup merge + concurrent gather contracts
(docs/developer_guide/federation.md)."""

from __future__ import annotations

import time

from traceml_tpu.federation.rollup import (
    gather_indexes,
    merge_fleet,
    severity_rank,
)


def _entry(sid, ranks=None, diag=None, finished=False, ts=0.0, **extra):
    e = {
        "session": sid,
        "db_exists": True,
        "last_update_ts": ts,
        "ranks": ranks or {},
        "finished": finished,
        "primary_diagnosis": diag,
    }
    e.update(extra)
    return e


def _index(*entries):
    return {"version": 1, "ts": 100.0, "sessions": list(entries)}


def test_merge_totals_and_lost_ranks():
    merged = merge_fleet({
        "a:1": _index(
            _entry("s1", ranks={"ACTIVE": 4}, ts=3.0,
                   workload="training"),
            _entry("s2", ranks={"ACTIVE": 2, "lost": 1}, ts=2.0),
        ),
        "b:2": _index(
            _entry("s3", ranks={"FINISHED": 8}, finished=True, ts=1.0,
                   workload="training+serving"),
        ),
    })
    t = merged["totals"]
    assert t["sessions"] == 3
    assert t["finished"] == 1
    assert t["live"] == 2
    assert t["rank_states"] == {"ACTIVE": 6, "lost": 1, "FINISHED": 8}
    assert t["lost_ranks"] == 1
    assert t["workloads"] == {"training": 1, "training+serving": 1}
    # every row is annotated with its shard
    assert {(r["session"], r["shard"]) for r in merged["sessions"]} == {
        ("s1", "a:1"), ("s2", "a:1"), ("s3", "b:2")
    }


def test_worst_diagnosis_ranks_severity_across_shards():
    merged = merge_fleet({
        "a:1": _index(_entry("s1", diag={
            "kind": "dataloader_bottleneck", "severity": "warning",
            "summary": "input-bound"})),
        "b:2": _index(_entry("s2", diag={
            "kind": "rank_lost", "severity": "critical",
            "summary": "rank 3 lost"})),
    })
    worst = merged["worst_diagnosis"]
    assert worst["kind"] == "rank_lost"
    assert worst["session"] == "s2"
    assert worst["shard"] == "b:2"


def test_severity_rank_ordering():
    assert severity_rank("critical") > severity_rank("warning")
    assert severity_rank("warning") > severity_rank("info")
    # unknown severities surface above warnings, below errors
    assert severity_rank("weird") > severity_rank("warning")
    assert severity_rank("weird") < severity_rank("error")


def test_stale_shard_sessions_marked_not_dropped():
    merged = merge_fleet(
        {
            "a:1": _index(_entry("s1", ts=2.0)),
            "b:2": _index(_entry("s2", ts=1.0)),  # last good index
        },
        stale_shards=["b:2"],
    )
    by_sid = {r["session"]: r for r in merged["sessions"]}
    assert by_sid["s1"]["stale"] is False
    assert by_sid["s2"]["stale"] is True
    shard_rows = {r["shard"]: r for r in merged["shards"]}
    assert shard_rows["b:2"]["stale"] is True
    assert shard_rows["b:2"]["alive"] is False
    assert shard_rows["a:1"]["alive"] is True


def test_dead_shard_with_no_cached_index_still_listed():
    merged = merge_fleet({"a:1": _index(), "b:2": None},
                         stale_shards=["b:2"])
    shard_rows = {r["shard"]: r for r in merged["shards"]}
    assert shard_rows["b:2"]["alive"] is False
    assert shard_rows["b:2"]["sessions"] == 0


def test_pagination_is_deterministic_and_complete():
    entries = [_entry(f"s{i:02d}", ts=float(i % 3)) for i in range(25)]
    per_shard = {"a:1": _index(*entries)}
    seen = []
    p0 = merge_fleet(per_shard, page=0, page_size=10)
    assert p0["pages"] == 3
    for page in range(p0["pages"]):
        m = merge_fleet(per_shard, page=page, page_size=10)
        seen.extend(r["session"] for r in m["sessions"])
    assert sorted(seen) == sorted(e["session"] for e in entries)
    assert len(seen) == len(set(seen))  # no row on two pages


def test_page_past_end_is_empty_not_error():
    merged = merge_fleet({"a:1": _index(_entry("s1"))}, page=99)
    assert merged["sessions"] == []
    assert merged["totals"]["sessions"] == 1


def test_gather_respects_deadline_with_hung_shard():
    def fetch(shard, timeout):
        if shard == "hung:1":
            time.sleep(5.0)
        return _index(_entry(f"from-{shard}"))

    t0 = time.monotonic()
    results, failed = gather_indexes(
        ["ok:1", "hung:1"], fetch, deadline_s=0.3
    )
    elapsed = time.monotonic() - t0
    assert elapsed < 2.0, "gather must not wait out a hung shard"
    assert failed == ["hung:1"]
    assert results["ok:1"]["sessions"][0]["session"] == "from-ok:1"
    assert results["hung:1"] is None


def test_gather_collects_all_when_fast():
    results, failed = gather_indexes(
        ["a:1", "b:2"],
        lambda shard, timeout: _index(_entry(f"s-{shard}")),
        deadline_s=2.0,
    )
    assert failed == []
    assert set(results) == {"a:1", "b:2"}
