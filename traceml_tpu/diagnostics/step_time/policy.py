"""Step-time thresholds, live vs summary
(reference: src/traceml_ai/diagnostics/step_time/policy.py:9-75 — the
numeric policy is kept compatible so verdicts line up with the
reference's on equivalent data; the compile policy is TPU-new).
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class StepTimePolicy:
    # input share of step (median across ranks)
    input_share_warn: float
    input_share_critical: float
    # residual share
    residual_share_warn: float
    residual_share_critical: float
    # compute-bound (info-grade: the job is healthy-but-saturated)
    compute_share_info: float
    compute_share_high: float
    # straggler scoring
    straggler_score_fire: float = 0.10
    straggler_dominance: float = 1.25  # component must beat 2nd by this
    skew_gate: float = 0.06
    # compile share (TPU-new): recompilation storms.  Compiles within the
    # first N absolute steps are warmup, not recompiles.
    compile_share_warn: float = 0.10
    compile_share_critical: float = 0.25
    compile_warmup_steps: int = 3
    # device occupancy (device-busy share of wall clock) — the TPU
    # stand-in for the reference's GPU-utilization rule
    # (reference: diagnostics/system/rules.py GPUUtilizationRule)
    occupancy_warn: float = 0.30
    occupancy_critical: float = 0.15
    # MFU (achieved/peak FLOP/s, TPU-new): only judged when the chip is
    # the bottleneck (compute share ≥ mfu_compute_gate) — a busy chip
    # at low MFU means the program wastes the MXU (fusion, precision,
    # tiny matmuls), which occupancy alone cannot see.  Well-tuned LLM
    # training lands 0.35–0.55; below 0.15 something is structurally
    # wrong.
    mfu_low_warn: float = 0.15
    mfu_moderate: float = 0.30
    mfu_compute_gate: float = 0.50
    min_steps: int = 20


LIVE_POLICY = StepTimePolicy(
    input_share_warn=0.25,
    input_share_critical=0.35,
    residual_share_warn=0.15,
    residual_share_critical=0.25,
    compute_share_info=0.85,
    compute_share_high=0.92,
    min_steps=20,
)

SUMMARY_POLICY = StepTimePolicy(
    input_share_warn=0.30,
    input_share_critical=0.40,
    residual_share_warn=0.18,
    residual_share_critical=0.28,
    compute_share_info=0.85,
    compute_share_high=0.92,
    min_steps=50,
)


def policy_for(mode: str) -> StepTimePolicy:
    return SUMMARY_POLICY if mode == "summary" else LIVE_POLICY
