"""Fake `lightning` (new layout): the API lives in lightning.pytorch."""

from lightning import pytorch  # noqa: F401

Trainer = pytorch.Trainer
Callback = pytorch.Callback
LightningModule = pytorch.LightningModule
__version__ = "2.0-fake"
