"""Lifecycle protocols (reference: src/traceml_ai/core/lifecycle.py:12-31).

Components that participate in the runtime/aggregator lifecycle implement
one or more of these.  Kept as runtime-checkable protocols so fakes in tests
need no inheritance.
"""

from __future__ import annotations

from typing import Protocol, runtime_checkable


@runtime_checkable
class Startable(Protocol):
    def start(self) -> None: ...


@runtime_checkable
class Stoppable(Protocol):
    def stop(self) -> None: ...


@runtime_checkable
class Tickable(Protocol):
    """Called periodically from an owning loop (sampler tick, UI tick)."""

    def tick(self) -> None: ...
