from traceml_tpu.diagnostics.common import (
    DiagnosticIssue,
    DiagnosticResult,
)
from traceml_tpu.diagnostics.model_diagnostics import compose


def _result(domain, issues):
    return DiagnosticResult(domain=domain, issues=issues)


def test_compose_all_healthy():
    out = compose({"step_time": _result("step_time", []),
                   "step_memory": _result("step_memory", [])})
    assert out.headline.kind == "HEALTHY"
    assert out.domain_health == {"step_time": True, "step_memory": True}
    assert out.issues == []


def test_compose_model_domain_outranks_env_at_equal_severity():
    st = _result("step_time", [DiagnosticIssue(
        kind="INPUT_BOUND", severity="warning", score=0.3)])
    sysd = _result("system", [DiagnosticIssue(
        kind="HIGH_HOST_CPU", severity="warning", score=0.9)])
    out = compose({"step_time": st, "system": sysd})
    assert out.headline.kind == "INPUT_BOUND"
    assert [i.kind for i in out.issues] == ["INPUT_BOUND", "HIGH_HOST_CPU"]


def test_compose_critical_env_beats_warning_model():
    st = _result("step_time", [DiagnosticIssue(
        kind="INPUT_BOUND", severity="warning", score=0.3)])
    mem = _result("system", [DiagnosticIssue(
        kind="HIGH_DEVICE_MEMORY", severity="critical", score=0.97)])
    out = compose({"step_time": st, "system": mem})
    assert out.headline.kind == "HIGH_DEVICE_MEMORY"


def test_compose_tags_domains_in_evidence():
    st = _result("step_memory", [DiagnosticIssue(
        kind="MEMORY_IMBALANCE", severity="warning", score=0.25)])
    out = compose({"step_memory": st})
    assert out.issues[0].evidence["domain"] == "step_memory"
    assert out.to_dict()["headline"]["kind"] == "MEMORY_IMBALANCE"
