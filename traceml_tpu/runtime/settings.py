"""Frozen runtime settings + the TRACEML_* env contract
(reference: src/traceml_ai/runtime/settings.py:26-82 and the env block
launcher/commands.py:292-341 — the ONLY contract between the launcher
and child processes).
"""

from __future__ import annotations

import dataclasses
import os
from pathlib import Path
from typing import Dict, Optional

from traceml_tpu.config import flags

ENV_PREFIX = "TRACEML_"

# canonical env var names — aliases into the declared registry
# (config/flags.py) so every name exists in exactly one place
ENV_SESSION_ID = flags.SESSION_ID.name
ENV_LOGS_DIR = flags.LOGS_DIR.name
ENV_MODE = flags.MODE.name  # cli | summary
ENV_AGG_HOST = flags.AGGREGATOR_HOST.name
ENV_AGG_BIND_HOST = flags.AGGREGATOR_BIND_HOST.name
ENV_AGG_PORT = flags.AGGREGATOR_PORT.name
ENV_SAMPLER_INTERVAL = flags.SAMPLER_INTERVAL_SEC.name
ENV_MAX_STEPS = flags.TRACE_MAX_STEPS.name
ENV_DISABLE = flags.DISABLE.name
ENV_DISK_BACKUP = flags.DISK_BACKUP.name
ENV_CAPTURE_STDERR = flags.CAPTURE_STDERR.name
ENV_RUN_NAME = flags.RUN_NAME.name
ENV_EXPECTED_WORLD_SIZE = flags.EXPECTED_WORLD_SIZE.name
ENV_FINALIZE_TIMEOUT = flags.FINALIZE_TIMEOUT_SEC.name
ENV_SUMMARY_WINDOW_ROWS = flags.SUMMARY_WINDOW_ROWS.name
ENV_SERVE_MAX_SESSIONS = flags.SERVE_MAX_SESSIONS.name
ENV_SCRIPT = flags.SCRIPT.name
ENV_SCRIPT_ARGS = flags.SCRIPT_ARGS.name
ENV_TRANSPORT = flags.TRANSPORT.name
ENV_TRANSPORT_COMPRESS = flags.TRANSPORT_COMPRESS.name
ENV_SHM_RING_BYTES = flags.SHM_RING_BYTES.name
ENV_SHM_DIR = flags.SHM_DIR.name
ENV_UDS_PATH = flags.UDS_PATH.name


@dataclasses.dataclass(frozen=True)
class AggregatorEndpoint:
    """connect_host vs bind_host split for multi-node
    (reference: settings.py:36-49)."""

    connect_host: str = "127.0.0.1"
    bind_host: str = "127.0.0.1"
    port: int = 0


@dataclasses.dataclass(frozen=True)
class TraceMLSettings:
    session_id: str = "local"
    logs_dir: Path = Path("./traceml_logs")
    mode: str = "cli"  # cli | summary
    aggregator: AggregatorEndpoint = dataclasses.field(
        default_factory=AggregatorEndpoint
    )
    sampler_interval_sec: float = 1.0
    trace_max_steps: Optional[int] = None
    disabled: bool = False
    disk_backup: bool = False
    capture_stderr: bool = True
    run_name: Optional[str] = None
    expected_world_size: Optional[int] = None
    finalize_timeout_sec: float = 300.0
    summary_window_rows: int = 10000
    # serving tier: max concurrently-open session publishers (LRU bound
    # on sqlite connections) when one aggregator serves a fleet
    serve_max_sessions: int = 8
    # transport tier (docs/developer_guide/native-transport.md):
    # auto | shm | uds | tcp, plus the compression / shm knobs
    transport: str = "auto"
    transport_compress: str = "auto"
    shm_ring_bytes: int = 4194304
    shm_dir: Optional[str] = None
    uds_path: Optional[str] = None

    @property
    def session_dir(self) -> Path:
        return Path(self.logs_dir) / self.session_id

    def rank_dir(self, global_rank: int) -> Path:
        return self.session_dir / f"rank_{global_rank}"

    def to_dict(self) -> Dict[str, object]:
        """JSON-safe dict (actor/subprocess hand-off)."""
        d = dataclasses.asdict(self)
        d["logs_dir"] = str(self.logs_dir)
        return d

    @classmethod
    def from_dict(cls, data: Dict[str, object]) -> "TraceMLSettings":
        data = dict(data)
        agg = data.get("aggregator")
        if isinstance(agg, dict):
            data["aggregator"] = AggregatorEndpoint(**agg)
        if "logs_dir" in data:
            data["logs_dir"] = Path(str(data["logs_dir"]))
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in data.items() if k in known})

    @property
    def control_dir(self) -> Path:
        return self.session_dir / "control"


def settings_from_env(env: Optional[Dict[str, str]] = None) -> TraceMLSettings:
    e = dict(os.environ) if env is None else dict(env)
    max_steps = flags.TRACE_MAX_STEPS.raw(e)
    expected_ws = flags.EXPECTED_WORLD_SIZE.raw(e)
    connect_host = flags.AGGREGATOR_HOST.raw(e) or "127.0.0.1"
    return TraceMLSettings(
        session_id=flags.SESSION_ID.raw(e) or "local",
        logs_dir=Path(flags.LOGS_DIR.raw(e) or "./traceml_logs"),
        mode=flags.MODE.raw(e) or "cli",
        aggregator=AggregatorEndpoint(
            connect_host=connect_host,
            bind_host=flags.AGGREGATOR_BIND_HOST.raw(e) or connect_host,
            port=flags.AGGREGATOR_PORT.get_int(0, e),
        ),
        sampler_interval_sec=flags.SAMPLER_INTERVAL_SEC.get_float(1.0, e),
        trace_max_steps=int(max_steps) if max_steps else None,
        disabled=flags.DISABLE.truthy(e),
        disk_backup=flags.DISK_BACKUP.truthy(e),
        capture_stderr=flags.CAPTURE_STDERR.truthy(e),
        run_name=flags.RUN_NAME.raw(e) or None,
        expected_world_size=int(expected_ws) if expected_ws else None,
        finalize_timeout_sec=flags.FINALIZE_TIMEOUT_SEC.get_float(300.0, e),
        summary_window_rows=flags.SUMMARY_WINDOW_ROWS.get_int(10000, e),
        serve_max_sessions=flags.SERVE_MAX_SESSIONS.get_int(8, e),
        transport=flags.TRANSPORT.raw(e) or "auto",
        transport_compress=flags.TRANSPORT_COMPRESS.raw(e) or "auto",
        shm_ring_bytes=flags.SHM_RING_BYTES.get_int(4194304, e),
        shm_dir=flags.SHM_DIR.raw(e) or None,
        uds_path=flags.UDS_PATH.raw(e) or None,
    )


def settings_to_env(s: TraceMLSettings) -> Dict[str, str]:
    """The launcher-side half of the contract."""
    env = {
        ENV_SESSION_ID: s.session_id,
        ENV_LOGS_DIR: str(s.logs_dir),
        ENV_MODE: s.mode,
        ENV_AGG_HOST: s.aggregator.connect_host,
        ENV_AGG_BIND_HOST: s.aggregator.bind_host,
        ENV_AGG_PORT: str(s.aggregator.port),
        ENV_SAMPLER_INTERVAL: str(s.sampler_interval_sec),
        ENV_CAPTURE_STDERR: "1" if s.capture_stderr else "0",
        ENV_FINALIZE_TIMEOUT: str(s.finalize_timeout_sec),
        ENV_SUMMARY_WINDOW_ROWS: str(s.summary_window_rows),
        ENV_SERVE_MAX_SESSIONS: str(s.serve_max_sessions),
    }
    if s.trace_max_steps is not None:
        env[ENV_MAX_STEPS] = str(s.trace_max_steps)
    if s.disabled:
        env[ENV_DISABLE] = "1"
    if s.disk_backup:
        env[ENV_DISK_BACKUP] = "1"
    if s.run_name:
        env[ENV_RUN_NAME] = s.run_name
    if s.expected_world_size is not None:
        env[ENV_EXPECTED_WORLD_SIZE] = str(s.expected_world_size)
    if s.transport != "auto":
        env[ENV_TRANSPORT] = s.transport
    if s.transport_compress != "auto":
        env[ENV_TRANSPORT_COMPRESS] = s.transport_compress
    if s.shm_ring_bytes != 4194304:
        env[ENV_SHM_RING_BYTES] = str(s.shm_ring_bytes)
    if s.shm_dir:
        env[ENV_SHM_DIR] = s.shm_dir
    if s.uds_path:
        env[ENV_UDS_PATH] = s.uds_path
    return env
