from pathlib import Path

from traceml_tpu.database import Database, DBIncrementalSender, DatabaseWriter
from traceml_tpu.database.database_writer import iter_backup_file
from traceml_tpu.telemetry import SenderIdentity, normalize_telemetry_envelope


def _rows(payload, table):
    """Materialize a wire table (schema-2 columnar) back to row dicts."""
    return normalize_telemetry_envelope(payload).tables[table]


def test_bounded_append_and_tail():
    db = Database(max_rows_per_table=5)
    for i in range(8):
        db.add_record("t", {"i": i})
    assert db.append_count("t") == 8
    rows = db.tail("t")
    assert [r["i"] for r in rows] == [3, 4, 5, 6, 7]
    assert [r["i"] for r in db.tail("t", 2)] == [6, 7]
    assert db.tail("missing") == []


def test_rows_since_with_eviction():
    db = Database(max_rows_per_table=5)
    for i in range(3):
        db.add_record("t", {"i": i})
    assert [r["i"] for r in db.rows_since("t", 0)] == [0, 1, 2]
    for i in range(3, 10):
        db.add_record("t", {"i": i})
    # cursor at 3; 7 new appended but only 5 retained
    got = [r["i"] for r in db.rows_since("t", 3)]
    assert got == [5, 6, 7, 8, 9]
    assert db.rows_since("t", 10) == []


def test_incremental_sender_ships_only_new():
    db = Database()
    sender = DBIncrementalSender("step_time", db)
    sender.set_identity(SenderIdentity(session_id="s", global_rank=1))
    assert sender.collect_payload() is None
    db.add_record("steps", {"step": 1})
    p1 = sender.collect_payload()
    assert p1 is not None
    assert p1["meta"]["sampler"] == "step_time"
    assert p1["meta"]["global_rank"] == 1
    assert _rows(p1, "steps") == [{"step": 1}]
    # nothing new → None
    assert sender.collect_payload() is None
    db.add_record("steps", {"step": 2})
    db.add_record("other", {"x": 1})
    p2 = sender.collect_payload()
    assert _rows(p2, "steps") == [{"step": 2}]
    assert _rows(p2, "other") == [{"x": 1}]


def test_incremental_sender_cursor_sequence_with_eviction():
    """Cursor sequence battery (reference: sender-cursor sequence tests):
    interleaved appends, eviction between collections, and cursor
    monotonicity — the sender must never re-ship or skip silently except
    when rows were evicted before collection."""
    db = Database(max_rows_per_table=4)
    sender = DBIncrementalSender("system", db)
    sender.set_identity(SenderIdentity(session_id="s", global_rank=0))

    db.add_records("t", [{"i": 0}, {"i": 1}])
    assert [r["i"] for r in _rows(sender.collect_payload(), "t")] == [0, 1]

    # burst past the retention bound between ticks: rows 2..8 appended,
    # only the newest 4 retained — the sender ships what survived
    db.add_records("t", [{"i": i} for i in range(2, 9)])
    got = [r["i"] for r in _rows(sender.collect_payload(), "t")]
    assert got == [5, 6, 7, 8]

    # cursor is at the append head now: silence means None, repeatedly
    assert sender.collect_payload() is None
    assert sender.collect_payload() is None

    # resumes cleanly after silence
    db.add_record("t", {"i": 9})
    assert [r["i"] for r in _rows(sender.collect_payload(), "t")] == [9]


def test_incremental_sender_multi_table_independent_cursors():
    db = Database()
    sender = DBIncrementalSender("s", db)
    sender.set_identity(SenderIdentity(session_id="s", global_rank=0))
    db.add_record("a", {"i": 0})
    p = sender.collect_payload()
    assert set(p["body"]["tables"]) == {"a"}
    db.add_record("b", {"j": 0})
    p = sender.collect_payload()
    assert set(p["body"]["tables"]) == {"b"}  # table a's cursor untouched
    db.add_record("a", {"i": 1})
    db.add_record("b", {"j": 1})
    p = sender.collect_payload()
    assert [r["i"] for r in _rows(p, "a")] == [1]
    assert [r["j"] for r in _rows(p, "b")] == [1]


def test_incremental_sender_reset_reships_retained_rows():
    db = Database(max_rows_per_table=4)
    sender = DBIncrementalSender("s", db)
    sender.set_identity(SenderIdentity(session_id="s", global_rank=0))
    db.add_records("t", [{"i": i} for i in range(6)])
    sender.collect_payload()
    assert sender.collect_payload() is None
    sender.reset()  # reconnect semantics: replay what's still retained
    got = [r["i"] for r in _rows(sender.collect_payload(), "t")]
    assert got == [2, 3, 4, 5]


def test_collect_since_lock_copy_bounded():
    """collect_since must copy O(new rows) under the lock, not the whole
    retained deque — 2000 single-row collections against a 100k-row table
    would cost ~200M element copies with a full-deque copy."""
    import time

    db = Database(max_rows_per_table=100_000)
    db.add_records("t", [{"i": i} for i in range(100_000)])
    rows, cursor = db.collect_since("t", 0)
    assert len(rows) == 100_000
    t0 = time.perf_counter()
    for i in range(2000):
        db.add_record("t", {"i": 100_000 + i})
        rows, cursor = db.collect_since("t", cursor)
        assert [r["i"] for r in rows] == [100_000 + i]
    elapsed = time.perf_counter() - t0
    assert elapsed < 1.0, f"tail collection took {elapsed:.2f}s — O(deque) copy?"


def test_disk_writer_roundtrip(tmp_path):
    db = Database()
    w = DatabaseWriter("s", db, tmp_path, flush_every=1)
    db.add_records("t", [{"i": 0}, {"i": 1}])
    assert w.flush(force=True) == 2
    db.add_record("t", {"i": 2})
    assert w.flush(force=True) == 1
    rows = list(iter_backup_file(Path(tmp_path) / "s" / "t.msgpack"))
    assert [r["i"] for r in rows] == [0, 1, 2]


def test_disk_writer_disabled():
    db = Database()
    w = DatabaseWriter("s", db, None)
    db.add_record("t", {"i": 0})
    assert w.flush(force=True) == 0
