"""Failure-detection E2E: the aggregator dying mid-run must DEGRADE the
run (training completes, manifest says so), never fail it
(reference contract: commands.py:549-564 + fail-open TCPClient)."""

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[2]

SCRIPT = """
import time
import numpy as np
import jax, jax.numpy as jnp
import traceml_tpu

def step_fn(w, x):
    return w - 0.01 * jax.grad(lambda w, x: jnp.sum((x @ w) ** 2))(w, x)

step = traceml_tpu.wrap_step_fn(step_fn)
w = jnp.ones((16, 16))
rng = np.random.default_rng(0)
for i in range(40):
    with traceml_tpu.trace_step():
        x = jax.device_put(rng.normal(size=(4, 16)).astype(np.float32))
        w = step(w, x)
    time.sleep(0.05)
print("training finished fine")
"""


def test_aggregator_death_degrades_not_fails(tmp_path):
    script = tmp_path / "train.py"
    script.write_text(SCRIPT)
    logs = tmp_path / "logs"
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(REPO)
    # this test pins the restart-budget-exhausted contract (degrade, not
    # fail); the restart path itself is covered by test_chaos_e2e.py
    env["TRACEML_AGG_MAX_RESTARTS"] = "0"
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "traceml_tpu", "run",
            "--mode", "summary", "--logs-dir", str(logs),
            "--run-name", "degrade", "--sampler-interval", "0.25",
            "--finalize-timeout", "20", str(script),
        ],
        env=env, cwd=str(tmp_path),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    # wait for the aggregator ready file, then murder the aggregator
    session = None
    deadline = time.monotonic() + 60
    agg_pid = None
    while time.monotonic() < deadline and agg_pid is None:
        sessions = list(logs.glob("degrade*/aggregator_ready.json"))
        if sessions:
            session = sessions[0].parent
            agg_pid = json.loads(sessions[0].read_text())["pid"]
        time.sleep(0.2)
    assert agg_pid, "aggregator never became ready"
    time.sleep(1.5)  # let some telemetry flow first
    os.kill(agg_pid, signal.SIGKILL)

    out, _ = proc.communicate(timeout=240)
    assert proc.returncode == 0, out[-3000:]
    assert "training finished fine" in out
    manifest = json.loads((session / "manifest.json").read_text())
    assert manifest["status"] == "completed"
    assert manifest["telemetry_status"] == "degraded"
